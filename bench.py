"""Single-chip training throughput benchmark.

Trains the flagship Llama-family decoder for a few steps on the local
accelerator (the driver runs this on one real TPU chip) and reports model FLOPs
utilization. Target from BASELINE.json: Llama-3-8B ZeRO-3 bf16 @ >=45% MFU on
v5p-64; single-chip MFU is the per-chip proxy tracked across rounds
(``vs_baseline`` = MFU / 0.45).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
"""

import json
import os
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    """bf16 peak FLOPs/s per chip by TPU generation (public spec sheets)."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12,
        "v6 lite": 918e12, "v6e": 918e12,
        "v3": 123e12, "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # conservative default


def main():
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    # Sized to fit one chip's HBM with fp32 master + Adam moments (~18 B/param).
    model_cfg = llama.LlamaConfig(
        vocab_size=int(os.environ.get("BENCH_VOCAB", 32000)),
        hidden_size=int(os.environ.get("BENCH_HIDDEN", 2048)),
        intermediate_size=int(os.environ.get("BENCH_FFN", 5632)),
        num_layers=int(os.environ.get("BENCH_LAYERS", 10)),
        num_heads=16,
        num_kv_heads=8,
        max_seq_len=2048,
    ) if on_tpu else llama.LlamaConfig.tiny(512)

    seq = int(os.environ.get("BENCH_SEQ", 2048)) if on_tpu else 64
    batch = int(os.environ.get("BENCH_BATCH", 16)) if on_tpu else 4
    steps = int(os.environ.get("BENCH_STEPS", 10)) if on_tpu else 3

    config = {
        "train_micro_batch_size_per_device": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "sequence_length": seq,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1},
        "activation_checkpointing": {"enabled": True, "policy": "dots_saveable"},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(
            model_cfg, ctx=ctx, remat=True,
            remat_policy=None,
        ),
        config=config,
    )

    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(0, model_cfg.vocab_size, (batch, seq), dtype=np.int32)}

    # warmup/compile
    engine.train_batch(make_batch())
    engine.train_batch(make_batch())

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(make_batch())
    elapsed = time.perf_counter() - t0

    tokens_per_s = steps * batch * seq / elapsed
    n = llama.num_params(model_cfg)
    flops_per_token = llama.flops_per_token(model_cfg, seq)
    model_flops_per_s = tokens_per_s * flops_per_token
    peak = _peak_flops(jax.devices()[0]) if on_tpu else 1e12
    mfu = model_flops_per_s / peak

    result = {
        "metric": "llama_train_mfu_single_chip",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_s": round(tokens_per_s, 1),
        "model_params": n,
        "seq_len": seq,
        "final_loss": round(float(loss), 4),
        "device": str(jax.devices()[0].device_kind),
        "backend": jax.default_backend(),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
