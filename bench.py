"""Single-chip training throughput benchmark.

Trains the flagship Llama-family decoder for a few steps on the local
accelerator (the driver runs this on one real TPU chip) and reports model FLOPs
utilization. Target from BASELINE.json: Llama-3-8B ZeRO-3 bf16 @ >=45% MFU on
v5p-64; single-chip MFU is the per-chip proxy tracked across rounds
(``vs_baseline`` = MFU / 0.45).

OOM-safe by construction: the parent process never initializes the accelerator;
each candidate config runs in its own subprocess (the autotuner's trial pattern,
``deepspeed_tpu/autotuning/autotuner.py``), and on failure (RESOURCE_EXHAUSTED
or anything else) the ladder backs off to a smaller config. Configs are sized
from the device's HBM capacity by generation, not guessed.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
"""

import json
import os
import subprocess
import sys
import time

# (bf16 peak FLOPs/s, HBM bytes) per chip by TPU generation (public spec sheets)
CHIP_TABLE = {
    "v5 lite": (197e12, 16e9), "v5e": (197e12, 16e9),
    "v5p": (459e12, 95e9),
    "v4": (275e12, 32e9),
    "v6 lite": (918e12, 32e9), "v6e": (918e12, 32e9),
    "v3": (123e12, 16e9),
    "v2": (45e12, 8e9),
    "v5": (459e12, 95e9),
}


def chip_spec(device_kind: str):
    kind = device_kind.lower()
    for key, val in CHIP_TABLE.items():
        if key in kind:
            return val
    print(f"bench: unknown device kind {device_kind!r}; assuming 197 TFLOPs / 16 GB",
          file=sys.stderr)
    return (197e12, 16e9)


def candidate_ladder(hbm_bytes: float):
    """Descending ladder of (hidden, ffn, layers, vocab, heads, kv, batch, seq).

    State bytes/param on the fused step path: fp32 master + Adam m/v (12) +
    fp32 grad accumulator (4) + transient bf16 cast (2) = ~18. Each rung keeps
    18*params plus a logits/activation estimate within ~80% of HBM; the
    subprocess trial is still the ground truth.
    """
    if hbm_bytes >= 90e9:      # v5p-class
        ladder = [
            (4096, 14336, 16, 32768, 32, 8, 8, 2048),
            (4096, 14336, 12, 32768, 32, 8, 8, 2048),
            (2048, 5632, 16, 32768, 16, 8, 8, 2048),
        ]
    elif hbm_bytes >= 30e9:    # v4 / v6e-class
        ladder = [
            (2048, 5632, 16, 32768, 16, 8, 8, 2048),
            (2048, 5632, 12, 32768, 16, 8, 8, 2048),
            (2048, 5632, 8, 32768, 16, 8, 8, 2048),
        ]
    else:                      # 16 GB-class (v5e, v3)
        ladder = [
            (2048, 5632, 8, 32768, 16, 8, 8, 2048),
            (2048, 5632, 8, 32768, 16, 8, 4, 2048),
            (2048, 5632, 6, 32768, 16, 8, 4, 2048),
            (1536, 4096, 8, 32768, 16, 8, 4, 2048),
        ]
    ladder.append((1024, 2816, 6, 16384, 16, 8, 4, 1024))  # safety net
    return ladder


def run_trial_subprocess(cfg_tuple, steps: int, timeout: float = 900.0,
                         zero_stage: int | None = None):
    env = dict(os.environ)
    hidden, ffn, layers, vocab, heads, kv, batch, seq = cfg_tuple
    env.update(
        BENCH_TRIAL="1",
        BENCH_HIDDEN=str(hidden), BENCH_FFN=str(ffn), BENCH_LAYERS=str(layers),
        BENCH_VOCAB=str(vocab), BENCH_HEADS=str(heads), BENCH_KV=str(kv),
        BENCH_BATCH=str(batch), BENCH_SEQ=str(seq), BENCH_STEPS=str(steps),
    )
    if zero_stage is not None:  # else the operator's BENCH_STAGE (if any) pins it
        env["BENCH_STAGE"] = str(zero_stage)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, "timeout"
    if proc.returncode != 0:
        return None, (proc.stderr or proc.stdout)[-2000:]
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, "no JSON in trial output:\n" + proc.stdout[-2000:]


def trial_main():
    """Child process: build the engine from env, time steps, print one JSON line."""
    import numpy as np
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    e = os.environ
    model_cfg = llama.LlamaConfig(
        vocab_size=int(e["BENCH_VOCAB"]),
        hidden_size=int(e["BENCH_HIDDEN"]),
        intermediate_size=int(e["BENCH_FFN"]),
        num_layers=int(e["BENCH_LAYERS"]),
        num_heads=int(e["BENCH_HEADS"]),
        num_kv_heads=int(e["BENCH_KV"]),
        max_seq_len=int(e["BENCH_SEQ"]),
    )
    seq, batch, steps = int(e["BENCH_SEQ"]), int(e["BENCH_BATCH"]), int(e["BENCH_STEPS"])
    stage = int(e.get("BENCH_STAGE", "0"))

    # stage 3 shards over fsdp: claim every device for it (on a single chip
    # the plan degenerates to stage 0 — real sharding overhead needs a pod)
    n_dev = len(jax.devices())
    mesh = {"data": 1, "fsdp": n_dev} if stage >= 3 and n_dev > 1 else {"data": -1}
    config = {
        "train_micro_batch_size_per_device": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "sequence_length": seq,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh,
        "activation_checkpointing": {
            "enabled": e.get("BENCH_REMAT", "1") == "1",
            "policy": e.get("BENCH_REMAT_POLICY", "dots_saveable"),
        },
    }
    if e.get("BENCH_TILED_LOGITS") == "1":
        # ALST tiled logits loss: trades the [B*S, V] logits buffer for
        # tiled compute — frees HBM for larger batches
        config["sequence_parallel"] = {
            "tiled_logits": True,
            "tile_size": int(e.get("BENCH_TILE", "2048")),
        }
    engine, _, _, _ = deepspeed_tpu.initialize(
        # remat/policy inherit from the config via ShardCtx (single source)
        model=lambda ctx: llama.build(model_cfg, ctx=ctx),
        config=config,
    )

    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(0, model_cfg.vocab_size, (batch, seq), dtype=np.int32)}

    # settle via value fetch: block_until_ready can return early over the
    # tunneled-TPU transport, a fetched scalar cannot
    float(engine.train_batch(make_batch()))  # compile
    float(engine.train_batch(make_batch()))  # warm
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(make_batch())
    loss = float(loss)  # steps dispatch async; settle before timing
    elapsed = time.perf_counter() - t0

    tokens_per_s = steps * batch * seq / elapsed
    flops_per_token = llama.flops_per_token(model_cfg, seq)
    peak, _ = chip_spec(getattr(jax.devices()[0], "device_kind", ""))
    if jax.default_backend() != "tpu":
        peak = 1e12  # nominal denominator for CPU smoke runs
    mfu = tokens_per_s * flops_per_token / peak
    print(json.dumps({
        "metric": "llama_train_mfu_single_chip",
        "zero_stage": stage,
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_s": round(tokens_per_s, 1),
        "model_params": llama.num_params(model_cfg),
        "seq_len": seq,
        "batch": batch,
        "final_loss": round(loss, 4),
        "device": str(jax.devices()[0].device_kind),
        "backend": jax.default_backend(),
    }))


def probe_device():
    """Probe backend/device kind in a throwaway subprocess so the parent never
    holds the TPU (a held chip would make every trial subprocess fail to init)."""
    code = (
        "import jax, json;"
        "d = jax.devices()[0];"
        "print(json.dumps({'backend': jax.default_backend(),"
        " 'kind': getattr(d, 'device_kind', '')}))"
    )
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=300)
    if proc.returncode != 0:
        raise RuntimeError("device probe failed:\n" + proc.stderr[-2000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError("device probe produced no JSON")


def main():
    if os.environ.get("BENCH_TRIAL"):
        return trial_main()

    info = probe_device()
    if info["backend"] != "tpu":
        # CPU smoke mode: tiny in-subprocess trials (stage 0 + stage 3), nominal peak
        smoke = (256, 688, 2, 512, 4, 2, 4, 64)
        result, err = run_trial_subprocess(smoke, steps=3)
        if result is None:
            print(err, file=sys.stderr)
            return 1
        r3, err3 = run_trial_subprocess(smoke, steps=3, zero_stage=3)
        if r3 is not None:
            result["mfu_zero3"] = r3["value"]
        else:
            print(f"stage-3 smoke trial failed:\n{err3}", file=sys.stderr)
        print(json.dumps(result))
        return 0

    _, hbm = chip_spec(info["kind"])
    steps = int(os.environ.get("BENCH_STEPS", 10))

    # explicit shape overrides pin a single config (no ladder)
    shape_vars = ("BENCH_HIDDEN", "BENCH_FFN", "BENCH_LAYERS", "BENCH_VOCAB",
                  "BENCH_HEADS", "BENCH_KV", "BENCH_BATCH", "BENCH_SEQ")
    if any(v in os.environ for v in shape_vars):
        e = os.environ
        rung = (int(e.get("BENCH_HIDDEN", 2048)), int(e.get("BENCH_FFN", 5632)),
                int(e.get("BENCH_LAYERS", 8)), int(e.get("BENCH_VOCAB", 32768)),
                int(e.get("BENCH_HEADS", 16)), int(e.get("BENCH_KV", 8)),
                int(e.get("BENCH_BATCH", 8)), int(e.get("BENCH_SEQ", 2048)))
        result, err = run_trial_subprocess(rung, steps=steps)
        if result is None:
            print(f"pinned bench config {rung} failed:\n{err}", file=sys.stderr)
            return 1
        print(json.dumps(result))
        return 0

    errors = []
    for rung in candidate_ladder(hbm):
        result, err = run_trial_subprocess(rung, steps=steps)
        if result is not None:
            # the north-star path is ZeRO-3 (BASELINE: Llama-3-8B stage 3);
            # report its MFU on the same rung alongside the headline number
            # (single-chip stage 3 measures the code path's overhead — the
            # sharding itself needs the fsdp axis of a real pod)
            r3, err3 = run_trial_subprocess(rung, steps=steps, zero_stage=3)
            if r3 is not None:
                result["mfu_zero3"] = r3["value"]
                result["tokens_per_s_zero3"] = r3.get("tokens_per_s")
            else:
                print(f"stage-3 rung failed (headline unaffected):\n{err3}",
                      file=sys.stderr)
            print(json.dumps(result))
            return 0
        errors.append(f"config {rung}: {err[-300:] if err else 'unknown'}")
        print(f"bench rung {rung} failed, backing off:\n{err}", file=sys.stderr)
    print("all bench rungs failed:\n" + "\n".join(errors), file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
