"""Single-chip training throughput benchmark.

Trains the flagship Llama-family decoder for a few steps on the local
accelerator (the driver runs this on one real TPU chip) and reports model FLOPs
utilization. Target from BASELINE.json: Llama-3-8B ZeRO-3 bf16 @ >=45% MFU on
v5p-64; single-chip MFU is the per-chip proxy tracked across rounds
(``vs_baseline`` = MFU / 0.45).

OOM-safe by construction: the parent process never initializes the accelerator;
each candidate config runs in its own subprocess (the autotuner's trial pattern,
``deepspeed_tpu/autotuning/autotuner.py``), and on failure (RESOURCE_EXHAUSTED
or anything else) the ladder backs off to a smaller config. Configs are sized
from the device's HBM capacity by generation, not guessed.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
"""

import json
import os
import shutil
import subprocess
import sys
import time

# Persistent XLA compilation cache: verified effective through the axon
# remote-compile transport (second process: 1.46 s compile -> 0.02 s). Trial
# subprocesses inherit it via the environment, so the serve engine's
# program-zoo warmup and repeat bench invocations stop paying multi-second
# compiles (which were dominating staggered-serve latency).
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

# (bf16 peak FLOPs/s, HBM bytes) per chip by TPU generation (public spec sheets)
CHIP_TABLE = {
    "v5 lite": (197e12, 16e9), "v5e": (197e12, 16e9),
    "v5p": (459e12, 95e9),
    "v4": (275e12, 32e9),
    "v6 lite": (918e12, 32e9), "v6e": (918e12, 32e9),
    "v3": (123e12, 16e9),
    "v2": (45e12, 8e9),
    "v5": (459e12, 95e9),
}


def chip_spec(device_kind: str):
    kind = device_kind.lower()
    for key, val in CHIP_TABLE.items():
        if key in kind:
            return val
    print(f"bench: unknown device kind {device_kind!r}; assuming 197 TFLOPs / 16 GB",
          file=sys.stderr)
    return (197e12, 16e9)


def candidate_ladder(hbm_bytes: float):
    """Descending ladder of (hidden, ffn, layers, vocab, heads, kv, batch, seq).

    State bytes/param on the fused step path: fp32 master + Adam m/v (12) +
    fp32 grad accumulator (4) + transient bf16 cast (2) = ~18. Each rung keeps
    18*params plus a logits/activation estimate within ~80% of HBM; the
    subprocess trial is still the ground truth.
    """
    if hbm_bytes >= 90e9:      # v5p-class
        ladder = [
            (4096, 14336, 16, 32768, 32, 8, 8, 2048),
            (4096, 14336, 12, 32768, 32, 8, 8, 2048),
            (2048, 5632, 16, 32768, 16, 8, 8, 2048),
        ]
    elif hbm_bytes >= 30e9:    # v4 / v6e-class
        ladder = [
            (2048, 5632, 16, 32768, 16, 8, 8, 2048),
            (2048, 5632, 12, 32768, 16, 8, 8, 2048),
            (2048, 5632, 8, 32768, 16, 8, 8, 2048),
        ]
    else:                      # 16 GB-class (v5e, v3)
        ladder = [
            (2048, 5632, 8, 32768, 16, 8, 8, 2048),
            (2048, 5632, 8, 32768, 16, 8, 4, 2048),
            (2048, 5632, 6, 32768, 16, 8, 4, 2048),
            (1536, 4096, 8, 32768, 16, 8, 4, 2048),
        ]
    ladder.append((1024, 2816, 6, 16384, 16, 8, 4, 1024))  # safety net
    return ladder


def _child_error(reason, proc=None, flag=None):
    """Structured child-process failure record: every trial/bench failure
    carries the child's rc + tail stderr instead of an opaque string (the
    BENCH_r05 'rc=1, device relay dead' incident was undiagnosable from
    the old format). Serializable — top-level failures emit it under an
    ``"error"`` key in the JSON output."""
    err = {"reason": reason, "rc": None, "stderr": ""}
    if flag:
        err["flag"] = flag
    if proc is not None:
        err["rc"] = proc.returncode
        err["stderr"] = (proc.stderr or proc.stdout or "")[-2000:]
    return err


def _err_text(err):
    """Human-readable rendering of a _child_error dict (or legacy string)."""
    if isinstance(err, dict):
        head = f"reason={err.get('reason')} rc={err.get('rc')}"
        if err.get("flag"):
            head += f" flag={err['flag']}"
        tail = err.get("stderr") or ""
        return head + ("\n" + tail if tail else "")
    return str(err)


def _fail_json(err):
    """Emit the structured error as the bench's JSON line (stdout) so
    automation parses a real ``error`` field instead of grepping stderr."""
    print(json.dumps(
        {"error": err if isinstance(err, dict) else {"reason": str(err)}}))


def run_trial_subprocess(cfg_tuple, steps: int, timeout: float = 900.0,
                         zero_stage: int | None = None):
    env = dict(os.environ)
    hidden, ffn, layers, vocab, heads, kv, batch, seq = cfg_tuple
    env.update(
        BENCH_TRIAL="1",
        BENCH_HIDDEN=str(hidden), BENCH_FFN=str(ffn), BENCH_LAYERS=str(layers),
        BENCH_VOCAB=str(vocab), BENCH_HEADS=str(heads), BENCH_KV=str(kv),
        BENCH_BATCH=str(batch), BENCH_SEQ=str(seq), BENCH_STEPS=str(steps),
    )
    if zero_stage is not None:  # else the operator's BENCH_STAGE (if any) pins it
        env["BENCH_STAGE"] = str(zero_stage)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, _child_error(f"trial timed out after {timeout:g}s")
    if proc.returncode != 0:
        return None, _child_error("trial child exited nonzero", proc)
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, _child_error("no JSON in trial output", proc)


def trial_main():
    """Child process: build the engine from env, time steps, print one JSON line."""
    import numpy as np
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    e = os.environ
    model_cfg = llama.LlamaConfig(
        vocab_size=int(e["BENCH_VOCAB"]),
        hidden_size=int(e["BENCH_HIDDEN"]),
        intermediate_size=int(e["BENCH_FFN"]),
        num_layers=int(e["BENCH_LAYERS"]),
        num_heads=int(e["BENCH_HEADS"]),
        num_kv_heads=int(e["BENCH_KV"]),
        max_seq_len=int(e["BENCH_SEQ"]),
    )
    seq, batch, steps = int(e["BENCH_SEQ"]), int(e["BENCH_BATCH"]), int(e["BENCH_STEPS"])
    stage = int(e.get("BENCH_STAGE", "0"))

    # stage 3 shards over fsdp: claim every device for it (on a single chip
    # the plan degenerates to stage 0 — real sharding overhead needs a pod)
    n_dev = len(jax.devices())
    mesh = {"data": 1, "fsdp": n_dev} if stage >= 3 and n_dev > 1 else {"data": -1}
    config = {
        "train_micro_batch_size_per_device": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "sequence_length": seq,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh,
        "activation_checkpointing": {
            "enabled": e.get("BENCH_REMAT", "1") == "1",
            "policy": e.get("BENCH_REMAT_POLICY", "dots_saveable"),
        },
    }
    if e.get("BENCH_TILED_LOGITS") == "1":
        # ALST tiled logits loss: trades the [B*S, V] logits buffer for
        # tiled compute — frees HBM for larger batches
        config["sequence_parallel"] = {
            "tiled_logits": True,
            "tile_size": int(e.get("BENCH_TILE", "2048")),
        }
    # every bench run doubles as a telemetry fixture: step spans, HBM
    # watermarks, and the final registry snapshot land in a JSONL under
    # runs/ (gitignored; docs/OBSERVABILITY.md)
    tel_path = e.get("BENCH_TELEMETRY_JSONL", os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "runs",
        "BENCH_telemetry.jsonl"))
    config["telemetry"] = {"enabled": True, "jsonl_path": tel_path}
    engine, _, _, _ = deepspeed_tpu.initialize(
        # remat/policy inherit from the config via ShardCtx (single source)
        model=lambda ctx: llama.build(model_cfg, ctx=ctx),
        config=config,
    )

    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(0, model_cfg.vocab_size, (batch, seq), dtype=np.int32)}

    # settle via value fetch: block_until_ready can return early over the
    # tunneled-TPU transport, a fetched scalar cannot
    float(engine.train_batch(make_batch()))  # compile
    float(engine.train_batch(make_batch()))  # warm
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(make_batch())
    loss = float(loss)  # steps dispatch async; settle before timing
    elapsed = time.perf_counter() - t0

    tokens_per_s = steps * batch * seq / elapsed
    flops_per_token = llama.flops_per_token(model_cfg, seq)
    peak, _ = chip_spec(getattr(jax.devices()[0], "device_kind", ""))
    if jax.default_backend() != "tpu":
        peak = 1e12  # nominal denominator for CPU smoke runs
    mfu = tokens_per_s * flops_per_token / peak
    from deepspeed_tpu import telemetry

    telemetry.TELEMETRY.close()  # appends the final registry snapshot record
    print(json.dumps({
        "metric": "llama_train_mfu_single_chip",
        "zero_stage": stage,
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_s": round(tokens_per_s, 1),
        "model_params": llama.num_params(model_cfg),
        "seq_len": seq,
        "batch": batch,
        "final_loss": round(loss, 4),
        "device": str(jax.devices()[0].device_kind),
        "backend": jax.default_backend(),
        "telemetry_jsonl": tel_path,
    }))


def serve_trial_main():
    """Child process: mixed prefill/decode serving throughput — the ragged
    continuous-batching engine vs (a) the dense padded-batch engine and (b) a
    naive per-request loop, same model + workload for all three.

    Reference bar: FastGen's 2.3x effective throughput vs padded serving
    (``blogs/deepspeed-fastgen/README.md:28``). Useful tokens (prompt +
    generated) are identical across systems; only wall time differs.
    Prints one JSON line of serving metrics.
    """
    import numpy as np
    import jax

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
    from deepspeed_tpu.models import llama

    e = os.environ
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model_cfg = llama.LlamaConfig(
            vocab_size=int(e.get("BENCH_VOCAB", 32768)),
            hidden_size=int(e.get("BENCH_HIDDEN", 2048)),
            intermediate_size=int(e.get("BENCH_FFN", 5632)),
            num_layers=int(e.get("BENCH_LAYERS", 8)),
            num_heads=int(e.get("BENCH_HEADS", 16)),
            num_kv_heads=int(e.get("BENCH_KV", 8)),
            max_seq_len=1024,
        )
        n_req, max_new, max_prompt = 32, 48, 512
        prompt_lens = [64, 128, 256, 512]
        # budget/max_seqs sized so the whole load admits in one wave and
        # prefill takes few dispatches: over the tunneled single chip every
        # host->device dispatch pays a flat ~100-200 ms transport RTT
        # (measured), so dispatch count — not FLOPs — is the first-order
        # serving cost here; the dense baseline amortizes it over one
        # whole-batch decode scan per batch
        max_seqs, budget, block, tile, ahead = 32, 1024, 32, 128, 48
        fused, depth = 16, 3
    else:
        model_cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=688,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
        )
        n_req, max_new, max_prompt = 6, 8, 64
        prompt_lens = [16, 32, 64]
        max_seqs, budget, block, tile, ahead = 4, 64, 16, 16, 8
        fused, depth = 4, 2

    # request-lifecycle spans (queue wait, TTFT, per-token decode latency,
    # preemptions) for every ragged request in this trial
    from deepspeed_tpu import telemetry

    tel_path = e.get("BENCH_TELEMETRY_JSONL", os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "runs",
        "BENCH_serve_telemetry.jsonl"))
    telemetry.configure(enabled=True, jsonl_path=tel_path)

    rng = np.random.default_rng(0)
    lens = [int(prompt_lens[i % len(prompt_lens)]) for i in range(n_req)]
    rng.shuffle(lens)
    prompts = [rng.integers(0, model_cfg.vocab_size, (L,), dtype=np.int32)
               for L in lens]
    useful_tokens = sum(lens) + n_req * max_new

    mbs = -(-(max_prompt + max_new) // block)
    rcfg = RaggedConfig(
        max_tokens_per_step=budget, max_seqs=max_seqs, block_size=block,
        num_blocks=max_seqs * mbs + 1, max_blocks_per_seq=mbs,
        # fused multi-step decode: without it, one dispatch per generated
        # token makes decode dispatch-latency-bound (especially over the
        # tunneled single chip this bench runs on)
        decode_run_ahead=int(e.get("BENCH_RUN_AHEAD", ahead)),
        # tiled prefill: one KV-block fetch per tile instead of per token
        # (the per-token decode kernel is O(context) DMA per token,
        # ~tile x redundant on prefill chunks)
        prefill_tile=int(e.get("BENCH_PREFILL_TILE", tile)),
        # fused mixed chunks + async dispatch window: prompt chunks ride
        # step 0 of the same K-step program the decodes run ahead in, and
        # chunk t+1 dispatches before chunk t's readback — arrivals no
        # longer collapse the engine to one dispatch per token (the round-4
        # staggered-latency fix)
        fused_chunk=int(e.get("BENCH_FUSED_CHUNK", fused)),
        pipeline_depth=int(e.get("BENCH_PIPELINE_DEPTH", depth)),
    )
    ragged = RaggedInferenceEngine(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx),
        ragged_config=rcfg, seed=0,
    )
    # precompile the fused program zoo (fills the persistent cache; without
    # it, shape combos first hit mid-serve cost 4-5 s stalls each)
    t0 = time.perf_counter()
    nwarm = ragged.warmup()
    print(f"# ragged warmup: {nwarm} programs in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    def run_ragged():
        for i, p in enumerate(prompts):
            ragged.put(("r", i), p, max_new_tokens=max_new)
        out = ragged.generate_all()
        assert all(len(v) == max_new for v in out.values())

    # warmup: one full untimed pass compiles every bucket size the workload
    # hits (jit specializes per token-batch bucket)
    run_ragged()
    t0 = time.perf_counter()
    run_ragged()
    ragged_s = time.perf_counter() - t0

    dense = InferenceEngine(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx), seed=0)

    def pad_batch(batch_prompts):
        out = np.zeros((len(batch_prompts), max_prompt), np.int32)
        for i, p in enumerate(batch_prompts):
            out[i, :len(p)] = p  # left-aligned; generation timing unaffected
        return out

    def run_dense():
        # padded static batches of max_seqs (the v1-engine serving shape)
        for i in range(0, n_req, max_seqs):
            dense.generate(pad_batch(prompts[i:i + max_seqs]),
                           max_new_tokens=max_new)

    run_dense()  # warm: compiles every batch shape incl. the partial tail
    t0 = time.perf_counter()
    run_dense()
    dense_s = time.perf_counter() - t0

    def run_naive():
        # one request at a time, padded to the max prompt (single compile)
        for p in prompts:
            dense.generate(pad_batch([p]), max_new_tokens=max_new)

    dense.generate(pad_batch([prompts[0]]), max_new_tokens=max_new)  # compile
    t0 = time.perf_counter()
    run_naive()
    naive_s = time.perf_counter() - t0

    sched = ragged.tokens_scheduled + ragged.tokens_padded

    # ------------------------------------------------- staggered arrivals
    # The FastGen effective-throughput scenario: requests ARRIVE over time.
    # Dense serving must run wave-by-wave (whoever has arrived pads into a
    # full batch and later arrivals wait out the whole generation);
    # continuous batching admits mid-flight. Latency = finish - arrival.
    interval = (0.15 if on_tpu else 0.5)  # seconds between arrivals
    arrivals = [i * interval for i in range(n_req)]

    def run_ragged_staggered(tag):
        lat = {}
        t0 = time.perf_counter()
        nxt = 0
        while nxt < n_req or ragged.has_work:
            now = time.perf_counter() - t0
            while nxt < n_req and arrivals[nxt] <= now:
                ragged.put((tag, nxt), prompts[nxt], max_new_tokens=max_new)
                nxt += 1
            if ragged.has_work:
                done_before = ragged.finished_uids
                ragged.step()
                for uid in ragged.finished_uids - done_before:
                    lat[uid] = (time.perf_counter() - t0) - arrivals[uid[1]]
            elif nxt < n_req:
                time.sleep(max(0.0, arrivals[nxt] - (time.perf_counter() - t0)))
        return lat

    def run_dense_staggered():
        lat = {}
        t0 = time.perf_counter()
        nxt = 0
        while nxt < n_req:
            now = time.perf_counter() - t0
            if arrivals[nxt] > now:
                time.sleep(arrivals[nxt] - now)
            now = time.perf_counter() - t0
            wave = []
            while nxt < n_req and arrivals[nxt] <= now and len(wave) < max_seqs:
                wave.append(nxt)
                nxt += 1
            # always the warmed full-batch program: a per-wave-size program
            # would compile inside the timed region, and the full-batch
            # padding IS dense serving's cost under continuous load
            batch = pad_batch([prompts[i] for i in wave]
                              + [prompts[0]] * (max_seqs - len(wave)))
            dense.generate(batch, max_new_tokens=max_new)
            fin = time.perf_counter() - t0
            for i in wave:
                lat[i] = fin - arrivals[i]
        return lat

    run_ragged_staggered("w")  # warm: compiles the staggered-mix programs
    disp0, tok0 = ragged.dispatch_count, ragged.tokens_emitted
    rag_lat = list(run_ragged_staggered("s").values())
    stag_dispatches = ragged.dispatch_count - disp0
    stag_generated = ragged.tokens_emitted - tok0
    den_lat = list(run_dense_staggered().values())
    rag_mean = sum(rag_lat) / len(rag_lat)
    den_mean = sum(den_lat) / len(den_lat)
    telemetry.TELEMETRY.close()
    print(json.dumps({
        "ragged_tokens_per_s": round(useful_tokens / ragged_s, 1),
        "dense_tokens_per_s": round(useful_tokens / dense_s, 1),
        "naive_tokens_per_s": round(useful_tokens / naive_s, 1),
        "ragged_vs_dense": round(dense_s / ragged_s, 3),
        "ragged_vs_naive": round(naive_s / ragged_s, 3),
        "ragged_padding_frac": round(ragged.tokens_padded / max(sched, 1), 4),
        # staggered-arrival (continuous) load: mean per-request latency and
        # the dense/ragged ratio — >1 means continuous batching wins. On
        # THIS transport the ratio is dominated by the flat per-dispatch RTT
        # (~180 ms): mixed prefill/decode steps emit ~1 token/seq/dispatch
        # while the dense baseline amortizes a whole wave into one scan.
        # On a local TPU host (sub-ms dispatch) the same scheduling is
        # compute-bound and the comparison flips — read these numbers as a
        # transport measurement, not engine quality (see bench docstring).
        "staggered_ragged_mean_latency_s": round(rag_mean, 3),
        "staggered_dense_mean_latency_s": round(den_mean, 3),
        "staggered_latency_ratio": round(den_mean / rag_mean, 3),
        # dispatch economy under continuous load (the round-4 target:
        # <= 0.25 dispatches per generated token)
        "staggered_dispatches": stag_dispatches,
        "staggered_dispatches_per_token": round(
            stag_dispatches / max(stag_generated, 1), 4),
        "serve_reqs": n_req,
        "serve_useful_tokens": useful_tokens,
        "serve_max_new": max_new,
        "telemetry_jsonl": tel_path,
    }))


def decode_steady_main():
    """Child process: steady-state decode dispatch-overhead benchmark.

    The PR-4 target: once every live sequence is decoding, the engine's
    per-dispatch host work should be admission-free — device-resident
    scheduler rows, delta-synced block table, one packed staging buffer,
    double-buffered readback. This trial runs the SAME pure-decode workload
    through (a) the device-resident path, (b) the legacy host-staged path
    (``device_state=False``), and (c) the dense padded engine, and reports
    tokens/s plus a host-staging vs readback vs H2D breakdown per dispatch.
    It then re-checks token parity (device vs host-staged) across all four
    dispatch modes with greedy and seeded sampling — a perf path that
    changes tokens is a non-result. One JSON line out.
    """
    import numpy as np
    import jax

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
    from deepspeed_tpu.models import llama

    e = os.environ
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model_cfg = llama.LlamaConfig(
            vocab_size=32768, hidden_size=2048, intermediate_size=5632,
            num_layers=8, num_heads=16, num_kv_heads=8, max_seq_len=1024)
        n_req, prompt_len, max_new = 16, 64, 96
        max_seqs, budget, block, ahead = 16, 256, 32, 32
        fused, depth, tile = 16, 3, 64
        sched_k, econ_k, econ_new = 16, 128, 190
    else:
        model_cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=688,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)
        n_req, prompt_len = 4, 16
        max_new = int(e.get("BENCH_STEADY_MAX_NEW", 24))
        max_seqs, budget, block, ahead = 4, 64, 16, 8
        fused, depth, tile = 4, 2, 16
        sched_k, econ_k, econ_new = 8, 128, 190

    rng = np.random.default_rng(0)
    # equal-length prompts: the dense baseline then pads nothing, so the
    # ragged-vs-dense ratio isolates dispatch overhead, not padding waste
    prompts = [rng.integers(0, model_cfg.vocab_size, (prompt_len,),
                            dtype=np.int32) for _ in range(n_req)]
    mbs = -(-(prompt_len + max_new) // block)
    build_model = lambda ctx: llama.build(model_cfg, ctx=ctx)  # noqa: E731

    def build(device_state, **over):
        kw = dict(max_tokens_per_step=budget, max_seqs=max_seqs,
                  block_size=block, num_blocks=max_seqs * mbs + 1,
                  max_blocks_per_seq=mbs, decode_run_ahead=ahead,
                  prefill_tile=tile, fused_chunk=fused, pipeline_depth=depth,
                  device_state=device_state)
        kw.update(over)
        return RaggedInferenceEngine(
            model=build_model, ragged_config=RaggedConfig(**kw), seed=0)

    def run(engine, tag):
        for i, p in enumerate(prompts):
            engine.put((tag, i), p, max_new_tokens=max_new)
        return engine.generate_all()

    def measure(device_state, **over):
        engine = build(device_state, **over)
        run(engine, "warm")  # compiles every bucket this workload hits
        # reset the dispatch-overhead meters: the warmup pass pays tracing +
        # compilation on the host, which is not steady-state staging cost
        engine.host_stage_ns = engine.readback_ns = 0
        engine.h2d_bytes = engine._h2d_seen = 0
        d0 = engine.dispatch_count
        t0 = time.perf_counter()
        out = run(engine, "run")
        dt = time.perf_counter() - t0
        disp = max(engine.dispatch_count - d0, 1)
        toks = sum(len(v) for v in out.values())
        return {
            "tokens_per_s": round(toks / dt, 1),
            "host_stage_ms_per_step": round(
                engine.host_stage_ns / disp / 1e6, 4),
            "readback_ms_per_step": round(
                engine.readback_ns / disp / 1e6, 4),
            "h2d_bytes_per_step": round(engine.h2d_bytes / disp, 1),
            "dispatches": disp,
            "wall_s": round(dt, 3),
        }, out

    dev, dev_out = measure(True)
    host, host_out = measure(False)
    # the PR-10 headline: K decode steps per dispatch via the device-side
    # multi-step scheduler (speculation stays OFF here — random weights
    # give the n-gram draft source nothing to match, so acceptance would
    # only add verify lanes; its win is measured separately below)
    sch, sch_out = measure(True, sched_steps=sched_k)

    dense = InferenceEngine(model=build_model, seed=0)
    batch = np.stack(prompts)
    dense.generate(batch, max_new_tokens=max_new)  # compile
    t0 = time.perf_counter()
    dense.generate(batch, max_new_tokens=max_new)
    dense_tok_s = n_req * max_new / (time.perf_counter() - t0)

    # token parity, all 4 dispatch modes x greedy+seeded, device vs host
    modes = {
        "plain": dict(decode_run_ahead=0, prefill_tile=0, fused_chunk=0),
        "tiled": dict(decode_run_ahead=0, fused_chunk=0),
        "run_ahead": dict(prefill_tile=0, fused_chunk=0),
        "fused": {},
    }

    def parity_run(engine):
        for i, p in enumerate(prompts[:3]):
            kw = {} if i == 0 else dict(temperature=0.9, top_k=20,
                                        top_p=0.9, seed=7 + i)
            engine.put(i, p, max_new_tokens=6, **kw)
        return engine.generate_all()

    # three verdicts per mode, all against the plain host-staged streams:
    # device-resident state, the multi-step scheduler, and scheduler +
    # self-speculation (exact-match verify => must be token-identical)
    parity, sched_parity, spec_parity = {}, {}, {}
    for name, over in modes.items():
        base = parity_run(build(False, **over))
        parity[name] = parity_run(build(True, **over)) == base
        sched_parity[name] = parity_run(
            build(True, sched_steps=sched_k, **over)) == base
        spec_parity[name] = parity_run(
            build(True, sched_steps=sched_k, spec_draft=4, **over)) == base

    # speculation acceptance on a draftable workload: a repetitive prompt
    # gives the n-gram source real matches (random weights + random prompts
    # would measure nothing)
    spec_eng = build(True, sched_steps=sched_k, spec_draft=4)
    pat = list(rng.integers(0, model_cfg.vocab_size, (5,))) * 4
    spec_eng.put("rep", np.asarray(pat, np.int32), max_new_tokens=max_new)
    spec_eng.generate_all()
    spec_rate = spec_eng.spec_accepted / max(spec_eng.spec_proposed, 1)

    # dispatch economy under staggered arrivals: requests trickle in, and
    # once the LAST arrival reaches steady decode the scheduler should run
    # the whole remaining tail at K steps per dispatch — dispatches per
    # token over that steady segment is the number the flat per-dispatch
    # RTT multiplies
    mbs_econ = -(-(prompt_len + econ_new) // block)
    econ = RaggedInferenceEngine(
        model=build_model, ragged_config=RaggedConfig(
            max_tokens_per_step=budget, max_seqs=max_seqs,
            block_size=block, num_blocks=max_seqs * mbs_econ + 1,
            max_blocks_per_seq=mbs_econ, sched_steps=econ_k), seed=0)
    fed = 0
    d0 = t0 = None
    for step_i in range(100000):
        # one arrival per engine turn: each new request prefillls while the
        # earlier ones decode, so no row ever runs a deep solo chunk before
        # the batch fills
        if fed < n_req:
            econ.put(fed, prompts[fed], max_new_tokens=econ_new)
            fed += 1
        if not econ.has_work:
            break
        econ.step()
        if (d0 is None and fed == n_req and not econ._queued
                and all(s.in_decode for s in econ._running.values())):
            d0, t0 = econ.dispatch_count, econ.tokens_emitted
    econ.drain()
    econ_disp = econ.dispatch_count - d0
    econ_toks = max(econ.tokens_emitted - t0, 1)
    stag_dpt = round(econ_disp / econ_toks, 4)

    print(json.dumps({
        "steady_ragged_tokens_per_s": sch["tokens_per_s"],
        "steady_ragged_no_sched_tokens_per_s": dev["tokens_per_s"],
        "steady_host_staged_tokens_per_s": host["tokens_per_s"],
        "steady_dense_tokens_per_s": round(dense_tok_s, 1),
        # the headline: multi-step scheduled decode vs the dense padded
        # engine (was 0.276 with one host dispatch per token-step)
        "steady_ragged_vs_dense": round(
            sch["tokens_per_s"] / dense_tok_s, 3),
        "steady_ragged_vs_dense_no_sched": round(
            dev["tokens_per_s"] / dense_tok_s, 3),
        "ragged_vs_dense": round(sch["tokens_per_s"] / dense_tok_s, 3),
        # how much per-dispatch host staging the device-resident path
        # removed vs the pre-PR host-staged path
        "steady_staging_reduction": round(
            host["host_stage_ms_per_step"]
            / max(dev["host_stage_ms_per_step"], 1e-9), 2),
        "steady_device_state": dev,
        "steady_host_staged": host,
        "steady_sched": sch,
        "steady_sched_steps": sched_k,
        "steady_dispatches_per_token": round(
            sch["dispatches"] / max(n_req * max_new, 1), 4),
        # dispatch economy over the steady tail of a staggered-arrival run
        # (scheduler depth econ_k, generation econ_new)
        "staggered_dispatches_per_token": stag_dpt,
        "staggered_econ_dispatches": econ_disp,
        "staggered_econ_tokens": econ_toks,
        "steady_outputs_match": dev_out == host_out and sch_out == host_out,
        "steady_parity": parity,
        "steady_sched_parity": sched_parity,
        "steady_spec_parity": spec_parity,
        "steady_spec_proposed": spec_eng.spec_proposed,
        "steady_spec_accepted": spec_eng.spec_accepted,
        "steady_spec_acceptance_rate": round(spec_rate, 3),
        "steady_reqs": n_req,
        "steady_max_new": max_new,
    }))


def run_decode_steady_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_DECODE_STEADY", timeout)


def train_anatomy_main():
    """Child process: training step anatomy (telemetry/stepscope.py).

    Runs a short training loop with stepscope enabled — per-step phase
    decomposition (data wait / H2D / forward / backward / grad collectives /
    optimizer / recompile / checkpoint stall), MFU attribution, overlap
    fraction and goodput — and emits the full breakdown as one JSON line so
    BENCH_r0x records track overlap/goodput alongside MFU (ROADMAP item #4's
    measurement harness). Also exports the step→phase trace and reports span
    counts plus the scrape-visibility of the headline gauges, which the CI
    smoke step asserts on.
    """
    import tempfile

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.telemetry import TELEMETRY

    e = os.environ
    model_cfg = llama.LlamaConfig(
        vocab_size=int(e.get("BENCH_ANATOMY_VOCAB", 512)),
        hidden_size=int(e.get("BENCH_ANATOMY_HIDDEN", 128)),
        intermediate_size=int(e.get("BENCH_ANATOMY_FFN", 256)),
        num_layers=int(e.get("BENCH_ANATOMY_LAYERS", 2)),
        num_heads=int(e.get("BENCH_ANATOMY_HEADS", 4)),
        num_kv_heads=int(e.get("BENCH_ANATOMY_KV", 2)),
        max_seq_len=int(e.get("BENCH_ANATOMY_SEQ", 128)),
    )
    seq = int(e.get("BENCH_ANATOMY_SEQ", 128))
    steps = int(e.get("BENCH_ANATOMY_STEPS", 8))
    gas = int(e.get("BENCH_ANATOMY_GAS", 2))
    # default batch covers gas x dp (8 simulated devices on the CPU backend)
    batch = int(e.get("BENCH_ANATOMY_BATCH",
                      max(8, gas * jax.device_count())))
    # device-capture window every N steps (0 disables); the default lands
    # one window inside the default step budget, past warmup/compile
    profile_interval = int(e.get("BENCH_ANATOMY_PROFILE_INTERVAL", 4))

    runs_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "runs")
    os.makedirs(runs_dir, exist_ok=True)
    config = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": gas,
        "sequence_length": seq,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1},
        "telemetry": {
            "enabled": True,
            "jsonl_path": os.path.join(runs_dir,
                                       "BENCH_train_anatomy_telemetry.jsonl"),
            "stepscope": {
                "enabled": True,
                "profile_interval_steps": profile_interval,
                "profile_dir": os.path.join(runs_dir, "devprof"),
            },
        },
    }
    def run_leg(overlap_on: bool, checkpoint: bool = False):
        """One training leg: identical data/seed, grad_overlap toggled.

        Returns the stepscope summary, devprof capture, final params and the
        per-leg overlap gauges — the off leg is the fused baseline the on
        leg's parity and latency-hiding claims are measured against."""
        from deepspeed_tpu.comm.topology import reset_topology

        reset_topology()
        # fresh trace ring + registry per leg: the exported trace and the
        # scrape asserts below see only the on leg's spans/gauges
        TELEMETRY.reset()
        leg_cfg = json.loads(json.dumps(config))
        # the overlap path needs a data axis to reduce over; single-device
        # runs degrade to an off-vs-off A/B (parity trivially exact)
        if overlap_on and jax.device_count() > 1:
            leg_cfg["zero_optimization"]["grad_overlap"] = {
                "enabled": True,
                "bucket_bytes": int(e.get("BENCH_ANATOMY_BUCKET_BYTES",
                                          4 << 20)),
            }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(model_cfg, ctx=ctx), config=leg_cfg)

        rng = np.random.default_rng(0)

        def data_iter():
            while True:
                yield {"input_ids": rng.integers(
                    0, model_cfg.vocab_size,
                    (batch // gas, seq), dtype=np.int32)}

        it = data_iter()
        for _ in range(steps):
            engine.train_batch(data_iter=it)
        if checkpoint:
            # one checkpoint save so the goodput ledger has a checkpoint entry
            with tempfile.TemporaryDirectory() as ckpt_dir:
                engine.save_checkpoint(ckpt_dir)
        summary = engine.stepscope.summary()
        devprof_last = engine.devprof_last
        devprof_summary = (devprof_last or {}).get("summary")
        phase_totals = summary.get("phase_seconds_total") or {}
        total_phase = sum(phase_totals.values()) or 1.0
        leg = {
            "summary": summary,
            "devprof_last": devprof_last,
            "devprof_summary": devprof_summary,
            "params": jax.tree_util.tree_map(np.asarray, engine.params),
            "overlap_fraction_estimate": summary.get("overlap_fraction"),
            "overlap_fraction_measured":
                (devprof_summary or {}).get("overlap_fraction_measured"),
            # ZeRO-1 sharded update: the optimizer phase share should SHRINK
            # on the on leg (each rank updates 1/dp of every bucket)
            "optimizer_phase_share":
                phase_totals.get("optimizer", 0.0) / total_phase,
            # per-bucket wire time: the devprof families feeding the
            # devprof_collective_seconds{op=} histogram
            "collective_wire": [
                {"op": c.get("op"), "seconds": c.get("seconds"),
                 "count": c.get("count")}
                for c in (devprof_summary or {}).get("collectives", [])],
        }
        return engine, leg

    # leg A: fused baseline (overlap off); leg B: bucketed async overlap.
    # Same seed, same data stream — leg B's params must stay inside the
    # documented fp-reorder bound of leg A's.
    off_engine, leg_off = run_leg(overlap_on=False)
    off_engine.destroy()
    engine, leg_on = run_leg(overlap_on=True, checkpoint=True)

    parity_drift = max(
        float(np.max(np.abs(a - b)))
        for a, b in zip(jax.tree_util.tree_leaves(leg_off["params"]),
                        jax.tree_util.tree_leaves(leg_on["params"])))
    # documented fp-reorder bound (ring sum order + local-mean-then-pmean;
    # docs/TP_OVERLAP.md "grad-sync overlap") at bf16 compute precision
    parity_ok = parity_drift < float(e.get("BENCH_ANATOMY_PARITY_TOL", 5e-3))

    summary = leg_on["summary"]
    devprof_last = leg_on["devprof_last"]
    devprof_summary = leg_on["devprof_summary"]
    measured_overlap = leg_on["overlap_fraction_measured"]

    trace_path = os.path.join(runs_dir, "BENCH_train_anatomy_trace.json")
    trace = TELEMETRY.dump_trace(trace_path)
    events = trace.get("traceEvents", [])
    step_spans = [ev for ev in events if ev.get("name") == "train/step"]
    step_ids = {ev.get("args", {}).get("span_id") for ev in step_spans}
    phase_spans = [ev for ev in events
                   if str(ev.get("name", "")).startswith("train/phase/")]
    nested = [ev for ev in phase_spans
              if ev.get("args", {}).get("parent_id") in step_ids]
    phase_ids = {ev.get("args", {}).get("span_id") for ev in phase_spans}
    host_ids = step_ids | phase_ids
    device_spans = [ev for ev in events
                    if str(ev.get("name", "")).startswith("device/")]
    device_nested = [ev for ev in device_spans
                     if ev.get("args", {}).get("parent_id") in host_ids]
    prom = TELEMETRY.registry.render_prometheus()

    engine.destroy()
    print(json.dumps({
        "error": None,
        "anatomy": summary,
        "steps": steps,
        "train_batch_size": batch,
        "gas": gas,
        "overlap_fraction_estimate": summary.get("overlap_fraction"),
        "overlap_fraction_measured": measured_overlap,
        # A/B overlap anatomy: fused baseline (off) vs bucketed async
        # grad collectives + sharded update (on), same seed and data
        "overlap": {
            "enabled": jax.device_count() > 1,
            "parity_max_drift": parity_drift,
            "parity_ok": parity_ok,
            "off": {
                "overlap_fraction_estimate":
                    leg_off["overlap_fraction_estimate"],
                "overlap_fraction_measured":
                    leg_off["overlap_fraction_measured"],
                "optimizer_phase_share": leg_off["optimizer_phase_share"],
                "collective_wire": leg_off["collective_wire"],
            },
            "on": {
                "overlap_fraction_estimate":
                    leg_on["overlap_fraction_estimate"],
                "overlap_fraction_measured":
                    leg_on["overlap_fraction_measured"],
                "optimizer_phase_share": leg_on["optimizer_phase_share"],
                "collective_wire": leg_on["collective_wire"],
            },
        },
        "devprof": {
            "enabled": profile_interval > 0,
            "summary": devprof_summary,
            "merged_spans": (devprof_last or {}).get("merged_spans", 0),
            "op_count": (devprof_summary or {}).get("op_count", 0),
        },
        "trace_path": trace_path,
        "trace_step_spans": len(step_spans),
        "trace_phase_spans": len(phase_spans),
        "trace_nested_phase_spans": len(nested),
        "trace_device_spans": len(device_spans),
        "trace_nested_device_spans": len(device_nested),
        "scrape_has_overlap": "train_overlap_fraction" in prom,
        "scrape_has_estimate_overlap":
            'train_overlap_fraction{source="estimate"}' in prom,
        "scrape_has_measured_overlap":
            'train_overlap_fraction{source="measured"}' in prom,
        "scrape_has_devprof_capture": "devprof_captures_total" in prom,
        "scrape_has_goodput": "train_goodput" in prom,
        "scrape_has_phase_histogram": "step_phase_seconds" in prom,
        "scrape_has_flops_source": "train_flops_source" in prom,
    }))
    return 0


def run_train_anatomy_subprocess(timeout: float = 900.0):
    # the overlap A/B needs a data axis: on the CPU backend simulate the
    # 8-device mesh (tests/conftest.py's strategy); real accelerators keep
    # their native device count
    extra = None
    flags = os.environ.get("XLA_FLAGS", "")
    if (os.environ.get("JAX_PLATFORMS") == "cpu"
            and "xla_force_host_platform_device_count" not in flags):
        extra = {"XLA_FLAGS":
                 (flags + " --xla_force_host_platform_device_count=8").strip()}
    return _run_flagged_subprocess("BENCH_TRAIN_ANATOMY", timeout,
                                   extra_env=extra)


def infinity_trial_main():
    """Child process: ZeRO-Infinity offload rung — train a model whose fp32
    training state EXCEEDS the chip's HBM (params + Adam moments + grads),
    only possible because master params/optimizer state live in pinned host
    DRAM and stream through HBM per scanned layer / per optimizer sub-group
    (runtime/param_offload.py; round-4 item 1 'done' criterion). Prints one
    JSON line of offload metrics."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.comm.topology import reset_topology
    from deepspeed_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        _, hbm = chip_spec(getattr(jax.devices()[0], "device_kind", ""))
        # ~1.15B params: fp32 state = params(4) + m(4) + v(4) + grads(4)
        # = 16 bytes/param = 18.4 GB > the 16 GB-class chip this runs on
        # (on bigger chips the claim is still reported, just not exceeded)
        model_cfg = llama.LlamaConfig(
            vocab_size=8192, hidden_size=2048, intermediate_size=5504,
            num_layers=24, num_heads=16, num_kv_heads=8, max_seq_len=512)
        batch_sz, seq = 2, 512
    else:
        hbm = 16e9
        model_cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=344,
            num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=128)
        batch_sz, seq = 2, 64
    reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx),
        config={
            "train_micro_batch_size_per_device": batch_sz,
            "gradient_accumulation_steps": 1, "steps_per_print": 0,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
            "zero_optimization": {
                "stage": 3, "sub_group_size": 100_000_000,
                "offload_param": {"device": "cpu"},
                "offload_optimizer": {"device": "cpu"}},
            "activation_checkpointing": {"enabled": True},
            "mesh": {"data": 1, "fsdp": 1}, "seed": 7,
        }, seed=7)
    n_params = engine.model_spec.num_params
    state_bytes = n_params * 16
    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(
            0, model_cfg.vocab_size, (batch_sz, seq), dtype=np.int32)}

    l0 = float(engine.train_batch(make_batch()))  # compile
    t0 = time.perf_counter()
    l1 = float(engine.train_batch(make_batch()))
    jax.block_until_ready(engine.params)
    step_s = time.perf_counter() - t0
    # device footprint of the fwd/bwd program: host args hold the masters
    dev_arg = host_arg = -1
    try:
        if engine._grads_jit is None:
            engine._grads_jit = engine._build_grads_fn()
        db = engine._put_gas_batch(make_batch())
        ma = engine._grads_jit.lower(
            engine.params, engine.scale_state, jnp.int32(0),
            engine._train_rng, db).compile().memory_analysis()
        dev_arg = int(ma.argument_size_in_bytes)
        host_arg = int(ma.host_argument_size_in_bytes)
    except Exception:
        pass
    print(json.dumps({
        "infinity_params": n_params,
        "infinity_state_gb": round(state_bytes / 2**30, 1),
        "infinity_hbm_gb": round(hbm / 2**30, 1),
        "infinity_state_exceeds_hbm": bool(state_bytes > hbm),
        "infinity_step_s": round(step_s, 2),
        "infinity_loss_finite": bool(np.isfinite(l0) and np.isfinite(l1)),
        "infinity_device_arg_bytes": dev_arg,
        "infinity_host_arg_bytes": host_arg,
    }))


def run_infinity_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_INFINITY", timeout)


def learn_trial_main():
    """Child process: learning-evidence rung — byte-level LM on real text
    (this repo's own source corpus; the environment has no network egress, so
    a local natural-text corpus approximates BASELINE.md's loss-curve-parity
    bar within this sandbox). ~50 steps must show clear descent: the MFU
    headline ships with evidence the step actually learns, not just runs.
    Prints one JSON line of learning metrics.
    """
    import numpy as np
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    here = os.path.dirname(os.path.abspath(__file__))
    chunks = []
    for root, _, files in sorted(os.walk(os.path.join(here, "deepspeed_tpu"))):
        for f in sorted(files):
            if f.endswith(".py"):
                with open(os.path.join(root, f), "rb") as fh:
                    chunks.append(fh.read())
    corpus = np.frombuffer(b"\n".join(chunks), np.uint8).astype(np.int32)

    if on_tpu:
        model_cfg = llama.LlamaConfig(
            vocab_size=256, hidden_size=384, intermediate_size=1024,
            num_layers=6, num_heads=6, num_kv_heads=6, max_seq_len=512)
        steps, batch, seq = 50, 32, 512
    else:
        model_cfg = llama.LlamaConfig(
            vocab_size=256, hidden_size=128, intermediate_size=344,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128)
        steps, batch, seq = 20, 8, 128

    config = {
        "train_micro_batch_size_per_device": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "sequence_length": seq,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4,
                                                  "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_max_lr": 3e-4, "warmup_num_steps": 10}},
        "mesh": {"data": -1},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx), config=config)

    rng = np.random.default_rng(1)

    def make_batch():
        starts = rng.integers(0, len(corpus) - seq - 1, batch)
        return {"input_ids": np.stack([corpus[s:s + seq] for s in starts])}

    losses = [float(engine.train_batch(make_batch())) for _ in range(steps)]
    initial = float(np.mean(losses[:3]))
    final = float(np.mean(losses[-3:]))
    print(json.dumps({
        "learn_initial_loss": round(initial, 4),
        "learn_final_loss": round(final, 4),
        "learn_steps": steps,
        "learn_corpus_bytes": int(len(corpus)),
        # pass bar: clear descent on real text (random-init byte LM starts
        # near ln(256)=5.55; structure should cut it well under 70% by ~50
        # steps at this scale)
        "learn_pass": bool(final < 0.7 * initial),
    }))


def _run_flagged_subprocess(env_flag: str, timeout: float = 900.0,
                            extra_env: dict | None = None):
    """Re-exec this file with ``env_flag=1`` and parse the trailing JSON line
    (the serve/learn trial pattern; run_trial_subprocess builds its env from
    shape vars so it stays separate)."""
    env = dict(os.environ)
    env[env_flag] = "1"
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, _child_error(f"timed out after {timeout:g}s",
                                  flag=env_flag)
    if proc.returncode != 0:
        return None, _child_error("child exited nonzero", proc, flag=env_flag)
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, _child_error(f"no JSON in {env_flag} output", proc,
                              flag=env_flag)


def run_learn_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_LEARN", timeout)


def run_serve_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_SERVE", timeout)


def serving_bench_main():
    """Child process: the full serving tier under open-loop Poisson load.

    Where serve_trial_main measures the *engine* (closed workload, direct
    ``put()``/``generate_all()``), this drives the whole stack a deployment
    would run — HTTP frontend → router admission → EngineLoop → ragged
    engine — with a Poisson open-loop client (arrivals don't wait for
    completions, the standard serving-bench discipline: closed-loop clients
    hide queueing collapse). Reports the latencies a user would see:
    p50/p99 TTFT, per-token decode latency, rejected-request rate (429s),
    and goodput (useful tokens/s over wall time). One JSON line out.
    """
    import http.client
    import threading

    import numpy as np
    import jax

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.serving import RouterConfig, build_server

    e = os.environ
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model_cfg = llama.LlamaConfig(
            vocab_size=32768, hidden_size=2048, intermediate_size=5632,
            num_layers=8, num_heads=16, num_kv_heads=8, max_seq_len=1024)
        n_req, max_new, rate = 48, 48, 8.0
        prompt_lens = [64, 128, 256, 512]
        max_seqs, budget, block, tile, ahead = 32, 1024, 32, 128, 48
        fused, depth, max_prompt = 16, 3, 512
    else:
        model_cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=688,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)
        n_req, max_new, rate = 10, 8, 4.0
        prompt_lens = [16, 32, 64]
        max_seqs, budget, block, tile, ahead = 4, 64, 16, 16, 8
        fused, depth, max_prompt = 4, 2, 64
    n_req = int(e.get("BENCH_SERVING_REQUESTS", n_req))
    rate = float(e.get("BENCH_SERVING_RATE", rate))  # arrivals per second
    # shared-prefix workload (--shared-prefix-tokens): every prompt opens
    # with the same N tokens (system prompt / few-shot template traffic) and
    # the engine runs with the block-level prefix cache on — after the first
    # request retires, later prefills splice the shared blocks instead of
    # recomputing them
    shared_prefix = int(e.get("BENCH_SERVING_SHARED_PREFIX", 0))
    # tiered KV cache (--kv-tier): shrink the HBM pool so the shared-prefix
    # working set overflows it by >=3x, and let the engine demote evicted
    # prefix blocks host-ward instead of dropping them (docs/SERVING.md)
    kv_tier = e.get("BENCH_SERVING_KV_TIER", "") not in ("", "0")
    # low-bit KV serving (--kv-quant): the tiered workload with the pool,
    # tier payloads, prefix splices and handoffs all running the named
    # codec (docs/SERVING.md "Low-bit serving"). Implies --kv-tier so the
    # combined hit rate measures restores of *quantized* payloads, and
    # adds a quant-vs-fp drift probe to the verdict.
    kv_quant = e.get("BENCH_SERVING_KV_QUANT", "")
    if kv_quant in ("0", "off"):
        kv_quant = ""
    if kv_quant:
        kv_tier = True
    if kv_tier and shared_prefix == 0:
        shared_prefix = 2 * block  # two full blocks per prefix group

    tel_path = e.get("BENCH_TELEMETRY_JSONL", os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "runs",
        "BENCH_serving_telemetry.jsonl"))
    telemetry.configure(enabled=True, jsonl_path=tel_path, memledger=True)

    if shared_prefix >= max_prompt:
        raise SystemExit(f"BENCH_SERVING_SHARED_PREFIX={shared_prefix} must "
                         f"be < the max prompt length ({max_prompt})")
    mbs = -(-(max_prompt + max_new) // block)
    num_blocks = max_seqs * mbs + 1
    if kv_tier:
        # tiny HBM budget: roughly two in-flight requests' worth, so the
        # n_groups x (prefix + tails) working set is >=3x the pool and
        # every reuse after churn crosses a tier boundary
        num_blocks = 2 * mbs + 1
    rcfg = RaggedConfig(
        max_tokens_per_step=budget, max_seqs=max_seqs, block_size=block,
        num_blocks=num_blocks, max_blocks_per_seq=mbs,
        decode_run_ahead=ahead, prefill_tile=tile,
        fused_chunk=fused, pipeline_depth=depth,
        enable_prefix_cache=shared_prefix > 0 or kv_tier,
        kv_tier=kv_tier,
        kv_tier_host_blocks=4 * mbs,
        kv_tier_disk_blocks=8 * mbs,
        kv_tier_dir=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "runs", "kvtier",
            f"bench-{os.getpid()}"),
        quant=kv_quant or "off")
    engine = RaggedInferenceEngine(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx),
        ragged_config=rcfg, seed=0)
    engine.warmup()

    frontend, router, loops = build_server(
        [engine], router_cfg=RouterConfig(
            max_queue_tokens=int(e.get("BENCH_SERVING_QUEUE_TOKENS", 2048))))

    rng = np.random.default_rng(0)
    if kv_tier:
        # n_groups distinct shared prefixes, every unique prompt issued
        # TWICE with identical sampling params: deterministic per-request
        # seeds make the pair token-identical whether the second admission
        # re-prefilled, spliced HBM blocks, or restored demoted tiers —
        # so occurrence parity is the end-to-end tiering check
        n_groups = 3
        n_req = int(e.get("BENCH_SERVING_REQUESTS", 2 * n_groups * 4))
        n_uniq = max(n_groups, n_req // 2)
        prefixes = [rng.integers(0, model_cfg.vocab_size, (shared_prefix,),
                                 dtype=np.int32).tolist()
                    for _ in range(n_groups)]
        reqs = []  # (uniq_id, prompt, sampling-extras)
        for u in range(n_uniq):
            p = prefixes[u % n_groups] + rng.integers(
                0, model_cfg.vocab_size, (max_prompt - shared_prefix,),
                dtype=np.int32).tolist()
            extra = {} if u % 2 == 0 else \
                {"temperature": 0.9, "top_k": 20, "seed": 1000 + u}
            reqs.append((u, p, extra))
        reqs = [reqs[i % n_uniq] for i in range(n_req)]
        rng.shuffle(reqs)
        prompts = [r[1] for r in reqs]
    else:
        prefix = rng.integers(0, model_cfg.vocab_size, (shared_prefix,),
                              dtype=np.int32).tolist()
        prompts = [prefix + rng.integers(
            0, model_cfg.vocab_size,
            (max(1, int(prompt_lens[i % len(prompt_lens)]) - shared_prefix),),
            dtype=np.int32).tolist() for i in range(n_req)]
        rng.shuffle(prompts)
        reqs = [(i, p, {}) for i, p in enumerate(prompts)]
    # open-loop schedule: exponential inter-arrival gaps, fixed before the
    # clock starts so client-side jitter can't thin the offered load
    gaps = rng.exponential(1.0 / rate, n_req)
    arrivals = np.cumsum(gaps)

    results = []  # dicts: {rejected, ttft, token_times, useful}
    results_lock = threading.Lock()

    def one_request(prompt, extra=None, uniq_id=None):
        conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                          timeout=120)
        body = json.dumps({"prompt": prompt, "max_tokens": max_new,
                           "stream": True, **(extra or {})})
        t_send = time.perf_counter()
        rec = {"rejected": False, "ttft": None, "token_times": [],
               "useful": 0, "tokens": [], "uniq_id": uniq_id}
        try:
            conn.request("POST", "/v1/completions", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status == 429:
                rec["rejected"] = True
                resp.read()
                return rec
            while True:
                line = resp.readline()
                if not line:
                    break
                if not line.startswith(b"data:"):
                    continue
                data = line[5:].strip()
                if data == b"[DONE]":
                    break
                frame = json.loads(data)
                if "token" in frame:
                    now = time.perf_counter()
                    if rec["ttft"] is None:
                        rec["ttft"] = now - t_send
                    rec["token_times"].append(now)
                    rec["tokens"].append(frame["token"])
            rec["useful"] = len(prompt) + len(rec["token_times"])
        finally:
            conn.close()
        return rec

    if kv_tier:
        # serial per-group warmup: publish each prefix once before the open
        # loop so group misses are the warmups, not a thundering-herd race
        for g in range(n_groups):
            one_request(prefixes[g] + [1, 2, 3], extra={"max_tokens": 1})

    threads = []
    t0 = time.perf_counter()
    for i in range(n_req):
        delay = t0 + arrivals[i] - time.perf_counter()
        if delay > 0:
            time.sleep(delay)

        def fire(r=reqs[i]):
            rec = one_request(r[1], extra=r[2], uniq_id=r[0])
            with results_lock:
                results.append(rec)

        th = threading.Thread(target=fire, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=300)
    wall = time.perf_counter() - t0
    frontend.drain(timeout=60)

    done = [r for r in results if not r["rejected"] and r["ttft"] is not None]
    rejected = sum(1 for r in results if r["rejected"])
    ttfts = [r["ttft"] for r in done]
    gaps_s = [g for r in done
              for g in np.diff(r["token_times"]).tolist()]
    goodput = sum(r["useful"] for r in done) / wall if wall > 0 else 0.0
    decided = engine.prefix_hits + engine.prefix_misses
    cache_stats = {
        "serving_shared_prefix_tokens": shared_prefix,
        "serving_prefix_cache_hits": engine.prefix_hits,
        "serving_prefix_cache_hit_rate":
            round(engine.prefix_hits / decided, 4) if decided else 0.0,
        "serving_prefill_tokens_saved": engine.prefix_tokens_reused,
        "serving_prefix_cache_evictions": engine.allocator.evictions,
        "serving_tokens_scheduled": engine.tokens_scheduled,
    } if shared_prefix > 0 else {}
    kv_tier_stats = {}
    if kv_tier:
        st = engine.kv_tier_stats() or {}
        # occurrence parity: both sends of a unique prompt must stream the
        # same tokens — the tiered splice may never show in the output
        by_uniq = {}
        for r in done:
            if r.get("uniq_id") is not None:
                by_uniq.setdefault(r["uniq_id"], []).append(r["tokens"])
        pairs = [v for v in by_uniq.values() if len(v) >= 2]
        parity_ok = all(all(t == v[0] for t in v[1:]) for v in pairs)
        promoted = (st.get("promoted_admissions_host", 0)
                    + st.get("promoted_admissions_disk", 0))
        kv_tier_stats = {
            "enabled": True,
            "hbm_blocks": rcfg.num_blocks,
            "combined_hit_rate":
                round(engine.prefix_hits / decided, 4) if decided else 0.0,
            "hits_from_hbm": engine.prefix_hits - promoted,
            "hits_via_host_restore": st.get("promoted_admissions_host", 0),
            "hits_via_disk_restore": st.get("promoted_admissions_disk", 0),
            "parity_pairs_checked": len(pairs),
            "parity_ok": parity_ok,
            **{f"kvtier_{k}": v for k, v in st.items()},
        }
    kv_quant_stats = {}
    if kv_quant:
        from deepspeed_tpu.inference import kvquant as _kvq

        qst = engine.kv_quant_stats() or {}

        # drift probe: the SAME prompts through a quant-off and a quant-on
        # engine (spec decode on, so the verdict covers both budget axes:
        # greedy token-match rate and spec accept-rate drift)
        def _probe(qspec):
            pcfg = RaggedConfig(
                max_tokens_per_step=budget, max_seqs=2, block_size=block,
                num_blocks=2 * mbs + 1, max_blocks_per_seq=mbs,
                sched_steps=8, spec_draft=4, quant=qspec)
            pe = RaggedInferenceEngine(
                model=lambda ctx: llama.build(model_cfg, ctx=ctx),
                ragged_config=pcfg, seed=0)
            for i in range(3):
                pe.put(i, [int(t) for t in prompts[i][:32]],
                       max_new_tokens=12)
            toks = pe.generate_all()
            acc = (pe.spec_accepted / pe.spec_proposed
                   if pe.spec_proposed else None)
            return toks, acc

        base_toks, base_acc = _probe("off")
        q_toks, q_acc = _probe(kv_quant)
        match = _kvq.token_match_rate(base_toks, q_toks)
        drift = (abs(q_acc - base_acc)
                 if base_acc is not None and q_acc is not None else None)
        kv_quant_stats = {
            "enabled": True,
            "codec": qst.get("codec", kv_quant),
            "resident_block_multiplier":
                round(qst.get("resident_multiplier_vs_fp16", 0.0), 4),
            "kv_block_bytes": qst.get("block_bytes"),
            "fp16_block_bytes": qst.get("fp16_block_bytes"),
            "blocks_allocated_total": qst.get("blocks_allocated_total"),
            "bytes_saved_total": qst.get("bytes_saved_total"),
            "drift": _kvq.drift_verdict(match, drift),
        }
    # memory-ledger picture BEFORE close() tears the ledger down: per-owner
    # bytes + the final census gap (the leak detector's reading for the run)
    led = telemetry.TELEMETRY.memledger
    memory = {}
    if led is not None:
        census = led.census()
        memory = {
            "owners": {k: v for k, v in led.owner_bytes().items() if v},
            "attributed_bytes": census["attributed_bytes"],
            "live_bytes": census["live_bytes"],
            "unattributed_bytes": census["unattributed_bytes"],
            "unattributed_fraction": census["unattributed_fraction"],
            "drift_alarm": census["drift_alarm"],
            "oom_reports": list(led.oom_reports),
        }
        if kv_tier:
            # per-tier residency so the off-device bytes the census excludes
            # from reconciliation are still visible next to the device pool
            st = engine.kv_tier_stats() or {}
            memory["kv_tier_bytes"] = {
                "host": st.get("host_bytes", 0),
                "disk": st.get("disk_bytes", 0),
            }
            memory["offdevice_bytes"] = census.get("offdevice_bytes", 0)
    if kv_tier and engine._kvtier is not None:
        # per-pid spill directory: drop it with the run so repeated bench
        # invocations don't accumulate dead records under runs/kvtier/
        engine._kvtier.close()
        import shutil
        shutil.rmtree(rcfg.kv_tier_dir, ignore_errors=True)
    telemetry.TELEMETRY.close()
    print(json.dumps({
        "metric": "serving_frontend_poisson",
        "serving_requests": n_req,
        "serving_rate_rps": rate,
        **cache_stats,
        **({"kv_tier": kv_tier_stats} if kv_tier_stats else {}),
        **({"kv_quant": kv_quant_stats} if kv_quant_stats else {}),
        "serving_completed": len(done),
        "serving_rejected": rejected,
        "serving_rejected_rate": round(rejected / max(1, len(results)), 4),
        "serving_ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 2)
        if ttfts else None,
        "serving_ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 2)
        if ttfts else None,
        "serving_token_latency_ms": round(float(np.mean(gaps_s)) * 1e3, 2)
        if gaps_s else None,
        "serving_goodput_tokens_per_s": round(goodput, 1),
        "serving_wall_s": round(wall, 2),
        "memory": memory,
        "backend": jax.default_backend(),
        "telemetry_jsonl": tel_path,
    }))
    return 0


def run_serving_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_SERVING", timeout)


def tenant_bench_main():
    """Child process: multi-tenant metering + fair-share trial
    (``--mode serving --tenants N``, docs/OBSERVABILITY.md).

    N tenants share one replica under open-loop load. Tenant 0 ("hog") is
    a batch-class capacity hog — long prompts, long decodes, the highest
    arrival rate; the rest are interactive-class bystanders. The verdict
    checks the cost-attribution plane end to end: per-tenant block-seconds
    must sum to the pool occupancy integral (+-5%), per-class SLO series
    must exist, the ``/debug/tenants`` ledger must rank the hog first, and
    the interactive tenants must actually complete (the fair-share signal
    protecting them from the hog's backlog). One JSON line out.
    """
    import http.client
    import threading

    import numpy as np
    import jax

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.ragged import (
        RaggedConfig, RaggedInferenceEngine)
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.serving import RouterConfig, build_server

    e = os.environ
    n_tenants = max(2, int(e.get("BENCH_TENANTS_N", 2)))
    model_cfg = llama.LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=688,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)
    max_seqs, budget, block, max_prompt, max_new = 4, 64, 16, 64, 8
    hog_reqs = int(e.get("BENCH_TENANTS_HOG_REQUESTS", 8))
    int_reqs = int(e.get("BENCH_TENANTS_INTERACTIVE_REQUESTS", 5))
    rate = float(e.get("BENCH_SERVING_RATE", 6.0))

    tel_path = e.get("BENCH_TELEMETRY_JSONL", os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "runs",
        "BENCH_tenants_telemetry.jsonl"))
    telemetry.configure(enabled=True, jsonl_path=tel_path,
                        costmeter={"enabled": True},
                        slo={"enabled": True, "classes": True})

    mbs = -(-(max_prompt + max_new) // block)
    rcfg = RaggedConfig(
        max_tokens_per_step=budget, max_seqs=max_seqs, block_size=block,
        num_blocks=max_seqs * mbs + 1, max_blocks_per_seq=mbs,
        enable_prefix_cache=True)
    engine = RaggedInferenceEngine(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx),
        ragged_config=rcfg, seed=0)
    engine.warmup()
    frontend, router, loops = build_server(
        [engine], router_cfg=RouterConfig(
            max_queue_tokens=int(e.get("BENCH_SERVING_QUEUE_TOKENS", 768))))

    # workload: tenant 0 hogs (batch class, long prompts+decodes, front-
    # loaded arrivals); tenants 1..N-1 are interactive bystanders. Distinct
    # random prompts per request keep the block-seconds integral exact
    # (shared blocks would be N x counted per tenant vs once in the pool).
    rng = np.random.default_rng(0)
    work = []  # (tenant, sla_class, prompt, max_tokens)
    for _ in range(hog_reqs):
        p = rng.integers(0, model_cfg.vocab_size, (max_prompt,),
                         dtype=np.int32).tolist()
        work.append(("hog", "batch", p, max_new))
    for t in range(1, n_tenants):
        for _ in range(int_reqs):
            p = rng.integers(0, model_cfg.vocab_size, (16,),
                             dtype=np.int32).tolist()
            work.append((f"tenant{t}", "interactive", p, 4))
    order = rng.permutation(len(work))
    gaps = rng.exponential(1.0 / rate, len(work))
    arrivals = np.cumsum(gaps)

    results = []
    results_lock = threading.Lock()

    def one_request(tenant, sla_class, prompt, mx):
        conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                          timeout=120)
        body = json.dumps({"prompt": prompt, "max_tokens": mx,
                           "stream": False, "tenant": tenant,
                           "sla_class": sla_class})
        t_send = time.perf_counter()
        rec = {"tenant": tenant, "sla_class": sla_class, "rejected": False,
               "latency": None, "tokens": 0, "echo_ok": False}
        try:
            conn.request("POST", "/v1/completions", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 429:
                rec["rejected"] = True
                return rec
            if resp.status == 200:
                rec["latency"] = time.perf_counter() - t_send
                payload = json.loads(data)
                rec["tokens"] = int(
                    (payload.get("usage") or {}).get("completion_tokens", 0))
                rec["echo_ok"] = (payload.get("tenant") == tenant
                                  and payload.get("sla_class") == sla_class)
        finally:
            conn.close()
        return rec

    def http_get(path):
        conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                          timeout=30)
        try:
            conn.request("GET", path)
            return conn.getresponse().read().decode("utf-8")
        finally:
            conn.close()

    threads = []
    t0 = time.perf_counter()
    for i, j in enumerate(order):
        delay = t0 + arrivals[i] - time.perf_counter()
        if delay > 0:
            time.sleep(delay)

        def fire(w=work[j]):
            rec = one_request(*w)
            with results_lock:
                results.append(rec)

        th = threading.Thread(target=fire, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=300)
    wall = time.perf_counter() - t0

    metrics_text = http_get("/metrics")
    debug_tenants = json.loads(http_get("/debug/tenants"))
    frontend.drain(timeout=60)

    # --- per-tenant / per-class rollups from the client's view
    by_tenant: dict[str, dict] = {}
    by_class: dict[str, list] = {"interactive": [], "batch": []}
    for r in results:
        d = by_tenant.setdefault(r["tenant"], {
            "sla_class": r["sla_class"], "requests": 0, "completed": 0,
            "rejected": 0, "tokens": 0, "latencies": []})
        d["requests"] += 1
        if r["rejected"]:
            d["rejected"] += 1
        elif r["latency"] is not None:
            d["completed"] += 1
            d["tokens"] += r["tokens"]
            d["latencies"].append(r["latency"])
            by_class[r["sla_class"]].append(r["latency"])

    # --- ledger view: block-seconds share + the occupancy-integral check
    rows = debug_tenants.get("tenants") or {}
    pool_s = float(debug_tenants.get("pool_block_seconds") or 0.0)
    tenant_s = {t: float(r.get("kv_block_seconds", 0.0))
                + float(r.get("retained_block_seconds", 0.0))
                for t, r in rows.items()}
    total_s = sum(tenant_s.values())
    integral_rel_err = (abs(total_s - pool_s) / pool_s if pool_s > 0
                        else None)
    integral_ok = integral_rel_err is not None and integral_rel_err <= 0.05

    tenant_labels = set()
    slo_classes = set()
    for line in metrics_text.splitlines():
        if line.startswith("request_cost_") and 'tenant="' in line:
            tenant_labels.add(line.split('tenant="', 1)[1].split('"', 1)[0])
        if line.startswith("slo_good_fraction") and 'sla_class="' in line:
            slo_classes.add(
                line.split('sla_class="', 1)[1].split('"', 1)[0])

    top = debug_tenants.get("top_by_block_seconds") or []
    interactive_done = sum(
        d["completed"] for d in by_tenant.values()
        if d["sla_class"] == "interactive")
    interactive_total = sum(
        d["requests"] for d in by_tenant.values()
        if d["sla_class"] == "interactive")
    # the fair-share verdict: every interactive request completed (the hog
    # never starved the bystanders), every tenant shows up in the ledger,
    # the hog tops the block-seconds ranking, and the echo held
    fair_share_ok = bool(
        interactive_total > 0
        and interactive_done == interactive_total
        and all(t in tenant_s for t in by_tenant)
        and top and top[0]["tenant"] == "hog"
        and all(r["echo_ok"] for r in results
                if not r["rejected"] and r["latency"] is not None))

    def p99_ms(vals):
        return (round(float(np.percentile(vals, 99)) * 1e3, 2)
                if vals else None)

    telemetry.TELEMETRY.close()
    print(json.dumps({
        "metric": "serving_tenant_metering",
        "tenants_requested": n_tenants,
        "serving_wall_s": round(wall, 2),
        "tenants": {
            t: {
                "sla_class": d["sla_class"],
                "requests": d["requests"],
                "completed": d["completed"],
                "rejected": d["rejected"],
                "tokens_per_s": round(d["tokens"] / wall, 2) if wall else 0.0,
                "latency_p99_ms": p99_ms(d["latencies"]),
                "block_seconds": round(tenant_s.get(t, 0.0), 6),
                "block_seconds_share": round(tenant_s.get(t, 0.0) / total_s,
                                             4) if total_s else 0.0,
            } for t, d in by_tenant.items()},
        "per_class": {
            cls: {"completed": len(v), "p99_latency_ms": p99_ms(v)}
            for cls, v in by_class.items()},
        "pool_block_seconds": round(pool_s, 6),
        "tenant_block_seconds_sum": round(total_s, 6),
        "integral_rel_err": (round(integral_rel_err, 4)
                             if integral_rel_err is not None else None),
        "block_seconds_integral_ok": integral_ok,
        "metrics_tenant_labels": sorted(tenant_labels),
        "slo_class_series": sorted(slo_classes),
        "debug_tenants_top": top,
        "fair_share_ok": fair_share_ok,
        "backend": jax.default_backend(),
        "telemetry_jsonl": tel_path,
    }))
    return 0


def run_tenants_subprocess(n_tenants: int = 2, timeout: float = 900.0):
    return _run_flagged_subprocess(
        "BENCH_TENANTS", timeout,
        extra_env={"BENCH_TENANTS_N": str(n_tenants)})


def disagg_bench_main():
    """Child process: disaggregated prefill/decode serving measurement
    (``--mode serving --disagg``, docs/SERVING.md).

    Builds a one-process cluster — 1 prefill replica, 2 decode replicas
    sharing the same params — and reports what the disagg tier adds over
    the plain serving bench: KV-transfer volume, handoff latency, cluster
    prefix-index hit rate, and autoscale events, plus a parity verdict
    (cluster output token-identical to a single-replica engine, greedy AND
    seeded). One JSON line out.
    """
    import http.client

    import numpy as np
    import jax

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.ragged import (
        RaggedConfig, RaggedInferenceEngine)
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.serving import (
        ClusterConfig, DecodeAutoscaler, EngineLoop, RouterConfig,
        build_cluster_server)

    e = os.environ
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model_cfg = llama.LlamaConfig(
            vocab_size=32768, hidden_size=2048, intermediate_size=5632,
            num_layers=8, num_heads=16, num_kv_heads=8, max_seq_len=1024)
        max_new, shared, n_shared = 32, 128, 12
        max_seqs, budget, block, max_prompt = 16, 512, 32, 512
    else:
        model_cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=688,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)
        max_new, shared, n_shared = 6, 16, 4
        max_seqs, budget, block, max_prompt = 3, 64, 8, 64
    max_new = int(e.get("BENCH_DISAGG_MAX_NEW", max_new))
    n_shared = int(e.get("BENCH_DISAGG_REQUESTS", n_shared))

    tel_path = e.get("BENCH_TELEMETRY_JSONL", os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "runs",
        "BENCH_disagg_telemetry.jsonl"))
    telemetry.configure(enabled=True, jsonl_path=tel_path, slo=True)

    mbs = -(-(max_prompt + max_new) // block)
    rcfg = RaggedConfig(
        max_tokens_per_step=budget, max_seqs=max_seqs, block_size=block,
        num_blocks=max_seqs * mbs + 1, max_blocks_per_seq=mbs,
        enable_prefix_cache=True)

    def mk(params=None):
        return RaggedInferenceEngine(
            model=lambda ctx: llama.build(model_cfg, ctx=ctx),
            ragged_config=rcfg, seed=0, params=params)

    pre = mk()
    params = pre.params
    frontend, cluster, loops = build_cluster_server(
        [pre], [mk(params), mk(params)],
        cluster_cfg=ClusterConfig(min_decode_replicas=1,
                                  max_decode_replicas=4,
                                  autoscale_cooldown_s=0.0),
        router_cfg=RouterConfig(max_queue_tokens=4096))

    rng = np.random.default_rng(0)
    prefix = rng.integers(1, model_cfg.vocab_size,
                          (shared,), dtype=np.int32).tolist()

    def post(body: dict) -> dict:
        conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                          timeout=300)
        conn.request("POST", "/v1/completions", body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"HTTP {resp.status}: {out}")
        return out

    error = None
    parity = {}
    try:
        # ---- parity probe: cluster vs single-replica, greedy + seeded ---
        ref = mk(params)
        probe = prefix + rng.integers(
            1, model_cfg.vocab_size, (8,), dtype=np.int32).tolist()
        for name, sampling in (
                ("greedy", {}),
                ("seeded", {"temperature": 0.9, "top_k": 20, "seed": 123})):
            ref.put(f"p-{name}", probe, max_new_tokens=max_new,
                    temperature=sampling.get("temperature", 0.0),
                    top_k=sampling.get("top_k", 0),
                    seed=sampling.get("seed", 0))
            while f"p-{name}" not in ref.finished_uids:
                ref.step()
            want = ref._results[f"p-{name}"].generated
            got = post({"prompt": probe, "max_tokens": max_new,
                        **sampling})["choices"][0]["tokens"]
            parity[name] = bool(got == want)

        # ---- shared-prefix workload: cluster-level reuse ----------------
        t0 = time.perf_counter()
        for i in range(n_shared):
            tail = rng.integers(1, model_cfg.vocab_size,
                                (8,), dtype=np.int32).tolist()
            post({"prompt": prefix + tail, "max_tokens": max_new})
        wall = time.perf_counter() - t0

        # ---- autoscaler: forced up + down tick (policy demonstration) ---
        def factory(name):
            return EngineLoop(mk(params), name=name, role="decode")

        scaler = DecodeAutoscaler(cluster, factory, cfg=cluster.cfg,
                                  burn_fn=lambda: 2.0)
        up = scaler.tick()
        scaler._burn_fn = lambda: 0.0
        down = scaler.tick()
        scaler.stop()
        autoscale_ok = up == 1 and down == -1
    except Exception as ex:  # noqa: BLE001 - bench child must emit JSON
        error = f"{type(ex).__name__}: {ex}"
        wall = 0.0
        autoscale_ok = False
    finally:
        cluster.begin_drain()
        for lp in loops:
            lp.join(timeout=60)
        frontend.close()

    cs = cluster.cluster_stats()
    idx = cs["prefix_index"]
    looked = idx["hits"] + idx["misses"]
    handoffs = cs["handoffs"]["ok"] + cs["handoffs"]["failed"]
    telemetry.TELEMETRY.close()
    print(json.dumps({
        "metric": "serving_disagg",
        "error": error,
        "disagg_parity": parity,
        "disagg_requests": cs["disagg_requests"],
        "disagg_completed_wall_s": round(wall, 2),
        "kv_transfer_bytes": cs["kv_transfer"]["bytes"],
        "kv_transfer_count": cs["kv_transfer"]["transfers"],
        "handoffs_ok": cs["handoffs"]["ok"],
        "handoffs_failed": cs["handoffs"]["failed"],
        "handoff_latency_ms": round(
            cs["handoffs"]["seconds"] / handoffs * 1e3, 2) if handoffs
        else None,
        "cluster_prefix_hits": idx["hits"],
        "cluster_prefix_hit_rate": round(idx["hits"] / looked, 4)
        if looked else 0.0,
        "cluster_prefix_entries": idx["entries"],
        "prefix_transfers": cs["prefix_transfers"],
        "fallbacks": cs["fallbacks"],
        "autoscale_events": cs["autoscale_events"],
        "autoscale_up_down_ok": autoscale_ok,
        "replica_roles": cs["roles"],
        "backend": jax.default_backend(),
        "telemetry_jsonl": tel_path,
    }))
    return 0 if error is None else 1


def run_disagg_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_SERVING_DISAGG", timeout)


def fleet_worker_main():
    """Grandchild process: ONE fleet worker (``BENCH_FLEET_WORKER`` =
    prefill|decode) in the 2-process ``--mode fleet`` topology.

    Both roles configure telemetry with tracing + a FleetReporter, write a
    liveness beacon, run their half of a disaggregated request, then flush
    metric snapshot + trace spill into the shared fleet dir. The prefill
    worker exports the KVHandoff (traceparent stamped) to a file; the
    decode worker imports it, finishes the decode under the SAME trace,
    then serves the rollup HTTP surface (``/debug/fleet``,
    ``/metrics/fleet``, ``/healthz``) and probes it. One JSON line out.
    """
    import http.client

    import numpy as np
    import jax

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.elasticity.agent import publish_heartbeat_ages
    from deepspeed_tpu.inference.ragged import (
        KVHandoff, RaggedConfig, RaggedInferenceEngine)
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.serving import (
        EngineLoop, ReplicaRouter, ServingFrontend)

    e = os.environ
    role = e["BENCH_FLEET_WORKER"]
    fleet_dir = e["BENCH_FLEET_DIR"]
    hb_dir = e["BENCH_FLEET_HEARTBEATS"]
    handoff_path = e["BENCH_FLEET_HANDOFF"]
    rank = 0 if role == "prefill" else 1
    worker = f"{role}-0"

    telemetry.configure(
        enabled=True, tracing=True,
        slo={"enabled": True, "replica": worker},
        fleet={"enabled": True, "dir": fleet_dir, "worker": worker,
               "labels": {"role": role}})
    tel = telemetry.TELEMETRY
    tracer = tel.tracer

    # tiny model on every backend: this leg measures the observability
    # plane (federation + stitching), not model throughput
    model_cfg = llama.LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=688,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)
    max_new, max_prompt, block, max_seqs = 6, 16, 8, 3
    mbs = -(-(max_prompt + max_new) // block)
    rcfg = RaggedConfig(
        max_tokens_per_step=64, max_seqs=max_seqs, block_size=block,
        num_blocks=max_seqs * mbs + 1, max_blocks_per_seq=mbs,
        enable_prefix_cache=True)
    # seed=0 on both sides -> identical params, a genuine resume
    eng = RaggedInferenceEngine(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx),
        ragged_config=rcfg, seed=0)

    # liveness beacon (sentinel heartbeat protocol), then surface beacon
    # ages as gauges so they federate; the sleep keeps the youngest age
    # strictly nonzero for the CI assert
    with open(os.path.join(hb_dir, f"heartbeat_{rank}.json"), "w") as f:
        json.dump({"rank": rank, "role": role, "pid": os.getpid()}, f)
    time.sleep(0.06)

    out = {"worker": worker, "role": role, "pid": os.getpid(),
           "backend": jax.default_backend()}
    uid = "fleet-req"
    t0 = time.perf_counter()
    if role == "prefill":
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, model_cfg.vocab_size,
                              (12,), dtype=np.int32).tolist()
        root = tracer.extract(None)
        eng.put(uid, prompt, max_new_tokens=max_new, handoff=True,
                trace=root)
        while uid not in eng.finished_uids:
            eng.step()
        rec = eng.export_handoff(uid)
        if rec is None or rec.traceparent is None:
            raise RuntimeError("prefill produced no traced handoff")
        buf = rec.to_bytes()
        with open(handoff_path + ".tmp", "wb") as f:
            f.write(buf)
        os.replace(handoff_path + ".tmp", handoff_path)
        tracer.finish(root, "fleet/request", t0, time.perf_counter(),
                      role=role, uid=uid)
        out.update(trace_id=root.trace_id, handoff_bytes=len(buf),
                   wall_s=round(time.perf_counter() - t0, 3))
    else:
        with open(handoff_path, "rb") as f:
            rec = KVHandoff.from_bytes(f.read())
        if not eng.import_handoff(rec):
            raise RuntimeError("decode replica could not adopt the handoff")
        while rec.uid not in eng.finished_uids:
            eng.step()
        gen = list(eng.get_request(rec.uid).generated)
        out.update(trace_id=(rec.traceparent or "--").split("-")[1],
                   generated_tokens=len(gen), resumed_from_pos=rec.pos,
                   wall_s=round(time.perf_counter() - t0, 3))

    publish_heartbeat_ages(hb_dir, telemetry=tel)
    tel.fleet.flush()  # metrics snapshot + trace spill, atomically

    if role == "decode":
        # both workers' snapshots are on disk now (prefill ran first):
        # serve the rollup surface off a cold replica router and probe it
        frontend = ServingFrontend(
            ReplicaRouter([EngineLoop(eng, name=worker, role="decode")]),
            fleet_dir=fleet_dir).start()

        def get(path: str) -> tuple[int, dict | str]:
            conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                              timeout=60)
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read().decode("utf-8", "replace")
            conn.close()
            ctype = resp.getheader("Content-Type") or ""
            return resp.status, (json.loads(body)
                                 if "json" in ctype else body)
        try:
            st_d, debug = get("/debug/fleet")
            st_m, prom = get("/metrics/fleet")
            st_h, health = get("/healthz")
        finally:
            frontend.close()
        import re
        out.update(
            http_debug_fleet={
                "status": st_d,
                "workers": len(debug.get("workers", []))
                if isinstance(debug, dict) else 0,
                "verdict": (debug.get("health") or {}).get("verdict")
                if isinstance(debug, dict) else None,
                "heartbeat_ages": debug.get("heartbeat_ages")
                if isinstance(debug, dict) else None,
            },
            http_metrics_fleet={
                "status": st_m,
                "worker_labels": sorted(set(
                    re.findall(r'worker="([^"]+)"', prom)))
                if isinstance(prom, str) else [],
            },
            http_healthz={
                "status": st_h,
                "state": health.get("status")
                if isinstance(health, dict) else None,
                "fleet": health.get("fleet")
                if isinstance(health, dict) else None,
            })

    telemetry.TELEMETRY.close()
    print(json.dumps(out))
    return 0


def fleet_bench_main():
    """Child process: the 2-process fleet observability trial
    (``--mode fleet``, docs/OBSERVABILITY.md).

    Spawns a prefill worker and a decode worker as SEPARATE processes
    sharing only a fleet dir, a heartbeat dir, and a KVHandoff file, then
    verifies the fleet plane end to end: a single stitched trace_id whose
    spans come from both worker pids in the merged Perfetto export, a
    federated scrape carrying >= 2 distinct ``worker=`` label values, and
    nonzero heartbeat-age gauges. One JSON line out.
    """
    import re

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.elasticity.agent import (
        beacon_ages, publish_heartbeat_ages)
    from deepspeed_tpu.telemetry.fleet import (
        FleetAggregator, merge_fleet_traces)

    base = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "runs", "BENCH_fleet")
    shutil.rmtree(base, ignore_errors=True)
    fleet_dir = os.path.join(base, "fleet")
    hb_dir = os.path.join(base, "heartbeats")
    os.makedirs(fleet_dir, exist_ok=True)
    os.makedirs(hb_dir, exist_ok=True)
    handoff_path = os.path.join(base, "handoff.bin")

    def run_worker(role: str) -> dict:
        env = dict(os.environ)
        env.pop("BENCH_FLEET", None)  # a worker must never recurse
        env["BENCH_FLEET_WORKER"] = role
        env["BENCH_FLEET_DIR"] = fleet_dir
        env["BENCH_FLEET_HEARTBEATS"] = hb_dir
        env["BENCH_FLEET_HANDOFF"] = handoff_path
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0:
            raise RuntimeError(
                f"{role} worker exited {proc.returncode}:\n"
                + proc.stderr[-2000:])
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError(f"no JSON from {role} worker:\n"
                           + proc.stdout[-2000:])

    error = None
    workers = {}
    try:
        workers["prefill"] = run_worker("prefill")
        workers["decode"] = run_worker("decode")
    except Exception as ex:  # noqa: BLE001 - bench child must emit JSON
        error = f"{type(ex).__name__}: {ex}"

    # offline rollup in the parent: aggregate the dir both workers fed
    telemetry.configure(enabled=True)
    agg = FleetAggregator(fleet_dir, ttl_s=300.0,
                          registry=telemetry.TELEMETRY.registry)
    debug = agg.debug_payload()
    prom = agg.render_prometheus()
    fed_workers = sorted(set(re.findall(r'worker="([^"]+)"', prom)))

    merged = merge_fleet_traces(fleet_dir)
    tids = merged["otherData"]["trace_ids"]
    want_tid = workers.get("prefill", {}).get("trace_id")
    stitched_pids = {ev["pid"] for ev in merged["traceEvents"]
                     if ev.get("ph") == "X"
                     and ev["args"].get("trace_id") == want_tid}
    trace_path = os.path.join(base, "fleet_trace.json")
    with open(trace_path, "w") as f:
        json.dump(merged, f)

    ages = beacon_ages(hb_dir)
    publish_heartbeat_ages(hb_dir, telemetry=telemetry.TELEMETRY)

    same_tid = (want_tid is not None
                and workers.get("decode", {}).get("trace_id") == want_tid)
    stitched_ok = bool(same_tid and len(stitched_pids) >= 2
                       and tids == [want_tid])
    federated_ok = len(fed_workers) >= 2
    heartbeat_ok = (len(ages) >= 2
                    and all(a > 0.0 for a in ages.values()))
    http_ok = all(
        v.get("status") == 200 for v in (
            workers.get("decode", {}).get("http_debug_fleet", {}),
            workers.get("decode", {}).get("http_metrics_fleet", {}),
            workers.get("decode", {}).get("http_healthz", {})))
    fleet_ok = bool(error is None and stitched_ok and federated_ok
                    and heartbeat_ok and http_ok
                    and len(debug["workers"]) >= 2)
    telemetry.TELEMETRY.close()
    print(json.dumps({
        "metric": "fleet_observability",
        "error": error,
        "fleet_ok": fleet_ok,
        "stitched_trace_id": want_tid,
        "stitched_trace_ids_total": len(tids),
        "stitched_span_pids": sorted(stitched_pids),
        "stitched_spans": merged["otherData"]["spans"],
        "stitched_ok": stitched_ok,
        "trace_workers": merged["otherData"]["workers"],
        "trace_path": trace_path,
        "federated_worker_labels": fed_workers,
        "federated_ok": federated_ok,
        "debug_workers": len(debug["workers"]),
        "fleet_health": debug["health"]["verdict"],
        "fleet_health_reasons": debug["health"]["reasons"],
        "heartbeat_ages_s": {str(r): round(a, 3)
                             for r, a in sorted(ages.items())},
        "heartbeat_ok": heartbeat_ok,
        "http_ok": http_ok,
        "workers": workers,
    }))
    return 0 if fleet_ok else 1


def run_fleet_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_FLEET", timeout)


def chaos_bench_main():
    try:
        return _chaos_bench_impl()
    except Exception as ex:  # noqa: BLE001 - chaos child must emit JSON
        import traceback
        traceback.print_exc()
        print(json.dumps({"metric": "serving_chaos", "chaos_ok": False,
                          "error": {"reason": f"{type(ex).__name__}: {ex}"}}))
        return 1


def _chaos_bench_impl():
    """Child process: chaos smoke over the full serving path.

    Arms a FIXED, seeded fault schedule (deepspeed_tpu/serving/faults.py) —
    transient dispatch raise, readback hang, a dispatch burst long enough
    to trip automatic degradation, and a block-allocation fault — then
    drives concurrent HTTP requests with pinned per-request seeds and
    checks the fault-tolerance contract end to end: zero hung requests,
    zero leaked KV blocks after drain, completed requests token-identical
    to a fault-free reference run, and at least one automatic
    device_state→host-staged fallback visible in /healthz and telemetry.
    One JSON line out; ``chaos_ok`` + a structured ``error`` field carry
    the verdict (see docs/FAULT_TOLERANCE.md).
    """
    import http.client
    import threading

    import numpy as np
    import jax

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.ragged import (
        RaggedConfig,
        RaggedInferenceEngine,
    )
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.serving import RouterConfig, build_server, faults

    e = os.environ
    telemetry.configure(enabled=True)

    model_cfg = llama.LlamaConfig(
        vocab_size=97, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128)

    def make_engine():
        rcfg = RaggedConfig(
            max_tokens_per_step=16, max_seqs=3, block_size=4, num_blocks=49,
            max_blocks_per_seq=16, decode_run_ahead=4, prefill_tile=8,
            fused_chunk=4, pipeline_depth=2, device_state=True,
            dispatch_retries=2, retry_backoff_s=0.01, degrade_after=2)
        return RaggedInferenceEngine(
            model=lambda ctx: llama.build(model_cfg, ctx=ctx),
            ragged_config=rcfg, seed=0)

    n_req = int(e.get("BENCH_CHAOS_REQUESTS", 8))
    max_new = 8
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 97, (int(n),), dtype=np.int32).tolist()
               for n in rng.integers(4, 20, n_req)]

    # fault-free reference FIRST (injector still disarmed): per-request
    # seeds pin the sampled tokens, so the chaos run must reproduce these
    # exactly for every request the faults didn't kill
    ref_eng = make_engine()
    for i, p in enumerate(prompts):
        ref_eng.put(f"ref-{i}", p, max_new_tokens=max_new, temperature=0.8,
                    seed=1000 + i)
    ref_out = ref_eng.generate_all()
    reference = {i: ref_out[f"ref-{i}"] for i in range(n_req)}
    del ref_eng

    engine = make_engine()
    frontend, router, loops = build_server(
        [engine], router_cfg=RouterConfig())
    inj = faults.get_fault_injector()
    inj.configure([
        # one transient dispatch blip: the watchdog retries it away
        {"point": faults.POINT_DISPATCH, "kind": "raise", "after": 1},
        # a wedged readback surfacing as TimeoutError: also transient
        {"point": faults.POINT_READBACK, "kind": "hang", "after": 6,
         "delay_s": 0.01},
        # a dispatch failure burst: with degrade_after=2 this forces the
        # automatic device_state→host-staged fallback (and possibly the
        # plain-step rung after it)
        {"point": faults.POINT_DISPATCH, "kind": "raise", "after": 10,
         "times": 4},
        # one block-allocation fault mid-admission
        {"point": faults.POINT_ALLOC, "kind": "raise", "after": 2},
    ], seed=int(e.get("BENCH_CHAOS_SEED", 0)))

    results: dict = {}
    lock = threading.Lock()

    def one_request(i):
        conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                          timeout=120)
        body = json.dumps({"prompt": prompts[i], "max_tokens": max_new,
                           "temperature": 0.8, "seed": 1000 + i})
        try:
            conn.request("POST", "/v1/completions", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = json.loads(resp.read() or b"{}")
            with lock:
                results[i] = (resp.status, data)
        except Exception as ex:  # noqa: BLE001 - a dropped conn is a result
            with lock:
                results[i] = (None, {"error": {"reason": str(ex)}})
        finally:
            conn.close()

    threads = [threading.Thread(target=one_request, args=(i,), daemon=True)
               for i in range(n_req)]
    for th in threads:
        th.start()
        time.sleep(0.05)  # stagger arrivals so faults land mid-flight
    for th in threads:
        th.join(timeout=180)
    hung = sum(1 for th in threads if th.is_alive())

    # health + metrics BEFORE drain: degradation must be visible live
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=30)
    conn.request("GET", "/healthz")
    healthz = json.loads(conn.getresponse().read())
    conn.request("GET", "/metrics")
    metrics_text = conn.getresponse().read().decode("utf-8")
    conn.close()

    fired = inj.counts()
    inj.reset()  # disarm before drain so shutdown can't re-fire
    drained = frontend.drain(timeout=60)
    leaked = (engine.cfg.num_blocks - 1) - engine.allocator.free_blocks

    completed = [i for i, (st, _) in results.items() if st == 200]
    mismatches = [
        i for i in completed
        if results[i][1]["choices"][0]["tokens"] != reference[i]
    ]
    metric_degraded = any(
        line.split()[-1] not in ("0", "0.0")
        for line in metrics_text.splitlines()
        if line.startswith(("degraded_mode", "replica_degraded_mode")))
    checks = {
        "no_hung_requests": hung == 0,
        "no_leaked_blocks": leaked == 0,
        "drained_clean": bool(drained),
        "all_responses_terminal": len(results) == n_req,
        "parity_with_fault_free_run": not mismatches and bool(completed),
        "auto_degraded": engine.degraded_mode >= 1,
        "healthz_degraded": healthz.get("status") == "degraded",
        "metrics_degraded": metric_degraded,
    }
    ok = all(checks.values())
    telemetry.TELEMETRY.close()
    print(json.dumps({
        "metric": "serving_chaos",
        "chaos_ok": ok,
        "error": None if ok else {
            "reason": "chaos assertions failed",
            "failed": sorted(k for k, v in checks.items() if not v)},
        "chaos_checks": checks,
        "chaos_requests": n_req,
        "chaos_completed": len(completed),
        "chaos_failed": len(results) - len(completed),
        "chaos_hung": hung,
        "chaos_leaked_blocks": leaked,
        "chaos_parity_mismatches": len(mismatches),
        "chaos_degraded_mode": engine.degraded_mode,
        "chaos_degraded_reason": engine.degraded_reason,
        "chaos_step_retries": engine.step_retries,
        "chaos_step_failures": engine.step_failures,
        "chaos_loop_crashes": loops[0].crash_count,
        "chaos_loop_respawns": loops[0].respawn_count,
        "chaos_faults_fired": fired,
        "chaos_healthz": healthz.get("status"),
        "backend": jax.default_backend(),
    }))
    return 0


def run_chaos_subprocess(timeout: float = 600.0):
    return _run_flagged_subprocess("BENCH_CHAOS", timeout)


def train_chaos_worker_main():
    """Chaos-harness training worker (child of ``--mode train-chaos``).

    Trains a tiny llama with a fully deterministic data stream (batch i is a
    pure function of i via :class:`CheckpointableLoader`), checkpointing
    every ``CHAOS_SAVE_EVERY`` steps into ``CHAOS_DIR/ckpt``; on start it
    resumes from the newest VERIFIED checkpoint (the fallback ladder).
    Armed faults arrive as JSON in ``CHAOS_FAULTS`` — including ``kill``
    kinds that SIGKILL this process mid-flush/mid-commit. Every trained
    step's loss is appended (fsynced) to ``CHAOS_DIR/trajectory.jsonl`` and
    lifecycle events to ``CHAOS_DIR/status.jsonl`` so the orchestrator can
    stitch and judge the run."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.checkpoint import engine as ckpt
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.runtime.dataloader import CheckpointableLoader
    from deepspeed_tpu.serving import faults

    e = os.environ
    work_dir = e["CHAOS_DIR"]
    ckpt_dir = os.path.join(work_dir, "ckpt")
    total_steps = int(e.get("CHAOS_TOTAL_STEPS", 10))
    save_every = int(e.get("CHAOS_SAVE_EVERY", 2))
    batch, seq, vocab = 4, 32, 97

    def append_event(path, obj):
        with open(path, "a") as f:
            f.write(json.dumps(obj) + "\n")
            f.flush()
            os.fsync(f.fileno())

    status_path = os.path.join(work_dir, "status.jsonl")
    traj_path = os.path.join(work_dir, "trajectory.jsonl")

    model_cfg = llama.LlamaConfig(
        vocab_size=vocab, hidden_size=32, intermediate_size=64, num_layers=1,
        num_heads=4, num_kv_heads=2, max_seq_len=seq)

    def batch_for(i):
        rng = np.random.default_rng(777 + i)
        return {"input_ids": rng.integers(0, vocab, (batch, seq),
                                          dtype=np.int32)}

    def factory(skip):
        def gen():
            i = skip
            while True:
                yield batch_for(i)
                i += 1
        return gen()

    loader = CheckpointableLoader(factory)
    config = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": 1,
        "sequence_length": seq,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1},
        "checkpoint": {"keep_n_latest": 3},
        "seed": 5,
    }
    if e.get("CHAOS_SENTINEL"):
        # self-healing legs: the divergence sentinel with quarantine state
        # persisted under the work dir (a pre-seeded quarantine.json is how
        # the clean-reference run skips the batches the chaos run healed
        # around) and the heartbeat beacon the elastic agent polls
        config["sentinel"] = {
            "enabled": True,
            "warmup_steps": 3,
            "report_dir": os.path.join(work_dir, "reports"),
            "state_dir": os.path.join(work_dir, "state"),
            "checkpoint_dir": ckpt_dir,
        }
    mesh_devices = None
    if e.get("CHAOS_PIPE"):
        # staged-pipeline leg: 4 scanned layers split across 2 stage
        # programs on one device, 4 microbatches per 1F1B round (the step
        # pulls GAS loader items, so each step consumes 4 stream entries);
        # the orchestrator SIGKILLs a stage thread mid-schedule via the
        # pipe.stage fault point and expects exact stitched resume
        import jax
        model_cfg = llama.LlamaConfig(
            vocab_size=vocab, hidden_size=32, intermediate_size=64,
            num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=seq)
        config["train_batch_size"] = batch * 4
        config["gradient_accumulation_steps"] = 4
        config["mesh"] = {"data": 1}
        config["pipeline"] = {"stages": 2, "schedule": "1f1b"}
        mesh_devices = jax.devices()[:1]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(model_cfg, ctx=ctx), config=config,
        training_data=loader, seed=5, mesh_devices=mesh_devices)

    # arm the orchestrator's fault schedule BEFORE the resume: the
    # corrupt-at-load attempt models read-time bit-rot discovered during
    # this run's own verification pass, and kill specs at save seams are
    # untouched by load-point fires (per-spec hit counters)
    specs = json.loads(e.get("CHAOS_FAULTS", "[]"))
    if specs:
        faults.get_fault_injector().configure(
            specs, seed=int(e.get("CHAOS_SEED", 0)))

    # resume from the newest verified checkpoint (ladders past corruption)
    latest_before = ckpt.latest_tag(ckpt_dir) if os.path.isdir(ckpt_dir) else None
    try:
        path, _ = engine.load_checkpoint(ckpt_dir)
    except ckpt.CheckpointCorruptError as ex:
        append_event(status_path, {"event": "exhausted", "stage": ex.stage})
        return 4
    append_event(status_path, {
        "event": "resume" if path else "fresh",
        "tag": os.path.basename(path) if path else None,
        "latest": latest_before, "step": engine.global_steps})

    while engine.global_steps < total_steps:
        step = engine.global_steps
        loss = engine.train_batch()
        if engine.global_steps <= step:
            # the sentinel rolled back: the step counter rewound to the
            # pinned checkpoint. Don't log the anomalous loss — the replay
            # re-logs every step from the restore point (last write wins in
            # the orchestrator's stitched-parity check).
            append_event(status_path, {"event": "rollback", "from": step,
                                       "to": engine.global_steps})
            continue
        append_event(traj_path, {"step": step,
                                 "loss": float(np.asarray(loss))})
        if engine.global_steps % save_every == 0:
            tag = f"global_step{engine.global_steps}"
            engine.save_checkpoint(ckpt_dir)
            append_event(status_path, {"event": "saved", "tag": tag})
    done = {"event": "done", "step": engine.global_steps}
    if engine._sentinel is not None:
        done["rollbacks"] = engine.train_rollbacks
        done["quarantined"] = engine._sentinel.quarantined
    engine.destroy()
    append_event(status_path, done)
    print("CHAOS_WORKER_DONE")
    return 0


def train_chaos_main():
    try:
        return _train_chaos_impl()
    except Exception as ex:  # noqa: BLE001 - chaos child must emit JSON
        import traceback
        traceback.print_exc()
        print(json.dumps({"metric": "train_chaos", "train_chaos_ok": False,
                          "error": {"reason": f"{type(ex).__name__}: {ex}"}}))
        return 1


def _train_chaos_impl():
    """Kill–resume chaos harness for the training checkpoint path
    (docs/FAULT_TOLERANCE.md "Training: crash-safe checkpoints").

    Protocol: (1) run an uninterrupted reference worker and record its loss
    trajectory; (2) run the same workload under a seeded kill schedule —
    SIGKILL mid-flush, mid-commit, at the latest-pointer update (via the
    injector's ``kill`` fault kind, which dies AT the seam), plus one
    wall-clock-timer kill and one corrupt-bytes-at-load attempt — restarting
    after every death; (3) supervise the same worker under an
    :class:`ElasticAgent` whose second worker slot dies, forcing a restart
    at a reduced world size. Verdicts: a verified checkpoint always loads
    after every kill, the stitched chaos trajectory is step-identical to
    the reference, corruption triggered the fallback ladder (never a
    crash), and the agent finished at the smaller world size."""
    import random
    import shutil
    import signal as _signal
    import tempfile

    import jax

    from deepspeed_tpu.elasticity.agent import ElasticAgent, WorkerSpec

    e = os.environ
    seed = int(e.get("BENCH_TRAIN_CHAOS_SEED", 0))
    total_steps = int(e.get("BENCH_TRAIN_CHAOS_STEPS", 10))
    rng = random.Random(seed)
    bench_path = os.path.abspath(__file__)
    root = tempfile.mkdtemp(prefix="train_chaos_")

    def worker_env(work_dir, faults=None, sentinel=False, total=None,
                   save_every=None, pipe=False):
        env = dict(os.environ)
        env.pop("BENCH_TRAIN_CHAOS", None)
        env.update(
            BENCH_TRAIN_CHAOS_WORKER="1",
            CHAOS_DIR=work_dir,
            CHAOS_TOTAL_STEPS=str(total if total is not None else total_steps),
            CHAOS_SAVE_EVERY=str(save_every if save_every is not None
                                 else int(e.get("CHAOS_SAVE_EVERY", 2))),
            CHAOS_SEED=str(seed),
            CHAOS_FAULTS=json.dumps(faults or []),
        )
        if sentinel:
            env["CHAOS_SENTINEL"] = "1"
        if pipe:
            env["CHAOS_PIPE"] = "1"
        return env

    def read_jsonl(path):
        out = []
        if not os.path.exists(path):
            return out
        with open(path) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn trailing line from a kill mid-append
        return out

    def run_worker(work_dir, faults=None, kill_after=None, log_name="w",
                   **env_kw):
        """One worker run. Returns the exit code (negative = signal)."""
        os.makedirs(work_dir, exist_ok=True)
        log = open(os.path.join(work_dir, f"{log_name}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, bench_path],
            env=worker_env(work_dir, faults, **env_kw),
            stdout=log, stderr=log, cwd=os.path.dirname(bench_path))
        try:
            if kill_after is not None:
                try:
                    proc.wait(timeout=kill_after)
                except subprocess.TimeoutExpired:
                    proc.send_signal(_signal.SIGKILL)
            proc.wait(timeout=600)
        finally:
            log.close()
        return proc.returncode

    # ---- phase 1: uninterrupted reference trajectory
    ref_dir = os.path.join(root, "ref")
    rc = run_worker(ref_dir, log_name="ref")
    if rc != 0:
        raise RuntimeError(f"reference worker failed rc={rc} (see {ref_dir})")
    reference = {r["step"]: r["loss"] for r in read_jsonl(
        os.path.join(ref_dir, "trajectory.jsonl"))}
    if len(reference) != total_steps:
        raise RuntimeError(
            f"reference covered {len(reference)}/{total_steps} steps")

    # ---- phase 2: seeded kill schedule, restart after every death
    chaos_dir = os.path.join(root, "chaos")
    attempts = [
        # kill mid-flush: model fragments staged, optimizer not yet written
        ("kill@ckpt.flush", [{"point": "ckpt.flush", "kind": "kill",
                              "after": 3 + rng.randrange(3)}], None),
        # kill during device→host fragment collection: nothing staged yet
        ("kill@ckpt.collect", [{"point": "ckpt.collect", "kind": "kill",
                                "after": 1}], None),
        # kill mid-commit: manifest sealed in staging, promote never runs
        ("kill@ckpt.commit", [{"point": "ckpt.commit", "kind": "kill",
                               "after": rng.randrange(2)}], None),
        # kill at the latest-pointer update: dir promoted, pointer stale
        ("kill@ckpt.latest", [{"point": "ckpt.latest", "kind": "kill",
                               "after": rng.randrange(2)}], None),
        # wall-clock kill: lands wherever the run happens to be
        ("kill@timer", None, 4.0 + 6.0 * rng.random()),
        # silent bit-rot on the newest checkpoint, discovered at load time:
        # verification must catch it and ladder back, not crash
        ("corrupt@ckpt.load", [{"point": "ckpt.load", "kind": "corrupt-bytes",
                                "times": 1}], None),
    ]
    kills = []
    runs = []
    for i, (label, faults, kill_after) in enumerate(attempts):
        # no early exit on a clean run: a completed workload just means the
        # remaining attempts resume at the final step instantly — but the
        # corrupt-at-load attempt must still run to exercise the ladder
        rc = run_worker(chaos_dir, faults=faults, kill_after=kill_after,
                        log_name=f"attempt{i}")
        runs.append({"label": label, "rc": rc})
        if rc is not None and rc < 0:
            kills.append(label)
    extra = 0
    while runs[-1]["rc"] != 0 and extra < 5:
        extra += 1
        rc = run_worker(chaos_dir, log_name=f"extra{extra}")
        runs.append({"label": f"clean{extra}", "rc": rc})
    completed = runs[-1]["rc"] == 0

    status = read_jsonl(os.path.join(chaos_dir, "status.jsonl"))
    saves = [s for s in status if s["event"] == "saved"]
    resumes = [s for s in status if s["event"] == "resume"]
    fresh_starts = [s for s in status if s["event"] == "fresh"]
    exhausted = [s for s in status if s["event"] == "exhausted"]
    # every restart AFTER the first committed save must find a loadable
    # verified checkpoint — a "fresh" start past that point means a save
    # was lost; "exhausted" means verification found nothing at all
    first_save_at = status.index(saves[0]) if saves else len(status)
    late_fresh = [s for s in fresh_starts if status.index(s) > first_save_at]
    always_loadable = completed and not late_fresh and not exhausted
    # the corrupt-at-load attempt must have laddered back: some resume
    # loaded a tag older than what the latest pointer named
    fallbacks = [r for r in resumes
                 if r.get("latest") and r.get("tag") != r.get("latest")]

    trajectory = read_jsonl(os.path.join(chaos_dir, "trajectory.jsonl"))
    by_step: dict = {}
    for r in trajectory:
        by_step.setdefault(r["step"], []).append(r["loss"])
    coverage = sorted(by_step.keys())
    full_coverage = coverage == list(range(total_steps))
    max_rel = 0.0
    for s, losses in by_step.items():
        ref = reference.get(s)
        if ref is None:
            max_rel = float("inf")
            continue
        for l in losses:
            max_rel = max(max_rel, abs(l - ref) / max(1e-12, abs(ref)))
    parity = full_coverage and max_rel <= 1e-5

    # ---- phase 3: the ElasticAgent gets the same treatment — worker slot 1
    # dies mid-run, the agent restarts at a reduced world size, the trainer
    # resumes from its checkpoint and finishes
    elastic_dir = os.path.join(root, "elastic")
    os.makedirs(elastic_dir, exist_ok=True)
    elastic_log = open(os.path.join(elastic_dir, "trainer.log"), "ab")

    def make_worker(rank, world):
        if rank == 0:
            return WorkerSpec(cmd=[sys.executable, bench_path],
                              env=worker_env(elastic_dir))
        # a host that evicts mid-run (exactly once: at the reduced world
        # size the agent never fills this slot again)
        return WorkerSpec(cmd=[sys.executable, "-c",
                               "import time,sys; time.sleep(6); sys.exit(3)"])

    agent = ElasticAgent(
        target_batch_size=4, micro_batch_candidates=[2, 4],
        make_worker=make_worker, max_world_size=2, min_world_size=1,
        poll_interval=0.3, max_restarts=3)
    agent_rc = agent.run()
    elastic_log.close()
    elastic_traj = read_jsonl(os.path.join(elastic_dir, "trajectory.jsonl"))
    elastic_steps = {r["step"] for r in elastic_traj}
    elastic_parity = all(
        abs(r["loss"] - reference[r["step"]])
        <= 1e-5 * max(1e-12, abs(reference[r["step"]]))
        for r in elastic_traj if r["step"] in reference)
    elastic_ok = (agent_rc == 0
                  and elastic_steps == set(range(total_steps))
                  and elastic_parity)
    world_reduced = getattr(agent, "world_size", 2) == 1

    # ---- phase 4: divergence leg — self-healing from poisoned math.
    # One run eats a nan-grads fault (strike 1: quarantine + pin the
    # pre-anomaly tag) and a content-keyed poison-batch fault (strike 2:
    # rollback to the pin and replay with the quarantine applied). Then a
    # clean reference run — pre-armed with the chaos run's final quarantine
    # so its data stream is aligned — must produce a step-identical loss
    # trajectory: the healed run is indistinguishable from one that never
    # saw the poison.
    import numpy as np

    from deepspeed_tpu.runtime import sentinel as sentinel_mod

    sent_total, sent_save = 16, 3

    def chaos_batch_for(i):  # mirrors the worker's deterministic stream
        brng = np.random.default_rng(777 + i)
        return {"input_ids": brng.integers(0, 97, (4, 32), dtype=np.int32)}

    poison_fp = sentinel_mod.batch_fingerprint(chaos_batch_for(10))
    sent_chaos = os.path.join(root, "sent_chaos")
    sent_rc = run_worker(
        sent_chaos,
        faults=[
            {"point": "train.grads", "kind": "nan-grads", "after": 6,
             "times": 1},
            {"point": "data.batch", "kind": "poison-batch",
             "request_id": poison_fp, "times": 1},
        ],
        log_name="sent_chaos", sentinel=True, total=sent_total,
        save_every=sent_save)
    sent_status = read_jsonl(os.path.join(sent_chaos, "status.jsonl"))
    sent_rollbacks = [s for s in sent_status if s["event"] == "rollback"]
    sent_done = [s for s in sent_status if s["event"] == "done"]
    sent_quarantine = sentinel_mod.load_quarantine(
        os.path.join(sent_chaos, "state"))
    report_dir = os.path.join(sent_chaos, "reports")
    sent_reports = []
    if os.path.isdir(report_dir):
        for name in sorted(os.listdir(report_dir)):
            with open(os.path.join(report_dir, name)) as f:
                sent_reports.append((name, json.load(f)))

    # clean reference: same workload, no faults, quarantine pre-seeded so
    # the stream skips exactly the batches the chaos run learned to avoid
    sent_ref = os.path.join(root, "sent_ref")
    os.makedirs(os.path.join(sent_ref, "state"), exist_ok=True)
    sentinel_mod.save_quarantine(os.path.join(sent_ref, "state"),
                                 sent_quarantine)
    sent_ref_rc = run_worker(sent_ref, log_name="sent_ref", sentinel=True,
                             total=sent_total, save_every=sent_save)
    ref_last = {r["step"]: r["loss"] for r in read_jsonl(
        os.path.join(sent_ref, "trajectory.jsonl"))}
    chaos_last = {r["step"]: r["loss"] for r in read_jsonl(
        os.path.join(sent_chaos, "trajectory.jsonl"))}
    sent_max_rel = 0.0
    for s in range(sent_total):
        a, b = chaos_last.get(s), ref_last.get(s)
        if a is None or b is None or a != a or b != b:
            sent_max_rel = float("inf")
            continue
        sent_max_rel = max(sent_max_rel, abs(a - b) / max(1e-12, abs(b)))
    sent_parity = (set(chaos_last) == set(range(sent_total))
                   and sent_max_rel <= 1e-5)
    sent_forensics_ok = (
        bool(sent_reports)
        and any(n.startswith("sentinel_rollback") for n, _ in sent_reports)
        and all(r for _, r in sent_reports))

    # ---- phase 5: liveness leg — a wedge fault blocks the device fence
    # forever; the worker's heartbeat beacon goes stale, the agent SIGKILLs
    # the wedged-but-alive process, and the relaunch (no fault armed) heals
    # from the last checkpoint
    hb_dir = os.path.join(root, "wedge")
    os.makedirs(hb_dir, exist_ok=True)
    wedge_total, wedge_save = 8, 2
    wedge_armed = {"first": True}

    def make_wedge_worker(rank, world):
        faults = []
        if wedge_armed["first"]:
            # arm only the first incarnation: the relaunch must run clean
            wedge_armed["first"] = False
            faults = [{"point": "train.dispatch", "kind": "wedge",
                       "delay_s": 600.0, "after": 4, "times": 1}]
        return WorkerSpec(cmd=[sys.executable, bench_path],
                          env=worker_env(hb_dir, faults, sentinel=True,
                                         total=wedge_total,
                                         save_every=wedge_save))

    wedge_agent = ElasticAgent(
        target_batch_size=4, micro_batch_candidates=[2, 4],
        make_worker=make_wedge_worker, max_world_size=1, min_world_size=1,
        poll_interval=0.5, max_restarts=3,
        heartbeat_dir=os.path.join(hb_dir, "state"),
        heartbeat_timeout=5.0, heartbeat_grace=60.0)
    wedge_rc = wedge_agent.run()
    wedge_status = read_jsonl(os.path.join(hb_dir, "status.jsonl"))
    wedge_done = [s for s in wedge_status if s["event"] == "done"]
    wedge_kills = getattr(wedge_agent, "heartbeat_kills", 0)
    wedge_ok = (wedge_rc == 0 and bool(wedge_done)
                and wedge_kills >= 1
                and getattr(wedge_agent, "restarts", 0) >= 1)

    # ---- phase 6: staged-pipeline leg — SIGKILL a stage thread mid-1F1B
    # (pipe.stage fault point, request_id keyed to the stage-1 thread),
    # restart, and the stitched trajectory must be step-identical to an
    # uninterrupted 2-stage run (docs/PIPELINE.md "Failure semantics")
    pipe_total, pipe_save = 6, 2
    pipe_ref_dir = os.path.join(root, "pipe_ref")
    pipe_ref_rc = run_worker(pipe_ref_dir, log_name="pipe_ref", pipe=True,
                             total=pipe_total, save_every=pipe_save)
    pipe_ref_traj = {r["step"]: r["loss"] for r in read_jsonl(
        os.path.join(pipe_ref_dir, "trajectory.jsonl"))}

    # stage 1 executes 2*M = 8 schedule instructions per step; after=19
    # lands the kill inside the third step's 1F1B round, one step past the
    # step-2 checkpoint, so the restart must resume and replay exactly
    pipe_dir = os.path.join(root, "pipe")
    pipe_runs = []
    pipe_kill_rc = run_worker(
        pipe_dir,
        faults=[{"point": "pipe.stage", "kind": "kill",
                 "request_id": "stage1", "after": 19}],
        log_name="pipe_kill", pipe=True, total=pipe_total,
        save_every=pipe_save)
    pipe_runs.append({"label": "kill@pipe.stage", "rc": pipe_kill_rc})
    extra = 0
    while pipe_runs[-1]["rc"] != 0 and extra < 4:
        extra += 1
        rc = run_worker(pipe_dir, log_name=f"pipe_extra{extra}", pipe=True,
                        total=pipe_total, save_every=pipe_save)
        pipe_runs.append({"label": f"pipe_clean{extra}", "rc": rc})
    pipe_traj: dict = {}
    for r in read_jsonl(os.path.join(pipe_dir, "trajectory.jsonl")):
        pipe_traj[r["step"]] = r["loss"]  # replayed steps: last write wins
    pipe_max_rel = 0.0
    for s in range(pipe_total):
        a, b = pipe_traj.get(s), pipe_ref_traj.get(s)
        if a is None or b is None:
            pipe_max_rel = float("inf")
            continue
        pipe_max_rel = max(pipe_max_rel, abs(a - b) / max(1e-12, abs(b)))
    pipe_killed = pipe_kill_rc is not None and pipe_kill_rc < 0
    pipe_parity = (pipe_ref_rc == 0 and pipe_runs[-1]["rc"] == 0
                   and set(pipe_traj) == set(range(pipe_total))
                   and pipe_max_rel <= 1e-6)

    checks = {
        "completed": completed,
        "always_loadable": always_loadable,
        "kills_ge_3": len(kills) >= 3,
        "killed_mid_commit": "kill@ckpt.commit" in kills,
        "full_coverage": full_coverage,
        "trajectory_parity": parity,
        "fallback_observed": bool(fallbacks),
        "elastic_ok": elastic_ok,
        "elastic_world_reduced": world_reduced,
        "sentinel_self_heals": sent_rc == 0 and bool(sent_done),
        "sentinel_quarantined_two": len(sent_quarantine) == 2,
        "sentinel_one_rollback": len(sent_rollbacks) == 1,
        "sentinel_stitched_parity": sent_ref_rc == 0 and sent_parity,
        "sentinel_forensics": sent_forensics_ok,
        "wedge_heartbeat_kill": wedge_ok,
        "pipe_stage_killed": pipe_killed,
        "pipe_stitched_parity": pipe_parity,
    }
    ok = all(checks.values())
    if ok:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps({
        "metric": "train_chaos",
        "train_chaos_ok": ok,
        "error": None if ok else {
            "reason": "train-chaos assertions failed (artifacts kept in "
                      f"{root})",
            "failed": sorted(k for k, v in checks.items() if not v)},
        "train_chaos_checks": checks,
        "train_chaos_runs": runs,
        "train_chaos_kills": kills,
        "train_chaos_saves": len(saves),
        "train_chaos_resumes": len(resumes),
        "train_chaos_fallbacks": len(fallbacks),
        "train_chaos_max_rel_loss_diff": max_rel,
        "train_chaos_steps": total_steps,
        "elastic_agent_rc": agent_rc,
        "elastic_agent_restarts": getattr(agent, "restarts", None),
        "elastic_agent_world": getattr(agent, "world_size", None),
        "sentinel_rollbacks": len(sent_rollbacks),
        "sentinel_quarantined": sent_quarantine,
        "sentinel_reports": [n for n, _ in sent_reports],
        "sentinel_max_rel_loss_diff": sent_max_rel,
        "wedge_heartbeat_kills": wedge_kills,
        "wedge_agent_rc": wedge_rc,
        "wedge_agent_restarts": getattr(wedge_agent, "restarts", None),
        "pipe_runs": pipe_runs,
        "pipe_max_rel_loss_diff": pipe_max_rel,
        "backend": jax.default_backend(),
    }))
    return 0 if ok else 1


def run_train_chaos_subprocess(timeout: float = 1350.0):
    return _run_flagged_subprocess("BENCH_TRAIN_CHAOS", timeout)


def pipeline_bench_main():
    """Child process: staged-pipeline trial (runtime/pipe/, docs/PIPELINE.md).

    Trains the same tiny llama twice — single fused program, then a 2-stage
    1F1B pipeline over the identical deterministic batch stream — and
    reports the parity verdict (the staged run must reproduce the fused
    loss trajectory to <=1e-6 rel; on CPU it is bit-exact), the measured
    bubble fraction from stepscope's ``train_pipe_bubble_fraction`` gauge
    next to the schedule's analytic value, and the per-stage wall
    breakdown (busy seconds per stage thread vs schedule wall)."""
    import numpy as np
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.runtime.pipe.schedule import bubble_fraction
    from deepspeed_tpu.telemetry import TELEMETRY

    e = os.environ
    steps = int(e.get("BENCH_PIPELINE_STEPS", 8))
    stages = int(e.get("BENCH_PIPELINE_STAGES", 2))
    gas = int(e.get("BENCH_PIPELINE_GAS", 4))
    sched = e.get("BENCH_PIPELINE_SCHEDULE", "1f1b")
    n_layers, vocab, seq = 2 * stages, 97, 32

    model_cfg = llama.LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_layers=n_layers, num_heads=4, num_kv_heads=2, max_seq_len=seq)

    def batches():
        rng = np.random.default_rng(42)
        return [{"input_ids": rng.integers(0, vocab, (8, seq),
                                           dtype=np.int32)}
                for _ in range(steps)]

    def config(pipeline):
        cfg = {
            "train_micro_batch_size_per_device": 8 // gas,
            "gradient_accumulation_steps": gas,
            "steps_per_print": 0,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "mesh": {"data": 1},
            "fp16": {"enabled": True, "initial_scale_power": 8},
            "gradient_clipping": 1.0,
            "seed": 7,
        }
        if pipeline:
            cfg["pipeline"] = {"stages": stages, "schedule": sched}
            cfg["telemetry"] = {"enabled": True,
                                "stepscope": {"enabled": True}}
        return cfg

    def run(pipeline):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(model_cfg, ctx=ctx),
            config=config(pipeline), seed=11,
            mesh_devices=jax.devices()[:1])
        losses = [float(engine.train_batch(b)) for b in batches()]
        return engine, losses

    _, base = run(False)
    pipe_engine, pipe = run(True)

    max_rel = max(abs(a - b) / max(1e-12, abs(a))
                  for a, b in zip(base, pipe))
    parity_ok = max_rel <= 1e-6

    busy = list(pipe_engine._last_stage_busy)
    wall = pipe_engine._last_stage_wall
    measured_bubble = pipe_engine.stepscope._g_pipe_bubble.value()
    plan = pipe_engine.stage_plan
    analytic_bubble = bubble_fraction(sched, plan.n_virtual, gas)
    prom = TELEMETRY.registry.render_prometheus()

    checks = {
        "loss_parity": parity_ok,
        "bubble_gauge_nonzero": measured_bubble > 0.0,
        "stage_breakdown": len(busy) == stages and wall > 0.0,
        "scrape_has_pipe_bubble": "train_pipe_bubble_fraction" in prom,
        "scrape_has_stage_skew":
            'train_step_skew_ratio{stage="0"}' in prom,
    }
    ok = all(checks.values())
    pipe_engine.destroy()
    print(json.dumps({
        "metric": "pipeline",
        "pipeline_ok": ok,
        "error": None if ok else {
            "reason": "pipeline assertions failed",
            "failed": sorted(k for k, v in checks.items() if not v)},
        "pipeline_checks": checks,
        "stages": stages,
        "schedule": sched,
        "n_microbatches": gas,
        "steps": steps,
        "max_rel_loss_diff": max_rel,
        "bubble_fraction_measured": measured_bubble,
        "bubble_fraction_analytic": analytic_bubble,
        "stage_busy_s": [round(b, 4) for b in busy],
        "schedule_wall_s": round(wall, 4),
        "stage_restarts": pipe_engine.stage_restarts,
        "backend": jax.default_backend(),
    }))
    return 0 if ok else 1


def run_pipeline_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_PIPELINE", timeout)


def probe_device():
    """Probe backend/device kind in a throwaway subprocess so the parent never
    holds the TPU (a held chip would make every trial subprocess fail to init).

    A HUNG probe (observed: the axon tunnel relay dying outright — port 8083
    gone, jax.devices() blocking forever) must fail loudly with a diagnosis,
    not crash the bench with a raw TimeoutExpired. A dead relay sometimes
    comes back within seconds (supervisor restart), so the probe retries
    once with a short backoff before giving up."""
    code = (
        "import jax, json;"
        "d = jax.devices()[0];"
        "print(json.dumps({'backend': jax.default_backend(),"
        " 'kind': getattr(d, 'device_kind', '')}))"
    )
    last = None
    for attempt in range(2):
        if attempt:
            print("bench: device probe failed; retrying once in 5 s "
                  "(relay may be restarting)", file=sys.stderr)
            time.sleep(5.0)
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True, timeout=300)
        except subprocess.TimeoutExpired:
            last = ("bench: device probe hung for 300 s — the accelerator "
                    "transport is wedged or its relay died (check that "
                    "something listens on 127.0.0.1:8083).")
            continue
        if proc.returncode != 0:
            last = "device probe failed:\n" + proc.stderr[-2000:]
            continue
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        last = "device probe produced no JSON"
    raise SystemExit(
        f"{last}\nNo benchable device after retry; aborting.")


def _enable_jit_cache():
    """Persistent XLA compile cache for the trial subprocesses: the serving
    trial alone compiles ~10 bucketed programs; repeat bench runs reuse them.

    TPU-only: cache-deserialized CPU programs can deadlock on hosts whose
    CPUID over-advertises features (see tests/conftest.py)."""
    import jax

    if jax.default_backend() != "tpu":
        return
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DSTPU_BENCH_JIT_CACHE", "/tmp/dstpu_bench_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def smoke_main():
    """On-accelerator smoke suite (<5 min warm): the kernels and engine
    paths the CPU test mesh can only interpret-check run HERE, where Pallas
    actually lowers (round-4 item 9). Prints one JSON line with per-check
    status; exit code 1 on any failure."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    checks: dict = {}
    perf: dict = {}
    t_all = time.perf_counter()

    def run(name):
        def deco(fn):
            t0 = time.perf_counter()
            try:
                fn()
                checks[name] = {"ok": True,
                                "s": round(time.perf_counter() - t0, 2)}
            except Exception as e:  # noqa: BLE001 - report, don't crash suite
                checks[name] = {"ok": False, "error": str(e)[:300],
                                "s": round(time.perf_counter() - t0, 2)}
            return fn
        return deco

    @run("flash_attention_fwd_bwd")
    def _flash():
        from deepspeed_tpu.ops.attention import attention, xla_attention

        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(2, 256, 8, 64)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(2, 256, 4, 64)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(2, 256, 4, 64)), jnp.bfloat16)

        def loss_fl(q, k, v):
            return attention(q, k, v, causal=True, impl="pallas").astype(
                jnp.float32).sum()

        def loss_ref(q, k, v):
            return xla_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()

        o = jax.jit(lambda *a: attention(*a, causal=True, impl="pallas"))(
            q, k, v)
        o_ref = jax.jit(lambda *a: xla_attention(*a, causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(o, np.float32),
                                   np.asarray(o_ref, np.float32),
                                   atol=3e-2, rtol=3e-2)
        g = jax.jit(jax.grad(loss_fl, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=6e-2, rtol=6e-2)

    @run("paged_decode_kernel_vs_gather")
    def _paged():
        from deepspeed_tpu.ops.attention import paged_attention

        rng = np.random.default_rng(1)
        t, mb, bs, hq, hkv, d = 16, 8, 32, 16, 8, 64
        nb = t * mb + 1
        q = jnp.asarray(rng.normal(size=(t, hq, d)), jnp.bfloat16)
        kp = jnp.asarray(rng.normal(size=(nb, bs, hkv, d)), jnp.bfloat16)
        vp = jnp.asarray(rng.normal(size=(nb, bs, hkv, d)), jnp.bfloat16)
        slots = jnp.arange(t, dtype=jnp.int32)
        positions = jnp.asarray(rng.integers(1, mb * bs, (t,)), jnp.int32)
        # read-only parity check: aliased blocks across rows are fine
        bt = jnp.asarray(rng.integers(1, nb, (t + 1, mb)), jnp.int32)
        a = jax.jit(lambda *x: paged_attention(*x, impl="pallas"))(
            q, kp, vp, slots, positions, bt)
        b = jax.jit(lambda *x: paged_attention(*x, impl="xla"))(
            q, kp, vp, slots, positions, bt)
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=3e-2, rtol=3e-2)

    @run("zero3_train_step")
    def _z3():
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology
        from deepspeed_tpu.models import llama

        reset_topology()
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(llama.LlamaConfig.tiny(512),
                                          ctx=ctx),
            config={"train_micro_batch_size_per_device": 4,
                    "gradient_accumulation_steps": 1, "steps_per_print": 0,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3}, "mesh": {"data": -1},
                    "seed": 3}, seed=3)
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, 512, (4, 64), dtype=np.int32)}
        l0 = float(eng.train_batch(batch))
        l1 = float(eng.train_batch(batch))
        assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0, (l0, l1)

    @run("zero_infinity_memory")
    def _inf():
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology
        from deepspeed_tpu.models import llama

        reset_topology()
        mcfg = llama.LlamaConfig(
            vocab_size=2048, hidden_size=512, intermediate_size=1536,
            num_layers=8, num_heads=8, num_kv_heads=4, max_seq_len=512)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(mcfg, ctx=ctx),
            config={"train_micro_batch_size_per_device": 2,
                    "gradient_accumulation_steps": 1, "steps_per_print": 0,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "zero_optimization": {
                        "stage": 3, "sub_group_size": 4_000_000,
                        "offload_param": {"device": "cpu"},
                        "offload_optimizer": {"device": "cpu"}},
                    "activation_checkpointing": {"enabled": True},
                    "mesh": {"data": 1, "fsdp": 1}, "seed": 3}, seed=3)
        param_bytes = eng.model_spec.num_params * 4
        kinds = {x.sharding.memory_kind
                 for x in jax.tree_util.tree_leaves(eng.params)}
        assert kinds == {"pinned_host"}, kinds
        # the round-4 'done' criterion: peak HBM param bytes < total param
        # bytes — the grads program's device footprint must exclude the
        # host-resident masters (they are host args, streamed per layer)
        if eng._grads_jit is None:
            eng._grads_jit = eng._build_grads_fn()
        rng = np.random.default_rng(0)
        db = eng._put_gas_batch(
            {"input_ids": rng.integers(0, 2048, (2, 256), dtype=np.int32)})
        ma = eng._grads_jit.lower(
            eng.params, eng.scale_state, jnp.int32(0), eng._train_rng, db
        ).compile().memory_analysis()
        assert ma.argument_size_in_bytes < param_bytes / 4, \
            ma.argument_size_in_bytes
        assert ma.host_argument_size_in_bytes >= param_bytes, \
            ma.host_argument_size_in_bytes
        loss = float(eng.train_batch(
            {"input_ids": rng.integers(0, 2048, (2, 256), dtype=np.int32)}))
        assert np.isfinite(loss)

    @run("evoformer_sparse_perf")
    def _science():
        # perf evidence for the science kernels (round-4 weak #7): timed on
        # the real accelerator vs dense attention at the same shape; numbers
        # land in the smoke JSON
        from deepspeed_tpu.ops.evoformer import evoformer_attention
        from deepspeed_tpu.ops.sparse_attention import (
            blocksparse_attention,
            make_local_layout,
        )
        from deepspeed_tpu.ops.attention import xla_attention

        rng = np.random.default_rng(3)

        def timeit(f, *a, iters=10):
            o = f(*a)
            jax.block_until_ready(o)
            t0 = time.perf_counter()
            for _ in range(iters):
                o = f(*a)
            jax.block_until_ready(o)
            return (time.perf_counter() - t0) / iters * 1e3

        # evoformer: [B, S, R, H, D] MSA-row attention with pair biases
        q = jnp.asarray(rng.normal(size=(1, 8, 256, 4, 32)), jnp.bfloat16)
        b1 = jnp.asarray(rng.normal(size=(1, 8, 1, 1, 256)), jnp.float32)
        b2 = jnp.asarray(rng.normal(size=(1, 1, 4, 256, 256)), jnp.float32)
        evo = jax.jit(lambda q, b1, b2: evoformer_attention(
            q, q, q, (b1, b2), chunk_size=64))
        perf["evoformer_ms"] = round(timeit(evo, q, b1, b2), 2)

        # blocksparse local attention vs dense at seq 2048
        s, blk = 2048, 64
        layout = make_local_layout(s // blk, window=4)
        qs = jnp.asarray(rng.normal(size=(2, s, 8, 64)), jnp.bfloat16)
        sp = jax.jit(lambda q: blocksparse_attention(
            q, q, q, layout, blk, causal=True))
        dn = jax.jit(lambda q: xla_attention(q, q, q, causal=True))
        perf["sparse_local_ms"] = round(timeit(sp, qs), 2)
        perf["dense_same_shape_ms"] = round(timeit(dn, qs), 2)

    @run("ragged_fused_serve")
    def _serve():
        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.inference.ragged import (
            RaggedConfig,
            RaggedInferenceEngine,
        )
        from deepspeed_tpu.models import llama

        mcfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=688,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)
        rng = np.random.default_rng(2)
        prompts = {i: rng.integers(0, 512, (int(L),), dtype=np.int32)
                   for i, L in enumerate([9, 17, 33])}
        # fp32: greedy argmax parity between the dense-cache and paged-pool
        # attention orders must not hinge on bf16 ties
        dense = InferenceEngine(lambda ctx: llama.build(mcfg, ctx=ctx),
                                dtype=jnp.float32, seed=0)
        want = {u: list(np.asarray(
            dense.generate(p[None], max_new_tokens=8))[0, len(p):])
            for u, p in prompts.items()}
        eng = RaggedInferenceEngine(
            model=lambda ctx: llama.build(mcfg, ctx=ctx), seed=0,
            dtype=jnp.float32,
            ragged_config=RaggedConfig(
                max_tokens_per_step=64, max_seqs=4, block_size=16,
                num_blocks=33, max_blocks_per_seq=8, fused_chunk=4,
                pipeline_depth=2, prefill_tile=16))
        for u, p in prompts.items():
            eng.put(u, p, max_new_tokens=8)
        got = eng.generate_all()
        assert got == want, "fused serve != dense greedy"

    ok = all(c["ok"] for c in checks.values())
    print(json.dumps({"smoke_ok": ok, "checks": checks, "perf": perf,
                      "total_s": round(time.perf_counter() - t_all, 1),
                      "backend": __import__("jax").default_backend()}))
    return 0 if ok else 1


# ------------------------------------------------------------- autotuning
# shared tiny model for autotune probe legs: the parent search computes the
# profile fingerprint from the SAME spec the probe children measure, so the
# persisted winner round-trips through initialize()/router lookup by key
_PROBE_MODEL = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    max_seq_len=256)
_PROBE_SEQ = 128


def _probe_model_builder():
    from deepspeed_tpu.models import llama

    cfg = llama.LlamaConfig(**_PROBE_MODEL)
    return cfg, (lambda ctx: llama.build(cfg, ctx=ctx))


def _set_dotted(d: dict, dotted: str, value):
    node = d
    parts = dotted.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _probe_train(overrides, steps):
    """One bounded train probe leg: tiny engine + stepscope, scored by
    goodput x MFU (samples/s standing in for MFU on backends without a
    peak-FLOPs model) x (1 + overlap fraction)."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.topology import reset_topology
    from deepspeed_tpu.telemetry import TELEMETRY

    model_cfg, builder = _probe_model_builder()
    config = {
        "train_micro_batch_size_per_device": 2,
        "sequence_length": _PROBE_SEQ,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1},
        "telemetry": {"enabled": True,
                      "stepscope": {"enabled": True,
                                    "profile_interval_steps": 0}},
    }
    for name, value in overrides.items():
        _set_dotted(config, name, value)
    reset_topology()
    TELEMETRY.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(model=builder, config=config)
    rng = np.random.default_rng(0)

    def batch():
        return {"input_ids": rng.integers(
            0, model_cfg.vocab_size,
            (engine.train_batch_size, _PROBE_SEQ), dtype=np.int32)}

    float(engine.train_batch(batch()))  # compile + settle
    t0 = time.perf_counter()
    loss = None
    for _ in range(max(steps, 1)):
        loss = engine.train_batch(batch())
    float(loss)  # settle before reading the clock
    dt = (time.perf_counter() - t0) / max(steps, 1)
    summary = engine.stepscope.summary()
    goodput = float(summary.get("goodput") or 0.0)
    mfu = float(summary.get("mfu") or 0.0)
    overlap = float(summary.get("overlap_fraction") or 0.0)
    samples_per_sec = engine.train_batch_size / dt
    engine.destroy()
    return {
        "score": goodput * (mfu if mfu > 0.0 else samples_per_sec)
        * (1.0 + overlap),
        "goodput": round(goodput, 4),
        "mfu": round(mfu, 6),
        "overlap_fraction": round(overlap, 4),
        "samples_per_sec": round(samples_per_sec, 2),
        "step_ms": round(dt * 1000, 2),
        "phase_seconds_total": summary.get("phase_seconds_total"),
    }


def _probe_serve(overrides, steps):
    """One bounded serving probe leg: tiny ragged engine on a pure-decode
    workload, scored by tokens/s x SLO-good fraction; the memory census
    (<= 5% unattributed) and token parity vs the plain host-staged path
    are HARD gates — a perf config that leaks or changes tokens is a
    non-result whatever its throughput."""
    import numpy as np

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
    from deepspeed_tpu.telemetry import SloMonitor, default_objectives

    model_cfg, builder = _probe_model_builder()
    n_req, prompt_len = 4, 16
    max_new = max(8, 4 * int(steps))
    block = 16
    mbs = -(-(prompt_len + max_new) // block)
    base = dict(max_tokens_per_step=64, max_seqs=n_req, block_size=block,
                num_blocks=n_req * mbs + 1, max_blocks_per_seq=mbs)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model_cfg.vocab_size, (prompt_len,),
                            dtype=np.int32) for _ in range(n_req)]

    def build(device_state=True, **over):
        kw = dict(base)
        kw.update(over)
        return RaggedInferenceEngine(
            model=builder, seed=0,
            ragged_config=RaggedConfig(device_state=device_state, **kw))

    def run(engine, tag):
        for i, p in enumerate(prompts):
            engine.put((tag, i), p, max_new_tokens=max_new)
        return engine.generate_all()

    tel = telemetry.configure(enabled=True, memledger={"enabled": True},
                              hbm_watermarks=False)
    try:
        engine = build(**overrides)
        run(engine, "warm")  # compiles every bucket this workload hits
        t0 = time.perf_counter()
        out = run(engine, "run")
        dt = max(time.perf_counter() - t0, 1e-9)
        toks = sum(len(v) for v in out.values())
        tokens_per_s = toks / dt
        # census while the candidate is the only live engine: its pool +
        # params must be attributed, or the config is disqualified
        led = tel.memledger
        census = led.census(update_state=False) if led is not None else None
        census_ok = (census is None
                     or census["unattributed_fraction"] <= 0.05)
        # SLO burn over the measured leg: per-token decode latency samples
        mon = SloMonitor(default_objectives(), tel.registry)
        per_tok = dt / max(toks, 1)
        for i in range(n_req):
            mon.record("decode_latency", per_tok, now=float(i))
        slo = mon.stats("decode_latency", now=float(n_req))
    finally:
        telemetry.configure(enabled=False)

    # token parity: the candidate's dispatch path vs the plain host-staged
    # baseline under the SAME codec/cache knobs, greedy + seeded sampling
    def parity_run(engine):
        for i, p in enumerate(prompts[:3]):
            kw = {} if i == 0 else dict(temperature=0.9, top_k=20,
                                        top_p=0.9, seed=7 + i)
            engine.put(i, p, max_new_tokens=6, **kw)
        return engine.generate_all()

    dispatch_knobs = ("sched_steps", "spec_draft", "decode_run_ahead",
                      "prefill_tile", "fused_chunk", "pipeline_depth")
    plain = {k: v for k, v in overrides.items() if k not in dispatch_knobs}
    parity_ok = (parity_run(build(device_state=False, **plain))
                 == parity_run(build(**overrides)))

    return {
        "score": tokens_per_s * slo["good_fraction"],
        "tokens_per_s": round(tokens_per_s, 2),
        "slo_good_fraction": round(slo["good_fraction"], 4),
        "slo_burn_rate": round(slo["burn_rate"], 4),
        "census_unattributed_fraction":
            None if census is None else census["unattributed_fraction"],
        "census_ok": census_ok,
        "parity_ok": parity_ok,
        "tokens": toks,
        "wall_s": round(dt, 3),
    }


def probe_main():
    """Child process: ONE bounded autotuner probe leg (``--mode probe``).

    JSON-only output. An OOM/compile failure inside the leg prints a
    structured ``{"error": ...}`` line and exits 0 — the PR 6 child-error
    discipline: rc != 0 is reserved for a dead interpreter, and the hard
    wall-clock timeout lives in the parent (run_probe_subprocess)."""
    try:
        spec = json.loads(os.environ.get("BENCH_PROBE_SPEC") or "{}")
    except json.JSONDecodeError as e:
        _fail_json({"reason": f"bad BENCH_PROBE_SPEC: {e}"})
        return 0
    kind = spec.get("kind", "train")
    overrides = dict(spec.get("overrides") or {})
    steps = int(spec.get("steps", 3))
    try:
        if kind == "train":
            out = _probe_train(overrides, steps)
        elif kind == "serve":
            out = _probe_serve(overrides, steps)
        else:
            _fail_json({"reason": f"unknown probe kind {kind!r}"})
            return 0
    except Exception as e:  # OOM / compile failure = structured result
        _fail_json({"reason": f"{type(e).__name__}: {e}"[:500],
                    "kind": kind, "overrides": overrides})
        return 0
    out.update(error=None, kind=kind, overrides=overrides, steps=steps)
    print(json.dumps(out))
    return 0


def run_probe_subprocess(spec: dict, timeout: float | None = None):
    """Bounded probe leg with a hard wall-clock timeout; returns
    ``(result, None)`` or ``(None, structured_error)``."""
    t = float(spec.get("timeout_s") or timeout or 180.0)
    result, err = _run_flagged_subprocess(
        "BENCH_PROBE", t, extra_env={"BENCH_PROBE_SPEC": json.dumps(spec)})
    if result is not None and result.get("error"):
        return None, result["error"]
    return result, err


def autotune_bench_main():
    """Child process: the end-to-end measurement-driven autotune loop on a
    tiny model (``--mode autotune``, the CI smoke budget).

    Search both engines over trimmed knob sets via bounded probe legs
    (each leg a run_probe_subprocess child sharing the jit cache), with a
    synthetic headroom budget sized so at least one candidate is pruned
    before compiling; persist the winners as content-keyed profiles; then
    prove the round trip — a fresh ``initialize`` picks the tuned train
    knobs up (and an explicitly-written config key beats them), and the
    serving router loads the serve profile at startup. One JSON line."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.autotuning import (
        SERVE,
        TRAIN,
        KnobSearch,
        probe_model_info,
        profiles,
    )
    from deepspeed_tpu.comm.topology import reset_topology
    from deepspeed_tpu.telemetry import TELEMETRY

    t_all = time.perf_counter()
    runs_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "runs")
    profile_dir = (os.environ.get("BENCH_AUTOTUNE_DIR")
                   or os.path.join(runs_dir, "autotune"))
    steps = int(os.environ.get("BENCH_AUTOTUNE_STEPS", 3))
    _, builder = _probe_model_builder()
    info = probe_model_info(builder)
    fp = profiles.model_fingerprint(info)
    topo = profiles.current_topology()
    # counters (autotune_{trials,pruned,failed}_total) land in the registry
    telemetry.configure(enabled=True, hbm_watermarks=False)

    def runner(kind, overrides, probe_steps):
        return run_probe_subprocess({
            "kind": kind, "overrides": overrides, "steps": probe_steps,
            "timeout_s": float(os.environ.get("BENCH_AUTOTUNE_PROBE_TIMEOUT",
                                              120.0))})

    # synthetic headroom budget: the CPU backend reports no bytes_limit, so
    # an explicit budget stands in for the TPU's measured one — sized so
    # micro_batch=8 fits and the 16 corner is pruned without compiling
    est8 = info.state_bytes(0, 1) + info.activation_bytes(8, _PROBE_SEQ)
    limit = est8 * 1.3 / 0.9

    train = KnobSearch(
        TRAIN, model_info=info, steps=steps, seq_len=_PROBE_SEQ,
        memory_bytes=limit, n_devices=jax.device_count(),
        knob_names=("train_micro_batch_size_per_device",
                    "activation_checkpointing.enabled"),
        probe_runner=runner, profile_dir=profile_dir).tune()
    serve = KnobSearch(
        SERVE, model_info=info, steps=steps,
        knob_names=("sched_steps", "fused_chunk"),
        probe_runner=runner, profile_dir=profile_dir).tune()

    # --- round trip 1: a fresh initialize() loads the train profile ------
    reset_topology()
    TELEMETRY.reset()
    telemetry.configure(enabled=True, hbm_watermarks=False)
    raw = {
        "sequence_length": _PROBE_SEQ,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "autotuning": {"enabled": True, "profile_dir": profile_dir},
    }
    tuned_mb = train["best_overrides"].get(
        "train_micro_batch_size_per_device")
    if tuned_mb is None:  # profile carries no batch knob: pin one ourselves
        raw["train_micro_batch_size_per_device"] = 2
    engine, _, _, _ = deepspeed_tpu.initialize(model=builder, config=raw)

    def _cfg_get(cfg, dotted):
        node = cfg
        for part in dotted.split("."):
            node = getattr(node, part)
        return node

    reloaded_by_engine = all(
        _cfg_get(engine.config, k) == v
        for k, v in train["best_overrides"].items())
    engine_gauge_ok = ("tuned_profile_loaded"
                      in TELEMETRY.registry.render_prometheus())
    engine.destroy()

    # --- round trip 2: an explicitly-written config key beats the profile
    reset_topology()
    raw2 = dict(raw, train_micro_batch_size_per_device=1)
    engine2, _, _, _ = deepspeed_tpu.initialize(model=builder, config=raw2)
    config_wins_ok = engine2.config.train_micro_batch_size_per_device == 1
    engine2.destroy()

    # --- round trip 3: the serving router loads the serve profile --------
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
    from deepspeed_tpu.serving.engine_loop import EngineLoop
    from deepspeed_tpu.serving.router import ReplicaRouter, RouterConfig

    prof = profiles.load_profile(profile_dir, subsystem=SERVE,
                                 fingerprint=fp, workload="default")
    rcfg = RaggedConfig(max_tokens_per_step=64, max_seqs=4, block_size=16,
                        num_blocks=17, max_blocks_per_seq=4)
    applied = (profiles.apply_serving_profile(rcfg, prof)
               if prof else {"applied": {}, "skipped": {}})
    serve_applied_ok = all(getattr(rcfg, k) == v
                           for k, v in serve["best_overrides"].items())
    sengine = RaggedInferenceEngine(model=builder, ragged_config=rcfg,
                                    seed=0)
    router = ReplicaRouter(
        [EngineLoop(sengine, name="replica-0")],
        RouterConfig(autotune_profile_dir=profile_dir,
                     autotune_fingerprint=fp))
    reloaded_by_router = (router.tuned_overrides()
                          == serve["best_overrides"])
    router.refresh_metrics()
    router_gauge_ok = ('tuned_profile_loaded{kind="serving"}'
                       in TELEMETRY.registry.render_prometheus())

    def _leg(summary):
        return {k: summary[k] for k in (
            "best_overrides", "best_score", "baseline_score", "trials",
            "pruned", "failed", "gate_failures", "gate_violations_accepted",
            "profile_path")}

    autotune_ok = bool(
        train["pruned"] + serve["pruned"] >= 1
        and train["best_score"] >= train["baseline_score"]
        and serve["best_score"] >= serve["baseline_score"]
        and train["gate_violations_accepted"] == 0
        and serve["gate_violations_accepted"] == 0
        and reloaded_by_engine and engine_gauge_ok and config_wins_ok
        and serve_applied_ok and reloaded_by_router and router_gauge_ok)
    print(json.dumps({
        "error": None,
        "autotune_ok": autotune_ok,
        "backend": jax.default_backend(),
        "fingerprint": fp,
        "topology": topo,
        "train": _leg(train),
        "serve": _leg(serve),
        "pruned_total": train["pruned"] + serve["pruned"],
        "gate_violations_accepted": (train["gate_violations_accepted"]
                                     + serve["gate_violations_accepted"]),
        "profile": {
            "dir": profile_dir,
            "reloaded_by_engine": reloaded_by_engine,
            "engine_gauge_ok": engine_gauge_ok,
            "config_wins_ok": config_wins_ok,
            "serve_applied": applied["applied"],
            "serve_applied_ok": serve_applied_ok,
            "reloaded_by_router": reloaded_by_router,
            "router_gauge_ok": router_gauge_ok,
        },
        "total_s": round(time.perf_counter() - t_all, 1),
    }))
    return 0 if autotune_ok else 1


def run_autotune_subprocess(timeout: float = 900.0):
    return _run_flagged_subprocess("BENCH_AUTOTUNE", timeout)


def main():
    if "--mode" in sys.argv:
        mode = sys.argv[sys.argv.index("--mode") + 1:][:1]
        if mode == ["decode-steady"]:
            result, err = run_decode_steady_subprocess()
            if result is None:
                print(f"decode-steady bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0
        if mode == ["chaos"]:
            result, err = run_chaos_subprocess()
            if result is None:
                print(f"chaos bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0 if result.get("chaos_ok") else 1
        if mode == ["train-anatomy"]:
            result, err = run_train_anatomy_subprocess()
            if result is None:
                print(f"train-anatomy bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0
        if mode == ["train-chaos"]:
            result, err = run_train_chaos_subprocess()
            if result is None:
                print(f"train-chaos bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0 if result.get("train_chaos_ok") else 1
        if mode == ["pipeline"]:
            result, err = run_pipeline_subprocess()
            if result is None:
                print(f"pipeline bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0 if result.get("pipeline_ok") else 1
        if mode == ["fleet"]:
            result, err = run_fleet_subprocess()
            if result is None:
                print(f"fleet bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0 if result.get("fleet_ok") else 1
        if mode == ["probe"]:
            # one bounded autotuner probe leg; spec JSON via --probe-spec
            spec = {}
            if "--probe-spec" in sys.argv:
                val = sys.argv[sys.argv.index("--probe-spec") + 1:][:1]
                try:
                    spec = json.loads(val[0]) if val else {}
                except json.JSONDecodeError as e:
                    print(f"bench: bad --probe-spec: {e}", file=sys.stderr)
                    return 2
            result, err = run_probe_subprocess(spec)
            if result is None:
                print(f"probe failed:\n{_err_text(err)}", file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0
        if mode == ["autotune"]:
            # end-to-end measurement-driven autotune loop (docs/AUTOTUNING.md)
            result, err = run_autotune_subprocess()
            if result is None:
                print(f"autotune bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0 if result.get("autotune_ok") else 1
        if mode != ["serving"]:
            print(f"bench: unknown --mode {mode or '(missing)'}; "
                  "supported: serving, decode-steady, chaos, train-anatomy, "
                  "train-chaos, pipeline, fleet, probe, autotune",
                  file=sys.stderr)
            return 2
        if "--tenants" in sys.argv:
            # multi-tenant metering trial: N tenants (one batch-class hog +
            # interactive bystanders) against one replica with the cost
            # meter on — per-tenant tokens/s and block-seconds share, the
            # occupancy-integral check, per-class SLO series and the
            # fair-share verdict in the JSON line (docs/OBSERVABILITY.md)
            val = sys.argv[sys.argv.index("--tenants") + 1:][:1]
            if not val or not val[0].isdigit():
                print("bench: --tenants needs an integer", file=sys.stderr)
                return 2
            result, err = run_tenants_subprocess(int(val[0]))
            if result is None:
                print(f"tenant bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0 if result.get("fair_share_ok") else 1
        if "--disagg" in sys.argv:
            # disaggregated prefill/decode cluster trial (docs/SERVING.md):
            # parity verdict, KV-transfer volume, handoff latency, cluster
            # prefix hit rate, autoscale policy check
            result, err = run_disagg_subprocess()
            if result is None:
                print(f"disagg bench failed:\n{_err_text(err)}",
                      file=sys.stderr)
                _fail_json(err)
                return 1
            print(json.dumps(result))
            return 0 if result.get("error") is None else 1
        if "--shared-prefix-tokens" in sys.argv:
            # shared-prompt workload: prompts share an N-token prefix and
            # the engine serves with the block-level prefix cache enabled
            val = sys.argv[sys.argv.index("--shared-prefix-tokens") + 1:][:1]
            if not val or not val[0].isdigit():
                print("bench: --shared-prefix-tokens needs an integer",
                      file=sys.stderr)
                return 2
            os.environ["BENCH_SERVING_SHARED_PREFIX"] = val[0]
        if "--kv-tier" in sys.argv:
            # hierarchical KV-cache tiering trial: tiny HBM pool + host/disk
            # tiers, repeated shared-prefix prompts, occurrence-parity and
            # demotion/promotion/prefetch counters in the JSON verdict
            os.environ["BENCH_SERVING_KV_TIER"] = "1"
        if "--kv-quant" in sys.argv:
            # low-bit KV serving trial: the tiered workload with an int8
            # (or fp8: `--kv-quant fp8`) pool — resident-block multiplier,
            # combined tier hit rate over quantized payloads, and the
            # quant-vs-fp drift verdict in the JSON line
            val = sys.argv[sys.argv.index("--kv-quant") + 1:][:1]
            codec = val[0] if val and val[0] in ("int8", "fp8") else "int8"
            os.environ["BENCH_SERVING_KV_QUANT"] = codec
        result, err = run_serving_subprocess()
        if result is None:
            print(f"serving bench failed:\n{_err_text(err)}", file=sys.stderr)
            _fail_json(err)
            return 1
        print(json.dumps(result))
        return 0
    if "--smoke" in sys.argv or os.environ.get("BENCH_SMOKE"):
        _enable_jit_cache()
        return smoke_main()
    if os.environ.get("BENCH_PROBE"):
        # checked before BENCH_AUTOTUNE: the autotune orchestrator's flag
        # leaks into its probe children's environments, and a probe leg
        # must never recurse into orchestration. Probe legs share the jit
        # cache so repeated tiny-model compiles amortize across the search.
        _enable_jit_cache()
        return probe_main()
    if os.environ.get("BENCH_AUTOTUNE"):
        _enable_jit_cache()
        return autotune_bench_main()
    if os.environ.get("BENCH_TRAIN_CHAOS_WORKER"):
        # checked before BENCH_TRAIN_CHAOS: the orchestrator's own env flag
        # leaks into inherited worker environments unless popped there, and
        # a worker must never recurse into orchestration
        return train_chaos_worker_main()
    if os.environ.get("BENCH_TRAIN_CHAOS"):
        # no jit cache: workers are SIGKILL'd mid-write by design and must
        # not leave torn entries in the shared compile cache
        return train_chaos_main()
    if os.environ.get("BENCH_CHAOS"):
        # no jit cache: the chaos child runs a deliberately tiny model and
        # must not pollute the shared compile cache with fault-path programs
        return chaos_bench_main()
    if os.environ.get("BENCH_PIPELINE"):
        # no jit cache: per-stage programs are tiny and the parity verdict
        # must not hinge on a cache-deserialized fused baseline
        return pipeline_bench_main()
    if os.environ.get("BENCH_FLEET_WORKER"):
        # checked before BENCH_FLEET for the same reason as the train-chaos
        # worker: the orchestrator flag leaks into worker environments and
        # a fleet worker must never recurse into orchestration
        _enable_jit_cache()
        return fleet_worker_main()
    if os.environ.get("BENCH_FLEET"):
        # the orchestrator itself never touches jax; workers enable the
        # jit cache so the second worker reuses the first's programs
        return fleet_bench_main()
    if os.environ.get("BENCH_SERVING_DISAGG"):
        _enable_jit_cache()
        return disagg_bench_main()
    if os.environ.get("BENCH_TENANTS"):
        # checked before BENCH_SERVING: the tenant leg is its own child and
        # must never fall through into the plain serving trial
        _enable_jit_cache()
        return tenant_bench_main()
    if os.environ.get("BENCH_SERVING"):
        _enable_jit_cache()
        return serving_bench_main()
    if os.environ.get("BENCH_SERVE"):
        _enable_jit_cache()
        return serve_trial_main()
    if os.environ.get("BENCH_DECODE_STEADY"):
        _enable_jit_cache()
        return decode_steady_main()
    if os.environ.get("BENCH_TRAIN_ANATOMY"):
        # no shared jit cache: recompile accounting is part of what this
        # trial measures, so cold compiles must be real
        return train_anatomy_main()
    if os.environ.get("BENCH_LEARN"):
        _enable_jit_cache()
        return learn_trial_main()
    if os.environ.get("BENCH_INFINITY"):
        _enable_jit_cache()
        return infinity_trial_main()
    if os.environ.get("BENCH_TRIAL"):
        _enable_jit_cache()
        return trial_main()

    info = probe_device()
    if info["backend"] != "tpu":
        # CPU smoke mode: tiny in-subprocess trials (stage 0 + stage 3), nominal peak
        smoke = (256, 688, 2, 512, 4, 2, 4, 64)
        result, err = run_trial_subprocess(smoke, steps=3)
        if result is None:
            print(_err_text(err), file=sys.stderr)
            _fail_json(err)
            return 1
        r3, err3 = run_trial_subprocess(smoke, steps=3, zero_stage=3)
        if r3 is not None:
            result["mfu_zero3"] = r3["value"]
        else:
            print(f"stage-3 smoke trial failed:\n{_err_text(err3)}",
                  file=sys.stderr)
        serve, errs = run_serve_subprocess()
        if serve is not None:
            result.update(serve)
        else:
            print(f"serving smoke trial failed:\n{_err_text(errs)}",
                  file=sys.stderr)
        learn, errl = run_learn_subprocess()
        if learn is not None:
            result.update(learn)
        else:
            print(f"learning smoke trial failed:\n{_err_text(errl)}",
                  file=sys.stderr)
        inf, erri = run_infinity_subprocess()
        if inf is not None:
            result.update(inf)
        else:
            print(f"infinity smoke trial failed:\n{_err_text(erri)}",
                  file=sys.stderr)
        print(json.dumps(result))
        return 0

    _, hbm = chip_spec(info["kind"])
    steps = int(os.environ.get("BENCH_STEPS", 10))

    # explicit shape overrides pin a single config (no ladder)
    shape_vars = ("BENCH_HIDDEN", "BENCH_FFN", "BENCH_LAYERS", "BENCH_VOCAB",
                  "BENCH_HEADS", "BENCH_KV", "BENCH_BATCH", "BENCH_SEQ")
    if any(v in os.environ for v in shape_vars):
        e = os.environ
        rung = (int(e.get("BENCH_HIDDEN", 2048)), int(e.get("BENCH_FFN", 5632)),
                int(e.get("BENCH_LAYERS", 8)), int(e.get("BENCH_VOCAB", 32768)),
                int(e.get("BENCH_HEADS", 16)), int(e.get("BENCH_KV", 8)),
                int(e.get("BENCH_BATCH", 8)), int(e.get("BENCH_SEQ", 2048)))
        result, err = run_trial_subprocess(rung, steps=steps)
        if result is None:
            print(f"pinned bench config {rung} failed:\n{_err_text(err)}",
                  file=sys.stderr)
            _fail_json(err)
            return 1
        print(json.dumps(result))
        return 0

    errors = []
    for rung in candidate_ladder(hbm):
        result, err = run_trial_subprocess(rung, steps=steps)
        if result is not None:
            # the north-star path is ZeRO-3 (BASELINE: Llama-3-8B stage 3);
            # report its MFU on the same rung alongside the headline number
            # (single-chip stage 3 measures the code path's overhead — the
            # sharding itself needs the fsdp axis of a real pod)
            r3, err3 = run_trial_subprocess(rung, steps=steps, zero_stage=3)
            if (r3 is not None and r3["value"] < 0.5 * result["value"]):
                # stage-3 and stage-0 run the SAME single-chip program shape;
                # a large gap is transport noise (observed once: 0.086 vs a
                # 0.61 immediate rerun), not a real number — measure again
                print(f"stage-3 rung read {r3['value']} vs headline "
                      f"{result['value']}; retrying once", file=sys.stderr)
                r3b, _ = run_trial_subprocess(rung, steps=steps, zero_stage=3)
                if r3b is not None and r3b["value"] > r3["value"]:
                    r3 = r3b
            if r3 is not None:
                result["mfu_zero3"] = r3["value"]
                result["tokens_per_s_zero3"] = r3.get("tokens_per_s")
            else:
                print("stage-3 rung failed (headline unaffected):\n"
                      + _err_text(err3), file=sys.stderr)
            # serving ladder rung: ragged continuous batching vs dense padding
            # (reference FastGen effective-throughput headline)
            serve, errs = run_serve_subprocess()
            if serve is not None:
                result.update(serve)
            else:
                print("serving trial failed (headline unaffected):\n"
                      + _err_text(errs), file=sys.stderr)
            # learning-evidence rung: real-text byte LM, loss must descend
            learn, errl = run_learn_subprocess()
            if learn is not None:
                result.update(learn)
            else:
                print("learning trial failed (headline unaffected):\n"
                      + _err_text(errl), file=sys.stderr)
            # ZeRO-Infinity rung: fp32 training state > HBM, host-resident
            # masters streamed per layer/sub-group (round-4 item 1)
            inf, erri = run_infinity_subprocess()
            if inf is not None:
                result.update(inf)
            else:
                print("infinity trial failed (headline unaffected):\n"
                      + _err_text(erri), file=sys.stderr)
            print(json.dumps(result))
            return 0
        errors.append(
            f"config {rung}: {_err_text(err)[-300:] if err else 'unknown'}")
        print(f"bench rung {rung} failed, backing off:\n{_err_text(err)}",
              file=sys.stderr)
    print("all bench rungs failed:\n" + "\n".join(errors), file=sys.stderr)
    _fail_json({"reason": "all bench rungs failed", "rungs": errors})
    return 1


if __name__ == "__main__":
    sys.exit(main())
