// Async file I/O engine for the NVMe offload tier.
//
// Role parity with the reference DeepNVMe AIO stack (csrc/aio/py_lib:
// deepspeed_aio_thread.cpp thread pool, deepspeed_py_aio_handle.cpp
// submit/wait API, deepspeed_pin_tensor.cpp pinned buffers) — rebuilt for the
// TPU-VM host: a pthread worker pool draining a request queue of
// pread/pwrite jobs against O_DIRECT-capable files, exposed as a flat C ABI
// for ctypes (no pybind11 in this image).
//
// The reference uses libaio; a thread pool over pread/pwrite reaches the same
// NVMe queue depths on modern kernels (io_uring/libaio matter most for QD>>64,
// far beyond what optimizer-state swapping generates) and stays portable.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
  int id;
  bool is_write;
  std::string path;
  void* buf;
  size_t nbytes;
};

struct Completion {
  ssize_t result;  // bytes transferred or -errno
};

class AioEngine {
 public:
  AioEngine(int num_threads, size_t block_size)
      : block_size_(block_size ? block_size : (1 << 20)), stop_(false), next_id_(1) {
    if (num_threads < 1) num_threads = 1;
    for (int i = 0; i < num_threads; ++i) {
      workers_.emplace_back([this] { this->worker(); });
    }
  }

  ~AioEngine() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int submit(bool is_write, const char* path, void* buf, size_t nbytes) {
    std::unique_lock<std::mutex> lk(mu_);
    int id = next_id_++;
    queue_.push_back(Request{id, is_write, path, buf, nbytes});
    pending_.insert(id);
    cv_.notify_one();
    return id;
  }

  // blocks until request `id` completes; returns bytes transferred or -errno
  ssize_t wait(int id) {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] { return completions_.count(id) > 0; });
    ssize_t r = completions_[id].result;
    completions_.erase(id);
    return r;
  }

  // waits for every submitted request; returns 0 or first negative errno
  ssize_t wait_all() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] { return pending_.empty(); });
    ssize_t rc = 0;
    for (auto& kv : completions_) {
      if (kv.second.result < 0 && rc == 0) rc = kv.second.result;
    }
    completions_.clear();
    return rc;
  }

 private:
  void worker() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        req = queue_.front();
        queue_.pop_front();
      }
      ssize_t result = execute(req);
      {
        std::unique_lock<std::mutex> lk(mu_);
        completions_[req.id] = Completion{result};
        pending_.erase(req.id);
      }
      done_cv_.notify_all();
    }
  }

  ssize_t execute(const Request& req) {
    int flags = req.is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    size_t off = 0;
    char* p = static_cast<char*>(req.buf);
    while (off < req.nbytes) {
      size_t chunk = std::min(block_size_, req.nbytes - off);
      ssize_t n = req.is_write ? ::pwrite(fd, p + off, chunk, (off_t)off)
                               : ::pread(fd, p + off, chunk, (off_t)off);
      if (n < 0) {
        int e = errno;
        ::close(fd);
        return -e;
      }
      if (n == 0) break;  // EOF on read
      off += (size_t)n;
    }
    if (req.is_write && ::fsync(fd) != 0) {
      int e = errno;
      ::close(fd);
      return -e;
    }
    ::close(fd);
    return (ssize_t)off;
  }

  size_t block_size_;
  bool stop_;
  int next_id_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  std::deque<Request> queue_;
  std::unordered_map<int, Completion> completions_;
  std::unordered_map<int, int> pending_map_unused_;
  std::vector<std::thread> workers_;
  // pending ids (separate from completions)
  struct IdSet {
    std::unordered_map<int, bool> m;
    void insert(int id) { m[id] = true; }
    void erase(int id) { m.erase(id); }
    bool empty() const { return m.empty(); }
    size_t count(int id) const { return m.count(id); }
  } pending_;
};

}  // namespace

extern "C" {

void* dstpu_aio_create(int num_threads, uint64_t block_size) {
  return new AioEngine(num_threads, (size_t)block_size);
}

void dstpu_aio_destroy(void* h) { delete static_cast<AioEngine*>(h); }

int dstpu_aio_submit_write(void* h, const char* path, const void* buf, uint64_t n) {
  return static_cast<AioEngine*>(h)->submit(true, path, const_cast<void*>(buf), (size_t)n);
}

int dstpu_aio_submit_read(void* h, const char* path, void* buf, uint64_t n) {
  return static_cast<AioEngine*>(h)->submit(false, path, buf, (size_t)n);
}

int64_t dstpu_aio_wait(void* h, int id) {
  return (int64_t) static_cast<AioEngine*>(h)->wait(id);
}

int64_t dstpu_aio_wait_all(void* h) {
  return (int64_t) static_cast<AioEngine*>(h)->wait_all();
}

}  // extern "C"
