"""Test harness: simulate an 8-device mesh on CPU.

Mirrors the reference's distributed-in-one-box strategy
(``tests/unit/common.py DistributedExec`` spawns N processes + NCCL/gloo): here a
single process hosts N XLA CPU devices via
``--xla_force_host_platform_device_count`` and all collectives run for real
through the CPU backend. Must be set before jax initializes its backend.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags += " --xla_force_host_platform_device_count=8"

# On a 1-core box the 8 simulated device threads time-slice one CPU and XLA's
# collective-rendezvous watchdog can abort heavy tests.  The flags that relax
# it are NOT safe to hardcode: preloaded PJRT plugins (TPU tunnel) parse
# XLA_FLAGS with their own registry and F-abort on flags unknown to them.
# Probe in a subprocess and adopt only what this environment accepts.
from deepspeed_tpu.utils.xla_flags import probe_extra_xla_flags  # noqa: E402

_flags += "".join(
    " " + f
    for f in probe_extra_xla_flags(
        [
            "--xla_cpu_collective_call_warn_stuck_seconds=120",
            "--xla_cpu_collective_call_terminate_timeout_seconds=3600",
        ],
        base_flags=_flags,
    )
)
os.environ["XLA_FLAGS"] = _flags
os.environ["DSTPU_ACCELERATOR"] = "cpu"

# jax may already be preloaded (TPU-tunnel .pth hook) with JAX_PLATFORMS=axon;
# the backend itself initializes lazily, so redirecting the config here still works.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Synchronous CPU dispatch: with async dispatch, multiple in-flight 8-device
# collective programs time-slicing ONE core can wedge XLA's in-process
# collective rendezvous (observed as 0%-CPU hangs deep into long sessions).
# CPU-only knob; TPU async stepping is unaffected.
try:
    jax.config.update("jax_cpu_enable_async_dispatch", False)
except Exception:
    pass

# persistent compilation cache: repeat runs of the suite skip XLA recompiles
# (the dominant cost — every engine test jits a full train step)
_cache_dir = os.environ.get("DSTPU_TEST_JIT_CACHE", "/tmp/dstpu_jit_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_global_state():
    """Each test builds its own topology; reset the module-level singletons."""
    yield
    from deepspeed_tpu.comm.topology import reset_topology
    from deepspeed_tpu.utils.comms_logging import COMMS_LOGGER

    reset_topology()
    COMMS_LOGGER.reset()
    COMMS_LOGGER.enabled = False


@pytest.fixture
def mesh8():
    """A data=8 topology over the simulated devices."""
    from deepspeed_tpu.comm.comm import init_distributed
    from deepspeed_tpu.config.config import MeshConfig

    return init_distributed(MeshConfig(data=8))
