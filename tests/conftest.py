"""Test harness: simulate an 8-device mesh on CPU.

Mirrors the reference's distributed-in-one-box strategy
(``tests/unit/common.py DistributedExec`` spawns N processes + NCCL/gloo): here a
single process hosts N XLA CPU devices via
``--xla_force_host_platform_device_count`` and all collectives run for real
through the CPU backend. Must be set before jax initializes its backend.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags += " --xla_force_host_platform_device_count=8"

# On a 1-core box the 8 simulated device threads time-slice one CPU and XLA's
# collective-rendezvous watchdog can abort heavy tests.  The flags that relax
# it are NOT safe to hardcode: preloaded PJRT plugins (TPU tunnel) parse
# XLA_FLAGS with their own registry and F-abort on flags unknown to them.
# Probe in a subprocess and adopt only what this environment accepts.
from deepspeed_tpu.utils.xla_flags import probe_extra_xla_flags  # noqa: E402

_flags += "".join(
    " " + f
    for f in probe_extra_xla_flags(
        [
            "--xla_cpu_collective_call_warn_stuck_seconds=120",
            # a wedged collective must FAIL loudly (surfacing the emulation
            # artifact, see tests/unit/isolation.py) instead of eating the
            # whole suite window as a silent 0%-CPU hang
            "--xla_cpu_collective_call_terminate_timeout_seconds=600",
        ],
        base_flags=_flags,
    )
)
os.environ["XLA_FLAGS"] = _flags
os.environ["DSTPU_ACCELERATOR"] = "cpu"

# jax may already be preloaded (TPU-tunnel .pth hook) with JAX_PLATFORMS=axon;
# the backend itself initializes lazily, so redirecting the config here still works.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Synchronous CPU dispatch: with async dispatch, multiple in-flight 8-device
# collective programs time-slicing ONE core can wedge XLA's in-process
# collective rendezvous (observed as 0%-CPU hangs deep into long sessions).
# CPU-only knob; TPU async stepping is unaffected.
try:
    jax.config.update("jax_cpu_enable_async_dispatch", False)
except Exception:
    pass

# NO persistent compilation cache for the CPU test mesh. This VM's CPUID
# advertises features the kernel doesn't enable (XLA's AOT loader warns
# "Compile machine features ... vs host machine features ... could lead to
# execution errors such as SIGILL"); cache-DESERIALIZED CPU collective
# programs then deadlock with every thread futex-parked (root cause of the
# round-4 suite wedges: cold runs pass deterministically, cache-hit runs
# wedge). Opt back in explicitly with DSTPU_TEST_JIT_CACHE if your machine
# loads its own cache entries cleanly.
_cache_dir = os.environ.get("DSTPU_TEST_JIT_CACHE")
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


# ---------------------------------------------------------------- sharding
# A FULL-SUITE invocation (`pytest tests/ ...`) transparently runs as a few
# sequential fresh-process shards. Reason: XLA's emulated-CPU collective
# executor can deadlock (all threads futex-parked, 0% CPU, no watchdog fire)
# after enough DISTINCT multi-device programs have run in one process on this
# 1-core box. Empirically, file subsets of ~1/3 of the suite pass reliably
# while single-process full runs wedge at probabilistic points (three round-4
# runs: the NVMe step, the autotuner sweep, ...). Sharding keeps the
# advertised `python -m pytest tests/ -x -q` entry point working; targeted
# invocations (specific files/tests) are never sharded.
_N_SHARDS = 4


def pytest_cmdline_main(config):
    if os.environ.get("DSTPU_SUITE_SHARD"):
        return None  # we ARE a shard child: run normally
    args = list(config.invocation_params.args)
    positional = [a for a in args if not a.startswith("-")]
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    # shard only the full-suite spelling: `pytest tests/` (or the repo root)
    roots = {tests_dir, os.path.dirname(tests_dir)}
    if not positional or not all(
            os.path.abspath(p.rstrip("/")) in roots for p in positional):
        return None

    import glob
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "dstpu_test_isolation", os.path.join(tests_dir, "unit", "isolation.py"))
    isolation = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(isolation)

    files = sorted(glob.glob(os.path.join(tests_dir, "unit", "test_*.py")))
    if len(files) < _N_SHARDS + 1:
        return None
    flags = [a for a in args if a.startswith("-")]
    # round-robin by position: spreads the heavy engine files across shards
    shards = [files[i::_N_SHARDS] for i in range(_N_SHARDS)]
    env = dict(os.environ)
    env["DSTPU_SUITE_SHARD"] = "1"
    rc = 0
    for i, shard in enumerate(shards):
        for attempt in range(3):
            print(f"\n=== suite shard {i + 1}/{len(shards)} "
                  f"({len(shard)} files"
                  + (f", retry {attempt}" if attempt else "") + ") ===",
                  flush=True)
            shard_rc, stalled = isolation.run_with_stall_watchdog(
                [sys.executable, "-m", "pytest", *flags, *shard],
                env=env, stall_seconds=180, timeout=1500)
            if shard_rc is not None:
                rc = max(rc, shard_rc)
                break
            print(f"=== shard {i + 1} "
                  + ("stalled (emulation deadlock, see tests/unit/"
                     "isolation.py); retrying" if stalled else "timed out"),
                  flush=True)
        else:
            rc = max(rc, 1)
        if rc and ("-x" in flags or "--exitfirst" in flags):
            break
    return rc


@pytest.fixture(autouse=True)
def _reset_global_state():
    """Each test builds its own topology; reset the module-level singletons."""
    yield
    from deepspeed_tpu.comm.topology import reset_topology
    from deepspeed_tpu.serving.faults import get_fault_injector
    from deepspeed_tpu.telemetry import TELEMETRY
    from deepspeed_tpu.utils.comms_logging import COMMS_LOGGER

    reset_topology()
    COMMS_LOGGER.reset()
    COMMS_LOGGER.enabled = False
    TELEMETRY.reset()
    get_fault_injector().reset()


@pytest.fixture
def mesh8():
    """A data=8 topology over the simulated devices."""
    from deepspeed_tpu.comm.comm import init_distributed
    from deepspeed_tpu.config.config import MeshConfig

    return init_distributed(MeshConfig(data=8))
