"""Elastic agent: worker supervision, scale-down restart, preemption
checkpointing (reference ``elasticity/elastic_agent.py`` + checkpoint-based
recovery, SURVEY §5.3)."""

import os
import signal
import sys

import numpy as np
import pytest

from deepspeed_tpu.elasticity.agent import ElasticAgent, PreemptionHandler, WorkerSpec


def _worker_cmd(tmp_path, rank, world, die_rank=None):
    """A worker that writes its (rank, world), optionally dies once."""
    marker = tmp_path / f"died_once_{rank}"
    code = f"""
import os, sys, time
open({str(tmp_path)!r} + f"/seen_{{os.environ['RANK']}}_{{os.environ['WORLD_SIZE']}}", "w").close()
if os.environ['RANK'] == {die_rank!r} and not os.path.exists({str(marker)!r}):
    open({str(marker)!r}, "w").close()
    sys.exit(17)
time.sleep(0.2)
"""
    return [sys.executable, "-c", code]


class TestElasticAgent:
    def test_scale_down_restart(self, tmp_path):
        """A dying worker triggers relaunch at the next admissible world size
        with the remaining capacity."""

        def make(rank, world):
            env = dict(os.environ, RANK=str(rank), WORLD_SIZE=str(world))
            return WorkerSpec(cmd=_worker_cmd(tmp_path, rank, world, die_rank="1"),
                              env=env)

        agent = ElasticAgent(
            target_batch_size=32,
            micro_batch_candidates=[1, 2, 4],
            make_worker=make,
            max_world_size=4,
            poll_interval=0.1,
        )
        assert agent.admissible_world_sizes() == [1, 2, 4]
        assert agent.run() == 0
        # first wave at world=4 (rank 1 died once), second wave at world<=3 -> 2
        assert (tmp_path / "seen_0_4").exists()
        assert (tmp_path / "seen_0_2").exists()
        assert not (tmp_path / "seen_0_3").exists()  # 3 inadmissible for batch 32

    def test_no_admissible_size_raises(self):
        agent = ElasticAgent(
            target_batch_size=7,
            micro_batch_candidates=[2],
            make_worker=lambda r, w: WorkerSpec(cmd=["true"]),
            max_world_size=4,
        )
        with pytest.raises(ValueError, match="no admissible"):
            agent.admissible_world_sizes()

    def test_sigkilled_preemption_restarts(self, tmp_path):
        """A SIGKILL'd worker (negative returncode — a preempted host) must
        take the same restart branch as a nonzero exit."""
        marker = tmp_path / "killed_once"

        def make(rank, world):
            code = f"""
import os, signal, time
open({str(tmp_path)!r} + f"/ran_{{os.environ['RANK']}}_{{os.environ['WORLD_SIZE']}}", "w").close()
if not os.path.exists({str(marker)!r}):
    open({str(marker)!r}, "w").close()
    os.kill(os.getpid(), signal.SIGKILL)
time.sleep(0.2)
"""
            env = dict(os.environ, RANK=str(rank), WORLD_SIZE=str(world))
            return WorkerSpec(cmd=[sys.executable, "-c", code], env=env)

        agent = ElasticAgent(
            target_batch_size=8, micro_batch_candidates=[2, 4, 8],
            make_worker=make, max_world_size=2, min_world_size=1,
            poll_interval=0.1)
        assert agent.run() == 0
        assert agent.restarts == 1
        assert (tmp_path / "ran_0_2").exists()
        assert (tmp_path / "ran_0_1").exists()  # relaunched smaller

    def test_heartbeat_stale_worker_killed(self, tmp_path):
        """A worker that stays alive but never beats past the grace window
        is wedged: the agent SIGKILLs it and the relaunch completes."""
        hb_dir = tmp_path / "state"
        hb_dir.mkdir()
        marker = tmp_path / "wedged_once"

        def make(rank, world):
            code = f"""
import json, os, time
hb = os.path.join({str(hb_dir)!r}, "heartbeat_0.json")
if not os.path.exists({str(marker)!r}):
    open({str(marker)!r}, "w").close()
    time.sleep(600)  # wedged-but-alive: no beacon ever written
with open(hb, "w") as f:
    json.dump({{"step": 1}}, f)
time.sleep(0.2)
"""
            return WorkerSpec(cmd=[sys.executable, "-c", code],
                              env=dict(os.environ))

        agent = ElasticAgent(
            target_batch_size=4, micro_batch_candidates=[4],
            make_worker=make, max_world_size=1, min_world_size=1,
            poll_interval=0.1, heartbeat_dir=str(hb_dir),
            heartbeat_timeout=0.5, heartbeat_grace=1.5)
        assert agent.run() == 0
        assert agent.heartbeat_kills == 1
        assert agent.restarts == 1

    def test_sweep_stale_state(self, tmp_path):
        """Launch sweeps per-incarnation heartbeat beacons and torn
        quarantine files; a valid quarantine list (healing memory) stays."""
        hb_dir = tmp_path / "state"
        hb_dir.mkdir()
        (hb_dir / "heartbeat_0.json").write_text('{"step": 3}')
        (hb_dir / "heartbeat_1.json").write_text("torn{")
        (hb_dir / "quarantine.json").write_text('["abc123"]')

        agent = ElasticAgent(
            target_batch_size=4, micro_batch_candidates=[4],
            make_worker=lambda r, w: WorkerSpec(
                cmd=[sys.executable, "-c", "pass"], env=dict(os.environ)),
            max_world_size=1, poll_interval=0.1,
            heartbeat_dir=str(hb_dir), heartbeat_timeout=5.0)
        assert agent.run() == 0
        assert not (hb_dir / "heartbeat_0.json").exists()
        assert not (hb_dir / "heartbeat_1.json").exists()
        assert (hb_dir / "quarantine.json").read_text() == '["abc123"]'

        # torn quarantine is removed at the next launch
        (hb_dir / "quarantine.json").write_text('["abc123"')  # torn write
        agent2 = ElasticAgent(
            target_batch_size=4, micro_batch_candidates=[4],
            make_worker=lambda r, w: WorkerSpec(
                cmd=[sys.executable, "-c", "pass"], env=dict(os.environ)),
            max_world_size=1, poll_interval=0.1,
            heartbeat_dir=str(hb_dir), heartbeat_timeout=5.0)
        assert agent2.run() == 0
        assert not (hb_dir / "quarantine.json").exists()


class TestPreemptionHandler:
    def test_sigterm_checkpoints_and_stops(self, tmp_path):
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology
        from deepspeed_tpu.models import llama

        reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(llama.LlamaConfig.tiny(256), ctx=ctx),
            config={
                "train_micro_batch_size_per_device": 2,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "mesh": {"data": 8},
            },
        )
        handler = PreemptionHandler(engine, str(tmp_path))
        try:
            rng = np.random.default_rng(0)
            steps = 0
            for _ in range(5):
                if handler.should_stop:
                    break
                engine.train_batch(
                    {"input_ids": rng.integers(0, 256, (16, 16), dtype=np.int32)})
                steps += 1
                if steps == 2:  # the preemption notice arrives mid-run
                    os.kill(os.getpid(), signal.SIGTERM)
            path = handler.checkpoint_if_needed()
            assert handler.should_stop and steps == 2
            assert path is not None and (tmp_path / "preempt").is_dir()
            assert handler.checkpoint_if_needed() is None  # at most once
        finally:
            handler.restore()

    def test_drain_callbacks_engine_free(self):
        """Serving-style registration: no training engine, immediate hooks
        fire inside the signal handler, deferred hooks via drain(), each at
        most once."""
        handler = PreemptionHandler(signals=(signal.SIGTERM,))
        fired = []
        handler.register("stop-admission", lambda: fired.append("now") or "ok",
                         immediate=True)
        handler.register("flush", lambda: fired.append("later") or 7)
        with pytest.raises(ValueError, match="already registered"):
            handler.register("flush", lambda: None)
        try:
            assert handler.drain() == {}  # no signal yet -> no-op
            os.kill(os.getpid(), signal.SIGTERM)
            assert handler.should_stop and handler.stop_event.is_set()
            assert fired == ["now"]  # immediate hook ran in the handler
            results = handler.drain()
            assert fired == ["now", "later"]
            assert results == {"stop-admission": "ok", "flush": 7}
            assert handler.drain() == results  # at most once per hook
            assert handler.checkpoint_if_needed() is None  # engine-free
        finally:
            handler.restore()

    def test_engine_requires_save_dir(self):
        with pytest.raises(ValueError, match="save_dir"):
            PreemptionHandler(engine=object())
