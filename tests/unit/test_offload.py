"""Offload tiers: windowed sub-group optimizer state on host / NVMe
(reference: ``tests/unit/runtime/zero`` offload suites +
``test_nvme_checkpointing.py``)."""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime.offload import partition_groups

VOCAB = 256


def test_partition_groups():
    groups = partition_groups([10, 10, 50, 5, 100, 1], 60)
    assert groups == [[0, 1], [2, 3], [4], [5]]
    assert partition_groups([200], 60) == [[0]]  # oversized leaf -> own group
    assert partition_groups([], 60) == []


def _engine(offload_device, tmp_path, stage=2, sub_group=30_000):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": stage,
            "sub_group_size": sub_group,
            "offload_optimizer": {
                "device": offload_device,
                "nvme_path": str(tmp_path / "nvme"),
            },
        },
        "mesh": {"data": 2, "fsdp": 4},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    return engine


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}
            for _ in range(n)]


def _run(engine, batches):
    return [float(engine.train_batch(b)) for b in batches]


class TestWindowedOffload:
    def test_nvme_training_matches_baseline(self, tmp_path):
        """offload_optimizer.device=nvme: identical loss trajectory to the
        un-offloaded engine, optimizer state never device-resident."""
        batches = _batches(4)
        base = _run(_engine("none", tmp_path), batches)

        eng = _engine("nvme", tmp_path)
        assert eng.opt_state is None  # state lives on NVMe, not in HBM
        assert len(eng._groups) > 1   # genuinely windowed
        got = _run(eng, batches)
        np.testing.assert_allclose(got, base, rtol=2e-4, atol=2e-5)
        # still on disk after training, and never materialized on the engine
        assert eng.opt_state is None
        swp = [f for f in os.listdir(tmp_path / "nvme") if f.endswith(".swp")]
        assert len(swp) >= len(eng._groups)

    def test_cpu_windowed_matches_baseline(self, tmp_path):
        """Host-tier path: grouped in-jit update (memory kinds are a no-op on
        the CPU test backend, but the windowed group walk is exercised)."""
        batches = _batches(4, seed=3)
        base = _run(_engine("none", tmp_path), batches)
        eng = _engine("cpu", tmp_path)
        assert isinstance(eng.opt_state, list) and len(eng.opt_state) > 1
        got = _run(eng, batches)
        np.testing.assert_allclose(got, base, rtol=2e-4, atol=2e-5)

    def test_nvme_checkpoint_roundtrip(self, tmp_path):
        """Save/load with NVMe-offloaded state: resumed run matches the
        continuous one (reference test_nvme_checkpointing.py)."""
        batches = _batches(4, seed=5)
        cont = _engine("nvme", tmp_path / "a")
        cont_losses = _run(cont, batches)

        half = _engine("nvme", tmp_path / "b")
        _run(half, batches[:2])
        half.save_checkpoint(str(tmp_path / "ckpt"))

        resumed = _engine("nvme", tmp_path / "c")
        resumed.load_checkpoint(str(tmp_path / "ckpt"))
        got = _run(resumed, batches[2:])
        np.testing.assert_allclose(got, cont_losses[2:], rtol=2e-4, atol=2e-5)

    def test_backward_path_guarded_under_nvme(self, tmp_path):
        eng = _engine("nvme", tmp_path)
        with pytest.raises(NotImplementedError):
            eng.backward(_batches(1)[0])

    @pytest.mark.parametrize("device", ["cpu", "nvme"])
    def test_tensor_fragment_api_with_offload(self, tmp_path, device):
        """safe_get_full_optimizer_state resolves moments across the grouped
        and NVMe representations (reference test_zero_tensor_fragment.py)."""
        from deepspeed_tpu.utils.tensor_fragment import (
            safe_get_full_optimizer_state,
        )

        eng = _engine(device, tmp_path)
        eng.train_batch(_batches(1)[0])
        mu = safe_get_full_optimizer_state(eng, "layers/wq", "exp_avg")
        nu = safe_get_full_optimizer_state(eng, "layers/wq", "exp_avg_sq")
        assert mu.shape == np.asarray(eng.params["layers"]["wq"]).shape
        assert float(np.abs(mu).sum()) > 0 and float(nu.sum()) > 0
