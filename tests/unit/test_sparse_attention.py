"""Block-sparse attention: exactness vs dense-masked attention + compute
savings (reference ``deepspeed/ops/sparse_attention`` + its unit tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import repeat_kv, xla_attention
from deepspeed_tpu.ops.sparse_attention import (
    SparseSelfAttention,
    SparsityConfig,
    blocksparse_attention,
    make_bslongformer_layout,
    make_fixed_layout,
    make_local_layout,
)

B, S, H, HKV, D, BS = 2, 256, 4, 2, 16, 32


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, S, H, D)),
            jax.random.normal(ks[1], (B, S, HKV, D)),
            jax.random.normal(ks[2], (B, S, HKV, D)))


def _dense_reference(q, k, v, layout, causal):
    """Dense attention under the layout's elementwise mask."""
    nb = S // BS
    elem = np.kron(np.asarray(layout, bool), np.ones((BS, BS), bool))
    if causal:
        elem &= np.tril(np.ones((S, S), bool))
    bias = jnp.where(jnp.asarray(elem), 0.0, -1e30)[None, None]
    return xla_attention(q, repeat_kv(k, H // HKV), repeat_kv(v, H // HKV),
                         causal=False, bias=bias)


@pytest.mark.parametrize("make,args", [
    (make_local_layout, (S // BS, 2)),
    (make_fixed_layout, (S // BS, 2, 4)),
    (make_bslongformer_layout, (S // BS, 2, 1)),
])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense_masked(make, args, causal):
    q, k, v = _qkv()
    layout = make(*args)
    got = jax.jit(lambda q, k, v: blocksparse_attention(
        q, k, v, layout, BS, causal=causal))(q, k, v)
    ref = _dense_reference(q, k, v, layout, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_full_layout_equals_dense_causal():
    q, k, v = _qkv(1)
    layout = np.ones((S // BS, S // BS), bool)
    got = blocksparse_attention(q, k, v, layout, BS, causal=True)
    ref = xla_attention(q, repeat_kv(k, H // HKV), repeat_kv(v, H // HKV),
                        causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_compute_scales_with_active_blocks():
    """The sparse path's attention FLOPs shrink with the layout, not with S^2."""
    q, k, v = _qkv(2)
    sparse = jax.jit(lambda q, k, v: blocksparse_attention(
        q, k, v, make_local_layout(S // BS, 2), BS, causal=True))
    dense = jax.jit(lambda q, k, v: xla_attention(
        q, repeat_kv(k, H // HKV), repeat_kv(v, H // HKV), causal=True))
    fs = sparse.lower(q, k, v).compile().cost_analysis()["flops"]
    fd = dense.lower(q, k, v).compile().cost_analysis()["flops"]
    # window of 2 blocks out of 8 -> ~4x fewer attention flops
    assert fs < fd * 0.5, (fs, fd)


def test_sparse_self_attention_wrapper_and_grads():
    q, k, v = _qkv(3)
    attn = SparseSelfAttention(SparsityConfig(mode="fixed", block_size=BS,
                                              local_window=2, global_stride=4))

    def loss(q, k, v):
        return jnp.sum(attn(q, k, v) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    def loss_ref(q, k, v):
        ref = _dense_reference(q, k, v, attn.config.layout(S), True)
        return jnp.sum(ref ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-5, atol=5e-5)


def test_validation_errors():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="divisible"):
        blocksparse_attention(q, k, v, np.ones((4, 4), bool), 100)
    with pytest.raises(ValueError, match="layout shape"):
        blocksparse_attention(q, k, v, np.ones((4, 4), bool), BS)
    with pytest.raises(ValueError, match="unknown sparsity mode"):
        SparsityConfig(mode="nope").layout(S)