"""ZeRO-Infinity parameter offload (reference ``runtime/zero/
parameter_offload.py`` + ``swap_tensor/partitioned_param_swapper.py``):
host-resident master params streamed through HBM per scanned layer.

On the CPU test mesh the pinned-host memory kind is rejected by the SPMD
partitioner (see ``runtime/offload.supports_memory_kinds``), so storage
falls back to device while the full streaming code path — the
``ShardCtx.param_stream`` per-slice hook, the whole-leaf stream cast, the
group-walk param streaming — stays live; the memory claim itself is asserted
on real TPU by ``bench.py --smoke``."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import ConfigError
from deepspeed_tpu.models import llama

VOCAB = 256


def _cfg(stage=3, offload_param="cpu", offload_opt="cpu", remat=True,
         **over):
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": stage,
            "sub_group_size": 30_000,
            "offload_param": {"device": offload_param},
            "offload_optimizer": {"device": offload_opt},
        },
        "activation_checkpointing": {"enabled": remat},
        "mesh": {"data": 2, "fsdp": 4},
        "seed": 7,
    }
    cfg.update(over)
    return cfg


def _engine(cfg):
    reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    return engine


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}
            for _ in range(n)]


class TestParamOffload:
    def test_loss_parity_vs_dense_stage3(self):
        """Streaming the layer stack per scan slice tracks the plain stage-3
        engine's trajectory. Tolerance is bf16-loose: the baseline casts the
        whole stack to bf16 BEFORE the scan (layer-grad accumulation in bf16)
        while the streaming path casts per-slice inside it (accumulation in
        fp32) — the offloaded grads are the more precise of the two."""
        batches = _batches(4)
        base = [float(_engine(_cfg(offload_param="none", offload_opt="none",
                                   remat=True)).train_batch(b))
                for b in batches]
        eng = _engine(_cfg())
        assert eng.shard_ctx.param_stream is not None
        assert eng._param_offload_mask is not None
        # the stacked layer leaves are all marked for offload
        import jax

        assert all(jax.tree_util.tree_leaves(eng._param_offload_mask["layers"]))
        got = [float(eng.train_batch(b)) for b in batches]
        assert abs(got[0] - base[0]) < 1e-6  # identical first forward
        np.testing.assert_allclose(got, base, rtol=2e-2)

    def test_checkpoint_roundtrip(self, tmp_path):
        """Save under offload, load into a fresh offloaded engine, keep
        training: trajectories match an uninterrupted run."""
        batches = _batches(6, seed=3)
        ref = _engine(_cfg())
        ref_losses = [float(ref.train_batch(b)) for b in batches]

        eng = _engine(_cfg())
        for b in batches[:3]:
            eng.train_batch(b)
        eng.save_checkpoint(str(tmp_path), tag="s3")
        eng2 = _engine(_cfg())
        eng2.load_checkpoint(str(tmp_path), tag="s3")
        got = [float(eng2.train_batch(b)) for b in batches[3:]]
        np.testing.assert_allclose(got, ref_losses[3:], rtol=2e-4, atol=2e-5)


class TestParamOffloadConfigGuards:
    def test_requires_stage3(self):
        with pytest.raises((ConfigError, ValueError), match="stage"):
            _engine(_cfg(stage=2))

    def test_requires_remat(self):
        with pytest.raises((ConfigError, ValueError),
                           match="activation_checkpointing"):
            _engine(_cfg(remat=False))

    def test_requires_offloaded_optimizer(self):
        with pytest.raises((ConfigError, ValueError),
                           match="offload_optimizer"):
            _engine(_cfg(offload_opt="none"))

    def test_nvme_raises_loudly(self):
        """No silent no-op: the NVMe param tier is not implemented and must
        say so (the round-4 verdict's minimum bar)."""
        with pytest.raises((ConfigError, ValueError), match="nvme"):
            _engine(_cfg(offload_param="nvme"))
