"""ZenFlow split-update semantics (reference ``tests/unit/runtime/zenflow/``:
selective update correctness + engine cadence)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import Config
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime import zenflow

VOCAB = 256
BLOCK = 8


def test_select_topk_blocks():
    g = jnp.zeros((4 * BLOCK,)).at[2 * BLOCK:3 * BLOCK].set(5.0).at[0].set(1.0)
    idx = zenflow.select([g], ratio=0.5, block=BLOCK)[0]
    assert set(np.asarray(idx).tolist()) == {2, 0}


def test_hot_step_touches_only_hot_blocks():
    p = jnp.ones((3 * BLOCK,), jnp.float32)
    g = jnp.full((3 * BLOCK,), 0.1, jnp.float32)
    hot = zenflow.init_hot_state([jax.ShapeDtypeStruct(p.shape, p.dtype)],
                                 ratio=1 / 3, block=BLOCK)
    hot["leaves"][0]["idx"] = jnp.array([1], jnp.int32)
    acc = [jnp.zeros_like(g)]
    new_p, new_hot, new_acc = zenflow.hot_step(
        [p], hot, [g], acc, lr=0.1, finite=jnp.asarray(True),
        block=BLOCK, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0)
    moved = np.asarray(new_p[0] != p)
    assert moved[BLOCK:2 * BLOCK].all() and not moved[:BLOCK].any() \
        and not moved[2 * BLOCK:].any()
    a = np.asarray(new_acc[0])
    assert (a[BLOCK:2 * BLOCK] == 0).all()            # hot coords excluded
    np.testing.assert_allclose(a[:BLOCK], 0.1)        # cold coords accumulate
    assert np.asarray(new_hot["leaves"][0]["t"]).tolist() == [1]


def test_hot_step_overflow_is_a_noop():
    p = jnp.ones((2 * BLOCK,), jnp.float32)
    g = jnp.full((2 * BLOCK,), jnp.inf, jnp.float32)
    hot = zenflow.init_hot_state([jax.ShapeDtypeStruct(p.shape, p.dtype)],
                                 ratio=0.5, block=BLOCK)
    acc = [jnp.zeros_like(p)]
    new_p, new_hot, new_acc = zenflow.hot_step(
        [p], hot, [g], acc, lr=0.1, finite=jnp.asarray(False),
        block=BLOCK, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0)
    np.testing.assert_array_equal(np.asarray(new_p[0]), np.asarray(p))
    assert (np.asarray(new_acc[0]) == 0).all()
    assert np.asarray(new_hot["leaves"][0]["t"]).tolist() == [0]


def test_reselection_retains_overlapping_block_moments():
    hot = zenflow.init_hot_state(
        [jax.ShapeDtypeStruct((4 * BLOCK,), jnp.float32)], ratio=0.5, block=BLOCK)
    h = hot["leaves"][0]
    h["idx"] = jnp.array([3, 1], jnp.int32)
    h["m"] = jnp.stack([jnp.full((BLOCK,), 3.0), jnp.full((BLOCK,), 1.0)])
    h["v"] = h["m"] * 2
    h["t"] = jnp.array([7, 5], jnp.int32)
    out = zenflow.reset_moments(hot, [jnp.array([1, 2], jnp.int32)])["leaves"][0]
    # block 1 retained (m=1, t=5); block 2 fresh
    np.testing.assert_allclose(np.asarray(out["m"][0]), 1.0)
    np.testing.assert_allclose(np.asarray(out["v"][0]), 2.0)
    assert np.asarray(out["t"]).tolist() == [5, 0]
    assert (np.asarray(out["m"][1]) == 0).all()


def test_hot_k_uses_ceil():
    # 29 blocks at 5% -> ceil(1.45) = 2
    assert zenflow.hot_k(29 * BLOCK, 0.05, BLOCK) == 2


def test_restore_hot():
    old = jnp.zeros((2 * BLOCK,))
    new = jnp.ones((2 * BLOCK,))
    out = zenflow.restore_hot(old, new, jnp.array([0], jnp.int32), BLOCK)
    assert (np.asarray(out[:BLOCK]) == 0).all()
    assert (np.asarray(out[BLOCK:]) == 1).all()


def test_restore_hot_opt_state_undoes_moment_decay():
    import optax

    # two leaves, one hot block each; the cold walk saw zero grads at hot
    # blocks, decaying mu/nu there — the restore must undo exactly that
    old_mu = (jnp.full((2 * BLOCK,), 1.0), jnp.full((BLOCK,), 2.0))
    new_mu = (jnp.full((2 * BLOCK,), 0.9), jnp.full((BLOCK,), 1.8))
    old = optax.ScaleByAdamState(count=jnp.int32(3), mu=old_mu, nu=old_mu)
    new = optax.ScaleByAdamState(count=jnp.int32(4), mu=new_mu, nu=new_mu)
    hot_idx = (jnp.array([1], jnp.int32), jnp.array([0], jnp.int32))
    out = zenflow.restore_hot_opt_state(new, old, hot_idx, BLOCK)
    # leaf 0: block 1 hot -> old values; block 0 cold -> new values
    np.testing.assert_allclose(np.asarray(out.mu[0][:BLOCK]), 0.9)
    np.testing.assert_allclose(np.asarray(out.mu[0][BLOCK:]), 1.0)
    # leaf 1: its only block is hot -> fully restored
    np.testing.assert_allclose(np.asarray(out.nu[1]), 2.0)
    assert int(out.count) == 4  # scalar step counter untouched


def test_config_zero_zenflow_block_presence_enables():
    # reference semantics: a zenflow block under zero_optimization means ON
    # (zero/config.py:172 Optional[ZenFlowConfig]); enabled left unset must
    # not silently train dense
    cfg = Config.from_dict({
        "train_micro_batch_size_per_device": 1,
        "zero_optimization": {"stage": 2, "zenflow": {"topk_ratio": 0.1}},
    })
    assert cfg.zero_optimization.zenflow.enabled
    # an EMPTY block (all reference defaults) is also "present" => enabled
    cfg = Config.from_dict({
        "train_micro_batch_size_per_device": 1,
        "zero_optimization": {"stage": 2, "zenflow": {}},
    })
    assert cfg.zero_optimization.zenflow.enabled
    # an explicit enabled: false is honored
    cfg = Config.from_dict({
        "train_micro_batch_size_per_device": 1,
        "zero_optimization": {
            "stage": 2, "zenflow": {"enabled": False, "topk_ratio": 0.1}},
    })
    assert not cfg.zero_optimization.zenflow.enabled


def test_config_zenflow_accepts_auto_intervals():
    # reference ZenFlowConfig defaults select/update intervals to "auto"
    cfg = Config.from_dict({
        "train_micro_batch_size_per_device": 1,
        "zero_optimization": {"stage": 2, "zenflow": {
            "select_interval": "auto", "update_interval": "auto"}},
    })
    zf = cfg.zero_optimization.zenflow
    assert zf.enabled and zf.select_interval == 100 and zf.update_interval == 4


def test_config_top_level_zenflow_block():
    cfg = Config.from_dict({
        "train_micro_batch_size_per_device": 1,
        "zenflow": {"topk_ratio": 0.1, "update_interval": 3},
    })
    zf = cfg.zero_optimization.zenflow
    assert zf.enabled and zf.topk_ratio == 0.1 and zf.update_interval == 3


def test_config_zenflow_respects_legacy_zero_block():
    # hoisting zenflow must not create zero_optimization next to a legacy
    # 'zero' block (the deprecation migration would discard the user's zero)
    cfg = Config.from_dict({
        "train_micro_batch_size_per_device": 1,
        "zero": {"stage": 2, "offload_optimizer": {"device": "cpu"}},
        "zenflow": {"topk_ratio": 0.1},
    })
    assert cfg.zero_optimization.stage == 2
    assert cfg.zero_optimization.offload_optimizer.device == "cpu"
    assert cfg.zero_optimization.zenflow.enabled


def test_zenflow_requires_cpu_offload():
    reset_topology()
    with pytest.raises(ValueError, match="offload"):
        deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
            config={
                "train_micro_batch_size_per_device": 2,
                "zero_optimization": {"stage": 2,
                                      "zenflow": {"enabled": True}},
                "mesh": {"data": 8},
            },
        )


def _zf_engine(update_interval=3, warmup=2, ratio=0.25):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 2,
            "sub_group_size": 30_000,
            "offload_optimizer": {"device": "cpu"},
            "zenflow": {
                "enabled": True,
                "topk_ratio": ratio,
                "update_interval": update_interval,
                "select_interval": 4,
                "full_warm_up_rounds": warmup,
                "block": 64,
            },
        },
        "mesh": {"data": 2, "fsdp": 4},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    return engine


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}
            for _ in range(n)]


class TestZenFlowEngine:
    def test_trains_and_cold_cadence(self):
        engine = _zf_engine(update_interval=3, warmup=2)
        batch = _batches(1)[0]
        losses = [float(engine.train_batch(batch)) for _ in range(10)]
        assert all(np.isfinite(losses))
        assert np.mean(losses[-3:]) < np.mean(losses[:3])
        # cadence: warmup steps 0-1 dense (selection at step 1); hot steps
        # 2-9 with cold boundaries when the window fills (steps 4, 7) and a
        # flush at the step-9 re-selection, leaving 1 accumulated (step 9)
        assert engine._zf_selected
        assert engine._zf_n_acc == 1
        # params stay finite
        for leaf in jax.tree_util.tree_leaves(engine.params):
            assert bool(jnp.isfinite(leaf).all())

    def test_hot_state_is_small(self):
        engine = _zf_engine(ratio=0.25)
        [float(engine.train_batch(b)) for b in _batches(3)]
        total = sum(int(x.size) for x in jax.tree_util.tree_leaves(engine.params))
        hot_elems = zenflow.hot_state_elements(engine._zf_hot)
        # m+v for 25% of blocks ~ 0.5x model; block rounding on tiny leaves
        # inflates a little — must stay well under a full moment copy (2x)
        assert hot_elems < 1.0 * total

    def test_backward_path_rejected(self):
        engine = _zf_engine()
        with pytest.raises(NotImplementedError):
            engine.backward(_batches(1)[0])

    def test_load_checkpoint_resets_selective_state(self, tmp_path):
        engine = _zf_engine(update_interval=3, warmup=1)
        batch = _batches(1)[0]
        for _ in range(3):
            engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path / "ck"))
        for _ in range(2):  # leave a partially-filled cold window
            engine.train_batch(batch)
        assert engine._zf_n_acc > 0
        engine.load_checkpoint(str(tmp_path / "ck"))
        assert engine._zf_n_acc == 0 and engine._zf_acc is None
        assert not engine._zf_selected
        more = [float(engine.train_batch(batch)) for _ in range(3)]
        assert all(np.isfinite(more))
