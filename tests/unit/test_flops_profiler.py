"""flops_profiler: program_cost against a tiny jitted model, ProfileResult
fields, get_model_profile memoization, and the engine-facing FlopsProfiler
start/stop protocol (previously untested outside the engine path)."""

from types import SimpleNamespace

import jax.numpy as jnp
import pytest

from deepspeed_tpu.models import llama
from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    ProfileResult,
    get_model_profile,
    program_cost,
)


def test_program_cost_reports_flops_on_cpu():
    def fn(x):
        return x @ x

    cost = program_cost(fn, jnp.ones((16, 16), jnp.float32))
    # CPU XLA reports the cost model: a 16x16 matmul is 2*16^3 = 8192 flops
    # (plus epsilon for fusion overheads)
    assert cost.get("flops", 0.0) >= 2 * 16 ** 3


def _spec(vocab=128):
    return llama.build(llama.LlamaConfig.tiny(vocab))


def test_get_model_profile_fields():
    spec = _spec()
    prof = get_model_profile(spec, batch=2, seq=16, with_compiled=False)
    assert isinstance(prof, ProfileResult)
    assert prof.params == spec.num_params > 0
    assert prof.flops_fwd > 0.0
    assert prof.macs_fwd == pytest.approx(prof.flops_fwd / 2.0)
    assert set(prof.breakdown) == {"qkv+out", "attention", "mlp", "lm_head"}
    assert all(v > 0 for v in prof.breakdown.values())
    # analytic-only call: no compiled cost analysis
    assert prof.compiled == {}
    assert "fwd flops" in prof.format_profile()


def test_get_model_profile_compiled_cost():
    prof = get_model_profile(_spec(), batch=1, seq=8, with_compiled=True)
    # CPU backend reports the XLA cost model for the compiled forward
    assert prof.compiled.get("flops", 0.0) > 0.0


def test_get_model_profile_memoized():
    spec = _spec()
    a = get_model_profile(spec, batch=2, seq=16, with_compiled=False)
    b = get_model_profile(spec, batch=2, seq=16, with_compiled=False)
    assert a is b  # same spec + shape: cached object, no recompute
    c = get_model_profile(spec, batch=4, seq=16, with_compiled=False)
    assert c is not a  # shape participates in the key
    other = _spec()
    d = get_model_profile(other, batch=2, seq=16, with_compiled=False)
    assert d is not a  # spec identity participates in the key


def test_flops_profiler_start_stop_protocol():
    spec = _spec()
    engine = SimpleNamespace(
        model_spec=spec,
        config=SimpleNamespace(train_micro_batch_size_per_device=2,
                               sequence_length=16),
    )
    prof = FlopsProfiler(engine)
    assert prof.result is None
    prof.start_profile()
    assert prof.result is not None
    assert prof.result.flops_fwd > 0.0
    prof.stop_profile()  # reference-protocol no-op, must not clear the result
    assert prof.result.flops_fwd > 0.0
    prof.print_model_profile()  # formats without raising


def test_flops_profiler_falls_back_to_max_seq_len():
    spec = _spec()
    engine = SimpleNamespace(
        model_spec=spec,
        config=SimpleNamespace(train_micro_batch_size_per_device=1,
                               sequence_length=None),
    )
    prof = FlopsProfiler(engine)
    prof.print_model_profile()  # start_profile on demand via max_seq_len
    assert prof.result is not None and prof.result.flops_fwd > 0.0
