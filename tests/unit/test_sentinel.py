"""Self-healing training: device verdict, quarantine, policy ladder,
liveness, and the engine-level heal loop (docs/FAULT_TOLERANCE.md
"Training: self-healing")."""

import json
import os
import time
import tracemalloc

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import SentinelConfig
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime import sentinel
from deepspeed_tpu.runtime.dataloader import (CheckpointableLoader,
                                              RepeatingLoader)
from deepspeed_tpu.serving.faults import classify_transient, get_fault_injector

VOCAB = 97


def _vcfg(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("warmup_steps", 3)
    kw.setdefault("grad_window", 4)
    kw.setdefault("grad_quantile", 0.75)
    return SentinelConfig(**kw)


def _feed(st, cfg, n, loss=1.0, gnorm=1.0):
    """Push n accepted steps through the verdict; returns the state."""
    for i in range(n):
        st, anom, _, _ = sentinel.verdict(
            st, jnp.float32(loss + 0.01 * i), jnp.float32(gnorm),
            jnp.asarray(True), cfg)
        assert not bool(anom)
    return st


# ------------------------------------------------------------- device verdict
class TestVerdict:
    def test_warmup_gates_loss_spike(self):
        """Before warmup_steps accepted steps the loss gate is unarmed: a
        huge-but-finite first loss is ordinary early training, not anomaly."""
        cfg = _vcfg(warmup_steps=5)
        st = sentinel.init_state(cfg)
        st, anom, reason, _ = sentinel.verdict(
            st, jnp.float32(1e4), jnp.float32(1.0), jnp.asarray(True), cfg)
        assert not bool(anom) and int(reason) == 0
        assert int(st.seen) == 1  # accepted into the stats

    def test_nonfinite_flags_even_in_warmup(self):
        cfg = _vcfg(warmup_steps=100)
        st = sentinel.init_state(cfg)
        st, anom, reason, _ = sentinel.verdict(
            st, jnp.float32(1.0), jnp.float32(1.0), jnp.asarray(False), cfg)
        assert bool(anom)
        assert int(reason) & sentinel.REASON_NONFINITE
        _, anom2, reason2, _ = sentinel.verdict(
            st, jnp.float32(float("nan")), jnp.float32(1.0),
            jnp.asarray(True), cfg)
        assert bool(anom2) and int(reason2) & sentinel.REASON_NONFINITE

    def test_loss_spike_flagged_and_stats_not_poisoned(self):
        cfg = _vcfg()
        st = _feed(sentinel.init_state(cfg), cfg, 5)
        ema0, var0, seen0 = st.loss_ema, st.loss_var, int(st.seen)
        st, anom, reason, _ = sentinel.verdict(
            st, jnp.float32(100.0), jnp.float32(1.0), jnp.asarray(True), cfg)
        assert bool(anom)
        assert "loss-spike" in sentinel.reason_names(int(reason))
        # the spike must NOT be chased into the rolling stats — an ingested
        # spike would mask the next one
        assert float(st.loss_ema) == float(ema0)
        assert float(st.loss_var) == float(var0)
        assert int(st.seen) == seen0

    def test_gnorm_spike_flagged(self):
        cfg = _vcfg()
        st = _feed(sentinel.init_state(cfg), cfg, 5)
        _, anom, reason, _ = sentinel.verdict(
            st, jnp.float32(1.0), jnp.float32(500.0), jnp.asarray(True), cfg)
        assert bool(anom)
        assert "grad-spike" in sentinel.reason_names(int(reason))

    def test_streak_counts_and_resets_like_good_steps(self):
        """The streak mirrors precision.update_loss_scale's good_steps: one
        accepted step zeroes it, each skip increments it, and crossing
        max_consecutive_skips raises REASON_SKIP_STREAK."""
        cfg = _vcfg(max_consecutive_skips=2)
        st = _feed(sentinel.init_state(cfg), cfg, 5)
        st, _, reason, streak = sentinel.verdict(
            st, jnp.float32(1.0), jnp.float32(1.0), jnp.asarray(False), cfg)
        assert int(streak) == 1
        assert not int(reason) & sentinel.REASON_SKIP_STREAK
        st, _, reason, streak = sentinel.verdict(
            st, jnp.float32(1.0), jnp.float32(1.0), jnp.asarray(False), cfg)
        assert int(streak) == 2
        assert int(reason) & sentinel.REASON_SKIP_STREAK
        st, anom, _, streak = sentinel.verdict(
            st, jnp.float32(1.0), jnp.float32(1.0), jnp.asarray(True), cfg)
        assert not bool(anom) and int(streak) == 0


# ------------------------------------------------------------- fingerprinting
class TestFingerprint:
    def test_key_order_independent(self):
        a = {"x": np.arange(6, dtype=np.int32),
             "y": np.ones((2, 3), np.float32)}
        b = dict(reversed(list(a.items())))
        assert sentinel.batch_fingerprint(a) == sentinel.batch_fingerprint(b)

    def test_content_shape_dtype_sensitive(self):
        base = {"x": np.arange(6, dtype=np.int32)}
        fp = sentinel.batch_fingerprint(base)
        bumped = {"x": np.arange(6, dtype=np.int32)}
        bumped["x"][3] += 1
        assert sentinel.batch_fingerprint(bumped) != fp
        assert sentinel.batch_fingerprint(
            {"x": np.arange(6, dtype=np.int64)}) != fp
        assert sentinel.batch_fingerprint(
            {"x": np.arange(6, dtype=np.int32).reshape(2, 3)}) != fp

    def test_concat_resplit_round_trip(self):
        """The engine fingerprints GAS microbatches by reshaping the
        concatenated batch; that must reproduce the fingerprints of the
        original loader-delivered microbatches bit-for-bit."""
        rng = np.random.default_rng(0)
        micro = [{"input_ids": rng.integers(0, VOCAB, (4, 8), np.int32)}
                 for _ in range(3)]
        want = [sentinel.batch_fingerprint(m) for m in micro]
        cat = {"input_ids": np.concatenate([m["input_ids"] for m in micro])}
        got = []
        for i in range(3):
            v = cat["input_ids"]
            got.append(sentinel.batch_fingerprint(
                {"input_ids": v.reshape((3, v.shape[0] // 3) + v.shape[1:])[i]}))
        assert got == want


# ------------------------------------------------------- loaders + quarantine
def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (2, 4), np.int32)}
            for _ in range(n)]


class TestLoaderQuarantine:
    def test_repeating_loader_skips_and_counts_raw(self):
        data = _batches(4)
        dl = RepeatingLoader(data)
        bad = sentinel.batch_fingerprint(data[1])
        dl.quarantine([bad])
        first, second = next(dl), next(dl)
        np.testing.assert_array_equal(first["input_ids"],
                                      data[0]["input_ids"])
        np.testing.assert_array_equal(second["input_ids"],
                                      data[2]["input_ids"])  # 1 skipped
        assert dl.quarantined_skipped == 1
        # position counts RAW pulls (3: delivered 0, skipped 1, delivered 2)
        assert dl.state_dict()["pos"] == 3
        assert dl.state_dict()["quarantine"] == [bad]

    def test_repeating_loader_state_round_trip(self):
        data = _batches(5, seed=1)
        dl = RepeatingLoader(data)
        bad = sentinel.batch_fingerprint(data[2])
        dl.quarantine([bad])
        for _ in range(3):  # delivers 0, 1, 3 (2 skipped)
            next(dl)
        state = dl.state_dict()
        fresh = RepeatingLoader(_batches(5, seed=1))
        fresh.load_state_dict(state)
        np.testing.assert_array_equal(next(fresh)["input_ids"],
                                      data[4]["input_ids"])
        assert fresh.quarantined == [bad]  # unioned, never cleared

    def test_checkpointable_loader_state_round_trip(self):
        def factory(skip):
            def gen():
                i = skip
                while True:
                    r = np.random.default_rng(100 + i)
                    yield {"input_ids": r.integers(0, VOCAB, (2, 4), np.int32)}
                    i += 1
            return gen()

        dl = CheckpointableLoader(factory)
        bad = sentinel.batch_fingerprint(next(factory(1)))
        dl.quarantine([bad])
        got = [next(dl) for _ in range(2)]  # stream 0 and 2 (1 skipped)
        np.testing.assert_array_equal(got[1]["input_ids"],
                                      next(factory(2))["input_ids"])
        assert dl.batches_consumed == 3  # raw pulls, skip included
        fresh = CheckpointableLoader(factory)
        fresh.load_state_dict(dl.state_dict())
        np.testing.assert_array_equal(next(fresh)["input_ids"],
                                      next(factory(3))["input_ids"])
        assert fresh.quarantined == [bad]


# ------------------------------------------------------------- policy ladder
def _pcfg(tmp_path=None, **kw):
    kw.setdefault("enabled", True)
    kw.setdefault("window_steps", 10)
    if tmp_path is not None:
        kw.setdefault("state_dir", str(tmp_path / "state"))
    return SentinelConfig(**kw)


class TestPolicyLadder:
    def test_three_strikes_escalate(self, tmp_path):
        pol = sentinel.SentinelPolicy(_pcfg(tmp_path))
        assert pol.observe(sentinel.REASON_LOSS_SPIKE, ["aaa"],
                           latest_tag="global_step3") == "quarantine"
        assert pol.rollback_tag == "global_step3"  # pinned at strike 1
        assert pol.observe(sentinel.REASON_LOSS_SPIKE, ["bbb"],
                           latest_tag="global_step5") == "rollback"
        # the pin must NOT chase the newest checkpoint: global_step5 was
        # saved after the first anomaly skewed the batch stream
        assert pol.rollback_tag == "global_step3"
        assert pol.observe(sentinel.REASON_GRAD_SPIKE, []) == "halt"
        assert pol.quarantined == ["aaa", "bbb"]
        assert pol.anomalies == 3

    def test_reduce_lr_third_strike(self):
        pol = sentinel.SentinelPolicy(_pcfg(on_third_strike="reduce-lr"))
        pol.observe(1, [])
        pol.observe(1, [])
        assert pol.observe(1, []) == "reduce-lr"

    def test_rollback_rung_skippable(self):
        pol = sentinel.SentinelPolicy(_pcfg(rollback=False))
        assert pol.observe(1, []) == "quarantine"
        assert pol.observe(1, []) == "halt"  # rung 2 disabled -> escalate

    def test_window_expiry_resets_ladder(self):
        pol = sentinel.SentinelPolicy(_pcfg(window_steps=5))
        assert pol.observe(1, ["aaa"]) == "quarantine"
        for _ in range(10):  # accepted steps age the strike out
            pol.tick()
        assert pol.observe(1, ["bbb"]) == "quarantine"  # strike 1 again
        assert pol.strikes_in_window == 1
        assert pol.quarantined == ["aaa", "bbb"]  # quarantine is monotonic

    def test_wedge_budget(self):
        pol = sentinel.SentinelPolicy(_pcfg(max_wedges=2))
        assert pol.observe_wedge() == "rollback"
        assert pol.observe_wedge() == "halt"  # budget spent
        pol2 = sentinel.SentinelPolicy(_pcfg(max_wedges=3, rollback=False))
        assert pol2.observe_wedge() == "halt"  # no rollback rung -> halt

    def test_quarantine_persistence_and_torn_file(self, tmp_path):
        state = str(tmp_path / "state")
        cfg = _pcfg(state_dir=state)
        pol = sentinel.SentinelPolicy(cfg)
        pol.quarantine(["bbb", "aaa", "", "aaa"])  # empty/dup dropped
        assert sentinel.load_quarantine(state) == ["aaa", "bbb"]
        # a fresh policy (restarted worker) reloads the healing memory
        assert sentinel.SentinelPolicy(cfg).quarantined == ["aaa", "bbb"]
        # a torn file reads as empty rather than crashing the restart
        with open(sentinel.quarantine_path(state), "w") as f:
            f.write('["aaa", "bb')
        assert sentinel.load_quarantine(state) == []
        assert sentinel.SentinelPolicy(cfg).quarantined == []


# ------------------------------------------------------------------ liveness
class TestLiveness:
    def test_watched_call_passes_values_and_errors(self):
        assert sentinel.watched_call(lambda: 42, timeout_s=5.0) == 42
        with pytest.raises(KeyError):
            sentinel.watched_call(lambda: {}["missing"], timeout_s=5.0)

    def test_watched_call_wedge_is_transient(self):
        with pytest.raises(sentinel.TrainingWedgeError) as ei:
            sentinel.watched_call(lambda: time.sleep(5), timeout_s=0.05)
        # shared taxonomy with the serving dispatch fence: a wedge is
        # transient (recovery = rollback/restart), not a crash
        assert classify_transient(ei.value)

    def test_heartbeat_throttles(self, tmp_path):
        hb = sentinel.Heartbeat(str(tmp_path), rank=0, interval_s=60.0)
        assert hb.beat(1)
        assert not hb.beat(2)  # inside the throttle window
        payload = json.loads(open(hb.path).read())
        assert payload["step"] == 1 and payload["pid"] == os.getpid()
        hb2 = sentinel.Heartbeat(str(tmp_path), rank=0, interval_s=0.0)
        assert hb2.beat(3) and hb2.beat(4)  # interval 0 -> every step


# -------------------------------------------------------------- engine level
def _builder():
    return lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx)


def _config(sentinel_over=None, **over):
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": 8},
        "bf16": {"enabled": False},
        "seed": 7,
    }
    cfg.update(over)
    if sentinel_over is not None:
        sent = {"enabled": True, "warmup_steps": 3, "window_steps": 50}
        sent.update(sentinel_over)
        cfg["sentinel"] = sent
    return cfg


def _batch_for(i, batch=16, seq=16):
    rng = np.random.default_rng(1000 + i)
    return {"input_ids": rng.integers(0, VOCAB, (batch, seq), np.int32)}


def _stream_factory(skip):
    def gen():
        i = skip
        while True:
            yield _batch_for(i)
            i += 1
    return gen()


class TestEngineSentinel:
    def test_disabled_trajectory_identical(self):
        """sentinel.enabled=False must trace the exact pre-sentinel step
        program: bit-identical losses to a config with no sentinel block."""
        from deepspeed_tpu.comm.topology import reset_topology

        engine_a, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(), config=_config(), seed=11)
        base = [float(engine_a.train_batch(_batch_for(i))) for i in range(4)]
        reset_topology()
        engine_b, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(),
            config=_config(sentinel_over={"enabled": False}), seed=11)
        off = [float(engine_b.train_batch(_batch_for(i))) for i in range(4)]
        assert base == off

    def test_disabled_after_step_never_syncs_skip_flag(self):
        """Satellite pin: steady state (no monitor/telemetry) must not
        host-sync the skip flag in _after_step — bf16 AND fp16. A guard
        object that raises on bool() rides through the metrics dict."""

        class GuardScalar:
            def astype(self, dtype):
                return jnp.int32(0)

            def __bool__(self):
                raise AssertionError(
                    "_after_step host-synced the skip flag on the hot path")

        from deepspeed_tpu.comm.topology import reset_topology

        for precision_cfg in ({"bf16": {"enabled": True}},
                              {"fp16": {"enabled": True}}):
            reset_topology()
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=_builder(), config=_config(**precision_cfg), seed=11)
            engine.train_batch(_batch_for(0))
            engine._after_step({"skipped": GuardScalar()})  # must not raise

    def test_disabled_hot_path_allocates_nothing_from_sentinel(self):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(), config=_config(), seed=11)
        for i in range(2):  # warm the jit + host caches
            engine.train_batch(_batch_for(i))
        tracemalloc.start()
        try:
            engine.train_batch(_batch_for(2))
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        stats = snap.filter_traces(
            [tracemalloc.Filter(True, "*/runtime/sentinel.py")]).statistics(
                "lineno")
        assert not stats, stats

    def test_detects_spike_and_quarantines(self, tmp_path):
        """A loss-spike directive at the train.grads seam is flagged by the
        fused verdict; strike 1 quarantines the batch fingerprints and
        writes forensics."""
        report_dir = str(tmp_path / "reports")
        state_dir = str(tmp_path / "state")
        get_fault_injector().configure([
            {"point": "train.grads", "kind": "loss-spike",
             "after": 4, "times": 1}])
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(),
            config=_config(sentinel_over={"report_dir": report_dir,
                                          "state_dir": state_dir}),
            seed=11)
        for i in range(6):
            engine.train_batch(_batch_for(i))
        pol = engine._sentinel
        want_fp = sentinel.batch_fingerprint(_batch_for(4))
        assert pol.anomalies == 1
        assert pol.quarantined == [want_fp]
        assert sentinel.load_quarantine(state_dir) == [want_fp]
        reports = os.listdir(report_dir)
        assert any(r.startswith("sentinel_quarantine_") for r in reports)
        ctx = json.loads(open(os.path.join(report_dir, reports[0])).read())
        assert ctx["action"] == "quarantine"
        assert ctx["fingerprints"] == [want_fp]
        assert "loss-spike" in ctx["reason"]

    def test_rollback_replay_matches_clean_run(self, tmp_path):
        """The full heal: nan-grads (strike 1, quarantine + pin), poisoned
        batch (strike 2, rollback to the pinned tag + replay with the
        quarantine honored). The stitched trajectory must equal a clean
        sentinel-enabled run that never saw the quarantined batches."""
        from deepspeed_tpu.comm.topology import reset_topology

        total, save_every = 10, 3
        ckpt = str(tmp_path / "ckpt")
        poison_fp = sentinel.batch_fingerprint(_batch_for(6))
        get_fault_injector().configure([
            {"point": "train.grads", "kind": "nan-grads",
             "after": 3, "times": 1},
            {"point": "data.batch", "kind": "poison-batch",
             "request_id": poison_fp, "times": 1}])
        sent = {"report_dir": str(tmp_path / "reports"),
                "state_dir": str(tmp_path / "state"),
                "checkpoint_dir": ckpt}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(), config=_config(sentinel_over=sent), seed=11,
            training_data=CheckpointableLoader(_stream_factory))
        healed: dict[int, float] = {}
        rollbacks = 0
        while engine.global_steps < total:
            step = engine.global_steps
            loss = engine.train_batch()
            if engine.global_steps <= step:
                rollbacks += 1
                continue  # rolled back mid-call; the replay rewrites steps
            healed[step] = float(loss)
            if engine.global_steps % save_every == 0:
                engine.save_checkpoint(ckpt)
        assert rollbacks == 1
        assert engine.train_rollbacks == 1
        quarantined = set(engine._sentinel.quarantined)
        assert quarantined == {sentinel.batch_fingerprint(_batch_for(3)),
                               poison_fp}

        # clean reference: same stream, quarantine pre-seeded, no faults
        get_fault_injector().reset()
        reset_topology()
        ref_state = str(tmp_path / "ref_state")
        sentinel.save_quarantine(ref_state, sorted(quarantined))
        ref_sent = {"report_dir": str(tmp_path / "ref_reports"),
                    "state_dir": ref_state}
        ref, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(), config=_config(sentinel_over=ref_sent), seed=11,
            training_data=CheckpointableLoader(_stream_factory))
        ref._apply_quarantine_to_loader()
        clean = [float(ref.train_batch()) for _ in range(total)]
        assert set(healed) == set(range(total))
        np.testing.assert_allclose([healed[i] for i in range(total)], clean,
                                   rtol=1e-6, atol=0.0)

    def test_rollback_without_checkpoint_halts(self, tmp_path):
        """Strike 2 with no verified checkpoint anywhere: the ladder halts
        loudly with a forensics report instead of limping on."""
        report_dir = str(tmp_path / "reports")
        get_fault_injector().configure([
            {"point": "train.grads", "kind": "nan-grads",
             "after": 3, "times": 2}])
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(),
            config=_config(sentinel_over={"report_dir": report_dir}),
            seed=11)
        with pytest.raises(sentinel.DivergenceHaltError) as ei:
            for i in range(6):
                engine.train_batch(_batch_for(i))
        assert ei.value.report and os.path.exists(ei.value.report)
        report = json.loads(open(ei.value.report).read())
        assert report["type"] == "sentinel_report"
