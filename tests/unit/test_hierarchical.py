"""MiCS / ZeRO++ hpZ hierarchical partitioning: full-world optimizer/grad
sharding with fast-axis-only live params (reference ``runtime/zero/mics.py``,
``partition_parameters.py:1806`` secondary partition)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama

VOCAB = 256


def _engine(stage, hierarchical, mesh=None):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage,
                              "hierarchical_partitioning": hierarchical},
        "mesh": mesh or {"data": 2, "fsdp": 4},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    return engine


def _losses(engine, n=4):
    rng = np.random.default_rng(3)
    return [float(engine.train_batch(
        {"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}))
        for _ in range(n)]


@pytest.mark.parametrize("stage", [2, 3])
def test_loss_parity_vs_plain(stage):
    base = _losses(_engine(stage, False))
    hier = _losses(_engine(stage, True))
    np.testing.assert_allclose(hier, base, rtol=2e-4, atol=2e-5)


def test_layouts_and_memory():
    """Opt/grad state shards over data x fsdp (1/8 of each big leaf); live
    stage-3 params shard over fsdp only (hpZ secondary: gathers stay on the
    fast axis)."""
    engine = _engine(3, True)
    shard_spec = str(engine.plan.shard_specs["layers"]["wq"])
    live_spec = str(engine.plan.param_specs["layers"]["wq"])
    assert "data" in shard_spec and "fsdp" in shard_spec
    assert "fsdp" in live_spec and "data" not in live_spec

    wq = engine.params["layers"]["wq"]
    # live param: 8 devices, sharded 4-way over fsdp -> shard = 1/4 of leaf
    assert wq.addressable_shards[0].data.size == wq.size // 4
    # optimizer moment: sharded 8-way over data x fsdp
    mu = jax.tree_util.tree_leaves(engine.opt_state)
    big = max(mu, key=lambda x: x.size)
    assert big.addressable_shards[0].data.size == big.size // 8


def test_hpz_knob_translates(tmp_path):
    """Reference zero_hpz_partition_size configs map onto the feature."""
    from deepspeed_tpu.config.config import load_config

    cfg = load_config({
        "train_micro_batch_size_per_device": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "zero_hpz_partition_size": 4},
    })
    assert cfg.zero_optimization.hierarchical_partitioning is True
