"""Aux subsystems: monitor writers, flops profiler, launcher parsing, elasticity
(reference: ``tests/unit/monitor``, ``profiling``, ``launcher``, ``elasticity``)."""

import os

import numpy as np
import pytest

from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import MonitorConfig
from deepspeed_tpu.elasticity.elasticity import (
    compute_elastic_config,
    ensure_immutable_elastic_config,
    get_compatible_world_sizes,
)
from deepspeed_tpu.launcher import runner
from deepspeed_tpu.monitor.monitor import CSVMonitor, MonitorMaster


# ----------------------------------------------------------------- monitor
def test_csv_monitor_writes(tmp_path):
    mon = CSVMonitor({"output_path": str(tmp_path), "job_name": "job"})
    mon.write_events([("Train/Samples/train_loss", 1.5, 10),
                      ("Train/Samples/train_loss", 1.2, 20)])
    path = tmp_path / "job" / "Train_Samples_train_loss.csv"
    lines = path.read_text().strip().splitlines()
    assert lines[0].startswith("step")
    assert lines[1] == "10,1.5"
    assert lines[2] == "20,1.2"


def test_monitor_master_fanout(tmp_path):
    cfg = MonitorConfig(enabled=True,
                        csv_monitor={"enabled": True, "output_path": str(tmp_path)})
    master = MonitorMaster(cfg)
    assert master.enabled
    master.write_events([("x", 1.0, 1)])
    master.flush()
    assert (tmp_path / "dstpu" / "x.csv").exists()


def test_monitor_disabled_by_default():
    assert not MonitorMaster(MonitorConfig()).enabled


# ----------------------------------------------------------------- flops profiler
def test_flops_profiler_analytic_and_compiled():
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.profiling.flops_profiler import get_model_profile

    spec = llama.build(llama.LlamaConfig.tiny(256))
    prof = get_model_profile(spec, batch=2, seq=16)
    assert prof.params == spec.num_params
    assert prof.flops_fwd > 0
    assert set(prof.breakdown) == {"qkv+out", "attention", "mlp", "lm_head"}
    # XLA cost model should report flops in the same order of magnitude
    if "flops" in prof.compiled:
        assert prof.compiled["flops"] == pytest.approx(prof.flops_fwd, rel=1.0)


# ----------------------------------------------------------------- launcher
def test_hostfile_parse_and_filter(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("# cluster\nworker-0 slots=4\nworker-1 slots=4\nworker-2 slots=8\n")
    hosts = runner.fetch_hostfile(str(hf))
    assert hosts == {"worker-0": 4, "worker-1": 4, "worker-2": 8}
    sel = runner.filter_hosts(hosts, include="worker-0@worker-2")
    assert list(sel) == ["worker-0", "worker-2"]
    sel = runner.filter_hosts(hosts, exclude="worker-1")
    assert "worker-1" not in sel
    with pytest.raises(ValueError):
        runner.filter_hosts(hosts, include="nope")


def test_hostfile_duplicate_raises(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("a slots=1\na slots=2\n")
    with pytest.raises(ValueError):
        runner.fetch_hostfile(str(hf))


# ------------------------------------------------------- multinode runners
def test_ssh_runner_cmds():
    from deepspeed_tpu.launcher.multinode_runner import SSHRunner

    r = SSHRunner("train.py", ["--foo", "1"], hosts=["h0", "h1"],
                  coordinator="h0:29500", ssh_port=2222,
                  extra_env={"K": "v"})
    cmds = r.get_cmd()
    assert len(cmds) == 2
    assert cmds[0][:3] == ["ssh", "-p", "2222"]
    assert cmds[1][3] == "h1"
    assert "export DSTPU_PROCESS_ID=1;" in cmds[1][4]
    assert "export DSTPU_NUM_PROCESSES=2;" in cmds[0][4]
    assert "export K=v;" in cmds[0][4]
    assert "train.py --foo 1" in cmds[0][4]


def test_slurm_runner_cmd():
    from deepspeed_tpu.launcher.multinode_runner import SlurmRunner

    r = SlurmRunner("train.py", ["--n", "2"], num_nodes=4,
                    coordinator="n0:29500", nodelist="n0,n1,n2,n3",
                    partition="tpu", account="ml")
    (cmd,) = r.get_cmd()
    s = " ".join(cmd[:-1])
    assert cmd[0] == "srun"
    assert "--nodes 4" in s and "--ntasks 4" in s and "--ntasks-per-node 1" in s
    assert "--nodelist n0,n1,n2,n3" in s and "--partition tpu" in s
    assert "--account ml" in s
    # rank wiring resolves on the allocation, not at submit time
    assert "export DSTPU_PROCESS_ID=$SLURM_PROCID;" in cmd[-1]
    assert "export DSTPU_COORDINATOR=n0:29500;" in cmd[-1]
    assert "train.py --n 2" in cmd[-1]


def test_gcloud_runner_cmd():
    from deepspeed_tpu.launcher.multinode_runner import GcloudTPURunner

    r = GcloudTPURunner("train.py", [], tpu_name="pod-a", zone="us-east5-a",
                        project="proj")
    (cmd,) = r.get_cmd()
    s = " ".join(cmd)
    assert "gcloud compute tpus tpu-vm ssh pod-a" in s
    assert "--zone us-east5-a" in s and "--worker=all" in s
    assert "--project proj" in s
    # TPU runtime wires ranks itself: no DSTPU_* env injected
    assert "DSTPU_COORDINATOR" not in cmd[-1]


def test_gke_runner_manifest():
    from deepspeed_tpu.launcher.multinode_runner import GKERunner

    r = GKERunner("train.py", ["--x"], job_name="j1", num_nodes=8,
                  image="gcr.io/p/i:tag", tpu_topology="4x8",
                  accelerator="tpu-v5p-slice", extra_env={"A": "b"})
    m = r.get_manifest()
    # scalars are JSON-quoted (valid YAML for any value, incl. quotes)
    assert "kind: JobSet" in m and 'name: "j1"' in m
    assert "parallelism: 8" in m and "completions: 8" in m
    assert 'gke-tpu-topology: "4x8"' in m
    assert 'gke-tpu-accelerator: "tpu-v5p-slice"' in m
    assert "python train.py --x" in m
    assert 'name: "A"' in m and 'value: "b"' in m
    # chip count derived from topology: 4x8 = 32 chips over 8 nodes
    assert 'google.com/tpu: "4"' in m
    assert r.get_cmd() == [["kubectl", "apply", "-f", "-"]]
    # a value with quotes/newlines still yields parseable YAML scalars
    r2 = GKERunner("t.py", [], job_name="x", num_nodes=2, image="i",
                   tpu_topology="2x4",
                   extra_env={"B": 'he said "hi"\nline2'})
    m2 = r2.get_manifest()
    assert '"he said \\"hi\\"\\nline2"' in m2
    assert 'google.com/tpu: "4"' in m2  # 8 chips / 2 nodes


def test_cli_builds_slurm_runner(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("n0 slots=1\nn1 slots=1\n")
    import argparse

    args = argparse.Namespace(
        hostfile=str(hf), include="", exclude="", master_addr=None,
        master_port=29500, ssh_port=22, launcher="slurm", num_nodes=0,
        partition="", account="", tpu_name="", zone="", project="",
        image="", job_name="dstpu-job", tpu_topology="", accelerator="",
        script="t.py", script_args=[])
    r = runner.build_runner(args, {})
    assert r.name == "slurm" and r.num_nodes == 2
    assert r.coordinator == "n0:29500"
    # no hostfile and no master_addr: a per-task shell fallback cannot name
    # one common coordinator — must be a hard error
    args.hostfile = None
    args.num_nodes = 4
    with pytest.raises(ValueError, match="master_addr"):
        runner.build_runner(args, {})


# ----------------------------------------------------------------- elasticity
def test_compatible_world_sizes():
    # batch 64, micro in {2,4}: every w dividing 32 works
    valid = get_compatible_world_sizes(64, [2, 4], 1, 16)
    assert 8 in valid and 16 in valid and 5 not in valid


def test_compute_elastic_config():
    ec = compute_elastic_config(target_batch_size=64, micro_batches=[2, 4, 8],
                                max_world_size=8)
    assert ec.final_batch_size >= 32
    assert all(ec.final_batch_size % (ec.micro_batch_per_world[w] * w) == 0
               for w in ec.valid_world_sizes)


def test_elastic_immutable_guard():
    frozen = {"train_batch_size": 64}
    ensure_immutable_elastic_config({"train_batch_size": 64}, frozen)
    with pytest.raises(ConfigError):
        ensure_immutable_elastic_config({"train_batch_size": 32}, frozen)
