"""Aux subsystems: monitor writers, flops profiler, launcher parsing, elasticity
(reference: ``tests/unit/monitor``, ``profiling``, ``launcher``, ``elasticity``)."""

import os

import numpy as np
import pytest

from deepspeed_tpu.config.base import ConfigError
from deepspeed_tpu.config.config import MonitorConfig
from deepspeed_tpu.elasticity.elasticity import (
    compute_elastic_config,
    ensure_immutable_elastic_config,
    get_compatible_world_sizes,
)
from deepspeed_tpu.launcher import runner
from deepspeed_tpu.monitor.monitor import CSVMonitor, MonitorMaster


# ----------------------------------------------------------------- monitor
def test_csv_monitor_writes(tmp_path):
    mon = CSVMonitor({"output_path": str(tmp_path), "job_name": "job"})
    mon.write_events([("Train/Samples/train_loss", 1.5, 10),
                      ("Train/Samples/train_loss", 1.2, 20)])
    path = tmp_path / "job" / "Train_Samples_train_loss.csv"
    lines = path.read_text().strip().splitlines()
    assert lines[0].startswith("step")
    assert lines[1] == "10,1.5"
    assert lines[2] == "20,1.2"


def test_monitor_master_fanout(tmp_path):
    cfg = MonitorConfig(enabled=True,
                        csv_monitor={"enabled": True, "output_path": str(tmp_path)})
    master = MonitorMaster(cfg)
    assert master.enabled
    master.write_events([("x", 1.0, 1)])
    master.flush()
    assert (tmp_path / "dstpu" / "x.csv").exists()


def test_monitor_disabled_by_default():
    assert not MonitorMaster(MonitorConfig()).enabled


# ----------------------------------------------------------------- flops profiler
def test_flops_profiler_analytic_and_compiled():
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.profiling.flops_profiler import get_model_profile

    spec = llama.build(llama.LlamaConfig.tiny(256))
    prof = get_model_profile(spec, batch=2, seq=16)
    assert prof.params == spec.num_params
    assert prof.flops_fwd > 0
    assert set(prof.breakdown) == {"qkv+out", "attention", "mlp", "lm_head"}
    # XLA cost model should report flops in the same order of magnitude
    if "flops" in prof.compiled:
        assert prof.compiled["flops"] == pytest.approx(prof.flops_fwd, rel=1.0)


# ----------------------------------------------------------------- launcher
def test_hostfile_parse_and_filter(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("# cluster\nworker-0 slots=4\nworker-1 slots=4\nworker-2 slots=8\n")
    hosts = runner.fetch_hostfile(str(hf))
    assert hosts == {"worker-0": 4, "worker-1": 4, "worker-2": 8}
    sel = runner.filter_hosts(hosts, include="worker-0@worker-2")
    assert list(sel) == ["worker-0", "worker-2"]
    sel = runner.filter_hosts(hosts, exclude="worker-1")
    assert "worker-1" not in sel
    with pytest.raises(ValueError):
        runner.filter_hosts(hosts, include="nope")


def test_hostfile_duplicate_raises(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("a slots=1\na slots=2\n")
    with pytest.raises(ValueError):
        runner.fetch_hostfile(str(hf))


def test_node_cmd_env():
    cmd = runner.build_node_cmd("train.py", ["--foo", "1"], "h0:29500", 4, 2,
                                {"XLA_FLAGS": "--xla_dump_to=/tmp/d"})
    assert "export DSTPU_COORDINATOR=h0:29500;" in cmd
    assert "export DSTPU_NUM_PROCESSES=4;" in cmd
    assert "export DSTPU_PROCESS_ID=2;" in cmd
    assert "train.py --foo 1" in cmd


# ----------------------------------------------------------------- elasticity
def test_compatible_world_sizes():
    # batch 64, micro in {2,4}: every w dividing 32 works
    valid = get_compatible_world_sizes(64, [2, 4], 1, 16)
    assert 8 in valid and 16 in valid and 5 not in valid


def test_compute_elastic_config():
    ec = compute_elastic_config(target_batch_size=64, micro_batches=[2, 4, 8],
                                max_world_size=8)
    assert ec.final_batch_size >= 32
    assert all(ec.final_batch_size % (ec.micro_batch_per_world[w] * w) == 0
               for w in ec.valid_world_sizes)


def test_elastic_immutable_guard():
    frozen = {"train_batch_size": 64}
    ensure_immutable_elastic_config({"train_batch_size": 64}, frozen)
    with pytest.raises(ConfigError):
        ensure_immutable_elastic_config({"train_batch_size": 32}, frozen)
