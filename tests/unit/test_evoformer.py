"""Evoformer attention (reference ``DS4Sci_EvoformerAttention`` numerics,
``tests/benchmarks/DS4Sci_EvoformerAttention_bench.py`` shapes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.evoformer import evoformer_attention


def _inputs(b=1, n=3, r=16, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    q, k, v = (jnp.asarray(rng.normal(size=(b, n, r, h, d)).astype(np.float32))
               for _ in range(3))
    bias1 = jnp.asarray(rng.normal(size=(b, n, 1, 1, r)).astype(np.float32))
    bias2 = jnp.asarray(rng.normal(size=(b, 1, h, r, r)).astype(np.float32))
    return q, k, v, bias1, bias2


def _ref(q, k, v, bias1, bias2):
    d = q.shape[-1]
    s = jnp.einsum("bnrhd,bnshd->bnhrs", q / jnp.sqrt(jnp.float32(d)), k)
    if bias1 is not None:
        s = s + bias1
    if bias2 is not None:
        s = s + bias2
    return jnp.einsum("bnhrs,bnshd->bnrhd", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("use_b1,use_b2", [(True, True), (True, False),
                                           (False, False)])
def test_matches_dense_reference(use_b1, use_b2):
    q, k, v, b1, b2 = _inputs()
    biases = ([b1] if use_b1 else []) + ([b2] if use_b1 and use_b2 else [])
    out = evoformer_attention(q, k, v, biases)
    ref = _ref(q, k, v, b1 if use_b1 else None,
               b2 if (use_b1 and use_b2) else None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_chunked_matches_dense_and_grads():
    q, k, v, b1, b2 = _inputs(r=32)
    dense = evoformer_attention(q, k, v, [b1, b2])
    chunked = evoformer_attention(q, k, v, [b1, b2], chunk_size=8)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)

    g1 = jax.grad(lambda q: jnp.sum(
        jnp.square(evoformer_attention(q, k, v, [b1, b2]))))(q)
    g2 = jax.grad(lambda q: jnp.sum(
        jnp.square(evoformer_attention(q, k, v, [b1, b2], chunk_size=8))))(q)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1),
                               rtol=2e-4, atol=2e-4)


def test_bias_shape_validation():
    q, k, v, b1, b2 = _inputs()
    with pytest.raises(ValueError, match="bias1"):
        evoformer_attention(q, k, v, [b2])
    with pytest.raises(ValueError, match="bias2"):
        evoformer_attention(q, k, v, [b1, b1])
