"""Serving tier: protocol validation + SSE framing, router placement and
admission math (pure, no sockets), engine-level cancel/deadline KV release,
EngineLoop delivery/drain, and one end-to-end HTTP test (ephemeral port, SSE
stream, 429 + Retry-After under overload, SIGTERM-style graceful drain)."""

import http.client
import json
import os
import signal
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.elasticity.agent import PreemptionHandler
from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (
    CompletionRequest,
    EngineLoop,
    Overloaded,
    ProtocolError,
    ReplicaStats,
    RouterConfig,
    ServingFrontend,
    ReplicaRouter,
    decode_sse,
    encode_sse,
    plan_placement,
    sse_done,
)

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
RCFG = RaggedConfig(
    max_tokens_per_step=16, max_seqs=3, block_size=4,
    num_blocks=49, max_blocks_per_seq=16,
)


def _engine():
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), RCFG, dtype=jnp.float32, seed=0)


def _prompt(n, seed=0):
    return [int(t) for t in
            np.random.default_rng(seed).integers(0, CFG.vocab_size, n)]


# --------------------------------------------------------------- protocol
class TestProtocol:
    def test_validation_rejects_bad_requests(self):
        for body in (
            {},                                     # missing prompt
            {"prompt": []},                         # empty prompt
            {"prompt": [1, "x"]},                   # non-integer token
            {"prompt": [-1]},                       # negative id
            {"prompt": [1], "max_tokens": 0},
            {"prompt": [1], "temperature": -0.1},
            {"prompt": [1], "top_p": 0.0},
            {"prompt": [1], "deadline_s": -1},
            {"prompt": [1], "seed": -3},
            {"prompt": [1], "frequency_penalty": 1.0},  # unknown field
        ):
            with pytest.raises(ProtocolError):
                CompletionRequest.from_json(body)

    def test_from_json_defaults_and_budget(self):
        req = CompletionRequest.from_json(
            {"prompt": [3, 1, 4], "max_tokens": 5, "stream": True})
        assert req.stream and req.total_tokens == 8
        assert req.request_id.startswith("cmpl-")
        assert req.seed is None
        req = CompletionRequest.from_json({"prompt": [3], "seed": 7})
        assert req.seed == 7

    def test_priority_bounds_validated(self):
        from deepspeed_tpu.serving.protocol import PRIORITY_MAX, PRIORITY_MIN

        # the exact boundaries are accepted verbatim
        for edge in (PRIORITY_MIN, PRIORITY_MAX, 0):
            req = CompletionRequest.from_json(
                {"prompt": [1], "priority": edge})
            assert req.priority == edge
        # anything outside (or non-integer) is a protocol error, never a
        # silent clamp — the scheduler must see exactly what the client sent
        for bad in (PRIORITY_MIN - 1, PRIORITY_MAX + 1, 10**9, "high", 1.5):
            with pytest.raises(ProtocolError):
                CompletionRequest.from_json({"prompt": [1], "priority": bad})

    def test_tenant_and_sla_class_validated(self):
        req = CompletionRequest.from_json(
            {"prompt": [1], "tenant": "acme", "sla_class": "batch"})
        assert req.tenant == "acme" and req.sla_class == "batch"
        # defaults when absent from the wire
        req = CompletionRequest.from_json({"prompt": [1]})
        assert req.tenant == "default" and req.sla_class == "interactive"
        for body in (
            {"prompt": [1], "tenant": ""},
            {"prompt": [1], "tenant": "x" * 65},
            {"prompt": [1], "sla_class": "platinum"},
        ):
            with pytest.raises(ProtocolError):
                CompletionRequest.from_json(body)

    def test_sse_round_trip(self):
        frames = [{"id": "r1", "token": 17, "index": 0},
                  {"id": "r1", "token": 3, "index": 1},
                  {"choices": [{"finish_reason": "length"}]}]
        wire = b"".join(encode_sse(f) for f in frames) + sse_done()
        decoded = decode_sse(wire)
        assert decoded[:-1] == frames and decoded[-1] == "[DONE]"

    def test_sse_event_and_multiline_data(self):
        wire = encode_sse({"a": 1}, event="error")
        assert wire.startswith(b"event: error\n")
        # spec: multiple data: lines join with newlines
        assert decode_sse(b"data: [DO\ndata: NE]\n\n") == ["[DO\nNE]"]


# ----------------------------------------------------------------- router
def _stats(name="r0", alive=True, draining=False, queued=0, inflight=0,
           outstanding_tokens=0, free_blocks=48, pending_blocks=0,
           block_size=4, usable_blocks=48, max_request_blocks=16,
           max_request_tokens=128):
    return ReplicaStats(
        name=name, alive=alive, draining=draining, queued=queued,
        inflight=inflight, outstanding_tokens=outstanding_tokens,
        free_blocks=free_blocks, pending_blocks=pending_blocks,
        block_size=block_size, usable_blocks=usable_blocks,
        max_request_blocks=max_request_blocks,
        max_request_tokens=max_request_tokens)


class TestPlacement:
    def test_least_outstanding_tokens_wins(self):
        stats = [_stats("a", outstanding_tokens=100),
                 _stats("b", outstanding_tokens=10),
                 _stats("c", outstanding_tokens=50)]
        idx, verdict = plan_placement(stats, 20, RouterConfig())
        assert (idx, verdict) == (1, "admit")

    def test_kv_pressure_falls_back_to_queue(self):
        # needs ceil(20/4)=5 blocks; only 2 free after pending — queue it
        stats = [_stats(free_blocks=4, pending_blocks=2)]
        idx, verdict = plan_placement(stats, 20, RouterConfig())
        assert (idx, verdict) == (0, "queue")

    def test_admit_prefers_free_blocks_over_shorter_queue(self):
        stats = [_stats("full", outstanding_tokens=5, free_blocks=0),
                 _stats("free", outstanding_tokens=90, free_blocks=48)]
        idx, verdict = plan_placement(stats, 20, RouterConfig())
        assert (idx, verdict) == (1, "admit")

    def test_queue_bound_rejects(self):
        cfg = RouterConfig(max_queue_tokens=64)
        stats = [_stats(outstanding_tokens=60, free_blocks=0)]
        idx, verdict = plan_placement(stats, 20, cfg)
        assert (idx, verdict) == (None, "overloaded")

    def test_draining_and_dead_replicas_excluded(self):
        stats = [_stats(draining=True), _stats(alive=False)]
        assert plan_placement(stats, 4, RouterConfig()) == (None, "draining")


# ------------------------------------------------- engine cancel/deadline
class TestEngineAbort:
    def test_cancel_frees_kv_and_emits_span(self):
        telemetry.configure(enabled=True)
        eng = _engine()
        baseline = eng.allocator.free_blocks
        eng.put("keep", _prompt(5), max_new_tokens=6)
        eng.put("kill", _prompt(9, seed=1), max_new_tokens=6)
        for _ in range(3):  # admit + a few decode steps
            eng.step()
        assert eng.cancel("kill") is True
        assert eng.cancel("kill") is False  # idempotent: already aborted
        assert eng.cancel("nope") is False
        while eng.has_work:
            eng.step()
        assert eng.allocator.free_blocks == baseline
        assert eng._results["kill"].status == "cancelled"
        assert len(eng._results["keep"].generated) == 6
        assert telemetry.TELEMETRY.counter(
            "inference_requests_cancelled_total").value() == 1

    def test_cancel_queued_request_never_admits(self):
        eng = _engine()
        baseline = eng.allocator.free_blocks
        eng.put("q", _prompt(5), max_new_tokens=4)
        assert eng.cancel("q") is True
        out = eng.step()
        assert out == {} or "q" not in out
        assert not eng.has_work
        assert eng.allocator.free_blocks == baseline
        assert eng._results["q"].status == "cancelled"

    def test_deadline_expiry_times_out(self):
        telemetry.configure(enabled=True)
        eng = _engine()
        baseline = eng.allocator.free_blocks
        eng.put("slow", _prompt(5), max_new_tokens=8, deadline_s=0.01)
        eng.step()  # admit
        time.sleep(0.03)
        while eng.has_work:
            eng.step()
        assert eng._results["slow"].status == "timeout"
        assert len(eng._results["slow"].generated) < 8
        assert eng.allocator.free_blocks == baseline
        assert telemetry.TELEMETRY.counter(
            "inference_requests_timeout_total").value() == 1

    def test_deadline_validation(self):
        eng = _engine()
        with pytest.raises(ValueError):
            eng.put("bad", _prompt(4), deadline_s=0.0)


# -------------------------------------------------------------- EngineLoop
class TestEngineLoop:
    def test_stream_delivery_and_drain(self):
        loop = EngineLoop(_engine(), name="t0").start()
        try:
            streams = [loop.submit(CompletionRequest(
                prompt=_prompt(5 + 3 * i, seed=i), max_tokens=4))
                for i in range(3)]
            for s in streams:
                tokens, reason = s.collect(timeout=60)
                assert len(tokens) == 4 and reason == "length"
        finally:
            assert loop.close(timeout=60)
        assert not loop.stats().alive

    def test_cancel_mid_stream_frees_blocks(self):
        eng = _engine()
        baseline = eng.allocator.free_blocks
        loop = EngineLoop(eng, name="t1").start()
        try:
            s = loop.submit(CompletionRequest(prompt=_prompt(5),
                                              max_tokens=32))
            ev = s.events(timeout=60)
            kind, _ = next(ev)
            assert kind == "token"
            loop.cancel(s.request_id)
            kinds = [k for k, _ in ev]
            assert kinds[-1] == "done" and s.finish_reason == "cancelled"
        finally:
            loop.close(timeout=60)
        assert eng.allocator.free_blocks == baseline

    def test_submit_after_drain_rejected(self):
        loop = EngineLoop(_engine(), name="t2").start()
        loop.begin_drain()
        from deepspeed_tpu.serving import ReplicaDraining

        with pytest.raises(ReplicaDraining):
            loop.submit(CompletionRequest(prompt=[1], max_tokens=1))
        assert loop.join(timeout=60)


# ---------------------------------------------------------- end-to-end HTTP
@pytest.fixture
def server():
    eng = _engine()
    loop = EngineLoop(eng, name="e2e")
    router = ReplicaRouter([loop], RouterConfig(max_queue_tokens=96))
    frontend = ServingFrontend(router, port=0)
    loop.start()
    frontend.start()
    yield frontend, router, loop, eng
    frontend.router.begin_drain()
    loop.join(timeout=60)
    frontend.close()


def _post(frontend, body, timeout=120):
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=timeout)
    conn.request("POST", "/v1/completions", body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    return conn, conn.getresponse()


class TestEndToEnd:
    def test_sse_completion_stream(self, server):
        frontend, _, _, _ = server
        conn, resp = _post(frontend, {"prompt": _prompt(5), "max_tokens": 4,
                                      "stream": True})
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        frames = decode_sse(resp.read())
        conn.close()
        assert frames[-1] == "[DONE]"
        tokens = [f["token"] for f in frames if "token" in f]
        final = frames[-2]
        assert final["choices"][0]["finish_reason"] == "length"
        assert final["choices"][0]["tokens"] == tokens and len(tokens) == 4
        assert final["usage"]["prompt_tokens"] == 5

    def test_non_streaming_json(self, server):
        frontend, _, _, _ = server
        conn, resp = _post(frontend, {"prompt": _prompt(5), "max_tokens": 3})
        assert resp.status == 200
        body = json.loads(resp.read())
        conn.close()
        assert body["object"] == "completion"
        assert len(body["choices"][0]["tokens"]) == 3
        assert body["usage"]["total_tokens"] == 8

    def test_bad_request_400(self, server):
        frontend, _, _, _ = server
        conn, resp = _post(frontend, {"prompt": []})
        assert resp.status == 400
        assert "error" in json.loads(resp.read())
        conn.close()

    def test_out_of_range_priority_400(self, server):
        frontend, _, _, _ = server
        for bad in (1000, -1000, "urgent"):
            conn, resp = _post(frontend, {"prompt": _prompt(4),
                                          "priority": bad})
            assert resp.status == 400
            err = json.loads(resp.read())["error"]
            assert "priority" in err["message"]
            conn.close()

    def test_tenant_identity_echoed(self, server):
        frontend, _, _, _ = server
        conn, resp = _post(frontend, {"prompt": _prompt(5), "max_tokens": 2,
                                      "tenant": "acme", "sla_class": "batch"})
        assert resp.status == 200
        body = json.loads(resp.read())
        conn.close()
        assert body["tenant"] == "acme"
        assert body["sla_class"] == "batch"
        # invalid identity is a structured 400, not a silent default
        conn, resp = _post(frontend, {"prompt": _prompt(4),
                                      "sla_class": "platinum"})
        assert resp.status == 400
        assert "sla_class" in json.loads(resp.read())["error"]["message"]
        conn.close()

    def test_overload_429_retry_after(self):
        # cold loop (never started): submissions pile up in the inbox, so
        # admission state is deterministic — no race with the step loop
        eng = _engine()
        loop = EngineLoop(eng, name="cold")
        router = ReplicaRouter([loop], RouterConfig(
            max_queue_tokens=30, retry_after_s=2.5))
        frontend = ServingFrontend(router, port=0).start()
        try:
            router.submit(CompletionRequest(prompt=_prompt(20), max_tokens=10))
            conn, resp = _post(frontend, {"prompt": _prompt(20),
                                          "max_tokens": 10})
            assert resp.status == 429
            assert resp.getheader("Retry-After") == "2.5"
            assert "replicas past" in json.loads(resp.read())["error"]["message"]
            conn.close()
            # healthz agrees the server is saturated
            c2 = http.client.HTTPConnection(frontend.host, frontend.port)
            c2.request("GET", "/healthz")
            h = c2.getresponse()
            assert h.status == 200
            assert json.loads(h.read())["status"] == "overloaded"
            c2.close()
        finally:
            frontend.close()

    def test_oversized_request_400_not_429(self, server):
        frontend, _, _, _ = server
        conn, resp = _post(frontend, {"prompt": _prompt(100),
                                      "max_tokens": 100})
        assert resp.status == 400  # can never fit -> client error, not retry
        conn.close()

    def test_metrics_endpoint(self, server):
        frontend, _, _, _ = server
        telemetry.configure(enabled=True)
        conn, resp = _post(frontend, {"prompt": _prompt(5), "max_tokens": 2})
        resp.read()
        conn.close()
        c = http.client.HTTPConnection(frontend.host, frontend.port)
        c.request("GET", "/metrics")
        m = c.getresponse()
        assert m.status == 200
        assert m.getheader("Content-Type").startswith("text/plain")
        page = m.read().decode()
        c.close()
        assert "serving_requests_admitted_total 1" in page
        assert "serving_queue_depth" in page
        assert "serving_draining 0" in page

    def test_sigterm_drain_finishes_inflight(self):
        eng = _engine()
        loop = EngineLoop(eng, name="drain")
        router = ReplicaRouter([loop], RouterConfig(max_queue_tokens=96))
        frontend = ServingFrontend(router, port=0)
        loop.start()
        frontend.start()
        handler = PreemptionHandler(signals=(signal.SIGTERM,))
        frontend.install_preemption_handler(handler)
        try:
            results = {}

            def run_one(i):
                conn, resp = _post(frontend, {
                    "prompt": _prompt(5 + i, seed=i), "max_tokens": 6,
                    "stream": True})
                results[i] = decode_sse(resp.read())
                conn.close()

            threads = [threading.Thread(target=run_one, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            while not eng.has_work and any(t.is_alive() for t in threads):
                time.sleep(0.005)  # wait until work is genuinely inflight
            os.kill(os.getpid(), signal.SIGTERM)
            assert handler.should_stop
            assert router.state() == "draining"
            # new work is refused while draining (healthz -> 503)
            c = http.client.HTTPConnection(frontend.host, frontend.port)
            c.request("GET", "/healthz")
            assert c.getresponse().status == 503
            c.close()
            conn, resp = _post(frontend, {"prompt": _prompt(4),
                                          "max_tokens": 2})
            assert resp.status == 503
            conn.close()
            # ... but inflight requests run to completion
            for t in threads:
                t.join(timeout=120)
            assert loop.join(timeout=60)
            for i in range(2):
                final = results[i][-2]
                assert final["choices"][0]["finish_reason"] == "length"
                assert len(final["choices"][0]["tokens"]) == 6
            assert eng.allocator.free_blocks == RCFG.num_blocks - 1
        finally:
            handler.restore()
            frontend.close()
