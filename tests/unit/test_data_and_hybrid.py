"""Curriculum scheduler, random-LTD, hybrid engine, tensor-fragment APIs,
zero_to_fp32 conversion."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler,
    apply_seqlen_curriculum,
    random_ltd_drop,
)

VOCAB = 256


def test_curriculum_linear():
    s = CurriculumScheduler(min_difficulty=64, max_difficulty=512,
                            total_curriculum_step=100, difficulty_step=64)
    assert s.get_difficulty(0) == 64
    assert s.get_difficulty(50) == 256  # 64 + 0.5*448 = 288 -> floor to 256
    assert s.get_difficulty(100) == 512
    assert s.get_difficulty(10_000) == 512


def test_curriculum_root_and_discrete():
    root = CurriculumScheduler(min_difficulty=0, max_difficulty=100,
                               total_curriculum_step=100, difficulty_step=1,
                               schedule_type="fixed_root", root_degree=2)
    assert root.get_difficulty(25) == 50  # sqrt(0.25) = 0.5
    disc = CurriculumScheduler(min_difficulty=1, max_difficulty=3,
                               schedule_type="fixed_discrete",
                               discrete_difficulties=[64, 128, 256],
                               discrete_max_steps=[10, 20, 30])
    assert disc.get_difficulty(5) == 64
    assert disc.get_difficulty(15) == 128
    assert disc.get_difficulty(99) == 256


def test_seqlen_curriculum_truncates():
    b = {"input_ids": np.arange(64).reshape(2, 32), "weight": np.ones(2)}
    out = apply_seqlen_curriculum(b, 8)
    assert out["input_ids"].shape == (2, 8)
    assert out["weight"].shape == (2,)


def test_random_ltd_alignment():
    rng = np.random.default_rng(0)
    ids = np.arange(64).reshape(2, 32)
    batch = {"input_ids": ids, "labels": ids * 10}
    out = random_ltd_drop(batch, keep_ratio=0.5, rng=rng)
    assert out["input_ids"].shape == (2, 16)
    np.testing.assert_array_equal(out["labels"], out["input_ids"] * 10)  # aligned
    assert out["input_ids"][0, 0] == 0  # first token protected


def _make_hybrid():
    reset_topology()
    from deepspeed_tpu.config.config import load_config
    from deepspeed_tpu.comm.comm import init_distributed
    from deepspeed_tpu.runtime.hybrid_engine import HybridEngine

    cfg = load_config({
        "train_micro_batch_size_per_device": 2,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"data": 1, "fsdp": 8},
    })
    topo = init_distributed(cfg.mesh)
    cfg.resolve_batch_sizes(topo.dp_world_size)
    import jax.numpy as jnp

    return HybridEngine(
        lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        cfg, topo, inference_dtype=jnp.float32,
    )


def test_hybrid_train_and_generate():
    engine = _make_hybrid()
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, VOCAB, (engine.train_batch_size, 16),
                                       dtype=np.int32)}
    l0 = float(engine.train_batch(batch))
    out = engine.generate(np.zeros((2, 4), np.int32), max_new_tokens=4)
    assert out.shape == (2, 8)
    l1 = float(engine.train_batch(batch))
    assert l1 < l0  # generation didn't corrupt training state
    out2 = engine.generate(np.zeros((2, 4), np.int32), max_new_tokens=4)
    # weights changed between rollouts -> generation may differ; shape stable
    assert out2.shape == (2, 8)


def test_hybrid_eval_cast_reused_within_step():
    """The eval-dtype cast happens once per training step (the reference's
    one-time container build), not once per generate call."""
    engine = _make_hybrid()
    p1 = engine.eval_params
    engine.generate(np.zeros((2, 4), np.int32), max_new_tokens=2)
    assert engine.eval_params is p1  # same object across rollout calls
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, VOCAB, (engine.train_batch_size, 16),
                                       dtype=np.int32)}
    engine.train_batch(batch)
    assert engine.eval_params is not p1  # new weights -> fresh cast


def test_hybrid_kv_persistence_matches_oneshot():
    """prefill + repeated decode_more must produce exactly the one-shot
    greedy generation — the KV carried across calls is the same cache."""
    engine = _make_hybrid()
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, VOCAB, (2, 6), dtype=np.int32)
    oneshot = engine.generate(prompt, max_new_tokens=6)

    state = engine.prefill(prompt, max_len=16)
    state = engine.decode_more(state, 2)
    state = engine.decode_more(state, 4)
    np.testing.assert_array_equal(state.tokens, oneshot)
    assert state.pos == 12

    with pytest.raises(ValueError, match="max_len"):
        engine.decode_more(state, 10)


def test_hybrid_rollout_batching_and_logprobs():
    """generate_rollouts covers a mixed-length prompt set with bucketed
    batches; logprobs are the sampled tokens' true log-probabilities
    (greedy: argmax => logprob is the max-entry logprob, finite, <= 0)."""
    engine = _make_hybrid()
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, VOCAB, (L,), dtype=np.int32)
               for L in (3, 7, 7, 5, 3, 9)]
    rolls = engine.generate_rollouts(prompts, rollout_batch_size=2,
                                     max_new_tokens=4, temperature=0.0,
                                     seed=0)
    assert len(rolls) == 6
    for r, p in zip(rolls, prompts):
        np.testing.assert_array_equal(r["prompt"], p)
        assert r["tokens"].shape == (4,)
        assert r["logprobs"].shape == (4,)
        assert np.all(np.isfinite(r["logprobs"])) and np.all(r["logprobs"] <= 0)
        np.testing.assert_array_equal(r["full"], np.concatenate([p, r["tokens"]]))


def test_hybrid_ppo_shaped_loop():
    """Miniature RLHF loop (rejection-sampling flavor): generate rollouts →
    reward → train on the best half → generate again. Training loss must
    descend and generation must stay shape-coherent on the updated weights."""
    engine = _make_hybrid()
    rng = np.random.default_rng(3)
    target = 7  # reward: occurrences of a target token in the continuation
    losses = []
    for it in range(3):
        prompts = [rng.integers(1, VOCAB, (6,), dtype=np.int32)
                   for _ in range(8)]
        rolls = engine.generate_rollouts(prompts, rollout_batch_size=4,
                                         max_new_tokens=6, temperature=1.0,
                                         seed=it)
        scored = sorted(rolls, key=lambda r: -int(np.sum(r["tokens"] == target)))
        best = scored[:4]
        width = max(len(r["full"]) for r in best)
        batch = np.zeros((engine.train_batch_size, width), np.int32)
        for j in range(engine.train_batch_size):
            seq = best[j % len(best)]["full"]
            batch[j, :len(seq)] = seq
        losses.append(float(engine.train_batch({"input_ids": batch})))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses  # learns the selected rollouts
    out = engine.generate(np.zeros((2, 4), np.int32), max_new_tokens=3)
    assert out.shape == (2, 7)


def test_tensor_fragment_apis():
    from deepspeed_tpu.utils import tensor_fragment as tf

    engine = _make_hybrid()
    names = tf.list_param_names(engine)
    assert "embed" in names and "layers/wq" in names
    w = tf.safe_get_full_fp32_param(engine, "layers/wq")
    assert w.shape == (2, 64, 64)
    tf.safe_set_full_fp32_param(engine, "layers/wq", np.zeros_like(w))
    assert np.abs(tf.safe_get_full_fp32_param(engine, "layers/wq")).max() == 0
    mu = tf.safe_get_full_optimizer_state(engine, "layers/wq", "exp_avg")
    assert mu.shape == w.shape


def test_zero_to_fp32(tmp_path):
    from deepspeed_tpu.checkpoint.zero_to_fp32 import (
        convert_checkpoint_to_fp32_state_file,
        get_fp32_state_dict_from_checkpoint,
    )

    engine = _make_hybrid()
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    state = get_fp32_state_dict_from_checkpoint(str(tmp_path / "ckpt"))
    assert any("wq" in k for k in state)
    out = tmp_path / "consolidated.npz"
    convert_checkpoint_to_fp32_state_file(str(tmp_path / "ckpt"), str(out))
    assert out.exists()
    loaded = np.load(out)
    total = sum(loaded[k].size for k in loaded.files)
    assert total == engine.model_spec.num_params
