"""Model correctness: shapes, causality, loss decreases under SGD, logical-axis
tree congruence (reference test style: ``tests/unit/simple_model.py`` fixtures +
train-and-assert-loss-decreases)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import gpt2, llama
from deepspeed_tpu.models.api import causal_lm_loss, count_params


@pytest.fixture(params=["llama", "gpt2"])
def model_spec(request):
    if request.param == "llama":
        return llama.build(llama.LlamaConfig.tiny())
    return gpt2.build(gpt2.GPT2Config.tiny())


def test_forward_shape(model_spec):
    params = model_spec.init_fn(jax.random.PRNGKey(0))
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = model_spec.forward_fn(params, ids)
    assert logits.shape == (2, 16, model_spec.config.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_num_params_matches_tree(model_spec):
    params = model_spec.init_fn(jax.random.PRNGKey(0))
    assert count_params(params) == model_spec.num_params


def test_logical_axes_congruent(model_spec):
    params = model_spec.init_fn(jax.random.PRNGKey(0))
    axes = model_spec.param_logical_axes
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_a = jax.tree_util.tree_leaves_with_path(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    key = lambda item: jax.tree_util.keystr(item[0])
    for (pp, leaf), (pa, ax) in zip(sorted(flat_p, key=key), sorted(flat_a, key=key)):
        assert jax.tree_util.keystr(pp) == jax.tree_util.keystr(pa)
        assert len(ax) == leaf.ndim, f"{jax.tree_util.keystr(pp)}: {ax} vs {leaf.shape}"


def test_causality(model_spec):
    """Changing a future token must not affect past logits."""
    params = model_spec.init_fn(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 250)
    logits_a = model_spec.forward_fn(params, ids)
    ids_b = ids.at[0, 8].set((ids[0, 8] + 1) % 250)
    logits_b = model_spec.forward_fn(params, ids_b)
    np.testing.assert_allclose(np.asarray(logits_a[0, :8]), np.asarray(logits_b[0, :8]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(logits_a[0, 8:]), np.asarray(logits_b[0, 8:]))


def test_loss_decreases(model_spec):
    params = model_spec.init_fn(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 250)
    batch = {"input_ids": ids}

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(model_spec.loss_fn)(p, batch)
        return loss, jax.tree_util.tree_map(lambda x, gx: x - 0.05 * gx, p, g)

    losses = []
    for _ in range(8):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_remat_matches_no_remat():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 250)
    a = llama.forward(cfg, params, ids, remat=False)
    b = llama.forward(cfg, params, ids, remat=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_gqa_kv_heads():
    cfg = llama.LlamaConfig.tiny()  # 4 q heads, 2 kv heads
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    assert params["layers"]["wk"].shape == (cfg.num_layers, cfg.hidden_size, 2 * cfg.hd)
    assert params["layers"]["wq"].shape == (cfg.num_layers, cfg.hidden_size, 4 * cfg.hd)


def test_causal_lm_loss_masking():
    logits = jnp.zeros((1, 4, 10))
    labels = jnp.array([[1, -100, 2, -100]])
    loss = causal_lm_loss(logits, None, labels=labels)
    # uniform logits -> loss = log(10) over the 2 unmasked positions
    assert float(loss) == pytest.approx(np.log(10), rel=1e-5)


def test_tied_embeddings():
    cfg = llama.LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                            num_layers=1, num_heads=2, num_kv_heads=2, tie_embeddings=True)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    assert "lm_head" not in params
    logits = llama.forward(cfg, params, jnp.zeros((1, 8), jnp.int32))
    assert logits.shape == (1, 8, 128)
    assert llama.num_params(cfg) == count_params(params)
