"""Disaggregated prefill/decode serving (serving/cluster.py).

The contract under test: a request whose prompt runs on a dedicated
prefill replica and whose decode resumes on a different replica via the
KV-handoff record generates EXACTLY the tokens a single engine would —
greedy and sampled-with-fixed-seed, in every dispatch mode. Plus the
cluster-wide prefix index (a replica that never saw a prompt can serve
its cached prefix after a block transfer), the role-aware placement
invariants (decode traffic never lands on a prefill replica), handoff
failover (prefill death mid-handoff, decode import rejection), the
stale-probe re-validation at admission, and the SLO-burn decode
autoscaler policy.
"""

import http.client
import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (
    ClusterConfig,
    ClusterPrefixIndex,
    CompletionRequest,
    DecodeAutoscaler,
    EngineLoop,
    ReplicaRouter,
    ReplicaStats,
    RouterConfig,
    ServingCluster,
    build_cluster_server,
    plan_placement,
    transfer_beats_prefill,
)
from deepspeed_tpu.serving.faults import POINT_LOOP, get_fault_injector

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)

BS = 4  # block size used throughout — prompts below are built around it


def _engine(cache=False, params=None, **over):
    kw = dict(max_tokens_per_step=16, max_seqs=3, block_size=BS,
              num_blocks=49, max_blocks_per_seq=16,
              enable_prefix_cache=cache)
    kw.update(over)
    return RaggedInferenceEngine(
        model=lambda ctx: llama.build(CFG, ctx=ctx),
        ragged_config=RaggedConfig(**kw), dtype=jnp.float32, seed=0,
        params=params)


# the four dispatch modes: plain SplitFuse, tiled prefill, decode run-ahead,
# fused mixed pipeline
MODES = {
    "plain": {},
    "tiled": {"prefill_tile": 8},
    "run_ahead": {"decode_run_ahead": 4},
    "fused": {"fused_chunk": 4, "pipeline_depth": 2},
}

SHARED = [11, 7, 3, 5, 2, 13, 17, 19]          # two full blocks of 4
PROMPT_A = SHARED + [23, 29, 31]
PROMPT_B = SHARED + [37, 41]
SAMPLED = dict(temperature=0.9, top_k=20, seed=123)
MAX_NEW = 6


def _run(eng, uid):
    deadline = time.perf_counter() + 120
    while uid not in eng.finished_uids:
        assert time.perf_counter() < deadline, "engine did not finish"
        eng.step()
    return list(eng._results[uid].generated)


@pytest.fixture(scope="module")
def ref_tokens():
    """Single-engine reference generations every split run must match."""
    eng = _engine()
    eng.put("ga", PROMPT_A, max_new_tokens=MAX_NEW)
    eng.put("sa", PROMPT_A, max_new_tokens=MAX_NEW, **SAMPLED)
    eng.put("gb", PROMPT_B, max_new_tokens=MAX_NEW)
    out = eng.generate_all()
    return {k: list(v) for k, v in out.items()}


# ----------------------------------------------------- transfer cost model
class TestTransferCostModel:
    def test_fast_link_small_kv_prefers_transfer(self):
        cfg = ClusterConfig(transfer_gbps=100.0, prefill_tokens_per_s=1000.0)
        assert transfer_beats_prefill(64, bytes_per_token=1024, cfg=cfg)

    def test_slow_link_fat_kv_prefers_prefill(self):
        cfg = ClusterConfig(transfer_gbps=0.001,
                            prefill_tokens_per_s=1_000_000.0)
        assert not transfer_beats_prefill(64, bytes_per_token=1 << 20,
                                          cfg=cfg)

    def test_zero_length_prefix_never_transfers(self):
        cfg = ClusterConfig(transfer_gbps=100.0, prefill_tokens_per_s=1000.0)
        assert not transfer_beats_prefill(0, bytes_per_token=1024, cfg=cfg)
        assert not transfer_beats_prefill(-3, bytes_per_token=1024, cfg=cfg)

    def test_exact_cost_tie_prefers_prefill(self):
        # wire: 125_000 B/token * 8 b/B / 1 Gb/s = 1 ms/token;
        # prefill: 1000 tok/s = 1 ms/token — a dead tie must NOT transfer
        # (strict <: the local prefill avoids the channel's failure modes)
        cfg = ClusterConfig(transfer_gbps=1.0, prefill_tokens_per_s=1000.0)
        assert not transfer_beats_prefill(64, bytes_per_token=125_000,
                                          cfg=cfg)
        # one byte under the tie flips it
        assert transfer_beats_prefill(64, bytes_per_token=124_999, cfg=cfg)

    def test_unknown_bandwidth_is_conservative(self):
        # an unreported (-1) bandwidth or prefill rate would go negative in
        # the divisor and claim a free wire — both must mean "no transfer"
        cfg = ClusterConfig(transfer_gbps=-1.0, prefill_tokens_per_s=1000.0)
        assert not transfer_beats_prefill(64, bytes_per_token=16, cfg=cfg)
        cfg = ClusterConfig(transfer_gbps=100.0, prefill_tokens_per_s=-1.0)
        assert not transfer_beats_prefill(64, bytes_per_token=16, cfg=cfg)


# ----------------------------------------------------- cluster prefix index
def _chain(tokens):
    """Hash-chain keys for full blocks of ``tokens`` — the allocator's
    exact keying: (parent_key, tuple(block_tokens))."""
    keys, key = [], None
    for i in range(len(tokens) // BS):
        key = (key, tuple(tokens[i * BS:(i + 1) * BS]))
        keys.append(key)
    return keys


class TestClusterPrefixIndex:
    def test_best_holder_longest_contiguous_chain(self):
        idx = ClusterPrefixIndex()
        k1, k2 = _chain(SHARED)
        idx.publish("A", k1)
        idx.publish("A", k2)
        idx.publish("B", k1)
        prompt = SHARED + [1]  # 9 tokens: both blocks eligible
        assert idx.best_holder(prompt, BS) == (8, "A")
        # coverage must be on a SINGLE replica: excluding A falls back to
        # B's one-block chain, not a two-replica stitch
        assert idx.best_holder(prompt, BS,
                               exclude=frozenset({"A"})) == (4, "B")
        assert idx.hits == 2

    def test_missing_root_is_a_miss(self):
        idx = ClusterPrefixIndex()
        _, k2 = _chain(SHARED)
        idx.publish("A", k2)  # link without its root: unusable for a splice
        assert idx.best_holder(SHARED + [1], BS) == (0, None)
        assert idx.misses == 1

    def test_match_capped_one_block_short_of_prompt(self):
        idx = ClusterPrefixIndex()
        for k in _chain(SHARED):
            idx.publish("A", k)
        # 8-token prompt: only (8-1)//4 = 1 block may splice — a full
        # splice must still leave a real first-token forward
        assert idx.best_holder(SHARED, BS) == (4, "A")

    def test_evict_and_drop_replica_invalidate(self):
        idx = ClusterPrefixIndex()
        k1, k2 = _chain(SHARED)
        for name in ("A", "B"):
            idx.publish(name, k1)
            idx.publish(name, k2)
        idx.evict("A", k2)
        assert idx.best_holder(SHARED + [1], BS) == (8, "B")
        assert idx.drop_replica("B") == 2
        assert idx.best_holder(SHARED + [1], BS) == (4, "A")
        assert idx.invalidations == 3
        assert idx.stats()["entries"] == 1

    def test_listener_bridges_publish_evict_reset(self):
        idx = ClusterPrefixIndex()
        lst = idx.listener_for("r0")
        k1, k2 = _chain(SHARED)
        lst.on_publish(k1)
        lst.on_publish(k2)
        assert idx.best_holder(SHARED + [1], BS) == (8, "r0")
        lst.on_evict(k2)
        assert idx.best_holder(SHARED + [1], BS) == (4, "r0")
        lst.on_reset()
        assert idx.stats()["entries"] == 0


class TestTierAwareIndex:
    """Demotion keeps the holder (the replica can restore from its tiers)
    but tags the entry so placement ties prefer blocks still in HBM."""

    def test_demote_keeps_holder_routable(self):
        idx = ClusterPrefixIndex()
        k1, k2 = _chain(SHARED)
        lst = idx.listener_for("A")
        lst.on_publish(k1)
        lst.on_publish(k2)
        lst.on_demote(k2)
        # still full coverage: a request routed to A restores k2 at
        # admission — unlike on_evict, which would cap the match at 4
        assert idx.best_holder(SHARED + [1], BS) == (8, "A")
        s = idx.stats()
        assert s["demoted_entries"] == 1 and s["demotions"] == 1
        assert s["invalidations"] == 0

    def test_tie_prefers_hbm_holder(self):
        idx = ClusterPrefixIndex()
        k1, k2 = _chain(SHARED)
        for name in ("A", "B"):
            idx.publish(name, k1)
            idx.publish(name, k2)
        # equal coverage; A's chain is part-demoted -> B wins despite the
        # name tie-break preferring "A"
        idx.demote("A", k1)
        assert idx.best_holder(SHARED + [1], BS) == (8, "B")

    def test_republish_is_the_promotion_edge(self):
        idx = ClusterPrefixIndex()
        k1, k2 = _chain(SHARED)
        for name in ("A", "B"):
            idx.publish(name, k1)
            idx.publish(name, k2)
        idx.demote("A", k1)
        idx.publish("A", k1)  # restored to HBM: republish resets the tag
        assert idx.best_holder(SHARED + [1], BS) == (8, "A")
        assert idx.stats()["demoted_entries"] == 0

    def test_demoted_entry_still_evictable(self):
        idx = ClusterPrefixIndex()
        k1, _ = _chain(SHARED)
        idx.publish("A", k1)
        idx.demote("A", k1)
        idx.evict("A", k1)  # the tiers dropped it too (disk budget/clear)
        assert idx.stats()["entries"] == 0
        assert idx.best_holder(SHARED + [1], BS) == (0, None)


# ------------------------------------------------------ role-aware placement
def _stats(name="r0", role="unified", alive=True, draining=False,
           outstanding_tokens=0, free_blocks=48):
    return ReplicaStats(
        name=name, alive=alive, draining=draining, queued=0, inflight=0,
        outstanding_tokens=outstanding_tokens, free_blocks=free_blocks,
        pending_blocks=0, block_size=4, usable_blocks=48,
        max_request_blocks=16, max_request_tokens=128, role=role)


class TestPlacementRoles:
    def test_default_roles_never_pick_prefill(self):
        stats = [_stats("pre", role="prefill", outstanding_tokens=0),
                 _stats("dec", role="decode", outstanding_tokens=100)]
        # the prefill replica is idle and would win on load — the role
        # filter (which resubmit/failover also goes through) excludes it
        assert plan_placement(stats, 20, RouterConfig()) == (1, "admit")

    def test_prefill_only_pool_is_unplaceable(self):
        stats = [_stats("pre", role="prefill")]
        idx, verdict = plan_placement(stats, 20, RouterConfig())
        assert idx is None and verdict == "draining"

    def test_explicit_prefill_role_selects_prefill(self):
        stats = [_stats("pre", role="prefill"),
                 _stats("dec", role="decode")]
        idx, _ = plan_placement(stats, 20, RouterConfig(),
                                roles=("prefill",))
        assert idx == 0


# --------------------------------------------- engine-level handoff parity
@pytest.mark.parametrize("mode", sorted(MODES))
class TestHandoffParity:
    def test_split_prefill_decode_token_identical(self, mode, ref_tokens):
        a = _engine(**MODES[mode])
        b = _engine(**MODES[mode])
        for uid, sampling in (("ga", {}), ("sa", SAMPLED)):
            a.put(uid, PROMPT_A, max_new_tokens=MAX_NEW, handoff=True,
                  **sampling)
            first = _run(a, uid)
            assert len(first) == 1  # prefill emits exactly one token
            record = a.export_handoff(uid)
            assert record is not None and record.uid == uid
            assert record.n_blocks * BS >= len(PROMPT_A)
            assert b.import_handoff(record)
            got = _run(b, uid)
            # decode replica re-delivers from index 0: the prefill token
            # plus every decode token, identical to the unsplit run
            assert got == ref_tokens[uid], (mode, uid)
        assert a.kv_blocks_exported > 0
        assert b.kv_blocks_imported == a.kv_blocks_exported


class TestHandoffEdgeCases:
    def test_handoff_after_prefix_hit_still_parity(self, ref_tokens):
        a = _engine(cache=True)
        a.put("warm", PROMPT_A, max_new_tokens=MAX_NEW)
        _run(a, "warm")  # retires + publishes SHARED's blocks
        a.put("gb", PROMPT_B, max_new_tokens=MAX_NEW, handoff=True)
        _run(a, "gb")
        assert a.prefix_hits == 1  # the handoff prompt spliced cached blocks
        record = a.export_handoff("gb")
        b = _engine(cache=True)
        assert b.import_handoff(record)
        assert _run(b, "gb") == ref_tokens["gb"]

    def test_reset_state_fails_parked_handoffs(self):
        a = _engine()
        a.put("u", PROMPT_A, max_new_tokens=MAX_NEW, handoff=True)
        _run(a, "u")
        a.reset_state()
        assert a._results["u"].status == "error"
        assert a.export_handoff("u") is None

    def test_stale_cached_prefix_probe_falls_back_to_cold(self, ref_tokens):
        # the router promised 8 cached tokens (a stale cluster-index read);
        # the local cache is cold — admission must count the stale probe
        # and cold-prefill rather than splice garbage
        eng = _engine(cache=True)
        eng.put("ga", PROMPT_A, max_new_tokens=MAX_NEW,
                expected_cached_tokens=8)
        assert _run(eng, "ga") == ref_tokens["ga"]
        assert eng.prefix_stale_probes == 1


# ----------------------------------------- cross-replica prefix transfer
class TestPrefixTransfer:
    def test_import_gives_hits_on_replica_that_never_saw_prompt(
            self, ref_tokens):
        a = _engine(cache=True)
        a.put("warm", PROMPT_A, max_new_tokens=MAX_NEW)
        _run(a, "warm")
        payload = a.export_prefix(PROMPT_A)
        assert payload is not None and payload.tokens == SHARED

        b = _engine(cache=True)  # never ran any prompt
        assert b.import_prefix(payload) == len(SHARED)
        b.put("gb", PROMPT_B, max_new_tokens=MAX_NEW)
        got = _run(b, "gb")
        assert b.prefix_hits == 1  # reuse without ever prefilling SHARED
        assert got == ref_tokens["gb"]

    def test_export_prefix_none_when_cold_or_disabled(self):
        assert _engine().export_prefix(PROMPT_A) is None
        assert _engine(cache=True).export_prefix(PROMPT_A) is None


# --------------------------------------------------------- cluster end-to-end
def _post(frontend, body, timeout=120):
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=timeout)
    conn.request("POST", "/v1/completions", body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = json.loads(resp.read())
    conn.close()
    return resp.status, out


class TestClusterEndToEnd:
    def test_disagg_cluster_over_http(self, ref_tokens):
        pre = _engine(cache=True)
        params = pre.params
        frontend, cluster, loops = build_cluster_server(
            [pre], [_engine(cache=True, params=params),
                    _engine(cache=True, params=params)],
            router_cfg=RouterConfig(max_queue_tokens=512))
        try:
            status, out = _post(frontend, {"prompt": PROMPT_A,
                                           "max_tokens": MAX_NEW})
            assert status == 200
            assert out["choices"][0]["tokens"] == ref_tokens["ga"]
            status, out = _post(frontend, {"prompt": PROMPT_A,
                                           "max_tokens": MAX_NEW, **SAMPLED})
            assert status == 200
            assert out["choices"][0]["tokens"] == ref_tokens["sa"]
            status, out = _post(frontend, {"prompt": PROMPT_B,
                                           "max_tokens": MAX_NEW})
            assert status == 200
            assert out["choices"][0]["tokens"] == ref_tokens["gb"]

            cs = cluster.cluster_stats()
            assert cs["disagg_requests"] == 3
            assert cs["handoffs"]["ok"] == 3 and cs["handoffs"]["failed"] == 0
            assert cs["fallbacks"] == {}
            # PROMPT_A warmed the index; PROMPT_B's chain resolved a holder
            assert cs["prefix_index"]["hits"] >= 1
            assert cs["roles"] == {"prefill": 1, "decode": 2}

            conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                              timeout=60)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            hz = json.loads(resp.read())
            conn.close()
            assert resp.status == 200
            roles = {r["name"]: r["role"] for r in hz["replicas"]}
            assert roles == {"prefill-0": "prefill", "decode-0": "decode",
                             "decode-1": "decode"}
            assert hz["cluster"]["disagg_requests"] == 3
        finally:
            cluster.begin_drain()
            for lp in loops:
                lp.join(timeout=60)
            frontend.close()

    def test_decode_import_rejection_fails_over(self, ref_tokens):
        pre = _engine()
        params = pre.params
        loops = [EngineLoop(pre, name="prefill-0", role="prefill"),
                 EngineLoop(_engine(params=params), name="decode-0",
                            role="decode"),
                 EngineLoop(_engine(params=params), name="decode-1",
                            role="decode")]
        cluster = ServingCluster([loops[0]], loops[1:],
                                 router_cfg=RouterConfig(max_queue_tokens=512))
        for lp in loops:
            lp.start()
        try:
            # decode-0 rejects every import (capacity lie) — the cluster
            # must retry the handoff on decode-1, not fail the request
            loops[1].call(
                lambda e: setattr(e, "import_handoff", lambda h: False))
            stream = cluster.submit(
                CompletionRequest(prompt=PROMPT_A, max_tokens=MAX_NEW))
            tokens, reason = stream.collect(timeout=120)
            assert tokens == ref_tokens["ga"] and reason == "length"
            assert loops[2].call(lambda e: e.kv_blocks_imported) > 0
            cs = cluster.cluster_stats()
            assert cs["handoffs"]["ok"] == 1 and cs["fallbacks"] == {}
        finally:
            cluster.begin_drain()
            for lp in loops:
                lp.join(timeout=60)

    def test_prefill_death_mid_handoff_replays_identically(self, ref_tokens):
        pre0 = _engine()
        params = pre0.params
        loops = [EngineLoop(pre0, name="prefill-0", role="prefill",
                            max_respawns=0),
                 EngineLoop(_engine(params=params), name="prefill-1",
                            role="prefill"),
                 EngineLoop(_engine(params=params), name="decode-0",
                            role="decode")]
        cluster = ServingCluster(loops[:2], loops[2:],
                                 router_cfg=RouterConfig(max_queue_tokens=512))
        for lp in loops:
            lp.start()
        inj = get_fault_injector()
        try:
            # one fatal loop fault: it fires on the replica that picks up
            # the prompt (idle loops never reach POINT_LOOP), killing
            # prefill-0 mid-handoff; the retry replays on prefill-1 and the
            # per-request seed makes the output token-identical
            inj.configure([{"point": POINT_LOOP, "fatal": True, "times": 1}])
            stream = cluster.submit(
                CompletionRequest(prompt=PROMPT_A, max_tokens=MAX_NEW,
                                  **SAMPLED))
            tokens, reason = stream.collect(timeout=120)
            assert tokens == ref_tokens["sa"] and reason == "length"
            assert not loops[0].stats().alive
            cs = cluster.cluster_stats()
            assert cs["handoffs"]["ok"] == 1 and cs["fallbacks"] == {}
        finally:
            inj.reset()
            cluster.begin_drain()
            for lp in loops:
                lp.join(timeout=60)


# --------------------------------------------------- router pool management
class TestRouterPool:
    def test_add_remove_replica(self):
        e = _engine()
        a = EngineLoop(e, name="a")
        b = EngineLoop(_engine(params=e.params), name="b")
        router = ReplicaRouter([a], RouterConfig())
        assert not router.remove_replica(a)  # refuses to empty the pool
        router.add_replica(b)
        assert [r["name"] for r in router.health()] == ["a", "b"]
        assert router.remove_replica(a)
        assert [r["name"] for r in router.health()] == ["b"]
        assert router.health()[0]["role"] == "unified"


# ------------------------------------------------------------- autoscaler
class TestDecodeAutoscaler:
    def test_burn_driven_scale_up_down_with_bounds(self):
        pre = _engine()
        params = pre.params
        loops = [EngineLoop(pre, name="prefill-0", role="prefill"),
                 EngineLoop(_engine(params=params), name="decode-0",
                            role="decode")]
        cfg = ClusterConfig(min_decode_replicas=1, max_decode_replicas=2,
                            autoscale_cooldown_s=0.0)
        cluster = ServingCluster(loops[:1], loops[1:], cfg=cfg)
        for lp in loops:
            lp.start()
        burn = [2.0]

        def factory(name):
            return EngineLoop(_engine(params=params), name=name,
                              role="decode")

        scaler = DecodeAutoscaler(cluster, factory, cfg=cfg,
                                  burn_fn=lambda: burn[0])
        try:
            assert scaler.tick() == 1
            assert cluster.cluster_stats()["roles"]["decode"] == 2
            assert scaler.tick() == 0      # at max_decode_replicas
            burn[0] = 0.0
            assert scaler.tick() == -1
            assert scaler.tick() == 0      # at min_decode_replicas
            deadline = time.perf_counter() + 60
            while (cluster.cluster_stats()["roles"]["decode"] != 1
                   and time.perf_counter() < deadline):
                time.sleep(0.01)           # drain reaper removes the victim
            assert cluster.cluster_stats()["roles"]["decode"] == 1
            events = [e["direction"]
                      for e in cluster.cluster_stats()["autoscale_events"]]
            assert events == ["up", "down"]
        finally:
            scaler.stop()
            cluster.begin_drain()
            cluster.drain(timeout=60)

    def test_cooldown_dwell_blocks_back_to_back_actions(self):
        pre = _engine()
        loops = [EngineLoop(pre, name="prefill-0", role="prefill"),
                 EngineLoop(_engine(params=pre.params), name="decode-0",
                            role="decode")]
        cfg = ClusterConfig(autoscale_cooldown_s=3600.0,
                            max_decode_replicas=4)
        cluster = ServingCluster(loops[:1], loops[1:], cfg=cfg)
        scaler = DecodeAutoscaler(
            cluster, lambda name: None, cfg=cfg, burn_fn=lambda: 2.0)
        scaler._last_action = time.perf_counter()  # as if it just acted
        assert scaler.tick() == 0
