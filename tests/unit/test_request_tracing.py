"""End-to-end request tracing (telemetry/tracing.py + the serving thread):
W3C traceparent propagation, span-tree integrity across frontend → router →
engine loop → ragged engine, Chrome trace-event export validity, the
zero-allocation-when-off pin on the ragged hot path, compile-cache miss
observability, and SLO burn-rate health reflection.

(``tests/unit/test_tracing.py`` covers the utils-level profiler tracing;
this file covers the request-tracing subsystem added with the serving
observability work.)"""

import http.client
import json
import tracemalloc

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (
    EngineLoop,
    ReplicaRouter,
    RouterConfig,
    ServingFrontend,
)
from deepspeed_tpu.serving.protocol import decode_sse
from deepspeed_tpu.telemetry.slo import SloMonitor, default_objectives
from deepspeed_tpu.telemetry.tracing import (
    TraceContext,
    Tracer,
    format_traceparent,
    parse_traceparent,
)

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
RCFG = RaggedConfig(
    max_tokens_per_step=16, max_seqs=3, block_size=4,
    num_blocks=49, max_blocks_per_seq=16,
)


def _engine():
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), RCFG, dtype=jnp.float32, seed=0)


def _prompt(n, seed=0):
    return [int(t) for t in
            np.random.default_rng(seed).integers(0, CFG.vocab_size, n)]


def _drain(eng, max_steps=500):
    for _ in range(max_steps):
        eng.step()
        if not eng.has_work:
            return
    raise AssertionError("engine did not drain")


# ---------------------------------------------------------- W3C context
class TestTraceparent:
    def test_parse_valid(self):
        tid = "a" * 32
        sid = "b" * 16
        assert parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid, True)
        assert parse_traceparent(f"00-{tid}-{sid}-00") == (tid, sid, False)
        # case/whitespace tolerant
        assert parse_traceparent(f"  00-{tid.upper()}-{sid}-01 ") == (
            tid, sid, True)

    def test_parse_rejects_malformed(self):
        tid, sid = "a" * 32, "b" * 16
        for bad in (
            None, "", 42, "garbage",
            f"ff-{tid}-{sid}-01",            # reserved version
            f"00-{'0' * 32}-{sid}-01",       # zero trace id
            f"00-{tid}-{'0' * 16}-01",       # zero span id
            f"00-{tid[:-1]}-{sid}-01",       # short trace id
            f"00-{tid}-{sid}",               # missing flags
        ):
            assert parse_traceparent(bad) is None

    def test_format_round_trip(self):
        ctx = TraceContext("c" * 32, "d" * 16)
        assert parse_traceparent(format_traceparent(ctx)) == (
            "c" * 32, "d" * 16, True)
        assert format_traceparent(ctx, sampled=False).endswith("-00")


class TestTracer:
    def _tracer(self, **kw):
        return Tracer(telemetry.get_telemetry().registry).configure(**kw)

    def test_disabled_is_inert(self):
        tr = Tracer(telemetry.get_telemetry().registry)
        assert tr.extract("00-" + "a" * 32 + "-" + "b" * 16 + "-01") is None
        assert tr.begin(TraceContext("a" * 32, "b" * 16)) is None
        tr.finish(None, "x", 0.0, 1.0)
        assert tr.snapshot() == []

    def test_extract_honors_upstream_decision(self):
        tr = self._tracer()
        hdr = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
        ctx = tr.extract(hdr)
        assert ctx.trace_id == "a" * 32 and ctx.parent_id == "b" * 16
        assert ctx.span_id != "b" * 16  # fresh server-side span
        # sampled flag 0: upstream opted out, no partial trees
        assert tr.extract(hdr[:-2] + "00") is None
        # malformed header -> fresh root
        root = tr.extract("bogus")
        assert root.parent_id is None and len(root.trace_id) == 32

    def test_head_sampling_is_deterministic(self):
        tr = self._tracer(sample_rate=0.25)
        kept = sum(tr.extract(None) is not None for _ in range(100))
        assert kept == 25
        tr = self._tracer(sample_rate=0.0)
        assert all(tr.extract(None) is None for _ in range(10))

    def test_ring_is_bounded(self):
        tr = self._tracer(ring_capacity=8)
        root = tr.extract(None)
        for i in range(20):
            tr.record(root, f"s{i}", float(i), float(i) + 0.5)
        spans = tr.snapshot()
        assert len(spans) == 8
        assert spans[0]["name"] == "s12" and spans[-1]["name"] == "s19"

    def test_span_histogram_feeds_registry(self):
        reg = telemetry.get_telemetry().registry
        tr = Tracer(reg).configure()
        root = tr.extract(None)
        tr.record(root, "unit/span", 0.0, 0.125)
        h = reg.histogram("trace_span_seconds")
        assert h.count(name="unit/span") == 1
        assert h.sum(name="unit/span") == pytest.approx(0.125)

    def test_chrome_export_shape_and_nesting(self):
        tr = self._tracer()
        root = tr.extract(None)
        child = tr.begin(root)
        tr.finish(child, "child", 1.0, 2.0, tokens=3)
        tr.finish(root, "root", 0.5, 2.5)
        trace = tr.export_chrome()
        events = trace["traceEvents"]
        assert len(events) == 2 and trace["displayTimeUnit"] == "ms"
        by_name = {e["name"]: e for e in events}
        for e in events:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert e["pid"] and e["tid"]
        c, r = by_name["child"], by_name["root"]
        assert c["args"]["parent_id"] == r["args"]["span_id"]
        assert c["args"]["trace_id"] == r["args"]["trace_id"]
        assert c["args"]["tokens"] == 3
        # timestamp containment: the child renders nested under the root
        assert r["ts"] <= c["ts"] and c["ts"] + c["dur"] <= r["ts"] + r["dur"]
        json.dumps(trace)  # wire-serializable as-is
        # filtered export excludes other traces
        other = tr.extract(None)
        tr.finish(other, "noise", 3.0, 4.0)
        only = tr.export_chrome(root.trace_id)
        assert {e["name"] for e in only["traceEvents"]} == {"child", "root"}


# ------------------------------------------------------- engine integration
class TestEngineTracing:
    def test_request_span_tree(self):
        telemetry.configure(enabled=True, tracing=True)
        eng = _engine()
        for uid, n in [("a", 5), ("b", 11)]:
            eng.put(uid, _prompt(n, seed=hash(uid) % 100), max_new_tokens=4)
        _drain(eng)
        spans = telemetry.get_telemetry().tracer.snapshot()
        per_trace = {}
        for s in spans:
            per_trace.setdefault(s["trace_id"], []).append(s)
        assert len(per_trace) == 2  # one tree per request, no cross-talk
        for tree in per_trace.values():
            names = {s["name"] for s in tree}
            assert {"engine/request", "request/admission",
                    "engine/prefill", "engine/decode",
                    "engine/readback"} <= names
            req = [s for s in tree if s["name"] == "engine/request"]
            assert len(req) == 1
            root_id = req[0]["span_id"]
            # every other span hangs off the request umbrella
            for s in tree:
                if s["name"] != "engine/request":
                    assert s["parent_id"] == root_id
            # dispatch spans carry the token count + dispatch mode
            for s in tree:
                if s["name"] in ("engine/prefill", "engine/decode"):
                    assert s["attrs"]["tokens"] >= 1
                    assert "mode" in s["attrs"]

    def test_put_parents_under_given_context(self):
        telemetry.configure(enabled=True, tracing=True)
        tr = telemetry.get_telemetry().tracer
        root = tr.extract(None)
        eng = _engine()
        eng.put("u", _prompt(5), max_new_tokens=2, trace=root)
        _drain(eng)
        req = [s for s in tr.snapshot() if s["name"] == "engine/request"]
        assert len(req) == 1
        assert req[0]["trace_id"] == root.trace_id
        assert req[0]["parent_id"] == root.span_id

    def test_sampling_drops_whole_requests(self):
        telemetry.configure(enabled=True, tracing={"enabled": True,
                                                   "sample_rate": 0.0})
        eng = _engine()
        eng.put("u", _prompt(5), max_new_tokens=2)
        _drain(eng)
        assert telemetry.get_telemetry().tracer.snapshot() == []

    def test_disabled_hot_path_allocates_nothing_in_tracer(self):
        """The zero-allocation pin: with tracing off, a full serve cycle
        must execute no allocating statement in tracing.py (the emit paths
        are guarded by one attribute read / a ``seq.trace is None`` check)."""
        telemetry.configure(enabled=True)  # telemetry on, tracing OFF
        eng = _engine()
        eng.put("w", _prompt(4, seed=9), max_new_tokens=2)
        _drain(eng)  # warm the jit caches outside the measured window
        tracemalloc.start(1)
        try:
            eng.put("u", _prompt(5), max_new_tokens=4)
            eng.put("v", _prompt(9, seed=1), max_new_tokens=4)
            _drain(eng)
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        stats = snap.filter_traces(
            [tracemalloc.Filter(True, "*/telemetry/tracing.py")]).statistics(
                "filename")
        assert sum(s.count for s in stats) == 0, stats

    def test_shape_bust_increments_program_cache_misses(self):
        """A dispatch outside the already-built program set is a serve-time
        jit cache miss: the engine-side counter and coverage gauge see it
        (independent of jax.monitoring, so it holds on any backend)."""
        telemetry.configure(enabled=True)
        eng = _engine()
        eng.put("a", _prompt(5), max_new_tokens=2)
        _drain(eng)
        tel = telemetry.get_telemetry()

        def total_misses() -> float:
            # kind-agnostic: which dispatch path serves depends on config
            return sum(
                float(line.rsplit(" ", 1)[1])
                for line in tel.registry.render_prometheus().splitlines()
                if line.startswith("ragged_program_cache_misses_total"))

        cold0 = total_misses()
        assert cold0 >= 1  # first dispatch compiled a fresh program
        warm = eng.program_cold_dispatches
        # same shapes again: no new programs
        eng.put("b", _prompt(5, seed=2), max_new_tokens=2)
        _drain(eng)
        assert eng.program_cold_dispatches == warm
        # bust the bucket ladder: three concurrent decodes need a wider
        # batch bucket than the single-request runs ever built
        for uid in ("c", "d", "e"):
            eng.put(uid, _prompt(4, seed=ord(uid[0])), max_new_tokens=3)
        _drain(eng)
        assert eng.program_cold_dispatches > warm
        assert total_misses() > cold0
        cov = tel.registry.gauge("ragged_warmup_coverage").value()
        assert 0.0 < cov < 1.0

    def test_backend_compile_counter_on_cpu(self):
        """jax.monitoring's backend-compile event fires on every real XLA
        compile, so building + serving a fresh engine must increment
        ``jit_cache_misses_total{source="monitoring"}``."""
        telemetry.configure(enabled=True)  # installs CompileWatch
        tel = telemetry.get_telemetry()
        cw = tel.compile_watch
        assert cw is not None
        if cw.fallback:  # pragma: no cover - jax without monitoring hooks
            pytest.skip("jax.monitoring unavailable; fallback covered below")
        before = tel.registry.counter(
            "jit_cache_misses_total").value(source="monitoring")
        eng = _engine()
        eng.put("a", _prompt(5), max_new_tokens=2)
        _drain(eng)
        after = tel.registry.counter(
            "jit_cache_misses_total").value(source="monitoring")
        assert after > before
        # the series renders at scrape time even when it is still zero
        assert "jit_cache_misses_total" in tel.registry.render_prometheus()

    def test_cache_size_delta_fallback(self):
        from deepspeed_tpu.telemetry.compile_watch import CompileWatch

        reg = telemetry.get_telemetry().registry
        cw = CompileWatch(reg)
        cw.fallback = True  # simulate a jax without monitoring hooks
        cw.note_cache_size(3)
        cw.note_cache_size(5)   # +2 programs -> 2 misses
        cw.note_cache_size(5)   # no delta
        cw.note_cache_size(4)   # shrink is not a miss
        assert reg.counter("jit_cache_misses_total").value(
            source="cache_size_delta") == 2


# ------------------------------------------------------------------- SLO
class TestSloMonitor:
    def test_burn_rate_math(self):
        reg = telemetry.get_telemetry().registry
        mon = SloMonitor(default_objectives(ttft_threshold_s=0.1,
                                            target=0.9, window_s=60.0), reg)
        for _ in range(8):
            mon.record("ttft", 0.05, now=100.0)
        for _ in range(2):
            mon.record("ttft", 0.5, now=100.0)
        s = mon.stats("ttft", now=100.0)
        assert s["count"] == 10 and s["good_fraction"] == pytest.approx(0.8)
        # bad fraction 0.2 over budget 0.1 -> burning 2x
        assert s["burn_rate"] == pytest.approx(2.0)
        assert s["breaching"]
        assert reg.gauge("slo_breaching").value(objective="ttft") == 1.0
        # bad samples age out of the window -> healthy again
        s = mon.stats("ttft", now=200.0)
        assert s["count"] == 0 and not s["breaching"]
        assert s["good_fraction"] == 1.0

    def test_min_samples_guards_noise(self):
        mon = SloMonitor(default_objectives(ttft_threshold_s=0.1),
                         telemetry.get_telemetry().registry)
        for _ in range(SloMonitor.MIN_SAMPLES - 1):
            mon.record("ttft", 9.9, now=10.0)  # 100% bad but too few
        assert not mon.stats("ttft", now=10.0)["breaching"]
        mon.record("ttft", 9.9, now=10.0)
        assert mon.stats("ttft", now=10.0)["breaching"]

    def test_unknown_objective_ignored(self):
        mon = SloMonitor(default_objectives(),
                         telemetry.get_telemetry().registry)
        mon.record("nope", 1.0)  # must not raise
        assert "nope" not in mon.health()


# ------------------------------------------------------- serving end-to-end
@pytest.fixture
def traced_server():
    # telemetry (and the CompileWatch) must be live BEFORE the engine
    # builds so its compiles are observed
    telemetry.configure(
        enabled=True, tracing=True, slo={"enabled": True, "window_s": 60.0})
    eng = _engine()
    loop = EngineLoop(eng, name="traced")
    router = ReplicaRouter([loop], RouterConfig(max_queue_tokens=96))
    frontend = ServingFrontend(router, port=0)
    loop.start()
    frontend.start()
    yield frontend, router, loop, eng
    frontend.router.begin_drain()
    loop.join(timeout=60)
    frontend.close()


def _post(frontend, body, headers=None, timeout=120):
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=timeout)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", "/v1/completions", body=json.dumps(body),
                 headers=hdrs)
    return conn, conn.getresponse()


def _get(frontend, path):
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    status, headers = resp.status, dict(resp.getheaders())
    conn.close()
    return status, headers, body


class TestServingTracePropagation:
    def test_client_traceparent_threads_to_engine_spans(self, traced_server):
        frontend, _, _, _ = traced_server
        trace_id = "f" * 32
        parent = "1234567890abcdef"
        conn, resp = _post(
            frontend, {"prompt": _prompt(5), "max_tokens": 3},
            headers={"traceparent": f"00-{trace_id}-{parent}-01"})
        assert resp.status == 200
        echoed = parse_traceparent(resp.getheader("traceparent"))
        body = json.loads(resp.read())
        conn.close()
        assert echoed[0] == trace_id  # same trace, server-side span id
        assert body["trace_id"] == trace_id
        spans = telemetry.get_telemetry().tracer.snapshot(trace_id)
        names = {s["name"] for s in spans}
        assert {"http/request", "router/submit", "loop/inbox_wait",
                "engine/request", "request/admission", "engine/prefill",
                "engine/decode", "engine/readback"} <= names
        by_id = {s["span_id"]: s for s in spans}
        root = [s for s in spans if s["name"] == "http/request"]
        assert len(root) == 1 and root[0]["parent_id"] == parent
        # single connected tree: every non-root span's parent is recorded
        for s in spans:
            if s is root[0]:
                continue
            assert s["parent_id"] in by_id, s
        # the engine umbrella hangs off the HTTP root and the per-dispatch
        # spans hang off the umbrella
        req = next(s for s in spans if s["name"] == "engine/request")
        assert req["parent_id"] == root[0]["span_id"]
        for s in spans:
            if s["name"].startswith("engine/") and s is not req:
                assert s["parent_id"] == req["span_id"]
        # ... and /debug/trace serves the same tree as valid Chrome JSON
        status, headers, raw = _get(frontend,
                                    f"/debug/trace?trace_id={trace_id}")
        assert status == 200
        trace = json.loads(raw)
        assert {e["name"] for e in trace["traceEvents"]} == names
        for e in trace["traceEvents"]:
            assert e["ph"] == "X" and e["pid"] and e["tid"]
            assert e["args"]["trace_id"] == trace_id

    def test_sse_frames_carry_trace_id(self, traced_server):
        frontend, _, _, _ = traced_server
        conn, resp = _post(frontend, {"prompt": _prompt(5), "max_tokens": 3,
                                      "stream": True})
        assert resp.status == 200
        trace_id = parse_traceparent(resp.getheader("traceparent"))[0]
        frames = decode_sse(resp.read())
        conn.close()
        tokens = [f for f in frames if "token" in f]
        assert tokens and all(f["trace_id"] == trace_id for f in tokens)
        final = frames[-2]
        assert final["trace_id"] == trace_id

    def test_metrics_route_ignores_query_string(self, traced_server):
        frontend, _, _, _ = traced_server
        status, _, body = _get(frontend, "/metrics?foo=1&bar=2")
        assert status == 200
        page = body.decode()
        assert "jit_cache_misses_total" in page
        assert "slo_burn_rate" in page
        status, _, _ = _get(frontend, "/healthz?verbose=1")
        assert status == 200

    def test_timeout_maps_to_504_with_retry_hint(self):
        telemetry.configure(enabled=True, tracing=True)
        eng = _engine()
        loop = EngineLoop(eng, name="slowpoke")
        router = ReplicaRouter([loop], RouterConfig(max_queue_tokens=96))
        frontend = ServingFrontend(router, port=0,
                                   request_timeout_s=0.02)
        loop.start()
        frontend.start()
        try:
            conn, resp = _post(frontend, {"prompt": _prompt(5),
                                          "max_tokens": 8})
            assert resp.status == 504  # gateway timeout, not client error
            assert resp.getheader("Retry-After") == "1"
            err = json.loads(resp.read())["error"]
            conn.close()
            assert err["retry_after_s"] == 1.0
            assert err["timeout_s"] == pytest.approx(0.02)
            assert "did not complete" in err["message"]
        finally:
            frontend.router.begin_drain()
            loop.join(timeout=60)
            frontend.close()

    def test_healthz_reflects_slo_burn(self, traced_server):
        frontend, _, _, _ = traced_server
        tel = telemetry.get_telemetry()
        status, _, body = _get(frontend, "/healthz")
        assert status == 200
        h = json.loads(body)
        assert h["status"] == "ready"
        assert "ttft" in h["slo"] and not h["slo"]["ttft"]["breaching"]
        # burn the whole error budget: every in-window TTFT is bad
        for _ in range(SloMonitor.MIN_SAMPLES + 1):
            tel.observe_slo("ttft", 99.0)
        status, _, body = _get(frontend, "/healthz")
        h = json.loads(body)
        assert status == 200  # degraded still serves
        assert h["status"] == "degraded"
        assert h["slo"]["ttft"]["breaching"]
        assert h["slo"]["ttft"]["burn_rate"] > 1.0
