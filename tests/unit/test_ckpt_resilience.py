"""Crash-safety of the checkpoint commit protocol (docs/FAULT_TOLERANCE.md,
"Training: crash-safe checkpoints"): two-phase commit invariants, the
verification stages, the fallback ladder, pointer/rotation hygiene, and the
async-writer error path. Structural tests build checkpoint dirs by hand (no
engine, fast); the load-path tests drive a real training engine."""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint import engine as ckpt
from deepspeed_tpu.checkpoint import serialization as ser
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.telemetry import TELEMETRY

VOCAB = 256


# --------------------------------------------------------- structural (no jax)
def _make_committed(save_dir, tag, step_payload=b"x" * 64, point_latest=True):
    """Build a committed checkpoint the way the engine does: stage → commit."""
    stage = ckpt.staging_dir(str(save_dir), tag)
    os.makedirs(stage)
    with open(os.path.join(stage, "model_shard_p0.npz"), "wb") as f:
        f.write(step_payload)
    final = ckpt.commit_checkpoint(str(save_dir), tag, {"global_steps": 1})
    if point_latest:
        ckpt.write_latest(str(save_dir), tag)
    return final


def test_commit_writes_file_table_and_verifies(tmp_path):
    final = _make_committed(tmp_path, "global_step4")
    manifest = ckpt.verify_checkpoint(final)
    assert manifest["commit_protocol"] == 2
    assert manifest["files"]["model_shard_p0.npz"]["bytes"] == 64
    assert not os.path.exists(ckpt.staging_dir(str(tmp_path), "global_step4"))
    assert ckpt.latest_tag(str(tmp_path)) == "global_step4"


def test_verify_stages(tmp_path):
    """Each corruption mode is detected and named by its verification stage."""
    final = _make_committed(tmp_path, "global_step2")
    payload = os.path.join(final, "model_shard_p0.npz")

    # silent bit flip → checksum-mismatch (deep only)
    with open(payload, "rb") as f:
        good = f.read()
    with open(payload, "r+b") as f:
        f.write(bytes([good[0] ^ 0xFF]))
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.verify_checkpoint(final)
    assert ei.value.stage == "checksum-mismatch"
    ckpt.verify_checkpoint(final, deep=False)  # same size: shallow passes

    # truncation → size-mismatch
    with open(payload, "r+b") as f:
        f.truncate(10)
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.verify_checkpoint(final)
    assert ei.value.stage == "size-mismatch"

    # file listed in the manifest but gone → file-missing
    os.unlink(payload)
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.verify_checkpoint(final)
    assert ei.value.stage == "file-missing"

    # no manifest at all → manifest-missing
    os.unlink(os.path.join(final, ckpt.MANIFEST))
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.verify_checkpoint(final)
    assert ei.value.stage == "manifest-missing"

    # a staging dir is never a checkpoint, however complete it looks
    stage = ckpt.staging_dir(str(tmp_path), "global_step6")
    os.makedirs(stage)
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.verify_checkpoint(stage)
    assert ei.value.stage == "uncommitted"


def test_multihost_partial_index_residue_is_uncommitted(tmp_path):
    """A crash between the per-process ``.index.p*.json`` writes and
    ``finalize_index`` leaves partial indexes with no merged one — the
    checkpoint never committed and must read as corrupt, not half-load."""
    final = _make_committed(tmp_path, "global_step8")
    with open(os.path.join(final, "model.index.p0.json"), "w") as f:
        json.dump({"embed": {"fragments": []}}, f)
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.verify_checkpoint(final)
    assert ei.value.stage == "uncommitted"

    # once the merged index exists, residue is harmless — but the merged
    # index's fragments must exist and cover their leaves
    with open(os.path.join(final, "model.index.json"), "w") as f:
        json.dump({"embed": {"shape": [4], "dtype": "float32", "fragments": [
            {"file": "model_shard_p0.npz", "key": "embed",
             "index": [[0, 2]]}]}}, f)
    # manifest doesn't list the new files; rebuild it to keep checksums valid
    manifest = ser.load_json(os.path.join(final, ckpt.MANIFEST))
    manifest["files"] = ckpt.build_file_table(final, fsync=False)
    ser.save_json(os.path.join(final, ckpt.MANIFEST), manifest)
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.verify_checkpoint(final)
    assert ei.value.stage == "fragment-coverage"


def test_latest_pointer_garbage_tolerated(tmp_path):
    """An unreadable/garbage ``latest`` must not take the run down — the
    loader falls back to the on-disk ladder."""
    _make_committed(tmp_path, "global_step2", point_latest=False)
    latest = os.path.join(str(tmp_path), "latest")

    TELEMETRY.enabled = True
    for garbage in (b"", b"\0\0\0\0", b"a/b", b"x" * 600):
        with open(latest, "wb") as f:
            f.write(garbage)
        assert ckpt.latest_tag(str(tmp_path)) is None
    prom = TELEMETRY.registry.render_prometheus()
    assert 'checkpoint_corrupt_total{stage="latest-garbage"}' in prom

    os.unlink(latest)
    os.mkdir(latest)  # open() raises IsADirectoryError (an OSError)
    assert ckpt.latest_tag(str(tmp_path)) is None
    # the ladder still finds the committed tag
    assert ckpt.list_tags(str(tmp_path)) == ["global_step2"]


def test_atomic_write_leaves_no_residue(tmp_path):
    target = tmp_path / "latest"
    ser.atomic_write_text(str(target), "global_step10")
    ser.atomic_write_text(str(target), "global_step12")
    assert target.read_text() == "global_step12"
    assert [p.name for p in tmp_path.iterdir()] == ["latest"]


def test_rotation_orders_by_step_not_mtime(tmp_path):
    """Rotation must evict by the step parsed from the tag: a re-synced or
    restored old checkpoint with a fresh mtime must still be the one evicted.
    Staging dirs and uncommitted residue are neither counted nor deleted."""
    for tag in ("global_step10", "global_step9", "global_step2"):
        _make_committed(tmp_path, tag, point_latest=False)
    os.utime(tmp_path / "global_step2")  # restored old tag: newest mtime
    os.makedirs(tmp_path / ".tmp-global_step12")  # mid-save staging
    os.makedirs(tmp_path / "residue")  # dir without manifest: not a ckpt
    ckpt.write_latest(str(tmp_path), "global_step9")

    ckpt.rotate_checkpoints(str(tmp_path), keep_n=2)
    kept = sorted(d.name for d in tmp_path.iterdir() if d.is_dir())
    assert kept == [".tmp-global_step12", "global_step10", "global_step9",
                    "residue"]

    # latest's target survives even when keep_n would evict it: with the
    # pointer on the OLDER tag, keep_n=1 keeps exactly the pointed tag
    ckpt.write_latest(str(tmp_path), "global_step9")
    ckpt.rotate_checkpoints(str(tmp_path), keep_n=1)
    kept = sorted(d.name for d in tmp_path.iterdir() if d.is_dir())
    assert "global_step9" in kept and "global_step10" not in kept


def test_rotation_protects_just_written_tag(tmp_path):
    for tag in ("global_step2", "global_step4"):
        _make_committed(tmp_path, tag, point_latest=False)
    ckpt.write_latest(str(tmp_path), "global_step4")
    # protect= is the tag the caller just wrote, pointer not yet moved
    ckpt.rotate_checkpoints(str(tmp_path), keep_n=1, protect="global_step2")
    assert (tmp_path / "global_step2").is_dir()
    assert (tmp_path / "global_step4").is_dir()


def test_tag_ladder_ordering(tmp_path):
    for tag in ("global_step3", "global_step20", "alpha", "global_step7"):
        _make_committed(tmp_path, tag, point_latest=False)
    assert ckpt.list_tags(str(tmp_path)) == [
        "global_step20", "global_step7", "global_step3", "alpha"]
    assert ckpt.fallback_tags(str(tmp_path), failed="global_step20") == [
        "global_step7", "global_step3", "alpha"]
    assert ckpt.tag_step("global_step20") == 20
    assert ckpt.tag_step("alpha") == -1


# ------------------------------------------------------------- engine-backed
def _config(stage=0, mesh=None):
    return {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh or {"data": 8},
        "seed": 7,
    }


def _new_engine():
    reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=_config(), seed=11)
    return engine


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, VOCAB, (16, 16), dtype=np.int32)}


def test_fallback_ladder_and_exhaustion(tmp_path):
    """Corrupting the newest checkpoint walks the loader back one tag;
    corrupting every tag raises ``exhausted`` without touching engine state;
    ``.tmp-*`` residue from a killed save is skipped throughout."""
    engine = _new_engine()
    engine.train_batch(_batch(0))
    engine.save_checkpoint(str(tmp_path))  # global_step1
    engine.train_batch(_batch(1))
    engine.save_checkpoint(str(tmp_path))  # global_step2
    # crash residue: a staging dir that never promoted
    os.makedirs(tmp_path / ".tmp-global_step3")
    (tmp_path / ".tmp-global_step3" / "model_shard_p0.npz").write_bytes(b"zz")

    # flip one byte in the newest checkpoint's biggest payload
    newest = tmp_path / "global_step2"
    payload = max(newest.glob("*.npz"), key=lambda p: p.stat().st_size)
    raw = bytearray(payload.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    payload.write_bytes(raw)

    TELEMETRY.enabled = True
    loader = _new_engine()
    path, _ = loader.load_checkpoint(str(tmp_path))
    assert os.path.basename(path) == "global_step1"
    assert loader.global_steps == 1
    prom = TELEMETRY.registry.render_prometheus()
    assert 'checkpoint_corrupt_total{stage="checksum-mismatch"}' in prom
    assert "checkpoint_fallback_total 1" in prom
    assert "checkpoint_verify_seconds" in prom

    # now corrupt the survivor too: the ladder is exhausted and must raise,
    # with the loader's state untouched
    payload1 = max((tmp_path / "global_step1").glob("*.npz"),
                   key=lambda p: p.stat().st_size)
    raw = bytearray(payload1.read_bytes())
    raw[0] ^= 0xFF
    payload1.write_bytes(raw)
    fresh = _new_engine()
    before = [np.asarray(x).copy()
              for x in jax.tree_util.tree_leaves(fresh.params)]
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        fresh.load_checkpoint(str(tmp_path))
    assert ei.value.stage == "exhausted"
    assert fresh.global_steps == 0
    for a, b in zip(before, jax.tree_util.tree_leaves(fresh.params)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_load_missing_dir_returns_none(tmp_path):
    engine = _new_engine()
    path, state = engine.load_checkpoint(str(tmp_path / "nope"))
    assert path is None and state == {}


def test_async_writer_error_surfaces_at_destroy(tmp_path, monkeypatch):
    """A writer-thread failure must not be silently dropped: ``destroy()``
    (and the preemption path) join the writer and re-raise its error."""
    engine = _new_engine()
    engine.config.checkpoint.async_save = True
    engine.train_batch(_batch(0))

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt, "commit_checkpoint", boom)
    engine.save_checkpoint(str(tmp_path))  # returns before the flush fails
    with pytest.raises(RuntimeError, match="async checkpoint flush failed"):
        engine.destroy()
    # the error is consumed: a second destroy is clean
    engine.destroy()


def test_preempt_checkpoint_joins_writer(tmp_path, monkeypatch):
    """``PreemptionHandler._checkpoint`` is the last save before exit — it
    must surface an async-flush failure instead of reporting success while
    ``latest`` still names the previous checkpoint."""
    from deepspeed_tpu.elasticity.agent import PreemptionHandler

    engine = _new_engine()
    engine.config.checkpoint.async_save = True
    engine.train_batch(_batch(0))
    handler = PreemptionHandler(engine, str(tmp_path))

    def boom(*a, **k):
        raise OSError("enospc")

    try:
        monkeypatch.setattr(ckpt, "commit_checkpoint", boom)
        handler.should_stop = True
        with pytest.raises(RuntimeError, match="async checkpoint flush"):
            handler.checkpoint_if_needed()
    finally:
        handler.restore()
        engine._ckpt_writer_error = None
        engine.destroy()
