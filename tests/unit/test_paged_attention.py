"""Pallas paged flash-decode kernel vs the XLA padded-gather path
(reference ``inference/v2/kernels/ragged_ops`` blocked flash attention)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention import paged_attention


def _setup(seed=0, T=6, Hq=4, Hkv=2, D=16, NB=16, BS=8, MB=4):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(T, Hq, D)).astype(np.float32))
    kp = jnp.asarray(rng.normal(size=(NB, BS, Hkv, D)).astype(np.float32))
    vp = jnp.asarray(rng.normal(size=(NB, BS, Hkv, D)).astype(np.float32))
    bt = np.zeros((3, MB), np.int32)
    bt[0] = [3, 5, 7, 11]
    bt[1] = [2, 9, 1, 0]
    slots = jnp.asarray(np.array([0, 0, 1, 1, 0, 1], np.int32))
    pos = jnp.asarray(np.array([0, 13, 5, 8, 31, 17], np.int32))
    return q, kp, vp, slots, pos, jnp.asarray(bt)


def test_pallas_matches_xla_gather():
    args = _setup()
    out_x = paged_attention(*args, impl="xla")
    out_p = paged_attention(*args, impl="pallas")  # interpret mode on CPU
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)


def test_pallas_mixed_prefill_decode_positions():
    # positions within the same block and across block boundaries
    q, kp, vp, _, _, bt = _setup(T=4)
    slots = jnp.asarray(np.array([0, 0, 0, 0], np.int32))
    pos = jnp.asarray(np.array([7, 8, 15, 16], np.int32))  # block edges
    a = paged_attention(q[:4], kp, vp, slots, pos, bt, impl="xla")
    b = paged_attention(q[:4], kp, vp, slots, pos, bt, impl="pallas")
    np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                               rtol=2e-5, atol=2e-5)


def test_tiled_prefill_kernel_matches_xla():
    """The tiled prefill kernel (interpret mode on CPU) is exact vs the XLA
    fallback, including tile padding, block-edge positions and a pad tile."""
    from deepspeed_tpu.ops.attention import ragged_prefill_attention

    rng = np.random.default_rng(4)
    CT, Hq, Hkv, D, NB, BS, MB = 8, 4, 2, 16, 16, 8, 4
    # 4 tiles: seq0 chunk of 14 tokens (tiles 0-1, pos 5..18), seq1 chunk of
    # 6 tokens (tile 2, pos 0..5), tile 3 all-pad
    q = jnp.asarray(rng.normal(size=(4 * CT, Hq, D)).astype(np.float32))
    kp = jnp.asarray(rng.normal(size=(NB, BS, Hkv, D)).astype(np.float32))
    vp = jnp.asarray(rng.normal(size=(NB, BS, Hkv, D)).astype(np.float32))
    bt = np.zeros((3, MB), np.int32)
    bt[0] = [3, 5, 7, 11]
    bt[1] = [2, 9, 1, 0]
    ts = jnp.asarray(np.array([0, 0, 1, 2], np.int32))
    tp = jnp.asarray(np.array([5, 13, 0, 0], np.int32))
    tv = jnp.asarray(np.array([8, 6, 6, 0], np.int32))
    out_x = ragged_prefill_attention(q, kp, vp, ts, tp, tv, jnp.asarray(bt),
                                     CT, impl="xla")
    out_p = ragged_prefill_attention(q, kp, vp, ts, tp, tv, jnp.asarray(bt),
                                     CT, impl="pallas")
    # compare valid rows only (pad rows are unspecified garbage/zeros)
    for c in range(4):
        v = int(tv[c])
        a = np.asarray(out_x)[c * CT:c * CT + v]
        b = np.asarray(out_p)[c * CT:c * CT + v]
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=2e-5, err_msg=f"tile {c}")


def test_ragged_engine_uses_dispatcher():
    """End-to-end ragged generation still exact after the dispatcher swap."""
    from deepspeed_tpu.comm.topology import reset_topology
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
    from deepspeed_tpu.models import llama

    reset_topology()
    cfg = llama.LlamaConfig.tiny(256)
    eng = RaggedInferenceEngine(
        lambda ctx: llama.build(cfg, ctx=ctx),
        RaggedConfig(max_seqs=4, num_blocks=64, block_size=16,
                     max_tokens_per_step=32),
        dtype=jnp.float32, seed=3)
    eng.put("a", list(range(9)), max_new_tokens=5)
    out = eng.generate_all()
    assert len(out["a"]) == 5
