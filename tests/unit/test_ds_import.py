"""DeepSpeed-checkpoint importer (reference ``checkpoint/ds_to_universal.py
:121 extract_zero_shards`` / ``utils/zero_to_fp32.py``): synthetic
reference-layout checkpoints round-trip into this repo's pytrees and
universal fragment format."""

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint.ds_import import (  # noqa: E402
    import_checkpoint,
    read_zero_checkpoint,
    to_repo_params,
)
from deepspeed_tpu.models import llama  # noqa: E402

CFG = llama.LlamaConfig(
    vocab_size=64, hidden_size=16, intermediate_size=32, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=32, tie_embeddings=False)


def _torch_named(params):
    """Our pytree -> the torch/HF naming a DeepSpeed run would save
    (inverse of the llama ingestion recipes)."""
    named = {}
    named["module.model.embed_tokens.weight"] = params["embed"]
    named["module.model.norm.weight"] = params["final_norm"]
    named["module.lm_head.weight"] = params["lm_head"].T
    L = params["layers"]
    for i in range(CFG.num_layers):
        p = f"module.model.layers.{i}."
        named[p + "input_layernorm.weight"] = L["attn_norm"][i]
        named[p + "post_attention_layernorm.weight"] = L["mlp_norm"][i]
        for ours, theirs in (("wq", "self_attn.q_proj"),
                             ("wk", "self_attn.k_proj"),
                             ("wv", "self_attn.v_proj"),
                             ("wo", "self_attn.o_proj"),
                             ("w_gate", "mlp.gate_proj"),
                             ("w_up", "mlp.up_proj"),
                             ("w_down", "mlp.down_proj")):
            named[p + theirs + ".weight"] = np.asarray(L[ours][i]).T
    return {k: np.asarray(v, np.float32) for k, v in named.items()}


def _write_ds_checkpoint(ckpt_dir, named, stage, world=2, step=7):
    """Emit the reference on-disk layout for the given ZeRO stage."""
    os.makedirs(ckpt_dir, exist_ok=True)
    shapes = {k: tuple(v.shape) for k, v in named.items()}
    order = list(named)
    flat = np.concatenate([named[k].reshape(-1) for k in order])
    exp_avg = flat * 0.25
    exp_avg_sq = np.abs(flat) * 0.5

    def rank_slices(vec):
        if stage == 3:
            # per-param shards: each rank holds ceil(numel/world) of EVERY
            # param, concatenated in order
            per_rank = [[] for _ in range(world)]
            off = 0
            for k in order:
                n = named[k].size
                shard = -(-n // world)
                seg = np.zeros(shard * world, np.float32)
                seg[:n] = vec[off:off + n]
                for r in range(world):
                    per_rank[r].append(seg[r * shard:(r + 1) * shard])
                off += n
            return [np.concatenate(p) for p in per_rank]
        pad = (-flat.size) % world
        v = np.pad(vec, (0, pad))
        return np.split(v, world)

    model_name = ("zero_pp_rank_0_mp_rank_00_model_states.pt" if stage == 3
                  else "mp_rank_00_model_states.pt")
    torch.save({"module": {k: torch.tensor(v) for k, v in named.items()},
                "param_shapes": [shapes]},
               os.path.join(ckpt_dir, model_name))
    fp32 = rank_slices(flat)
    ms = rank_slices(exp_avg)
    vs = rank_slices(exp_avg_sq)
    key = "fp32_flat_groups" if stage == 3 else \
        "single_partition_of_fp32_groups"
    for r in range(world):
        osd = {
            key: [torch.tensor(fp32[r])],
            "partition_count": world,
            "zero_stage": stage,
            "base_optimizer_state": {
                "state": {0: {"exp_avg": torch.tensor(ms[r]),
                              "exp_avg_sq": torch.tensor(vs[r]),
                              "step": torch.tensor(step)}}},
        }
        torch.save({"optimizer_state_dict": osd,
                    "ds_config": {"zero_optimization": {"stage": stage}}},
                   os.path.join(
                       ckpt_dir,
                       f"zero_pp_rank_{r}_mp_rank_00_optim_states.pt"))
    return exp_avg


@pytest.mark.parametrize("stage", [2, 3])
def test_round_trip(tmp_path, stage):
    import jax

    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(np.asarray, params)
    named = _torch_named(params)
    _write_ds_checkpoint(str(tmp_path), named, stage=stage)

    got_named, moments, meta = read_zero_checkpoint(str(tmp_path))
    assert meta == {"step": 7, "zero_stage": stage, "world_size": 2,
                    "missing_moments": []}
    for k, v in named.items():
        np.testing.assert_allclose(got_named[k], v, rtol=1e-6)

    got = to_repo_params(got_named, "llama", CFG)
    flat_a = jax.tree_util.tree_leaves(got)
    flat_b = jax.tree_util.tree_leaves(params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    # moments map through the same recipes, param-congruent
    mu = to_repo_params(moments["exp_avg"], "llama", CFG)
    np.testing.assert_allclose(
        jax.tree_util.tree_leaves(mu)[0],
        0.25 * np.asarray(jax.tree_util.tree_leaves(params)[0]), rtol=1e-6)


def test_import_to_engine(tmp_path):
    """import_checkpoint writes this repo's universal format; a training
    engine resumes from it (migration path end to end)."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.comm.topology import reset_topology

    params = llama.init_params(CFG, jax.random.PRNGKey(1))
    params = jax.tree_util.tree_map(np.asarray, params)
    _write_ds_checkpoint(str(tmp_path / "ds"), _torch_named(params), stage=2)

    got, moments, meta = import_checkpoint(
        str(tmp_path / "ds"), "llama", CFG, out_dir=str(tmp_path / "uni"))

    reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(CFG, ctx=ctx),
        config={
            "train_micro_batch_size_per_device": 2,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "mesh": {"data": 8},
            "seed": 5,
        }, seed=5)
    engine.load_checkpoint(str(tmp_path / "uni"),
                           load_optimizer_states=False)
    assert engine.global_steps == 7
    for a, b in zip(jax.tree_util.tree_leaves(engine.params),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6)
    rng = np.random.default_rng(0)
    loss = float(engine.train_batch(
        {"input_ids": rng.integers(0, 64, (16, 8), dtype=np.int32)}))
    assert np.isfinite(loss)


def test_missing_moments_raise_unless_allowed(tmp_path):
    """Stripped optimizer state must not silently zero-fill Adam moments:
    default raises, allow_missing_moments=True warns + records in meta."""
    import jax

    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(np.asarray, params)
    _write_ds_checkpoint(str(tmp_path), _torch_named(params), stage=2)
    for f in os.listdir(str(tmp_path)):
        if not f.endswith("_optim_states.pt"):
            continue
        p = os.path.join(str(tmp_path), f)
        sd = torch.load(p, map_location="cpu", weights_only=False)
        st = sd["optimizer_state_dict"]["base_optimizer_state"]["state"][0]
        del st["exp_avg"], st["exp_avg_sq"]
        torch.save(sd, p)

    with pytest.raises(ValueError, match="exp_avg"):
        read_zero_checkpoint(str(tmp_path))

    named, moments, meta = read_zero_checkpoint(
        str(tmp_path), allow_missing_moments=True)
    assert meta["missing_moments"] == [(0, 0), (1, 0)]
    for v in moments["exp_avg"].values():
        assert not np.any(v)


def test_ambiguous_optim_file_order_raises(tmp_path):
    """>1 optim-state file without a parseable dp rank: glob order would
    silently scramble the partition concatenation — refuse instead."""
    import jax

    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(np.asarray, params)
    _write_ds_checkpoint(str(tmp_path), _torch_named(params), stage=2)
    for i, f in enumerate(sorted(os.listdir(str(tmp_path)))):
        if f.endswith("_optim_states.pt"):
            os.rename(os.path.join(str(tmp_path), f),
                      os.path.join(str(tmp_path),
                                   f"shard{i}_optim_states.pt"))
    with pytest.raises(ValueError, match="dp rank"):
        read_zero_checkpoint(str(tmp_path))
