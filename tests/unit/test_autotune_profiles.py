"""Tuned-profile persistence + the KnobSpace search driver (pure host-side:
no engines are built — probe legs are faked, so everything runs in-process).

Covers the docs/AUTOTUNING.md contracts: content-key stability across
restarts, stale-profile rejection when the model fingerprint or device
count changes, config-file-wins precedence on both engines, torn-file
tolerance (the PR 9 temp+fsync+os.replace commit protocol), headroom
pruning before compile, and the parity/census hard gates."""

import json
import os

import pytest

from deepspeed_tpu.autotuning import (
    DEFAULT_SPACE,
    SERVE,
    TRAIN,
    Knob,
    KnobSearch,
    KnobSpace,
    ModelInfo,
    profiles,
)
from deepspeed_tpu.config.config import Config, load_config

INFO = ModelInfo(num_params=600_000, hidden_size=128, num_layers=2)
FP = profiles.model_fingerprint(INFO)
TOPO = "tpu:8:TPU v4"


class TestProfilePersistence:
    def _save(self, d, **kw):
        args = dict(subsystem=TRAIN, fingerprint=FP, topology=TOPO,
                    workload="default",
                    overrides={"train_micro_batch_size_per_device": 8},
                    score=2.0, baseline_score=1.0)
        args.update(kw)
        return profiles.save_profile(str(d), **args)

    def test_content_key_stable_across_restarts(self):
        k1 = profiles.profile_key(FP, TOPO, "default", TRAIN)
        k2 = profiles.profile_key(FP, TOPO, "default", TRAIN)
        assert k1 == k2
        # any identity component changing moves the key
        assert profiles.profile_key("p1-h2-l3", TOPO, "default", TRAIN) != k1
        assert profiles.profile_key(FP, "tpu:16:TPU v4", "default", TRAIN) != k1
        assert profiles.profile_key(FP, TOPO, "long-context", TRAIN) != k1
        assert profiles.profile_key(FP, TOPO, "default", SERVE) != k1

    def test_round_trip(self, tmp_path):
        path = self._save(tmp_path)
        assert os.path.exists(path)
        prof = profiles.load_profile(str(tmp_path), subsystem=TRAIN,
                                     fingerprint=FP, topology=TOPO)
        assert prof is not None
        assert prof["overrides"] == {"train_micro_batch_size_per_device": 8}
        assert prof["score"] == 2.0 and prof["baseline_score"] == 1.0

    def test_stale_rejected_on_model_change(self, tmp_path):
        self._save(tmp_path)
        assert profiles.load_profile(
            str(tmp_path), subsystem=TRAIN, fingerprint="p999-h1-l1",
            topology=TOPO) is None

    def test_stale_rejected_on_device_count_change(self, tmp_path):
        self._save(tmp_path)
        assert profiles.load_profile(
            str(tmp_path), subsystem=TRAIN, fingerprint=FP,
            topology="tpu:16:TPU v4") is None

    def test_tampered_file_rejected(self, tmp_path):
        """A file copied to the right key but recording a different
        identity inside (rsync'd between machines) is rejected."""
        path = self._save(tmp_path)
        prof = json.load(open(path))
        prof["fingerprint"] = "p999-h1-l1"
        with open(path, "w") as f:
            json.dump(prof, f)
        assert profiles.load_profile(str(tmp_path), subsystem=TRAIN,
                                     fingerprint=FP, topology=TOPO) is None

    def test_torn_file_tolerated(self, tmp_path):
        path = self._save(tmp_path)
        full = open(path).read()
        with open(path, "w") as f:
            f.write(full[: len(full) // 2])  # simulated torn write
        assert profiles.load_profile(str(tmp_path), subsystem=TRAIN,
                                     fingerprint=FP, topology=TOPO) is None

    def test_atomic_commit_leaves_no_temp_files(self, tmp_path):
        self._save(tmp_path)
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]

    def test_knobspace_change_invalidates(self, tmp_path):
        self._save(tmp_path)
        other = KnobSpace(version=DEFAULT_SPACE.version + 1)
        other.register(Knob("train_micro_batch_size_per_device", TRAIN,
                            (1, 2), 2))
        assert profiles.load_profile(
            str(tmp_path), subsystem=TRAIN, fingerprint=FP, topology=TOPO,
            space=other) is None


class TestPrecedence:
    PROF = {"overrides": {
        "zero_optimization.stage": 2,
        "train_micro_batch_size_per_device": 8,
        "activation_checkpointing.enabled": True,
    }}

    def test_config_file_wins_over_tuned(self):
        raw = {"zero_optimization": {"stage": 3},
               "train_micro_batch_size_per_device": 2}
        cfg = load_config(raw)
        rec = profiles.apply_train_profile(cfg, raw, self.PROF)
        # explicitly-written keys keep their config-file values
        assert cfg.zero_optimization.stage == 3
        assert cfg.train_micro_batch_size_per_device == 2
        # the unwritten knob is filled from the profile
        assert cfg.activation_checkpointing.enabled is True
        assert "zero_optimization.stage" in rec["skipped"]
        assert "activation_checkpointing.enabled" in rec["applied"]

    def test_unwritten_knobs_filled(self):
        raw = {}
        cfg = load_config(raw)
        rec = profiles.apply_train_profile(cfg, raw, self.PROF)
        assert cfg.zero_optimization.stage == 2
        assert cfg.train_micro_batch_size_per_device == 8
        assert len(rec["applied"]) == 3 and not rec["skipped"]

    def test_legacy_zero_alias_counts_as_written(self):
        raw = {"zero": {"stage": 1}, "train_batch_size": 4}
        cfg = load_config(raw)
        profiles.apply_train_profile(cfg, raw, self.PROF)
        assert cfg.zero_optimization.stage == 1

    def test_batch_triangle_pin_blocks_tuned_micro_batch(self):
        """A pinned train_batch_size means the tuned micro-batch must not
        silently change gradient accumulation."""
        raw = {"train_batch_size": 64}
        cfg = load_config(raw)
        rec = profiles.apply_train_profile(cfg, raw, self.PROF)
        assert cfg.train_micro_batch_size_per_device is None
        assert "train_micro_batch_size_per_device" in rec["skipped"]

    def test_programmatic_config_default_wins(self):
        """No raw dict (Config built in code): a knob off its dataclass
        default counts as user-written."""
        cfg = Config()
        cfg.zero_optimization.stage = 1
        rec = profiles.apply_train_profile(cfg, None, self.PROF)
        assert cfg.zero_optimization.stage == 1  # user's value kept
        assert cfg.activation_checkpointing.enabled is True
        assert "zero_optimization.stage" in rec["skipped"]

    def test_serving_profile_fills_defaults_only(self):
        from deepspeed_tpu.inference.ragged import RaggedConfig

        rcfg = RaggedConfig(sched_steps=4)  # operator-written
        rec = profiles.apply_serving_profile(
            rcfg, {"overrides": {"sched_steps": 16, "fused_chunk": 8}})
        assert rcfg.sched_steps == 4  # config wins
        assert rcfg.fused_chunk == 8  # still-default field filled
        assert rec["skipped"] == {"sched_steps": 16}
        assert rec["applied"] == {"fused_chunk": 8}


class TestKnobSpace:
    def test_registry_shape(self):
        train = DEFAULT_SPACE.knobs(TRAIN)
        serve = DEFAULT_SPACE.knobs(SERVE)
        assert len(train) >= 5 and len(serve) >= 8
        for k in train + serve:
            assert k.default in k.domain

    def test_trim_and_order(self):
        names = ("activation_checkpointing.enabled",
                 "train_micro_batch_size_per_device")
        got = [k.name for k in DEFAULT_SPACE.knobs(TRAIN, names)]
        assert got == list(names)
        with pytest.raises(KeyError):
            DEFAULT_SPACE.knobs(TRAIN, ("no_such_knob",))

    def test_neighbors_respect_domain_hull(self):
        mb = DEFAULT_SPACE.get("train_micro_batch_size_per_device")
        assert set(mb.neighbors(4)) == {2, 8}
        assert mb.neighbors(16) == [8]  # 32 is past the hull
        guard = DEFAULT_SPACE.get("headroom_guard_fraction")
        assert 0.04 in guard.neighbors(0.02)
        remat = DEFAULT_SPACE.get("activation_checkpointing.enabled")
        assert remat.neighbors(True) == []  # discrete: no neighborhood

    def test_cost_hint_quant_credits_pool_bytes(self):
        q = DEFAULT_SPACE.get("quant")
        assert q.cost_bytes("int8", {"kv_pool_bytes": 1000}) == -500.0


class TestModelInfoShardedUpdate:
    def test_sharded_update_shards_master_and_opt(self):
        p = float(INFO.num_params)
        # stage 0 + sharded update == the ZeRO-1 estimate (master+opt = 12
        # of the 18 bytes/param shard across the data axis)
        assert INFO.state_bytes(0, 8, sharded_update=True) == \
            INFO.state_bytes(1, 8)
        assert INFO.state_bytes(0, 8, sharded_update=True) == \
            p * (6.0 + 12.0 / 8)
        # no shards -> no effect; higher stages already shard >= 12
        assert INFO.state_bytes(0, 1, sharded_update=True) == \
            INFO.state_bytes(0, 1)
        assert INFO.state_bytes(2, 8, sharded_update=True) == \
            INFO.state_bytes(2, 8)
        # positional call signature unchanged (existing callers)
        assert INFO.state_bytes(3, 8) < INFO.state_bytes(1, 8)


def _fake_runner(scores, calls=None, gates=None):
    """Probe runner stub: scores[frozenset(overrides.items())] -> score."""
    def runner(kind, overrides, steps):
        if calls is not None:
            calls.append(dict(overrides))
        key = frozenset(overrides.items())
        out = {"score": scores.get(key, 1.0), "samples_per_sec": 1.0}
        out.update((gates or {}).get(key, {}))
        return out, None
    return runner


class TestKnobSearch:
    MB = "train_micro_batch_size_per_device"
    REMAT = "activation_checkpointing.enabled"

    def test_headroom_prunes_before_probing(self):
        calls = []
        search = KnobSearch(
            TRAIN, model_info=INFO, n_devices=1, seq_len=128,
            knob_names=(self.MB,),
            # mb=8 fits, mb=16 must prune without a probe call
            memory_bytes=(INFO.state_bytes(0, 1)
                          + INFO.activation_bytes(8, 128)) * 1.01 / 0.9,
            probe_runner=_fake_runner({}, calls))
        out = search.tune()
        assert out["pruned"] >= 1
        assert not any(ov.get(self.MB) == 16 for ov in calls)
        pruned = [r for r in search.results if r.skipped]
        assert pruned and pruned[0].overrides[self.MB] == 16
        assert pruned[0].error.startswith("pruned:")

    def test_remat_halves_the_activation_estimate(self):
        est = lambda ov: KnobSearch(  # noqa: E731
            TRAIN, model_info=INFO, n_devices=1,
            seq_len=128)._estimate_bytes(ov)
        assert (est({self.MB: 8, self.REMAT: True})
                == est({self.MB: 8}) - INFO.activation_bytes(8, 128) / 2)

    def test_sharded_update_unlocks_pruned_corner(self):
        """The PR 18 fix: grad_overlap.sharded_update shrinks the stage-0
        state estimate so the pruner admits configs that actually fit."""
        ov_dense = {self.MB: 2}
        ov_sharded = {self.MB: 2,
                      "zero_optimization.grad_overlap.enabled": True,
                      "zero_optimization.grad_overlap.sharded_update": True}
        search = KnobSearch(TRAIN, model_info=INFO, n_devices=8, seq_len=128)
        assert (search._estimate_bytes(ov_sharded)
                < search._estimate_bytes(ov_dense))
        limit = search._estimate_bytes(ov_sharded) * 1.01 / 0.9
        search.memory_bytes = limit
        assert search._prune_reason(ov_dense)
        assert search._prune_reason(ov_sharded) is None

    def test_best_never_below_baseline_and_ascends(self):
        scores = {frozenset(): 1.0,
                  frozenset({(self.MB, 4)}): 2.0,
                  frozenset({(self.MB, 4), (self.REMAT, True)}): 3.0}
        out = KnobSearch(TRAIN, model_info=INFO, n_devices=1,
                         knob_names=(self.MB, self.REMAT),
                         probe_runner=_fake_runner(scores)).tune()
        assert out["best_overrides"] == {self.MB: 4, self.REMAT: True}
        assert out["best_score"] == 3.0 and out["baseline_score"] == 1.0

    def test_gate_violation_disqualifies(self):
        """A faster config that trips parity or census can never win."""
        key = frozenset({("sched_steps", 16)})
        scores = {frozenset(): 1.0, key: 100.0}
        out = KnobSearch(SERVE, knob_names=("sched_steps",),
                         probe_runner=_fake_runner(
                             scores, gates={key: {"parity_ok": False}})
                         ).tune()
        assert "sched_steps" not in out["best_overrides"]
        assert out["gate_failures"] == 1
        assert out["gate_violations_accepted"] == 0

    def test_winner_persists_and_reloads(self, tmp_path, monkeypatch):
        monkeypatch.setattr(profiles, "current_topology", lambda: TOPO)
        scores = {frozenset({(self.MB, 4)}): 5.0}
        out = KnobSearch(TRAIN, model_info=INFO, n_devices=1,
                         knob_names=(self.MB,),
                         probe_runner=_fake_runner(scores),
                         profile_dir=str(tmp_path)).tune()
        assert out["profile_path"] and os.path.exists(out["profile_path"])
        prof = profiles.load_profile(str(tmp_path), subsystem=TRAIN,
                                     fingerprint=FP, topology=TOPO)
        assert prof["overrides"] == {self.MB: 4}
        assert prof["score"] == 5.0

    def test_counters_bump_when_telemetry_on(self):
        from deepspeed_tpu import telemetry

        telemetry.configure(enabled=True, hbm_watermarks=False)
        try:
            KnobSearch(
                TRAIN, model_info=INFO, n_devices=1, knob_names=(self.MB,),
                memory_bytes=(INFO.state_bytes(0, 1)
                              + INFO.activation_bytes(8, 128)) * 1.01 / 0.9,
                probe_runner=_fake_runner({})).tune()
            snap = telemetry.snapshot()["metrics"]
            trials = snap["autotune_trials_total"]["series"][0]["value"]
            pruned = snap["autotune_pruned_total"]["series"][0]["value"]
            assert trials >= 2 and pruned >= 1
        finally:
            telemetry.configure(enabled=False)
