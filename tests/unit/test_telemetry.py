"""Telemetry subsystem: registry semantics, exporters, engine wiring
(docs/OBSERVABILITY.md; tentpole of the observability PR)."""

import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.telemetry import TELEMETRY
from deepspeed_tpu.telemetry.registry import MetricsRegistry


# ------------------------------------------------------------------ registry
class TestRegistry:
    def test_counter_semantics(self):
        r = MetricsRegistry()
        c = r.counter("requests_total", "reqs")
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5
        c.inc(1, op="all_reduce")
        assert c.value(op="all_reduce") == 1.0
        assert c.value() == 3.5  # label sets are independent series
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_get_or_create_and_kind_conflict(self):
        r = MetricsRegistry()
        assert r.counter("m") is r.counter("m")
        with pytest.raises(TypeError):
            r.gauge("m")

    def test_gauge_set_inc_dec(self):
        r = MetricsRegistry()
        g = r.gauge("depth")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6.0

    def test_histogram_buckets_cumulative(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 5
        assert h.sum() == pytest.approx(56.05)
        (series,) = h.snapshot()
        assert series["buckets"]["0.1"] == 1
        assert series["buckets"]["1.0"] == 3
        assert series["buckets"]["10.0"] == 4
        assert series["buckets"]["+Inf"] == 5

    def test_name_sanitization(self):
        r = MetricsRegistry()
        c = r.counter("train/step.count")
        assert c.name == "train_step_count"
        assert c is r.counter("train_step_count")


# ------------------------------------------------------------------ exposition
class TestPrometheus:
    def test_text_exposition_format(self):
        r = MetricsRegistry()
        r.counter("reqs_total", "total requests").inc(3, op="all_reduce")
        r.gauge("depth", "queue depth").set(2)
        r.histogram("lat_seconds", "latency", buckets=(1.0,)).observe(0.5)
        text = r.render_prometheus()
        assert "# HELP reqs_total total requests" in text
        assert "# TYPE reqs_total counter" in text
        assert 'reqs_total{op="all_reduce"} 3' in text
        assert "# TYPE depth gauge" in text and "depth 2" in text
        assert 'lat_seconds_bucket{le="1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 1' in text
        assert "lat_seconds_sum 0.5" in text
        assert "lat_seconds_count 1" in text
        assert text.endswith("\n")

    def test_http_endpoint_serves_registry(self):
        TELEMETRY.configure(enabled=True,
                            prometheus={"enabled": True, "port": 0})
        TELEMETRY.counter("served_total", "served").inc(7)
        port = TELEMETRY.prometheus_port
        assert port  # port 0 bound an ephemeral port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            body = resp.read().decode("utf-8")
            ctype = resp.headers["Content-Type"]
        assert ctype.startswith("text/plain; version=0.0.4")
        assert "served_total 7" in body


# ------------------------------------------------------------------ JSONL sink
def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestJsonl:
    def test_event_span_snapshot_roundtrip(self, tmp_path):
        path = tmp_path / "t.jsonl"
        TELEMETRY.configure(enabled=True, jsonl_path=str(path))
        TELEMETRY.event("unit/hello", step=3, detail="x")
        TELEMETRY.emit_span("unit/work", 0.25, step=3, phase="fwd")
        TELEMETRY.counter("unit_total").inc(2)
        TELEMETRY.close()
        records = _read_jsonl(path)
        by_type = {r["type"]: r for r in records}
        ev = next(r for r in records if r["name"] == "unit/hello")
        assert ev["step"] == 3 and ev["detail"] == "x" and "ts" in ev
        sp = next(r for r in records if r["name"] == "unit/work")
        assert sp["type"] == "span" and sp["dur_s"] == 0.25
        # close() persists the final registry state into the event log
        snap = by_type["snapshot"]
        series = snap["metrics"]["unit_total"]["series"]
        assert series[0]["value"] == 2
        # spans also feed the span_seconds histogram
        assert "span_seconds" in snap["metrics"]

    def test_disabled_is_noop(self, tmp_path):
        path = tmp_path / "none.jsonl"
        assert not TELEMETRY.enabled  # pristine default
        TELEMETRY.event("unit/dropped")
        TELEMETRY.emit_span("unit/dropped", 1.0)
        with TELEMETRY.span("unit/dropped"):
            pass
        TELEMETRY.sample_memory(step=0)
        assert not path.exists()
        assert "unit" not in str(TELEMETRY.snapshot()["metrics"])

    def test_configure_from_config_dataclass(self, tmp_path):
        from deepspeed_tpu.config.config import TelemetryConfig

        cfg = TelemetryConfig.from_dict(
            {"enabled": True, "jsonl_path": str(tmp_path / "c.jsonl"),
             "flush_interval_events": 1})
        TELEMETRY.configure(cfg)
        TELEMETRY.event("unit/cfg")
        records = _read_jsonl(tmp_path / "c.jsonl")
        assert any(r["name"] == "unit/cfg" for r in records)


# ------------------------------------------------------------------ satellites
class TestCSVMonitorHandles:
    def test_handles_cached_per_tag(self, tmp_path):
        from deepspeed_tpu.monitor.monitor import CSVMonitor

        m = CSVMonitor({"enabled": True, "output_path": str(tmp_path),
                        "job_name": "job"})
        m.write_events([("Train/loss", 1.0, 0), ("Train/lr", 0.1, 0)])
        m.write_events([("Train/loss", 0.5, 1)])
        assert len(m._files) == 2  # one append handle per tag, reused
        m.close()
        assert not m._files
        lines = (tmp_path / "job" / "Train_loss.csv").read_text().splitlines()
        assert lines[0].startswith("step") and len(lines) == 3


class TestCommsSummary:
    def test_eager_rows_carry_bandwidth(self):
        from deepspeed_tpu.utils.comms_logging import CommsLogger

        log = CommsLogger(enabled=True)
        log.append_eager("all_reduce", 1 << 20, 0.001, n_ranks=8)
        log.append_eager("all_reduce", 1 << 20, 0.003, n_ranks=8)
        text = log.log_summary()
        row = next(l for l in text.splitlines() if "all_reduce" in l)
        assert "algbw=" in row and "busbw=" in row
        assert "calls=2" in row

    def test_single_process_straggler_message(self):
        from deepspeed_tpu.utils.comms_logging import CommsLogger

        text = CommsLogger(enabled=True).log_summary(show_straggler=True)
        assert "single process" in text

    def test_straggler_warn_ratio_validated(self):
        from deepspeed_tpu.config.config import CommsLoggerConfig, ConfigError

        assert CommsLoggerConfig.from_dict(
            {"straggler_warn_ratio": 3.0}).straggler_warn_ratio == 3.0
        with pytest.raises(ConfigError):
            CommsLoggerConfig.from_dict({"straggler_warn_ratio": 0.5})

    def test_ledger_bridges_into_registry_even_when_logger_disabled(self):
        from deepspeed_tpu.utils.comms_logging import CommsLogger

        TELEMETRY.configure(enabled=True)
        log = CommsLogger(enabled=False)
        log.append_traced("all_gather", 256, "data", 8)
        log.append_eager("barrier", 0, 0.002, n_ranks=2)
        assert TELEMETRY.counter("comm_traced_bytes_total").value(
            op="all_gather") == 256
        assert TELEMETRY.counter("comm_eager_calls_total").value(
            op="barrier") == 1
        assert TELEMETRY.histogram("comm_eager_latency_seconds").count(
            op="barrier") == 1
        assert not log.traced  # disabled logger still keeps no ledger


# ------------------------------------------------------------------ engines
def test_train_steps_emit_spans_and_watermarks(tmp_path):
    reset_topology()
    path = tmp_path / "train.jsonl"
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(256), ctx=ctx),
        config={
            "train_micro_batch_size_per_device": 2,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "sequence_length": 16,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "mesh": {"data": 8},
            "telemetry": {"enabled": True, "jsonl_path": str(path),
                          "flush_interval_events": 1},
        },
    )
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 256, (16, 16), dtype=np.int32)}
    for _ in range(3):
        engine.train_batch(batch)
    engine.destroy()
    engine.destroy()  # idempotent

    records = _read_jsonl(path)
    steps = [r for r in records if r["type"] == "span"
             and r["name"] == "train/step"]
    assert len(steps) >= 2
    assert all("lr" in s and "grad_norm" in s and s["dur_s"] >= 0
               for s in steps)
    hbm = [r for r in records if r["type"] == "gauge"
           and r["name"] == "hbm_watermark"]
    assert hbm and hbm[0]["bytes_in_use"] > 0
    assert TELEMETRY.counter("train_steps_total").value() >= 3
    # the static comms plan (implicit GSPMD grad sync) lands in the registry
    assert TELEMETRY.counter("comm_traced_calls_total").value(
        op="all_reduce") >= 1
    # analytic flops fallback wired through to the throughput timer + gauge
    assert engine.tput_timer.flops_per_sample > 0
    assert TELEMETRY.gauge("train_flops_per_sample").value() > 0
    assert engine.tput_timer.tflops() > 0


def test_ragged_requests_emit_spans(tmp_path):
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine

    reset_topology()
    path = tmp_path / "ragged.jsonl"
    TELEMETRY.configure(enabled=True, jsonl_path=str(path),
                        flush_interval_events=1)
    eng = RaggedInferenceEngine(
        lambda ctx: llama.build(llama.LlamaConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
        ), ctx=ctx),
        RaggedConfig(max_tokens_per_step=16, max_seqs=3, block_size=4,
                     num_blocks=49, max_blocks_per_seq=16),
        dtype=jnp.float32, seed=0)
    rng = np.random.default_rng(0)
    eng.put("a", list(rng.integers(0, 97, 5)), max_new_tokens=4)
    eng.put("b", list(rng.integers(0, 97, 9)), max_new_tokens=4)
    out = eng.generate_all()
    assert len(out["a"]) == 4 and len(out["b"]) == 4
    TELEMETRY.close()

    records = _read_jsonl(path)
    spans = {r["uid"]: r for r in records if r["type"] == "span"
             and r["name"] == "inference/request"}
    assert set(spans) == {"a", "b"}
    for span in spans.values():
        assert span["ttft_s"] >= 0 and span["queue_wait_s"] >= 0
        assert span["decode_latency_s"] >= 0  # 4 tokens -> inter-token mean
        assert span["new_tokens"] == 4
    snap = next(r for r in records if r["type"] == "snapshot")
    metrics = snap["metrics"]
    assert metrics["inference_requests_total"]["series"][0]["value"] == 2
    assert metrics["inference_tokens_generated_total"]["series"][0]["value"] == 8
    assert "inference_ttft_seconds" in metrics
    assert "kv_page_occupancy" in metrics
