"""Model injection glue: HF dir -> engines (reference ``module_inject``
kernel-injection + ``tp_model_init`` surface)."""

import numpy as np
import pytest

from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.module_inject import (
    init_inference_from_hf,
    replace_policy_exists,
    tp_model_init_from_hf,
)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
# on the 1-core CI box torch's thread pool can starve XLA's collective
# rendezvous threads (observed as a stuck 2-participant all-reduce)
torch.set_num_threads(1)


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=97, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128)
    d = str(tmp_path_factory.mktemp("mi") / "hf")
    transformers.LlamaForCausalLM(cfg).eval().save_pretrained(
        d, safe_serialization=True)
    return d


def test_implicit_mesh_honors_existing_topology():
    """A config WITHOUT a mesh section must reuse a pre-built topology; an
    explicit conflicting mesh section rebuilds it."""
    import deepspeed_tpu
    from deepspeed_tpu.comm.comm import init_distributed
    from deepspeed_tpu.config.config import Config, MeshConfig
    from deepspeed_tpu.models import llama

    reset_topology()
    init_distributed(MeshConfig(data=2, fsdp=4))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(97), ctx=ctx),
        config={"train_micro_batch_size_per_device": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}})
    assert dict(engine.topo.sizes)["fsdp"] == 4  # topology honored
    # explicit conflicting mesh -> rebuild
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(97), ctx=ctx),
        config={"train_micro_batch_size_per_device": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "mesh": {"data": 8}})
    assert dict(engine2.topo.sizes)["fsdp"] == 1
    assert Config.from_dict({"train_micro_batch_size_per_device": 1}
                            ).mesh.is_explicit is False


def test_replace_policy_exists(hf_dir, tmp_path):
    assert replace_policy_exists(hf_dir)
    assert not replace_policy_exists(str(tmp_path))  # no config.json


def test_init_inference_from_hf(hf_dir):
    import jax.numpy as jnp

    reset_topology()
    eng = init_inference_from_hf(hf_dir, dtype=jnp.float32)
    ids = np.random.default_rng(0).integers(0, 97, (1, 12)).astype(np.int32)
    out = eng.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 16)


def test_init_inference_from_hf_ragged_woq(hf_dir):
    import jax.numpy as jnp

    from deepspeed_tpu.inference.ragged import RaggedConfig

    reset_topology()
    eng = init_inference_from_hf(
        hf_dir, ragged=True, dtype=jnp.float32, quantize_bits=8,
        ragged_config=RaggedConfig(max_seqs=2, num_blocks=32, block_size=16,
                                   max_tokens_per_step=16))
    eng.put("r", list(range(6)), max_new_tokens=3)
    out = eng.generate_all()
    assert len(out["r"]) == 3


_TP_TRAIN_SCRIPT = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from deepspeed_tpu.module_inject import tp_model_init_from_hf

engine, _, _, _ = tp_model_init_from_hf({hf!r}, config={{
    "train_micro_batch_size_per_device": 1,
    "optimizer": {{"type": "adamw", "params": {{"lr": 1e-3}}}},
    "zero_optimization": {{"stage": 2}},
    "mesh": {{"data": 4, "tensor": 2}},
}})
batch = {{"input_ids": np.random.default_rng(0).integers(
    0, 97, (4, 16)).astype(np.int32)}}
losses = [float(engine.train_batch(batch)) for _ in range(3)]
assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses
assert "tensor" in str(engine.params["layers"]["wq"].sharding.spec)
print("TP-TRAIN-OK", losses[0], losses[-1])
"""


def test_tp_model_init_from_hf(hf_dir):
    """Runs in a fresh subprocess (the reference DistributedExec pattern,
    ``tests/unit/common.py:139``): inside a shared pytest process this box's
    thread scheduling can starve XLA's 2-participant collective rendezvous
    (observed stuck cross-module all-reduce), which process isolation
    sidesteps deterministically."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    script = _TP_TRAIN_SCRIPT.format(repo=repo, hf=hf_dir)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "TP-TRAIN-OK" in proc.stdout
