"""Ragged/continuous-batching serving for the non-Llama families (the
round-4 gap: only llama set ragged_forward_fn). Mixtral exercises MoE over a
paged cache — per-token top-k routing at decode (reference
``inference/v2/model_implementations/mixtral`` + ``ragged_ops`` MoE
gather/scatter); GPT-2 exercises learned positional embeddings riding the
ragged per-token positions."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import gpt2, mixtral

MIX = mixtral.MixtralConfig.tiny(89)
GPT = gpt2.GPT2Config.tiny(89)


def _build(name):
    if name == "mixtral":
        return lambda ctx: mixtral.build(MIX, ctx=ctx)
    return lambda ctx: gpt2.build(GPT, ctx=ctx)


def _prompts(n=4, seed=3):
    rng = np.random.default_rng(seed)
    return {i: list(rng.integers(0, 89, (int(rng.integers(3, 12)),)))
            for i in range(n)}


def _dense_reference(name, prompts, max_new):
    eng = InferenceEngine(_build(name), dtype=jnp.float32, seed=0)
    out = {}
    for uid, p in prompts.items():
        full = eng.generate(np.asarray(p)[None], max_new_tokens=max_new)
        out[uid] = list(np.asarray(full[0, len(p):]))
    return out


def _ragged(name, fused=0, tile=0):
    return RaggedInferenceEngine(
        model=_build(name), dtype=jnp.float32, seed=0,
        ragged_config=RaggedConfig(
            max_tokens_per_step=16, max_seqs=3, block_size=4,
            num_blocks=49, max_blocks_per_seq=16,
            fused_chunk=fused, prefill_tile=tile))


@pytest.mark.parametrize("name", ["mixtral", "gpt2"])
class TestRaggedFamilies:
    def test_greedy_parity_vs_dense(self, name):
        """Continuous batching at mixed lengths must reproduce the dense
        engine's greedy continuations exactly (same weights, fp32)."""
        prompts = _prompts()
        want = _dense_reference(name, prompts, max_new=8)
        eng = _ragged(name)
        for uid, p in prompts.items():
            eng.put(uid, p, max_new_tokens=8)
        assert eng.generate_all() == want

    def test_fused_pipeline_parity(self, name):
        """The fused mixed-chunk pipeline serves the family too (device-fed
        multi-step decode over the paged cache, MoE routing inside the
        scan for mixtral)."""
        prompts = _prompts(5, seed=11)
        legacy = _ragged(name)
        fused = _ragged(name, fused=4)
        for uid, p in prompts.items():
            legacy.put(uid, p, max_new_tokens=7)
            fused.put(uid, p, max_new_tokens=7)
        assert fused.generate_all() == legacy.generate_all()

    def test_tiled_prefill_parity(self, name):
        prompts = _prompts(4, seed=7)
        flat = _ragged(name)
        tiled = _ragged(name, tile=4)
        for uid, p in prompts.items():
            flat.put(uid, p, max_new_tokens=5)
            tiled.put(uid, p, max_new_tokens=5)
        assert flat.generate_all() == tiled.generate_all()


def test_mixtral_decode_routing_is_per_token():
    """Decode tokens of DIFFERENT sequences in one mixed batch must route
    independently: serving two different prompts together equals serving
    them alone (no cross-request routing contamination)."""
    prompts = _prompts(3, seed=23)
    solo = {}
    for uid, p in prompts.items():
        eng = _ragged("mixtral")
        eng.put(uid, p, max_new_tokens=6)
        solo.update(eng.generate_all())
    together = _ragged("mixtral")
    for uid, p in prompts.items():
        together.put(uid, p, max_new_tokens=6)
    assert together.generate_all() == solo
