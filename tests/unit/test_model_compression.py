"""PLD + eigenvalue probe + compression-aware training (reference
``runtime/progressive_layer_drop.py``, ``runtime/eigenvalue.py``,
``deepspeed/compression/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.compression import (
    CompressionScheduler,
    fake_quantize,
    head_prune_mask,
    magnitude_prune_mask,
    row_prune_mask,
)
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime.progressive_layer_drop import (
    ProgressiveLayerDrop,
    pld_theta,
)

VOCAB = 256


# ------------------------------------------------------------------ PLD
def test_pld_schedule_matches_reference_curve():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta() == 1.0
    pld.update_state(0)
    assert pld.get_theta() == pytest.approx(1.0)
    pld.update_state(1000)
    # (1-0.5)*exp(-10)+0.5 ~ 0.50002
    assert pld.get_theta() == pytest.approx(0.5, abs=1e-3)
    # jittable twin agrees
    t = float(pld_theta(jnp.int32(1000), 0.5, 0.01))
    assert t == pytest.approx(pld.get_theta(), rel=1e-5)
    assert pld.get_state()["progressive_layer_drop"] is True


def test_pld_training_runs_and_drops():
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.05},
        "mesh": {"data": 8},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, VOCAB, (16, 16), dtype=np.int32)}
    losses = [float(engine.train_batch(batch)) for _ in range(8)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    with pytest.raises(NotImplementedError):
        engine.backward(batch)


# ------------------------------------------------------------------ eigenvalue
def test_eigenvalue_quadratic_form():
    """On a pure quadratic loss the Hessian is known: L = sum(a * w^2)
    has top eigenvalue 2*max(a) per block."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

    a = jnp.stack([jnp.array([1.0, 3.0]), jnp.array([5.0, 2.0])])  # [2 blocks, 2]

    def loss_fn(params, batch, rng=None):
        return jnp.sum(a * jnp.square(params["layers"]))

    params = {"layers": jnp.ones((2, 2))}
    probe = Eigenvalue(max_iter=50, tol=1e-4, layer_num=2)
    vals = probe.compute_eigenvalue(loss_fn, params, {}, jax.random.PRNGKey(0))
    # raw eigenvalues 6 and 10 -> post-processed to [0.6, 1.0]
    assert vals == pytest.approx([0.6, 1.0], rel=1e-2)


def test_eigenvalue_engine_probe():
    reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config={
            "train_micro_batch_size_per_device": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "eigenvalue": {"enabled": True, "max_iter": 3, "tol": 1e-1},
            "mesh": {"data": 8},
        }, seed=11,
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, VOCAB, (16, 16), dtype=np.int32)}
    vals = engine.compute_eigenvalue(batch)
    assert len(vals) == 2  # tiny llama has 2 layers
    assert all(0.0 <= v <= 1.0 for v in vals)


# ------------------------------------------------------------------ compression
def test_fake_quantize_ste():
    w = jnp.linspace(-1, 1, 32).reshape(4, 8)
    q = fake_quantize(w, bits=4)
    # quantized to <= 2^4 distinct levels, and gradient is identity (STE)
    assert len(np.unique(np.asarray(q))) <= 16
    g = jax.grad(lambda w: jnp.sum(fake_quantize(w, 4)))(w)
    np.testing.assert_allclose(np.asarray(g), 1.0)
    # 16-bit quantization is near-lossless
    np.testing.assert_allclose(np.asarray(fake_quantize(w, 16)),
                               np.asarray(w), atol=1e-3)


def test_prune_masks():
    w = jnp.arange(1.0, 17.0).reshape(4, 4)
    m = magnitude_prune_mask(w, ratio=0.5)
    assert float(m.sum()) <= 8
    rm = row_prune_mask(w, ratio=0.5)  # [1, out]
    assert rm.shape == (1, 4) and float(rm.sum()) == 2
    hm = head_prune_mask(w, ratio=0.5, num_heads=2)
    assert hm.shape == (4, 1) and float(hm.sum()) == 2


def test_apply_to_params_stacked_leaves_and_grad_masking():
    """Stacked [L, in, out] leaves must be handled per layer, and pruning
    masks must gate gradients (reference module-wrapper semantics)."""
    sched = CompressionScheduler({
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "sp": {"params": {"dense_ratio": 0.5}, "modules": ["w"]}},
        },
        "head_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {
                "hp": {"params": {"dense_ratio": 0.5}, "modules": ["wo"]}},
        },
    }, num_heads=2)
    params = {
        "layers": {
            "w": jnp.arange(1.0, 33.0).reshape(2, 4, 4),   # stacked 2 layers
            "wo": jnp.arange(1.0, 33.0).reshape(2, 4, 4),  # [L, H*Dh, out]
        }
    }
    out = sched.apply_to_params(params, jnp.int32(1))
    w = np.asarray(out["layers"]["w"])
    # each LAYER loses ~half its entries (per-layer quantile, not global)
    for layer in range(2):
        assert 6 <= (w[layer] == 0).sum() <= 10
    wo = np.asarray(out["layers"]["wo"])
    for layer in range(2):  # one of two heads (rows 0-1 vs 2-3) zeroed
        assert (wo[layer][:2] == 0).all() or (wo[layer][2:] == 0).all()

    # gradients at pruned coordinates must be zero when the mask is applied
    # inside the tape
    def loss(p):
        cp = sched.apply_to_params(p, jnp.int32(1))
        return jnp.sum(jnp.square(cp["layers"]["w"]))

    g = np.asarray(jax.grad(loss)(params)["layers"]["w"])
    assert ((w == 0) <= (g == 0)).all()


def test_scheduler_bits_annealing():
    sched = CompressionScheduler({
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 10},
            "different_groups": {
                "wq1": {"params": {"start_bits": 8, "target_bits": 4,
                                   "quantization_period": 5},
                        "modules": ["w_gate"]},
            },
        },
    })
    g = sched.config.methods["weight_quantization"].groups[0]
    bits = [float(sched.current_bits(g.params, "weight_quantization",
                                     jnp.int32(s))) for s in (0, 12, 17, 40)]
    assert bits == [8.0, 8.0, 7.0, 4.0]
    assert float(sched.is_active("weight_quantization", jnp.int32(5))) == 0.0
    assert float(sched.is_active("weight_quantization", jnp.int32(10))) == 1.0


def test_qat_training_end_to_end():
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 2},
                "different_groups": {
                    "all_mlp": {"params": {"start_bits": 8, "target_bits": 8},
                                "modules": ["w_gate", "w_up", "w_down"]},
                },
            },
            "sparse_pruning": {
                "shared_parameters": {"enabled": True, "schedule_offset": 3},
                "different_groups": {
                    "sp": {"params": {"dense_ratio": 0.8},
                           "modules": ["w_up"]},
                },
            },
        },
        "mesh": {"data": 8},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, VOCAB, (16, 16), dtype=np.int32)}
    losses = [float(engine.train_batch(batch)) for _ in range(6)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
