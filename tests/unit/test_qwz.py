"""ZeRO++ qwZ: int8 quantized weight all-gather on the stage-3 path
(reference ``partition_parameters.py:1446`` quantized all_gather_coalesced +
``csrc/quantization/swizzled_quantize.cu``).

Verifies the three claims that make qwZ real: (1) the rowwise quantizer
round-trips within int8 blockwise error, (2) the compiled stage-3 program
moves the weight all-gather onto an int8 payload (HLO-level bytes drop ~2x),
(3) training loss stays at parity with the bf16 gather."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import Config, ConfigError, MeshConfig
from deepspeed_tpu.models import llama
from deepspeed_tpu.ops.quantizer import dequantize_rows, quantize_rows
from deepspeed_tpu.parallel.qwz import quantized_gather

VOCAB = 256


# ------------------------------------------------------------------ quantizer
def test_quantize_rows_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 256), jnp.float32)
    q, s = quantize_rows(x, block=128)
    assert q.shape == x.shape and q.dtype == jnp.int8
    assert s.shape == (64, 2)
    y = dequantize_rows(q, s, jnp.float32)
    # int8 symmetric: error bounded by scale/2 = absmax/254 per block
    err = np.abs(np.asarray(y - x))
    bound = np.asarray(jnp.max(jnp.abs(x)) / 254.0 + 1e-6)
    assert err.max() <= bound * 1.01


def test_quantize_rows_padding():
    # last dim not divisible by block: padded internally, shape preserved
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 100), jnp.float32)
    q, s = quantize_rows(x, block=64)
    assert q.shape == (4, 100) and s.shape == (4, 2)
    y = dequantize_rows(q, s, jnp.float32, block=64)
    assert y.shape == (4, 100)
    assert np.abs(np.asarray(y - x)).max() < 0.05


# ------------------------------------------------------------------ HLO bytes
_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s8": 1, "u8": 1, "s32": 4,
                "pred": 1, "f64": 8, "s64": 8, "u32": 4}


def _all_gather_bytes(hlo: str) -> dict:
    """Sum all-gather result bytes per element type from HLO text."""
    out: dict = {}
    for m in re.finditer(
            r"=\s*(\w+)\[([\d,]*)\](?:\{[^}]*\})?\s+all-gather", hlo):
        dt, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out[dt] = out.get(dt, 0) + n * _DTYPE_BYTES.get(dt, 4)
    return out


def test_gather_rides_int8():
    reset_topology()
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    mesh = topo.mesh
    w_sh = NamedSharding(mesh, P("fsdp", None))
    rep = NamedSharding(mesh, P())
    x = jax.random.normal(jax.random.PRNGKey(0), (4096, 512), jnp.bfloat16)
    w = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (512, 512), jnp.bfloat16), w_sh)

    # baseline: the stage-3 gather-on-use, made explicit the same way the
    # qwZ path makes its int8 gather explicit
    def dense(w, x):
        return x @ jax.lax.with_sharding_constraint(w, rep)

    def qwz(w, x):
        return x @ quantized_gather(w, mesh, P("fsdp", None), 128)

    hlo_dense = jax.jit(dense, in_shardings=(w_sh, None),
                        out_shardings=rep).lower(w, x).compile().as_text()
    hlo_qwz = jax.jit(qwz, in_shardings=(w_sh, None),
                      out_shardings=rep).lower(w, x).compile().as_text()
    bd = _all_gather_bytes(hlo_dense)
    bq = _all_gather_bytes(hlo_qwz)
    # dense gathers the weight in a float type (CPU upcasts bf16 -> f32 on
    # the wire; TPU keeps bf16) — either way, full float weight bytes
    assert sum(bd.values()) >= 512 * 512 * 2, f"dense should gather the weight: {bd}"
    assert bq.get("s8", 0) == 512 * 512, f"qwz should gather the int8 weight: {bq}"
    # scales ride beside the payload but are tiny (1/block of the elements)
    float_bytes = sum(v for k, v in bq.items() if k != "s8")
    assert float_bytes <= 0.1 * bq["s8"], f"qwz float side-channel too big: {bq}"
    # vs the bf16-equivalent wire: int8 + scales ~= 0.5x + epsilon
    assert sum(bq.values()) < 0.65 * (512 * 512 * 2)


def test_gather_backward_is_straight_through():
    reset_topology()
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    mesh = topo.mesh
    w = jax.random.normal(jax.random.PRNGKey(1), (256, 128), jnp.float32)

    def loss(w, x):
        return jnp.sum(x @ quantized_gather(w, mesh, P("fsdp", None), 64))

    x = jax.random.normal(jax.random.PRNGKey(2), (4, 256), jnp.float32)
    g = jax.grad(loss)(w, x)
    # STE: d(sum(x@w))/dw = sum of x rows broadcast — exact, unquantized
    expect = jnp.broadcast_to(x.sum(0)[:, None], (256, 128))
    np.testing.assert_allclose(np.asarray(g), np.asarray(expect), rtol=1e-5)


# ------------------------------------------------------------------ config
def test_config_qwz_requires_stage3():
    with pytest.raises(ConfigError, match="stage 3"):
        Config.from_dict({
            "train_micro_batch_size_per_device": 1,
            "zero_optimization": {"stage": 2, "quantized_weights": True},
        })


def test_config_reference_spelling_maps():
    cfg = Config.from_dict({
        "train_micro_batch_size_per_device": 1,
        "zero_optimization": {"stage": 3, "zero_quantized_weights": True},
    })
    assert cfg.zero_optimization.quantized_weights


# ------------------------------------------------------------------ engine
def _engine(qwz: bool, mesh=None):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3, "quantized_weights": qwz,
                              "qwz_block": 64},
        "mesh": mesh or {"data": 2, "fsdp": 4},
        "seed": 5,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    return engine


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (16, 16), dtype=np.int32)}
            for _ in range(n)]


class TestQwzEngine:
    def test_loss_parity_vs_bf16_gather(self):
        # one repeated batch: memorization descends through the int8 weight
        # noise floor (varied tiny batches would not at this scale)
        batch = _batches(1)[0]
        ref = _engine(qwz=False)
        ref_losses = [float(ref.train_batch(batch)) for _ in range(8)]
        qw = _engine(qwz=True)
        assert qw.shard_ctx.qwz is not None
        qw_losses = [float(qw.train_batch(batch)) for _ in range(8)]
        assert all(np.isfinite(qw_losses))
        assert qw_losses[-1] < qw_losses[0]
        # int8 blockwise weight error perturbs the trajectory only slightly
        np.testing.assert_allclose(qw_losses, ref_losses, rtol=0.05)

    def test_composes_with_tensor_axis(self):
        engine = _engine(qwz=True, mesh={"data": 1, "fsdp": 4, "tensor": 2})
        losses = [float(engine.train_batch(b)) for b in _batches(3)]
        assert all(np.isfinite(losses))

    def test_rejected_with_pipeline(self):
        with pytest.raises(ValueError, match="pipeline"):
            _engine(qwz=True, mesh={"data": 1, "fsdp": 2, "pipeline": 4})
