"""SuperOffload: mixed HBM/host residency + speculative NVMe updates
(reference ``runtime/superoffload/superoffload_stage3.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama

VOCAB = 256


def _engine(device, tmp_path, super_offload=False, frac=0.5):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 2,
            "sub_group_size": 30_000,
            "offload_optimizer": {
                "device": device,
                "nvme_path": str(tmp_path / "nvme"),
                "super_offload": super_offload,
                "hbm_resident_fraction": frac,
            },
        },
        "mesh": {"data": 2, "fsdp": 4},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    return engine


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}
            for _ in range(n)]


def test_cpu_mixed_residency_parity(tmp_path):
    """SuperOffload residency must not change the update math."""
    base = [float(_engine("cpu", tmp_path).train_batch(b)) for b in _batches(4)]
    reset_topology()
    so = [float(_engine("cpu", tmp_path, super_offload=True).train_batch(b))
          for b in _batches(4)]
    np.testing.assert_allclose(base, so, rtol=1e-6)


def test_cpu_hbm_resident_group_count(tmp_path):
    engine = _engine("cpu", tmp_path, super_offload=True, frac=0.5)
    n_groups = len(engine._groups)
    assert n_groups >= 2
    # fraction of groups use the device sharding for storage (on backends
    # without a host tier both kinds coincide; the split must still exist)
    dev_like = sum(1 for dev_sh, store_sh in engine._group_shardings
                   if store_sh is dev_sh)
    assert dev_like >= int(round(0.5 * n_groups))


def test_nvme_speculative_parity(tmp_path):
    """The speculative (sync-free) walk computes exactly the blocking walk."""
    batches = _batches(4)
    base = [float(_engine("nvme", tmp_path / "a").train_batch(b)) for b in batches]
    reset_topology()
    spec = [float(_engine("nvme", tmp_path / "b", super_offload=True).train_batch(b))
            for b in batches]
    np.testing.assert_allclose(base, spec, rtol=1e-6)


def test_group_apply_overflow_guard(tmp_path):
    """finite=False must write back unchanged params + state (the on-device
    equivalent of the reference's speculative-step rollback)."""
    engine = _engine("nvme", tmp_path, super_offload=True)
    apply_g = engine._group_apply(0)
    pg = (jnp.ones((8,), jnp.float32),)
    state = engine.optimizer.init(pg)
    gg = (jnp.full((8,), jnp.inf, jnp.float32),)
    newp, new_state = apply_g(pg, state, gg, jnp.float32(1.0),
                              jnp.float32(0.1), jnp.asarray(False))
    np.testing.assert_array_equal(np.asarray(newp[0]), np.ones(8))
    for a, b in zip(jax.tree_util.tree_leaves(new_state),
                    jax.tree_util.tree_leaves(engine.optimizer.init(pg))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
