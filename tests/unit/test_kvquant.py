"""Low-bit KV serving (inference/kvquant.py).

The contract under test: with ``RaggedConfig.quant`` set, every KV block —
HBM pool, prefix-cache retained set, host/disk tiers, handoff wire — is
stored low-bit (int8 / fp8-e4m3) with per-row-per-head scales, quantized
ONCE at the paged write site and dequantized inside the jitted gather; the
drift vs the fp path stays inside ``DRIFT_BUDGET`` across every dispatch
mode, the accounting (bytes-per-token, block bytes, memledger, admission
headroom) sees the quantized sizes, a persisted record read back under a
different codec config raises, and ``quant="off"`` (the default) keeps the
engine bit-identical to the unquantized path.
"""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.inference import kvquant
from deepspeed_tpu.inference.kvquant import (
    DRIFT_BUDGET,
    QuantizedKV,
    build_quantized_paged_cache,
    drift_verdict,
    get_codec,
    paged_block_bytes,
    parse_quant,
    quantize_kv_rows,
    dequantize_kv_rows,
    token_match_rate,
)
from deepspeed_tpu.inference.ragged import (
    KVHandoff,
    RaggedConfig,
    RaggedInferenceEngine,
)
from deepspeed_tpu.models import llama

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)

BS = 4

MODES = {
    "plain": {},
    "tiled": {"prefill_tile": 8},
    "run_ahead": {"decode_run_ahead": 4},
    "fused": {"fused_chunk": 4, "pipeline_depth": 2},
}

SHARED = [11, 7, 3, 5, 2, 13, 17, 19]          # two full blocks of 4
PROMPT_A = SHARED + [23, 29, 31]
PROMPT_B = SHARED + [37, 41]
PROMPTS = {0: [5, 6, 7, 8, 9, 10], 1: [11, 12, 13],
           2: [1, 2, 3, 4, 5, 6, 7, 8, 9]}


def _engine(quant="off", quantize_bits=0, **over):
    kw = dict(max_tokens_per_step=16, max_seqs=3, block_size=BS,
              num_blocks=29, max_blocks_per_seq=16, quant=quant)
    kw.update(over)
    return RaggedInferenceEngine(
        model=lambda ctx: llama.build(CFG, ctx=ctx),
        ragged_config=RaggedConfig(**kw), dtype=jnp.float32, seed=0,
        quantize_bits=quantize_bits)


def _run(eng, prompts=PROMPTS, max_new=8, temperature=0.0):
    for i, p in prompts.items():
        kw = dict(max_new_tokens=max_new)
        if temperature:
            kw.update(temperature=temperature, seed=100 + int(i))
        eng.put(i, p, **kw)
    return eng.generate_all()


# ----------------------------------------------------------------- codec math
class TestCodec:
    def test_roundtrip_relative_error(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(64, 2, 64)).astype(np.float32)) * 3.0
        for name, tol in (("int8", 0.02), ("fp8", 0.08)):
            q, s = quantize_kv_rows(x, get_codec(name))
            back = dequantize_kv_rows(q, s)
            err = float(jnp.max(jnp.abs(back - x)))
            amax = float(jnp.max(jnp.abs(x)))
            assert err <= tol * amax, (name, err, amax)

    def test_zero_rows_exact_and_scale_one(self):
        x = jnp.zeros((4, 2, 8), jnp.float32)
        q, s = quantize_kv_rows(x, get_codec("int8"))
        np.testing.assert_array_equal(np.asarray(q), 0)
        np.testing.assert_array_equal(np.asarray(s), 1.0)
        np.testing.assert_array_equal(np.asarray(dequantize_kv_rows(q, s)), 0.0)

    def test_row_independence(self):
        # rewriting one row must not change another's quantization: scales
        # are per (row, head), so quantizing rows separately == together
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(6, 2, 16)).astype(np.float32))
        c = get_codec("int8")
        q_all, s_all = quantize_kv_rows(x, c)
        q_one, s_one = quantize_kv_rows(x[3:4], c)
        np.testing.assert_array_equal(np.asarray(q_all[3:4]), np.asarray(q_one))
        np.testing.assert_array_equal(np.asarray(s_all[3:4]), np.asarray(s_one))

    def test_fp8_saturates_instead_of_overflowing(self):
        x = jnp.full((1, 1, 4), 1e4, jnp.float32)
        q, s = quantize_kv_rows(x, get_codec("fp8"))
        assert np.all(np.isfinite(np.asarray(q, dtype=np.float32)))

    def test_unknown_codec_raises(self):
        with pytest.raises(ValueError, match="unknown KV codec"):
            get_codec("int3")


class TestParseQuant:
    def test_grammar(self):
        assert parse_quant(None) == (None, 0, False)
        assert parse_quant("off") == (None, 0, False)
        p = parse_quant("int8+woq8+qcol")
        assert p.kv.name == "int8" and p.woq_bits == 8 and p.qcol
        assert parse_quant("fp8").kv.name == "fp8"
        assert parse_quant("woq4").woq_bits == 4

    def test_rejects_conflicts_and_unknowns(self):
        with pytest.raises(ValueError, match="more than one KV codec"):
            parse_quant("int8+fp8")
        with pytest.raises(ValueError, match="more than one woq"):
            parse_quant("woq8+woq4")
        with pytest.raises(ValueError, match="unknown component"):
            parse_quant("int8+turbo")
        with pytest.raises(ValueError, match="must be a string"):
            parse_quant(8)


# ------------------------------------------------------------------ the pytree
def _init_fn(nb, bs, dtype, heads=2, dim=64, layers=2):
    return {"k": jnp.zeros((layers, nb, bs, heads, dim), dtype),
            "v": jnp.zeros((layers, nb, bs, heads, dim), dtype)}


class TestQuantizedKV:
    def test_pool_built_at_storage_precision(self):
        pool = build_quantized_paged_cache(_init_fn, 8, BS, jnp.float16,
                                           get_codec("int8"))
        k = pool["k"]
        assert k.q.dtype == jnp.int8 and k.s.dtype == jnp.float16
        assert k.shape == (2, 8, BS, 2, 64)      # payload shape
        assert k.dtype == np.dtype("float16")    # COMPUTE dtype
        assert k.s.shape == k.q.shape[:-1]

    def test_resident_multiplier_vs_fp16_clears_floor(self):
        # at head_dim 64: int8 payload + f16 per-row-per-head scale is
        # 1 + 2/64 bytes/elem vs 2 -> ~1.94x, over the 1.8x acceptance floor
        pool = build_quantized_paged_cache(_init_fn, 8, BS, jnp.float16,
                                           get_codec("int8"))
        q_bytes = sum(leaf.nbytes for leaf in pool.values())
        fp16_bytes = sum(
            a.nbytes for a in jax.tree_util.tree_leaves(
                _init_fn(8, BS, jnp.float16)))
        assert fp16_bytes / q_bytes >= 1.8

    def test_paged_block_bytes(self):
        # [L=2, nb, bs=4, H=2, D=64] fp16 k+v: 2*4*2*64*2 bytes * 2 leaves
        assert paged_block_bytes(_init_fn, 8, BS, jnp.float16) == \
            2 * (2 * BS * 2 * 64 * 2)

    def test_tree_map_and_scan_slicing_preserve_wrapper(self):
        pool = build_quantized_paged_cache(_init_fn, 4, BS, jnp.float32,
                                           get_codec("fp8"))
        sliced = jax.tree_util.tree_map(lambda a: a[:, :2], pool)
        assert isinstance(sliced["k"], QuantizedKV)
        assert sliced["k"].codec == "fp8"
        assert sliced["k"].shape[1] == 2 and sliced["k"].s.shape[1] == 2

    def test_pickle_roundtrip(self):
        pool = build_quantized_paged_cache(_init_fn, 4, BS, jnp.float32,
                                           get_codec("int8"))
        back = pickle.loads(pickle.dumps(pool["k"]))
        assert back.codec == "int8" and back.is_quantized_kv
        assert np.asarray(back.q).shape == pool["k"].q.shape
        assert back.nbytes == pool["k"].nbytes

    def test_scatter_then_gather_roundtrip(self):
        full = build_quantized_paged_cache(_init_fn, 4, BS, jnp.float32,
                                           get_codec("int8"))["k"]
        # per-layer slice the way lax.scan sees it: through the pytree
        pool = jax.tree_util.tree_map(lambda a: a[0], full)
        rng = np.random.default_rng(2)
        rows = jnp.asarray(rng.normal(size=(3, 2, 64)).astype(np.float32))
        blk = jnp.asarray([1, 1, 2]); off = jnp.asarray([0, 1, 3])
        pool = pool.scatter_rows(blk, off, rows)
        got = pool.gather_dequant(jnp.asarray([[1, 2]]))  # [1, 2, bs, H, D]
        amax = float(jnp.max(jnp.abs(rows)))
        np.testing.assert_allclose(np.asarray(got[0, 0, 0]),
                                   np.asarray(rows[0]), atol=0.02 * amax)
        np.testing.assert_allclose(np.asarray(got[0, 1, 3]),
                                   np.asarray(rows[2]), atol=0.02 * amax)


# --------------------------------------------------------- drift-gated parity
@pytest.fixture(scope="module")
def ref():
    """One fp32 plain-mode reference, greedy and seeded. The dispatch modes
    are token-identical to the plain path by the engine's own contract
    (pinned in test_ragged/test_kvtier), so this single baseline serves
    every mode's drift comparison."""
    eng = _engine()
    return {"greedy": _run(eng), "seeded": _run(eng, temperature=0.8)}


class TestEngineParity:
    def test_quant_off_is_bit_identical_and_plain_pool(self, ref):
        # the off path must not even build QuantizedKV wrappers
        explicit = _engine(quant="off")
        assert not hasattr(explicit.cache["k"], "is_quantized_kv")
        assert _run(explicit) == ref["greedy"]
        assert _run(explicit, temperature=0.8) == ref["seeded"]

    @pytest.mark.parametrize("mode", list(MODES))
    def test_int8_greedy_within_budget_all_modes(self, mode, ref):
        got = _run(_engine("int8", **MODES[mode]))
        assert token_match_rate(ref["greedy"], got) >= \
            DRIFT_BUDGET["greedy_match_min"]

    def test_fp8_greedy_and_seeded_within_budget(self, ref):
        q = _engine("fp8")
        assert token_match_rate(ref["greedy"], _run(q)) >= \
            DRIFT_BUDGET["greedy_match_min"]
        assert token_match_rate(ref["seeded"],
                                _run(q, temperature=0.8)) >= \
            DRIFT_BUDGET["greedy_match_min"]

    def test_int8_seeded_sampling_deterministic(self, ref):
        q = _engine("int8")
        a = _run(q, temperature=0.8)
        b = _run(q, temperature=0.8)
        assert a == b
        assert token_match_rate(ref["seeded"], a) >= \
            DRIFT_BUDGET["greedy_match_min"]

    def test_spec_decode_accept_rate_drift(self):
        rates = {}
        for name in ("off", "int8"):
            eng = _engine(name, sched_steps=8, spec_draft=4)
            eng.put("s", PROMPT_A, max_new_tokens=8)
            eng.generate_all()
            assert eng.spec_proposed > 0
            rates[name] = eng.spec_accepted / eng.spec_proposed
        drift = abs(rates["int8"] - rates["off"])
        assert drift <= DRIFT_BUDGET["spec_accept_drift_max"], rates

    def test_prefix_cache_hit_parity(self):
        # a quant engine serving PROMPT_B from PROMPT_A's cached blocks must
        # match a cold quant engine exactly: the retained set holds the SAME
        # quantized payload the write produced (no second rounding)
        warm = _engine("int8", enable_prefix_cache=True)
        warm.put("warm", PROMPT_A, max_new_tokens=4)
        warm.generate_all()
        warm.put("g", PROMPT_B, max_new_tokens=6)
        got = warm.generate_all()
        assert warm.prefix_hits >= 1
        cold = _engine("int8", enable_prefix_cache=False)
        cold.put("g", PROMPT_B, max_new_tokens=6)
        assert got["g"] == cold.generate_all()["g"]


class TestTierAndHandoff:
    def test_demote_promote_roundtrip_token_identical(self, tmp_path):
        t = _engine("int8", num_blocks=13, enable_prefix_cache=True,
                    kv_tier=True, kv_tier_host_blocks=2,
                    kv_tier_disk_blocks=64, kv_tier_dir=str(tmp_path),
                    kv_tier_prefill_tokens_per_s=1e-6)
        t.put("warm", PROMPT_A, max_new_tokens=4)
        t.generate_all()
        for i in range(6):  # churn: force demotion of the shared blocks
            t.put(f"churn{i}", [50 + i * 7 + j for j in range(9)],
                  max_new_tokens=4)
            t.generate_all()
        t.put("g", PROMPT_B, max_new_tokens=6)
        got = t.generate_all()
        st = t._kvtier.stats()
        assert st["demotions"] > 0 and st["promotions"] > 0
        assert st["codec"] == "int8"
        cold = _engine("int8", enable_prefix_cache=False)
        cold.put("g", PROMPT_B, max_new_tokens=6)
        assert got["g"] == cold.generate_all()["g"]

    @pytest.fixture(scope="class")
    def int8_handoff(self):
        src = _engine("int8")
        src.put("h", PROMPT_A, max_new_tokens=5, handoff=True)
        src.generate_all()
        return src.export_handoff("h")

    def test_handoff_resume_across_quant_engines(self, int8_handoff):
        assert int8_handoff.codec == "int8"
        dst = _engine("int8")
        assert dst.import_handoff(
            KVHandoff.from_bytes(int8_handoff.to_bytes()))
        got = dst.generate_all()
        cold = _engine("int8", enable_prefix_cache=False)
        cold.put("h", PROMPT_A, max_new_tokens=5)
        assert got["h"] == cold.generate_all()["h"]

    def test_handoff_codec_mismatch_raises(self, int8_handoff):
        with pytest.raises(ValueError, match="codec"):
            _engine("off").import_handoff(int8_handoff)
        with pytest.raises(ValueError, match="codec"):
            _engine("fp8").import_handoff(int8_handoff)

    def test_prefix_transfer_codec_mismatch_is_graceful_miss(self):
        src = _engine("int8", enable_prefix_cache=True)
        src.put("warm", PROMPT_A, max_new_tokens=4)
        src.generate_all()
        payload = src.export_prefix(PROMPT_B)
        assert payload is not None and payload.codec == "int8"
        # matched codec imports; mismatched codec returns 0, never raises
        dst_ok = _engine("int8", enable_prefix_cache=True)
        assert dst_ok.import_prefix(payload) > 0
        dst_off = _engine("off", enable_prefix_cache=True)
        assert dst_off.import_prefix(payload) == 0


# ------------------------------------------------------- accounting surfaces
class TestAccounting:
    def test_bytes_per_token_and_block_bytes_shrink(self):
        off, q = _engine("off"), _engine("int8")
        assert q.kv_bytes_per_token() < off.kv_bytes_per_token()
        assert q._block_bytes() < off._block_bytes()
        # int8 payload + f16 scales at head_dim 8: 1.25 bytes/elem vs 4 fp32
        assert off.kv_bytes_per_token() / q.kv_bytes_per_token() \
            == pytest.approx(3.2)

    def test_kv_quant_stats_surface(self):
        q = _engine("int8")
        _run(q, max_new=4)
        st = q.kv_quant_stats()
        assert st["codec"] == "int8"
        assert st["resident_multiplier_vs_fp16"] == pytest.approx(
            st["fp16_block_bytes"] / st["block_bytes"])
        assert st["blocks_allocated_total"] > 0
        assert st["bytes_saved_total"] == st["blocks_allocated_total"] * (
            st["fp_block_bytes"] - st["block_bytes"])
        assert _engine("off").kv_quant_stats() is None

    def test_memledger_owner_counts_quantized_bytes(self, tmp_path):
        from deepspeed_tpu import telemetry
        tel = telemetry.configure(enabled=True, memledger={
            "enabled": True, "report_dir": str(tmp_path)})
        try:
            q = _engine("int8")
            _run(q, max_new=4)
            led = tel.memledger
            owners = led.breakdown()["owners"]
            want = sum(int(a.nbytes)
                       for a in jax.tree_util.tree_leaves(q.cache))
            assert owners["kv_pool"] == want
            assert led.census()["unattributed_fraction"] <= 0.05
            snap = telemetry.snapshot()["metrics"]
            assert snap["kvquant_enabled"]["series"][0]["value"] == 1.0
            assert snap["kvquant_bytes_saved_total"]["series"][0]["value"] > 0
        finally:
            telemetry.configure(enabled=False)

    def test_woq_component_equals_quantize_bits(self):
        a = _engine("woq8")
        b = _engine(quantize_bits=8)
        assert a.quantize_bits == b.quantize_bits == 8
        assert _run(a, max_new=4) == _run(b, max_new=4)


# ------------------------------------------------- quantized TP collective
class TestQuantizedCollective:
    @pytest.fixture
    def mesh(self):
        reset_topology()
        yield init_distributed(MeshConfig(data=2, tensor=4)).mesh
        reset_topology()

    def test_int8_wire_in_hlo_and_argmax_parity(self, mesh):
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 64), jnp.float32)
        f = jax.jit(lambda v: kvquant.quantized_logits_all_gather(
            v, mesh, axis="tensor"))
        out = f(x)
        assert bool(jnp.all(jnp.argmax(out, -1) == jnp.argmax(x, -1)))
        assert float(jnp.max(jnp.abs(out - x))) < 0.05
        txt = f.lower(x).compile().as_text()
        ag = [l for l in txt.splitlines() if "all-gather" in l]
        assert ag and any("s8[" in l for l in ag)

    def test_identity_fallbacks(self, mesh):
        x = jnp.ones((2, 63))
        assert kvquant.quantized_logits_all_gather(x, None) is x
        # vocab not divisible by the shard count: identity, not an error
        out = kvquant.quantized_logits_all_gather(x, mesh, axis="tensor")
        assert out.shape == x.shape
        assert kvquant.quantized_logits_all_gather(
            x, mesh, axis="absent") is x


# ------------------------------------------------------------- drift verdict
class TestDriftVerdict:
    def test_token_match_rate_prefix_semantics(self):
        want = {0: [1, 2, 3, 4], 1: [5, 6]}
        assert token_match_rate(want, want) == 1.0
        got = {0: [1, 2, 9, 4], 1: [5, 6]}  # divergence stops the prefix
        assert token_match_rate(want, got) == pytest.approx(4 / 6)
        assert token_match_rate({}, {}) == 1.0

    def test_verdict_applies_budget(self):
        ok = drift_verdict(0.99, 0.01)
        assert ok["ok"] and ok["budget"] == DRIFT_BUDGET
        assert not drift_verdict(0.90, 0.0)["ok"]
        assert not drift_verdict(1.0, 0.05)["ok"]
        assert drift_verdict(1.0, None)["ok"]
