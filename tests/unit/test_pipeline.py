"""Pipeline parallelism: exactness of the collective microbatch pipeline vs the
plain layer scan, and end-to-end PP training parity
(reference: ``tests/unit/runtime/pipe/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models import llama
from deepspeed_tpu.parallel.pipeline import pipeline_apply

VOCAB = 256


def test_pipeline_apply_matches_scan():
    topo = init_distributed(MeshConfig(data=2, pipeline=4))
    # toy layer: x @ w + b, stacked [L=8, D, D]
    L, B, D = 8, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    params = {
        "w": jax.random.normal(ks[0], (L, D, D)) * 0.1,
        "b": jax.random.normal(ks[1], (L, D)) * 0.1,
    }
    x = jax.random.normal(ks[2], (B, D))

    def layer(c, lp):
        return jnp.tanh(c @ lp["w"] + lp["b"])

    ref = jax.lax.scan(lambda c, lp: (layer(c, lp), None), x, params)[0]
    out = jax.jit(
        lambda p, x: pipeline_apply(layer, p, x, topo.mesh, num_microbatches=4)
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match_scan():
    topo = init_distributed(MeshConfig(data=2, pipeline=4))
    L, B, D = 4, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    params = {"w": jax.random.normal(ks[0], (L, D, D)) * 0.1}
    x = jax.random.normal(ks[1], (B, D))

    def layer(c, lp):
        return jnp.tanh(c @ lp["w"])

    def loss_pipe(p):
        return jnp.sum(pipeline_apply(layer, p, x, topo.mesh, num_microbatches=2) ** 2)

    def loss_ref(p):
        return jnp.sum(jax.lax.scan(lambda c, lp: (layer(c, lp), None), x, p)[0] ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_ref = jax.grad(loss_ref)(params)
    np.testing.assert_allclose(np.asarray(g_pipe["w"]), np.asarray(g_ref["w"]),
                               rtol=2e-5, atol=2e-5)


def _cfg(mesh, n_micro=0, gas=1, schedule="gpipe", stage=0, batch=64):
    cfg = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "pipeline": {"num_microbatches": n_micro, "schedule": schedule},
        "mesh": mesh,
        "seed": 7,
    }
    if schedule == "1f1b":
        # fp32 keeps the many-tick schedule fast enough on the bf16-emulating
        # CPU test mesh (the 40s collective watchdog is real here)
        cfg["bf16"] = {"enabled": False}
    return cfg


def _run(mesh, n_micro=0, n=3, gas=1, schedule="gpipe", stage=0, batch=64,
         schedule_base_fp32=False):
    reset_topology()
    cfg = _cfg(mesh, n_micro, gas=gas, schedule=schedule, stage=stage, batch=batch)
    if schedule_base_fp32:
        cfg["bf16"] = {"enabled": False}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg,
        seed=11,
    )
    rng = np.random.default_rng(3)
    losses = []
    for _ in range(n):
        b = {"input_ids": rng.integers(0, VOCAB, (engine.train_batch_size, 16), dtype=np.int32)}
        losses.append(float(engine.train_batch(b)))
    return engine, losses


def test_pp_training_loss_parity():
    """PP=2 (tiny model has 2 layers) must match the DP-only trajectory."""
    _, base = _run({"data": 8})
    _, pp = _run({"data": 4, "pipeline": 2}, n_micro=2)
    np.testing.assert_allclose(base, pp, rtol=3e-4, atol=3e-5)


def test_pp_1f1b_training_loss_parity():
    """1F1B engine schedule (GAS microbatches = pipeline microbatches) must
    match the same-precision DP-only trajectory with the same GAS."""
    _, base = _run({"data": 8}, gas=4, schedule_base_fp32=True, batch=32)
    _, pp = _run({"data": 4, "pipeline": 2}, gas=4, schedule="1f1b", batch=32)
    np.testing.assert_allclose(base, pp, rtol=3e-4, atol=3e-5)


def test_pp_1f1b_composes_with_fsdp():
    """pp=2 x fsdp=2 under ZeRO-2 with the 1F1B schedule: stacked layer
    weights carry BOTH the pipeline and fsdp axes in the grad/opt layout and
    the trajectory matches DP."""
    _, base = _run({"data": 8}, gas=4, stage=2, schedule_base_fp32=True, batch=32)
    engine, pp = _run({"data": 2, "pipeline": 2, "fsdp": 2}, gas=4,
                      schedule="1f1b", stage=2, batch=32)
    np.testing.assert_allclose(base, pp, rtol=3e-4, atol=3e-5)
    spec = str(engine.plan.shard_specs["layers"]["wq"])
    assert "pipeline" in spec and "fsdp" in spec


def test_pp_layers_sharded_over_pipeline_axis():
    engine, _ = _run({"data": 4, "pipeline": 2}, n_micro=2, n=1)
    wq = engine.params["layers"]["wq"]
    assert "pipeline" in str(wq.sharding.spec)
    # 2 layers over 2 stages: each device holds one layer slice
    assert wq.addressable_shards[0].data.shape[0] == 1
