"""Checkpoint round-trips incl. cross-topology resharding
(reference test style: ``tests/unit/checkpoint/`` save->load->compare and the
DistributedFixture save-at-N/load-at-M pattern)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama

VOCAB = 256


def _builder():
    return lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx)


def _config(stage, mesh, gas=1):
    return {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_max_lr": 1e-3, "warmup_num_steps": 5}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh,
        "seed": 7,
    }


def _batches(n, batch, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (batch, 16), dtype=np.int32)} for _ in range(n)]


def _new_engine(stage, mesh):
    reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=_builder(), config=_config(stage, mesh), seed=11
    )
    return engine


def test_save_load_roundtrip(tmp_path):
    engine = _new_engine(2, {"data": 1, "fsdp": 8})
    for b in _batches(3, engine.train_batch_size):
        engine.train_batch(b)
    engine.save_checkpoint(str(tmp_path))
    assert (tmp_path / "latest").exists()
    saved_params = jax.tree_util.tree_map(np.asarray, engine.params)

    engine2 = _new_engine(2, {"data": 1, "fsdp": 8})
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine2.global_steps == 3
    for a, b in zip(jax.tree_util.tree_leaves(saved_params),
                    jax.tree_util.tree_leaves(engine2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_training_matches_continuous(tmp_path):
    """save at step2 + resume for 2 == 4 continuous steps (same data/rng)."""
    batches = _batches(4, 16, seed=3)
    cont = _new_engine(1, {"data": 1, "fsdp": 8})
    for b in batches:
        cont.train_batch(b)
    cont_params = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, cont.params))

    half = _new_engine(1, {"data": 1, "fsdp": 8})
    for b in batches[:2]:
        half.train_batch(b)
    half.save_checkpoint(str(tmp_path), tag="mid")

    resumed = _new_engine(1, {"data": 1, "fsdp": 8})
    resumed.load_checkpoint(str(tmp_path), tag="mid")
    # exact resume: the manifest carries the rng stream state — no manual
    # rng surgery, the resumed engine replays the continuous trajectory
    assert np.array_equal(np.asarray(resumed._rng), np.asarray(half._rng))
    for b in batches[2:]:
        resumed.train_batch(b)
    for a, b in zip(cont_params, jax.tree_util.tree_leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6)


def test_exact_resume_restores_dataloader_position(tmp_path):
    """The manifest carries the data-iterator position: a resumed run pulls
    the SAME next batch the interrupted run would have — loss trajectories
    are step-identical without any caller-side data bookkeeping."""
    from deepspeed_tpu.runtime.dataloader import CheckpointableLoader

    def factory(skip):
        def gen():
            i = skip
            while True:
                rng = np.random.default_rng(100 + i)
                yield {"input_ids": rng.integers(0, VOCAB, (16, 16),
                                                 dtype=np.int32)}
                i += 1
        return gen()

    def new_engine():
        reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=_builder(), config=_config(0, {"data": 8}),
            training_data=CheckpointableLoader(factory), seed=11)
        return engine

    cont = new_engine()
    cont_losses = [float(cont.train_batch()) for _ in range(4)]

    half = new_engine()
    for _ in range(2):
        half.train_batch()
    half.save_checkpoint(str(tmp_path))

    resumed = new_engine()
    resumed.load_checkpoint(str(tmp_path))
    assert resumed.training_dataloader.batches_consumed == 2
    tail = [float(resumed.train_batch()) for _ in range(2)]
    np.testing.assert_allclose(tail, cont_losses[2:], rtol=2e-5)


def test_reshard_across_zero_stage_and_mesh(tmp_path):
    """Universal-checkpoint semantics: save under ZeRO-3 fsdp=8, load under
    ZeRO-0 dp=8 and under tp=4 — loss trajectories continue identically."""
    src = _new_engine(3, {"data": 1, "fsdp": 8})
    for b in _batches(2, src.train_batch_size, seed=5):
        src.train_batch(b)
    src.save_checkpoint(str(tmp_path))
    probe = _batches(1, 16, seed=9)[0]
    src_loss = float(src.forward(probe))

    for stage, mesh in [(0, {"data": 8}), (0, {"data": 2, "tensor": 4}),
                        (2, {"data": 2, "fsdp": 4})]:
        dst = _new_engine(stage, mesh)
        dst.load_checkpoint(str(tmp_path))
        assert float(dst.forward(probe)) == pytest.approx(src_loss, rel=1e-4)


def test_keep_n_latest(tmp_path):
    engine = _new_engine(0, {"data": 8})
    engine.config.checkpoint.keep_n_latest = 2
    for i in range(4):
        engine.train_batch(_batches(1, engine.train_batch_size, seed=i)[0])
        engine.save_checkpoint(str(tmp_path), tag=f"step{i}")
    dirs = sorted(d.name for d in tmp_path.iterdir() if d.is_dir())
    assert len(dirs) == 2
    assert "step3" in dirs


def test_async_save(tmp_path):
    engine = _new_engine(0, {"data": 8})
    engine.config.checkpoint.async_save = True
    engine.train_batch(_batches(1, engine.train_batch_size)[0])
    engine.save_checkpoint(str(tmp_path))
    engine._join_ckpt_writer()
    engine2 = _new_engine(0, {"data": 8})
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None


def test_sharded_files_and_peak_memory(tmp_path):
    """The format's scalability contract: fragments are per-shard (no process
    writes a full fsdp-sharded leaf), and save/load peaks stay at shard
    granularity — ~1/mesh_shards of the big leaves, never a whole-model or
    whole-leaf gather (reference per-rank zero_pp_rank_* files +
    ds_to_universal fragments)."""
    from deepspeed_tpu.checkpoint import sharded

    engine = _new_engine(3, {"data": 1, "fsdp": 8})
    engine.train_batch(_batches(1, engine.train_batch_size)[0])
    engine.save_checkpoint(str(tmp_path), tag="t")
    save_peak = sharded.LAST_STATS["save_peak_bytes"]

    # biggest fp32 leaf and its expected shard size under fsdp=8
    big = max(jax.tree_util.tree_leaves(engine.params), key=lambda x: x.nbytes)
    assert save_peak <= big.nbytes // 8 + 1024, (
        f"save materialized {save_peak}B — full-leaf gather? "
        f"(largest leaf {big.nbytes}B)"
    )

    # the index records per-fragment boxes, not whole leaves
    import json

    with open(tmp_path / "t" / "model.index.json") as f:
        index = json.load(f)
    frag_counts = [len(m["fragments"]) for m in index.values()]
    assert max(frag_counts) == 8  # fsdp-sharded leaves split into 8 fragments

    dst = _new_engine(3, {"data": 2, "fsdp": 4})  # different mesh
    dst.load_checkpoint(str(tmp_path), tag="t")
    load_peak = sharded.LAST_STATS["load_peak_bytes"]
    # target shard (1/4 of leaf) + one source fragment (1/8 of leaf)
    assert load_peak <= big.nbytes // 4 + big.nbytes // 8 + 1024
    for a, b in zip(jax.tree_util.tree_leaves(engine.params),
                    jax.tree_util.tree_leaves(dst.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_to_fp32_consolidation(tmp_path):
    from deepspeed_tpu.checkpoint.zero_to_fp32 import (
        get_fp32_state_dict_from_checkpoint,
    )

    engine = _new_engine(2, {"data": 1, "fsdp": 8})
    engine.train_batch(_batches(1, engine.train_batch_size)[0])
    engine.save_checkpoint(str(tmp_path))
    state = get_fp32_state_dict_from_checkpoint(str(tmp_path))
    ref = {k: np.asarray(v) for k, v in zip(
        ["embed"], [engine.params["embed"]])}
    np.testing.assert_array_equal(state["embed"], ref["embed"])
