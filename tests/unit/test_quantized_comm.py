"""Quantized collectives (ZeRO++ qgZ / 1-bit comm) — int8 on the wire,
error-feedback convergence, engine training parity
(reference: ``tests/unit/comm``, ``tests/unit/runtime/comm`` + onebit suites)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.quantized_collectives import quantized_all_reduce_arrays
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models import llama

VOCAB = 256


@pytest.fixture
def data_mesh():
    return init_distributed(MeshConfig(data=8)).mesh


class TestQuantizedAllReduce:
    def test_mean_within_quantization_tolerance(self, data_mesh):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(8, 1000)).astype(np.float32))
        err = jnp.zeros_like(x)
        mean, _ = jax.jit(
            lambda x, e: quantized_all_reduce_arrays(x, e, data_mesh, "data")
        )(x, err)
        true = np.asarray(x).mean(axis=0)
        rel = np.abs(np.asarray(mean)[0] - true).max() / np.abs(true).max()
        assert rel < 0.02, rel

    def test_error_feedback_kills_bias(self, data_mesh):
        """Averaging repeated reductions of the SAME tensor must converge to
        the exact mean — the error-feedback property 1-bit Adam relies on."""
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(8, 512)).astype(np.float32))
        e = jnp.zeros_like(x)
        f = jax.jit(lambda x, e: quantized_all_reduce_arrays(x, e, data_mesh, "data"))
        acc = np.zeros(512)
        n = 40
        for _ in range(n):
            m, e = f(x, e)
            acc += np.asarray(m)[0]
        true = np.asarray(x).mean(axis=0)
        one_shot = np.abs(np.asarray(f(x, jnp.zeros_like(x))[0])[0] - true).max()
        with_ef = np.abs(acc / n - true).max()
        assert with_ef < one_shot / 5, (with_ef, one_shot)

    def test_wire_dtype_is_int8(self, data_mesh):
        """The VERDICT 'done' criterion: the collective operands in the
        compiled HLO are s8, i.e. compression happens ON THE WIRE, not just
        numerically."""
        x = jnp.zeros((8, 256), jnp.float32)
        f = jax.jit(lambda x, e: quantized_all_reduce_arrays(x, e, data_mesh, "data"))
        txt = f.lower(x, jnp.zeros_like(x)).compile().as_text()
        a2a = [l for l in txt.splitlines() if "all-to-all" in l]
        ag = [l for l in txt.splitlines() if "all-gather" in l]
        assert a2a and any("s8[" in l for l in a2a), "all-to-all payload not int8"
        assert ag and any("s8[" in l for l in ag), "all-gather payload not int8"

    @pytest.mark.parametrize("bits,dtype_tag,chunk_bytes", [
        # for n=8 ranks, 4096 elements -> 512-element chunks: the per-chunk
        # wire payload is 64 sign-bytes (1-bit, n/8) or 256 nibble-bytes
        # (4-bit, n/2)
        (1, "u8[", 64),
        (4, "s8[", 256),
    ])
    def test_low_bit_wire_bytes(self, data_mesh, bits, dtype_tag, chunk_bytes):
        """Round-4 item 4 'done' criterion: the all-to-all operand IS the
        packed payload — byte count ~ n/8 (1-bit) and n/2 (int4). XLA may
        lower the all-to-all as one [n, B] operand or a tuple of [1, B]
        per-destination pieces; both count, as long as the payload bytes per
        chunk match the packed size."""
        x = jnp.zeros((8, 4096), jnp.float32)
        f = jax.jit(lambda x, e: quantized_all_reduce_arrays(
            x, e, data_mesh, "data", bits=bits, block=64))
        txt = f.lower(x, jnp.zeros_like(x)).compile().as_text()
        a2a = [l for l in txt.splitlines() if "all-to-all" in l
               and dtype_tag in l]
        assert a2a, f"no {dtype_tag} all-to-all operand (bits={bits})"
        import re

        sizes = set()
        for line in a2a:
            for m in re.finditer(re.escape(dtype_tag) + r"([0-9,]+)\]", line):
                dims = [int(d) for d in m.group(1).split(",")]
                p = 1
                for d in dims:
                    p *= d
                sizes.add(p)
        assert sizes & {chunk_bytes, 8 * chunk_bytes}, (sizes, chunk_bytes)

    def test_one_bit_error_feedback_converges(self, data_mesh):
        """1-bit wire + error feedback: the running average of repeated
        reductions converges to the exact mean (the compressed-allreduce
        guarantee 1-bit Adam is built on)."""
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(8, 512)).astype(np.float32))
        e = jnp.zeros_like(x)
        f = jax.jit(lambda x, e: quantized_all_reduce_arrays(
            x, e, data_mesh, "data", bits=1, block=64))
        true = np.asarray(x).mean(axis=0)
        acc = np.zeros(512)
        errs = {}
        for i in range(240):
            m, e = f(x, e)
            acc += np.asarray(m)[0]
            if i + 1 in (120, 240):
                errs[i + 1] = np.abs(acc / (i + 1) - true).max()
        one_shot = np.abs(np.asarray(f(x, jnp.zeros_like(x))[0])[0] - true).max()
        # O(1/n) telescoping: doubling the horizon ~halves the running-mean
        # error (measured 0.24 -> 0.127), and the long average beats the
        # one-shot sign noise by >5x
        assert errs[240] < one_shot / 5, (errs, one_shot)
        assert errs[240] < errs[120] * 0.7, errs


def _train(config_extra, optimizer=None, steps=6, seed=3, mesh=None, stage=1):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": optimizer or {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage, **config_extra},
        "mesh": mesh or {"data": 8},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    rng = np.random.default_rng(seed)
    batch = {"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}
    return [float(engine.train_batch(batch)) for _ in range(steps)]


class TestQuantizedTraining:
    def test_convergence_parity_vs_exact_reduction(self):
        """qgZ-compressed training must track the exact-reduction trajectory
        closely (not bit-exact — int8 wire — but convergent and close)."""
        base = _train({})
        quant = _train({"quantized_gradients": True})
        assert quant[-1] < quant[0] * 0.8  # converges
        np.testing.assert_allclose(quant, base, rtol=0.06)

    def test_composes_with_fsdp_stage2(self):
        """qgZ over data must compose with fsdp-sharded grads/opt state
        (reference qgZ exists FOR ZeRO: coalesced_collectives.py:31) —
        manual over data, fsdp GSPMD-auto inside."""
        mesh = {"data": 2, "fsdp": 4}
        base = _train({}, mesh=mesh, stage=2)
        quant = _train({"quantized_gradients": True}, mesh=mesh, stage=2)
        assert quant[-1] < quant[0] * 0.8
        np.testing.assert_allclose(quant, base, rtol=0.06)

    def test_composes_with_fsdp_stage3(self):
        mesh = {"data": 2, "fsdp": 4}
        base = _train({}, mesh=mesh, stage=3)
        quant = _train({"quantized_gradients": True}, mesh=mesh, stage=3)
        assert quant[-1] < quant[0] * 0.8
        np.testing.assert_allclose(quant, base, rtol=0.06)

    def test_requires_data_axis(self):
        reset_topology()
        with pytest.raises(ValueError, match="data"):
            deepspeed_tpu.initialize(
                model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
                config={
                    "train_micro_batch_size_per_device": 2,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1, "quantized_gradients": True},
                    "mesh": {"fsdp": 8},
                },
            )


class TestOnebitAdam:
    def test_matches_adamw_during_warmup(self):
        """With freeze_step beyond the run, 1-bit Adam IS Adam(W wd=0)."""
        adam = _train({}, optimizer={"type": "adam", "params": {"lr": 1e-2}})
        onebit = _train({}, optimizer={
            "type": "onebit_adam",
            "params": {"lr": 1e-2, "freeze_step": 1000},
        })
        np.testing.assert_allclose(onebit, adam, rtol=1e-4)

    def test_frozen_variance_with_quantized_comm_converges(self):
        """The full 1-bit Adam recipe: warmup with exact stats, then frozen
        variance + compressed gradient communication."""
        losses = _train(
            {"quantized_gradients": True},
            optimizer={"type": "onebit_adam",
                       "params": {"lr": 3e-3, "freeze_step": 5}},
            steps=10,
        )
        # keeps descending THROUGH the freeze point (step 5)
        assert losses[-1] < losses[5] < losses[0] * 0.85, losses


class TestOnebitLamb:
    """1-bit LAMB semantics (reference ``runtime/fp16/onebit/lamb.py``)."""

    def test_matches_lamb_during_warmup(self):
        import optax

        from deepspeed_tpu.config.config import OptimizerConfig
        from deepspeed_tpu.ops.optimizers import build_optimizer

        tx = build_optimizer(OptimizerConfig(
            type="onebit_lamb",
            params={"lr": 1e-2, "freeze_step": 1000}), learning_rate=1e-2)
        ref = optax.lamb(1e-2, weight_decay=0.0)
        params = {"w": jnp.ones((8, 8)) * 0.5, "b": jnp.arange(8.0)}
        s1, s2 = tx.init(params), ref.init(params)
        rng = np.random.default_rng(0)
        p1 = p2 = params
        for _ in range(4):
            g = {"w": jnp.asarray(rng.normal(size=(8, 8)), jnp.float32) * 0.1,
                 "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32) * 0.1}
            u1, s1 = tx.update(g, s1, p1)
            u2, s2 = ref.update(g, s2, p2)
            p1 = optax.apply_updates(p1, u1)
            p2 = optax.apply_updates(p2, u2)
        for k in p1:
            np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                       rtol=1e-4, atol=1e-6)

    def test_variance_freezes_and_updates_stay_normalized(self):
        from deepspeed_tpu.ops.optimizers import scale_by_onebit_lamb

        # min_coeff=0: the low-side bound exists for degenerate tiny updates,
        # not to defeat normalization of huge ones
        tx = scale_by_onebit_lamb(warmup_steps=3, min_coeff=0.0)
        params = {"w": jnp.ones((16,))}
        state = tx.init(params)
        rng = np.random.default_rng(1)
        nu_frozen = None
        for i in range(8):
            g = {"w": jnp.asarray(rng.normal(size=(16,)) * (10.0 ** i),
                                  jnp.float32)}
            u, state = tx.update(g, state, params)
            if i == 2:  # step count == 3 == freeze point
                nu_frozen = np.asarray(state.nu["w"]).copy()
        np.testing.assert_array_equal(np.asarray(state.nu["w"]), nu_frozen)
        # the live trust ratio keeps the applied norm pinned to ||p|| even as
        # momentum drifts over the frozen variance (the stability property)
        un = float(jnp.linalg.norm(u["w"]))
        pn = float(jnp.linalg.norm(params["w"]))
        assert un <= pn * 1.01, (un, pn)

    def test_converges_with_quantized_comm(self):
        losses = _train(
            {"quantized_gradients": True},
            optimizer={"type": "onebit_lamb",
                       "params": {"lr": 5e-3, "freeze_step": 5}},
            steps=10,
        )
        # trust-ratio scaling makes LAMB deliberate at tiny scale: require
        # monotone-ish descent through the freeze point, not a big drop
        assert losses[-1] < losses[5] < losses[0], losses


class TestOneBitWire:
    """1-bit Adam with a REAL 1-bit wire (round-4 item 4): dense reduction
    during freeze_step warmup, sign+scale compressed reduction after."""

    def test_one_bit_adam_compressed_wire_parity(self):
        opt = {"type": "onebit_adam", "params": {"lr": 5e-3, "freeze_step": 3}}
        base = _train({}, optimizer=opt, steps=10)
        comp = _train({"quantized_gradients": True,
                       "quantized_gradients_bits": 1},
                      optimizer=opt, steps=10)
        assert comp[-1] < comp[0] * 0.9  # still converges on the 1-bit wire
        # warmup steps are dense-wire: EXACTLY equal trajectories there
        np.testing.assert_allclose(comp[:3], base[:3], rtol=1e-5)
        # compressed phase tracks loosely (sign-only gradients)
        np.testing.assert_allclose(comp, base, rtol=0.25)

    def test_dense_phase_leaves_error_buffers_untouched(self):
        """Observable phase switch: during freeze_step the compressed program
        must not run, so the error-feedback residuals stay exactly zero."""
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology

        reset_topology()
        cfg = {
            "train_micro_batch_size_per_device": 2,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 0,
            "optimizer": {"type": "onebit_adam",
                          "params": {"lr": 1e-3, "freeze_step": 4}},
            "zero_optimization": {"stage": 1, "quantized_gradients": True,
                                  "quantized_gradients_bits": 1},
            "mesh": {"data": 8},
            "seed": 7,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB),
                                          ctx=ctx),
            config=cfg, seed=11)
        assert engine._qgrad_warmup_steps == 4
        rng = np.random.default_rng(3)
        batch = {"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}
        for _ in range(2):
            engine.train_batch(batch)
        err = np.concatenate([np.asarray(x).ravel() for x in
                              jax.tree_util.tree_leaves(engine._qgrad_error)])
        assert not err.any()
        for _ in range(3):  # cross freeze_step
            engine.train_batch(batch)
        err = np.concatenate([np.asarray(x).ravel() for x in
                              jax.tree_util.tree_leaves(engine._qgrad_error)])
        assert err.any()  # compressed wire engaged, residuals now live


class TestZeroOneAdam:
    """0/1 Adam semantics (reference ``runtime/fp16/onebit/zoadam.py``)."""

    def test_sparse_variance_refresh_schedule(self):
        from deepspeed_tpu.ops.optimizers import scale_by_zero_one_adam

        tx = scale_by_zero_one_adam(var_freeze_step=100, var_update_scaler=4)
        params = {"w": jnp.ones((8,))}
        state = tx.init(params)
        g = {"w": jnp.ones((8,), jnp.float32)}
        refreshes = []
        prev = np.asarray(state.nu["w"]).copy()
        for _ in range(16):
            _, state = tx.update(g, state, params)
            cur = np.asarray(state.nu["w"])
            refreshes.append(not np.array_equal(cur, prev))
            prev = cur.copy()
        # dense refresh in the first interval, sparser later
        assert all(refreshes[:4])
        assert sum(refreshes[8:]) < 8

    def test_variance_fully_frozen_after_freeze_step(self):
        from deepspeed_tpu.ops.optimizers import scale_by_zero_one_adam

        tx = scale_by_zero_one_adam(var_freeze_step=4, var_update_scaler=2)
        params = {"w": jnp.ones((8,))}
        state = tx.init(params)
        rng = np.random.default_rng(2)
        for i in range(12):
            g = {"w": jnp.asarray(rng.normal(size=(8,)), jnp.float32)}
            _, state = tx.update(g, state, params)
            if i == 3:
                frozen = np.asarray(state.nu["w"]).copy()
        np.testing.assert_array_equal(np.asarray(state.nu["w"]), frozen)

    def test_trains(self):
        losses = _train(
            {},
            optimizer={"type": "zero_one_adam",
                       "params": {"lr": 3e-3, "var_freeze_step": 5,
                                  "var_update_scaler": 2}},
            steps=8,
        )
        assert losses[-1] < losses[0] * 0.9, losses


class TestLoco:
    """LOCO reducer (reference ``coalesced_collectives.py:81``)."""

    def test_mean_within_tolerance(self, data_mesh):
        from deepspeed_tpu.comm.quantized_collectives import (
            loco_quantized_all_reduce_arrays,
        )

        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(8, 1024)), jnp.float32)
        el = jnp.zeros_like(x)
        es = jnp.zeros((8, 1024 // 8), jnp.float32)
        mean, _, _ = jax.jit(
            lambda x, el, es: loco_quantized_all_reduce_arrays(
                x, el, es, data_mesh, "data"))(x, el, es)
        np.testing.assert_allclose(np.asarray(mean[0]),
                                   np.asarray(x.mean(axis=0)),
                                   rtol=0.0, atol=0.05)

    def test_error_feedback_kills_bias(self, data_mesh):
        from deepspeed_tpu.comm.quantized_collectives import (
            loco_quantized_all_reduce_arrays,
        )

        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(8, 1024)), jnp.float32)
        true = np.asarray(x.mean(axis=0))
        el = jnp.zeros_like(x)
        es = jnp.zeros((8, 1024 // 8), jnp.float32)
        f = jax.jit(lambda x, el, es: loco_quantized_all_reduce_arrays(
            x, el, es, data_mesh, "data"))
        acc = np.zeros_like(true)
        n_rounds = 24
        for _ in range(n_rounds):
            mean, el, es = f(x, el, es)
            acc += np.asarray(mean[0])
        # the time-average converges to the true mean (both residual sinks
        # re-inject their quantization error)
        np.testing.assert_allclose(acc / n_rounds, true, rtol=0.0, atol=5e-3)
