"""Quantized collectives (ZeRO++ qgZ / 1-bit comm) — int8 on the wire,
error-feedback convergence, engine training parity
(reference: ``tests/unit/comm``, ``tests/unit/runtime/comm`` + onebit suites)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.quantized_collectives import quantized_all_reduce_arrays
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models import llama

VOCAB = 256


@pytest.fixture
def data_mesh():
    return init_distributed(MeshConfig(data=8)).mesh


class TestQuantizedAllReduce:
    def test_mean_within_quantization_tolerance(self, data_mesh):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(8, 1000)).astype(np.float32))
        err = jnp.zeros_like(x)
        mean, _ = jax.jit(
            lambda x, e: quantized_all_reduce_arrays(x, e, data_mesh, "data")
        )(x, err)
        true = np.asarray(x).mean(axis=0)
        rel = np.abs(np.asarray(mean)[0] - true).max() / np.abs(true).max()
        assert rel < 0.02, rel

    def test_error_feedback_kills_bias(self, data_mesh):
        """Averaging repeated reductions of the SAME tensor must converge to
        the exact mean — the error-feedback property 1-bit Adam relies on."""
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(8, 512)).astype(np.float32))
        e = jnp.zeros_like(x)
        f = jax.jit(lambda x, e: quantized_all_reduce_arrays(x, e, data_mesh, "data"))
        acc = np.zeros(512)
        n = 40
        for _ in range(n):
            m, e = f(x, e)
            acc += np.asarray(m)[0]
        true = np.asarray(x).mean(axis=0)
        one_shot = np.abs(np.asarray(f(x, jnp.zeros_like(x))[0])[0] - true).max()
        with_ef = np.abs(acc / n - true).max()
        assert with_ef < one_shot / 5, (with_ef, one_shot)

    def test_wire_dtype_is_int8(self, data_mesh):
        """The VERDICT 'done' criterion: the collective operands in the
        compiled HLO are s8, i.e. compression happens ON THE WIRE, not just
        numerically."""
        x = jnp.zeros((8, 256), jnp.float32)
        f = jax.jit(lambda x, e: quantized_all_reduce_arrays(x, e, data_mesh, "data"))
        txt = f.lower(x, jnp.zeros_like(x)).compile().as_text()
        a2a = [l for l in txt.splitlines() if "all-to-all" in l]
        ag = [l for l in txt.splitlines() if "all-gather" in l]
        assert a2a and any("s8[" in l for l in a2a), "all-to-all payload not int8"
        assert ag and any("s8[" in l for l in ag), "all-gather payload not int8"


def _train(config_extra, optimizer=None, steps=6, seed=3):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": optimizer or {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1, **config_extra},
        "mesh": {"data": 8},
        "seed": 7,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11,
    )
    rng = np.random.default_rng(seed)
    batch = {"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}
    return [float(engine.train_batch(batch)) for _ in range(steps)]


class TestQuantizedTraining:
    def test_convergence_parity_vs_exact_reduction(self):
        """qgZ-compressed training must track the exact-reduction trajectory
        closely (not bit-exact — int8 wire — but convergent and close)."""
        base = _train({})
        quant = _train({"quantized_gradients": True})
        assert quant[-1] < quant[0] * 0.8  # converges
        np.testing.assert_allclose(quant, base, rtol=0.06)

    def test_requires_pure_dp_mesh(self):
        reset_topology()
        with pytest.raises(ValueError, match="data-parallel"):
            deepspeed_tpu.initialize(
                model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
                config={
                    "train_micro_batch_size_per_device": 2,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1, "quantized_gradients": True},
                    "mesh": {"data": 2, "fsdp": 4},
                },
            )


class TestOnebitAdam:
    def test_matches_adamw_during_warmup(self):
        """With freeze_step beyond the run, 1-bit Adam IS Adam(W wd=0)."""
        adam = _train({}, optimizer={"type": "adam", "params": {"lr": 1e-2}})
        onebit = _train({}, optimizer={
            "type": "onebit_adam",
            "params": {"lr": 1e-2, "freeze_step": 1000},
        })
        np.testing.assert_allclose(onebit, adam, rtol=1e-4)

    def test_frozen_variance_with_quantized_comm_converges(self):
        """The full 1-bit Adam recipe: warmup with exact stats, then frozen
        variance + compressed gradient communication."""
        losses = _train(
            {"quantized_gradients": True},
            optimizer={"type": "onebit_adam",
                       "params": {"lr": 3e-3, "freeze_step": 5}},
            steps=10,
        )
        # keeps descending THROUGH the freeze point (step 5)
        assert losses[-1] < losses[5] < losses[0] * 0.85, losses
