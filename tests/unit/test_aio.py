"""Native AIO engine + NVMe swapper (reference: ``tests/unit/ops/aio``,
``runtime/swap_tensor`` suites). Compiles the C++ module on first run."""

import ctypes

import jax
import numpy as np
import pytest

from deepspeed_tpu.ops.op_builder import AsyncIOBuilder
from deepspeed_tpu.runtime.nvme_swap import AsyncTensorSwapper


@pytest.fixture(scope="module")
def aio_lib():
    builder = AsyncIOBuilder()
    if not builder.is_compatible():
        pytest.skip("no g++ toolchain")
    return builder.load()


def test_raw_write_read_roundtrip(aio_lib, tmp_path):
    h = aio_lib.dstpu_aio_create(2, 1 << 16)
    data = np.random.default_rng(0).standard_normal(100_000).astype(np.float32)
    out = np.empty_like(data)
    path = str(tmp_path / "blob.bin").encode()

    wid = aio_lib.dstpu_aio_submit_write(h, path, data.ctypes.data_as(ctypes.c_void_p), data.nbytes)
    assert aio_lib.dstpu_aio_wait(h, wid) == data.nbytes
    rid = aio_lib.dstpu_aio_submit_read(h, path, out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
    assert aio_lib.dstpu_aio_wait(h, rid) == out.nbytes
    np.testing.assert_array_equal(out, data)
    aio_lib.dstpu_aio_destroy(h)


def test_missing_file_returns_errno(aio_lib, tmp_path):
    h = aio_lib.dstpu_aio_create(1, 0)
    buf = np.zeros(16, np.float32)
    rid = aio_lib.dstpu_aio_submit_read(h, str(tmp_path / "nope").encode(),
                                        buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes)
    assert aio_lib.dstpu_aio_wait(h, rid) < 0
    aio_lib.dstpu_aio_destroy(h)


def test_swapper_tree_roundtrip(tmp_path):
    if not AsyncIOBuilder().is_compatible():
        pytest.skip("no g++ toolchain")
    swapper = AsyncTensorSwapper(str(tmp_path), num_threads=2)
    tree = {
        "mu": {"w": np.random.default_rng(1).standard_normal((64, 32)).astype(np.float32)},
        "nu": {"w": np.random.default_rng(2).standard_normal((64, 32)).astype(np.float32)},
    }
    swapper.swap_out_tree("opt", tree)
    swapper.commit()
    back = swapper.swap_in_tree("opt", jax.tree_util.tree_map(np.zeros_like, tree))
    for a, b in zip(jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(a, b)
    swapper.close()


def test_swapper_many_concurrent_writes(tmp_path):
    if not AsyncIOBuilder().is_compatible():
        pytest.skip("no g++ toolchain")
    swapper = AsyncTensorSwapper(str(tmp_path), num_threads=4)
    arrays = {f"a{i}": np.full((1000,), i, np.float32) for i in range(32)}
    for k, v in arrays.items():
        swapper.swap_out(k, v)
    swapper.commit()
    for k, v in arrays.items():
        np.testing.assert_array_equal(swapper.swap_in(k, v.shape, v.dtype), v)
    swapper.close()
