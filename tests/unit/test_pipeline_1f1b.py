"""1F1B pipeline schedule: grad/loss parity vs unpipelined autodiff, PP x fsdp
composition, bubble math (reference ``schedule.py:189 TrainSchedule`` +
``tests/unit/runtime/pipe``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.parallel.pipeline_1f1b import (
    bubble_fraction,
    pipeline_train_grads,
)

V, D, L = 37, 16, 8


def _toy_params(seed=0):
    k = jax.random.split(jax.random.PRNGKey(seed), 3)
    stacked = {"w": jax.random.normal(k[0], (L, D, D)) * 0.3}
    extras = {
        "embed": jax.random.normal(k[1], (V, D)) * 0.5,
        "head": jax.random.normal(k[2], (D, V)) * 0.5,
    }
    return stacked, extras


def _stage0(extras, mb_in):
    return extras["embed"][mb_in["ids"]]


def _block(layer_slice, extras, x):
    del extras
    return jax.lax.scan(
        lambda c, w: (jnp.tanh(c @ w), None), x, layer_slice["w"])[0]


def _last(extras, y, tgt):
    logits = y @ extras["head"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, tgt["labels"][..., None], axis=-1)[..., 0]
    return jnp.mean(logz - true)


def _reference(stacked, extras, ids, labels):
    """Unpipelined autodiff baseline over the SAME microbatch mean."""

    def loss_fn(stacked, extras):
        m = ids.shape[0]
        losses = []
        for i in range(m):
            x = _stage0(extras, {"ids": ids[i]})
            x = _block(stacked, extras, x)
            losses.append(_last(extras, x, {"labels": labels[i]}))
        return sum(losses) / m

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(stacked, extras)
    return loss, grads[0], grads[1]


def _data(m, mb=2, s=6, seed=1):
    r = np.random.default_rng(seed)
    return (r.integers(0, V, (m, mb, s)).astype(np.int32),
            r.integers(0, V, (m, mb, s)).astype(np.int32))


@pytest.mark.parametrize("mesh_cfg,label", [
    (MeshConfig(data=4, pipeline=2), "pp2"),
    (MeshConfig(data=2, pipeline=4), "pp4"),
    (MeshConfig(data=2, pipeline=2, fsdp=2), "pp2xfsdp2"),
    (MeshConfig(data=1, pipeline=2, fsdp=4), "pp2xfsdp4"),
])
def test_grad_parity(mesh_cfg, label):
    reset_topology()
    topo = init_distributed(mesh_cfg)
    stacked, extras = _toy_params()
    m = 6  # microbatches > stages everywhere
    ids, labels = _data(m)

    ref_loss, ref_gl, ref_ge = _reference(stacked, extras, ids, labels)
    loss, gl, ge = jax.jit(
        lambda sp, ex, mi, mt: pipeline_train_grads(
            _stage0, _block, _last, sp, ex, mi, mt, topo.mesh)
    )(stacked, extras, {"ids": jnp.asarray(ids)}, {"labels": jnp.asarray(labels)})

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves((gl, ge)),
                    jax.tree_util.tree_leaves((ref_gl, ref_ge))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_microbatches_below_stages_rejected():
    reset_topology()
    topo = init_distributed(MeshConfig(data=2, pipeline=4))
    stacked, extras = _toy_params()
    ids, labels = _data(2)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_train_grads(_stage0, _block, _last, stacked, extras,
                             {"ids": jnp.asarray(ids)},
                             {"labels": jnp.asarray(labels)}, topo.mesh)


def test_bubble_fraction():
    # GPipe and 1F1B share the bubble; the schedule's win is the P-deep
    # activation stash. M=P gives 2(P-1)/(4P-2) ~ 50%-ish; M>>P -> ~0.
    assert bubble_fraction(4, 4) == pytest.approx(6 / 14)
    assert bubble_fraction(4, 32) == pytest.approx(6 / 70)
    assert bubble_fraction(1, 8) == 0.0


def test_activation_memory_bounded_in_m():
    """The 1F1B stash is P-deep: growing M must not grow live activation
    temps proportionally (GPipe-with-autodiff saves O(M) residuals)."""
    reset_topology()
    topo = init_distributed(MeshConfig(data=4, pipeline=2))
    stacked, extras = _toy_params()

    def temp_bytes(m):
        ids, labels = _data(m, mb=4, s=64)
        c = jax.jit(
            lambda sp, ex, mi, mt: pipeline_train_grads(
                _stage0, _block, _last, sp, ex, mi, mt, topo.mesh)
        ).lower(stacked, extras, {"ids": jnp.asarray(ids)},
                {"labels": jnp.asarray(labels)}).compile()
        return c.memory_analysis().temp_size_in_bytes

    t4, t16 = temp_bytes(4), temp_bytes(16)
    # inputs grow 4x; activations must not: allow 2x total slack
    assert t16 < t4 * 2, (t4, t16)
