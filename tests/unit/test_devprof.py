"""Device-timeline profiler (telemetry/devprof.py).

Pins the PR acceptance criteria: the op classifier and interval-union
overlap math against a checked-in synthetic trace fixture (no live profiler
needed), a live-capture smoke on the CPU backend, windowed capture through
the training engine with profiled steps excluded from stepscope's pinned
invariants and the throughput average, ``/debug/profile`` end-to-end
including concurrent-capture rejection, device-op span nesting in the
merged Perfetto export, capture-dir rotation, and a zero-allocation hot
path when profiling is not configured (tracemalloc-pinned like stepscope)."""

import http.client
import json
import os
import threading
import time
import tracemalloc
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import telemetry
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.telemetry import TELEMETRY
from deepspeed_tpu.telemetry.devprof import (
    ANCHOR_NAME,
    DeviceProfiler,
    _union,
    capture_serving,
    classify_op,
    derive_timeline,
    load_trace_dir,
    merge_into_ring,
    op_family,
    parse_chrome_trace,
    shift_ops,
)
from deepspeed_tpu.telemetry.tracing import TraceContext, _new_span_id

FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                       "devprof_synthetic_trace.json")


def _fixture():
    with open(FIXTURE) as f:
        return json.load(f)


# ---------------------------------------------------------------- classifier

def test_classifier_families():
    assert classify_op("all-reduce.1") == "collective"
    assert classify_op("%all-gather-start.3") == "collective"
    assert classify_op("reduce-scatter.7") == "collective"
    assert classify_op("collective-permute-done.2") == "collective"
    assert classify_op("psum.4") == "collective"
    assert classify_op("fusion.12") == "compute"
    assert classify_op("dot.3") == "compute"
    assert classify_op("dynamic-update-slice.9") == "compute"
    assert classify_op("copy-start.3") == "copy"
    assert classify_op("copy.1") == "copy"
    assert classify_op("MemcpyH2D") == "copy"
    assert classify_op("MemcpyD2H") == "copy"
    assert classify_op("infeed.1") == "infeed_outfeed"
    assert classify_op("outfeed.2") == "infeed_outfeed"
    # reduce-window must NOT hit the reduce-scatter collective family
    assert classify_op("reduce-window.5") == "compute"


def test_op_family_normalization():
    assert op_family("%all-gather-start.3") == "all-gather"
    assert op_family("fusion.12") == "fusion"
    assert op_family("copy-done.2") == "copy"
    assert op_family("MemcpyH2D") == "memcpyh2d"
    assert op_family("reduce.8") == "reduce"


# -------------------------------------------------------------------- parser

def test_parse_synthetic_fixture():
    ops, anchor_us = parse_chrome_trace(_fixture())
    # 9 device ops: 2 via args.hlo_op, 7 via the device-pid "XLA Ops" rule;
    # the anchor, host python spans, the "Steps" aggregate lane, and the
    # zero-duration marker are all excluded
    assert len(ops) == 9
    assert anchor_us == pytest.approx(1000.0)
    names = [o["name"] for o in ops]
    assert "fusion.1" in names and "copy-start.2" in names
    assert "train_step" not in names and "zero-dur-marker" not in names
    by_cls = {}
    for o in ops:
        by_cls[o["cls"]] = by_cls.get(o["cls"], 0) + 1
    assert by_cls == {"compute": 2, "collective": 2, "copy": 4,
                      "infeed_outfeed": 1}
    assert ops == sorted(ops, key=lambda o: o["t0"])


def test_anchor_shift_aligns_clocks():
    ops, anchor_us = parse_chrome_trace(_fixture())
    t_anchor_host = 500.0  # pretend perf_counter at the anchor annotation
    shift_ops(ops, t_anchor_host - anchor_us * 1e-6)
    # fusion.1 starts at the same trace timestamp as the anchor -> lands
    # exactly on the host-side anchor stamp
    first = min(ops, key=lambda o: o["t0"])
    assert first["name"] == "fusion.1"
    assert first["t0"] == pytest.approx(500.0, abs=1e-9)
    assert first["t1"] == pytest.approx(500.0 + 400e-6, abs=1e-9)


# ------------------------------------------------------------- derived math

def test_union_merges_overlapping_intervals():
    assert _union([(1.0, 2.0), (1.5, 3.0), (4.0, 5.0), (5.0, 6.0)]) == [
        (1.0, 3.0), (4.0, 6.0)]
    assert _union([]) == []


def test_overlap_math_exact():
    ops, _ = parse_chrome_trace(_fixture())
    s = derive_timeline(ops)
    # compute union [1000,1400]+[1600,1800]us; all-reduce [1200,1500] overlaps
    # 200us, all-gather [1700,1800] overlaps 100us -> 300/400 = 0.75
    assert s["collective_seconds"] == pytest.approx(400e-6)
    assert s["collective_overlapped_seconds"] == pytest.approx(300e-6)
    assert s["overlap_fraction_measured"] == pytest.approx(0.75)
    assert s["class_seconds"]["compute"] == pytest.approx(600e-6)
    assert s["class_seconds"]["collective"] == pytest.approx(400e-6)
    assert s["class_seconds"]["copy"] == pytest.approx(95e-6)
    assert s["class_seconds"]["infeed_outfeed"] == pytest.approx(30e-6)
    assert s["copy_seconds"]["h2d"] == pytest.approx(20e-6)
    assert s["copy_seconds"]["d2h"] == pytest.approx(15e-6)
    assert s["copy_seconds"]["device"] == pytest.approx(60e-6)
    # busy union 825us over the [1000,1980]us window -> idle 155/980
    assert s["window_s"] == pytest.approx(980e-6)
    assert s["device_busy_s"] == pytest.approx(825e-6)
    assert s["idle_fraction"] == pytest.approx(155.0 / 980.0)
    top = {t["op"]: t for t in s["top_ops"]}
    assert top["fusion"]["seconds"] == pytest.approx(600e-6)
    assert top["fusion"]["count"] == 2
    assert s["top_ops"][0]["op"] == "fusion"  # sorted by seconds desc
    colls = {c["op"] for c in s["collectives"]}
    assert colls == {"all-reduce", "all-gather"}


def test_derive_empty_ops_is_vacuous():
    s = derive_timeline([])
    assert s["op_count"] == 0
    assert s["overlap_fraction_measured"] == 1.0  # no wire time to expose
    assert s["idle_fraction"] == 0.0
    assert s["top_ops"] == []


# ------------------------------------------------------------- ring merging

def _host_span(tracer, name, t0, t1, parent=None, trace_id=None):
    ctx = TraceContext(trace_id or uuid.uuid4().hex, _new_span_id(),
                       parent.span_id if parent else None)
    tracer.finish(ctx, name, t0, t1)
    return ctx


def test_merge_nests_under_smallest_host_span():
    telemetry.configure(enabled=True, tracing=True)
    tracer = TELEMETRY.tracer
    step = _host_span(tracer, "train/step", 100.0, 101.0)
    fwd = _host_span(tracer, "train/phase/forward", 100.0, 100.5,
                     parent=step, trace_id=step.trace_id)
    bwd = _host_span(tracer, "train/phase/backward", 100.5, 101.0,
                     parent=step, trace_id=step.trace_id)
    ops = [
        {"name": "fusion.1", "family": "fusion", "cls": "compute",
         "t0": 100.1, "t1": 100.3},
        {"name": "all-reduce.1", "family": "all-reduce", "cls": "collective",
         "t0": 100.6, "t1": 100.9},
        {"name": "dot.9", "family": "dot", "cls": "compute",
         "t0": 102.4, "t1": 102.6},  # outside every host span
    ]
    merged = merge_into_ring(tracer, ops)
    assert merged == 3
    spans = {s["name"]: s for s in tracer.snapshot()
             if s["name"].startswith("device/")}
    assert spans["device/compute/fusion"]["parent_id"] == fwd.span_id
    assert spans["device/collective/all-reduce"]["parent_id"] == bwd.span_id
    # the orphan hangs off the synthetic window root, not floating free
    root = spans["device/window"]
    assert spans["device/compute/dot"]["parent_id"] == root["span_id"]
    assert spans["device/compute/fusion"]["attrs"]["hlo_op"] == "fusion.1"


def test_merge_caps_op_count():
    telemetry.configure(enabled=True, tracing=True)
    tracer = TELEMETRY.tracer
    ops = [{"name": f"dot.{i}", "family": "dot", "cls": "compute",
            "t0": float(i), "t1": float(i) + 0.5} for i in range(50)]
    merged = merge_into_ring(tracer, ops, max_ops=10)
    assert merged == 10


# ------------------------------------------------------- live capture (CPU)

def test_live_capture_smoke(tmp_path):
    telemetry.configure(enabled=True, tracing=True)
    prof = DeviceProfiler(TELEMETRY, out_dir=str(tmp_path), keep=2)
    assert prof.begin(tag="smoke")
    try:
        x = jnp.ones((64, 64), jnp.float32)
        y = jax.jit(lambda a: a @ a)(x)
        jax.block_until_ready(y)
    finally:
        res = prof.end(kind="train")
    assert res is not None
    summary = res["summary"]
    assert summary["op_count"] > 0, "live CPU capture produced no device ops"
    assert summary["class_seconds"]["compute"] > 0.0
    assert 0.0 <= summary["overlap_fraction_measured"] <= 1.0
    assert res["trace_path"] and os.path.exists(res["trace_path"])
    # metrics exported, including the measured-source overlap gauge
    reg = TELEMETRY.registry
    assert reg.counter("devprof_captures_total").value(trigger="smoke") == 1
    assert 0.0 <= reg.gauge("train_overlap_fraction").value(
        source="measured") <= 1.0
    assert reg.counter("devprof_ops_total").value(
        **{"class": "compute"}) > 0
    # the capture slot is released: a new window can start
    assert prof.begin(tag="smoke2")
    prof.abort()


def test_single_concurrent_capture_guard(tmp_path):
    prof_a = DeviceProfiler(out_dir=str(tmp_path / "a"))
    prof_b = DeviceProfiler(out_dir=str(tmp_path / "b"))
    assert prof_a.begin()
    try:
        # the guard is process-wide, not per-instance
        assert not prof_b.begin()
        assert not prof_a.begin()
    finally:
        prof_a.abort()
    assert prof_b.begin()
    prof_b.abort()


def test_capture_dirs_rotate(tmp_path):
    prof = DeviceProfiler(out_dir=str(tmp_path), keep=2)
    for _ in range(4):
        assert prof.begin()
        jax.block_until_ready(jnp.zeros((8, 8)) + 1.0)
        assert prof.end() is not None
    caps = sorted(p for p in os.listdir(tmp_path) if p.startswith("cap-"))
    assert len(caps) == 2, f"rotation kept {caps}"
    pid = os.getpid()
    assert caps == [f"cap-{pid}-000003", f"cap-{pid}-000004"]


def test_capture_dirs_per_worker(tmp_path):
    """Regression: capture dirs are pid-scoped and rotation never touches a
    sibling worker's captures in the same shared runs/devprof dir."""
    foreign = [tmp_path / "cap-999999-000001", tmp_path / "cap-999999-000002",
               tmp_path / "cap-999999-000003"]
    for d in foreign:
        d.mkdir()
    prof = DeviceProfiler(out_dir=str(tmp_path), keep=1)
    for _ in range(3):
        assert prof.begin()
        jax.block_until_ready(jnp.zeros((4, 4)) + 1.0)
        assert prof.end() is not None
    caps = sorted(p for p in os.listdir(tmp_path) if p.startswith("cap-"))
    # every foreign (other-pid) dir survives; local ones rotated to keep=1
    for d in foreign:
        assert d.exists(), "rotation deleted another worker's capture"
    local = [c for c in caps if c.startswith(f"cap-{os.getpid()}-")]
    assert local == [f"cap-{os.getpid()}-000003"]


def test_load_trace_dir_missing():
    assert load_trace_dir("/nonexistent/devprof") == (None, None)


# --------------------------------------------------- engine windowed capture

def _train_engine(tmp_path, interval=2):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "sequence_length": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": 8},
        "telemetry": {
            "enabled": True,
            "stepscope": {
                "enabled": True,
                "profile_interval_steps": interval,
                "profile_dir": str(tmp_path),
                "profile_keep": 2,
            },
        },
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(256), ctx=ctx),
        config=cfg)
    return engine


def _batch(n=16, seq=16):
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 256, (n, seq), dtype=np.int32)}


def test_engine_windowed_capture_and_invariants(tmp_path):
    engine = _train_engine(tmp_path, interval=2)
    batch = _batch()
    for _ in range(4):
        engine.train_batch(batch)  # global_steps 0..3 -> capture at step 2
    res = engine.devprof_last
    assert res is not None, "interval trigger never completed a capture"
    assert res["summary"]["op_count"] > 0
    assert 0.0 <= res["summary"]["overlap_fraction_measured"] <= 1.0
    assert res["summary"]["trigger"] == "stepscope"

    # regression pin: a capture mid-run leaves stepscope's invariants
    # intact — the profiled step is excluded from averages and the ±5%
    # phase-sum pin still holds over the counted steps
    s = engine.stepscope.summary()
    assert s["steps"] == 3
    assert s["profiled_steps"] == 1
    assert s["phase_sum_over_step_ratio"] == pytest.approx(1.0, abs=0.05)
    assert s["goodput_seconds"]["profiling"] > 0.0

    # throughput exclusion: the compile-bearing first step AND the
    # capture-bearing step are both out of the average
    assert engine.tput_timer.excluded_count >= 2

    # both overlap sources on the scrape
    reg = TELEMETRY.registry
    prom = reg.render_prometheus()
    assert 'train_overlap_fraction{source="estimate"}' in prom
    assert 'train_overlap_fraction{source="measured"}' in prom

    # merged Perfetto export: device ops nest under host step/phase spans
    events = TELEMETRY.dump_trace()["traceEvents"]
    host_ids = {e["args"]["span_id"] for e in events
                if e["name"] == "train/step"
                or e["name"].startswith("train/phase/")}
    device = [e for e in events if e["name"].startswith("device/")]
    assert device, "no device spans merged into the trace ring"
    nested = [e for e in device
              if e["args"].get("parent_id") in host_ids]
    assert nested, "device spans did not nest under host phase spans"
    # profiled step is span-visible and flagged
    flagged = [e for e in events if e["name"] == "train/step"
               and e["args"].get("profiled")]
    assert len(flagged) == 1


def test_disabled_devprof_allocates_nothing(tmp_path):
    engine = _train_engine(tmp_path, interval=0)  # stepscope on, devprof off
    assert engine._devprof is None
    batch = _batch()
    engine.train_batch(batch)  # compile outside the pin
    tracemalloc.start()
    try:
        for _ in range(3):
            engine.train_batch(batch)
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    stats = snap.filter_traces(
        [tracemalloc.Filter(True, "*/telemetry/devprof.py")]).statistics(
            "filename")
    total = sum(s.size for s in stats)
    assert total == 0, f"devprof allocated {total}B while disabled"


# -------------------------------------------------- /debug/profile e2e

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)


@pytest.fixture
def serving_stack():
    from deepspeed_tpu.inference.ragged import (
        RaggedConfig,
        RaggedInferenceEngine,
    )
    from deepspeed_tpu.serving import (
        EngineLoop,
        ReplicaRouter,
        RouterConfig,
        ServingFrontend,
    )

    telemetry.configure(enabled=True, tracing=True)
    eng = RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx),
        RaggedConfig(max_tokens_per_step=16, max_seqs=3, block_size=4,
                     num_blocks=49, max_blocks_per_seq=16),
        dtype=jnp.float32, seed=0)
    loop = EngineLoop(eng, name="devprof-replica")
    router = ReplicaRouter([loop], RouterConfig(max_queue_tokens=96))
    frontend = ServingFrontend(router, port=0)
    loop.start()
    frontend.start()
    yield frontend, loop
    frontend.router.begin_drain()
    loop.join(timeout=60)
    frontend.close()


def _get(frontend, path):
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=120)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    status = resp.status
    conn.close()
    return status, body


def _post_completion(frontend, max_tokens=8):
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=120)
    prompt = [int(t) for t in
              np.random.default_rng(0).integers(0, CFG.vocab_size, 5)]
    conn.request("POST", "/v1/completions",
                 body=json.dumps({"prompt": prompt,
                                  "max_tokens": max_tokens}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


def test_debug_profile_e2e(serving_stack, tmp_path):
    frontend, loop = serving_stack
    results = {}

    def _profile():
        results["profile"] = _get(
            frontend, "/debug/profile?steps=3&timeout_s=20")

    t = threading.Thread(target=_profile)
    t.start()
    time.sleep(0.3)  # let the capture open before the work arrives
    status, _ = _post_completion(frontend, max_tokens=8)
    assert status == 200
    t.join(timeout=60)
    pstatus, pbody = results["profile"]
    assert pstatus == 200, pbody
    payload = json.loads(pbody)
    assert payload["enabled"] is True
    assert payload["requested_steps"] == 3
    assert payload["observed_steps"] >= 3  # prefill + decode steps
    assert payload["summary"]["op_count"] > 0
    assert 0.0 <= payload["summary"]["overlap_fraction_measured"] <= 1.0
    assert loop.steps >= payload["observed_steps"]


def test_debug_profile_rejects_concurrent_capture(serving_stack, tmp_path):
    frontend, _ = serving_stack
    holder = DeviceProfiler(out_dir=str(tmp_path))
    assert holder.begin()
    try:
        status, body = _get(frontend,
                            "/debug/profile?steps=1&timeout_s=0.2")
        assert status == 409
        assert "in progress" in json.loads(body)["error"]["message"]
    finally:
        holder.abort()


def test_debug_profile_rejects_bad_params(serving_stack):
    frontend, _ = serving_stack
    status, _ = _get(frontend, "/debug/profile?steps=abc")
    assert status == 400


def test_capture_serving_idle_window(tmp_path):
    telemetry.configure(enabled=True, tracing=True)

    class _IdleLoop:
        steps = 0

    res = capture_serving([_IdleLoop()], steps=2, max_wait_s=0.2,
                          telemetry=TELEMETRY, out_dir=str(tmp_path))
    assert res is not None
    assert res["observed_steps"] == 0
    assert res["summary"]["overlap_fraction_measured"] == 1.0


def test_anchor_constant_stable():
    # the parser looks the anchor up by name; keep them in lockstep
    assert ANCHOR_NAME == "devprof/anchor"
