"""Quantizer numerics, compressed collectives, OptimizedLinear/LoRA
(reference: ``tests/unit/ops`` quantizer suites, ``runtime/comm`` compressed,
``linear/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.linear import (
    LoRAConfig,
    QuantizedParameter,
    init_lora,
    optimized_linear,
)
from deepspeed_tpu.ops.quantizer import (
    dequantize,
    quantize,
    quantize_dequantize,
    quantization_error,
)
from deepspeed_tpu.runtime.compressed_comm import (
    compressed_grad_allreduce,
    init_error_feedback,
)


def test_int8_roundtrip_error_small():
    x = jax.random.normal(jax.random.PRNGKey(0), (1000,))
    qt = quantize(x, bits=8, block=256)
    assert qt.values.dtype == jnp.int8
    rec = dequantize(qt)
    # int8 symmetric: error bounded by scale/2 per element
    max_scale = float(jnp.max(qt.scales))
    assert float(jnp.max(jnp.abs(rec - x))) <= max_scale * 0.5 + 1e-6


def test_int4_packing_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(1), (512,))
    qt = quantize(x, bits=4, block=128)
    assert qt.values.shape[-1] == 64  # packed two per byte
    rec = dequantize(qt)
    assert rec.shape == x.shape
    # int4 is coarse; check correlation instead of tight error
    corr = float(jnp.corrcoef(jnp.stack([x, rec]))[0, 1])
    assert corr > 0.95


def test_non_divisible_shape_padding():
    x = jax.random.normal(jax.random.PRNGKey(2), (7, 13))
    rec = quantize_dequantize(x, bits=8, block=32)
    assert rec.shape == x.shape
    assert float(jnp.max(jnp.abs(rec - x))) < 0.1


def test_error_feedback_residual_exact():
    x = jax.random.normal(jax.random.PRNGKey(3), (256,))
    err = quantization_error(x, bits=8, block=64)
    rec = quantize_dequantize(x, bits=8, block=64)
    np.testing.assert_allclose(np.asarray(rec + err), np.asarray(x), rtol=1e-6)


def test_compressed_allreduce_mean_and_error_feedback():
    topo = init_distributed(MeshConfig(data=8))
    grads = {"w": jax.random.normal(jax.random.PRNGKey(4), (64, 64))}
    error = init_error_feedback(grads)

    reduced, new_error = jax.jit(
        lambda g, e: compressed_grad_allreduce(g, e, topo.mesh, bits=8)
    )(grads, error)
    # replicated input -> mean equals the dequantized input; error = residual
    approx = np.asarray(reduced["w"])
    np.testing.assert_allclose(approx + np.asarray(new_error["w"]),
                               np.asarray(grads["w"]), atol=1e-5)
    # compression error is small but nonzero
    assert 0 < float(np.abs(np.asarray(new_error["w"])).max()) < 0.05


def test_quantized_parameter_linear():
    w = jax.random.normal(jax.random.PRNGKey(5), (32, 16)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(6), (4, 32))
    qw = QuantizedParameter(w)
    y = optimized_linear(x, qw)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w), rtol=0.1, atol=0.05)


def test_lora_starts_as_identity_and_trains():
    w = jax.random.normal(jax.random.PRNGKey(7), (32, 16)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(8), (4, 32))
    cfg = LoRAConfig(lora_r=4, lora_alpha=8.0)
    lora = init_lora(jax.random.PRNGKey(9), 32, 16, cfg)
    y0 = optimized_linear(x, w, lora=lora, lora_cfg=cfg)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(x @ w), rtol=1e-5)

    # gradients flow only through lora factors when base is quantized-frozen
    qw = QuantizedParameter(w)

    def loss(lora):
        return jnp.sum(optimized_linear(x, qw, lora=lora, lora_cfg=cfg) ** 2)

    g = jax.grad(loss)(lora)
    # with B=0 the adapter output is 0, so dL/dA = 0 but dL/dB != 0
    assert float(jnp.abs(g["lora_b"]).max()) > 0


def test_sharded_base_weight():
    """Reference base_weight_sharding: the frozen base persists SHARDED over
    the fsdp axis (1/world resident per rank between uses); the forward
    gathers on use and matches the unsharded result exactly."""
    from deepspeed_tpu.comm.topology import reset_topology
    from deepspeed_tpu.linear.optimized_linear import shard_base_weight

    reset_topology()
    mesh = init_distributed(MeshConfig(data=1, fsdp=8)).mesh
    w = jax.random.normal(jax.random.PRNGKey(3), (256, 64)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 256))
    qw = QuantizedParameter(w)
    sq = shard_base_weight(qw, mesh)
    # storage is genuinely sharded on the leading (blocked) dim
    assert "fsdp" in str(sq.values.sharding.spec)
    y = jax.jit(lambda x: optimized_linear(x, sq))(x)
    y_ref = optimized_linear(x, qw)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)
    # dense base shards too
    sw = shard_base_weight(w, mesh)
    assert "fsdp" in str(sw.sharding.spec)
