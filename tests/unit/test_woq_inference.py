"""Weight-only quantized inference (reference ``inference/quantization/``
WOQ layers + ``init_inference`` int8 path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.models import llama
from deepspeed_tpu.ops.quantizer import (
    QuantizedWeight,
    maybe_dequantize,
    quantize_params,
)

VOCAB = 256


def _params():
    cfg = llama.LlamaConfig.tiny(VOCAB)
    return cfg, llama.init_params(cfg, jax.random.PRNGKey(0))


def test_quantize_params_structure():
    cfg, params = _params()
    qp = quantize_params(params, bits=8)
    # embedding and norms stay dense
    assert isinstance(qp["embed"], jnp.ndarray)
    assert isinstance(qp["final_norm"], jnp.ndarray)
    # stacked layer weights quantize per layer (leading layer dim kept)
    wq = qp["layers"]["wq"]
    assert isinstance(wq, QuantizedWeight)
    assert wq.values.shape[0] == cfg.num_layers
    assert wq.shape == tuple(params["layers"]["wq"].shape[1:])
    # lax.scan-style slice of the tree dequantizes to the per-layer weight
    sliced = jax.tree_util.tree_map(lambda x: x[0], wq)
    deq = maybe_dequantize(sliced, jnp.float32)
    ref = np.asarray(params["layers"]["wq"][0])
    assert deq.shape == ref.shape
    assert np.abs(np.asarray(deq) - ref).max() < 0.01  # int8 block error


def test_quantized_tree_is_smaller():
    _, params = _params()
    qp = quantize_params(params, bits=8)

    def nbytes(t):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(t)
                   if hasattr(x, "dtype"))

    dense = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params)
    # layer weights dominate; int8 + f32/block scales < bf16
    assert nbytes(qp) < 0.8 * nbytes(dense)


@pytest.mark.parametrize("bits", [8, 4])
def test_woq_logits_close_and_generate(bits):
    reset_topology()
    cfg = llama.LlamaConfig.tiny(VOCAB)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    dense = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx),
                            params=params, dtype=jnp.float32)
    reset_topology()
    woq = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx),
                          params=params, dtype=jnp.float32,
                          quantize_bits=bits)
    ids = np.arange(16, dtype=np.int32)[None, :] % VOCAB
    l_d = np.asarray(dense.forward(ids))
    l_q = np.asarray(woq.forward(ids))
    # int8 tracks closely; int4 more loosely — argmax agreement is the bar
    agree = (l_d.argmax(-1) == l_q.argmax(-1)).mean()
    assert agree >= (0.9 if bits == 8 else 0.6), agree
    out = woq.generate(ids, max_new_tokens=8)
    assert out.shape == (1, 24)


def test_init_inference_int8_config():
    reset_topology()
    cfg = llama.LlamaConfig.tiny(VOCAB)
    eng = init_inference(
        lambda ctx: llama.build(cfg, ctx=ctx),
        config={"dtype": "torch.int8",
                "params": llama.init_params(cfg, jax.random.PRNGKey(0))})
    assert eng.quantize_bits == 8
    eng2 = init_inference(
        lambda ctx: llama.build(cfg, ctx=ctx),
        config={"quant": {"weight": {"num_bits": 4}},
                "params": llama.init_params(cfg, jax.random.PRNGKey(0))})
    assert eng2.quantize_bits == 4


def test_woq_gpt2_and_mixtral():
    """WOQ must work for every model family, not just llama."""
    from deepspeed_tpu.models import gpt2, mixtral

    from deepspeed_tpu.ops.quantizer import quantize_params as qp

    reset_topology()
    g = gpt2.GPT2Config(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
    gspec = gpt2.build(g)
    gparams = qp(gpt2.init_params(g, jax.random.PRNGKey(0)), bits=8,
                 skip=("wte", "wpe"))
    l = np.asarray(jax.jit(gspec.forward_fn)(
        gparams, np.arange(8, dtype=np.int32)[None, :]))
    assert np.isfinite(l).all()
    reset_topology()
    m = mixtral.MixtralConfig.tiny(VOCAB)
    params = mixtral.init_params(m, jax.random.PRNGKey(0))
    spec = mixtral.build(m)
    logits, = [np.asarray(jax.jit(spec.forward_fn)(
        jax.jit(lambda p: qp(p, bits=8))(params),
        np.arange(8, dtype=np.int32)[None, :]))]
    assert np.isfinite(logits).all()


def test_woq_gpt2_engine_path():
    """The ENGINE path must skip gpt2's wte/wpe tables (ModelSpec.woq_skip)."""
    from deepspeed_tpu.models import gpt2

    reset_topology()
    g = gpt2.GPT2Config(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64)
    spec = gpt2.build(g)
    from deepspeed_tpu.ops.quantizer import quantize_params as qp

    gparams = qp(gpt2.init_params(g, jax.random.PRNGKey(0)), bits=8,
                 skip=tuple(spec.woq_skip))
    assert isinstance(gparams["wte"], jnp.ndarray)
    assert isinstance(gparams["wpe"], jnp.ndarray)
    l = np.asarray(jax.jit(spec.forward_fn)(
        gparams, np.arange(8, dtype=np.int32)[None, :]))
    assert np.isfinite(l).all()


def test_woq_load_checkpoint_requantizes(tmp_path):
    """load_checkpoint on a WOQ engine loads dense then re-quantizes."""
    import deepspeed_tpu
    from deepspeed_tpu.ops.quantizer import QuantizedWeight

    reset_topology()
    cfg = llama.LlamaConfig.tiny(VOCAB)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(cfg, ctx=ctx),
        config={"train_micro_batch_size_per_device": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "mesh": {"data": 8}}, seed=11)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, VOCAB, (16, 16), dtype=np.int32)}
    engine.train_batch(batch)
    ckpt = engine.save_checkpoint(str(tmp_path / "ck"))
    del ckpt
    reset_topology()
    eng = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx),
                          dtype=jnp.float32, quantize_bits=8)
    before = np.asarray(eng.params["layers"]["wq"].values).copy()
    eng.load_checkpoint(str(tmp_path / "ck"))
    assert isinstance(eng.params["layers"]["wq"], QuantizedWeight)
    after = np.asarray(eng.params["layers"]["wq"].values)
    assert (before != after).any()  # trained weights actually loaded
    out = eng.generate(np.arange(8, dtype=np.int32)[None, :], max_new_tokens=4)
    assert out.shape == (1, 12)


def test_glob_module_patterns():
    from deepspeed_tpu.compression.scheduler import _match

    assert _match(["*.attention"], "layers/attention")   # glob fallback
    assert _match(["w_gate"], "layers/w_gate")           # substring regex
    assert not _match(["w_gate"], "layers/wq")


def test_ragged_engine_woq():
    from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine

    reset_topology()
    cfg = llama.LlamaConfig.tiny(VOCAB)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = RaggedInferenceEngine(
        lambda ctx: llama.build(cfg, ctx=ctx),
        RaggedConfig(max_seqs=4, num_blocks=64, block_size=16,
                     max_tokens_per_step=32),
        params=params, dtype=jnp.float32, quantize_bits=8)
    eng.put("a", list(range(10)), max_new_tokens=4)
    eng.put("b", list(range(5)), max_new_tokens=4)
    out = eng.generate_all()
    assert len(out["a"]) == 4 and len(out["b"]) == 4
    assert all(0 <= t < VOCAB for t in out["a"] + out["b"])


def test_quant_string_surface_equals_quantize_bits():
    """`quant="woq8"` (the kvquant one-config-surface grammar) must be the
    SAME engine as the legacy `quantize_bits=8` ctor arg, on both the dense
    and the init_inference config paths."""
    reset_topology()
    cfg = llama.LlamaConfig.tiny(VOCAB)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    a = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx),
                        params=params, quant="woq8")
    b = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx),
                        params=params, quantize_bits=8)
    assert a.quantize_bits == b.quantize_bits == 8
    prompt = np.arange(8)[None]
    np.testing.assert_array_equal(
        np.asarray(a.generate(prompt, max_new_tokens=4)),
        np.asarray(b.generate(prompt, max_new_tokens=4)))
    # the string form rides through the reference-style config dict too
    eng = init_inference(
        lambda ctx: llama.build(cfg, ctx=ctx),
        config={"quant": "woq4", "params": params})
    assert eng.quantize_bits == 4
    # a KV codec component is inert on the dense engine (paged-only), not
    # an error: one grammar, each engine takes the parts that apply
    eng2 = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx),
                           params=params, quant="int8+woq8")
    assert eng2.quantize_bits == 8
