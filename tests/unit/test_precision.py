"""Dynamic loss scaler semantics (reference: ``runtime/fp16/loss_scaler.py:187``)."""

import jax.numpy as jnp
import pytest

from deepspeed_tpu.config.config import FP16Config
from deepspeed_tpu.runtime import precision


def _cfg(**kw):
    return FP16Config(enabled=True, **kw)


def test_initial_scale():
    st = precision.init_loss_scale(_cfg(initial_scale_power=8))
    assert float(st.scale) == 256.0


def test_static_scale_never_moves():
    cfg = _cfg(loss_scale=128.0)
    st = precision.init_loss_scale(cfg)
    st = precision.update_loss_scale(st, jnp.asarray(False), cfg)
    assert float(st.scale) == 128.0


def test_overflow_halves_after_hysteresis():
    cfg = _cfg(initial_scale_power=4, hysteresis=2, min_loss_scale=1.0)
    st = precision.init_loss_scale(cfg)
    # first overflow eats hysteresis, scale unchanged
    st = precision.update_loss_scale(st, jnp.asarray(False), cfg)
    assert float(st.scale) == 16.0
    assert int(st.hysteresis) == 1
    # second overflow halves
    st = precision.update_loss_scale(st, jnp.asarray(False), cfg)
    assert float(st.scale) == 8.0


def test_min_scale_floor():
    cfg = _cfg(initial_scale_power=1, hysteresis=1, min_loss_scale=1.0)
    st = precision.init_loss_scale(cfg)
    for _ in range(5):
        st = precision.update_loss_scale(st, jnp.asarray(False), cfg)
    assert float(st.scale) == 1.0


def test_growth_after_window():
    cfg = _cfg(initial_scale_power=4, loss_scale_window=3, hysteresis=2)
    st = precision.init_loss_scale(cfg)
    for _ in range(3):
        st = precision.update_loss_scale(st, jnp.asarray(True), cfg)
    assert float(st.scale) == 32.0
    assert int(st.good_steps) == 0
    assert int(st.hysteresis) == 2  # refilled


def test_min_scale_floor_under_sustained_storm():
    """A sustained overflow storm parks the scale AT min_loss_scale and
    never pushes it below (or to zero): every post-floor overflow is a
    no-op on the scale, not a further halving."""
    cfg = _cfg(initial_scale_power=3, hysteresis=1, min_loss_scale=2.0)
    st = precision.init_loss_scale(cfg)
    seen = []
    for _ in range(20):
        st = precision.update_loss_scale(st, jnp.asarray(False), cfg)
        seen.append(float(st.scale))
    assert seen[-1] == 2.0
    assert min(seen) == 2.0  # floor held through the whole storm
    assert int(st.good_steps) == 0


def test_growth_window_resets_on_single_overflow():
    """One overflow inside the growth window zeroes good_steps: growth
    needs a FULL window of consecutive clean steps afterwards."""
    cfg = _cfg(initial_scale_power=4, loss_scale_window=4, hysteresis=1)
    st = precision.init_loss_scale(cfg)
    for _ in range(3):  # one short of the window
        st = precision.update_loss_scale(st, jnp.asarray(True), cfg)
    assert int(st.good_steps) == 3
    st = precision.update_loss_scale(st, jnp.asarray(False), cfg)
    assert int(st.good_steps) == 0  # window restarted
    assert float(st.scale) == 8.0  # hysteresis=1: the overflow also halved
    # three clean steps are NOT enough to grow again...
    for _ in range(3):
        st = precision.update_loss_scale(st, jnp.asarray(True), cfg)
    assert float(st.scale) == 8.0
    # ...the fourth completes the fresh window
    st = precision.update_loss_scale(st, jnp.asarray(True), cfg)
    assert float(st.scale) == 16.0


def test_grads_finite():
    good = {"a": jnp.ones((3,)), "b": {"c": jnp.zeros((2, 2))}}
    assert bool(precision.grads_finite(good))
    bad = {"a": jnp.array([1.0, jnp.nan]), "b": {"c": jnp.zeros((2, 2))}}
    assert not bool(precision.grads_finite(bad))
    inf = {"a": jnp.array([1.0, jnp.inf])}
    assert not bool(precision.grads_finite(inf))


def test_cast_to_compute_keeps_ints():
    tree = {"w": jnp.ones((2,), jnp.float32), "step": jnp.int32(3)}
    out = precision.cast_to_compute(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["step"].dtype == jnp.int32


def test_optimizer_registry():
    import optax

    from deepspeed_tpu.config.config import OptimizerConfig
    from deepspeed_tpu.ops.optimizers import build_optimizer

    for t in ["adamw", "adam", "sgd", "lion", "lamb", "adagrad"]:
        opt = build_optimizer(OptimizerConfig(type=t, params={"lr": 0.1, "weight_decay": 0.01}))
        assert isinstance(opt, optax.GradientTransformation)
        params = {"w": jnp.ones((4, 4))}
        state = opt.init(params)
        grads = {"w": jnp.ones((4, 4)) * 0.1}
        updates, _ = opt.update(grads, state, params)
        assert updates["w"].shape == (4, 4)
    with pytest.raises(ValueError):
        build_optimizer(OptimizerConfig(type="nope"))
