"""ThroughputTimer: samples/sec and tflops() math, zero-elapsed guards,
recompile-step exclusion (neither metric had direct coverage before)."""

import time

from deepspeed_tpu.utils.timer import ThroughputTimer


def test_tflops_zero_before_any_step():
    t = ThroughputTimer(batch_size=8, steps_per_output=0)
    t.flops_per_sample = 1e9
    assert t.throughput() == 0.0
    assert t.tflops() == 0.0


def test_stop_without_start_is_dropped():
    t = ThroughputTimer(batch_size=8, steps_per_output=0)
    t.flops_per_sample = 1e9
    # pre-warmup misuse: stop() before any start() must not divide against
    # the process epoch (_start == 0.0 would make total_elapsed ~ uptime)
    t.stop(global_step=True)
    assert t.step_count == 0
    assert t.total_elapsed == 0.0
    assert t.tflops() == 0.0


def test_throughput_and_tflops_math():
    t = ThroughputTimer(batch_size=4, steps_per_output=0)
    t.flops_per_sample = 2e12
    for _ in range(3):
        t.start()
        time.sleep(0.01)
        t.stop(global_step=True)
    assert t.step_count == 3
    # samples/sec = batch * steps / elapsed
    expected = 4 * 3 / t.total_elapsed
    assert abs(t.throughput() - expected) < 1e-9
    # tflops = flops_per_sample * samples_per_sec / 1e12
    assert abs(t.tflops() - 2e12 * expected / 1e12) < 1e-6
    assert t.tflops() > 0.0


def test_tflops_zero_without_flops_model():
    t = ThroughputTimer(batch_size=4, steps_per_output=0)
    t.start()
    time.sleep(0.005)
    t.stop(global_step=True)
    assert t.throughput() > 0.0
    assert t.tflops() == 0.0


def test_excluded_steps_do_not_pollute_average():
    t = ThroughputTimer(batch_size=2, steps_per_output=0)
    t.flops_per_sample = 1e12
    # a compile-bearing step: long wall, excluded from the average
    t.start()
    time.sleep(0.05)
    t.stop(global_step=True, exclude=True)
    assert t.step_count == 0
    assert t.excluded_count == 1
    assert t.excluded_elapsed > 0.0
    assert t.throughput() == 0.0
    # last_duration still reflects the excluded step (per-step telemetry)
    assert t.last_duration >= 0.05
    # steady steps after it: the average sees only their wall time
    for _ in range(2):
        t.start()
        time.sleep(0.005)
        t.stop(global_step=True)
    assert t.step_count == 2
    assert t.total_elapsed < 0.05  # compile stall not in the denominator
    steady = 2 * 2 / t.total_elapsed
    assert abs(t.throughput() - steady) < 1e-9
    assert t.tflops() > 0.0
