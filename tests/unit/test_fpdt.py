"""FPDT chunked attention with host-offloaded residuals (reference
``sequence/fpdt_layer.py`` numerics + the 128K-class memory behavior)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.ops.attention import xla_attention
from deepspeed_tpu.parallel.fpdt import fpdt_attention, host_offload_supported

VOCAB = 256


def _qkv(b=2, s=64, h=4, hkv=2, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (b, s, h, d), jnp.float32),
            jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32),
            jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32))


@pytest.mark.parametrize("offload", [False, None])
def test_forward_matches_dense(offload):
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: fpdt_attention(
        q, k, v, num_chunks=4, offload=offload))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_grads_match_dense():
    q, k, v = _qkv()

    def loss_fpdt(q, k, v):
        return jnp.sum(jnp.square(fpdt_attention(q, k, v, num_chunks=4)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v, causal=True)))

    g1 = jax.jit(jax.grad(loss_fpdt, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_noncausal_and_indivisible():
    q, k, v = _qkv(s=48)
    ref = xla_attention(q, k, v, causal=False)
    out = fpdt_attention(q, k, v, num_chunks=3, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="divisible"):
        fpdt_attention(q, k, v, num_chunks=5)


def test_backward_memory_is_subquadratic():
    """Compiled backward temp memory must scale ~S*(S/nc), not S^2 — the
    reference FPDT claim (chunked recompute, no stored score blocks)."""
    b, h, d = 1, 1, 32

    def temp_bytes(s, nc):
        q = jax.ShapeDtypeStruct((b, s, h, d), jnp.float32)

        def loss(q, k, v):
            return jnp.sum(fpdt_attention(q, k, v, num_chunks=nc,
                                          offload=False))

        comp = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q).compile()
        return comp.memory_analysis().temp_size_in_bytes

    # quadrupling S at fixed chunk SIZE (nc scales with S) must grow temps
    # ~4x (linear in S per chunk-pair), nowhere near the 16x of O(S^2)
    t1 = temp_bytes(2048, 8)    # chunk = 256
    t2 = temp_bytes(8192, 32)   # chunk = 256
    assert t2 < 6 * t1, (t1, t2)


@pytest.mark.skipif(not host_offload_supported(),
                    reason="backend has no host memory space")
def test_offload_residuals_compile_and_run():
    q, k, v = _qkv(s=128)

    def loss(q, k, v):
        return jnp.sum(jnp.square(fpdt_attention(q, k, v, num_chunks=8,
                                                 offload=True)))

    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(q, k, v)
    assert np.isfinite(float(val))
    for g in grads:
        assert bool(jnp.isfinite(g).all())


class TestEngineIntegration:
    def test_fpdt_ulysses_training(self):
        reset_topology()
        cfg = {
            "train_micro_batch_size_per_device": 2,
            "steps_per_print": 0,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "sequence_parallel": {"mode": "ulysses", "fpdt_chunks": 4},
            "mesh": {"data": 2, "sequence": 4},
            "sequence_length": 64,
            "seed": 7,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(
                llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
            config=cfg, seed=11,
        )
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, VOCAB, (4, 64), dtype=np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(6)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0] - 0.3

    def test_fpdt_ring_config_rejected(self):
        from deepspeed_tpu.config.base import ConfigError
        from deepspeed_tpu.config.config import Config

        with pytest.raises(ConfigError, match="ulysses"):
            Config.from_dict({
                "train_micro_batch_size_per_device": 1,
                "sequence_parallel": {"mode": "ring", "fpdt_chunks": 4},
            })

    def test_fpdt_single_chunk_rejected(self):
        from deepspeed_tpu.config.base import ConfigError
        from deepspeed_tpu.config.config import Config

        with pytest.raises(ConfigError, match=">= 2"):
            Config.from_dict({
                "train_micro_batch_size_per_device": 1,
                "sequence_parallel": {"fpdt_chunks": 1},
            })
