"""Device-side multi-step decode scheduling + self-speculative decoding
(``sched_steps`` / ``spec_draft``): token-identity parity against the plain
host-staged path across every dispatch mode (greedy AND seeded), prefix-cache
hits, mid-flight cancel during a multi-step chunk, the mid-chunk EOS
retirement masking in the fused programs, warmup coverage of the new
scheduler program family, and the speculation telemetry counters."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.inference.sampling import accept_drafts, propose_ngram_drafts
from deepspeed_tpu.models import llama

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
RCFG = RaggedConfig(
    max_tokens_per_step=16, max_seqs=3, block_size=4,
    num_blocks=49, max_blocks_per_seq=16,
)

# the four dispatch modes the scheduler loop must stay token-identical in
# (mirrors test_prefix_cache.MODES / test_ragged.DISPATCH_MODES)
MODES = {
    "plain": {},
    "tiled": {"prefill_tile": 8},
    "run_ahead": {"decode_run_ahead": 4},
    "fused": {"fused_chunk": 4, "pipeline_depth": 2},
}


def _engine(**over):
    cfg = dataclasses.replace(RCFG, **over)
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), cfg, dtype=jnp.float32, seed=0)


def _prompts(rng=0):
    r = np.random.default_rng(rng)
    return {
        "a": list(r.integers(0, CFG.vocab_size, 5)),
        "b": list(r.integers(0, CFG.vocab_size, 11)),
        "c": list(r.integers(0, CFG.vocab_size, 23)),
    }


def _mixed_load(eng, max_new=8):
    """Greedy rows + seeded-sampled rows in one batch (the scheduler
    program's sampled/greedy lanes must agree with the host path on both)."""
    for uid, p in _prompts(17).items():
        eng.put(uid, p, max_new_tokens=max_new)
    eng.put("s1", _prompts(19)["b"], max_new_tokens=max_new,
            temperature=0.9, top_k=20, seed=123)
    eng.put("s2", _prompts(19)["a"], max_new_tokens=max_new,
            temperature=0.7, top_p=0.9, seed=7)
    return eng.generate_all()


class TestSamplingPrimitives:
    def test_propose_ngram_drafts_most_recent_match(self):
        # row 0: suffix [5, 6] occurred earlier twice; the MOST RECENT
        # match (ending at index 6) supplies the continuation [9, 9, 9]
        hist = jnp.asarray([
            [5, 6, 7, 8, 0, 5, 6, 9, 9, 9, 5, 6],
            [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12],
        ], jnp.int32)
        pos = jnp.asarray([11, 5], jnp.int32)
        draft, matched = propose_ngram_drafts(hist, pos, ngram=2, depth=3)
        assert bool(matched[0])
        assert list(np.asarray(draft[0])) == [9, 9, 9]
        assert not bool(matched[1])          # no earlier [5, 6] in row 1
        assert list(np.asarray(draft[1])) == [0, 0, 0]

    def test_accept_drafts_prefix_budget_eos(self):
        draft = jnp.asarray([[4, 5, 6], [4, 5, 6], [4, 5, 6]], jnp.int32)
        picked = jnp.asarray([
            [4, 5, 9, 7],    # 2 leading matches -> emit 3 (incl. bonus)
            [4, 5, 6, 7],    # full match, but budget clamps to 2
            [4, 99, 6, 7],   # picked[1] is EOS -> truncate inclusive
        ], jnp.int32)
        budget = jnp.asarray([4, 2, 4], jnp.int32)
        eos = jnp.asarray([-1, -1, 99], jnp.int32)
        n_emit, n_acc = accept_drafts(draft, picked, budget, eos)
        assert list(np.asarray(n_emit)) == [3, 2, 2]
        assert list(np.asarray(n_acc)) == [2, 2, 1]


class TestSchedSpecParity:
    """The multi-step scheduler (and speculation on top of it) must emit
    EXACTLY the plain host-staged streams — greedy and seeded — in every
    dispatch mode, because acceptance is exact-match against the target's
    own deterministic picks."""

    @pytest.mark.parametrize("mode", list(MODES))
    def test_token_parity_vs_host_staged(self, mode):
        kw = MODES[mode]
        want = _mixed_load(_engine(device_state=False, **kw))
        sched = _engine(sched_steps=8, **kw)
        assert _mixed_load(sched) == want
        spec = _engine(sched_steps=8, spec_draft=4, **kw)
        assert _mixed_load(spec) == want
        # the sampled stream really sampled (not a greedy fallback)
        greedy = _engine(**kw)
        greedy.put("s1", _prompts(19)["b"], max_new_tokens=8)
        assert greedy.generate_all()["s1"] != want["s1"]

    def test_sched_cuts_dispatches_per_token(self):
        """The whole point of the tentpole: K decode steps per dispatch
        (no admission pressure: the batch fits max_seqs, so nothing caps
        the chunk depth)."""
        outs, engines = {}, {}
        for name, kw in (("base", {"device_state": False}),
                         ("sched", {"sched_steps": 8})):
            eng = _engine(**kw)
            for uid, p in _prompts(17).items():
                eng.put(uid, p, max_new_tokens=10)
            outs[name] = eng.generate_all()
            engines[name] = eng
        assert outs["sched"] == outs["base"]
        base, sched = engines["base"], engines["sched"]
        assert sched.tokens_emitted == base.tokens_emitted
        assert sched.dispatch_count < base.dispatch_count / 2

    def test_sched_off_by_default(self):
        cfg = RaggedConfig()
        assert cfg.sched_steps == 0 and cfg.spec_draft == 0

    def test_config_validation(self):
        with pytest.raises(ValueError):
            _engine(sched_steps=1)
        with pytest.raises(ValueError):
            _engine(spec_draft=2)          # requires sched_steps >= 2
        with pytest.raises(ValueError):
            _engine(sched_steps=4, spec_draft=2, spec_ngram=0)

    def test_kill_switch_leaves_sched_inert(self):
        """device_state=False (the watchdog's degradation rung) silently
        disables the scheduler loop — bit-identical host-staged behavior,
        zero sched dispatches."""
        base = _engine(device_state=False)
        want = _mixed_load(base)
        killed = _engine(device_state=False, sched_steps=8, spec_draft=4)
        assert _mixed_load(killed) == want
        assert killed.dispatch_count == base.dispatch_count

    def test_eos_mid_sched_chunk_truncates(self):
        """A row hitting EOS inside a multi-step chunk retires ON DEVICE:
        tokens after the EOS are never surfaced and the stream matches the
        host-staged run exactly."""
        prompts = _prompts(5)
        probe = _engine(device_state=False)
        for uid, p in prompts.items():
            probe.put(uid, p, max_new_tokens=10)
        ref = probe.generate_all()
        eos = int(ref["b"][2])  # EOS strikes mid-chunk for row "b"
        outs = {}
        for k, kw in (("host", {"device_state": False}),
                      ("sched", {"sched_steps": 8}),
                      ("spec", {"sched_steps": 8, "spec_draft": 4})):
            eng = _engine(**kw)
            for uid, p in prompts.items():
                eng.put(uid, p, max_new_tokens=10, eos_token_id=eos)
            outs[k] = eng.generate_all()
        assert outs["sched"] == outs["host"]
        assert outs["spec"] == outs["host"]
        assert outs["host"]["b"][-1] == eos and len(outs["host"]["b"]) <= 10

    def test_spec_counters_and_acceptance_gauge(self):
        telemetry.configure(enabled=True)
        eng = _engine(sched_steps=8, spec_draft=4)
        # a repetitive prompt gives the n-gram draft source real matches
        pat = [3, 1, 4, 1, 5] * 4
        eng.put("r", pat, max_new_tokens=12)
        eng.generate_all()
        assert eng.spec_proposed > 0
        assert 0 <= eng.spec_accepted <= eng.spec_proposed
        tel = telemetry.get_telemetry()
        assert tel.registry.counter(
            "spec_tokens_proposed_total").value() == eng.spec_proposed
        assert tel.registry.counter(
            "spec_tokens_accepted_total").value() == eng.spec_accepted
        rate = tel.registry.gauge("spec_acceptance_rate").value()
        assert rate == pytest.approx(
            eng.spec_accepted / eng.spec_proposed)


class TestPrefixCacheHitParity:
    def test_hit_parity_with_sched_and_spec(self):
        """A prefix-cache hit under the scheduler loop must still be
        token-identical to a cold run, greedy and seeded."""
        shared = [11, 7, 3, 5, 2, 13, 17, 19]      # two full blocks of 4
        warm_p = shared + [23, 29, 31]
        hit_p = shared + [37, 41]
        cold = _engine(sched_steps=8, spec_draft=4)
        cold.put("g", hit_p, max_new_tokens=8)
        cold.put("s", hit_p, max_new_tokens=8, temperature=0.9, top_k=20,
                 seed=123)
        want = cold.generate_all()

        warm = _engine(sched_steps=8, spec_draft=4,
                       enable_prefix_cache=True)
        warm.put("w", warm_p, max_new_tokens=6)
        warm.generate_all()
        warm.put("g", hit_p, max_new_tokens=8)
        warm.put("s", hit_p, max_new_tokens=8, temperature=0.9, top_k=20,
                 seed=123)
        got = warm.generate_all()
        assert warm.prefix_hits == 2
        assert got["g"] == want["g"] and got["s"] == want["s"]


class TestCancelMidMultiStep:
    @pytest.mark.parametrize("spec", [0, 4])
    def test_cancel_during_inflight_sched_chunk(self, spec):
        """cancel() while a multi-step chunk is in flight: the sequence
        retires via deferred release, blocks and slot recycle, and the
        surviving request's stream is unperturbed."""
        want = None
        for with_cancel in (False, True):
            eng = _engine(sched_steps=8, spec_draft=spec)
            prompts = _prompts(29)
            eng.put("keep", prompts["b"], max_new_tokens=8)
            if with_cancel:
                eng.put("dead", prompts["c"], max_new_tokens=8)
            # drive until a multi-step chunk is actually in flight
            for _ in range(50):
                eng.step()
                if any(r.get("kind") == "sched" for r in eng._pending):
                    break
            assert any(r.get("kind") == "sched" for r in eng._pending)
            if with_cancel:
                assert eng.cancel("dead")
            out = eng.generate_all()
            if with_cancel:
                assert eng.get_request("dead").status == "cancelled"
            if want is None:
                want = out["keep"]
            else:
                assert out["keep"] == want
        assert len(eng._free_slots) == RCFG.max_seqs
        assert eng.allocator.free_blocks == RCFG.num_blocks - 1


class TestFusedEosMasking:
    """Mid-chunk retirement in the FUSED path: a row that hits EOS inside a
    fused chunk stops contributing compute — later steps of its column carry
    the -1 sentinel, never real (wasted) tokens."""

    @pytest.mark.parametrize("device_state", [False, True])
    def test_post_eos_steps_are_masked(self, device_state):
        probe = _engine(device_state=False)
        p = _prompts(7)["b"]
        probe.put("x", p, max_new_tokens=10)
        ref = probe.generate_all()["x"]
        eos = int(ref[1])  # EOS at generated index 1: inside chunk 1

        # depth 2 keeps a chunk in flight across step() returns so the
        # probe below can actually inspect its readback buffer
        eng = _engine(fused_chunk=4, pipeline_depth=2,
                      device_state=device_state)
        eng.put("x", p, max_new_tokens=10, eos_token_id=eos)
        seen_masked = False
        for _ in range(50):
            if not eng.has_work:
                break
            eng.step()
            for rec in eng._inflight_chunks:
                dec = np.asarray(rec["dec_toks"])
                for j, (seq, k_s) in enumerate(rec["decs"]):
                    col = list(dec[:k_s, j])
                    if eos in col:
                        cut = col.index(eos)
                        assert all(t == -1 for t in col[cut + 1:]), (
                            "post-EOS steps surfaced real tokens", col)
                        if cut + 1 < k_s:
                            seen_masked = True
        assert seen_masked, "EOS never struck mid-chunk; probe setup broken"
        out = {u: list(s.generated) for u, s in eng._results.items()}
        assert out["x"] == ref[:2]  # truncated at EOS, nothing extra


class TestWarmupCoverage:
    def test_warmup_lowers_sched_programs(self):
        """warmup() must precompile the multi-step scheduler family too:
        with fused prefill + sched decode warmed, live traffic compiles
        NOTHING (program_cold_dispatches stays 0) and coverage reads 1.0."""
        telemetry.configure(enabled=True)
        eng = _engine(fused_chunk=4, pipeline_depth=2, sched_steps=4)
        assert eng.cfg.device_state
        n = eng.warmup()
        assert n > 0
        assert eng._dev_sched_jits   # scheduler programs actually lowered
        legacy = _engine(device_state=False)
        for uid, p in _prompts(31).items():
            eng.put(uid, p, max_new_tokens=6)
            legacy.put(uid, p, max_new_tokens=6)
        assert eng.generate_all() == legacy.generate_all()
        assert eng.program_dispatches > 0
        assert eng.program_cold_dispatches == 0, (
            "serve-time compile after warmup")
        tel = telemetry.get_telemetry()
        eng._sample_step_telemetry()
        assert tel.registry.gauge("ragged_warmup_coverage").value() == 1.0

    def test_warmup_covers_spec_variant(self):
        eng = _engine(fused_chunk=4, pipeline_depth=2, sched_steps=4,
                      spec_draft=2)
        assert eng.warmup() > 0
        legacy = _engine(device_state=False)
        for uid, p in _prompts(37).items():
            eng.put(uid, p, max_new_tokens=6)
            legacy.put(uid, p, max_new_tokens=6)
        assert eng.generate_all() == legacy.generate_all()
        assert eng.program_cold_dispatches == 0
