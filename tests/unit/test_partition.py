"""Sharding-planner semantics on the 8-device CPU mesh
(reference analogs: ZeRO stage layouts, AutoTP kv-head-aware sharding)."""

import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models import llama
from deepspeed_tpu.parallel.partition import opt_state_shardings, plan_sharding, shard_params


@pytest.fixture
def tiny():
    spec = llama.build(llama.LlamaConfig.tiny())  # d=64 f=128 hq=4 hkv=2 L=2
    params = spec.init_fn(jax.random.PRNGKey(0))
    return spec, params


def _plan(spec, params, topo, stage, **kw):
    return plan_sharding(spec.param_logical_axes, params, topo, zero_stage=stage,
                         dim_units=spec.logical_dim_units, **kw)


def test_stage0_replicated(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=8))
    plan = _plan(spec, params, topo, 0)
    for s in jax.tree_util.tree_leaves(plan.param_specs, is_leaf=lambda x: isinstance(x, P)):
        assert s == P(*([None] * len(s)))


def test_stage3_shards_params_over_fsdp(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    plan = _plan(spec, params, topo, 3)
    # wq [2, 64, 64]: largest within-layer dim sharded over fsdp, layers dim untouched
    assert plan.param_specs["layers"]["wq"] == P(None, "fsdp", None) or \
           plan.param_specs["layers"]["wq"] == P(None, None, "fsdp")
    assert plan.param_specs["embed"][0] is None or "fsdp" in str(plan.param_specs["embed"])
    # grads and opt shards match param layout at stage 3
    assert plan.grad_specs == plan.param_specs == plan.shard_specs


def test_stage2_grads_sharded_params_replicated(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    plan = _plan(spec, params, topo, 2)
    wq_param = plan.param_specs["layers"]["wq"]
    wq_grad = plan.grad_specs["layers"]["wq"]
    assert wq_param == P(None, None, None)
    assert "fsdp" in [a for a in wq_grad if a is not None]


def test_stage1_only_opt_sharded(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    plan = _plan(spec, params, topo, 1)
    assert plan.grad_specs == plan.param_specs  # grads replicated like params
    assert plan.shard_specs != plan.param_specs  # but opt template is sharded


def test_tp_head_sharding_and_kv_guard(tiny):
    spec, params = tiny
    # tensor=4: q heads (4) shardable; kv heads (2) NOT (2 % 4 != 0)
    topo = init_distributed(MeshConfig(data=2, tensor=4))
    plan = _plan(spec, params, topo, 0)
    assert plan.param_specs["layers"]["wq"] == P(None, None, "tensor")
    assert plan.param_specs["layers"]["wk"] == P(None, None, None)  # kv-head guard
    assert plan.param_specs["layers"]["w_gate"] == P(None, None, "tensor")
    assert plan.param_specs["layers"]["w_down"] == P(None, "tensor", None)
    assert plan.param_specs["embed"] == P("tensor", None)

    # tensor=2: kv heads shardable now
    topo = init_distributed(MeshConfig(data=4, tensor=2))
    plan = _plan(spec, params, topo, 0)
    assert plan.param_specs["layers"]["wk"] == P(None, None, "tensor")


def test_tp_plus_fsdp_compose(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=1, fsdp=2, tensor=4))
    plan = _plan(spec, params, topo, 3)
    wq = plan.param_specs["layers"]["wq"]
    assert wq == P(None, "fsdp", "tensor")


def test_persistence_threshold_keeps_small_params_replicated(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    plan = _plan(spec, params, topo, 3, persistence_threshold=500)
    # norms (2*64 = 128 elems) stay replicated; big matrices shard
    assert plan.param_specs["layers"]["attn_norm"] == P(None, None)
    assert "fsdp" in [a for a in plan.param_specs["layers"]["wq"] if a is not None]


def test_batch_spec(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=2, fsdp=2, sequence=2))
    plan = _plan(spec, params, topo, 3)
    assert plan.batch_spec == P(("data", "fsdp"), "sequence")


def test_shard_params_places_arrays(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    plan = _plan(spec, params, topo, 3)
    sharded = shard_params(params, plan)
    wq = sharded["layers"]["wq"]
    assert len(wq.sharding.device_set) == 8
    # each device holds 1/8 of the array
    assert wq.addressable_shards[0].data.size == wq.size // 8


def test_opt_state_sharding_inference(tiny):
    spec, params = tiny
    topo = init_distributed(MeshConfig(data=1, fsdp=8))
    plan = _plan(spec, params, topo, 1)
    opt = optax.adam(1e-3)
    shardings = opt_state_shardings(opt, params, plan)
    state = jax.jit(opt.init, out_shardings=shardings)(params)
    # moments are sharded like the stage-3 layout even though params replicate
    mu_wq = state[0].mu["layers"]["wq"]
    assert mu_wq.addressable_shards[0].data.size == mu_wq.size // 8
