"""Fleet observability plane (telemetry/fleet.py + rollup wiring):
cross-process metric federation merge semantics, atomic snapshot commit +
torn-file tolerance, stale-worker expiry, trace stitching across workers
onto one Perfetto timeline, fleet_health verdicts, the HTTP rollup surface
(/debug/fleet, /metrics/fleet, /healthz degradation), per-replica SLO
labels, heartbeat-age gauges, the pipeline-transport traceparent hop, the
KV-handoff trace seam, and the off-is-free pin (tracemalloc)."""

import http.client
import json
import os
import time
import tracemalloc

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.elasticity.agent import beacon_ages, publish_heartbeat_ages
from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime.pipe.transport import InProcTransport
from deepspeed_tpu.serving import (
    EngineLoop,
    ReplicaRouter,
    RouterConfig,
    ServingFrontend,
)
from deepspeed_tpu.telemetry.fleet import (
    FLEET_SCHEMA,
    FleetAggregator,
    FleetReporter,
    merge_fleet_traces,
    merge_metric_snapshots,
    render_federated_prometheus,
)
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.slo import SloMonitor, default_objectives
from deepspeed_tpu.telemetry.tracing import Tracer, format_traceparent

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
RCFG = RaggedConfig(
    max_tokens_per_step=16, max_seqs=3, block_size=4,
    num_blocks=49, max_blocks_per_seq=16,
)


def _engine():
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), RCFG, dtype=jnp.float32, seed=0)


def _prompt(n, seed=0):
    return [int(t) for t in
            np.random.default_rng(seed).integers(1, CFG.vocab_size, n)]


def _drain(eng, max_steps=500):
    for _ in range(max_steps):
        eng.step()
        if not eng.has_work:
            return
    raise AssertionError("engine did not drain")


class _Worker:
    """Stand-in for one process's telemetry singleton: a private registry +
    tracer pair, enough for a FleetReporter (no global side effects)."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self.tracer = Tracer(self.registry)


def _reporter(tmp_path, name, labels=None, tracing=False):
    w = _Worker()
    if tracing:
        w.tracer.configure(enabled=True)
    rep = FleetReporter(w, out_dir=str(tmp_path), worker=name, labels=labels)
    return w, rep


# ------------------------------------------------------ snapshot commit
class TestAtomicSnapshots:
    def test_publish_atomic_no_temp_left(self, tmp_path):
        w, rep = _reporter(tmp_path, "w1")
        w.registry.counter("c", "").inc(3)
        path = rep.publish()
        assert os.path.basename(path) == "metrics_w1.json"
        # commit protocol: temp + fsync + rename — nothing transient left
        assert not [f for f in os.listdir(tmp_path) if "tmp" in f]
        snap = json.load(open(path))
        assert snap["schema"] == FLEET_SCHEMA
        assert snap["worker"] == "w1" and snap["pid"] == os.getpid()
        assert snap["metrics"]["c"]["series"][0]["value"] == 3

    def test_seq_increments_per_publish(self, tmp_path):
        _, rep = _reporter(tmp_path, "w1")
        rep.publish()
        rep.publish()
        assert json.load(open(rep.metrics_path))["seq"] == 2

    def test_torn_and_foreign_files_skipped(self, tmp_path):
        w, rep = _reporter(tmp_path, "good")
        w.registry.counter("c", "").inc()
        rep.publish()
        # torn write, non-JSON junk, and a schema-less snapshot: all ignored
        (tmp_path / "metrics_torn.json").write_text('{"worker": "torn", "me')
        (tmp_path / "metrics_junk.json").write_bytes(b"\x00\x01binary")
        (tmp_path / "metrics_old.json").write_text(
            json.dumps({"worker": "old", "ts": time.time(), "metrics": {}}))
        agg = FleetAggregator(str(tmp_path), ttl_s=300.0)
        fresh, stale = agg.read_snapshots()
        assert [s["worker"] for s in fresh] == ["good"]
        assert stale == []

    def test_stale_expiry_splits_by_ttl(self, tmp_path):
        w1, rep1 = _reporter(tmp_path, "live")
        w2, rep2 = _reporter(tmp_path, "dead")
        rep1.publish()
        rep2.publish(now=time.time() - 120.0)
        agg = FleetAggregator(str(tmp_path), ttl_s=30.0)
        fresh, stale = agg.read_snapshots()
        assert [s["worker"] for s in fresh] == ["live"]
        assert [s["worker"] for s in stale] == ["dead"]
        payload = agg.debug_payload()
        assert payload["health"]["verdict"] == "degraded"
        assert any("stale" in r for r in payload["health"]["reasons"])
        # stale workers still listed, flagged not-live
        rows = {r["worker"]: r["live"] for r in payload["workers"]}
        assert rows == {"live": True, "dead": False}


# --------------------------------------------------------- merge semantics
class TestMergeSemantics:
    def _snap(self, worker, labels=None, fill=None):
        w = _Worker()
        if fill:
            fill(w.registry)
        return {"schema": FLEET_SCHEMA, "worker": worker, "pid": 1,
                "ts": time.time(), "seq": 1, "labels": labels or {},
                "metrics": w.registry.snapshot()}

    def test_counters_sum_across_workers(self):
        a = self._snap("a", fill=lambda r: r.counter("req", "").inc(3))
        b = self._snap("b", fill=lambda r: r.counter("req", "").inc(4))
        merged = merge_metric_snapshots([a, b])
        assert merged["req"]["kind"] == "counter"
        assert [s["value"] for s in merged["req"]["series"]] == [7]

    def test_counter_label_sets_stay_distinct(self):
        a = self._snap("a", fill=lambda r: r.counter("req", "").inc(
            2, route="x"))
        b = self._snap("b", fill=lambda r: r.counter("req", "").inc(
            5, route="y"))
        merged = merge_metric_snapshots([a, b])
        got = {tuple(sorted(s["labels"].items())): s["value"]
               for s in merged["req"]["series"]}
        assert got == {(("route", "x"),): 2, (("route", "y"),): 5}

    def test_gauges_keep_per_worker_series(self):
        a = self._snap("a", labels={"role": "prefill"},
                       fill=lambda r: r.gauge("depth", "").set(2))
        b = self._snap("b", labels={"role": "decode"},
                       fill=lambda r: r.gauge("depth", "").set(5))
        merged = merge_metric_snapshots([a, b])
        got = {s["labels"]["worker"]: (s["value"], s["labels"]["role"])
               for s in merged["depth"]["series"]}
        assert got == {"a": (2, "prefill"), "b": (5, "decode")}

    def test_reporter_labels_do_not_override_series_labels(self):
        # a series that already carries role= keeps its own value; the
        # reporter-level label only fills the gap
        a = self._snap("a", labels={"role": "reporter"},
                       fill=lambda r: r.gauge("g", "").set(1, role="series"))
        merged = merge_metric_snapshots([a])
        assert merged["g"]["series"][0]["labels"]["role"] == "series"

    def test_histogram_buckets_add(self):
        def fill(v):
            def _f(r):
                h = r.histogram("lat", "", buckets=(0.1, 1.0))
                h.observe(v)
            return _f
        merged = merge_metric_snapshots(
            [self._snap("a", fill=fill(0.05)),
             self._snap("b", fill=fill(0.5))])
        s = merged["lat"]["series"][0]
        assert s["count"] == 2
        assert s["sum"] == pytest.approx(0.55)
        assert s["buckets"]["0.1"] == 1      # cumulative: only the 0.05 obs
        assert s["buckets"]["1.0"] == 2
        assert s["buckets"]["+Inf"] == 2

    def test_kind_conflict_first_wins(self):
        a = self._snap("a", fill=lambda r: r.counter("m", "").inc())
        b = self._snap("b", fill=lambda r: r.gauge("m", "").set(9))
        merged = merge_metric_snapshots([a, b])
        assert merged["m"]["kind"] == "counter"
        assert [s["value"] for s in merged["m"]["series"]] == [1]

    def test_render_federated_prometheus(self):
        a = self._snap("a", fill=lambda r: (
            r.counter("req", "requests").inc(3),
            r.gauge("depth", "").set(2),
            r.histogram("lat", "", buckets=(0.1,)).observe(0.05)))
        b = self._snap("b", fill=lambda r: r.gauge("depth", "").set(5))
        text = render_federated_prometheus(merge_metric_snapshots([a, b]))
        assert "# TYPE req counter" in text
        assert "req 3" in text
        assert 'depth{worker="a"} 2' in text
        assert 'depth{worker="b"} 5' in text
        lines = [l for l in text.splitlines() if l.startswith("lat_bucket")]
        assert lines and '+Inf' in lines[-1]  # +Inf renders last


# ----------------------------------------------------------- health rollup
class TestHealthRollup:
    def _publish(self, tmp_path, name, fill=None, labels=None, now=None):
        w, rep = _reporter(tmp_path, name, labels=labels)
        if fill:
            fill(w.registry)
        rep.publish(now=now)

    def test_verdict_ok(self, tmp_path):
        self._publish(tmp_path, "a", labels={"role": "prefill"})
        self._publish(tmp_path, "b", labels={"role": "decode"})
        agg = FleetAggregator(str(tmp_path), ttl_s=300.0)
        payload = agg.debug_payload()
        assert payload["health"] == {
            "verdict": "ok", "value": 0.0, "reasons": []}
        assert payload["roles"] == {"prefill": 1, "decode": 1}
        assert agg.healthy()

    def test_verdict_critical_without_snapshots(self, tmp_path):
        payload = FleetAggregator(str(tmp_path), ttl_s=1.0).debug_payload()
        assert payload["health"]["verdict"] == "critical"
        assert "no live worker snapshots" in payload["health"]["reasons"]

    def test_one_breaching_worker_degrades(self, tmp_path):
        self._publish(tmp_path, "a", fill=lambda r: r.gauge(
            "slo_breaching", "").set(1, objective="ttft"))
        self._publish(tmp_path, "b")
        payload = FleetAggregator(str(tmp_path), ttl_s=300.0).debug_payload()
        assert payload["health"]["verdict"] == "degraded"
        assert any("slo breaching" in r for r in payload["health"]["reasons"])

    def test_every_worker_breaching_is_critical(self, tmp_path):
        for name in ("a", "b"):
            self._publish(tmp_path, name, fill=lambda r: r.gauge(
                "slo_breaching", "").set(1, objective="ttft"))
        payload = FleetAggregator(str(tmp_path), ttl_s=300.0).debug_payload()
        assert payload["health"]["verdict"] == "critical"

    def test_open_breaker_degrades(self, tmp_path):
        self._publish(tmp_path, "a", fill=lambda r: r.gauge(
            "replica_breaker_state", "").set(2, replica="d0", role="decode"))
        payload = FleetAggregator(str(tmp_path), ttl_s=300.0).debug_payload()
        assert payload["health"]["verdict"] == "degraded"
        assert payload["breakers"][0]["state"] == "open"

    def test_stale_heartbeat_gauge_degrades(self, tmp_path):
        self._publish(tmp_path, "a", fill=lambda r: r.gauge(
            "worker_heartbeat_age_seconds", "").set(400.0, rank="3"))
        agg = FleetAggregator(str(tmp_path), ttl_s=300.0)
        payload = agg.debug_payload()
        assert payload["heartbeat_ages"] == {"3": 400.0}
        assert payload["health"]["verdict"] == "degraded"
        assert any("heartbeat" in r for r in payload["health"]["reasons"])

    def test_fleet_health_gauges_published(self, tmp_path):
        self._publish(tmp_path, "a")
        reg = MetricsRegistry()
        FleetAggregator(str(tmp_path), ttl_s=300.0,
                        registry=reg).debug_payload()
        assert reg.gauge("fleet_health").value() == 0.0
        assert reg.gauge("fleet_workers_live").value() == 1.0

    def test_slo_burn_and_census_rollup(self, tmp_path):
        self._publish(tmp_path, "a", fill=lambda r: (
            r.gauge("slo_burn_rate", "").set(0.5, objective="ttft"),
            r.gauge("memory_census_bytes", "").set(1024),
            r.counter("elastic_restarts_total", "").inc(2)))
        payload = FleetAggregator(str(tmp_path), ttl_s=300.0).debug_payload()
        assert payload["slo_burn"] == {"a": {"ttft": 0.5}}
        assert payload["census"]["a"]["memory_census_bytes"] == 1024
        assert payload["restarts"] == 2


# --------------------------------------------------------- trace stitching
class TestTraceStitching:
    def _two_worker_spill(self, tmp_path):
        """Worker A records a root span; worker B continues the SAME trace
        from A's traceparent (the cross-process seam in miniature)."""
        wa, ra = _reporter(tmp_path, "wa", tracing=True)
        wb, rb = _reporter(tmp_path, "wb", tracing=True)
        root = wa.tracer.extract(None)
        t = time.perf_counter()
        wa.tracer.finish(root, "prefill/request", t, t + 0.01, role="prefill")
        child = wb.tracer.extract(format_traceparent(root))
        wb.tracer.finish(child, "decode/resume", t + 0.02, t + 0.05,
                         role="decode")
        ra.flush()
        rb.flush()
        return root, child

    def test_single_trace_two_process_tracks(self, tmp_path):
        root, child = self._two_worker_spill(tmp_path)
        merged = merge_fleet_traces(str(tmp_path))
        assert merged["otherData"]["trace_ids"] == [root.trace_id]
        spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        assert len(spans) == 2
        # distinct Perfetto process tracks even when spilled from one pid
        assert len({e["pid"] for e in spans}) == 2
        names = {e["name"] for e in merged["traceEvents"] if e["ph"] == "M"}
        assert "process_name" in names
        assert sorted(merged["otherData"]["workers"]) == ["wa", "wb"]

    def test_span_link_survives_the_seam(self, tmp_path):
        root, child = self._two_worker_spill(tmp_path)
        merged = merge_fleet_traces(str(tmp_path))
        by_name = {e["name"]: e for e in merged["traceEvents"]
                   if e["ph"] == "X"}
        resume = by_name["decode/resume"]["args"]
        assert resume["trace_id"] == root.trace_id
        assert resume["parent_id"] == root.span_id

    def test_clock_alignment_preserves_order(self, tmp_path):
        self._two_worker_spill(tmp_path)
        merged = merge_fleet_traces(str(tmp_path))
        by_name = {e["name"]: e for e in merged["traceEvents"]
                   if e["ph"] == "X"}
        assert by_name["prefill/request"]["ts"] <= by_name["decode/resume"]["ts"]
        assert all(e["ts"] >= 0 for e in merged["traceEvents"]
                   if e["ph"] == "X")

    def test_trace_id_filter(self, tmp_path):
        wa, ra = _reporter(tmp_path, "wa", tracing=True)
        t = time.perf_counter()
        keep = wa.tracer.extract(None)
        drop = wa.tracer.extract(None)
        wa.tracer.finish(keep, "keep", t, t + 0.01)
        wa.tracer.finish(drop, "drop", t, t + 0.01)
        ra.flush()
        merged = merge_fleet_traces(str(tmp_path), trace_id=keep.trace_id)
        assert [e["name"] for e in merged["traceEvents"]
                if e["ph"] == "X"] == ["keep"]

    def test_local_ring_dedups_against_own_spill(self, tmp_path):
        wa, ra = _reporter(tmp_path, "wa", tracing=True)
        ctx = wa.tracer.extract(None)
        t = time.perf_counter()
        wa.tracer.finish(ctx, "once", t, t + 0.01)
        ra.flush()
        # the same ring arrives twice: spilled file + live local tracer
        merged = merge_fleet_traces(str(tmp_path), local_tracer=wa.tracer)
        assert [e["name"] for e in merged["traceEvents"]
                if e["ph"] == "X"] == ["once"]


# ------------------------------------------------------ SLO replica labels
class TestSloReplicaLabels:
    def test_two_monitors_publish_disjoint_series(self):
        reg = MetricsRegistry()
        objectives = default_objectives()
        mon_a = SloMonitor(objectives, reg, replica="prefill-0")
        mon_b = SloMonitor(objectives, reg, replica="decode-0")
        for _ in range(SloMonitor.MIN_SAMPLES + 1):
            mon_a.record("ttft", 0.001)
            mon_b.record("ttft", 99.0)
        series = reg.gauge("slo_burn_rate").snapshot()
        by_replica = {s["labels"].get("replica"): s["value"]
                      for s in series if s["labels"]["objective"] == "ttft"}
        assert set(by_replica) == {"prefill-0", "decode-0"}
        assert by_replica["prefill-0"] < by_replica["decode-0"]

    def test_unnamed_monitor_keeps_bare_series(self):
        reg = MetricsRegistry()
        mon = SloMonitor(default_objectives(), reg)
        for _ in range(SloMonitor.MIN_SAMPLES + 1):
            mon.record("ttft", 0.001)
        series = reg.gauge("slo_burn_rate").snapshot()
        assert all("replica" not in s["labels"] for s in series)


# -------------------------------------------------------- heartbeat gauges
class TestHeartbeatAges:
    def test_beacon_ages_worst_of_stage_beacons(self, tmp_path):
        now = time.time()
        p_main = tmp_path / "heartbeat_0.json"
        p_stage = tmp_path / "heartbeat_0_s1.json"
        for p in (p_main, p_stage):
            p.write_text("{}")
        os.utime(p_main, (now - 1.0, now - 1.0))
        os.utime(p_stage, (now - 50.0, now - 50.0))  # wedged stage thread
        ages = beacon_ages(str(tmp_path), now=now)
        assert set(ages) == {0}
        assert ages[0] == pytest.approx(50.0, abs=2.0)

    def test_publish_gauges_with_rank_labels(self, tmp_path):
        now = time.time()
        for rank in (0, 1):
            p = tmp_path / f"heartbeat_{rank}.json"
            p.write_text("{}")
            os.utime(p, (now - 5.0, now - 5.0))
        telemetry.configure(enabled=True)
        ages = publish_heartbeat_ages(str(tmp_path),
                                      telemetry=telemetry.TELEMETRY)
        assert set(ages) == {0, 1}
        g = telemetry.TELEMETRY.registry.gauge("worker_heartbeat_age_seconds")
        for rank in ("0", "1"):
            assert g.value(rank=rank) > 0

    def test_missing_dir_is_empty(self, tmp_path):
        assert beacon_ages(str(tmp_path / "nope")) == {}
        assert publish_heartbeat_ages(None) == {}


# -------------------------------------------------- transport trace seam
class TestTransportHop:
    def test_hop_recorded_under_sender_context(self):
        telemetry.configure(enabled=True, tracing=True)
        tracer = telemetry.TELEMETRY.tracer
        ctx = tracer.extract(None)
        tp = InProcTransport(poll_interval_s=0.01)
        tp.send(0, 1, "act", 0, "payload", traceparent=format_traceparent(ctx))
        payload, waited = tp.recv(0, 1, "act", 0)
        assert payload == "payload" and waited >= 0.0
        spans = [s for s in tracer.snapshot() if s["name"] == "pipe/recv_act"]
        assert len(spans) == 1
        assert spans[0]["trace_id"] == ctx.trace_id
        assert spans[0]["parent_id"] == ctx.span_id
        assert spans[0]["attrs"] == {"src": 0, "dst": 1, "mb": 0}

    def test_untraced_payload_passes_raw(self):
        telemetry.configure(enabled=True, tracing=True)
        tp = InProcTransport(poll_interval_s=0.01)
        sent = object()
        tp.send(0, 1, "act", 0, sent)
        payload, _ = tp.recv(0, 1, "act", 0)
        assert payload is sent
        assert telemetry.TELEMETRY.tracer.snapshot() == []


# ------------------------------------------------------ KV handoff seam
class TestHandoffTraceSeam:
    def test_handoff_carries_one_trace_across_engines(self):
        telemetry.configure(enabled=True, tracing=True)
        tracer = telemetry.TELEMETRY.tracer
        root = tracer.extract(None)
        pre = _engine()
        pre.put("req", _prompt(9), max_new_tokens=4, handoff=True, trace=root)
        _drain(pre)
        rec = pre.export_handoff("req")
        assert rec is not None
        assert rec.traceparent is not None
        assert root.trace_id in rec.traceparent
        dec = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0, params=pre.params)
        assert dec.import_handoff(rec)
        _drain(dec)
        spans = [s for s in tracer.snapshot()
                 if s["name"] == "engine/request"]
        # prefill half + decode half, stitched onto ONE trace id
        assert len(spans) == 2
        assert {s["trace_id"] for s in spans} == {root.trace_id}

    def test_untraced_handoff_has_no_traceparent(self):
        pre = _engine()
        pre.put("req", _prompt(9), max_new_tokens=4, handoff=True)
        _drain(pre)
        rec = pre.export_handoff("req")
        assert rec is not None and rec.traceparent is None


# -------------------------------------------------------- HTTP surface
class TestFleetHttpSurface:
    def _get(self, frontend, path):
        conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                          timeout=60)
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read().decode()
        conn.close()
        ctype = resp.getheader("Content-Type") or ""
        return resp.status, (json.loads(body) if "json" in ctype else body)

    def test_debug_metrics_and_healthz_degradation(self, tmp_path):
        # local worker via the configured reporter + one breaching remote
        telemetry.configure(
            enabled=True,
            fleet={"enabled": True, "dir": str(tmp_path), "worker": "local",
                   "labels": {"role": "decode"}})
        telemetry.TELEMETRY.counter("c", "").inc()
        telemetry.TELEMETRY.fleet.flush()
        w, rep = _reporter(tmp_path, "remote", labels={"role": "prefill"})
        w.registry.gauge("slo_breaching", "").set(1, objective="ttft")
        rep.publish()

        eng = _engine()
        loop = EngineLoop(eng, name="fleet-test")
        frontend = ServingFrontend(
            ReplicaRouter([loop], RouterConfig()), fleet_dir=str(tmp_path))
        frontend.start()
        try:
            st, debug = self._get(frontend, "/debug/fleet")
            assert st == 200
            assert {r["worker"] for r in debug["workers"]} == {
                "local", "remote"}
            assert debug["health"]["verdict"] == "degraded"

            st, page = self._get(frontend, "/metrics/fleet")
            assert st == 200
            assert 'worker="local"' in page or "c 1" in page
            assert ('slo_breaching{objective="ttft",role="prefill",'
                    'worker="remote"} 1') in page

            st, health = self._get(frontend, "/healthz")
            assert st == 200
            assert health["fleet"]["verdict"] == "degraded"
            assert health["status"] == "degraded"
        finally:
            frontend.close()

        # no fleet configured anywhere: the surface reports disabled
        telemetry.TELEMETRY.reset()
        frontend = ServingFrontend(ReplicaRouter([loop], RouterConfig()))
        frontend.start()
        try:
            st, debug = self._get(frontend, "/debug/fleet")
            assert st == 200 and debug == {"enabled": False}
            st, _ = self._get(frontend, "/metrics/fleet")
            assert st == 404
        finally:
            frontend.close()


# ------------------------------------------------------------ off is free
class TestOffIsFree:
    def test_disabled_fleet_and_tracing_zero_alloc(self):
        """Telemetry off: serving a request + pumping untraced transport
        hops must execute zero fleet.py/tracing.py code (tracemalloc pin —
        the ISSUE's zero-alloc acceptance)."""
        eng = _engine()
        eng.put("warm", _prompt(8), max_new_tokens=4)
        _drain(eng)
        tp = InProcTransport(poll_interval_s=0.001)
        tracemalloc.start()
        try:
            eng.put("pin", _prompt(8, seed=1), max_new_tokens=4)
            _drain(eng)
            for mb in range(50):
                tp.send(0, 1, "act", mb, ("x", mb))
                tp.recv(0, 1, "act", mb)
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        for pattern in ("*/telemetry/fleet.py", "*/telemetry/tracing.py"):
            stats = snap.filter_traces(
                [tracemalloc.Filter(True, pattern)]).statistics("filename")
            total = sum(s.size for s in stats)
            assert total == 0, f"{pattern} allocated {total}B while disabled"
