"""Layerwise random token dropping + dynamic batching (reference
``runtime/data_pipeline/data_routing/basic_layer.py`` + ``csrc/random_ltd``;
``data_sampling`` variable-batch utilities) — round-4 item 10."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime.data_pipeline import (
    dynamic_batches,
    pad_dynamic_batch,
)

VOCAB = 128
CFG = llama.LlamaConfig(
    vocab_size=VOCAB, hidden_size=32, intermediate_size=64, num_layers=3,
    num_heads=4, num_kv_heads=2, max_seq_len=64)


class TestLayerwiseLTD:
    def test_grad_flows_through_every_layer(self):
        """Dropped tokens bypass a layer but the layer still trains: every
        layer's weights get nonzero gradients (the gather/scatter route
        keeps the tape intact — the point of LAYERWISE ltd vs data-layer
        dropping)."""
        spec = llama.build(CFG)
        params = spec.init_fn(jax.random.PRNGKey(0))
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, VOCAB, (2, 32), dtype=np.int32)}
        g = jax.grad(lambda p: spec.loss_fn(p, batch,
                                            jax.random.PRNGKey(1),
                                            ltd_keep=16))(params)
        wq = np.asarray(g["layers"]["wq"])  # [L, ...]
        for layer in range(CFG.num_layers):
            assert np.abs(wq[layer]).max() > 0, f"layer {layer} got no grads"

    def test_layers_draw_independent_subsets(self):
        """Each layer keeps its OWN random subset (per-layer fold_in): with
        one layer the kept set is one draw; the 3-layer loss differs from
        any all-layers-same-subset evaluation."""
        spec = llama.build(CFG)
        params = spec.init_fn(jax.random.PRNGKey(0))
        batch = {"input_ids": np.random.default_rng(1).integers(
            0, VOCAB, (2, 32), dtype=np.int32)}
        a = float(spec.loss_fn(params, batch, jax.random.PRNGKey(2),
                               ltd_keep=16))
        b = float(spec.loss_fn(params, batch, jax.random.PRNGKey(3),
                               ltd_keep=16))
        assert a != b  # subset choice moves the loss
        dense = float(spec.loss_fn(params, batch, jax.random.PRNGKey(2)))
        assert a != dense

    def test_engine_schedule_ramps_to_dense(self):
        reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(CFG, ctx=ctx),
            config={
                "train_micro_batch_size_per_device": 2,
                "gradient_accumulation_steps": 2,
                "steps_per_print": 0,
                "optimizer": {"type": "adamw", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1},
                "data_efficiency": {
                    "random_ltd": {"enabled": True,
                                   "start_keep_ratio": 0.5,
                                   "total_steps": 4, "bucket": 8}},
                "mesh": {"data": 8},
                "seed": 7,
            }, seed=11)
        # schedule: 32-token seq, ratio 0.5 -> 1.0 over 4 steps, bucket 8
        assert engine._ltd_keep_for_step(0, 32) == 16
        assert engine._ltd_keep_for_step(2, 32) == 24
        assert engine._ltd_keep_for_step(4, 32) == 0  # dense from here
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, VOCAB, (32, 32),
                                           dtype=np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(8)]
        assert all(np.isfinite(losses))
        # repeated batch must be learned despite per-step subset noise
        assert np.mean(losses[-2:]) < losses[0] * 0.95, losses
        assert set(engine._ltd_jits) == {16, 24, 0}  # one program per bucket

    def test_unsupported_model_raises(self):
        from deepspeed_tpu.models import mixtral

        reset_topology()
        with pytest.raises(ValueError, match="random_ltd"):
            deepspeed_tpu.initialize(
                model=lambda ctx: mixtral.build(
                    mixtral.MixtralConfig.tiny(VOCAB), ctx=ctx),
                config={
                    "train_micro_batch_size_per_device": 2,
                    "steps_per_print": 0,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "data_efficiency": {"random_ltd": {"enabled": True}},
                    "mesh": {"data": 8},
                }, seed=1)


class TestDynamicBatching:
    def test_token_budget_and_coverage(self):
        rng = np.random.default_rng(0)
        lengths = rng.integers(5, 200, (64,))
        batches = dynamic_batches(lengths, max_tokens=512, bucket_step=32,
                                  rng=np.random.default_rng(1))
        seen = [i for idx, _ in batches for i in idx]
        assert sorted(seen) == list(range(64))  # exactly once each
        for idx, padded in batches:
            assert padded % 32 == 0
            assert all(lengths[i] <= padded for i in idx)
            # budget respected whenever more than one row fits
            if len(idx) > 1:
                assert len(idx) * padded <= 512

    def test_long_sequences_get_fewer_rows(self):
        lengths = [30] * 8 + [500] * 8
        batches = dynamic_batches(lengths, max_tokens=1024, bucket_step=32)
        rows = {padded: len(idx) for idx, padded in batches}
        assert rows[32] > rows[512]

    def test_pad_dynamic_batch(self):
        samples = [np.arange(5), np.arange(9)]
        out = pad_dynamic_batch(samples, [0, 1], padded_len=16)
        assert out["input_ids"].shape == (2, 16)
        assert out["attention_mask"][0].sum() == 5
        assert out["attention_mask"][1].sum() == 9
        np.testing.assert_array_equal(out["input_ids"][0, :5], np.arange(5))


class TestMetricCurriculumSampler:
    def test_easy_first_then_everything(self):
        from deepspeed_tpu.runtime.data_pipeline import (
            CurriculumScheduler,
            MetricCurriculumSampler,
        )

        rng = np.random.default_rng(0)
        metrics = rng.normal(size=200)
        sched = CurriculumScheduler(min_difficulty=20, max_difficulty=100,
                                    schedule_type="fixed_linear",
                                    total_curriculum_step=100,
                                    difficulty_step=10)
        s = MetricCurriculumSampler(metrics, sched, seed=1)
        early = s.admitted(0)
        assert len(early) == 40  # easiest 20%
        thr = np.sort(metrics)[len(early) - 1]
        assert metrics[early].max() <= thr + 1e-12
        assert len(s.admitted(100)) == 200  # full set at the end
        batch = s.sample(0, 16)
        assert set(batch) <= set(early)

    def test_tiny_pool_samples_with_replacement(self):
        from deepspeed_tpu.runtime.data_pipeline import (
            CurriculumScheduler,
            MetricCurriculumSampler,
        )

        sched = CurriculumScheduler(min_difficulty=1, max_difficulty=100,
                                    schedule_type="fixed_linear",
                                    total_curriculum_step=10,
                                    difficulty_step=1)
        s = MetricCurriculumSampler(np.arange(10.0), sched, seed=2)
        batch = s.sample(0, 8)
        assert len(batch) == 8  # pool of 1, drawn with replacement
