"""ALST sequence tiling: tiled logits loss + tiled MLP.

Reference behavior matched: ``deepspeed/runtime/sequence_parallel/
ulysses_sp.py:1065 TiledFusedLogitsLoss`` / ``:943 TiledMLP`` — identical
numerics to the untiled path, sub-linear loss-head memory in sequence length.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.api import causal_lm_loss
from deepspeed_tpu.parallel.sequence_tiling import (
    tiled_apply,
    tiled_causal_lm_loss,
)


def _random_case(b=2, s=48, d=16, v=97, seed=0):
    k = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(k, 3)
    hidden = jax.random.normal(k1, (b, s, d), jnp.float32)
    head = jax.random.normal(k2, (d, v), jnp.float32) * 0.1
    ids = jax.random.randint(k3, (b, s), 0, v)
    return hidden, head, ids


class TestTiledLoss:
    @pytest.mark.parametrize("tile", [16, 48, 64])  # divides, equals, exceeds S
    def test_matches_untiled(self, tile):
        hidden, head, ids = _random_case()
        ref = causal_lm_loss(hidden @ head, ids)
        got = tiled_causal_lm_loss(hidden, head, ids, tile_size=tile)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5)

    def test_labels_ignore_index_and_zloss(self):
        hidden, head, ids = _random_case()
        labels = np.array(ids)  # writable copy
        labels[:, ::3] = -100  # mask a third of positions
        labels = jnp.asarray(labels)
        ref = causal_lm_loss(hidden @ head, ids, labels=labels, z_loss=1e-3)
        got = tiled_causal_lm_loss(hidden, head, ids, labels=labels,
                                   z_loss=1e-3, tile_size=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5)

    def test_grads_match(self):
        hidden, head, ids = _random_case(s=32)

        ref_g = jax.grad(
            lambda h, w: causal_lm_loss(h @ w, ids), argnums=(0, 1)
        )(hidden, head)
        got_g = jax.grad(
            lambda h, w: tiled_causal_lm_loss(h, w, ids, tile_size=8), argnums=(0, 1)
        )(hidden, head)
        for r, g in zip(ref_g, got_g):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=2e-4, atol=1e-6)

    def test_loss_head_memory_sublinear(self):
        """Compiled temp memory of the tiled loss must stay far below the
        full [B, S, V] logits block the untiled path materializes."""
        b, s, d, v, tile = 1, 1 << 14, 32, 2048, 512
        hidden = jnp.zeros((b, s, d), jnp.float32)
        head = jnp.zeros((d, v), jnp.float32)
        ids = jnp.zeros((b, s), jnp.int32)

        untiled = jax.jit(
            jax.grad(lambda h, w: causal_lm_loss(h @ w, ids), argnums=(0, 1))
        ).lower(hidden, head).compile()
        tiled = jax.jit(
            jax.grad(lambda h, w: tiled_causal_lm_loss(h, w, ids, tile_size=tile),
                     argnums=(0, 1))
        ).lower(hidden, head).compile()

        logits_bytes = b * s * v * 4
        untiled_temp = untiled.memory_analysis().temp_size_in_bytes
        tiled_temp = tiled.memory_analysis().temp_size_in_bytes
        assert untiled_temp >= logits_bytes
        assert tiled_temp < logits_bytes // 4, (
            f"tiled loss temp {tiled_temp} not sub-linear (logits {logits_bytes})"
        )


class TestTiledApply:
    def test_matches_direct(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 40, 8))
        w = jax.random.normal(jax.random.PRNGKey(1), (8, 24))

        def fn(t):
            return jax.nn.gelu(t @ w)

        np.testing.assert_allclose(
            np.asarray(tiled_apply(fn, x, 16)), np.asarray(fn(x)),
            rtol=1e-5, atol=1e-5,
        )

    def test_grad_matches(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 8))
        w = jax.random.normal(jax.random.PRNGKey(1), (8, 8))

        def loss_direct(w_):
            return jnp.sum(jnp.tanh(x @ w_) ** 2)

        def loss_tiled(w_):
            return jnp.sum(tiled_apply(lambda t: jnp.tanh(t @ w_), x, 8) ** 2)

        np.testing.assert_allclose(
            np.asarray(jax.grad(loss_tiled)(w)),
            np.asarray(jax.grad(loss_direct)(w)),
            rtol=1e-4, atol=1e-6,
        )


class TestEngineIntegration:
    def test_long_context_train_step(self):
        """Multi-thousand-token train step executes end-to-end on the 8-device
        CPU mesh: ring (context-parallel) attention + tiled loss + tiled MLP,
        finite loss. (Longer execution is out of reach for this 1-core CPU box
        — bf16 is emulated; the 128K memory claim is proven by compile-time
        analysis in test_128k_step_fits_memory_budget.)"""
        import deepspeed_tpu
        from deepspeed_tpu.models import llama

        seq = 1 << 12  # 4096 tokens, 512 per device
        cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=8, num_kv_heads=8, max_seq_len=seq)
        config = {
            "train_micro_batch_size_per_device": 1,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "sequence_length": seq,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "mesh": {"sequence": 8},
            "sequence_parallel": {"mode": "ring", "tiled_logits": True,
                                  "tiled_mlp": True, "tile_size": 2048},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(cfg, ctx=ctx), config=config)
        ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, seq), np.int32)
        loss = float(engine.train_batch({"input_ids": ids}))
        assert np.isfinite(loss)

    def test_128k_step_fits_memory_budget(self):
        """Compile (not run) a full 128K-token train step over the 8-device
        mesh with ring attention + ALST tiling and bound its per-device temp
        memory. The untiled loss path provably exceeds the budget: its
        [1, 128K, 32768] fp32 logits alone are 17 GB (> 4 GB budget) before
        counting the backward's second copy; the tiled step's entire compiled
        temp footprint must come in under the budget.
        """
        import deepspeed_tpu
        from deepspeed_tpu.models import llama

        seq = 1 << 17  # 131072 tokens
        vocab = 32768
        cfg = llama.LlamaConfig(
            vocab_size=vocab, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=1, num_kv_heads=1, head_dim=64,
            max_seq_len=seq)
        config = {
            "train_micro_batch_size_per_device": 1,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "sequence_length": seq,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "mesh": {"sequence": 8},
            "sequence_parallel": {"mode": "ring", "tiled_logits": True,
                                  "tiled_mlp": True, "tile_size": 2048},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(cfg, ctx=ctx), config=config)
        fn = engine._build_train_batch_fn()
        ids = np.zeros((1, seq), np.int32)
        batch = engine._put_gas_batch({"input_ids": ids})
        compiled = fn.lower(
            engine.params, engine.opt_state, engine.scale_state,
            jnp.int32(0), engine._rng, batch,
        ).compile()
        budget = 4 << 30
        untiled_logits_bytes = 1 * seq * vocab * 4
        assert untiled_logits_bytes > budget  # what the untiled path would need
        temp = compiled.memory_analysis().temp_size_in_bytes
        assert temp < budget, f"128K tiled step temp {temp/2**30:.2f} GiB > budget"

    def test_tiled_config_matches_untiled_loss(self):
        import deepspeed_tpu
        from deepspeed_tpu.comm.topology import reset_topology
        from deepspeed_tpu.models import llama

        cfg = llama.LlamaConfig.tiny(512)
        ids = np.random.default_rng(1).integers(0, 512, (4, 64), np.int32)
        losses = {}
        for tiled in (False, True):
            reset_topology()
            config = {
                "train_micro_batch_size_per_device": 4,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
                "mesh": {"data": 1},
                "sequence_parallel": {"tiled_logits": tiled, "tiled_mlp": tiled,
                                      "tile_size": 16},
            }
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=lambda ctx: llama.build(cfg, ctx=ctx), config=config,
                mesh_devices=jax.devices()[:1])
            losses[tiled] = float(engine.train_batch({"input_ids": ids}))
        np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
