"""Profiler tracing + debug/sanity modes (reference: nvtx instrumentation,
``enable_sanity_checks``, SURVEY §5.1-5.2)."""

import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.utils.tracing import instrument, named_scope, range_pop, range_push


def _engine(tmp_path, extra):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": 8},
        **extra,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(256), ctx=ctx),
        config=cfg,
    )
    return engine


def _batch(n=16):
    return {"input_ids": np.random.default_rng(0).integers(0, 256, (n, 16),
                                                           dtype=np.int32)}


def test_trace_window_produces_capture(tmp_path):
    trace_dir = str(tmp_path / "trace")
    engine = _engine(tmp_path, {
        "tracing": {"enabled": True, "trace_dir": trace_dir,
                    "start_step": 1, "num_steps": 2},
    })
    for _ in range(4):
        engine.train_batch(_batch())
    engine.step_tracer.close()
    # a profile capture landed on disk (xplane proto under plugins/profile)
    found = [f for root, _, files in os.walk(trace_dir) for f in files]
    assert found, "no trace files written"


def test_capture_survives_raising_step(tmp_path):
    """A step that raises inside the capture window must not wedge the next
    capture: stop_trace() is idempotent and exception-safe, and the stale
    StepTraceAnnotation is exited on the next before_step."""
    trace_dir = str(tmp_path / "trace")
    engine = _engine(tmp_path, {
        "tracing": {"enabled": True, "trace_dir": trace_dir,
                    "start_step": 0, "num_steps": 2},
    })
    orig = engine._put_gas_batch

    def boom(batch):
        raise RuntimeError("injected step failure")

    engine._put_gas_batch = boom
    with pytest.raises(RuntimeError, match="injected"):
        engine.train_batch(_batch())  # fails inside the open window
    engine._put_gas_batch = orig
    # the window recovers: subsequent steps run and the capture closes
    for _ in range(3):
        engine.train_batch(_batch())
    # double stop: second call is a no-op, not an unmatched-stop crash
    engine.step_tracer.stop_trace()
    engine.step_tracer.stop_trace()
    engine.step_tracer.close()
    found = [f for root, _, files in os.walk(trace_dir) for f in files]
    assert found, "no trace files written after mid-window failure"


def test_instrument_and_ranges_run():
    calls = []

    @instrument("unit-span")
    def work(x):
        calls.append(x)
        return x + 1

    assert work(1) == 2 and calls == [1]
    ann = range_push("manual-span")
    range_pop(ann)
    with named_scope("scoped"):
        pass


def test_sanity_checks_catch_bad_batches(tmp_path):
    engine = _engine(tmp_path, {"debug": {"sanity_checks": True}})
    engine.train_batch(_batch())  # good batch passes
    with pytest.raises(ValueError, match="train_batch_size"):
        engine.train_batch(_batch(n=8))
    with pytest.raises(ValueError, match="integer"):
        engine.train_batch({"input_ids": np.zeros((16, 16), np.float32)})
    with pytest.raises(ValueError, match="leading dim"):
        engine.train_batch({"input_ids": _batch()["input_ids"],
                            "labels": np.zeros((4, 16), np.int32)})


def test_debug_nans_config_flag(tmp_path):
    import jax

    engine = _engine(tmp_path, {"debug": {"nans": True}})
    try:
        assert jax.config.jax_debug_nans
        engine.train_batch(_batch())  # clean step passes under the trap
    finally:
        jax.config.update("jax_debug_nans", False)
