"""Collective facade tests on the simulated 8-device CPU mesh.

Reference analog: ``tests/unit/comm/`` — collectives produce correct values and
the comms logger records bytes/counts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import comm
from deepspeed_tpu.config.config import CommsLoggerConfig, MeshConfig
from deepspeed_tpu.utils.comms_logging import COMMS_LOGGER


def _shard_map(fn, mesh, in_specs, out_specs):
    from deepspeed_tpu.utils.compat import shard_map_compat

    return shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=False)


def test_topology_auto_data_axis(mesh8):
    assert mesh8.world_size == 8
    assert mesh8.size("data") == 8
    assert mesh8.dp_world_size == 8
    assert mesh8.describe()


def test_topology_mixed_axes():
    topo = comm.init_distributed(MeshConfig(data=2, fsdp=2, tensor=2))
    assert topo.world_size == 8
    assert topo.dp_world_size == 4  # data * fsdp
    assert set(topo.active_axes()) == {"data", "fsdp", "tensor"}


def test_topology_bad_sizes():
    with pytest.raises(ValueError, match="not divisible"):
        comm.init_distributed(MeshConfig(data=-1, tensor=3))
    with pytest.raises(ValueError, match="product"):
        comm.init_distributed(MeshConfig(data=3, tensor=2))


def test_all_reduce_and_gather(mesh8):
    mesh = mesh8.mesh
    x = jnp.arange(16.0).reshape(8, 2)

    f = _shard_map(lambda v: comm.all_reduce(v, "data"), mesh, (P("data", None),), P("data", None))
    out = jax.jit(f)(x)
    expected = np.broadcast_to(np.asarray(x).sum(0, keepdims=True), (8, 2))
    np.testing.assert_allclose(np.asarray(out), expected)

    g = _shard_map(lambda v: comm.all_gather(v, "data", gather_dim=0), mesh,
                   (P("data", None),), P(None, None))
    np.testing.assert_allclose(np.asarray(jax.jit(g)(x)), np.asarray(x))


def test_reduce_scatter(mesh8):
    mesh = mesh8.mesh
    x = jnp.ones((64, 8))
    f = _shard_map(lambda v: comm.reduce_scatter(v, "data", scatter_dim=0), mesh,
                   (P("data", None),), P("data", None))
    out = jax.jit(f)(x)
    # each rank's (8,8) tile reduce-scatters to a (1,8) shard of row-sums = 8
    np.testing.assert_allclose(np.asarray(out), np.full((8, 8), 8.0))


def test_all_to_all_round_trip(mesh8):
    """Ulysses property: all_to_all then its inverse restores the input."""
    mesh = mesh8.mesh
    x = jnp.arange(8 * 8 * 4.0).reshape(8, 8, 4)  # (seq, heads, dim) sharded on seq

    def fwd(v):
        v = comm.all_to_all(v, "sequence", split_dim=1, concat_dim=0)  # seq-shard -> head-shard
        v = comm.all_to_all(v, "sequence", split_dim=0, concat_dim=1)  # back
        return v

    comm.init_distributed(MeshConfig(data=1, sequence=8))
    mesh = comm.get_mesh()
    f = _shard_map(fwd, mesh, (P("sequence", None, None),), P("sequence", None, None))
    np.testing.assert_allclose(np.asarray(jax.jit(f)(x)), np.asarray(x))


def test_ring_shift(mesh8):
    mesh = mesh8.mesh
    x = jnp.arange(8.0).reshape(8, 1)
    f = _shard_map(lambda v: comm.ring_shift(v, "data", 1), mesh, (P("data", None),), P("data", None))
    out = np.asarray(jax.jit(f)(x)).ravel()
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_comms_logger_records(mesh8):
    comm.configure(CommsLoggerConfig(enabled=True, verbose=False))
    mesh = mesh8.mesh
    x = jnp.ones((8, 4), jnp.float32)
    f = _shard_map(lambda v: comm.all_reduce(v, "data"), mesh, (P("data", None),), P("data", None))
    jax.jit(f)(x).block_until_ready()
    rec = COMMS_LOGGER.traced["all_reduce"]
    assert rec.count >= 1
    assert rec.total_bytes >= 4 * 4  # one shard's bytes
    summary = comm.log_summary()
    assert "all_reduce" in summary


def test_host_collectives_single_process():
    v = np.arange(4.0)
    np.testing.assert_allclose(comm.host_broadcast(v), v)
    comm.barrier()
    out = comm.host_allgather(v)
    assert out.shape == (1, 4)
