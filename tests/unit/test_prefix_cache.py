"""Block-level prefix caching: ref-counted KV block reuse across requests.

The contract under test: with ``enable_prefix_cache=True``, a request whose
prompt shares full cached blocks with a retired request splices those blocks
(no re-prefill) and still generates EXACTLY the tokens a cold engine would —
greedy and sampled-with-fixed-seed, in every dispatch mode. Plus the
allocator invariants that make sharing safe: refcounts, LRU eviction funded
strictly by free memory, and double-free detection.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.ragged import (
    BlockedAllocator,
    RaggedConfig,
    RaggedInferenceEngine,
)
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving.engine_loop import ReplicaStats
from deepspeed_tpu.serving.router import RouterConfig, plan_placement

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)

BS = 4  # block size used throughout — prompts below are built around it


def _engine(cache=False, **over):
    kw = dict(max_tokens_per_step=16, max_seqs=3, block_size=BS,
              num_blocks=49, max_blocks_per_seq=16,
              enable_prefix_cache=cache)
    kw.update(over)
    return RaggedInferenceEngine(
        model=lambda ctx: llama.build(CFG, ctx=ctx),
        ragged_config=RaggedConfig(**kw), dtype=jnp.float32, seed=0)


# the four dispatch modes: plain SplitFuse, tiled prefill, decode run-ahead,
# fused mixed pipeline
MODES = {
    "plain": {},
    "tiled": {"prefill_tile": 8},
    "run_ahead": {"decode_run_ahead": 4},
    "fused": {"fused_chunk": 4, "pipeline_depth": 2},
}

SHARED = [11, 7, 3, 5, 2, 13, 17, 19]          # two full blocks of 4
PROMPT_A = SHARED + [23, 29, 31]               # warms the cache
PROMPT_B = SHARED + [37, 41]                   # must hit both shared blocks


class TestBlockedAllocatorRefcounts:
    def test_acquire_free_refcount_roundtrip(self):
        a = BlockedAllocator(9)
        blocks = a.allocate(2)
        a.acquire(blocks)          # second owner
        a.free(blocks)             # first owner drops
        assert a.free_blocks == 6  # still held by the second owner
        a.free(blocks)
        assert a.free_blocks == 8

    def test_double_free_raises(self):
        a = BlockedAllocator(9)
        blocks = a.allocate(1)
        a.free(blocks)
        with pytest.raises(ValueError, match="double free"):
            a.free(blocks)

    def test_published_blocks_are_retained_then_evicted_lru(self):
        a = BlockedAllocator(5)  # 4 usable
        blocks = a.allocate(4)
        for i, b in enumerate(blocks):
            a.publish(b, ("k", i))
        a.free(blocks)  # all refcount 0 -> retained, LRU order = free order
        assert a.retained_blocks == 4 and a.free_blocks == 4
        # allocation is funded by evicting the OLDEST published blocks
        got = a.allocate(2)
        assert a.evictions == 2
        assert a.lookup(("k", 0)) is None and a.lookup(("k", 1)) is None
        assert a.lookup(("k", 2)) is not None
        a.free(got)

    def test_acquire_removes_from_lru(self):
        a = BlockedAllocator(5)
        blocks = a.allocate(2)
        a.publish(blocks[0], "key0")
        a.free(blocks)
        hit = [a.lookup("key0")]
        a.acquire(hit)  # refcount 0 -> 1, leaves the evictable LRU
        assert a.retained_blocks == 0
        # exhausting the pool must NOT evict the re-referenced block
        a.allocate(a.free_blocks)
        assert a.lookup("key0") == hit[0]

    def test_exhaustion_still_raises(self):
        a = BlockedAllocator(5)
        a.allocate(4)
        with pytest.raises(RuntimeError, match="exhausted"):
            a.allocate(1)


class TestHitVsColdParity:
    """A cache hit must be token-identical to a cold run — the KV spliced
    from the index stands in for KV the engine would have computed."""

    @pytest.mark.parametrize("mode", list(MODES))
    def test_token_exact_greedy_and_seeded_sampled(self, mode):
        kw = MODES[mode]
        cold = _engine(cache=False, **kw)
        cold.put("g", PROMPT_B, max_new_tokens=8)
        cold.put("s", PROMPT_B, max_new_tokens=8, temperature=0.9, top_k=20,
                 seed=123)
        want = cold.generate_all()

        warm = _engine(cache=True, **kw)
        warm.put("warmup", PROMPT_A, max_new_tokens=6)
        warm.generate_all()
        assert warm.prefix_misses == 1 and warm.prefix_hits == 0

        warm.put("g", PROMPT_B, max_new_tokens=8)
        warm.put("s", PROMPT_B, max_new_tokens=8, temperature=0.9, top_k=20,
                 seed=123)
        got = warm.generate_all()
        assert got["g"] == want["g"]
        assert got["s"] == want["s"]
        # both requests spliced the two shared blocks (8 tokens each)
        assert warm.prefix_hits == 2
        assert warm.prefix_tokens_reused == 2 * len(SHARED)
        # sampled-with-fixed-seed really sampled (not greedy fallback)
        assert want["s"] != want["g"]

    def test_partial_block_prefix_falls_back_to_prefill(self):
        warm = _engine(cache=True)
        warm.put(0, [11, 7, 3], max_new_tokens=4)  # < one full block
        warm.generate_all()
        warm.put(1, [11, 7, 3, 99], max_new_tokens=4)
        warm.generate_all()
        assert warm.prefix_hits == 0 and warm.prefix_misses == 2
        # a full-prompt re-ask caps the match one block short of the prompt:
        # 4-token prompt = 1 full block, cap (len-1)//bs = 0 -> still a miss
        warm.put(2, [11, 7, 3, 99], max_new_tokens=4)
        warm.generate_all()
        assert warm.prefix_hits == 0

    def test_disabled_by_default_stays_cold(self):
        eng = _engine(cache=False)
        eng.put(0, PROMPT_A, max_new_tokens=4)
        eng.generate_all()
        eng.put(1, PROMPT_B, max_new_tokens=4)
        eng.generate_all()
        assert eng.prefix_hits == eng.prefix_misses == 0
        assert eng.allocator.cached_blocks == 0
        assert eng.allocator.retained_blocks == 0
        assert eng.cached_prefix_len(PROMPT_B) == 0


class TestLifecycleInvariants:
    def test_refcounts_consistent_under_interleaved_cancel(self):
        eng = _engine(cache=True)
        for uid in range(5):
            eng.put(uid, SHARED + [60 + uid, 61 + uid, 62 + uid],
                    max_new_tokens=6)
        eng.step()
        eng.cancel(1)  # mid-flight: shared blocks must survive the cancel
        eng.generate_all()
        eng.put(9, PROMPT_B, max_new_tokens=4)
        out = eng.generate_all()
        assert len(out[9]) == 4 and eng.prefix_hits >= 1
        alloc = eng.allocator
        # everything is retired: no live references anywhere, and every
        # usable block is either free or retained by the cache
        assert all(r == 0 for r in alloc._refs)
        assert len(alloc._free) + alloc.retained_blocks == alloc.num_blocks - 1
        assert alloc.free_blocks == alloc.num_blocks - 1

    def test_eviction_under_pool_pressure(self):
        # 13 blocks usable (12 + scratch is block 0 of 14): each retired
        # request publishes its full prompt blocks; distinct prompts pile up
        # until allocation must evict
        eng = _engine(cache=True, num_blocks=14, max_seqs=2,
                      max_blocks_per_seq=8)
        rng = np.random.default_rng(7)
        # each retired request publishes 2 blocks and returns 1 to the free
        # list, so the free list shrinks by 2 per round: 8 rounds drain it
        for uid in range(8):
            eng.put(uid, list(rng.integers(0, 97, (8,))), max_new_tokens=4)
            eng.generate_all()
        assert eng.allocator.evictions > 0
        # the pool never deadlocks: a fresh worst-case request still admits
        eng.put("last", list(rng.integers(0, 97, (8,))), max_new_tokens=4)
        assert len(eng.generate_all()["last"]) == 4

    def test_cache_hit_shares_blocks_between_live_sequences(self):
        eng = _engine(cache=True)
        eng.put(0, PROMPT_A, max_new_tokens=4)
        eng.generate_all()
        eng.put(1, PROMPT_B, max_new_tokens=6)
        eng.put(2, SHARED + [71], max_new_tokens=6)
        eng.step()  # admits both; each splices the SAME two cached blocks
        live = list(eng._running.values())
        assert len(live) == 2 and eng.prefix_hits == 2
        assert live[0].blocks[:2] == live[1].blocks[:2]
        # refcount 2: one reference per live sequence sharing the block
        assert all(eng.allocator._refs[b] == 2 for b in live[0].blocks[:2])
        out = eng.generate_all()
        assert len(out[1]) == 6 and len(out[2]) == 6


class TestRouterCacheAwareAdmission:
    def _stats(self, name, free_blocks, outstanding=0):
        return ReplicaStats(
            name=name, alive=True, draining=False, queued=0, inflight=0,
            outstanding_tokens=outstanding, free_blocks=free_blocks,
            pending_blocks=0, block_size=4, usable_blocks=48,
            max_request_blocks=16, max_request_tokens=64)

    def test_cached_prefix_nets_out_block_need(self):
        cfg = RouterConfig(max_queue_tokens=4096)
        # 24 total tokens = 6 blocks worst case; replica has only 4 free
        stats = [self._stats("r0", free_blocks=4)]
        idx, verdict = plan_placement(stats, 24, cfg)
        assert verdict == "queue"
        # 8 cached tokens = 2 blocks already resident -> need 4 -> admit
        idx, verdict = plan_placement(stats, 24, cfg, cached_tokens=[8])
        assert (idx, verdict) == (0, "admit")

    def test_cached_prefix_nets_out_queue_bound(self):
        cfg = RouterConfig(max_queue_tokens=30)
        stats = [self._stats("r0", free_blocks=48, outstanding=10)]
        assert plan_placement(stats, 24, cfg)[1] == "overloaded"
        assert plan_placement(stats, 24, cfg, cached_tokens=[8])[1] == "admit"

    def test_tie_breaks_to_the_replica_holding_the_prefix(self):
        cfg = RouterConfig()
        stats = [self._stats("r0", free_blocks=48),
                 self._stats("r1", free_blocks=48)]
        idx, verdict = plan_placement(stats, 24, cfg, cached_tokens=[0, 8])
        assert (idx, verdict) == (1, "admit")

    def test_partial_block_cached_tokens_do_not_over_credit(self):
        cfg = RouterConfig()
        stats = [self._stats("r0", free_blocks=6)]
        # 3 cached tokens < one block: block need must NOT shrink
        idx, verdict = plan_placement(stats, 24, cfg, cached_tokens=[3])
        assert (idx, verdict) == (0, "admit")
        stats = [self._stats("r0", free_blocks=5)]
        assert plan_placement(stats, 24, cfg, cached_tokens=[3])[1] == "queue"
