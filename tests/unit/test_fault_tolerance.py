"""Serving-path fault tolerance (docs/FAULT_TOLERANCE.md): the deterministic
fault-injection harness, the ragged engine's dispatch watchdog (retry +
automatic degradation), engine-loop crash containment and thread respawn,
the router's circuit breaker with half-open recovery, replica failover with
token-identical replay, deadline shedding, SIGTERM drain under injected
faults, and client-disconnect KV release."""

import http.client
import json
import os
import signal
import socket
import struct
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.elasticity.agent import PreemptionHandler
from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (
    POINT_DISPATCH,
    POINT_LOOP,
    POINT_SUBMIT,
    CompletionRequest,
    EngineLoop,
    FatalFaultError,
    FaultError,
    FaultSpec,
    Overloaded,
    ReplicaRouter,
    RouterConfig,
    ServingFrontend,
    StreamError,
    classify_transient,
    get_fault_injector,
)
from deepspeed_tpu.serving.faults import POINT_ALLOC, POINT_READBACK
from deepspeed_tpu.serving.router import DeadlineExceeded

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
# full device-resident + fused pipeline (the chaos-bench shape): exercises
# the watchdog across the richest dispatch path
WCFG = dict(
    max_tokens_per_step=16, max_seqs=3, block_size=4, num_blocks=49,
    max_blocks_per_seq=16, decode_run_ahead=4, prefill_tile=8,
    fused_chunk=4, pipeline_depth=2, device_state=True,
    dispatch_retries=2, retry_backoff_s=0.01, degrade_after=2)
# plain host-staged single-program path: cheapest to compile, used by the
# loop/router tests that don't care which dispatch family runs
PCFG = dict(
    max_tokens_per_step=16, max_seqs=3, block_size=4, num_blocks=49,
    max_blocks_per_seq=16, decode_run_ahead=0, prefill_tile=0,
    fused_chunk=0, device_state=False,
    dispatch_retries=2, retry_backoff_s=0.01, degrade_after=2)


def _engine(cfg=PCFG, **over):
    rcfg = RaggedConfig(**{**cfg, **over})
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), rcfg,
        dtype=jnp.float32, seed=0)


def _prompt(n, seed=0):
    return [int(t) for t in
            np.random.default_rng(seed).integers(0, CFG.vocab_size, n)]


PROMPTS = [_prompt(6, seed=1), _prompt(11, seed=2), _prompt(17, seed=3)]


def _put_all(eng, max_new=6):
    for i, p in enumerate(PROMPTS):
        eng.put(i, p, max_new_tokens=max_new, temperature=0.8, seed=100 + i)


@pytest.fixture(scope="module")
def ref_tokens():
    """Fault-free reference generation on the full device path; every
    fault-injected run below must reproduce these tokens exactly."""
    eng = _engine(WCFG)
    _put_all(eng)
    return eng.generate_all()


# ----------------------------------------------------------- the injector
class TestFaultInjector:
    def test_off_by_default_and_after_reset(self):
        inj = get_fault_injector()
        assert not inj.enabled
        inj.fire(POINT_DISPATCH)  # disarmed: must be a no-op
        inj.arm(POINT_DISPATCH)
        assert inj.enabled
        inj.reset()
        assert not inj.enabled
        inj.fire(POINT_DISPATCH)

    def test_deterministic_schedule(self):
        inj = get_fault_injector()
        inj.configure([FaultSpec(point=POINT_DISPATCH, after=2, times=2,
                                 every=2)])
        fired = []
        for i in range(10):
            try:
                inj.fire(POINT_DISPATCH)
            except FaultError:
                fired.append(i)
        # eligible hits are 3,4,5,... -> every=2 fires on hits 3 and 5
        assert fired == [2, 4]
        assert inj.counts() == {POINT_DISPATCH: 2}

    def test_request_id_filter_and_fatal(self):
        inj = get_fault_injector()
        inj.configure([{"point": POINT_SUBMIT, "request_id": "r1",
                        "fatal": True}])
        inj.fire(POINT_SUBMIT, request_id="r0")  # not the target
        with pytest.raises(FatalFaultError):
            inj.fire(POINT_SUBMIT, request_id="r1")
        inj.fire(POINT_SUBMIT, request_id="r1")  # times=1: spent

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError):
            FaultSpec(point="engine.nonsense")

    def test_classify_transient_taxonomy(self):
        assert classify_transient(FaultError("x"))
        assert not classify_transient(FatalFaultError("x"))
        assert classify_transient(TimeoutError("stuck"))
        assert classify_transient(ConnectionResetError("gone"))
        assert classify_transient(RuntimeError("transfer UNAVAILABLE: retry"))
        assert not classify_transient(RuntimeError("KV pool exhausted"))
        assert not classify_transient(ValueError("bad shape"))


# ------------------------------------------------------ dispatch watchdog
class TestDispatchWatchdog:
    def test_transient_fault_retried_token_identical(self, ref_tokens):
        eng = _engine(WCFG)
        get_fault_injector().configure(
            [{"point": POINT_DISPATCH, "after": 1}])
        _put_all(eng)
        assert eng.generate_all() == ref_tokens
        assert eng.step_retries >= 1 and eng.step_failures >= 1
        assert eng.degraded_mode == 0
        assert eng.allocator.free_blocks == eng.cfg.num_blocks - 1

    def test_burst_degrades_to_host_staged_fallback(self, ref_tokens):
        eng = _engine(WCFG)
        # two consecutive failures = degrade_after -> automatic fallback
        get_fault_injector().configure(
            [{"point": POINT_DISPATCH, "after": 2, "times": 2}])
        _put_all(eng)
        assert eng.generate_all() == ref_tokens
        assert eng.degraded_mode == 1 and not eng.cfg.device_state
        assert eng.degraded_reason
        assert eng.allocator.free_blocks == eng.cfg.num_blocks - 1

    def test_alloc_and_readback_faults_recover(self, ref_tokens):
        eng = _engine(WCFG)
        get_fault_injector().configure([
            {"point": POINT_ALLOC, "after": 1},
            {"point": POINT_READBACK, "kind": "hang", "after": 3,
             "delay_s": 0.01},
        ])
        _put_all(eng)
        assert eng.generate_all() == ref_tokens
        assert eng.step_failures >= 2
        assert eng.allocator.free_blocks == eng.cfg.num_blocks - 1


# ------------------------------------------------- loop crash containment
class TestCrashContainment:
    def test_fatal_fault_fails_requests_rebuilds_engine(self):
        eng = _engine()
        baseline = eng.allocator.free_blocks
        loop = EngineLoop(eng, name="contain").start()
        try:
            get_fault_injector().configure(
                [{"point": POINT_DISPATCH, "fatal": True}])
            s = loop.submit(CompletionRequest(prompt=_prompt(5),
                                              max_tokens=8))
            with pytest.raises(StreamError):
                s.collect(timeout=60)
            assert s.error_code == 500 and s.error_reason == "engine_crash"
            assert loop.crash_count == 1
            # the loop survived, the engine state was rebuilt, and the
            # replica keeps serving
            assert loop.stats().alive
            assert eng.allocator.free_blocks == baseline
            s2 = loop.submit(CompletionRequest(prompt=_prompt(5),
                                               max_tokens=4))
            tokens, reason = s2.collect(timeout=60)
            assert len(tokens) == 4 and reason == "length"
        finally:
            loop.close(timeout=60)

    def test_loop_thread_death_respawns(self):
        eng = _engine()
        loop = EngineLoop(eng, name="respawn").start()
        try:
            # POINT_LOOP fires outside the step try/except: it kills the
            # loop thread itself, exercising the respawn path
            get_fault_injector().configure(
                [{"point": POINT_LOOP, "fatal": True}])
            s = loop.submit(CompletionRequest(prompt=_prompt(5),
                                              max_tokens=4))
            with pytest.raises(StreamError):
                s.collect(timeout=60)
            assert s.error_code == 503 and s.error_reason == "replica_died"
            deadline = time.perf_counter() + 30
            while loop.respawn_count == 0 and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert loop.respawn_count == 1
            assert loop.stats().alive and not loop.draining
            s2 = loop.submit(CompletionRequest(prompt=_prompt(7, seed=4),
                                               max_tokens=3))
            tokens, reason = s2.collect(timeout=60)
            assert len(tokens) == 3 and reason == "length"
        finally:
            loop.close(timeout=60)

    def test_cancel_during_retry_releases_blocks(self):
        eng = _engine(PCFG, dispatch_retries=10, retry_backoff_s=0.05)
        baseline = eng.allocator.free_blocks
        loop = EngineLoop(eng, name="cancelretry").start()
        inj = get_fault_injector()
        try:
            spec = inj.arm(POINT_DISPATCH, times=4)
            s = loop.submit(CompletionRequest(prompt=_prompt(5),
                                              max_tokens=16))
            while spec.fired == 0:  # the watchdog is now inside its retries
                time.sleep(0.005)
            loop.cancel(s.request_id)
            tokens, reason = s.collect(timeout=60)
            assert reason == "cancelled"
            assert loop.stats().alive
        finally:
            loop.close(timeout=60)
        assert eng.allocator.free_blocks == baseline


# --------------------------------------------- router breaker + shedding
class TestRouterBreaker:
    def test_quarantine_then_half_open_probe_recovers(self):
        # cold loop: nothing steps, so submit failures come only from the
        # injected router.submit faults and the state machine is exact
        loop = EngineLoop(_engine(), name="breaker")
        router = ReplicaRouter([loop], RouterConfig(
            breaker_failures=2, breaker_reset_s=0.2))
        inj = get_fault_injector()
        inj.configure([{"point": POINT_SUBMIT, "times": 2}])
        for _ in range(2):  # two failed submits trip the breaker open
            with pytest.raises(Overloaded):
                router.submit(CompletionRequest(prompt=[1], max_tokens=1))
        assert router.health()[0]["state"] == "quarantined"
        assert router.health()[0]["breaker"] == "open"
        assert router.state() == "degraded"
        # while open (dwell not elapsed) the replica admits nothing
        with pytest.raises(Overloaded) as exc:
            router.submit(CompletionRequest(prompt=[1], max_tokens=1))
        assert exc.value.retry_after_s == 0.2
        time.sleep(0.25)
        # dwell elapsed: one half-open probe goes through and closes it
        stream = router.submit(CompletionRequest(prompt=[1], max_tokens=1))
        assert stream is not None
        assert router.health()[0]["state"] == "healthy"
        assert router.state() == "ready"

    def test_expired_deadline_shed_before_placement(self):
        loop = EngineLoop(_engine(), name="shed")
        router = ReplicaRouter([loop])
        req = CompletionRequest(prompt=_prompt(4), max_tokens=4,
                                deadline_s=0.05)
        req.t_submit = time.perf_counter() - 0.2
        with pytest.raises(DeadlineExceeded):
            router.submit(req)
        # the doomed request never reached the replica
        assert loop.stats().queued == 0

    def test_degraded_engine_surfaces_in_state_and_health(self):
        loop = EngineLoop(_engine(), name="degraded")
        router = ReplicaRouter([loop])
        assert router.state() == "ready"
        loop._engine.degraded_mode = 1
        assert router.state() == "degraded"
        h = router.health()[0]
        assert h["state"] == "degraded" and h["degraded_mode"] == 1


# ---------------------------------------------------------- replica failover
class TestReplicaFailover:
    def test_failover_resubmission_token_identical(self):
        ref = _engine()
        ref.put("ref", PROMPTS[0], max_new_tokens=6, temperature=0.8,
                seed=100)
        expected = ref.generate_all()["ref"]

        eng_a, eng_b = _engine(), _engine()
        loop_a = EngineLoop(eng_a, name="rep-a", max_respawns=0)
        loop_b = EngineLoop(eng_b, name="rep-b")
        router = ReplicaRouter([loop_a, loop_b], RouterConfig(max_failovers=1))
        # only the replica that picked up the request trips the loop fault
        # (an idle loop never reaches POINT_LOOP); max_respawns=0 makes the
        # death final, forcing failover to the survivor
        get_fault_injector().configure(
            [{"point": POINT_LOOP, "fatal": True}])
        loop_a.start()
        loop_b.start()
        try:
            req = CompletionRequest(prompt=PROMPTS[0], max_tokens=6,
                                    temperature=0.8, seed=100)
            stream = router.submit(req)
            with pytest.raises(StreamError):
                stream.collect(timeout=60)
            assert stream.error_reason == "replica_died"
            assert not loop_a.stats().alive
            replay = router.resubmit(req)
            assert replay is not None
            tokens, reason = replay.collect(timeout=60)
            assert tokens == expected and reason == "length"
            # per-request failover budget: a second resubmit is refused
            assert router.resubmit(req) is None
        finally:
            loop_b.close(timeout=60)
            loop_a.join(timeout=10)


# ----------------------------------------- drain + disconnect under faults
def _post(frontend, body, timeout=120):
    conn = http.client.HTTPConnection(frontend.host, frontend.port,
                                      timeout=timeout)
    conn.request("POST", "/v1/completions", body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    return conn, conn.getresponse()


class TestDrainAndDisconnect:
    def test_sigterm_drain_with_inflight_injected_faults(self):
        eng = _engine()
        loop = EngineLoop(eng, name="faultdrain")
        router = ReplicaRouter([loop], RouterConfig(max_queue_tokens=96))
        frontend = ServingFrontend(router, port=0)
        loop.start()
        frontend.start()
        handler = PreemptionHandler(signals=(signal.SIGTERM,))
        frontend.install_preemption_handler(handler)
        get_fault_injector().configure(
            [{"point": POINT_DISPATCH, "after": 1, "times": 2}])
        try:
            results = {}

            def run_one(i):
                conn, resp = _post(frontend, {
                    "prompt": _prompt(5 + i, seed=i), "max_tokens": 6})
                results[i] = (resp.status, json.loads(resp.read()))
                conn.close()

            threads = [threading.Thread(target=run_one, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            while not eng.has_work and any(t.is_alive() for t in threads):
                time.sleep(0.005)
            os.kill(os.getpid(), signal.SIGTERM)
            assert handler.should_stop
            for t in threads:
                t.join(timeout=120)
            assert not any(t.is_alive() for t in threads)
            # inflight work survived the injected faults AND the drain
            for status, body in results.values():
                assert status == 200
                assert len(body["choices"][0]["tokens"]) == 6
            assert loop.join(timeout=60)
            assert eng.step_failures >= 1  # the faults really fired
            assert eng.allocator.free_blocks == eng.cfg.num_blocks - 1
        finally:
            handler.restore()
            frontend.close()

    def test_client_disconnect_mid_sse_releases_kv(self):
        eng = _engine()
        baseline = eng.allocator.free_blocks
        loop = EngineLoop(eng, name="disc")
        router = ReplicaRouter([loop])
        frontend = ServingFrontend(router, port=0)
        loop.start()
        frontend.start()
        try:
            body = json.dumps({"prompt": _prompt(5), "max_tokens": 48,
                               "stream": True}).encode()
            sock = socket.create_connection((frontend.host, frontend.port),
                                            timeout=60)
            sock.sendall(b"POST /v1/completions HTTP/1.1\r\n"
                         b"Host: t\r\nContent-Type: application/json\r\n"
                         b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
            head = sock.recv(4096)  # status line (+ first frames)
            assert b" 200 " in head.split(b"\r\n", 1)[0]
            # abrupt client disconnect mid-stream: RST on close so the
            # server's next SSE write fails immediately
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
            sock.close()
            deadline = time.perf_counter() + 60
            while (eng.allocator.free_blocks != baseline
                   and time.perf_counter() < deadline):
                time.sleep(0.02)
            # the frontend hit the broken pipe, cancelled the request, and
            # the engine released every KV block
            assert eng.allocator.free_blocks == baseline
        finally:
            loop.close(timeout=60)
            frontend.close()
