"""Ragged/continuous-batching inference (reference ``tests/unit/inference/v2``:
ragged manager, blocked allocator, engine numerics vs the dense path)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.ragged import (
    BlockedAllocator,
    RaggedConfig,
    RaggedInferenceEngine,
)
from deepspeed_tpu.models import llama

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
RCFG = RaggedConfig(
    max_tokens_per_step=16, max_seqs=3, block_size=4,
    num_blocks=49, max_blocks_per_seq=16,
)


class TestBlockedAllocator:
    def test_allocate_free_roundtrip(self):
        a = BlockedAllocator(9)
        assert a.free_blocks == 8  # block 0 reserved as scratch
        got = a.allocate(3)
        assert len(set(got)) == 3 and 0 not in got
        assert a.free_blocks == 5
        a.free(got)
        assert a.free_blocks == 8

    def test_exhaustion_raises(self):
        a = BlockedAllocator(4)
        a.allocate(3)
        with pytest.raises(RuntimeError):
            a.allocate(1)

    def test_double_free_and_scratch_guard(self):
        a = BlockedAllocator(4)
        blocks = a.allocate(2)
        a.free(blocks)
        with pytest.raises(ValueError):
            a.free([blocks[0]])
        with pytest.raises(ValueError):
            a.free([0])


def _dense_reference(prompts, max_new):
    """Greedy continuation per prompt via the dense v1 engine."""
    eng = InferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), dtype=jnp.float32, seed=0
    )
    out = {}
    for uid, p in prompts.items():
        full = eng.generate(np.asarray(p)[None], max_new_tokens=max_new)
        out[uid] = list(np.asarray(full[0, len(p):]))
    return out


def _prompts(rng=0):
    r = np.random.default_rng(rng)
    return {
        "a": list(r.integers(0, CFG.vocab_size, 5)),
        "b": list(r.integers(0, CFG.vocab_size, 11)),
        "c": list(r.integers(0, CFG.vocab_size, 23)),
    }


class TestRaggedEngine:
    def test_mixed_length_parity_vs_dense(self):
        """Three different-length prompts admitted together produce exactly
        the dense engine's greedy continuations."""
        prompts = _prompts()
        max_new = 8
        ref = _dense_reference(prompts, max_new)

        eng = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        for uid, p in prompts.items():
            eng.put(uid, p, max_new_tokens=max_new)
        got = eng.generate_all()
        for uid in prompts:
            assert got[uid] == [int(t) for t in ref[uid]], uid

    def test_decode_run_ahead_token_parity(self):
        """The fused multi-step decode (decode_run_ahead) must emit exactly
        the per-step engine's greedy tokens — it only changes dispatch
        granularity, never the math."""
        prompts = _prompts(7)
        max_new = 9
        base = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        for uid, p in prompts.items():
            base.put(uid, p, max_new_tokens=max_new)
        expect = base.generate_all()

        import dataclasses

        fused = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx),
            dataclasses.replace(RCFG, decode_run_ahead=4),
            dtype=jnp.float32, seed=0,
        )
        for uid, p in prompts.items():
            fused.put(uid, p, max_new_tokens=max_new)
        got = fused.generate_all()
        assert got == expect
        # the run-ahead path actually engaged: far fewer host steps than
        # tokens generated would imply is impossible to check directly, but
        # the chunk program must have compiled (device-resident variant by
        # default; legacy _chunk_jit when device_state is off)
        assert fused._dev_chunk_jits or fused._chunk_jit is not None

    def test_run_ahead_respects_eos_and_limits(self):
        """EOS inside a fused chunk truncates the stream exactly as the
        per-step path does, and max_new_tokens is never exceeded."""
        import dataclasses

        prompts = _prompts(11)
        base = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        for uid, p in prompts.items():
            base.put(uid, p, max_new_tokens=7)
        expect = base.generate_all()
        # pick an eos that actually appears mid-stream for at least one seq
        eos = next((t for toks in expect.values() for t in toks[:-1]), None)

        fused = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx),
            dataclasses.replace(RCFG, decode_run_ahead=5),
            dtype=jnp.float32, seed=0, eos_token_id=eos,
        )
        for uid, p in prompts.items():
            fused.put(uid, p, max_new_tokens=7)
        got = fused.generate_all()
        for uid, toks in got.items():
            assert len(toks) <= 7
            if eos in toks:
                assert toks.index(eos) == len(toks) - 1  # truncated at EOS

    def test_tiled_prefill_token_parity(self):
        """The tile-aligned prefill layout + tiled attention path must emit
        exactly the per-token engine's greedy tokens (XLA fallback on CPU
        exercises the scheduler layout + metadata; kernel math is covered by
        test_paged_attention's interpret-mode parity)."""
        import dataclasses

        prompts = _prompts(13)
        max_new = 7
        base = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        for uid, p in prompts.items():
            base.put(uid, p, max_new_tokens=max_new)
        expect = base.generate_all()

        tiled = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx),
            dataclasses.replace(RCFG, prefill_tile=8),
            dtype=jnp.float32, seed=0,
        )
        for uid, p in prompts.items():
            tiled.put(uid, p, max_new_tokens=max_new)
        got = tiled.generate_all()
        assert got == expect
        assert (any(key[2] > 0 for key in tiled._dev_step_jits)
                or tiled._tiled_jits), "tiled step programs never engaged"

    def test_tiled_prefill_rejected_without_model_support(self):
        import dataclasses

        def build_no_tiles(ctx):
            spec = llama.build(CFG, ctx=ctx)
            spec.supports_prefill_tiles = False
            return spec

        with pytest.raises(ValueError, match="prefill_tiles"):
            RaggedInferenceEngine(
                build_no_tiles,
                dataclasses.replace(RCFG, prefill_tile=8),
                dtype=jnp.float32, seed=0,
            )

    def test_continuous_admission(self):
        """A request put() mid-flight (while others decode) still matches the
        dense reference — continuous batching semantics."""
        prompts = _prompts(3)
        max_new = 6
        ref = _dense_reference(prompts, max_new)

        eng = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        eng.put("a", prompts["a"], max_new_tokens=max_new)
        eng.put("b", prompts["b"], max_new_tokens=max_new)
        for _ in range(3):  # a/b prefill and start decoding
            eng.step()
        eng.put("c", prompts["c"], max_new_tokens=max_new)  # late admission
        got = eng.generate_all()
        for uid in prompts:
            assert got[uid] == [int(t) for t in ref[uid]], uid

    def test_blocks_and_slots_recycled(self):
        eng = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        total_free = eng.allocator.free_blocks
        # two waves through the same engine: slots and blocks must recycle
        for wave in range(2):
            for uid, p in _prompts(wave).items():
                eng.put(f"{wave}-{uid}", p, max_new_tokens=4)
            eng.generate_all()
            assert eng.allocator.free_blocks == total_free
            assert len(eng._free_slots) == RCFG.max_seqs

    def test_eos_stops_sequence(self):
        eng = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        # run once to learn what the first generated token is, then use it as eos
        eng.put("probe", _prompts()["a"], max_new_tokens=4)
        first = eng.generate_all()["probe"][0]
        eng.put("x", _prompts()["a"], max_new_tokens=4, eos_token_id=first)
        out = eng.generate_all()["x"]
        assert out == [first]  # stopped at eos, not max_new

    def test_never_admittable_request_rejected_at_put(self):
        """A request whose worst case exceeds the whole pool is rejected
        upfront instead of stalling the queue and deadlocking the engine."""
        tiny_pool = RaggedConfig(
            max_tokens_per_step=8, max_seqs=2, block_size=2,
            num_blocks=3, max_blocks_per_seq=8,
        )
        eng = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), tiny_pool,
            dtype=jnp.float32, seed=0,
        )
        r = np.random.default_rng(0)
        with pytest.raises(ValueError, match="never be admitted"):
            eng.put("a", r.integers(0, CFG.vocab_size, 6), max_new_tokens=4)
        # a request that does fit the pool still completes
        eng.put("ok", r.integers(0, CFG.vocab_size, 2), max_new_tokens=2)
        assert len(eng.generate_all()["ok"]) == 2

    def test_conservative_admission_completes_oversubscribed_load(self):
        """Requests whose combined worst case exceeds the pool but which fit
        sequentially must all complete: admission reserves worst-case blocks,
        so later requests wait instead of deadlocking mid-decode."""
        pool = RaggedConfig(
            max_tokens_per_step=16, max_seqs=3, block_size=4,
            num_blocks=12, max_blocks_per_seq=8,  # 11 usable blocks
        )
        eng = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), pool,
            dtype=jnp.float32, seed=0,
        )
        r = np.random.default_rng(0)
        # worst cases: ceil(20/4)=5, ceil(22/4)=6, ceil(17/4)=5 -> 16 > 11
        for uid, (plen, new) in {"a": (14, 6), "b": (16, 6), "c": (12, 5)}.items():
            eng.put(uid, r.integers(0, CFG.vocab_size, plen), max_new_tokens=new)
        out = eng.generate_all()
        assert sorted(out) == ["a", "b", "c"]
        assert [len(out[u]) for u in "abc"] == [6, 6, 5]

    def test_splitfuse_efficiency_vs_dense_padding(self):
        """Scheduled useful tokens must beat dense pad-to-max batching: the
        dense engine processes batch*max_prompt prefill + batch*max_new decode
        token-slots; the ragged schedule only pays for real tokens plus
        bucket-padding slack, which must come in strictly lower at mixed
        lengths."""
        prompts = _prompts()
        max_new = 8
        eng = RaggedInferenceEngine(
            lambda ctx: llama.build(CFG, ctx=ctx), RCFG,
            dtype=jnp.float32, seed=0,
        )
        for uid, p in prompts.items():
            eng.put(uid, p, max_new_tokens=max_new)
        eng.generate_all()
        dense_token_slots = len(prompts) * (
            max(len(p) for p in prompts.values()) + max_new
        )
        ragged_token_slots = eng.tokens_scheduled + eng.tokens_padded
        assert ragged_token_slots < dense_token_slots, (
            f"ragged {ragged_token_slots} >= dense {dense_token_slots}"
        )


# the four dispatch modes the device-resident state must stay
# token-identical in (mirrors test_prefix_cache.MODES)
DISPATCH_MODES = {
    "plain": {},
    "tiled": {"prefill_tile": 8},
    "run_ahead": {"decode_run_ahead": 4},
    "fused": {"fused_chunk": 4, "pipeline_depth": 2},
}


def _engine_ds(device_state, **over):
    import dataclasses

    cfg = dataclasses.replace(RCFG, device_state=device_state, **over)
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), cfg, dtype=jnp.float32, seed=0)


class TestDeviceResidentState:
    """cfg.device_state keeps slot rows / block table / feed tokens on
    device and double-buffers readback; it must be token-identical to the
    legacy host-staged path in every mode, greedy and seeded-sampled."""

    @pytest.mark.parametrize("mode", list(DISPATCH_MODES))
    def test_token_parity_vs_host_staged(self, mode):
        kw = DISPATCH_MODES[mode]
        outs = {}
        for dev in (False, True):
            eng = _engine_ds(dev, **kw)
            for uid, p in _prompts(17).items():
                eng.put(uid, p, max_new_tokens=8)
            eng.put("s1", _prompts(19)["b"], max_new_tokens=8,
                    temperature=0.9, top_k=20, seed=123)
            eng.put("s2", _prompts(19)["a"], max_new_tokens=6,
                    temperature=0.7, top_p=0.9, seed=7)
            outs[dev] = eng.generate_all()
        assert outs[True] == outs[False]
        # the sampled streams really sampled (not a greedy fallback)
        greedy = _engine_ds(True, **kw)
        greedy.put("s1", _prompts(19)["b"], max_new_tokens=8)
        assert greedy.generate_all()["s1"] != outs[True]["s1"]

    def test_steady_decode_stages_zero_bytes(self):
        """The whole point: once every sequence is decoding, the packed
        staging buffer byte-compares equal step to step and the block table
        has no dirty rows — further steps upload NOTHING."""
        # block_size 16: the whole request (11 prompt + 5 new = 16 tokens)
        # fits one block, so no mid-decode table growth dirties a row
        eng = _engine_ds(True, block_size=16, num_blocks=13,
                         max_blocks_per_seq=8)
        eng.put("a", _prompts(23)["b"], max_new_tokens=5)
        eng.step()  # prefill dispatch
        eng.step()  # first decode dispatch (staging buffer cached here)
        assert all(s.in_decode for s in eng._running.values())
        h2d0 = eng.h2d_bytes
        for _ in range(2):
            eng.step()
        assert eng.h2d_bytes == h2d0, (
            "steady-state decode still staging host bytes")

    def test_readback_is_double_buffered(self):
        """A dispatched step's tokens are reconciled one step later (window
        of one pending dispatch), and drain() flushes the window."""
        eng = _engine_ds(True)
        eng.put("a", _prompts()["a"], max_new_tokens=6)
        eng.step()  # prefill dispatched, nothing reconciled yet
        assert len(eng._pending) == 1
        assert eng._results.get("a") is None
        eng.drain()
        assert not eng._pending
        out = eng.generate_all()
        assert len(out["a"]) == 6

    @pytest.mark.parametrize("mode", list(DISPATCH_MODES))
    def test_cancel_mid_flight_with_pending_dispatch(self, mode):
        """cancel() while a dispatch is in flight: the sequence retires via
        the deferred-release machinery, its KV blocks and slot recycle, and
        the remaining request still finishes with correct tokens."""
        kw = DISPATCH_MODES[mode]
        want = None
        for with_cancel in (False, True):
            eng = _engine_ds(True, **kw)
            prompts = _prompts(29)
            eng.put("keep", prompts["b"], max_new_tokens=8)
            if with_cancel:
                eng.put("dead", prompts["c"], max_new_tokens=8)
            eng.step()  # dispatch in flight referencing both
            if with_cancel:
                assert eng.cancel("dead")
            out = eng.generate_all()
            if with_cancel:
                assert eng.get_request("dead").status == "cancelled"
            if want is None:
                want = out["keep"]
            else:
                assert out["keep"] == want
        assert len(eng._free_slots) == RCFG.max_seqs
        usable = RCFG.num_blocks - 1
        assert eng.allocator.free_blocks == usable

    def test_deadline_timeout_mid_flight(self):
        eng = _engine_ds(True)
        eng.put("t", _prompts()["c"], max_new_tokens=40, deadline_s=0.05)
        eng.step()
        import time as _time

        _time.sleep(0.08)
        eng.generate_all()
        seq = eng.get_request("t")
        assert seq.status == "timeout"
        assert len(eng._free_slots) == RCFG.max_seqs

    def test_slot_reuse_rewrites_device_rows(self):
        """A retired slot reused by a new request must behave as a fresh
        row (seed/params rewritten at admission): an oversubscribed sampled
        workload matches the legacy host-staged path request for request."""
        eng = _engine_ds(True)
        fresh = _engine_ds(False)
        for wave in (0, 1):
            for uid, p in _prompts(wave).items():
                eng.put(f"{wave}-{uid}", p, max_new_tokens=5,
                        temperature=0.8, seed=100 + wave)
        got = eng.generate_all()
        for wave in (0, 1):
            for uid, p in _prompts(wave).items():
                fresh.put(f"{wave}-{uid}", p, max_new_tokens=5,
                          temperature=0.8, seed=100 + wave)
        assert fresh.generate_all() == got
