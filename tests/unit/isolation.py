"""Subprocess isolation + stall watchdog for mesh-churny tests.

XLA's emulated-CPU collective executor can deadlock (every thread
futex-parked, 0% CPU, no stuck-collective watchdog fire) on this 1-core box.
Observed round 3 on EP programs and round 4 on the NVMe-offload step, the
autotuner sweep, and even fresh subprocesses running two meshes back-to-back.
It is probabilistic and an artifact of ``--xla_force_host_platform_device_count``
emulation, not a framework property: the identical scenarios pass standalone
and on real hardware, and a retried run virtually always succeeds.

Two tools:
- :func:`run_isolated` — run a scenario in a fresh python subprocess, with a
  CPU-progress watchdog that kills and RETRIES a wedged child instead of
  hanging the suite.
- :func:`tree_cpu_ticks` / :func:`run_with_stall_watchdog` — the same
  watchdog for arbitrary commands (the suite shard runner in
  tests/conftest.py uses it).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

PREAMBLE = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_enable_async_dispatch", False)
except Exception:
    pass
# no persistent compile cache: cache-deserialized CPU collective programs
# deadlock on this VM (see tests/conftest.py)
_cache = os.environ.get("DSTPU_TEST_JIT_CACHE")
if _cache:
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
import numpy as np
"""


def tree_cpu_ticks(pid: int) -> int:
    """utime+stime of ``pid`` and every descendant (a parent blocked on a
    working child must count as progressing)."""
    total = 0
    stack = [pid]
    while stack:
        p = stack.pop()
        try:
            with open(f"/proc/{p}/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            total += int(parts[11]) + int(parts[12])  # utime, stime
            for tid in os.listdir(f"/proc/{p}/task"):
                with open(f"/proc/{p}/task/{tid}/children") as f:
                    stack.extend(int(c) for c in f.read().split())
        except (OSError, IndexError, ValueError):
            continue
    return total


def run_with_stall_watchdog(cmd, env=None, stall_seconds: int = 120,
                            timeout: int = 900, poll: int = 5, **popen_kw):
    """Run ``cmd``; kill it if its process tree makes no CPU progress for
    ``stall_seconds`` (the wedge signature). Returns
    ``(returncode_or_None, stalled: bool)`` — ``stalled=True`` means it was
    killed by the watchdog and is worth retrying."""
    proc = subprocess.Popen(cmd, env=env, **popen_kw)
    deadline = time.monotonic() + timeout
    last_ticks = -1
    last_progress = time.monotonic()
    while True:
        rc = proc.poll()
        if rc is not None:
            return rc, False
        now = time.monotonic()
        ticks = tree_cpu_ticks(proc.pid)
        if ticks != last_ticks:
            last_ticks = ticks
            last_progress = now
        if now - last_progress > stall_seconds or now > deadline:
            stalled = now - last_progress > stall_seconds
            proc.kill()
            proc.wait()
            return None, stalled
        time.sleep(poll)


def run_isolated(body: str, marker: str, timeout: int = 600,
                 attempts: int = 3) -> None:
    """Run ``PREAMBLE + body`` in a fresh python subprocess; assert it exits
    0 and prints ``marker``. A child wedged by the emulation deadlock (no
    CPU progress for 90 s) is killed and retried."""
    import tempfile

    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for attempt in range(attempts):
        with tempfile.TemporaryFile("w+") as fh:
            rc, stalled = run_with_stall_watchdog(
                [sys.executable, "-c", PREAMBLE + body], env=env,
                stall_seconds=90, timeout=timeout, cwd=repo,
                stdout=fh, stderr=subprocess.STDOUT)
            fh.seek(0)
            text = fh.read()
        if rc == 0:
            assert marker in text, text[-2000:]
            return
        if not stalled:
            raise AssertionError(
                f"isolated scenario failed rc={rc}:\n{text[-3000:]}")
        print(f"isolated scenario wedged (attempt {attempt + 1}/{attempts}); "
              "retrying", file=sys.stderr)
    raise AssertionError(
        f"isolated scenario wedged {attempts} times (XLA CPU-emulation "
        "collective deadlock; see tests/unit/isolation.py)")
