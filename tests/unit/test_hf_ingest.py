"""HF checkpoint ingestion: logits parity against real transformers models
(reference: ``module_inject`` AutoTP/checkpoint-loading test coverage —
``tests/unit/model_parallelism``, ``tests/unit/inference`` load real HF
checkpoints and compare outputs)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import gpt2, llama, mixtral
from deepspeed_tpu.models.hf_ingest import config_from_hf, load_hf_params

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _save_hf(tmp_path, model):
    d = str(tmp_path / "hf")
    model.save_pretrained(d, safe_serialization=True)
    return d


def _hf_logits(model, ids):
    with torch.no_grad():
        return model(torch.from_numpy(ids).long()).logits.float().numpy()


@pytest.fixture
def ids():
    return np.random.default_rng(0).integers(0, 64, (2, 16)).astype(np.int32)


class TestLlamaIngest:
    @pytest.mark.parametrize("tied", [False, True])
    def test_logits_parity(self, tmp_path, ids, tied):
        hf_cfg = transformers.LlamaConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, rope_theta=10000.0,
            tie_word_embeddings=tied,
        )
        hf = transformers.LlamaForCausalLM(hf_cfg).eval()
        d = _save_hf(tmp_path, hf)

        family, cfg = config_from_hf(d)
        assert family == "llama" and cfg.num_kv_heads == 2
        assert cfg.tie_embeddings == tied
        params, _ = load_hf_params(d)
        ours = np.asarray(
            llama.forward(cfg, jax.tree_util.tree_map(jnp.asarray, params),
                          jnp.asarray(ids))
        )
        np.testing.assert_allclose(ours, _hf_logits(hf, ids), rtol=2e-4, atol=2e-4)

    def test_sharded_load_under_plan(self, tmp_path, ids, mesh8):
        """Leaves go straight onto the mesh under the training plan; forward
        still matches HF."""
        from deepspeed_tpu.parallel.partition import plan_sharding

        hf_cfg = transformers.LlamaConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128,
        )
        hf = transformers.LlamaForCausalLM(hf_cfg).eval()
        d = _save_hf(tmp_path, hf)

        family, cfg = config_from_hf(d)
        spec = llama.build(cfg)
        plan = plan_sharding(
            spec.param_logical_axes,
            jax.eval_shape(spec.init_fn, jax.random.PRNGKey(0)),
            mesh8, zero_stage=3, use_tp=False,
            dim_units=spec.logical_dim_units,
        )
        params, _ = load_hf_params(d, shardings=plan.param_shardings)
        leaf = params["layers"]["wq"]
        assert hasattr(leaf, "sharding")  # on device, not numpy
        ours = np.asarray(llama.forward(cfg, params, jnp.asarray(ids)))
        np.testing.assert_allclose(ours, _hf_logits(hf, ids), rtol=2e-4, atol=2e-4)


class TestGPT2Ingest:
    def test_logits_parity(self, tmp_path, ids):
        hf_cfg = transformers.GPT2Config(
            vocab_size=97, n_embd=32, n_layer=2, n_head=4, n_positions=64,
        )
        hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
        d = _save_hf(tmp_path, hf)

        family, cfg = config_from_hf(d)
        assert family == "gpt2" and cfg.max_seq_len == 64
        params, _ = load_hf_params(d)
        ours = np.asarray(
            gpt2.forward(cfg, jax.tree_util.tree_map(jnp.asarray, params),
                         jnp.asarray(ids))
        )
        np.testing.assert_allclose(ours, _hf_logits(hf, ids), rtol=2e-4, atol=2e-4)


class TestMixtralIngest:
    def test_logits_parity(self, tmp_path, ids):
        hf_cfg = transformers.MixtralConfig(
            vocab_size=97, hidden_size=32, intermediate_size=48,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            num_local_experts=4, num_experts_per_tok=2,
            max_position_embeddings=128, rope_theta=10000.0,
        )
        hf = transformers.MixtralForCausalLM(hf_cfg).eval()
        d = _save_hf(tmp_path, hf)

        family, cfg = config_from_hf(d)
        assert family == "mixtral" and cfg.num_experts == 4 and cfg.top_k == 2
        # dropless capacity so routing matches HF's exact top-k dispatch
        cfg = dataclasses.replace(cfg, capacity_factor=float(cfg.num_experts))
        params, _ = load_hf_params(d, family="mixtral", cfg=cfg)
        ours = np.asarray(
            mixtral.forward(cfg, jax.tree_util.tree_map(jnp.asarray, params),
                            jnp.asarray(ids), train=True)
        )
        np.testing.assert_allclose(ours, _hf_logits(hf, ids), rtol=3e-4, atol=3e-4)
