"""Sampling surface: temperature / top-k / top-p / repetition penalty
(reference ``inference/engine.py:586 _generate`` HF sampling kwargs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.sampling import (
    apply_repetition_penalty,
    sample_tokens,
    update_seen,
)


def _logits(rows):
    return jnp.asarray(np.array(rows, np.float32))


class TestTopK:
    def test_samples_only_from_top_k(self):
        logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 50)),
                             jnp.float32)
        order = np.argsort(np.asarray(logits), axis=-1)[:, ::-1]
        allowed = {(r, int(c)) for r in range(4) for c in order[r, :5]}
        for s in range(30):
            toks, _ = sample_tokens(logits, jax.random.PRNGKey(s),
                                    temperature=1.0, top_k=5)
            for r, t in enumerate(np.asarray(toks)):
                assert (r, int(t)) in allowed

    def test_top_k_one_is_greedy(self):
        logits = jnp.asarray(np.random.default_rng(1).normal(size=(3, 20)),
                             jnp.float32)
        toks, lp = sample_tokens(logits, jax.random.PRNGKey(0),
                                 temperature=1.0, top_k=1)
        np.testing.assert_array_equal(
            np.asarray(toks), np.argmax(np.asarray(logits), axis=-1))
        # single-choice distribution: logprob of the chosen token is ~0
        assert np.all(np.asarray(lp) > -1e-3)

    def test_per_row_k(self):
        logits = _logits([[0.0, 1.0, 2.0, 3.0]] * 2)
        for s in range(20):
            toks, _ = sample_tokens(logits, jax.random.PRNGKey(s),
                                    temperature=1.0,
                                    top_k=np.asarray([1, 2], np.int32))
            assert int(toks[0]) == 3
            assert int(toks[1]) in (2, 3)


class TestTopP:
    def test_mass_bound(self):
        """The surviving set is the smallest descending-probability prefix
        with cumulative mass >= top_p."""
        p = np.array([[0.5, 0.3, 0.15, 0.05]], np.float32)
        logits = jnp.asarray(np.log(p))
        # top_p=0.6: {0.5} reaches only 0.5 < 0.6, so {0.5, 0.3} survives
        seen = set()
        for s in range(200):
            toks, _ = sample_tokens(logits, jax.random.PRNGKey(s),
                                    temperature=1.0, top_p=0.6)
            seen.add(int(toks[0]))
        assert seen == {0, 1}

    def test_top_of_distribution_always_survives(self):
        p = np.array([[0.9, 0.06, 0.04]], np.float32)
        logits = jnp.asarray(np.log(p))
        for s in range(50):
            toks, _ = sample_tokens(logits, jax.random.PRNGKey(s),
                                    temperature=1.0, top_p=0.01)
            assert int(toks[0]) == 0  # tiny top_p -> argmax only

    def test_disabled_at_one(self):
        logits = jnp.asarray(
            np.random.default_rng(2).normal(size=(2, 30)), jnp.float32)
        a, _ = sample_tokens(logits, jax.random.PRNGKey(7), 1.0, top_p=1.0)
        b, _ = sample_tokens(logits, jax.random.PRNGKey(7), 1.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestRepetitionPenalty:
    def test_monotone_suppression(self):
        """Raising the penalty strictly lowers a seen token's (positive and
        negative) logit relative to unseen ones — the CTRL rule."""
        logits = _logits([[2.0, 1.9, -0.5]])
        seen = jnp.asarray([[True, False, True]])
        prev = None
        for pen in (1.0, 1.2, 1.5, 2.0):
            out = np.asarray(apply_repetition_penalty(
                logits, seen, jnp.asarray([pen], jnp.float32)))[0]
            assert out[1] == pytest.approx(1.9)  # unseen untouched
            if prev is not None:
                assert out[0] < prev[0]
                assert out[2] < prev[2]
            prev = out

    def test_greedy_flip(self):
        """A large enough penalty flips a greedy pick off a seen token."""
        logits = _logits([[2.0, 1.9]])
        seen = jnp.asarray([[True, False]])
        toks, _ = sample_tokens(logits, jax.random.PRNGKey(0), 0.0,
                                repetition_penalty=2.0, seen_mask=seen)
        assert int(toks[0]) == 1

    def test_update_seen(self):
        seen = jnp.zeros((2, 5), jnp.bool_)
        seen = update_seen(seen, jnp.asarray([3, 0]))
        got = np.asarray(seen)
        assert got[0, 3] and got[1, 0] and got.sum() == 2


class TestGreedySampledMix:
    def test_per_row_temperature(self):
        logits = jnp.asarray(
            np.random.default_rng(3).normal(size=(2, 40)), jnp.float32)
        toks, lp = sample_tokens(
            logits, jax.random.PRNGKey(5),
            temperature=np.asarray([0.0, 1.0], np.float32))
        assert int(toks[0]) == int(np.argmax(np.asarray(logits)[0]))
        assert np.all(np.asarray(lp) <= 0.0)


class TestEngineIntegration:
    def test_dense_generate_sampling(self):
        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=64)
        eng = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx),
                              dtype=jnp.float32, seed=0)
        ids = np.random.default_rng(4).integers(0, 97, (2, 8), dtype=np.int32)
        greedy = eng.generate(ids, max_new_tokens=6)
        topk1 = eng.generate(ids, max_new_tokens=6, temperature=0.7, top_k=1)
        np.testing.assert_array_equal(greedy, topk1)  # top_k=1 == greedy
        sampled = eng.generate(ids, max_new_tokens=6, temperature=1.2,
                               top_p=0.95, seed=3)
        assert sampled.shape == greedy.shape
        assert np.all((sampled >= 0) & (sampled < 97))
        # no-repeat under a harsh penalty: a generated token never repeats
        pen = eng.generate(ids[:1], max_new_tokens=6, repetition_penalty=1e9)
        new = list(pen[0, 8:])
        assert len(set(new)) == len(new)
        assert not set(new) & set(ids[0])  # prompt tokens penalized too

    def test_hybrid_rollout_logprobs_match_behavior_policy(self):
        """top_k=1 makes the final distribution a point mass -> recorded
        logprobs ~0; this only holds if logprobs come from the SAME filtered
        distribution the token was drawn from (the round-4 advisor fix,
        generalized)."""
        from deepspeed_tpu.comm.comm import init_distributed
        from deepspeed_tpu.comm.topology import reset_topology
        from deepspeed_tpu.config.config import load_config
        from deepspeed_tpu.models import llama
        from deepspeed_tpu.runtime.hybrid_engine import HybridEngine

        reset_topology()
        cfg = load_config({
            "train_micro_batch_size_per_device": 2,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "mesh": {"data": -1},
            "seed": 3,
        })
        topo = init_distributed(cfg.mesh)
        cfg.resolve_batch_sizes(topo.dp_world_size)
        engine = HybridEngine(
            lambda ctx: llama.build(llama.LlamaConfig.tiny(97), ctx=ctx),
            cfg, topo, inference_dtype=jnp.float32)
        prompts = [np.arange(5, dtype=np.int32), np.arange(7, dtype=np.int32)]
        outs = engine.generate_rollouts(
            prompts, max_new_tokens=4, temperature=0.8, top_k=1, seed=1)
        for o in outs:
            assert np.all(np.asarray(o["logprobs"]) > -1e-3)
        outs2 = engine.generate_rollouts(
            prompts, max_new_tokens=4, temperature=0.8, top_p=0.9, seed=1)
        for o in outs2:
            lps = np.asarray(o["logprobs"])
            assert np.all(lps <= 0.0) and np.all(lps > -20.0)
