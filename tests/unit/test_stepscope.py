"""Training step anatomy (telemetry/stepscope.py + engine wiring).

Pins the PR acceptance criteria: nested step→phase trace spans with and
without grad accumulation, phase sum within 5% of the measured step wall
clock, overlap/goodput/MFU gauges on the scrape, recompile exclusion from the
throughput average, checkpoint stall accounting, and a zero-allocation hot
path when stepscope is disabled (tracemalloc-pinned, same discipline as the
PR 5 serving tracer)."""

import tracemalloc

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import llama
from deepspeed_tpu.telemetry import TELEMETRY


def _engine(extra=None, gas=1, stepscope=True):
    reset_topology()
    telemetry = {"enabled": True}
    if stepscope:
        telemetry["stepscope"] = {"enabled": True}
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 0,
        "sequence_length": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": 8},
        "telemetry": telemetry,
        **(extra or {}),
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(256), ctx=ctx),
        config=cfg)
    return engine


def _batch(n=16, seq=16):
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 256, (n, seq), dtype=np.int32)}


@pytest.mark.parametrize("gas", [1, 2])
def test_nested_step_phase_spans(gas):
    engine = _engine(gas=gas)
    batch = _batch(16 * gas)
    for _ in range(3):
        engine.train_batch(batch)
    events = TELEMETRY.dump_trace()["traceEvents"]
    steps = [e for e in events if e["name"] == "train/step"]
    assert len(steps) == 3
    step_ids = {e["args"]["span_id"] for e in steps}
    phases = [e for e in events if e["name"].startswith("train/phase/")]
    assert phases, "no phase children recorded"
    # every phase span is the child of some step span (Perfetto nesting)
    assert all(e["args"].get("parent_id") in step_ids for e in phases)
    names = {e["name"].removeprefix("train/phase/") for e in phases}
    # h2d + the attributed compute split always present; recompile on the
    # compile-bearing steps
    assert {"h2d", "forward", "backward"} <= names
    assert "recompile" in names
    # children tile inside their parent's [ts, ts+dur] window
    by_id = {e["args"]["span_id"]: e for e in steps}
    for ph in phases:
        parent = by_id[ph["args"]["parent_id"]]
        assert ph["ts"] >= parent["ts"] - 1.0  # 1 us slack on float math
        assert (ph["ts"] + ph["dur"]) <= (parent["ts"] + parent["dur"]) + 1.0


def test_phase_sum_matches_step_wall_clock():
    engine = _engine()
    batch = _batch()
    for _ in range(4):
        engine.train_batch(batch)
    s = engine.stepscope.summary()
    assert s["steps"] == 4
    # acceptance pin: per-phase decomposition sums to the measured step wall
    # clock within 5% (the host residual closes the ledger by construction,
    # so this checks the accounting stays coherent end to end)
    assert s["phase_sum_over_step_ratio"] == pytest.approx(1.0, abs=0.05)
    # the same invariant per step from the trace
    events = TELEMETRY.dump_trace()["traceEvents"]
    steps = {e["args"]["span_id"]: e for e in events
             if e["name"] == "train/step"}
    for sid, step_ev in steps.items():
        kid_sum = sum(e["dur"] for e in events
                      if e["name"].startswith("train/phase/")
                      and e["args"].get("parent_id") == sid)
        assert kid_sum == pytest.approx(step_ev["dur"], rel=0.05)


def test_gauges_and_scrape():
    engine = _engine()
    batch = _batch()
    for _ in range(3):
        engine.train_batch(batch)
    reg = TELEMETRY.registry
    overlap = reg.gauge("train_overlap_fraction").value(source="estimate")
    goodput = reg.gauge("train_goodput").value()
    assert 0.0 <= overlap <= 1.0
    assert 0.0 < goodput <= 1.0
    assert reg.gauge("train_step_skew_ratio").value() == 1.0  # single host
    assert reg.gauge("train_mfu").value() > 0.0
    assert reg.gauge("train_flops_source").value(
        source=engine._flops_source) == 1.0
    assert engine._flops_source in ("analytic", "cost_analysis")
    # goodput ledger: productive + recompile categories populated
    c = reg.counter("train_goodput_seconds_total")
    assert c.value(category="productive") > 0.0
    assert c.value(category="recompile") > 0.0
    assert c.value(category="warmup") > 0.0
    prom = reg.render_prometheus()
    for series in ("train_overlap_fraction", "train_goodput",
                   "step_phase_seconds", "train_goodput_seconds_total",
                   "train_flops_source"):
        assert series in prom
    # summary mirrors the gauges
    s = engine.stepscope.summary()
    assert s["goodput"] == pytest.approx(
        reg.gauge("train_goodput").value(), abs=0.2)
    assert s["goodput_seconds"]["recompile"] > 0.0


def test_recompile_steps_excluded_from_throughput():
    engine = _engine()
    batch = _batch()
    for _ in range(4):
        engine.train_batch(batch)
    # the first step compiled the fused program: excluded from the average
    assert engine.tput_timer.excluded_count >= 1
    assert engine.tput_timer.step_count >= 1
    assert engine.tput_timer.excluded_elapsed > engine.tput_timer.total_elapsed / max(
        engine.tput_timer.step_count, 1), "compile step should dwarf a steady step"


def test_checkpoint_stall_accounted(tmp_path):
    engine = _engine()
    batch = _batch()
    for _ in range(2):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path))
    s = engine.stepscope.summary()
    assert s["goodput_seconds"]["checkpoint"] > 0.0
    events = TELEMETRY.dump_trace()["traceEvents"]
    assert any(e["name"] == "train/checkpoint_stall" for e in events)


def test_disabled_scope_allocates_nothing():
    engine = _engine(stepscope=False)
    batch = _batch()
    engine.train_batch(batch)  # compile outside the pin
    assert not engine.stepscope.enabled
    tracemalloc.start()
    try:
        for _ in range(3):
            engine.train_batch(batch)
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    stats = snap.filter_traces(
        [tracemalloc.Filter(True, "*/telemetry/stepscope.py")]).statistics(
            "filename")
    total = sum(s.size for s in stats)
    assert total == 0, f"stepscope allocated {total}B while disabled"


def test_summary_disabled_shape():
    engine = _engine(stepscope=False)
    assert engine.stepscope.summary() == {"enabled": False}
