"""Config-tree tests (reference test analog: config round-trips, batch triangle)."""

import pytest

from deepspeed_tpu.config.base import AUTO, ConfigError
from deepspeed_tpu.config.config import Config, load_config


def test_default_config():
    cfg = Config.from_dict({})
    assert cfg.bf16.enabled
    assert cfg.zero_optimization.stage == 0
    assert cfg.optimizer.type == "adamw"


def test_round_trip():
    src = {
        "train_micro_batch_size_per_device": 4,
        "gradient_accumulation_steps": 2,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 3, "offload_optimizer": {"device": "cpu"}},
        "mesh": {"fsdp": 4, "data": 2},
    }
    cfg = Config.from_dict(src)
    dumped = cfg.to_dict()
    cfg2 = Config.from_dict(dumped)
    assert cfg2.to_dict() == dumped
    assert cfg2.zero_optimization.stage == 3
    assert cfg2.zero_optimization.offload_optimizer.device == "cpu"
    assert cfg2.mesh.fsdp == 4


def test_unknown_key_rejected():
    with pytest.raises(ConfigError, match="unknown config key"):
        Config.from_dict({"not_a_real_key": 1})
    with pytest.raises(ConfigError, match="unknown config key"):
        Config.from_dict({"zero_optimization": {"stage": 1, "bogus": True}})


def test_deprecated_alias_migrates():
    cfg = Config.from_dict({"train_micro_batch_size_per_gpu": 8})
    assert cfg.train_micro_batch_size_per_device == 8


def test_auto_fields():
    cfg = Config.from_dict({"train_batch_size": "auto", "train_micro_batch_size_per_device": 2})
    assert cfg.train_batch_size == AUTO
    cfg.resolve_batch_sizes(dp_world_size=4)
    assert cfg.train_batch_size == 8
    with pytest.raises(ConfigError, match="'auto' is not supported"):
        Config.from_dict({"steps_per_print": "auto"})


def test_batch_triangle_resolution():
    cfg = Config.from_dict({"train_batch_size": 32, "train_micro_batch_size_per_device": 2})
    cfg.resolve_batch_sizes(dp_world_size=4)
    assert cfg.gradient_accumulation_steps == 4

    cfg = Config.from_dict({"train_batch_size": 32, "gradient_accumulation_steps": 2})
    cfg.resolve_batch_sizes(dp_world_size=4)
    assert cfg.train_micro_batch_size_per_device == 4

    cfg = Config.from_dict(
        {"train_batch_size": 30, "train_micro_batch_size_per_device": 4}
    )
    with pytest.raises(ConfigError, match="not divisible"):
        cfg.resolve_batch_sizes(dp_world_size=4)

    cfg = Config.from_dict({
        "train_batch_size": 16,
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 4,
    })
    with pytest.raises(ConfigError, match="Inconsistent"):
        cfg.resolve_batch_sizes(dp_world_size=4)


def test_invalid_values():
    with pytest.raises(ConfigError):
        Config.from_dict({"zero_optimization": {"stage": 5}})
    with pytest.raises(ConfigError):
        Config.from_dict({"optimizer": {"type": "rmsprop_nope"}})
    with pytest.raises(ConfigError, match="cannot both"):
        Config.from_dict({"fp16": {"enabled": True}, "bf16": {"enabled": True}})


def test_fp16_alone_disables_bf16_default():
    cfg = Config.from_dict({"fp16": {"enabled": True}})
    assert cfg.fp16.enabled is True and cfg.bf16.enabled is False
    assert cfg.precision_name == "fp16"


def test_legacy_cpu_offload_bool():
    cfg = Config.from_dict({"zero_optimization": {"stage": 2, "cpu_offload": True}})
    assert cfg.zero_optimization.offload_optimizer.device == "cpu"
    cfg = Config.from_dict({"zero_optimization": {"cpu_offload": False}})
    assert cfg.zero_optimization.offload_optimizer.device == "none"


def test_triangle_only_train_batch():
    cfg = Config.from_dict({"train_batch_size": 32})
    cfg.resolve_batch_sizes(dp_world_size=4)
    assert cfg.train_micro_batch_size_per_device == 8
    assert cfg.gradient_accumulation_steps == 1


def test_load_config_from_json(tmp_path):
    p = tmp_path / "ds.json"
    p.write_text('{"train_micro_batch_size_per_device": 2, "fp16": {"enabled": true}, "bf16": {"enabled": false}}')
    cfg = load_config(str(p))
    assert cfg.fp16.enabled and not cfg.bf16.enabled
    import jax.numpy as jnp

    assert cfg.compute_dtype == jnp.float16
