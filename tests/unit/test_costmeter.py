"""Request-level cost attribution (telemetry/costmeter.py +
docs/OBSERVABILITY.md "Cost attribution & tenant metering"):

- the occupancy-integral invariant: per-tenant KV block-seconds (live +
  retained carveout) must sum to the pool's busy-block integral (+-5%)
- cross-tenant prefix reuse is a symmetric credit/debit transfer
- tenant label cardinality is bounded (LRU cap, overflow folds into
  ``__other__``) while the ledger keeps exact rows
- meter off: the serving hot path executes ZERO costmeter.py code
  (tracemalloc-pinned) and tokens are identical to the unmetered engine
- per-SLA-class SLO windows burn independently (a batch backlog cannot
  flip the interactive objective, or vice versa)
"""

import json
import time
import tracemalloc

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import llama
from deepspeed_tpu.telemetry import (
    TELEMETRY,
    CostMeter,
    MetricsRegistry,
    OTHER_TENANT,
    RequestCost,
    SloMonitor,
    TenantLedger,
    default_class_objectives,
    default_objectives,
)

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
PCFG = dict(
    max_tokens_per_step=16, max_seqs=3, block_size=4, num_blocks=49,
    max_blocks_per_seq=16, decode_run_ahead=0, prefill_tile=0,
    fused_chunk=0, device_state=False)


def _engine(**over):
    rcfg = RaggedConfig(**{**PCFG, **over})
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), rcfg,
        dtype=jnp.float32, seed=0)


def _prompt(n, seed=0):
    return [int(t) for t in
            np.random.default_rng(seed).integers(0, CFG.vocab_size, n)]


def _meter(**over):
    telemetry.configure(enabled=True,
                        costmeter={"enabled": True, **over})
    return TELEMETRY.costmeter


@pytest.fixture(autouse=True, scope="module")
def _reset_telemetry():
    yield
    telemetry.configure(enabled=False)


@pytest.fixture(scope="module")
def ref_tokens():
    """Meter-off reference: every meter-on run must match."""
    telemetry.configure(enabled=False)
    eng = _engine()
    for i in range(3):
        eng.put(i, _prompt(9, seed=i), max_new_tokens=5)
    return eng.generate_all()


# ------------------------------------------------------------ pure ledger
class TestLedger:
    def test_transfer_symmetry(self):
        led = TenantLedger()
        led.transfer("pub", "con", 3)
        led.transfer("pub", "con", 2)
        rows = {r["tenant"]: r for r in led.rows()}
        assert rows["pub"]["prefix_credit_blocks"] == 5
        assert rows["con"]["prefix_debit_blocks"] == 5
        assert rows["pub"]["prefix_debit_blocks"] == 0
        assert rows["con"]["prefix_credit_blocks"] == 0

    def test_outstanding_share_single_tenant_parity(self):
        led = TenantLedger()
        led.set_outstanding({"only": 7})
        share, fair = led.outstanding_share("only")
        assert share == 1.0 and fair == 1.0  # penalty vanishes exactly

    def test_outstanding_share_multi_tenant(self):
        led = TenantLedger()
        led.set_outstanding({"hog": 9, "small": 3})
        share, fair = led.outstanding_share("hog")
        assert share == pytest.approx(0.75) and fair == pytest.approx(0.5)

    def test_label_cap_folds_to_other(self):
        reg = MetricsRegistry()
        cm = CostMeter(reg, max_tenants=2)
        for t in ("a", "b", "c", "d"):
            cost = RequestCost(tenant=t, sla_class="interactive")
            cost.decode_tokens = 1
            cost.kv_block_seconds = 0.5
            cm.observe(cost)
        prom = reg.render_prometheus()
        assert 'tenant="a"' in prom and 'tenant="b"' in prom
        assert 'tenant="c"' not in prom and 'tenant="d"' not in prom
        assert f'tenant="{OTHER_TENANT}"' in prom
        assert cm.label_folds >= 2
        # the ledger keeps EXACT rows past the label cap
        rows = {r["tenant"] for r in cm.ledger.rows()}
        assert {"a", "b", "c", "d"} <= rows
        payload = cm.debug_payload()
        json.dumps(payload)  # /debug/tenants must stay serializable
        assert payload["distinct_tenant_labels"] == 2
        assert payload["label_folds"] >= 2

    def test_tick_accumulates_and_attributes(self):
        reg = MetricsRegistry()
        cm = CostMeter(reg)
        a = cm.start("a", "interactive")
        b = cm.start("b", "batch")
        cm.tick(2.0, [(a, 3), (b, 1)], retained=[("a", 2)],
                pool_busy_blocks=6)
        assert a.kv_block_seconds == pytest.approx(6.0)
        assert b.kv_block_seconds == pytest.approx(2.0)
        rows = {r["tenant"]: r for r in cm.ledger.rows()}
        assert rows["a"]["retained_block_seconds"] == pytest.approx(4.0)
        # per-tenant integrals sum to the pool integral exactly here
        assert 6.0 + 2.0 + 4.0 == pytest.approx(6 * 2.0)


# ----------------------------------------------------- engine attribution
class TestEngineAttribution:
    def test_block_seconds_sum_matches_pool_integral(self):
        """Distinct prompts (no cross-seq block sharing): the per-tenant
        occupancy integrals must reconstruct the pool's busy integral."""
        cm = _meter()
        eng = _engine(enable_prefix_cache=True)
        for i in range(3):
            eng.put(i, _prompt(9, seed=10 + i), max_new_tokens=5,
                    tenant=f"t{i % 2}",
                    sla_class="interactive" if i % 2 else "batch")
        eng.generate_all()
        payload = cm.debug_payload()
        per_tenant = sum(
            r["kv_block_seconds"] + r["retained_block_seconds"]
            for r in payload["tenants"].values())
        pool = payload["pool_block_seconds"]
        assert pool > 0
        assert per_tenant == pytest.approx(pool, rel=0.05)

    def test_cross_tenant_prefix_credit_debit(self):
        """Tenant B splicing blocks tenant A published is a symmetric
        ledger transfer: A's credit == B's debit == spliced blocks."""
        cm = _meter()
        eng = _engine(enable_prefix_cache=True)
        shared = _prompt(8, seed=42)  # two full blocks at block_size=4
        eng.put("pub", shared, max_new_tokens=2, tenant="alice")
        eng.generate_all()
        eng.put("con", shared + _prompt(4, seed=43), max_new_tokens=2,
                tenant="bob")
        eng.generate_all()
        rows = {r["tenant"]: r for r in cm.ledger.rows()}
        credit = rows["alice"]["prefix_credit_blocks"]
        debit = rows["bob"]["prefix_debit_blocks"]
        assert credit == debit == 2
        assert rows["bob"]["prefix_credit_blocks"] == 0

    def test_queue_and_prefill_charged(self):
        cm = _meter()
        eng = _engine()
        eng.put(0, _prompt(9, seed=7), max_new_tokens=3, tenant="q")
        eng.generate_all()
        row = {r["tenant"]: r for r in cm.ledger.rows()}["q"]
        assert row["prefill_tokens"] == 9
        assert row["decode_tokens"] >= 3
        assert row["decode_dispatches"] >= 1
        assert row["requests"] == 1

    def test_reset_state_finalizes_costs(self):
        cm = _meter()
        eng = _engine()
        eng.put(0, _prompt(9, seed=3), max_new_tokens=40, tenant="rz")
        eng.step()
        eng.reset_state()
        rows = {r["tenant"]: r for r in cm.ledger.rows()}
        assert rows["rz"]["requests"] == 1  # folded exactly once
        assert not eng._block_tenant


# ------------------------------------------------------------ off is free
class TestOffIsFree:
    def test_meter_off_zero_allocations(self, ref_tokens):
        """Telemetry on but the meter off: serving a full batch must
        execute zero costmeter.py code — pinned by tracemalloc."""
        telemetry.configure(enabled=True)
        assert TELEMETRY.costmeter is None
        eng = _engine()
        for i in range(3):
            eng.put(i, _prompt(9, seed=i), max_new_tokens=5)
        tracemalloc.start()
        try:
            toks = eng.generate_all()
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        assert toks == ref_tokens
        stats = snap.filter_traces([tracemalloc.Filter(
            True, "*/telemetry/costmeter.py")]).statistics("filename")
        total = sum(s.size for s in stats)
        assert total == 0, f"costmeter allocated {total}B while disabled"

    def test_meter_on_tokens_identical(self, ref_tokens):
        _meter()
        eng = _engine()
        for i in range(3):
            eng.put(i, _prompt(9, seed=i), max_new_tokens=5,
                    tenant=f"t{i}")
        assert eng.generate_all() == ref_tokens


# ------------------------------------------------------- per-class SLO
class TestClassSlo:
    def _monitor(self, reg=None):
        reg = reg or MetricsRegistry()
        return SloMonitor(
            default_objectives(window_s=60.0), reg,
            class_objectives=default_class_objectives(window_s=60.0)), reg

    def test_batch_breach_does_not_flip_interactive(self):
        mon, reg = self._monitor()
        # breaching_classes() reads the real monotonic clock, so the
        # samples must sit inside its window, not at a synthetic epoch
        now = time.monotonic()
        for i in range(10):
            # terrible for batch (threshold 5s), recorded against batch only
            mon.record("ttft", 20.0, now=now + i, sla_class="batch")
            # healthy interactive samples
            mon.record("ttft", 0.01, now=now + i, sla_class="interactive")
        t = now + 10
        assert mon.stats("ttft", now=t, sla_class="batch")["breaching"]
        assert not mon.stats("ttft", now=t,
                             sla_class="interactive")["breaching"]
        assert ("batch", "ttft") in mon.breaching_classes()
        assert ("interactive", "ttft") not in mon.breaching_classes()
        prom = reg.render_prometheus()
        assert 'slo_good_fraction{objective="ttft",sla_class="batch"}' in prom
        assert ('slo_good_fraction{objective="ttft",'
                'sla_class="interactive"}') in prom

    def test_class_thresholds_differ(self):
        mon, _ = self._monitor()
        now = 2000.0
        # 1s TTFT: bad for interactive (0.5s), fine for batch (5s)
        for i in range(10):
            mon.record("ttft", 1.0, now=now + i, sla_class="interactive")
            mon.record("ttft", 1.0, now=now + i, sla_class="batch")
        t = now + 10
        assert mon.stats("ttft", now=t,
                         sla_class="interactive")["breaching"]
        assert not mon.stats("ttft", now=t, sla_class="batch")["breaching"]

    def test_health_includes_by_class(self):
        mon, _ = self._monitor()
        mon.record("ttft", 0.1, now=10.0, sla_class="interactive")
        h = mon.health()
        assert "by_class" in h
        assert "interactive" in h["by_class"]
        assert "ttft" in h["by_class"]["interactive"]
