"""Overlap-first backward: bucket-plan determinism, ring-collective parity,
and engine-level parity pins of the bucketed async grad path vs the fused
baseline (bucketed-vs-fused, sharded-vs-replicated update, qgZ composition,
exactness kill switch, sentinel verdict equivalence on poisoned grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError
from deepspeed_tpu.models import llama
from deepspeed_tpu.parallel import grad_overlap as go
from deepspeed_tpu.utils.compat import shard_map_compat

VOCAB = 256


# ------------------------------------------------------------------ plan unit
def _tree(order="abc"):
    leaves = {
        "a": {"w": np.arange(300, dtype=np.float32).reshape(30, 10)},
        "b": {"w": np.arange(38, dtype=np.float32)},
        "c": {"w": np.arange(1200, dtype=np.float32).reshape(40, 30)},
    }
    return {k: leaves[k] for k in order}


def test_plan_deterministic_and_insertion_order_invariant():
    p1 = go.plan_buckets(_tree("abc"), dp=8, target_bytes=1024)
    p2 = go.plan_buckets(_tree("cba"), dp=8, target_bytes=1024)
    p3 = go.plan_buckets(_tree("abc"), dp=8, target_bytes=1024)
    assert p1 == p2 == p3
    # assignment is keyed by the sorted leaf path, stable across restarts
    assert list(p1.paths) == sorted(p1.paths)


def test_plan_pow2_cap_and_padding():
    plan = go.plan_buckets(_tree(), dp=8, target_bytes=1500)
    # 1500 is pow2-floored to 1024
    assert plan.target_bytes == 1024
    for b in plan.buckets:
        assert b.padded % (8 * go._PAD) == 0
        assert b.shard * 8 == b.padded
        assert b.padded >= b.elems
    covered = sorted(l.pos for b in plan.buckets for l in b.leaves)
    assert covered == list(range(len(plan.paths)))


def test_plan_oversize_leaf_gets_own_bucket():
    plan = go.plan_buckets(_tree(), dp=2, target_bytes=256)
    big = [b for b in plan.buckets if any(l.size == 1200 for l in b.leaves)]
    assert len(big) == 1 and len(big[0].leaves) == 1


def test_plan_rejects_non_float_leaves():
    with pytest.raises(ValueError, match="float leaves only"):
        go.plan_buckets({"w": np.arange(4)}, dp=2, target_bytes=256)


def test_pack_unpack_round_trip():
    tree = _tree()
    plan = go.plan_buckets(tree, dp=8, target_bytes=1024)
    leaves, tdef = go.ordered_leaves(tree, plan)
    flats = [go.pack_bucket(leaves, b) for b in plan.buckets]
    out = go.unflatten_buckets(flats, plan, tdef)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_wire_bytes_codec_aware():
    assert go.wire_bytes_per_element("fp32") == 4.0
    # intN: N/8 payload + two fp32 scale stages per 64-block
    assert go.wire_bytes_per_element("int8") == pytest.approx(1.0 + 8 / 64)
    assert go.wire_bytes_per_element("int4") == pytest.approx(0.5 + 8 / 64)
    plan8 = go.plan_buckets(_tree(), dp=8, target_bytes=1024, codec="int8")
    plan32 = go.plan_buckets(_tree(), dp=8, target_bytes=1024, codec="fp32")
    for b8, b32 in zip(plan8.buckets, plan32.buckets):
        assert b8.wire_bytes < b32.wire_bytes / 3  # ~3.6x less on the wire


# ------------------------------------------------------------ ring collectives
def _mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def test_ring_reduce_scatter_matches_psum():
    mesh = _mesh8()
    x = np.random.default_rng(0).standard_normal((8, 1024)).astype(np.float32)

    def local(xs):
        return go.ring_reduce_scatter_sum(xs[0], "data")[None]

    got = shard_map_compat(local, mesh=mesh, in_specs=(P("data"),),
                           out_specs=P("data"), axis_names={"data"},
                           check_vma=False)(x)
    np.testing.assert_allclose(np.asarray(got).reshape(-1), x.sum(axis=0),
                               rtol=1e-5, atol=1e-5)


def test_ring_all_gather_matches_replication():
    mesh = _mesh8()
    x = np.random.default_rng(1).standard_normal((8, 128)).astype(np.float32)

    def local(xs):
        return go.ring_all_gather(xs[0], "data")[None]

    got = shard_map_compat(local, mesh=mesh, in_specs=(P("data"),),
                           out_specs=P("data"), axis_names={"data"},
                           check_vma=False)(x)
    for r in range(8):
        np.testing.assert_array_equal(np.asarray(got[r]).reshape(-1),
                                      x.reshape(-1))


# ------------------------------------------------------------------ engine e2e
def _builder():
    return lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx)


def _config(overlap=None, gas=1, fp16=False, clip=1.0, qgz=False,
            sentinel=False, optimizer="adamw"):
    zero = {"stage": 0}
    if qgz:
        zero["quantized_gradients"] = True
    if overlap is not None:
        zero["grad_overlap"] = overlap
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 0,
        "optimizer": {"type": optimizer, "params": {"lr": 1e-3}},
        "zero_optimization": zero,
        "mesh": {"data": 8},
        "sequence_length": 16,
        "seed": 7,
    }
    if clip:
        cfg["gradient_clipping"] = clip
    if fp16:
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    if sentinel:
        cfg["sentinel"] = {"enabled": True}
    return cfg


def _batches(n, batch, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (batch, 16), dtype=np.int32)}
            for _ in range(n)]


def _run(cfg, n_steps=3, poison_step=None):
    from deepspeed_tpu.comm.topology import reset_topology

    reset_topology()
    engine = deepspeed_tpu.initialize(model=_builder(), config=cfg, seed=11)[0]
    losses, metrics = [], []
    for i, b in enumerate(_batches(n_steps, engine.train_batch_size)):
        if i == poison_step:
            lead = b["input_ids"].shape[0]
            b = dict(b)
            b["__loss_mult__"] = np.full((lead,), np.nan, np.float32)
        losses.append(float(engine.train_batch(b)))
        metrics.append(dict(engine._last_metrics))
    params = jax.tree_util.tree_map(np.asarray, engine.params)
    engine.destroy()
    return losses, params, metrics


def _max_drift(a, b):
    return max(float(np.max(np.abs(x - y)))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


OV = {"enabled": True, "bucket_bytes": 65536}


def test_bucketed_matches_fused_and_sharded_matches_replicated():
    """The three core parity pins in one compile budget: bucketed-sharded vs
    fused baseline (fp-reorder bounded), sharded vs replicated update
    (bit-identical — elementwise update commutes with sharding), exactness
    kill switch (bit-identical to baseline)."""
    base_l, base_p, _ = _run(_config())
    sh_l, sh_p, _ = _run(_config(overlap=OV))
    rep_l, rep_p, _ = _run(_config(overlap={**OV, "sharded_update": False}))
    ex_l, ex_p, _ = _run(_config(overlap={**OV, "exact": True}))

    # documented fp-reorder bound (ring sum order + local-mean-then-pmean)
    np.testing.assert_allclose(base_l, sh_l, rtol=2e-4, atol=2e-4)
    assert _max_drift(base_p, sh_p) < 5e-3

    # sharded and replicated updates are the same math, elementwise
    assert sh_l == rep_l
    assert _max_drift(sh_p, rep_p) == 0.0

    # exact: true routes through the fused baseline program — bit-identical
    assert ex_l == base_l
    assert _max_drift(ex_p, base_p) == 0.0


@pytest.mark.slow
@pytest.mark.parametrize("gas,fp16,clip", [
    (2, False, 1.0), (1, True, 1.0), (2, True, 0.0), (1, False, 0.0),
])
def test_overlap_parity_matrix(gas, fp16, clip):
    kw = dict(gas=gas, fp16=fp16, clip=clip)
    base_l, base_p, _ = _run(_config(**kw))
    ov_l, ov_p, _ = _run(_config(overlap=OV, **kw))
    np.testing.assert_allclose(base_l, ov_l, rtol=3e-4, atol=3e-4)
    assert _max_drift(base_p, ov_p) < 5e-3


@pytest.mark.slow
def test_qgz_bucketed_matches_unbucketed():
    """qgZ int8 per-bucket reduction vs the per-leaf qgrad baseline: same
    codec, same error-feedback semantics, different payload granularity."""
    q_l, q_p, _ = _run(_config(qgz=True))
    oq_l, oq_p, _ = _run(_config(overlap=OV, qgz=True))
    np.testing.assert_allclose(q_l, oq_l, rtol=1e-3, atol=1e-3)
    assert _max_drift(q_p, oq_p) < 5e-3


def test_sentinel_verdict_equivalence_on_nan_grads():
    """A poisoned (NaN-grad) step must produce the same sentinel verdict and
    the same skip behavior through the overlap path as through the fused
    baseline: step skipped, params untouched, anomaly flagged."""
    base_l, base_p, base_m = _run(_config(sentinel=True), poison_step=1)
    ov_l, ov_p, ov_m = _run(_config(overlap=OV, sentinel=True),
                            poison_step=1)
    for m in (base_m[1], ov_m[1]):
        assert bool(m["anomalous"]) and float(m["skipped"]) == 1.0
    for m in (base_m[0], ov_m[0]):
        assert not bool(m["anomalous"]) and float(m["skipped"]) == 0.0
    # verdict equivalence: overlap skips exactly when the baseline skips
    assert [bool(m["anomalous"]) for m in base_m] == \
        [bool(m["anomalous"]) for m in ov_m]
    np.testing.assert_allclose(base_l[2], ov_l[2], rtol=3e-4, atol=3e-4)


def test_comms_plan_and_bucket_telemetry():
    from deepspeed_tpu.telemetry import TELEMETRY
    from deepspeed_tpu.utils.comms_logging import COMMS_LOGGER

    cfg = _config(overlap=OV)
    cfg["comms_logger"] = {"enabled": True}
    cfg["telemetry"] = {"enabled": True}
    _run(cfg, n_steps=1)
    plan_rows = COMMS_LOGGER.traced
    rs, ag = plan_rows["reduce_scatter"], plan_rows["all_gather"]
    snap = TELEMETRY.registry.snapshot()
    n_buckets = int(snap["grad_bucket_count"]["series"][0]["value"])
    assert n_buckets > 1
    # one reduce-scatter row per bucket; ONE ring all-gather of updated params
    assert rs.count == n_buckets
    assert ag.count == 1
    wire = snap["grad_bucket_wire_bytes"]["series"]
    assert len(wire) == n_buckets
    assert all(s["labels"].get("codec") == "fp32" for s in wire)
    assert sum(s["value"] for s in wire) == rs.total_bytes


def test_grad_wire_bytes_codec_aware():
    from deepspeed_tpu.comm.topology import reset_topology

    reset_topology()
    eng = deepspeed_tpu.initialize(model=_builder(), config=_config(),
                                   seed=11)[0]
    fp32_wire = eng._grad_wire_bytes()
    n = sum(l.size for l in jax.tree_util.tree_leaves(eng.params))
    # fused fp32: 2 * 4B * n * (dp-1)/dp — the pre-codec formula
    assert fp32_wire == pytest.approx(2.0 * 4.0 * n * 7 / 8)
    eng.destroy()
    reset_topology()
    eng = deepspeed_tpu.initialize(model=_builder(), config=_config(qgz=True),
                                   seed=11)[0]
    q_wire = eng._grad_wire_bytes()
    assert q_wire < fp32_wire / 3  # int8 estimate, not 4x-pessimistic fp32
    eng.destroy()


def test_config_validation():
    with pytest.raises(ConfigError):
        _run(_config(overlap={"enabled": True, "bucket_bytes": 8}), n_steps=0)
    # sharded update needs an elementwise optimizer
    with pytest.raises(ValueError, match="sharded_update"):
        _run(_config(overlap=OV, optimizer="lamb"), n_steps=0)


def test_backward_api_refused_under_overlap():
    from deepspeed_tpu.comm.topology import reset_topology

    reset_topology()
    eng = deepspeed_tpu.initialize(model=_builder(), config=_config(overlap=OV),
                                   seed=11)[0]
    with pytest.raises(RuntimeError, match="grad_overlap"):
        eng.backward(_batches(1, eng.train_batch_size)[0])
    eng.destroy()
