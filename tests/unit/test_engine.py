"""Engine end-to-end on the 8-device CPU mesh: loss decreases, ZeRO-stage loss
parity, fp16 loss scaling, GAS equivalence, fwd/bwd/step parity path
(reference test style: ``tests/unit/runtime`` train-and-compare suites)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2, llama
from deepspeed_tpu.runtime.dataloader import random_token_loader

VOCAB = 256


def _builder(kind="llama"):
    if kind == "llama":
        return lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx)
    return lambda ctx: gpt2.build(gpt2.GPT2Config.tiny(VOCAB), ctx=ctx)


def _config(stage=0, **over):
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": over.pop("gas", 1),
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "mesh": over.pop("mesh", {"data": 8}),
        "bf16": {"enabled": over.pop("bf16", False)},
        "seed": 7,
    }
    cfg.update(over)
    return cfg


def _fixed_batches(n, batch, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {"input_ids": rng.integers(0, VOCAB, (batch, seq), dtype=np.int32)}
        for _ in range(n)
    ]


def _run(stage, n_steps=6, gas=1, mesh=None, kind="llama", bf16=False, fp16=None, seed=0):
    cfg = _config(stage=stage, gas=gas, mesh=mesh or {"data": 8}, bf16=bf16)
    if fp16:
        cfg["fp16"] = fp16
        cfg["bf16"] = {"enabled": False}
    engine, _, _, _ = deepspeed_tpu.initialize(model=_builder(kind), config=cfg, seed=11)
    batches = _fixed_batches(n_steps, engine.train_batch_size, seed=seed)
    losses = [float(engine.train_batch(b)) for b in batches]
    return engine, losses


def test_train_loss_decreases():
    engine, losses = _run(stage=0, n_steps=8)
    assert losses[-1] < losses[0], losses
    assert engine.global_steps == 8
    assert engine.global_samples == 8 * engine.train_batch_size


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_loss_parity(stage):
    """All ZeRO stages must produce the same loss trajectory as stage 0
    (reference: zero suites comparing vs unpartitioned baseline)."""
    _, base = _run(stage=0, n_steps=5, mesh={"data": 1, "fsdp": 8})
    _, test = _run(stage=stage, n_steps=5, mesh={"data": 1, "fsdp": 8})
    np.testing.assert_allclose(base, test, rtol=2e-4, atol=2e-5)


def test_zero3_params_actually_sharded():
    engine, _ = _run(stage=3, n_steps=1, mesh={"data": 1, "fsdp": 8})
    wq = engine.params["layers"]["wq"]
    assert wq.addressable_shards[0].data.size == wq.size // 8
    mu = engine.opt_state[0].mu["layers"]["wq"]
    assert mu.addressable_shards[0].data.size == mu.size // 8


def test_gas_matches_big_batch():
    """GAS=4 with micro=2 must match GAS=1 with micro=8 (same global batch)."""
    cfg_a = _config(stage=0, gas=4)
    cfg_b = _config(stage=0, gas=1)
    cfg_b["train_micro_batch_size_per_device"] = 8

    batches = _fixed_batches(4, 64, seed=3)
    engine_a, _, _, _ = deepspeed_tpu.initialize(model=_builder(), config=cfg_a, seed=11)
    losses_a = [float(engine_a.train_batch(b)) for b in batches]
    from deepspeed_tpu.comm.topology import reset_topology

    reset_topology()
    engine_b, _, _, _ = deepspeed_tpu.initialize(model=_builder(), config=cfg_b, seed=11)
    losses_b = [float(engine_b.train_batch(b)) for b in batches]
    np.testing.assert_allclose(losses_a, losses_b, rtol=1e-4)


def test_forward_backward_step_parity_with_train_batch():
    """The fwd/bwd/step protocol must match the fused train_batch path."""
    batches = _fixed_batches(2, 16, seed=5)

    engine_a, _, _, _ = deepspeed_tpu.initialize(
        model=_builder(), config=_config(stage=2, gas=2), seed=11
    )
    for b in batches:
        loss_a = engine_a.train_batch(b)

    from deepspeed_tpu.comm.topology import reset_topology

    reset_topology()
    engine_b, _, _, _ = deepspeed_tpu.initialize(
        model=_builder(), config=_config(stage=2, gas=2), seed=11
    )
    for b in batches:
        half = b["input_ids"].shape[0] // 2
        l1 = engine_b.backward({"input_ids": b["input_ids"][:half]})
        assert not engine_b.is_gradient_accumulation_boundary()
        l2 = engine_b.backward({"input_ids": b["input_ids"][half:]})
        assert engine_b.is_gradient_accumulation_boundary()
        engine_b.step()
        loss_b = (float(l1) + float(l2)) / 2

    leaves_a = jax.tree_util.tree_leaves(engine_a.params)
    leaves_b = jax.tree_util.tree_leaves(engine_b.params)
    for a, b_ in zip(leaves_a, leaves_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=1e-5)
    assert float(loss_a) == pytest.approx(loss_b, rel=1e-4)


def test_fp16_loss_scaling_and_overflow_skip():
    engine, losses = _run(
        stage=0,
        n_steps=3,
        fp16={"enabled": True, "initial_scale_power": 4, "loss_scale_window": 2},
        kind="gpt2",
    )
    assert engine.loss_scale >= 16.0  # grew after window or stayed
    assert all(np.isfinite(losses))

    # force an overflow: blow up a parameter so grads go inf
    engine.params["wte"] = engine.params["wte"].at[0, 0].set(jnp.float32(3e38))
    before = jax.tree_util.tree_map(np.asarray, engine.params["layers"])
    scale_before = engine.loss_scale
    engine.train_batch(_fixed_batches(1, engine.train_batch_size, seed=9)[0])
    assert engine.skipped_steps >= 1
    assert engine.loss_scale <= scale_before
    after = engine.params["layers"]
    np.testing.assert_array_equal(np.asarray(after["wq"]), before["wq"])  # update skipped


def test_bf16_trains():
    engine, losses = _run(stage=2, n_steps=5, bf16=True, mesh={"data": 2, "fsdp": 4})
    assert losses[-1] < losses[0]
    # master weights stay fp32
    assert engine.params["layers"]["wq"].dtype == jnp.float32


def test_gradient_clipping():
    cfg = _config(stage=0)
    cfg["gradient_clipping"] = 1e-6  # clip everything to ~zero update
    engine, _, _, _ = deepspeed_tpu.initialize(model=_builder(), config=cfg, seed=11)
    before = np.asarray(engine.params["layers"]["wq"]).copy()
    engine.train_batch(_fixed_batches(1, engine.train_batch_size)[0])
    after = np.asarray(engine.params["layers"]["wq"])
    assert np.abs(after - before).max() < 1e-4
    assert engine.get_global_grad_norm() > 0


def test_train_with_data_iter():
    cfg = _config(stage=0, gas=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=_builder("gpt2"), config=cfg, seed=11)
    loader = random_token_loader(engine.config.train_micro_batch_size_per_device * 8,
                                 16, VOCAB, seed=1)
    loss = engine.train_batch(data_iter=loader)
    assert np.isfinite(float(loss))
    assert engine.micro_steps == 2


def test_tp_plus_dp_trains():
    engine, losses = _run(stage=0, n_steps=4, mesh={"data": 2, "tensor": 4})
    assert losses[-1] < losses[0]
    wq = engine.params["layers"]["wq"]
    assert "tensor" in str(wq.sharding.spec)
