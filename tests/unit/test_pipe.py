"""Staged MPMD pipeline runtime (runtime/pipe/, docs/PIPELINE.md): the
partitioner's boundary math and subset/merge round-trip, closed-form
schedule validity, exact loss-trajectory parity of the 2-stage engine
against the fused single-program baseline (fp16 scaling + accumulation +
clipping on), per-stage checkpoint fragments with cross-topology restore,
in-process stage-crash replay, the pipe observability gauges, and the
staging-refusal guardrails."""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint import engine as ckpt
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime.engine import Engine
from deepspeed_tpu.runtime.pipe import partition, schedule
from deepspeed_tpu.runtime.pipe.engine import PipeEngine
from deepspeed_tpu.serving import faults

VOCAB = 97


def _builder(n_layers=4, tie=False):
    def build(ctx):
        return llama.build(llama.LlamaConfig(
            vocab_size=VOCAB, hidden_size=32, intermediate_size=64,
            num_layers=n_layers, num_heads=4, num_kv_heads=2,
            max_seq_len=64, tie_embeddings=tie), ctx=ctx)
    return build


def _config(extra=None, gas=2):
    cfg = {
        "train_micro_batch_size_per_device": 4,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "mesh": {"data": 1},
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "gradient_clipping": 1.0,
        "seed": 7,
    }
    cfg.update(extra or {})
    return cfg


def _batches(n, bsz, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (bsz, seq), dtype=np.int32)}
            for _ in range(n)]


def _run(extra, n=4, n_layers=4, gas=2, seed=0):
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=_builder(n_layers), config=_config(extra, gas=gas), seed=11,
        mesh_devices=jax.devices()[:1])
    losses = [float(eng.train_batch(b))
              for b in _batches(n, eng.train_batch_size, seed=seed)]
    return eng, losses


# ---------------------------------------------------------------- partitioner

def test_plan_stages_uniform_and_uneven():
    plan = partition.plan_stages(4, 2)
    assert plan.boundaries == (0, 2, 4)
    # remainder spreads over the leading chunks
    plan = partition.plan_stages(7, 3)
    assert plan.boundaries == (0, 3, 5, 7)
    assert [plan.layer_range(v) for v in range(3)] == [(0, 3), (3, 5), (5, 7)]
    # interleaved: virtual chunks pinned to thread v % S
    plan = partition.plan_stages(8, 2, interleave=2)
    assert plan.n_virtual == 4 and plan.boundaries == (0, 2, 4, 6, 8)
    assert plan.chunks_of(0) == [0, 2] and plan.chunks_of(1) == [1, 3]


def test_plan_stages_parameters_method_balances_cost():
    # heavy head: cost-balanced boundary moves left of the uniform midpoint
    costs = [10.0, 10.0, 1.0, 1.0, 1.0, 1.0]
    plan = partition.plan_stages(6, 2, method="parameters",
                                 layer_costs=costs)
    assert plan.boundaries[1] <= 2
    # without cost data the method degrades to uniform
    plan = partition.plan_stages(6, 2, method="parameters")
    assert plan.boundaries == (0, 3, 6)


def test_plan_stages_rejects_bad_plans():
    with pytest.raises(ValueError, match="at least one layer"):
        partition.plan_stages(2, 4)
    with pytest.raises(ValueError, match="at least one layer"):
        partition.plan_stages(4, 2, interleave=4)
    with pytest.raises(ValueError, match="partition_method"):
        partition.plan_stages(4, 2, method="zigzag")


def test_split_merge_roundtrip():
    rng = np.random.default_rng(0)

    def arr(*shape):
        return rng.normal(size=shape).astype(np.float32)

    params = {
        "layers": {"w": arr(6, 3), "b": arr(6)},
        "embed": arr(5, 3),
        "head": arr(3, 5),
    }
    plan = partition.plan_stages(6, 3)
    owner = {"embed": "first", "head": "last"}
    trees = partition.split_params(params, plan, owner)
    assert "embed" in trees[0] and "embed" not in trees[1]
    assert "head" in trees[2] and "head" not in trees[0]
    assert trees[1]["layers"]["w"].shape == (2, 3)
    merged = partition.merge_params(trees, plan)
    for key in ("embed", "head"):
        np.testing.assert_array_equal(merged[key], params[key])
    np.testing.assert_array_equal(merged["layers"]["w"], params["layers"]["w"])
    # an unowned extra key is a loud error, not a silently dropped tensor
    with pytest.raises(ValueError, match="no stage owner"):
        partition.split_params(params, plan, {"embed": "first"})


# ------------------------------------------------------------------ schedules

@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
@pytest.mark.parametrize("n_stages,interleave,n_micro", [
    (2, 1, 1), (2, 1, 4), (2, 2, 4), (3, 1, 5), (4, 2, 8),
])
def test_schedules_validate(sched, n_stages, interleave, n_micro):
    n_virtual = n_stages * interleave
    instrs = schedule.build_schedule(sched, n_virtual, n_micro)
    schedule.validate_schedule(instrs, n_virtual, n_stages, n_micro)
    frac = schedule.bubble_fraction(sched, n_virtual, n_micro)
    assert 0.0 < frac < 1.0
    # more microbatches shrink the bubble
    assert schedule.bubble_fraction(sched, n_virtual, 4 * n_micro) < frac


def test_validate_schedule_catches_corruption():
    instrs = schedule.build_schedule("1f1b", 2, 2)
    with pytest.raises(ValueError, match="permutation"):
        schedule.validate_schedule(instrs[:-1], 2, 2, 2)
    # swapping two ops within a thread breaks the dependency order
    broken = [schedule.Instr(i.t, i.v, "B" if i.op == "F" else "F", i.mb)
              for i in instrs]
    with pytest.raises(ValueError):
        schedule.validate_schedule(broken, 2, 2, 2)


# --------------------------------------------------------------------- parity

def test_1f1b_parity_16_steps():
    """Acceptance pin: 2-stage 1F1B loss trajectory within 1e-6 rel of the
    fused baseline over 16 steps with GAS, fp16 loss scaling, and gradient
    clipping all on (on CPU the two are bit-identical — the boundary update
    reduces over the merged gradient tree, so the clip coefficient is the
    same fp32 scalar; see docs/PIPELINE.md)."""
    _, base = _run(None, n=16)
    eng, pipe = _run({"pipeline": {"stages": 2, "schedule": "1f1b"}}, n=16)
    assert isinstance(eng, PipeEngine)
    rel = max(abs(a - b) / max(abs(a), 1e-12) for a, b in zip(base, pipe))
    assert rel <= 1e-6, (rel, base, pipe)


def test_gpipe_and_interleaved_parity():
    _, base = _run(None, n=3)
    _, gp = _run({"pipeline": {"stages": 2, "schedule": "gpipe"}}, n=3)
    assert base == gp, (base, gp)
    # interleaved 1F1B: 8 layers, 2 stages x 2 chunks = 4 virtual stages
    _, base8 = _run(None, n=3, n_layers=8, gas=4)
    _, il = _run({"pipeline": {"stages": 2, "interleave": 2,
                               "schedule": "1f1b"}},
                 n=3, n_layers=8, gas=4)
    assert base8 == il, (base8, il)


def test_stages_1_degenerates_to_plain_engine():
    eng0, l0 = _run(None, n=1)
    eng1, l1 = _run({"pipeline": {"stages": 1}}, n=1)
    assert type(eng0) is Engine and type(eng1) is Engine
    assert l0 == l1


# ---------------------------------------------------------------- checkpoints

def test_pipeline_checkpoint_fragments_and_cross_stage_restore(tmp_path):
    save_dir = str(tmp_path / "ckpt")
    pipe_eng, _ = _run({"pipeline": {"stages": 2, "schedule": "1f1b"}}, n=2)
    pipe_eng.save_checkpoint(save_dir, tag="t2")
    cont = _batches(4, pipe_eng.train_batch_size)[2:4]
    after = [float(pipe_eng.train_batch(b)) for b in cont]

    # per-stage fragment naming + the manifest's pipeline row
    files = sorted(os.listdir(os.path.join(save_dir, "t2")))
    for name in ("model_shard_p0_s0.npz", "model_shard_p0_s1.npz",
                 "optimizer_shard_p0_s0.npz", "optimizer_shard_p0_s1.npz"):
        assert name in files, files
    with open(os.path.join(save_dir, "t2", "manifest.json")) as f:
        man = json.load(f)
    row = man["pipeline"]
    assert row["stages"] == 2 and row["schedule"] == "1f1b"
    assert row["boundaries"] == [0, 2, 4]
    assert set(row["fragments"]) == {"0", "1"}

    # 2-stage save -> 2-stage restore: exact resume
    p2, _, _, _ = deepspeed_tpu.initialize(
        model=_builder(), config=_config({"pipeline": {"stages": 2}}),
        seed=11, mesh_devices=jax.devices()[:1])
    p2.load_checkpoint(save_dir, tag="t2")
    assert [float(p2.train_batch(b)) for b in cont] == after

    # 2-stage save -> single-program merged restore: exact resume
    p1, _, _, _ = deepspeed_tpu.initialize(
        model=_builder(), config=_config(), seed=11,
        mesh_devices=jax.devices()[:1])
    p1.load_checkpoint(save_dir, tag="t2")
    assert [float(p1.train_batch(b)) for b in cont] == after


def test_verify_checkpoint_flags_missing_pipeline_fragment(tmp_path):
    man = {"pipeline": {"stages": 2,
                        "fragments": {"0": ["model_shard_p0_s0.npz"],
                                      "1": ["model_shard_p0_s1.npz"]}}}
    with pytest.raises(ckpt.CheckpointCorruptError) as err:
        ckpt._verify_pipeline_fragments(str(tmp_path), "t0", man)
    assert err.value.stage == "pipeline-fragments"


# ------------------------------------------------------------ failure + scope

def test_stage_crash_replays_exactly():
    inj = faults.get_fault_injector()
    inj.reset()
    try:
        _, clean = _run({"pipeline": {"stages": 2, "schedule": "1f1b"}}, n=3)
        inj.configure([{"point": "pipe.stage", "kind": "raise", "times": 1,
                        "request_id": "stage1", "after": 6}])
        eng, crashed = _run({"pipeline": {"stages": 2, "schedule": "1f1b"}},
                            n=3)
        assert eng.stage_restarts >= 1
        assert clean == crashed, (clean, crashed)
    finally:
        inj.reset()


def test_pipe_observability_gauges():
    from deepspeed_tpu.telemetry import TELEMETRY

    eng, _ = _run({"pipeline": {"stages": 2, "schedule": "1f1b"},
                   "telemetry": {"enabled": True,
                                 "stepscope": {"enabled": True}}}, n=2)
    assert len(eng._last_stage_busy) == 2 and eng._last_stage_wall > 0
    assert eng.stepscope._g_pipe_bubble.value() > 0.0
    prom = TELEMETRY.registry.render_prometheus()
    assert "train_pipe_bubble_fraction" in prom
    assert 'train_step_skew_ratio{stage="0"}' in prom
    assert 'train_step_skew_ratio{stage="1"}' in prom
    # the pipe_bubble phase joins the ledger without breaking the wall pin
    summary = eng.stepscope.summary()
    assert summary["phase_seconds_total"].get("pipe_bubble", 0.0) > 0.0
    assert abs(summary["phase_sum_over_step_ratio"] - 1.0) <= 0.05


def test_staging_refuses_unsupported_features():
    # tied embeddings: no stage owner for the shared table
    with pytest.raises(ValueError, match="tie"):
        deepspeed_tpu.initialize(
            model=_builder(tie=True),
            config=_config({"pipeline": {"stages": 2}}),
            seed=11, mesh_devices=jax.devices()[:1])
    # in-jit pipeline mesh axis + staged runtime is a contradiction
    with pytest.raises(ValueError):
        deepspeed_tpu.initialize(
            model=_builder(),
            config=_config({"pipeline": {"stages": 2},
                            "mesh": {"data": 1, "pipeline": 2}}),
            seed=11, mesh_devices=jax.devices()[:2])
