"""MoE gating semantics + expert-parallel training
(reference: ``tests/unit/moe/`` and ``sharded_moe.py`` gating math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MoEConfig
from deepspeed_tpu.models import mixtral
from deepspeed_tpu.parallel.moe import compute_capacity, moe_ffn, top_k_gating

VOCAB = 256


def test_capacity_math():
    # reference: capacity_factor * tokens / experts, floored at min_capacity
    assert compute_capacity(64, 4, 1.0, 4) == 16
    assert compute_capacity(64, 4, 1.25, 4) == 20
    assert compute_capacity(8, 8, 1.0, 4) == 4  # min_capacity floor


def test_top1_routing_selects_argmax():
    logits = jnp.array([[5.0, 0.0, 0.0], [0.0, 5.0, 0.0], [0.0, 0.0, 5.0]])
    g = top_k_gating(logits, k=1, capacity=3)
    picked = np.argmax(np.asarray(g.dispatch).sum(-1), axis=-1)
    np.testing.assert_array_equal(picked, [0, 1, 2])
    assert float(g.dropped_frac) == 0.0


def test_top2_combine_weights_normalized():
    logits = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
    g = top_k_gating(logits, k=2, capacity=16)
    weights = np.asarray(g.combine).sum(axis=(1, 2))
    np.testing.assert_allclose(weights, np.ones(16), rtol=1e-5)


def test_capacity_dropping():
    # all tokens want expert 0; capacity 2 -> rest dropped
    logits = jnp.tile(jnp.array([[10.0, 0.0]]), (8, 1))
    g = top_k_gating(logits, k=1, capacity=2)
    kept = np.asarray(g.dispatch)[:, 0, :].sum()
    assert kept == 2
    assert float(g.dropped_frac) == pytest.approx(6 / 8)
    # first-come-first-served (slot order): tokens 0,1 kept
    assert np.asarray(g.dispatch)[0, 0].sum() == 1
    assert np.asarray(g.dispatch)[2, 0].sum() == 0


def test_aux_loss_uniform_is_one():
    """Perfectly uniform routing gives aux == 1 (GShard normalization)."""
    t, e = 64, 4
    logits = jnp.zeros((t, e)).at[jnp.arange(t), jnp.arange(t) % e].set(5.0)
    g = top_k_gating(logits, k=1, capacity=t)
    assert float(g.aux_loss) == pytest.approx(1.0, rel=0.05)


def test_moe_ffn_shapes_and_dropless():
    cfg = MoEConfig(enabled=True, num_experts=4, top_k=2, drop_tokens=False)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    router = jax.random.normal(jax.random.PRNGKey(1), (16, 4)) * 0.1
    wg = jax.random.normal(jax.random.PRNGKey(2), (4, 16, 32)) * 0.1
    wu = jax.random.normal(jax.random.PRNGKey(3), (4, 16, 32)) * 0.1
    wd = jax.random.normal(jax.random.PRNGKey(4), (4, 32, 16)) * 0.1
    y, aux = moe_ffn(x, router, wg, wu, wd, cfg)
    assert y.shape == x.shape
    assert np.isfinite(float(aux))


def _cfg(mesh, stage=0):
    return {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh,
        "seed": 7,
    }


def _run(mesh, stage=0, n=4):
    reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: mixtral.build(mixtral.MixtralConfig.tiny(VOCAB), ctx=ctx),
        config=_cfg(mesh, stage),
        seed=11,
    )
    rng = np.random.default_rng(3)
    losses = []
    for _ in range(n):
        b = {"input_ids": rng.integers(0, VOCAB, (engine.train_batch_size, 16), dtype=np.int32)}
        losses.append(float(engine.train_batch(b)))
    return engine, losses


def test_mixtral_trains_dense_mesh():
    engine, losses = _run({"data": 8})
    assert losses[-1] < losses[0], losses


_MOE_SETUP = """
import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import mixtral

def run(mesh, n=4, stage=0):
    reset_topology()
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
           "steps_per_print": 0,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": stage}, "mesh": mesh, "seed": 7}
    e, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: mixtral.build(mixtral.MixtralConfig.tiny(256), ctx=ctx),
        config=cfg, seed=11)
    r = np.random.default_rng(3)
    return [float(e.train_batch({"input_ids": r.integers(0, 256, (16, 16), np.int32)}))
            for _ in range(n)]
"""


def _run_isolated(body: str, marker: str) -> None:
    """EP training scenarios run in a clean subprocess — the in-process
    multi-mesh collective wedge (see tests/unit/isolation.py)."""
    from isolation import run_isolated

    run_isolated(_MOE_SETUP + body, marker)


def test_expert_parallel_loss_parity():
    """EP=4 must match the pure-DP trajectory (expert axis is a batch axis,
    so dp_world stays 8 and the data split is identical)."""
    _run_isolated("""
base = run({"data": 8})
ep = run({"data": 2, "expert": 4})
np.testing.assert_allclose(base, ep, rtol=3e-4, atol=3e-5)
print("PARITY_OK")
""", "PARITY_OK")


def test_expert_weights_sharded_over_expert_axis():
    # engine init only — in-process EP *training* programs wedge XLA's CPU
    # collectives deep into a pytest session (see the parity test's note);
    # the sharding-plan assertion needs no step
    engine, _ = _run({"data": 2, "expert": 4}, n=0)
    wg = engine.params["layers"]["w_gate"]
    assert "expert" in str(wg.sharding.spec)
    # 4 experts over 4-way expert axis: each device holds 1 expert's weights
    assert wg.addressable_shards[0].data.shape[1] == 1


def test_ep_plus_zero3():
    """EP x fsdp ZeRO-3 training converges (subprocess-isolated, see
    _run_isolated)."""
    _run_isolated("""
losses = run({"data": 1, "fsdp": 2, "expert": 4}, stage=3)
assert losses[-1] < losses[0], losses
print("EP_Z3_OK")
""", "EP_Z3_OK")
