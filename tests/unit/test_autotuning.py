"""Autotuner: small measured grid search (reference: ``tests/unit/autotuning``)."""

import numpy as np
import pytest

from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.models import llama

VOCAB = 256


def test_autotuner_picks_a_working_config():
    tuner = Autotuner(
        model_builder=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        base_config={
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "mesh": {"data": 8},
        },
        steps_per_trial=1,
    )
    best = tuner.tune(micro_batch_sizes=[2, 4], zero_stages=[0, 1],
                      seq_len=16, vocab=VOCAB)
    assert best["zero_stage"] in (0, 1)
    assert best["micro_batch"] in (2, 4)
    ok = [r for r in tuner.results if r.ok]
    assert len(ok) == 4  # all trials viable at this size
    assert max(r.samples_per_sec for r in ok) == \
        next(r for r in ok if r.overrides == best).samples_per_sec
