"""Autotuner: small measured grid search (reference: ``tests/unit/autotuning``).

The tuner builds many engines over VARIED meshes back-to-back — exactly the
in-process multi-mesh churn that can wedge XLA's emulated CPU collectives
(tests/unit/isolation.py) — so each scenario runs subprocess-isolated.
"""

from deepspeed_tpu.autotuning.autotuner import probe_model_info
from deepspeed_tpu.models import llama
from isolation import run_isolated  # tests/unit is rootdir-inserted by pytest

VOCAB = 256

_SETUP = """
from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.models import llama
VOCAB = 256
builder = lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx)
"""


def test_autotuner_picks_a_working_config():
    run_isolated(_SETUP + """
tuner = Autotuner(
    model_builder=builder,
    base_config={"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                 "mesh": {"data": 8}},
    steps_per_trial=1,
)
best = tuner.tune(micro_batch_sizes=[2, 4], zero_stages=[0, 1],
                  seq_len=16, vocab=VOCAB)
assert best["zero_stage"] in (0, 1)
assert best["micro_batch"] in (2, 4)
ok = [r for r in tuner.results if r.ok]
assert len(ok) == 4  # all trials viable at this size
assert max(r.samples_per_sec for r in ok) == \\
    next(r for r in ok if r.overrides == best).samples_per_sec
print("TUNE_OK")
""", "TUNE_OK")


def test_model_info_probe():
    """The model-profile estimates order correctly (pure math, in-process)."""
    builder = lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx)  # noqa: E731
    info = probe_model_info(builder)
    assert info.num_params > 0 and info.hidden_size == 64
    # sharding 8 ways shrinks the estimate; stage 3 shards the most
    assert info.state_bytes(3, 8) < info.state_bytes(1, 8) < info.state_bytes(0, 8)
    assert info.activation_bytes(4, 128) == 2 * info.activation_bytes(2, 128)


def test_model_info_pruning_skips_oversized_configs():
    """With a (synthetic) tiny memory limit, the model-profile estimate
    prunes stage-0 configs before any engine is built (reference
    model-info pruning, autotuner.py:42)."""
    run_isolated(_SETUP + """
from deepspeed_tpu.autotuning.autotuner import probe_model_info
info = probe_model_info(builder)
limit = info.state_bytes(0, 8) * 0.5
assert info.state_bytes(3, 8) < 0.9 * limit < info.state_bytes(0, 8)
tuner = Autotuner(
    model_builder=builder,
    base_config={"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                 "mesh": {"data": 1, "fsdp": 8}},
    steps_per_trial=1,
)
best = tuner.tune(micro_batch_sizes=[2], zero_stages=[0, 3],
                  seq_len=16, vocab=VOCAB, memory_bytes=limit)
skipped = [r for r in tuner.results if r.skipped]
assert skipped and skipped[0].overrides["zero_stage"] == 0
assert best["zero_stage"] == 3
print("PRUNE_OK")
""", "PRUNE_OK")


def test_refinement_dimensions_swept():
    """Phase 2 sweeps offload/TP/qgZ around the phase-1 winner and can
    return a refined config."""
    run_isolated(_SETUP + """
tuner = Autotuner(
    model_builder=builder,
    base_config={"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                 "mesh": {"data": 8}},
    steps_per_trial=1,
)
best = tuner.tune(micro_batch_sizes=[2], zero_stages=[1],
                  seq_len=16, vocab=VOCAB,
                  offload_devices=("none", "cpu"), tp_degrees=(1, 2),
                  try_qgz=True)
tried = [r.overrides for r in tuner.results]
assert any("offload" in ov for ov in tried)
assert any(ov.get("tp") == 2 for ov in tried)
assert any(ov.get("quantized_gradients") for ov in tried)
assert best["zero_stage"] == 1 and best["micro_batch"] == 2
print("REFINE_OK")
""", "REFINE_OK")


def test_joint_sweep_finds_interaction():
    """Phase 3 (round-4 weak #8): dimensions that each improve are ALSO
    tried together, and an interaction win (combo > either alone) is
    found. Trials are synthetic (monkeypatched) so the interaction is
    deterministic."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner, TrialResult

    speeds = {
        (): 1.0,                      # phase-1 winner baseline
        ("offload",): 2.0,            # each dim improves alone...
        ("tp",): 3.0,
        ("offload", "tp"): 10.0,      # ...and MORE together
    }

    def fake_trial(self, overrides, seq_len, vocab):
        key = tuple(sorted(
            k for k in ("offload", "tp") if overrides.get(k) not in
            (None, "none", 1)))
        sps = speeds.get(key, 0.5)
        return TrialResult(overrides=dict(overrides),
                           samples_per_sec=sps, step_ms=1000.0 / sps)

    tuner = Autotuner(model_builder=None, base_config={}, steps_per_trial=1)
    tuner._run_trial = fake_trial.__get__(tuner)
    best = tuner.tune(micro_batch_sizes=[2], zero_stages=[1],
                      seq_len=16, vocab=VOCAB,
                      offload_devices=("none", "cpu"), tp_degrees=(1, 2),
                      memory_bytes=0)
    assert best.get("offload") == "cpu" and best.get("tp") == 2, best
    combos = [r.overrides for r in tuner.results
              if r.overrides.get("offload") == "cpu"
              and r.overrides.get("tp") == 2]
    assert combos, "joint combo never tried"
