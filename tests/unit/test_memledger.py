"""HBM memory ledger (telemetry/memledger.py + docs/OBSERVABILITY.md):
per-owner byte attribution (handles + weakref'd providers), the
``jax.live_arrays()`` census and its drift alarm, OOM forensics via
injected RESOURCE_EXHAUSTED faults, headroom-driven admission parity, the
byte-scale histogram preset, Perfetto counter tracks, the per-device HBM
sampler, and the off-is-free guarantee (tracemalloc-pinned)."""

import json
import os
import tracemalloc

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.ragged import (
    BlockedAllocator,
    RaggedConfig,
    RaggedInferenceEngine,
)
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving.faults import (
    POINT_ALLOC,
    POINT_DISPATCH,
    get_fault_injector,
)
from deepspeed_tpu.telemetry import (
    BYTE_BUCKETS,
    MEMORY_OWNERS,
    TELEMETRY,
    MemoryLedger,
    is_resource_exhausted,
    tree_nbytes,
)

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)
# plain host-staged path: cheapest to compile; the fused/device-state OOM
# ladder is exercised by the CI memory-ledger smoke
PCFG = dict(
    max_tokens_per_step=16, max_seqs=3, block_size=4, num_blocks=49,
    max_blocks_per_seq=16, decode_run_ahead=0, prefill_tile=0,
    fused_chunk=0, device_state=False, dispatch_retries=2,
    retry_backoff_s=0.01, degrade_after=2)


def _engine(**over):
    rcfg = RaggedConfig(**{**PCFG, **over})
    return RaggedInferenceEngine(
        lambda ctx: llama.build(CFG, ctx=ctx), rcfg,
        dtype=jnp.float32, seed=0)


def _prompt(n, seed=0):
    return [int(t) for t in
            np.random.default_rng(seed).integers(0, CFG.vocab_size, n)]


PROMPTS = [_prompt(6, seed=1), _prompt(11, seed=2), _prompt(17, seed=3)]


def _put_all(eng, max_new=5):
    for i, p in enumerate(PROMPTS):
        eng.put(i, p, max_new_tokens=max_new, temperature=0.8, seed=100 + i)


def _ledger(tmp_path, **over):
    telemetry.configure(enabled=True, memledger={
        "enabled": True, "report_dir": str(tmp_path / "oom"), **over})
    return TELEMETRY.memledger


@pytest.fixture(scope="module")
def ref_tokens():
    """Ledger-off reference tokens: every ledger-on run must match."""
    eng = _engine()
    _put_all(eng)
    return eng.generate_all()


# -------------------------------------------------------------- accounting
class TestLedgerAccounting:
    def test_register_update_release(self, tmp_path):
        led = _ledger(tmp_path)
        h = led.register("kv_pool", "test/pool", 1000)
        assert led.owner_bytes()["kv_pool"] == 1000
        led.update(h, {"a": np.zeros(16, np.float32)})  # 64 bytes
        assert led.owner_bytes()["kv_pool"] == 64
        led.release(h)
        assert led.owner_bytes()["kv_pool"] == 0
        led.release(h)  # double release is harmless
        assert led.attributed_bytes() == 0

    def test_owner_taxonomy_enforced(self, tmp_path):
        led = _ledger(tmp_path)
        with pytest.raises(ValueError):
            led.register("nonsense_owner", "x", 1)
        with pytest.raises(ValueError):
            led.register_provider("nonsense_owner", "x", lambda: 0)
        assert set(led.owner_bytes()) == set(MEMORY_OWNERS)

    def test_provider_none_prunes(self, tmp_path):
        """The weakref idiom: a provider returning None (dead engine) is
        dropped and never read again."""
        led = _ledger(tmp_path)
        calls = []

        def fn():
            calls.append(1)
            return None if len(calls) > 1 else 512

        led.register_provider("staging_buffers", "test/dying", fn)
        assert led.owner_bytes()["staging_buffers"] == 512
        assert led.owner_bytes()["staging_buffers"] == 0  # fn -> None: pruned
        led.owner_bytes()
        assert len(calls) == 2  # pruned providers are not called again

    def test_offdevice_provider_excluded_from_census(self, tmp_path):
        """Host/disk KV-tier bytes are real and shown in the breakdown but
        invisible to jax.live_arrays() — the census must reconcile against
        device-resident attribution only, or tiering-on would read as
        over-attribution and trip the drift alarm."""
        led = _ledger(tmp_path)
        led.register("kv_pool", "test/pool", 1000)
        led.register_provider("host_kv_tier", "test/host_arena",
                              lambda: 700, offdevice=True)
        led.register_provider("disk_kv_tier", "test/disk_spill",
                              lambda: 300, offdevice=True)
        owners = led.owner_bytes()
        assert owners["host_kv_tier"] == 700
        assert owners["disk_kv_tier"] == 300
        assert led.owner_bytes(device_only=True)["host_kv_tier"] == 0
        rows = {r["name"]: r for r in led.breakdown()["providers"]}
        assert rows["test/host_arena"]["offdevice"] is True
        c = led.census(update_state=False)
        # attributed (device) = 1000; the 1000 off-device bytes ride in
        # their own column instead of skewing unattributed_fraction
        assert c["attributed_bytes"] == 1000
        assert c["offdevice_bytes"] == 1000

    def test_carveout_provider_moves_bytes_not_adds(self, tmp_path):
        """prefix-LRU / handoff bytes live INSIDE the kv_pool arrays: a
        carve-out re-attributes them without double-counting, so the
        attributed total still equals the real pool bytes."""
        led = _ledger(tmp_path)
        led.register("kv_pool", "test/pool", 1000)
        led.register_provider("prefix_cache_retained", "test/lru",
                              lambda: 300, carveout_of="kv_pool")
        led.register_provider("kv_handoff", "test/parked",
                              lambda: 100, carveout_of="kv_pool")
        owners = led.owner_bytes()
        assert owners["kv_pool"] == 600
        assert owners["prefix_cache_retained"] == 300
        assert owners["kv_handoff"] == 100
        assert led.attributed_bytes() == 1000  # each byte counted once
        providers = led.breakdown()["providers"]
        assert {"owner": "prefix_cache_retained", "name": "test/lru",
                "carveout_of": "kv_pool"} in providers
        with pytest.raises(ValueError):
            led.register_provider("kv_handoff", "x", lambda: 0,
                                  carveout_of="nonsense_owner")

    def test_carveout_never_drives_parent_negative(self, tmp_path):
        led = _ledger(tmp_path)
        led.register("kv_pool", "test/pool", 100)
        led.register_provider("prefix_cache_retained", "test/over",
                              lambda: 500, carveout_of="kv_pool")
        owners = led.owner_bytes()
        assert owners["kv_pool"] == 0
        assert owners["prefix_cache_retained"] == 100  # clamped to parent
        assert led.attributed_bytes() == 100

    def test_engine_pool_bytes_not_double_counted(self, tmp_path):
        """End-to-end: retained prefix blocks re-attribute pool bytes, so
        kv_pool + carve-outs must equal the real cache bytes exactly (the
        pre-carve-out ledger summed to cache + retained, overstating)."""
        led = _ledger(tmp_path)
        eng = _engine(enable_prefix_cache=True)
        _put_all(eng)
        eng.generate_all()
        assert eng.allocator.retained_blocks > 0  # retirement published
        owners = led.owner_bytes()
        assert owners["prefix_cache_retained"] \
            == eng.allocator.retained_blocks * eng._block_bytes()
        pool_total = (owners["kv_pool"] + owners["prefix_cache_retained"]
                      + owners["kv_handoff"])
        assert pool_total == tree_nbytes(eng.cache)

    def test_tree_nbytes(self):
        assert tree_nbytes(None) == 0
        assert tree_nbytes(12345) == 12345
        tree = {"w": np.zeros((4, 4), np.float32),
                "b": [jnp.zeros(8, jnp.int32)]}
        assert tree_nbytes(tree) == 64 + 32

    def test_byte_buckets_pow2(self):
        assert all(b == 2.0 ** p
                   for b, p in zip(BYTE_BUCKETS, range(10, 37, 2)))
        h = TELEMETRY.registry.histogram(
            "test_alloc_bytes", "x", buckets=BYTE_BUCKETS)
        h.observe(5000.0)
        assert h is not None


# ------------------------------------------------------------------ census
class TestCensus:
    def test_engine_reconciles_within_5pct(self, tmp_path):
        led = _ledger(tmp_path)
        # delta-based: a full-suite process carries live arrays leaked by
        # earlier tests (jit-cache constants etc.), so reconcile the bytes
        # THIS engine adds, not the process-wide absolute. The absolute
        # fresh-process <=5% pin lives in the CI memory-ledger smoke.
        base = led.census()["unattributed_bytes"]
        eng = _engine()
        _put_all(eng)
        eng.generate_all()
        c = led.census(step=1)
        assert c["live_bytes"] > 0
        grown = c["unattributed_bytes"] - base
        assert grown <= 0.05 * c["attributed_bytes"], (grown, c)
        owners = led.owner_bytes()
        assert owners["params"] > 0 and owners["kv_pool"] > 0
        assert owners["device_sched_state"] > 0
        # gauges materialized for every owner
        prom = TELEMETRY.registry.render_prometheus()
        for o in MEMORY_OWNERS:
            assert f'memory_bytes{{owner="{o}"}}' in prom

    def test_drift_alarm_needs_consecutive_censuses(self, tmp_path):
        led = _ledger(tmp_path, drift_threshold=0.0, drift_consecutive=3)
        leak = jnp.zeros(1024)  # held live + unattributed for the test
        leak.block_until_ready()
        assert not led.census()["drift_alarm"]
        assert not led.census()["drift_alarm"]
        c = led.census()  # third consecutive over-threshold census
        assert c["drift_alarm"] and c["drift_alarms_total"] == 1
        assert not led.census()["drift_alarm"]  # streak reset after firing

    def test_readonly_census_leaves_drift_state_alone(self, tmp_path):
        """GET /debug/memory and OOM forensics run read-only censuses: a
        scrape at any cadence must not advance (or reset) the step-loop's
        N-consecutive-census alarm streak."""
        led = _ledger(tmp_path, drift_threshold=0.0, drift_consecutive=3)
        leak = jnp.zeros(1024)
        leak.block_until_ready()
        assert not led.census()["drift_alarm"]
        assert not led.census()["drift_alarm"]  # streak = 2
        for _ in range(5):
            ro = led.census(update_state=False)
            assert not ro["drift_alarm"]
        led.debug_payload()  # endpoint scrape: also read-only
        # third state-updating census still completes the streak exactly
        c = led.census()
        assert c["drift_alarm"] and c["drift_alarms_total"] == 1

    def test_census_interval(self, tmp_path):
        led = _ledger(tmp_path, census_interval_steps=3)
        assert led.maybe_census(1) is None
        assert led.maybe_census(2) is None
        assert led.maybe_census(3) is not None

    def test_lazy_registration_after_configure(self, tmp_path):
        """Ledger configured AFTER engine construction (the common serving
        bring-up order): the per-step hook registers the owners on the
        first telemetry-enabled step instead of never."""
        telemetry.configure(enabled=False)
        eng = _engine()
        assert eng._memledger_handles is None  # nothing to register yet
        led = _ledger(tmp_path)
        _put_all(eng)
        eng.generate_all()
        assert eng._memledger_handles is not None
        owners = led.owner_bytes()
        assert owners["kv_pool"] > 0 and owners["params"] > 0

    def test_reset_state_refreshes_handles(self, tmp_path):
        led = _ledger(tmp_path)
        base = led.census()["unattributed_bytes"]
        eng = _engine()
        _put_all(eng)
        before = led.owner_bytes()["kv_pool"]
        eng.reset_state()
        assert led.owner_bytes()["kv_pool"] == before  # same-shape rebuild
        c = led.census()
        # the rebuilt pool must be re-attributed: only delta-growth allowed
        # (suite processes carry unattributed leftovers from earlier tests)
        grown = c["unattributed_bytes"] - base
        assert grown <= 0.05 * c["attributed_bytes"] + before, (grown, c)

    def test_perfetto_counter_track(self, tmp_path):
        telemetry.configure(enabled=True, tracing=True, memledger={
            "enabled": True, "report_dir": str(tmp_path / "oom")})
        led = TELEMETRY.memledger
        led.register("params", "t", 4096)
        led.refresh_gauges()
        trace = TELEMETRY.dump_trace()
        counters = [e for e in trace["traceEvents"] if e.get("ph") == "C"]
        assert counters and counters[-1]["args"]["params"] == 4096


# ----------------------------------------------------------- OOM forensics
class TestOomForensics:
    def test_alloc_seam_oom_report_and_recovery(self, tmp_path, ref_tokens):
        led = _ledger(tmp_path)
        inj = get_fault_injector()
        inj.arm(POINT_ALLOC, kind="oom", times=1)
        eng = _engine()
        _put_all(eng)
        toks = eng.generate_all()
        assert toks == ref_tokens  # watchdog retried; tokens identical
        assert eng.last_oom_report and os.path.exists(eng.last_oom_report)
        rep = json.load(open(eng.last_oom_report))
        assert rep["seam"] == "alloc"  # alloc seam won the _oom_recorded race
        assert rep["owners"]["kv_pool"] > 0 and rep["owners"]["params"] > 0
        assert "census" in rep and "device" in rep
        assert rep["context"]["free_blocks"] >= 0
        assert led.oom_reports == [eng.last_oom_report]
        prom = TELEMETRY.registry.render_prometheus()
        assert 'oom_events_total{seam="alloc"} 1' in prom

    def test_dispatch_seam_records_once(self, tmp_path, ref_tokens):
        led = _ledger(tmp_path)
        inj = get_fault_injector()
        inj.arm(POINT_DISPATCH, kind="oom", times=1)
        eng = _engine()
        _put_all(eng)
        assert eng.generate_all() == ref_tokens
        assert len(led.oom_reports) == 1
        assert json.load(open(led.oom_reports[0]))["seam"] == "dispatch"

    def test_is_resource_exhausted(self):
        assert is_resource_exhausted(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert is_resource_exhausted(ValueError("Out of memory allocating"))
        assert not is_resource_exhausted(RuntimeError("UNAVAILABLE: retry"))

    def test_record_oom_without_ledger_never_raises(self):
        # telemetry off entirely: the seam hook must be inert
        from deepspeed_tpu.telemetry.memledger import record_oom

        assert record_oom("dispatch", RuntimeError("RESOURCE_EXHAUSTED")) \
            is None


# ------------------------------------------------------ headroom admission
class TestHeadroomAdmission:
    def test_unknown_backend_is_static_parity(self, ref_tokens):
        # CPU accelerator: bytes_limit=0 -> headroom -1 even when enabled
        eng = _engine(headroom_admission=True)
        assert eng.admission_headroom_blocks() == -1
        _put_all(eng)
        assert eng.generate_all() == ref_tokens

    def test_ample_headroom_is_parity(self, ref_tokens):
        eng = _engine(headroom_admission=True)
        bb = eng._block_bytes()
        eng._mem_stats_fn = lambda: {
            "bytes_limit": 10_000 * bb, "bytes_in_use": 0}
        assert eng.admission_headroom_blocks() > eng.cfg.num_blocks
        _put_all(eng)
        assert eng.generate_all() == ref_tokens

    def test_headroom_nets_out_preallocated_pool(self):
        """The pool's free blocks are device bytes already funded at init:
        a device that merely LOOKS full because the pool preallocated it
        must not pin admission (the silent-hang regression)."""
        eng = _engine(headroom_admission=True)
        bb = eng._block_bytes()
        free_pool = eng.allocator.free_blocks  # 48: num_blocks-1 usable
        # device "full" but the deficit is exactly the pool's own footprint:
        # headroom = free_dev(10) + pool(48) - guard(5% of 1000 = 50) = 8
        eng._mem_stats_fn = lambda: {
            "bytes_limit": 1000 * bb, "bytes_in_use": 990 * bb}
        assert eng.admission_headroom_blocks() == 10 + free_pool - 50

    def test_scarce_headroom_pins_admission(self):
        eng = _engine(headroom_admission=True)
        bb = eng._block_bytes()
        # external pressure beyond what the pool could fund: free_dev=0,
        # pool credit 48 blocks, guard 5% of 2000 = 100 blocks -> 0
        eng._mem_stats_fn = lambda: {
            "bytes_limit": 2000 * bb, "bytes_in_use": 2000 * bb}
        assert eng.admission_headroom_blocks() == 0
        _put_all(eng)
        eng.step()
        assert not eng._running and len(eng._queued) == 3  # nobody admitted
        # pressure lifts: the same queue drains normally
        eng._mem_stats_fn = lambda: {
            "bytes_limit": 10_000 * bb, "bytes_in_use": 0}
        eng.step()
        assert eng._running

    def test_headroom_stall_alarm_raises(self):
        """A headroom wait that never lifts must become a loud failure,
        not a silent forever-idle loop (the guard suppression bug)."""
        eng = _engine(headroom_admission=True, headroom_stall_alarm_ticks=3)
        bb = eng._block_bytes()
        eng._mem_stats_fn = lambda: {
            "bytes_limit": 5000 * bb, "bytes_in_use": 5000 * bb}
        _put_all(eng)
        eng.step()
        eng.step()
        with pytest.raises(RuntimeError, match="headroom admission stalled"):
            eng.step()

    def test_default_is_off_and_disabled_knob_is_unknown(self):
        eng = _engine()
        assert eng.cfg.headroom_admission is False  # opt-in by default
        eng._mem_stats_fn = lambda: {"bytes_limit": 1 << 40, "bytes_in_use": 0}
        assert eng.admission_headroom_blocks() == -1

    def test_replica_stats_surface_headroom(self):
        from deepspeed_tpu.serving.engine_loop import EngineLoop

        eng = _engine(headroom_admission=True)
        bb = eng._block_bytes()
        free_pool = eng.allocator.free_blocks
        eng._mem_stats_fn = lambda: {
            "bytes_limit": 1000 * bb, "bytes_in_use": 0}
        loop = EngineLoop(eng, name="r0")
        try:
            s = loop.stats()
            assert s.headroom_blocks == 1000 + free_pool - 50
        finally:
            loop.close()

    def test_shrink_retained_to_budget(self):
        alloc = BlockedAllocator(10)
        blocks = alloc.allocate(6)
        for i, b in enumerate(blocks):
            alloc.publish(b, ("k", i))
        alloc.free(blocks)  # refcount 0 published -> retained in the LRU
        assert alloc.retained_blocks == 6
        assert alloc.shrink_retained(2) == 4  # evict LRU down to budget
        assert alloc.retained_blocks == 2
        assert alloc.shrink_retained(5) == 0  # ample budget: no-op


# ----------------------------------------------------------- HBM sampler
class _FakeAccel:
    def memory_stats_all_devices(self):
        return [
            {"bytes_in_use": 100, "bytes_limit": 1000, "bytes_reserved": 160,
             "largest_free_block_bytes": 700, "peak_bytes_in_use": 150},
            {"bytes_in_use": 900, "bytes_limit": 1000, "bytes_reserved": 960},
        ]


class TestHbmSampler:
    def test_per_device_and_fragmentation_gauges(self):
        from deepspeed_tpu.telemetry.memory import HbmWatermarkSampler

        telemetry.configure(enabled=True)
        s = HbmWatermarkSampler(TELEMETRY)
        s._accelerator = _FakeAccel()
        out = s.sample(step=1)
        assert out["bytes_in_use"] == 100  # device-0 legacy aggregate
        prom = TELEMETRY.registry.render_prometheus()
        assert 'hbm_device_bytes_in_use{device="1"} 900' in prom
        assert 'hbm_fragmentation_bytes{device="0"} 60' in prom
        assert 'hbm_fragmentation_bytes{device="1"} 60' in prom
        assert 'hbm_largest_free_block_bytes{device="0"} 700' in prom

    def test_no_stats_backend_goes_silent(self):
        from deepspeed_tpu.telemetry.memory import HbmWatermarkSampler

        telemetry.configure(enabled=True)

        class Broken:
            def memory_stats_all_devices(self):
                raise RuntimeError("no stats")

        s = HbmWatermarkSampler(TELEMETRY)
        s._accelerator = Broken()
        assert s.sample() == {}
        assert s._broken and s.sample() == {}


# -------------------------------------------------------------- off is free
class TestOffIsFree:
    def test_disabled_ledger_zero_allocations(self, ref_tokens):
        """Telemetry (and therefore the ledger) off: serving a full batch
        must execute zero memledger.py code — pinned by tracemalloc."""
        eng = _engine()
        _put_all(eng)
        tracemalloc.start()
        try:
            toks = eng.generate_all()
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        assert toks == ref_tokens
        stats = snap.filter_traces([tracemalloc.Filter(
            True, "*/telemetry/memledger.py")]).statistics("filename")
        total = sum(s.size for s in stats)
        assert total == 0, f"memledger allocated {total}B while disabled"

    def test_ledger_on_tokens_identical(self, tmp_path, ref_tokens):
        _ledger(tmp_path, census_interval_steps=2)
        eng = _engine()
        _put_all(eng)
        assert eng.generate_all() == ref_tokens

    def test_debug_payload_serializable(self, tmp_path):
        led = _ledger(tmp_path)
        eng = _engine()
        _put_all(eng)
        eng.generate_all()
        payload = led.debug_payload()
        assert payload["enabled"] is True
        json.dumps(payload)
        assert payload["census"]["live_bytes"] > 0
        assert payload["owners"]["kv_pool"] > 0
