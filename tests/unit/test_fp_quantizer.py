"""FP8/FP6/FP4 float-grid quantization (reference ``csrc/fp_quantizer`` +
``tests/unit/ops/fp_quantizer``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.fp_quantizer import (
    FPQuantizedTensor,
    fp_dequantize,
    fp_quantize,
    fp_quantize_dequantize,
)


@pytest.fixture
def x():
    return jnp.asarray(
        np.random.default_rng(0).normal(size=(64, 48)).astype(np.float32))


@pytest.mark.parametrize("fmt,max_rel", [
    ("fp8_e4m3", 0.07), ("fp8_e5m2", 0.30), ("fp6_e3m2", 0.30), ("fp4_e2m1", 0.60),
])
def test_roundtrip_error_bounded(x, fmt, max_rel):
    """Relative error on NORMAL-range values stays within the format's
    mantissa step (values under the block's subnormal threshold flush toward
    zero by design — same as the reference grids)."""
    xa = np.asarray(x)
    y = np.asarray(fp_quantize_dequantize(x, fmt=fmt, block=64))
    # consider elements comfortably inside each block's normal range
    absmax = np.abs(xa.reshape(-1, 64)).max(axis=-1, keepdims=True)
    mask = (np.abs(xa.reshape(-1, 64)) > absmax / 8).reshape(xa.shape)
    rel = np.abs(y - xa)[mask] / np.abs(xa)[mask]
    assert rel.max() < max_rel, (fmt, rel.max())


def test_precision_ordering(x):
    """More bits -> lower error (sanity that the grids differ as designed)."""
    errs = {}
    for fmt in ("fp8_e4m3", "fp6_e3m2", "fp4_e2m1"):
        y = np.asarray(fp_quantize_dequantize(x, fmt=fmt, block=64))
        errs[fmt] = float(np.abs(y - np.asarray(x)).mean())
    assert errs["fp8_e4m3"] < errs["fp6_e3m2"] < errs["fp4_e2m1"], errs


def test_fp8_values_are_native_dtype(x):
    qt = fp_quantize(x, fmt="fp8_e4m3", block=64)
    assert qt.values.dtype == jnp.float8_e4m3fn
    assert qt.scales.dtype == jnp.float32


def test_block_scales_isolate_outliers():
    """A huge value in one block must not destroy precision elsewhere."""
    v = np.ones((512,), np.float32) * 0.5
    v[0] = 1000.0
    y = np.asarray(fp_quantize_dequantize(jnp.asarray(v), fmt="fp8_e4m3", block=64))
    # blocks beyond the first are exact-ish
    np.testing.assert_allclose(y[64:], v[64:], rtol=0.05)


def test_jittable(x):
    # jit fusion may round grid-boundary ties differently than eager; bound
    # the disagreement by one grid quantum instead of demanding bit equality
    f = jax.jit(lambda t: fp_quantize_dequantize(t, fmt="fp6_e3m2", block=64))
    a = np.asarray(f(x))
    b = np.asarray(fp_quantize_dequantize(x, fmt="fp6_e3m2", block=64))
    assert np.abs(a - b).max() <= np.abs(np.asarray(x)).max() * 0.25
    np.testing.assert_array_equal(a, np.asarray(f(x)))  # deterministic


def test_unknown_format_rejected(x):
    with pytest.raises(ValueError, match="unknown format"):
        fp_quantize(x, fmt="fp3_e1m1")
