"""Sequence parallelism: Ulysses + ring attention exactness vs dense reference,
and end-to-end SP training parity (reference: ``tests/unit/sequence_parallelism/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models import llama
from deepspeed_tpu.ops.attention import xla_attention
from deepspeed_tpu.parallel.ring_attention import ring_attention
from deepspeed_tpu.parallel.ulysses import ulysses_attention

VOCAB = 256


def _qkv(b=2, s=32, hq=8, hkv=4, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d))
    k = jax.random.normal(ks[1], (b, s, hkv, d))
    v = jax.random.normal(ks[2], (b, s, hkv, d))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_exact(causal):
    topo = init_distributed(MeshConfig(data=1, sequence=8))
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, topo.mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match(
):
    topo = init_distributed(MeshConfig(data=2, sequence=4))
    q, k, v = _qkv(s=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, topo.mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


def test_ulysses_attention_exact():
    topo = init_distributed(MeshConfig(data=2, sequence=4))
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, topo.mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ulysses_uneven_heads_fallback():
    """3 kv heads with sp=4: head dim not divisible -> falls back, still exact
    (reference layer.py:131 uneven-head support)."""
    topo = init_distributed(MeshConfig(data=2, sequence=4))
    q, k, v = _qkv(hq=6, hkv=3)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, topo.mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def _sp_config(mode, mesh):
    return {
        "train_micro_batch_size_per_device": 4,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "sequence_parallel": {"mode": mode},
        "mesh": mesh,
        "seed": 7,
    }


@pytest.mark.parametrize("mode", ["ulysses", "ring"])
def test_sp_training_loss_parity(mode):
    """SP=4 training must match DP-only loss trajectory."""
    batches = [
        {"input_ids": np.random.default_rng(i).integers(0, VOCAB, (8, 32), dtype=np.int32)}
        for i in range(3)
    ]

    def run(mesh, mode):
        reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
            config=_sp_config(mode, mesh),
            seed=11,
        )
        return [float(engine.train_batch(b)) for b in batches]

    base = run({"data": 8}, "ulysses")
    sp = run({"data": 2, "sequence": 4}, mode)
    np.testing.assert_allclose(base, sp, rtol=3e-4, atol=3e-5)


# ------------------------------------------------------------------ AutoSP
def _user_model_spec(vocab=VOCAB, d=32, heads=4, layers=2):
    """A model written WITHOUT ShardCtx, using the standard
    jax.nn.dot_product_attention — the AutoSP target
    (reference sequence/auto_sp.py: detect sdpa, insert SP collectives)."""
    from functools import partial

    from deepspeed_tpu.models.api import ModelSpec, causal_lm_loss

    hd = d // heads

    def init_fn(rng):
        ks = jax.random.split(rng, 4)
        return {
            "embed": jax.random.normal(ks[0], (vocab, d)) * 0.02,
            "layers": {
                "wqkv": jax.random.normal(ks[1], (layers, d, 3 * d)) * 0.02,
                "wo": jax.random.normal(ks[2], (layers, d, d)) * 0.02,
                "w_mlp": jax.random.normal(ks[3], (layers, d, d)) * 0.02,
            },
        }

    def forward(params, ids):
        x = params["embed"][ids]
        b, s, _ = x.shape

        def layer(x, lp):
            qkv = (x @ lp["wqkv"]).reshape(b, s, 3, heads, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            o = jax.nn.dot_product_attention(q, k, v, is_causal=True)
            x = x + o.reshape(b, s, -1) @ lp["wo"]
            return x + jax.nn.gelu(x @ lp["w_mlp"]), None

        x, _ = jax.lax.scan(layer, x, params["layers"])
        return x @ params["embed"].T

    def loss_fn(params, batch, rng=None):
        return causal_lm_loss(forward(params, batch["input_ids"]),
                              batch["input_ids"])

    axes = {
        "embed": ("vocab", "embed"),
        "layers": {"wqkv": ("layers", "embed", None),
                   "wo": ("layers", "embed", "embed"),
                   "w_mlp": ("layers", "embed", "embed")},
    }
    return ModelSpec(name="user-sdpa", config=None, init_fn=init_fn,
                     loss_fn=loss_fn, forward_fn=forward,
                     param_logical_axes=axes)


def test_auto_sp_user_model_parity():
    """A ShardCtx-free user model trains under sequence_parallel.auto with
    the same trajectory as pure DP — the patched sdpa routed its attention
    through Ulysses."""
    batches = [
        {"input_ids": np.random.default_rng(i).integers(0, VOCAB, (8, 32), dtype=np.int32)}
        for i in range(3)
    ]

    def run(mesh, auto):
        reset_topology()
        cfg = _sp_config("ulysses", mesh)
        cfg["sequence_parallel"]["auto"] = auto
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=lambda ctx: _user_model_spec(), config=cfg, seed=11)
        return [float(engine.train_batch(b)) for b in batches]

    base = run({"data": 8}, auto=False)
    sp = run({"data": 2, "sequence": 4}, auto=True)
    assert all(np.isfinite(sp))
    np.testing.assert_allclose(base, sp, rtol=3e-4, atol=3e-5)


def test_auto_sp_patch_is_scoped():
    """The sdpa patch must not leak outside the auto_sp context."""
    from deepspeed_tpu.parallel.auto_sp import auto_sp

    topo = init_distributed(MeshConfig(data=2, sequence=4))
    orig = jax.nn.dot_product_attention
    with auto_sp(topo.mesh):
        assert jax.nn.dot_product_attention is not orig
    assert jax.nn.dot_product_attention is orig
