"""XLA_FLAGS hygiene: probing optional flags and sanitizing inherited ones.

The failure under test is the MULTICHIP dryrun crash: a parent environment
(or stale probe adoption) leaves flags in ``XLA_FLAGS`` that the pure-CPU
child's flag registry does not know, and ``parse_flags_from_env.cc`` F-aborts
the child with ``Unknown flag in XLA_FLAGS: ...`` before any user code runs.
These tests fake the probe subprocess so no real interpreter is spawned.
"""

import subprocess
from types import SimpleNamespace

import pytest

from deepspeed_tpu.utils import xla_flags as xf


class FakeRun:
    """Stand-in for subprocess.run that judges each probe by the flags the
    child would have parsed, and records every probe's flag set."""

    def __init__(self, rejected=(), transient=()):
        self.rejected = set(rejected)
        self.transient = set(transient)
        self.calls = []

    def __call__(self, argv, env=None, capture_output=True, timeout=None):
        flags = set((env or {}).get("XLA_FLAGS", "").split())
        self.calls.append(flags)
        if flags & self.transient:
            raise subprocess.TimeoutExpired(argv, timeout or 0)
        bad = flags & self.rejected
        if bad:
            marker = f"Unknown flag in XLA_FLAGS: {sorted(bad)[0]}"
            return SimpleNamespace(returncode=1, stdout=b"",
                                   stderr=marker.encode())
        return SimpleNamespace(returncode=0, stdout=b"", stderr=b"")


@pytest.fixture
def fake(monkeypatch):
    def install(**kw):
        runner = FakeRun(**kw)
        monkeypatch.setattr(xf.subprocess, "run", runner)
        return runner
    return install


class TestProbeExtraFlags:
    def test_clean_probe_adopts_all(self, fake):
        fake()
        got = xf.probe_extra_xla_flags(["--a=1", "--b=2"], use_cache=False)
        assert got == ["--a=1", "--b=2"]

    def test_rejection_bisects_to_the_bad_flag(self, fake):
        fake(rejected={"--bad=1"})
        got = xf.probe_extra_xla_flags(["--ok=1", "--bad=1"], use_cache=False)
        assert got == ["--ok=1"]

    def test_transient_default_drops(self, fake):
        fake(transient={"--flaky=1"})
        got = xf.probe_extra_xla_flags(["--flaky=1"], use_cache=False)
        assert got == []

    def test_transient_keep_transient_adopts(self, fake):
        fake(transient={"--flaky=1"})
        got = xf.probe_extra_xla_flags(["--flaky=1"], use_cache=False,
                                       keep_transient=True)
        assert got == ["--flaky=1"]

    def test_keep_transient_still_drops_definitive_rejections(self, fake):
        fake(rejected={"--bad=1"}, transient={"--flaky=1"})
        got = xf.probe_extra_xla_flags(
            ["--ok=1", "--bad=1", "--flaky=1"],
            use_cache=False, keep_transient=True)
        assert got == ["--ok=1", "--flaky=1"]


class TestSanitizeXlaFlags:
    def test_empty_is_empty(self, fake):
        runner = fake()
        assert xf.sanitize_xla_flags("", use_cache=False) == ""
        assert runner.calls == []  # no probe subprocess for nothing

    def test_wrong_platform_prefixes_dropped_without_probe(self, fake):
        runner = fake()
        got = xf.sanitize_xla_flags(
            "--xla_tpu_scoped_vmem_limit_kib=1024 --xla_gpu_autotune_level=2",
            target_platform="cpu", use_cache=False)
        assert got == ""
        # statically dropped: the probe child is never spawned for them
        assert runner.calls == []

    def test_unknown_inherited_flag_is_removed(self, fake):
        """The MULTICHIP_r02 crash: an inherited flag the CPU child's
        registry rejects must be filtered out, valid neighbors kept."""
        fake(rejected={"--xla_cpu_collective_call_warn_stuck_seconds=120"})
        got = xf.sanitize_xla_flags(
            "--xla_force_host_platform_device_count=8 "
            "--xla_cpu_collective_call_warn_stuck_seconds=120",
            target_platform="cpu", use_cache=False)
        assert got == "--xla_force_host_platform_device_count=8"

    def test_transient_probe_keeps_inherited_flags(self, fake):
        """Sanitizing must not silently strip the user's flags on a flaky
        probe — only a definitive rejection removes an inherited flag."""
        fake(transient={"--xla_cpu_enable_fast_math=true"})
        got = xf.sanitize_xla_flags(
            "--xla_cpu_enable_fast_math=true", target_platform="cpu",
            use_cache=False)
        assert got == "--xla_cpu_enable_fast_math=true"

    def test_order_preserved_and_tpu_target_keeps_tpu_flags(self, fake):
        fake()
        flags = ("--xla_tpu_scoped_vmem_limit_kib=1024 "
                 "--xla_force_host_platform_device_count=4")
        got = xf.sanitize_xla_flags(flags, target_platform="tpu",
                                    use_cache=False)
        assert got == flags
