"""Domino-style TP overlap (reference ``runtime/domino/transformer.py:250``)
and the committed TP-overlap finding (docs/TP_OVERLAP.md).

Numerics run on the 8-device CPU mesh; the schedule-level assertions compile
AOT for a TPU v5e:2x4 topology (no TPU devices needed) so the async-vs-sync
collective lowering is measured on the real target, not the CPU emulator.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.parallel.domino import (
    domino_swiglu_mlp,
    ring_all_reduce,
)
from deepspeed_tpu.utils.compat import shard_map_compat


def _tp_mesh(tensor=4, data=2):
    reset_topology()
    mesh = init_distributed(MeshConfig(data=data, tensor=tensor)).mesh
    from deepspeed_tpu.utils.compat import supports_partial_manual

    if not supports_partial_manual(mesh, {"tensor"}):
        pytest.skip("partial-manual shard_map unsupported on this jax "
                    "(would abort XLA's SPMD partitioner)")
    return mesh


def test_ring_all_reduce_matches_psum():
    mesh = _tp_mesh()
    x = jnp.arange(4 * 16, dtype=jnp.float32).reshape(4, 1, 16)

    def body(x):
        return (ring_all_reduce(x[0], "tensor")[None],
                jax.lax.psum(x[0], "tensor")[None])

    # partial-manual shard_map needs a jit context (eager rejects specs that
    # leave the auto axes implicit)
    ring, ref = jax.jit(shard_map_compat(
        body, mesh=mesh, in_specs=P("tensor"),
        out_specs=(P(None), P(None)), axis_names={"tensor"}, check_vma=False,
    ))(x)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), rtol=1e-6)


def test_domino_mlp_matches_dense():
    """The split-batch ring-reduced MLP is numerically the plain TP MLP."""
    mesh = _tp_mesh(tensor=4, data=2)
    rng = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    d, f = 64, 128
    x = jax.random.normal(k1, (8, 16, d), jnp.float32)
    wg = jax.device_put(jax.random.normal(k2, (d, f), jnp.float32) * 0.1,
                        NamedSharding(mesh, P(None, "tensor")))
    wu = jax.device_put(jax.random.normal(k3, (d, f), jnp.float32) * 0.1,
                        NamedSharding(mesh, P(None, "tensor")))
    wd = jax.device_put(jax.random.normal(k4, (f, d), jnp.float32) * 0.1,
                        NamedSharding(mesh, P("tensor", None)))

    def dense(x, wg, wu, wd):
        return (jax.nn.silu(x @ wg) * (x @ wu)) @ wd

    ref = jax.jit(dense)(x, wg, wu, wd)
    got = jax.jit(lambda x, a, b, c: domino_swiglu_mlp(x, a, b, c, mesh))(
        x, wg, wu, wd)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_domino_grads_match_dense():
    mesh = _tp_mesh(tensor=4, data=2)
    rng = jax.random.PRNGKey(1)
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    d, f = 32, 64
    x = jax.random.normal(k1, (4, 8, d), jnp.float32)
    wg = jax.random.normal(k2, (d, f), jnp.float32) * 0.1
    wu = jax.random.normal(k3, (d, f), jnp.float32) * 0.1
    wd = jax.random.normal(k4, (f, d), jnp.float32) * 0.1

    def dense_loss(ws):
        wg, wu, wd = ws
        return jnp.sum((jax.nn.silu(x @ wg) * (x @ wu)) @ wd) ** 2

    def domino_loss(ws):
        wg, wu, wd = ws
        return jnp.sum(domino_swiglu_mlp(x, wg, wu, wd, mesh)) ** 2

    g_ref = jax.jit(jax.grad(dense_loss))((wg, wu, wd))
    g_dom = jax.jit(jax.grad(domino_loss))((wg, wu, wd))
    for a, b in zip(g_dom, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_odd_batch_rejected():
    mesh = _tp_mesh()
    x = jnp.zeros((3, 8, 32))
    w = jnp.zeros((32, 64))
    wd = jnp.zeros((64, 32))
    with pytest.raises(ValueError, match="divisible"):
        domino_swiglu_mlp(x, w, w, wd, mesh)


# ------------------------------------------------------- TPU-target schedule
def _v5e_topology():
    from jax.experimental import topologies

    try:
        return topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x4")
    except Exception as e:  # pragma: no cover - toolchain without AOT support
        pytest.skip(f"TPU AOT topology unavailable: {e}")


def test_finding_gspmd_tp_allreduce_is_sync_on_tpu():
    """The committed finding's first leg: GSPMD's TP reduction compiles to a
    synchronous all-reduce on the TPU target (nothing for the scheduler to
    overlap) — the reason a Domino-style restructure exists at all."""
    from jax.sharding import Mesh

    topo = _v5e_topology()
    mesh = Mesh(np.array(topo.devices), ("tensor",))
    xs = jax.ShapeDtypeStruct((8, 128, 256), jnp.bfloat16,
                              sharding=NamedSharding(mesh, P()))
    w1 = jax.ShapeDtypeStruct((256, 1024), jnp.bfloat16,
                              sharding=NamedSharding(mesh, P(None, "tensor")))
    w2 = jax.ShapeDtypeStruct((1024, 256), jnp.bfloat16,
                              sharding=NamedSharding(mesh, P("tensor", None)))

    def blocks(x, w1, w2):
        for _ in range(2):
            x = jax.lax.with_sharding_constraint(
                jax.nn.gelu(x @ w1) @ w2, NamedSharding(mesh, P()))
        return x

    hlo = jax.jit(blocks).lower(xs, w1, w2).compile().as_text()
    assert len(re.findall(r" all-reduce\(", hlo)) > 0
    assert "all-reduce-start" not in hlo


def test_finding_domino_ring_is_async_on_tpu():
    """Second leg: the ppermute ring lowers to async collective-permute
    start/done pairs on the TPU target — the overlappable form."""
    from jax.sharding import Mesh

    topo = _v5e_topology()
    mesh = Mesh(np.array(topo.devices), ("tensor",))
    xs = jax.ShapeDtypeStruct((8, 128, 256), jnp.bfloat16,
                              sharding=NamedSharding(mesh, P()))
    w1 = jax.ShapeDtypeStruct((256, 1024), jnp.bfloat16,
                              sharding=NamedSharding(mesh, P(None, "tensor")))
    w2 = jax.ShapeDtypeStruct((256, 1024), jnp.bfloat16,
                              sharding=NamedSharding(mesh, P(None, "tensor")))
    wd = jax.ShapeDtypeStruct((1024, 256), jnp.bfloat16,
                              sharding=NamedSharding(mesh, P("tensor", None)))

    def f(x, wg, wu, wd):
        return domino_swiglu_mlp(x, wg, wu, wd, mesh)

    hlo = jax.jit(f).lower(xs, w1, w2, wd).compile().as_text()
    n_starts = len(re.findall(r"collective-permute-start\(", hlo))
    assert n_starts > 0, "ring must lower to async collective-permute pairs"
    assert len(re.findall(r" all-reduce\(", hlo)) == 0, \
        "no synchronous all-reduce may remain on the domino path"


def test_bucketed_backward_ring_is_async_on_tpu():
    """Grad-sync leg of the finding (docs/TP_OVERLAP.md "grad-sync overlap"):
    the bucketed backward's per-bucket ring reduce-scatter plus the sharded
    update's ring all-gather lower to async collective-permute start/done
    pairs on the TPU v5e target — with NO synchronous all-reduce left on the
    data axis — and the latency-hiding scheduler places independent fusions
    inside the transfer windows (the measured overlap the stepscope gauge
    reports)."""
    from jax.sharding import Mesh

    from deepspeed_tpu.parallel import grad_overlap as go

    topo = _v5e_topology()
    mesh = Mesh(np.array(topo.devices), ("data",))
    dp = 8
    d, f = 128, 256
    params = {
        "w1": jax.ShapeDtypeStruct((d, f), jnp.float32,
                                   sharding=NamedSharding(mesh, P())),
        "w2": jax.ShapeDtypeStruct((f, d), jnp.float32,
                                   sharding=NamedSharding(mesh, P())),
    }
    xs = jax.ShapeDtypeStruct((16, d), jnp.float32,
                              sharding=NamedSharding(mesh, P("data")))
    abstract = {k: np.zeros(v.shape, np.float32) for k, v in params.items()}
    plan = go.plan_buckets(abstract, dp=dp, target_bytes=1 << 17)
    leaves, tdef = go.ordered_leaves(abstract, plan)

    def local(p, xb):
        def loss(p):
            h = jnp.tanh(xb @ p["w1"])
            return jnp.mean((h @ p["w2"] - xb) ** 2)

        g = jax.grad(loss)(p)
        g_leaves, _ = go.ordered_leaves(g, plan)
        # bucketed ring reduce-scatter -> sharded sgd update -> ring gather
        new_flats = []
        for b in plan.buckets:
            rs = go.ring_reduce_scatter_sum(go.pack_bucket(g_leaves, b),
                                            "data") / dp
            p_sh = go.local_shard(
                go.pack_bucket(go.ordered_leaves(p, plan)[0], b), "data", dp)
            new_flats.append(go.ring_all_gather(p_sh - 1e-3 * rs, "data"))
        return go.unflatten_buckets(new_flats, plan, tdef)

    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(jax.tree_util.tree_map(lambda _: P(),
                                                           params), P("data")),
                          out_specs=jax.tree_util.tree_map(lambda _: P(),
                                                           params),
                          axis_names={"data"}, check_vma=False)
    hlo = jax.jit(fn).lower(params, xs).compile().as_text()

    n_starts = len(re.findall(r"collective-permute-start\(", hlo))
    n_dones = len(re.findall(r"collective-permute-done\(", hlo))
    assert n_starts > 0 and n_starts == n_dones, (n_starts, n_dones)
    assert len(re.findall(r" all-reduce\(", hlo)) == 0, \
        "no synchronous all-reduce may remain on the bucketed grad path"

    # latency hiding: at least one transfer window (start..done) must have an
    # independent fusion scheduled inside it
    lines = hlo.splitlines()
    overlapped = 0
    open_windows = 0
    for ln in lines:
        if "collective-permute-start(" in ln:
            open_windows += 1
        elif "collective-permute-done(" in ln:
            open_windows = max(0, open_windows - 1)
        elif open_windows and ("fusion(" in ln or " fusion." in ln):
            overlapped += 1
    assert overlapped > 0, \
        "scheduler placed no independent fusion inside any permute window"
