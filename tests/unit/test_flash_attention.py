"""Pallas flash attention vs the XLA reference (interpret mode on CPU;
the same kernel compiles for real on TPU). Reference test style:
``tests/unit/ops`` kernel-vs-eager numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import xla_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(b=2, sq=128, skv=128, hq=4, hkv=4, d=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, sq, hq, d), dtype)
    k = jax.random.normal(ks[1], (b, skv, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, skv, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_matches_xla(causal):
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal, None, 64, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_gqa_head_mapping():
    q, k, v = _qkv(hq=8, hkv=2)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, 64, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_multiple_kv_blocks_online_softmax():
    q, k, v = _qkv(sq=64, skv=256)
    ref = xla_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, False, None, 64, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_grads_match_xla():
    q, k, v = _qkv(sq=64, skv=64, hq=4, hkv=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 32, 32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


def test_unsupported_shape_raises():
    q, k, v = _qkv(hq=3, hkv=2)  # 3 % 2 != 0
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, True, None, 64, 64)


def test_bf16_inputs():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, 64, 64)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)
