"""Inference: cached decode equivalence vs full forward, greedy generation,
TP-sharded generation (reference: ``tests/unit/inference/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models import llama

VOCAB = 256


@pytest.fixture
def tiny_model():
    cfg = llama.LlamaConfig.tiny(VOCAB)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_cached_decode_matches_full_forward(tiny_model):
    """Prefill+decode through the KV cache must reproduce the dense forward."""
    cfg, params = tiny_model
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, VOCAB)
    full = llama.forward(cfg, params, ids).astype(jnp.float32)

    cache = llama.init_cache(cfg, 2, 16, jnp.float32)
    # prefill first 8, then decode one token at a time
    logits, cache = llama.decode_forward(cfg, params, ids[:, :8], cache, 0)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :8]),
                               rtol=2e-3, atol=2e-3)
    for t in range(8, 12):
        step_logits, cache = llama.decode_forward(cfg, params, ids[:, t:t + 1], cache, t)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]), np.asarray(full[:, t]),
                                   rtol=2e-3, atol=2e-3)


def test_greedy_generation_consistent(tiny_model):
    """Engine greedy decode must equal naive argmax-iterate on the dense model."""
    cfg, params = tiny_model
    from deepspeed_tpu.inference.engine import InferenceEngine

    init_distributed(MeshConfig(data=8))
    eng = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx), params=params,
                          dtype=jnp.float32)
    prompt = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, VOCAB))
    out = eng.generate(prompt, max_new_tokens=5)
    assert out.shape == (2, 11)

    # naive reference loop on fp32 dense forward
    ids = prompt.copy()
    for _ in range(5):
        logits = llama.forward(cfg, params, jnp.asarray(ids))
        nxt = np.argmax(np.asarray(logits[:, -1], np.float32), axis=-1)
        ids = np.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
    np.testing.assert_array_equal(out, ids)


def test_sampled_generation_runs(tiny_model):
    cfg, params = tiny_model
    from deepspeed_tpu.inference.engine import InferenceEngine

    init_distributed(MeshConfig(data=8))
    eng = InferenceEngine(lambda ctx: llama.build(cfg, ctx=ctx), params=params,
                          dtype=jnp.float32)
    prompt = np.zeros((1, 4), np.int32)
    a = eng.generate(prompt, max_new_tokens=8, temperature=1.0, seed=0)
    b = eng.generate(prompt, max_new_tokens=8, temperature=1.0, seed=1)
    assert a.shape == (1, 12)
    assert not np.array_equal(a, b)  # different seeds -> different samples


def test_init_inference_tp(tiny_model):
    cfg, params = tiny_model
    out = None
    eng = deepspeed_tpu.init_inference(
        lambda ctx: llama.build(cfg, ctx=ctx),
        {"tensor_parallel": {"tp_size": 4}, "dtype": "fp32", "params": params},
    )
    assert "tensor" in str(eng.params["layers"]["wq"].sharding.spec)
    out = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=3)
    assert out.shape == (1, 7)
