"""Fused mixed-chunk serving pipeline (reference FastGen SplitFuse +
multi-step scheduling, ``blogs/deepspeed-fastgen/README.md:28``): every
dispatch carries prompt chunks AND K decode steps, chunk t+1 dispatches
before chunk t's readback (device-fed next tokens, bounded speculation)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.ragged import RaggedConfig, RaggedInferenceEngine
from deepspeed_tpu.models import llama

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)


def _engine(fused_chunk=0, depth=2, tile=0, **over):
    kw = dict(max_tokens_per_step=16, max_seqs=3, block_size=4,
              num_blocks=49, max_blocks_per_seq=16,
              fused_chunk=fused_chunk, pipeline_depth=depth,
              prefill_tile=tile)
    kw.update(over)
    return RaggedInferenceEngine(
        model=lambda ctx: llama.build(CFG, ctx=ctx),
        ragged_config=RaggedConfig(**kw), dtype=jnp.float32, seed=0)


def _prompts(n=5, seed=3):
    rng = np.random.default_rng(seed)
    return {i: list(rng.integers(0, 97, (int(rng.integers(3, 12)),)))
            for i in range(n)}


class TestFusedPipeline:
    def test_greedy_parity_with_legacy(self):
        """The fused pipeline must emit EXACTLY the legacy engine's greedy
        streams (same weights, same prompts, mixed lengths)."""
        prompts = _prompts()
        legacy = _engine(fused_chunk=0)
        for uid, p in prompts.items():
            legacy.put(uid, p, max_new_tokens=9)
        want = legacy.generate_all()

        fused = _engine(fused_chunk=4, depth=2)
        for uid, p in prompts.items():
            fused.put(uid, p, max_new_tokens=9)
        got = fused.generate_all()
        assert got == want
        # the whole point: far fewer dispatches than tokens emitted
        assert fused.dispatch_count < legacy.dispatch_count
        assert fused.dispatch_count / max(fused.tokens_emitted, 1) <= 0.5

    def test_parity_with_staggered_arrivals(self):
        """Arrivals mid-generation must not perturb anyone's stream (the
        round-4 weakness: arrivals broke run-ahead; here they ride step 0 of
        the same fused program)."""
        prompts = _prompts(6, seed=11)
        legacy = _engine(fused_chunk=0)
        for uid, p in prompts.items():
            legacy.put(uid, p, max_new_tokens=7)
        want = legacy.generate_all()

        fused = _engine(fused_chunk=4, depth=2)
        items = list(prompts.items())
        fed = 0
        for step in range(500):
            if fed < len(items) and step % 2 == 0:
                uid, p = items[fed]
                fused.put(uid, p, max_new_tokens=7)
                fed += 1
            if fused.has_work:
                fused.step()
            if fed == len(items) and not fused.has_work:
                break
        assert not fused.has_work
        got = {uid: list(s.generated) for uid, s in fused._results.items()}
        assert got == want

    def test_eos_truncates_speculation(self):
        """EOS discovered at readback truncates the stream exactly where the
        legacy engine stops (post-EOS speculated tokens discarded)."""
        prompts = _prompts(3, seed=5)
        legacy = _engine(fused_chunk=0)
        for uid, p in prompts.items():
            legacy.put(uid, p, max_new_tokens=10)
        base = legacy.generate_all()
        # pick an eos that actually appears mid-stream for at least one uid
        eos = None
        for uid, toks in base.items():
            for t in toks[:-1]:
                eos = int(t)
                break
            if eos is not None:
                break
        assert eos is not None

        def run(fused_chunk):
            eng = _engine(fused_chunk=fused_chunk)
            for uid, p in prompts.items():
                eng.put(uid, p, max_new_tokens=10, eos_token_id=eos)
            return eng.generate_all()

        assert run(4) == run(0)

    def test_tiled_prefill_parity(self):
        """Fused pipeline with tile-aligned prefill matches the flat one."""
        prompts = _prompts(4, seed=7)
        flat = _engine(fused_chunk=4)
        tiled = _engine(fused_chunk=4, tile=4)
        for uid, p in prompts.items():
            flat.put(uid, p, max_new_tokens=6)
            tiled.put(uid, p, max_new_tokens=6)
        assert flat.generate_all() == tiled.generate_all()

    def test_sampled_decode_deterministic_per_seed(self):
        """Sampling rides inside the fused program: same engine seed ->
        same streams; differs from greedy; tokens in-vocab."""
        prompts = _prompts(3, seed=9)

        def run():
            eng = _engine(fused_chunk=4)
            for uid, p in prompts.items():
                eng.put(uid, p, max_new_tokens=8, temperature=0.9,
                        top_k=20, top_p=0.9)
            return eng.generate_all()

        a, b = run(), run()
        assert a == b
        greedy = _engine(fused_chunk=4)
        for uid, p in prompts.items():
            greedy.put(uid, p, max_new_tokens=8)
        g = greedy.generate_all()
        assert a != g
        assert all(0 <= t < 97 for toks in a.values() for t in toks)

    def test_pool_pressure_completes(self):
        """More requests than slots/blocks: the pipeline drains the queue
        through admission waves without deadlock and matches legacy."""
        prompts = _prompts(8, seed=13)
        legacy = _engine(fused_chunk=0, num_blocks=25)
        fused = _engine(fused_chunk=4, num_blocks=25)
        for uid, p in prompts.items():
            legacy.put(uid, p, max_new_tokens=6)
            fused.put(uid, p, max_new_tokens=6)
        assert fused.generate_all() == legacy.generate_all()


class TestFusedDeviceState:
    """The fused pipeline over device-resident scheduler rows
    (``device_state=True``, the default) vs the legacy host-staged path."""

    def test_device_vs_host_staged_parity_staggered_eos(self):
        """The hardest fused case: staggered arrivals (slot rows written
        mid-pipeline) + mid-stream EOS (device-speculated post-EOS tokens
        discarded at reconcile) + sampled rows. Device-resident state must
        reproduce the host-staged streams token for token."""
        prompts = _prompts(5, seed=21)
        base = _engine(fused_chunk=4, device_state=False)
        for uid, p in prompts.items():
            base.put(uid, p, max_new_tokens=8)
        eos = int(next(iter(base.generate_all().values()))[0])

        def run(device_state):
            eng = _engine(fused_chunk=4, device_state=device_state)
            items = list(prompts.items())
            fed = 0
            for step in range(500):
                if fed < len(items) and step % 2 == 0:
                    uid, p = items[fed]
                    kw = (dict(temperature=0.8, top_k=20, seed=uid)
                          if uid % 2 else {})
                    eng.put(uid, p, max_new_tokens=8, eos_token_id=eos, **kw)
                    fed += 1
                if eng.has_work:
                    eng.step()
                if fed == len(items) and not eng.has_work:
                    break
            assert not eng.has_work
            return {uid: list(s.generated) for uid, s in eng._results.items()}

        assert run(True) == run(False)

    def test_warmup_lowers_device_fused_programs(self):
        """warmup() must precompile the DEVICE variant of the fused program
        zoo when device_state is on — a serve-time compile stall on the
        first mixed chunk is exactly what warmup exists to prevent."""
        eng = _engine(fused_chunk=4, depth=2)
        assert eng.cfg.device_state
        n = eng.warmup()
        assert n > 0
        assert eng._dev_fused_jits  # device programs, not the legacy cache
        prompts = _prompts(4, seed=17)
        legacy = _engine(fused_chunk=4, device_state=False)
        for uid, p in prompts.items():
            eng.put(uid, p, max_new_tokens=6)
            legacy.put(uid, p, max_new_tokens=6)
        assert eng.generate_all() == legacy.generate_all()
