"""Diffusion UNet family (the reference's diffusers/spatial surface,
``model_implementations/diffusers/`` + ``csrc/spatial``): the model-agnostic
engine trains it unchanged; the DDIM sampler is one compiled scan."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.models import diffusion


def _cfg():
    return diffusion.UNetConfig.tiny()


def test_forward_shapes_and_determinism():
    cfg = _cfg()
    params = diffusion.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (2, cfg.image_size, cfg.image_size, cfg.in_channels))
    t = jnp.array([0, 50])
    out = jax.jit(lambda p, x, t: diffusion.forward(cfg, p, x, t))(params, x, t)
    assert out.shape == x.shape
    out2 = jax.jit(lambda p, x, t: diffusion.forward(cfg, p, x, t))(params, x, t)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # timestep conditioning is live: different t -> different prediction
    # (small at init by design — the resblocks' output convs start near zero,
    # the standard DDPM init — but strictly nonzero)
    out3 = jax.jit(lambda p, x, t: diffusion.forward(cfg, p, x, t))(
        params, x, jnp.array([99, 99]))
    assert np.abs(np.asarray(out) - np.asarray(out3)).max() > 1e-9


def test_engine_trains_unet_under_zero2():
    """The SAME engine that trains LMs trains the UNet (loss contract is
    model-agnostic): noise-prediction MSE descends under ZeRO-2 x fsdp."""
    reset_topology()
    cfg = _cfg()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: diffusion.build(cfg, ctx=ctx),
        config={
            "train_micro_batch_size_per_device": 2,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 0,
            "gradient_clipping": 1.0,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "mesh": {"data": 2, "fsdp": 4},
            "seed": 3,
        })
    # a fixed structured image set: the epsilon objective is learnable
    rng = np.random.default_rng(0)
    base = rng.normal(size=(16, cfg.image_size, cfg.image_size,
                            cfg.in_channels)).astype(np.float32)
    losses = [float(engine.train_batch({"images": base})) for _ in range(8)]
    assert all(np.isfinite(losses)), losses
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses
    # conv kernels sharded over fsdp per the planner (output-channel dim)
    big = max(jax.tree_util.tree_leaves(engine.params), key=lambda x: x.size)
    assert "fsdp" in str(big.sharding.spec)


def test_ddim_sampler_shapes_and_determinism():
    cfg = _cfg()
    params = diffusion.init_params(cfg, jax.random.PRNGKey(0))
    sample = jax.jit(lambda p, r: diffusion.ddim_sample(cfg, p, r, batch=2,
                                                        num_steps=5))
    a = sample(params, jax.random.PRNGKey(7))
    b = sample(params, jax.random.PRNGKey(7))
    assert a.shape == (2, cfg.image_size, cfg.image_size, cfg.in_channels)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = sample(params, jax.random.PRNGKey(8))
    assert np.abs(np.asarray(a) - np.asarray(c)).max() > 1e-6
    assert np.all(np.isfinite(np.asarray(a)))


def test_noise_schedule_monotone():
    ab = np.asarray(diffusion.ddpm_schedule(100))
    assert ab.shape == (100,)
    assert np.all(np.diff(ab) < 0) and ab[0] < 1.0 and ab[-1] > 0.0
