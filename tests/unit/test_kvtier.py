"""Hierarchical KV-cache tiering (inference/kvtier.py).

The contract under test: with ``kv_tier=True`` the prefix-cache LRU
*demotes* evicted published blocks (HBM → bounded host arena → disk spill)
instead of dropping them, and admission *promotes* demoted chain links back
through the jitted scatter path when the restore-vs-prefill cost model says
so — producing EXACTLY the tokens a cold engine would, greedy and
sampled-with-fixed-seed, in every dispatch mode. Plus the tier mechanics
that make that safe: length+sha256 framing, atomic disk records with a
torn-file sweep, LRU order in the host arena, conservative cost-model
edges, the async prefetch hit/abandoned protocol, and the
notify-before-free ordering the cluster index depends on.
"""

import os
import pickle
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.kvtier import (
    DiskTier,
    HostTier,
    KVCodecMismatch,
    KVTierStore,
    RECORD_MAGIC,
    _key_digest,
    frame_bytes,
    restore_beats_prefill,
    unframe_bytes,
)
from deepspeed_tpu.inference.ragged import (
    BlockedAllocator,
    KVHandoff,
    RaggedConfig,
    RaggedInferenceEngine,
)
from deepspeed_tpu.models import llama

CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
)

BS = 4  # block size used throughout — prompts below are built around it


def _engine(cache=True, **over):
    kw = dict(max_tokens_per_step=16, max_seqs=3, block_size=BS,
              num_blocks=13, max_blocks_per_seq=16,
              enable_prefix_cache=cache)
    kw.update(over)
    return RaggedInferenceEngine(
        model=lambda ctx: llama.build(CFG, ctx=ctx),
        ragged_config=RaggedConfig(**kw), dtype=jnp.float32, seed=0)


MODES = {
    "plain": {},
    "tiled": {"prefill_tile": 8},
    "run_ahead": {"decode_run_ahead": 4},
    "fused": {"fused_chunk": 4, "pipeline_depth": 2},
}

SHARED = [11, 7, 3, 5, 2, 13, 17, 19]          # two full blocks of 4
PROMPT_A = SHARED + [23, 29, 31]               # warms the cache
PROMPT_B = SHARED + [37, 41]                   # must hit both shared blocks


def _churn(eng, n=6, max_new=4):
    """Distinct single-use prompts that force LRU eviction (and with
    tiering on, demotion) of earlier published prefix blocks."""
    for i in range(n):
        eng.put(f"churn{i}", [50 + i * 7 + j for j in range(9)],
                max_new_tokens=max_new)
        eng.generate_all()


# ------------------------------------------------------------------ framing
class TestFraming:
    def test_roundtrip_and_chaining(self):
        a, b = b"hello", b"\x00" * 33
        buf = frame_bytes(a) + frame_bytes(b)
        got_a, off = unframe_bytes(buf)
        got_b, end = unframe_bytes(buf, off)
        assert (got_a, got_b) == (a, b) and end == len(buf)

    def test_flipped_byte_raises(self):
        buf = bytearray(frame_bytes(b"payload"))
        buf[-1] ^= 0x01
        with pytest.raises(ValueError):
            unframe_bytes(bytes(buf))

    def test_truncation_raises(self):
        buf = frame_bytes(b"payload")
        for cut in (1, 8, 39, len(buf) - 1):
            with pytest.raises(ValueError):
                unframe_bytes(buf[:cut])


class TestKVHandoffBytes:
    def _record(self):
        return KVHandoff(
            uid="r1", prompt=[1, 2, 3, 4, 5], generated=[7], pos=5,
            max_new_tokens=8, eos_token_id=None, temperature=0.9, top_k=20,
            top_p=1.0, seed=123, deadline_remaining_s=0.0,
            block_payload={"k": np.arange(24, dtype=np.float32
                                          ).reshape(2, 2, 2, 3)},
            row_iv=np.arange(5, dtype=np.int32),
            row_fv=np.asarray([0.9, 1.0], np.float32))

    def test_roundtrip(self):
        rec = self._record()
        back = KVHandoff.from_bytes(rec.to_bytes())
        assert back.uid == rec.uid and back.prompt == rec.prompt
        assert back.seed == rec.seed and back.pos == rec.pos
        np.testing.assert_array_equal(back.block_payload["k"],
                                      rec.block_payload["k"])
        np.testing.assert_array_equal(back.row_iv, rec.row_iv)

    def test_corruption_and_truncation_raise(self):
        buf = self._record().to_bytes()
        flipped = bytearray(buf)
        flipped[len(buf) // 2] ^= 0x01
        with pytest.raises(ValueError):
            KVHandoff.from_bytes(bytes(flipped))
        with pytest.raises(ValueError):
            KVHandoff.from_bytes(buf[:-3])
        with pytest.raises(ValueError):
            KVHandoff.from_bytes(b"XXXX" + buf[4:])
        with pytest.raises(ValueError):
            KVHandoff.from_bytes(buf + b"trailing")


# --------------------------------------------------------------- cost model
class TestRestoreCostModel:
    def test_zero_length_never_restores(self):
        assert not restore_beats_prefill(0, 1024, 100.0, 1000.0)
        assert not restore_beats_prefill(-4, 1024, 100.0, 1000.0)

    def test_exact_tie_prefers_prefill(self):
        # 125_000 B/token over 1 Gb/s = 1 ms/token; 1000 tok/s prefill =
        # 1 ms/token — a dead tie must NOT restore (strict <)
        assert not restore_beats_prefill(64, 125_000, 1.0, 1000.0)
        assert restore_beats_prefill(64, 124_999, 1.0, 1000.0)

    def test_unknown_bandwidth_is_conservative(self):
        # a -1 "unknown" bandwidth/rate would flip the inequality by going
        # negative; both must mean "re-prefill"
        assert not restore_beats_prefill(64, 16, -1.0, 1000.0)
        assert not restore_beats_prefill(64, 16, 100.0, -1.0)
        assert not restore_beats_prefill(64, 16, 0.0, 1000.0)


# ------------------------------------------------------------------- tiers
class TestHostTier:
    def test_lru_overflow_sheds_oldest(self):
        t = HostTier(2)
        assert t.put("a", 1) == []
        assert t.put("b", 2) == []
        shed = t.put("c", 3)
        assert shed == [("a", 1)] and len(t) == 2
        assert t.get("a") is None and t.get("c") == 3

    def test_get_touches_to_mru(self):
        t = HostTier(2)
        t.put("a", 1)
        t.put("b", 2)
        t.get("a")                       # a becomes MRU
        assert t.put("c", 3) == [("b", 2)]
        assert t.get("a") == 1

    def test_reput_touches_without_shedding(self):
        t = HostTier(2)
        t.put("a", 1)
        t.put("b", 2)
        assert t.put("a", 1) == []       # same chain key = same KV: touch
        assert t.put("c", 3) == [("b", 2)]


class TestDiskTier:
    def test_put_get_roundtrip_atomic(self, tmp_path):
        d = DiskTier(str(tmp_path / "kv"), budget_blocks=8)
        key = (None, (1, 2, 3, 4))
        payload = {"k": np.arange(8, dtype=np.float32)}
        assert d.put(key, payload)
        assert not any(".tmp." in n for n in os.listdir(d.directory))
        np.testing.assert_array_equal(d.get(key)["k"], payload["k"])
        assert d.get((None, (9, 9, 9, 9))) is None

    def test_budget_evicts_oldest(self, tmp_path):
        d = DiskTier(str(tmp_path / "kv"), budget_blocks=2)
        keys = [(None, (i,)) for i in range(3)]
        for k in keys:
            d.put(k, np.zeros(4))
        assert len(d) == 2
        assert d.get(keys[0]) is None and d.get(keys[2]) is not None

    def test_sweep_removes_torn_and_temp_files(self, tmp_path):
        root = str(tmp_path / "kv")
        d = DiskTier(root, budget_blocks=8)
        good = (None, (1, 2, 3, 4))
        d.put(good, np.arange(4))
        valid = os.path.join(root, os.listdir(root)[0])
        # a torn write (truncated record), a corrupt one, and a leftover temp
        with open(valid, "rb") as f:
            buf = f.read()
        with open(os.path.join(root, "torn" + DiskTier.SUFFIX), "wb") as f:
            f.write(buf[:len(buf) // 2])
        flipped = bytearray(buf)
        flipped[-1] ^= 0x01
        with open(os.path.join(root, "bad" + DiskTier.SUFFIX), "wb") as f:
            f.write(bytes(flipped))
        with open(os.path.join(root, f"x{DiskTier.SUFFIX}.tmp.123"),
                  "wb") as f:
            f.write(b"partial")
        # engine startup re-opens the directory: the sweep keeps only the
        # intact record
        d2 = DiskTier(root, budget_blocks=8)
        assert d2.sweep_removed == 3
        assert sorted(os.listdir(root)) == [os.path.basename(valid)]
        assert d2.get(good) is not None

    def test_codec_recorded_and_matched(self, tmp_path):
        root = str(tmp_path / "kv")
        key = (None, (1, 2, 3, 4))
        d = DiskTier(root, budget_blocks=8, codec="int8")
        d.put(key, {"q": np.zeros(4, np.int8), "s": np.ones(1, np.float16)})
        got = DiskTier(root, budget_blocks=8, codec="int8").get(key)
        assert got is not None and got["q"].dtype == np.int8

    def test_codec_mismatch_raises_not_misses(self, tmp_path):
        # a spill written under int8 read by an fp16/off engine is a CONFIG
        # error: silently dequantizing (or splicing raw int8 as fp rows)
        # would corrupt tokens, so get() must raise, never return None
        root = str(tmp_path / "kv")
        key = (None, (1, 2, 3, 4))
        DiskTier(root, budget_blocks=8, codec="int8").put(key, np.zeros(4))
        for other in ("off", "fp8"):
            reader = DiskTier(root, budget_blocks=8, codec=other)
            with pytest.raises(KVCodecMismatch, match="int8"):
                reader.get(key)
            # the record is intact, not a casualty: the matching engine
            # still reads it afterwards
            assert DiskTier(root, budget_blocks=8,
                            codec="int8").get(key) is not None

    def test_legacy_bare_key_record_reads_as_off(self, tmp_path):
        # records written before codec framing carry a bare pickled chain
        # key: they read fine under codec "off" and raise under a quant one
        root = str(tmp_path / "kv")
        os.makedirs(root)
        key = (None, (7, 8, 9))
        body = (RECORD_MAGIC
                + frame_bytes(pickle.dumps(key, protocol=4))
                + frame_bytes(pickle.dumps(np.arange(4), protocol=4)))
        with open(os.path.join(root, _key_digest(key) + DiskTier.SUFFIX),
                  "wb") as f:
            f.write(body)
        got = DiskTier(root, budget_blocks=8, codec="off").get(key)
        np.testing.assert_array_equal(got, np.arange(4))
        with pytest.raises(KVCodecMismatch):
            DiskTier(root, budget_blocks=8, codec="int8").get(key)

    def test_store_threads_codec_to_disk_and_stats(self, tmp_path):
        st = KVTierStore(host_blocks=2, disk_blocks=4,
                         directory=str(tmp_path / "kv"), codec="fp8")
        assert st.disk.codec == "fp8"
        assert st.stats()["codec"] == "fp8"


# ---------------------------------------------- allocator demotion ordering
class _RecordingListener:
    """Captures, at notification time, whether the block id was already
    back in the allocator free list — the satellite-1 invariant: the
    cluster index must hear about the eviction BEFORE the payload's block
    id is reusable."""

    def __init__(self, alloc):
        self.alloc = alloc
        self.events = []

    def on_publish(self, key):
        self.events.append(("publish", key))

    def on_evict(self, key):
        self.events.append(
            ("evict", key, self._freed()))

    def on_demote(self, key):
        self.events.append(("demote", key, self._freed()))

    def on_reset(self):
        self.events.append(("reset",))

    def _freed(self):
        return len(self.alloc._free)


class TestDemotionNotifyOrdering:
    def _evict_one(self, hook):
        a = BlockedAllocator(3)      # 2 usable
        lst = _RecordingListener(a)
        a.listener = lst
        a.demote_hook = hook
        blocks = a.allocate(2)
        a.publish(blocks[0], "key0")
        a.free(blocks)               # key0 retained, block[1] free
        a.allocate(2)                # forces eviction of key0
        return lst.events[-1]

    def test_demote_notified_before_block_freed(self):
        seen = {}

        def hook(block, key):
            seen["args"] = (block, key)
            return True

        ev = self._evict_one(hook)
        assert seen["args"][1] == "key0"
        # one block was free before the eviction; the evicted id must not
        # have joined it yet when the listener runs
        assert ev == ("demote", "key0", 1)

    def test_failed_demotion_falls_back_to_evict(self):
        ev = self._evict_one(lambda b, k: False)
        assert ev == ("evict", "key0", 1)

    def test_raising_hook_is_contained(self):
        def hook(b, k):
            raise RuntimeError("gather failed")

        ev = self._evict_one(hook)
        assert ev == ("evict", "key0", 1)


# --------------------------------------------------------- engine round-trip
class TestTieredParity:
    """Demoted-then-promoted prefixes must be invisible in the tokens."""

    @pytest.mark.parametrize("mode", list(MODES))
    def test_demote_promote_token_exact(self, mode, tmp_path):
        kw = MODES[mode]
        cold = _engine(cache=False, num_blocks=49, **kw)
        cold.put("g", PROMPT_B, max_new_tokens=8)
        cold.put("s", PROMPT_B, max_new_tokens=8, temperature=0.9,
                 top_k=20, seed=123)
        want = cold.generate_all()

        t = _engine(kv_tier=True, kv_tier_host_blocks=16,
                    kv_tier_dir=str(tmp_path / "kv"), **kw)
        t.put("warm", PROMPT_A, max_new_tokens=6)
        t.generate_all()
        _churn(t)                    # 13-block pool: the prefix demotes
        st = t.kv_tier_stats()
        assert st["demotions"] > 0

        t.put("g", PROMPT_B, max_new_tokens=8)
        t.put("s", PROMPT_B, max_new_tokens=8, temperature=0.9,
              top_k=20, seed=123)
        got = t.generate_all()
        assert got["g"] == want["g"]
        assert got["s"] == want["s"]
        st = t.kv_tier_stats()
        assert st["promotions_host"] > 0
        assert st["promoted_admissions_host"] >= 1

    def test_disk_spill_prefetch_hit_and_parity(self, tmp_path):
        t = _engine(kv_tier=True, kv_tier_host_blocks=2,
                    kv_tier_disk_blocks=32,
                    kv_tier_dir=str(tmp_path / "kv"))
        t.put("warm", PROMPT_A, max_new_tokens=6)
        t.generate_all()
        _churn(t, n=8)               # 2-block host arena overflows to disk
        st = t.kv_tier_stats()
        assert st["spills"] > 0 and st["disk_blocks"] > 0

        # the router-side kick: stage disk records host-ward off-thread,
        # then admit — the resolved job counts as a prefetch hit
        assert t.tier_prefetch_async(PROMPT_B)
        assert t._kvtier.wait_idle(10.0)
        t.put("g", PROMPT_B, max_new_tokens=8)
        got = t.generate_all()
        st = t.kv_tier_stats()
        assert st["prefetch_hits"] == 1
        assert st["promotions"] >= 2  # both shared blocks restored

        cold = _engine(cache=False, num_blocks=49)
        cold.put("g", PROMPT_B, max_new_tokens=8)
        assert got["g"] == cold.generate_all()["g"]
        t._kvtier.close()

    def test_prefetch_abandoned_is_token_identical(self, tmp_path):
        t = _engine(kv_tier=True, kv_tier_host_blocks=2,
                    kv_tier_disk_blocks=32,
                    kv_tier_dir=str(tmp_path / "kv"))
        t.put("warm", PROMPT_A, max_new_tokens=6)
        t.generate_all()
        _churn(t, n=8)
        # park the worker: admission outruns the staging job
        gate = threading.Event()
        t._kvtier._stall_for_test = gate
        assert t.tier_prefetch_async(PROMPT_B)
        t.put("g", PROMPT_B, max_new_tokens=8)
        got = t.generate_all()
        gate.set()
        st = t.kv_tier_stats()
        assert st["prefetch_abandoned"] == 1 and st["prefetch_hits"] == 0
        # the synchronous restore covered for it — tokens identical
        cold = _engine(cache=False, num_blocks=49)
        cold.put("g", PROMPT_B, max_new_tokens=8)
        assert got["g"] == cold.generate_all()["g"]
        t._kvtier.close()

    def test_cost_model_decline_still_correct(self, tmp_path):
        # a hopeless tier bandwidth: every restore is declined, the request
        # re-prefills — slower, never wrong
        t = _engine(kv_tier=True, kv_tier_host_blocks=16,
                    kv_tier_host_gbps=1e-9,
                    kv_tier_dir=str(tmp_path / "kv"))
        t.put("warm", PROMPT_A, max_new_tokens=6)
        t.generate_all()
        _churn(t)
        t.put("g", PROMPT_B, max_new_tokens=8)
        got = t.generate_all()
        st = t.kv_tier_stats()
        assert st["restore_declined"] > 0 and st["promotions"] == 0
        cold = _engine(cache=False, num_blocks=49)
        cold.put("g", PROMPT_B, max_new_tokens=8)
        assert got["g"] == cold.generate_all()["g"]

    def test_tier_store_survives_reset(self, tmp_path):
        t = _engine(kv_tier=True, kv_tier_host_blocks=16,
                    kv_tier_dir=str(tmp_path / "kv"))
        t.put("warm", PROMPT_A, max_new_tokens=6)
        t.generate_all()
        _churn(t)
        assert t.kv_tier_stats()["demotions"] > 0
        t.reset_state()
        # content-keyed records outlive the allocator generation: the
        # rewired demote hook and the parked payloads still promote
        assert t.allocator.demote_hook is not None
        t.put("g", PROMPT_B, max_new_tokens=8)
        got = t.generate_all()
        assert t.kv_tier_stats()["promotions"] > 0
        cold = _engine(cache=False, num_blocks=49)
        cold.put("g", PROMPT_B, max_new_tokens=8)
        assert got["g"] == cold.generate_all()["g"]


class TestTierConfigGates:
    def test_default_is_off(self):
        cfg = RaggedConfig()
        assert cfg.kv_tier is False and cfg.kv_tier_disk_blocks == 0

    def test_tier_requires_prefix_cache(self):
        with pytest.raises(ValueError, match="prefix_cache"):
            _engine(cache=False, kv_tier=True)

    def test_engine_without_tiering_has_no_store(self):
        t = _engine(cache=True)
        assert t._kvtier is None and t.kv_tier_stats() is None
        assert t.allocator.demote_hook is None


class TestStoreMechanics:
    def test_fetch_prefers_host_and_reports_tier(self, tmp_path):
        s = KVTierStore(host_blocks=1, disk_blocks=8,
                        directory=str(tmp_path / "kv"))
        s.demote("k1", np.arange(4))
        s.demote("k2", np.arange(4))     # k1 sheds to disk
        assert s.tier_of("k2") == 1 and s.tier_of("k1") == 2
        assert s.fetch("k2")[1] == 1
        assert s.fetch("k1")[1] == 2
        assert s.fetch("nope") is None
        assert s.stats()["spills"] == 1
        s.close()

    def test_spill_drop_without_disk_tier(self):
        s = KVTierStore(host_blocks=1)
        s.demote("k1", np.arange(4))
        s.demote("k2", np.arange(4))
        assert s.stats()["spill_drops"] == 1
        assert s.tier_of("k1") == 0      # gone for good
        s.close()

    def test_prefetch_dedupes_by_signature(self, tmp_path):
        s = KVTierStore(host_blocks=1, disk_blocks=8,
                        directory=str(tmp_path / "kv"))
        gate = threading.Event()
        s._stall_for_test = gate
        s.demote("k1", np.arange(4))
        assert s.prefetch(["k1"], sig="req")
        assert not s.prefetch(["k1"], sig="req")   # already pending
        assert not s.prefetch(["absent"], sig="other")  # nothing to stage
        gate.set()
        assert s.wait_idle(5.0)
        assert s.note_admission("req") == "hit"
        assert s.note_admission("req") is None     # resolved exactly once
        s.close()
