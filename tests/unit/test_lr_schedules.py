"""LR-schedule semantics vs hand-computed reference values
(reference: ``runtime/lr_schedules.py``)."""

import math

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.runtime import lr_schedules as lrs


def _lr(schedule, step):
    return float(schedule(jnp.int32(step)))


def test_warmup_log_matches_reference_gamma():
    s = lrs.warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=100, warmup_type="log")
    for step in [0, 1, 10, 50, 99]:
        gamma = math.log(step + 1) / math.log(100)
        assert _lr(s, step) == pytest.approx(0.1 * gamma, rel=1e-5)
    # past warmup: constant at max
    assert _lr(s, 100) == pytest.approx(0.1)
    assert _lr(s, 10_000) == pytest.approx(0.1)


def test_warmup_linear():
    s = lrs.warmup_lr(warmup_min_lr=0.01, warmup_max_lr=0.11, warmup_num_steps=10, warmup_type="linear")
    assert _lr(s, 0) == pytest.approx(0.01)
    assert _lr(s, 5) == pytest.approx(0.01 + 0.1 * 0.5)
    assert _lr(s, 10) == pytest.approx(0.11)


def test_warmup_decay_hits_zero_at_total():
    s = lrs.warmup_decay_lr(total_num_steps=100, warmup_max_lr=0.1, warmup_num_steps=10,
                            warmup_type="linear")
    assert _lr(s, 10) == pytest.approx(0.1)
    # halfway through decay window: (100-55)/(100-10) = 0.5
    assert _lr(s, 55) == pytest.approx(0.05)
    assert _lr(s, 100) == pytest.approx(0.0)
    assert _lr(s, 150) == pytest.approx(0.0)  # clamped, not negative


def test_warmup_cosine_parks_at_floor():
    s = lrs.warmup_cosine_lr(total_num_steps=100, base_lr=1.0, warmup_num_steps=10,
                             cos_min_ratio=0.1, warmup_type="linear")
    assert _lr(s, 10) <= 1.0
    assert _lr(s, 9) == pytest.approx(0.9)  # linear ramp 9/10
    # far past the end: stays at floor instead of oscillating
    assert _lr(s, 100) == pytest.approx(0.1, abs=1e-5)
    assert _lr(s, 500) == pytest.approx(0.1, abs=1e-5)


def test_one_cycle_triangle():
    s = lrs.one_cycle(cycle_min_lr=0.0, cycle_max_lr=1.0, cycle_first_step_size=10,
                      cycle_second_step_size=10)
    assert _lr(s, 0) == pytest.approx(0.0, abs=1e-6)
    assert _lr(s, 5) == pytest.approx(0.5, abs=1e-5)
    mid = _lr(s, 10)
    assert mid == pytest.approx(1.0, abs=1e-4)
    assert _lr(s, 15) == pytest.approx(0.5, abs=1e-4)


def test_lr_range_test_continuous_and_staircase():
    cont = lrs.lr_range_test(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                             lr_range_test_step_rate=1.0)
    # reference: min_lr * (1 + rate*(it+1)/step_size)
    assert _lr(cont, 0) == pytest.approx(0.01 * 1.1)
    assert _lr(cont, 19) == pytest.approx(0.01 * 3.0)
    stair = lrs.lr_range_test(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                              lr_range_test_step_rate=1.0, lr_range_test_staircase=True)
    assert _lr(stair, 0) == pytest.approx(0.01)
    assert _lr(stair, 9) == pytest.approx(0.02)


def test_schedules_are_jittable():
    s = lrs.warmup_decay_lr(total_num_steps=100, warmup_max_lr=0.1, warmup_num_steps=10)
    jitted = jax.jit(s)
    assert float(jitted(jnp.int32(50))) == pytest.approx(_lr(s, 50))


def test_build_schedule_factory():
    from deepspeed_tpu.config.config import SchedulerConfig

    s = lrs.build_schedule(SchedulerConfig(type="WarmupLR", params={"warmup_max_lr": 0.2}), 0.1)
    assert _lr(s, 10_000) == pytest.approx(0.2)
    s = lrs.build_schedule(None, 0.05)
    assert _lr(s, 123) == pytest.approx(0.05)
    with pytest.raises(ValueError):
        lrs.build_schedule(SchedulerConfig(type="Nope"), 0.1)


def test_stateful_wrapper_protocol():
    sched = lrs.LRScheduler(lrs.warmup_lr(warmup_max_lr=0.1, warmup_num_steps=10, warmup_type="linear"))
    sched.step()
    sched.step()
    assert sched.state_dict() == {"last_batch_iteration": 1}
    sched2 = lrs.LRScheduler(sched.schedule)
    sched2.load_state_dict(sched.state_dict())
    assert sched2.get_last_lr() == sched.get_last_lr()
