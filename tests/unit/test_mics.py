"""MiCS shard-group ZeRO-3 (reference ``runtime/zero/mics.py:63 MiCS_Init``
+ ``:361 MiCS_Optimizer``): shard degree bounded to a group of k < world
devices, replicas across world/k groups, cross-group gradient sync."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.comm.topology import reset_topology
from deepspeed_tpu.config.config import ConfigError
from deepspeed_tpu.models import llama

VOCAB = 256


def _engine(mics=0, mesh=None, stage=3, **zero_extra):
    reset_topology()
    cfg = {
        "train_micro_batch_size_per_device": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage, "mics_shard_size": mics,
                              **zero_extra},
        "seed": 7,
    }
    if mesh is not None:
        cfg["mesh"] = mesh
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=lambda ctx: llama.build(llama.LlamaConfig.tiny(VOCAB), ctx=ctx),
        config=cfg, seed=11)
    return engine


def _losses(engine, n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [float(engine.train_batch(
        {"input_ids": rng.integers(0, VOCAB, (32, 16), dtype=np.int32)}))
        for _ in range(n)]


class TestMics:
    def test_layout_shard_degree_equals_group(self):
        """Round-4 item 7 'done' criterion: shard degree = group size,
        replicas across groups — params/grads/opt state shard over an fsdp
        axis of size k, the data axis of size world/k replicates them."""
        eng = _engine(mics=4)
        assert eng.topo.size("fsdp") == 4
        assert eng.topo.size("data") == 2
        # a big stacked layer leaf: sharded over fsdp ONLY (not data)
        spec = eng.plan.param_specs["layers"]["w_gate"]
        flat = [e for e in spec if e is not None]
        assert flat == ["fsdp"] or flat == [("fsdp",)], spec
        # grads/opt state follow the same within-group layout (stage-3
        # shard_specs == param_specs without hierarchical partitioning)
        assert eng.plan.shard_specs["layers"]["w_gate"] == spec

    def test_loss_parity_vs_explicit_mesh(self):
        """mics_shard_size=k must train identically to the hand-shaped
        {data: world/k, fsdp: k} mesh (it IS that mesh)."""
        a = _losses(_engine(mics=4))
        b = _losses(_engine(mesh={"data": 2, "fsdp": 4}))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_loss_parity_vs_full_world_fsdp(self):
        """Bounding the shard group must not change the math, only the
        layout: same trajectory as full-world ZeRO-3 within bf16 noise."""
        a = _losses(_engine(mics=4))
        b = _losses(_engine(mesh={"data": 1, "fsdp": 8}))
        np.testing.assert_allclose(a, b, rtol=2e-2)
        assert abs(a[0] - b[0]) < 1e-5

    def test_requires_stage3(self):
        with pytest.raises((ConfigError, ValueError), match="stage 3"):
            _engine(mics=4, stage=2)

    def test_conflicting_mesh_rejected(self):
        with pytest.raises((ConfigError, ValueError), match="contradicts"):
            _engine(mics=4, mesh={"data": 1, "fsdp": 8})

    def test_conflicts_with_hpz(self):
        with pytest.raises((ConfigError, ValueError), match="pick one"):
            _engine(mics=4, hierarchical_partitioning=True)
