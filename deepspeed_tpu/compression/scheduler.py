"""Compression scheduler: which technique is active at which step, and how
hard (reference ``compression/scheduler.py compression_scheduler`` +
``basic_layer.py`` bit annealing).

Schedules are computed as traced scalars from the step, so one compiled
train program serves the whole schedule:
- activation gate: ``step >= schedule_offset`` as a 0/1 float,
- QAT bit annealing: ``start_bits`` down to ``target_bits``, one bit per
  ``quantization_period`` steps after the offset (reference
  LinearLayer_Compress bit-reduction cadence).

``apply_to_params`` maps the configured groups onto the param pytree by
"/"-joined path regex and applies fake-quant / pruning masks — the
functional analog of the reference's module-wrapper surgery
(``compress.py``).
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.config import CompressionConfig
from deepspeed_tpu.compression import functional as F


def _path_str(path) -> str:
    parts = []
    for k in path:
        key = getattr(k, "key", None)
        if key is None:
            key = getattr(k, "idx", "")
        parts.append(str(key))
    return "/".join(parts)


def _match(patterns, path: str) -> bool:
    """Pattern semantics: regex when the pattern compiles, else a glob
    (reference configs use globs like ``*.attention`` — those are invalid
    regexes, so they fall through to ``fnmatch``); a plain name is a regex
    substring search, matching the reference's substring behavior."""
    import fnmatch

    for p in patterns:
        if p == "*":
            return True
        try:
            if re.search(p, path):
                return True
        except re.error:
            # reference module names use "." separators; our paths use "/"
            if fnmatch.fnmatch(path, p.replace(".", "/")):
                return True
    return False


class CompressionScheduler:
    def __init__(self, config: CompressionConfig | dict | None,
                 num_heads: int = 0):
        if not isinstance(config, CompressionConfig):
            config = CompressionConfig.from_dict(config)
        self.config = config
        self.num_heads = num_heads
        self.training_steps = 0
        if config.methods["activation_quantization"].enabled:
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                "activation_quantization is parsed but NOT applied by the "
                "engine's param-compression path — wire "
                "deepspeed_tpu.compression.quantize_activation into the "
                "model's forward where activations should be quantized.")

    # ------------------------------------------------------- reference API
    def step(self, step_zero_check: bool = False) -> None:
        if not step_zero_check:
            self.training_steps += 1

    def is_active(self, method: str, step=None):
        """0/1 gate for a method at ``step`` (traced-friendly)."""
        m = self.config.methods.get(method)
        if m is None or not m.enabled:
            return jnp.float32(0.0) if step is not None else False
        if step is None:
            return self.training_steps >= m.schedule_offset
        return (step >= m.schedule_offset).astype(jnp.float32)

    def current_bits(self, group_params: dict, method: str, step):
        """Annealed bit width at ``step`` (traced)."""
        m = self.config.methods[method]
        start = float(group_params.get("start_bits", 8))
        target = float(group_params.get("target_bits", start))
        period = float(group_params.get("quantization_period", 1) or 1)
        done = jnp.maximum(step.astype(jnp.float32) - m.schedule_offset, 0.0)
        return jnp.maximum(start - jnp.floor(done / period), target)

    # ------------------------------------------------------- param surgery
    def apply_to_params(self, params, step):
        """Apply every active technique to matching param leaves; returns the
        compressed pytree (pure; call inside the jitted loss)."""
        cfg = self.config
        if not cfg.any_enabled:
            return params

        def per_layer(fn, w):
            """Stacked layer leaves ([L, in, out]) get the technique applied
            per layer (vmap over the leading layer dim); plain 2D weights
            directly."""
            return jax.vmap(fn)(w) if w.ndim >= 3 else fn(w)

        def transform(path, leaf):
            if leaf.ndim < 2:  # norms/biases are never compressed
                return leaf
            p = _path_str(path)
            out = leaf
            wq = cfg.methods["weight_quantization"]
            if wq.enabled:
                for g in wq.groups:
                    if _match(g.modules, p):
                        bits = self.current_bits(g.params, "weight_quantization", step)
                        gate = self.is_active("weight_quantization", step)
                        qg = int(g.params.get(
                            "quantize_groups", wq.shared.get("quantize_groups", 1)))
                        fq = per_layer(
                            lambda w: F.fake_quantize(w, bits, qg), out)
                        out = jnp.where(gate > 0, fq, out)
                        break
            sp = cfg.methods["sparse_pruning"]
            if sp.enabled:
                for g in sp.groups:
                    if _match(g.modules, p):
                        r = 1.0 - float(g.params.get("dense_ratio", 0.5))
                        gate = self.is_active("sparse_pruning", step)
                        pruned = per_layer(
                            lambda w: w * F.magnitude_prune_mask(w, r), out)
                        out = jnp.where(gate > 0, pruned, out)
                        break
            rp = cfg.methods["row_pruning"]
            if rp.enabled:
                for g in rp.groups:
                    if _match(g.modules, p):
                        r = 1.0 - float(g.params.get("dense_ratio", 0.5))
                        gate = self.is_active("row_pruning", step)
                        pruned = per_layer(
                            lambda w: w * F.row_prune_mask(w, r), out)
                        out = jnp.where(gate > 0, pruned, out)
                        break
            hp = cfg.methods["head_pruning"]
            if hp.enabled and self.num_heads:
                for g in hp.groups:
                    if _match(g.modules, p):
                        r = 1.0 - float(g.params.get("dense_ratio", 0.5))
                        gate = self.is_active("head_pruning", step)
                        nh = self.num_heads
                        pruned = per_layer(
                            lambda w: w * F.head_prune_mask(w, r, nh), out)
                        out = jnp.where(gate > 0, pruned, out)
                        break
            cp = cfg.methods["channel_pruning"]
            if cp.enabled:
                for g in cp.groups:
                    if _match(g.modules, p):
                        r = 1.0 - float(g.params.get("dense_ratio", 0.5))
                        gate = self.is_active("channel_pruning", step)
                        pruned = per_layer(
                            lambda w: w * F.channel_prune_mask(w, r), out)
                        out = jnp.where(gate > 0, pruned, out)
                        break
            return out

        return jax.tree_util.tree_map_with_path(transform, params)
