"""Compression config parsing.

Accepts the reference's ``compression_training`` ds_config shape
(``/root/reference/deepspeed/compression/config.py``,
``constants.py``): per-method ``shared_parameters`` (enabled,
schedule_offset, ...) plus ``different_groups`` mapping a group name to
``{params: {...}, modules: [patterns]}``. Module patterns match against the
"/"-joined param pytree path here (the functional analog of the reference's
module-name matching in ``compress.py get_module_name``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

METHODS = (
    "weight_quantization",
    "activation_quantization",
    "sparse_pruning",
    "row_pruning",
    "head_pruning",
    "channel_pruning",
)


@dataclass
class CompressionGroup:
    name: str
    params: dict
    modules: list  # regex patterns over "/"-joined param paths


@dataclass
class CompressionMethod:
    enabled: bool = False
    schedule_offset: int = 0
    shared: dict = field(default_factory=dict)
    groups: list = field(default_factory=list)  # [CompressionGroup]


@dataclass
class CompressionConfig:
    methods: dict = field(default_factory=dict)  # name -> CompressionMethod

    @classmethod
    def from_dict(cls, data: dict | None) -> "CompressionConfig":
        data = data or {}
        methods = {}
        for name in METHODS:
            block = data.get(name) or {}
            shared = dict(block.get("shared_parameters") or {})
            groups = []
            for gname, g in (block.get("different_groups") or {}).items():
                g = dict(g or {})
                groups.append(CompressionGroup(
                    name=gname,
                    params=dict(g.get("params") or {}),
                    modules=list(g.get("modules") or ["*"]),
                ))
            methods[name] = CompressionMethod(
                enabled=bool(shared.get("enabled", False)),
                schedule_offset=int(shared.get("schedule_offset", 0)),
                shared=shared,
                groups=groups,
            )
        return cls(methods=methods)

    def enabled_methods(self) -> list:
        return [n for n, m in self.methods.items() if m.enabled]

    @property
    def any_enabled(self) -> bool:
        return bool(self.enabled_methods())
