"""Functional compression transforms (jittable, traced-schedule friendly).

Role parity with the reference's ``compression/basic_layer.py`` layer
machinery (LinearLayer_Compress and friends): the reference mutates wrapped
modules; here every technique is a pure function on a weight (or a mask),
applied to the param pytree inside the jitted step, so the schedule (bits,
ratios) can be *traced* values and advance without recompilation.

- ``fake_quantize``: symmetric per-group fake quantization with a
  straight-through estimator (QAT; reference weight_quantization path).
- ``quantize_activation``: same math for activations.
- ``magnitude_prune_mask`` / ``row_prune_mask`` / ``head_prune_mask`` /
  ``channel_prune_mask``: unstructured and structured pruning masks by
  magnitude (reference sparse/row/head/channel pruning).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _ste(x, fx):
    """Straight-through estimator: forward fx, gradient of identity."""
    return x + jax.lax.stop_gradient(fx - x)


def fake_quantize(w, bits, groups: int = 1):
    """Symmetric per-group fake quant, STE gradients. ``bits`` may be a
    traced scalar (the annealing schedule runs inside jit)."""
    bits = jnp.asarray(bits, jnp.float32)
    n = jnp.maximum(2.0 ** (bits - 1.0) - 1.0, 1.0)
    flat = w.reshape(groups, -1).astype(jnp.float32)
    scale = jnp.max(jnp.abs(flat), axis=1, keepdims=True) / n
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(flat / scale) * scale
    return _ste(w, q.reshape(w.shape).astype(w.dtype))


def quantize_activation(x, bits, groups: int = 1):
    """Activation fake quant (reference activation_quantization); no STE
    needed for the value path but kept for symmetric gradients."""
    return fake_quantize(x, bits, groups)


def magnitude_prune_mask(w, ratio):
    """Zero the lowest-|w| ``ratio`` fraction (unstructured sparse pruning).
    ``ratio`` may be traced."""
    flat = jnp.abs(w.reshape(-1).astype(jnp.float32))
    thresh = jnp.quantile(flat, jnp.clip(ratio, 0.0, 1.0))
    return (jnp.abs(w) > thresh.astype(w.dtype)).astype(w.dtype)


def row_prune_mask(w, ratio):
    """Zero whole output rows by L1 norm (reference row_pruning; w is
    [in, out] here, rows = output features)."""
    norms = jnp.sum(jnp.abs(w.astype(jnp.float32)), axis=0)
    thresh = jnp.quantile(norms, jnp.clip(ratio, 0.0, 1.0))
    return (norms > thresh).astype(w.dtype)[None, :]


def channel_prune_mask(w, ratio):
    """Zero input channels by L1 norm (reference channel_pruning)."""
    norms = jnp.sum(jnp.abs(w.astype(jnp.float32)), axis=-1)
    thresh = jnp.quantile(norms, jnp.clip(ratio, 0.0, 1.0))
    return (norms > thresh).astype(w.dtype)[..., None]


def head_prune_mask(w, ratio, num_heads: int):
    """Zero whole attention heads of an output projection
    ``[H*Dh, out]`` by L1 norm (reference head_pruning on attn.dense)."""
    hd = w.shape[0] // num_heads
    norms = jnp.sum(jnp.abs(w.reshape(num_heads, hd, -1).astype(jnp.float32)),
                    axis=(1, 2))
    thresh = jnp.quantile(norms, jnp.clip(ratio, 0.0, 1.0))
    keep = (norms > thresh).astype(w.dtype)
    return jnp.repeat(keep, hd)[:, None]
