"""Compression-aware training (reference ``deepspeed/compression/``):
scheduled quantization-aware training + structured/unstructured pruning,
applied functionally to the param pytree inside the jitted step.
"""

from deepspeed_tpu.compression.config import CompressionConfig
from deepspeed_tpu.compression.functional import (
    fake_quantize,
    head_prune_mask,
    magnitude_prune_mask,
    quantize_activation,
    row_prune_mask,
)
from deepspeed_tpu.compression.scheduler import CompressionScheduler

__all__ = [
    "CompressionConfig",
    "CompressionScheduler",
    "fake_quantize",
    "quantize_activation",
    "magnitude_prune_mask",
    "row_prune_mask",
    "head_prune_mask",
]
