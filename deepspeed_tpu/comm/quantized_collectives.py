"""Quantized collectives: int8 payloads on the wire, error feedback kept.

Role parity with the reference's communication reducers:
- ZeRO++ qgZ ``all_to_all_quant_reduce`` and LOCO variant
  (``runtime/comm/coalesced_collectives.py:31,81``): quantize -> all-to-all of
  the int8 chunks -> local dequant+reduce -> requantize -> all-gather -> dequant,
  with the second-stage (owner-segment) error fed back LOCO-style.
- 1-bit / compressed allreduce backends (``runtime/comm/nccl.py:17``,
  ``compressed.py:14``): rank-local error feedback so quantization bias
  vanishes over steps.

TPU-native expression: the whole reducer runs inside ``shard_map`` and the
``lax.all_to_all`` / ``all_gather`` operands ARE the int8 payload plus the
small fp32 per-block scale vectors — wire bytes drop ~4x vs an fp32 ring
allreduce (the HLO-level test asserts the collective operand dtype is s8).
Intended for the bandwidth-poor axis (DCN between slices — the TPU analog of
the reference's inter-node links).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.quantizer import dequantize, quantize


def _pad_to(flat: jnp.ndarray, multiple: int) -> jnp.ndarray:
    pad = (-flat.size) % multiple
    return jnp.pad(flat, (0, pad)) if pad else flat


def quantized_all_reduce(x, axis_name: str, error=None, bits: int = 8,
                         block: int = 64):
    """Mean-allreduce of rank-local ``x`` over ``axis_name`` with int8 wire
    payloads (call inside ``shard_map``).

    Returns ``(mean, new_error)``. ``error`` is this rank's residual from the
    previous call (same shape as ``x``); the first-stage quantization error
    stays local, and the owner-segment second-stage error is re-injected
    scaled by the axis size (LOCO) so the *mean* converges.
    """
    if bits != 8:
        raise NotImplementedError(
            "quantized_all_reduce supports bits=8 only (int4 payloads are "
            "nibble-packed by the quantizer, incompatible with this reducer's "
            "inline dequantization layout)"
        )
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    shape = x.shape
    xf = x.astype(jnp.float32)
    if error is not None:
        xf = xf + error.astype(jnp.float32)

    flat = _pad_to(xf.reshape(-1), n * block)
    chunk = flat.size // n
    chunks = flat.reshape(n, chunk)

    # stage 1: quantize per chunk; all-to-all the int8 payload + scales
    qt = quantize(chunks, bits=bits, block=block)
    e1 = flat - dequantize(qt).reshape(-1)
    v = qt.values.reshape(n, -1)                      # int8 [n, chunk_bytes]
    s = qt.scales.reshape(n, -1)                      # f32  [n, chunk//block]
    v_recv = lax.all_to_all(v, axis_name, split_axis=0, concat_axis=0)
    s_recv = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)

    # local dequant + reduce of my segment
    blocks = v_recv.reshape(-1, block).astype(jnp.float32)
    scales = s_recv.reshape(-1)
    seg = (blocks * scales[:, None]).reshape(n, chunk).sum(axis=0) / n

    # stage 2: requantize my reduced segment; all-gather int8
    qt2 = quantize(seg, bits=bits, block=block)
    e2 = seg - dequantize(qt2).reshape(-1)[:chunk]
    v2 = lax.all_gather(qt2.values.reshape(-1), axis_name)   # int8 [n, ...]
    s2 = lax.all_gather(qt2.scales, axis_name)
    out_blocks = v2.reshape(-1, block).astype(jnp.float32)
    out = (out_blocks * s2.reshape(-1)[:, None]).reshape(-1)[: flat.size]
    mean = out[: xf.size].reshape(shape)

    # error feedback: my own stage-1 residuals (for every destination chunk)
    # plus my owner-segment stage-2 residual scaled back to sum space
    seg_err = lax.dynamic_update_slice(
        jnp.zeros_like(flat), e2 * n, (my * chunk,))
    new_error = (e1 + seg_err)[: xf.size].reshape(shape)
    return mean.astype(x.dtype), new_error.astype(jnp.float32)


def loco_quantized_all_reduce(x, axis_name: str, error_local=None,
                              error_server=None, bits: int = 8,
                              block: int = 64):
    """LOCO variant (reference ``coalesced_collectives.py:81``
    ``loco_all_to_all_quant_reduce``): like :func:`quantized_all_reduce` but
    the OWNER-side (second-stage) residual persists in its own buffer that
    compensates the *next* window's reduced segment, instead of being folded
    back into the sender-side residual. Keeping the two error sinks separate
    lets each converge at its own stage's statistics — the property LOCO adds
    over plain error feedback.

    Returns ``(mean, new_error_local, new_error_server)``. ``error_server``
    has the owner-segment shape: ``ceil(x.size / n)`` padded elements.
    """
    if bits != 8:
        raise NotImplementedError("loco_quantized_all_reduce supports bits=8 only")
    n = lax.axis_size(axis_name)
    shape = x.shape
    xf = x.astype(jnp.float32)
    if error_local is not None:
        xf = xf + error_local.astype(jnp.float32)

    flat = _pad_to(xf.reshape(-1), n * block)
    chunk = flat.size // n
    chunks = flat.reshape(n, chunk)

    # stage 1: quantize per destination chunk; all-to-all int8 + scales;
    # sender keeps its own residual (for every destination)
    qt = quantize(chunks, bits=bits, block=block)
    e1 = flat - dequantize(qt).reshape(-1)
    v = qt.values.reshape(n, -1)
    s = qt.scales.reshape(n, -1)
    v_recv = lax.all_to_all(v, axis_name, split_axis=0, concat_axis=0)
    s_recv = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)

    blocks = v_recv.reshape(-1, block).astype(jnp.float32)
    scales = s_recv.reshape(-1)
    seg = (blocks * scales[:, None]).reshape(n, chunk).sum(axis=0) / n
    # owner-side compensation: inject the PREVIOUS window's stage-2 residual
    if error_server is not None:
        seg = seg + error_server.astype(jnp.float32)

    # stage 2: requantize the compensated segment; residual stays owner-side
    qt2 = quantize(seg, bits=bits, block=block)
    new_es = seg - dequantize(qt2).reshape(-1)[:chunk]
    v2 = lax.all_gather(qt2.values.reshape(-1), axis_name)
    s2 = lax.all_gather(qt2.scales, axis_name)
    out_blocks = v2.reshape(-1, block).astype(jnp.float32)
    out = (out_blocks * s2.reshape(-1)[:, None]).reshape(-1)[: flat.size]
    mean = out[: xf.size].reshape(shape)

    new_el = e1[: xf.size].reshape(shape)
    return (mean.astype(x.dtype), new_el.astype(jnp.float32),
            new_es.astype(jnp.float32))


def loco_quantized_all_reduce_arrays(x, error_local, error_server, mesh,
                                     axis_name: str, bits: int = 8,
                                     block: int = 64):
    """Array-level wrapper for :func:`loco_quantized_all_reduce` (leading
    axis of size ``n`` sharded over ``axis_name``; the server residual is
    per-owner-segment, also leading-axis sharded)."""
    spec = P(axis_name)

    def body(xs, el, es):
        mean, nel, nes = loco_quantized_all_reduce(
            xs[0], axis_name, el[0], es[0], bits=bits, block=block)
        return mean[None], nel[None], nes[None]

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(P(None), spec, spec),
        axis_names={axis_name}, check_vma=False,
    )(x, error_local, error_server)


def quantized_all_reduce_arrays(x, error, mesh, axis_name: str,
                                bits: int = 8, block: int = 64):
    """Array-level wrapper for rank-varying inputs outside ``shard_map``:
    ``x``/``error`` carry a leading axis of size ``n`` sharded over
    ``axis_name`` (each rank's local contribution / residual)."""
    spec_x = P(axis_name)

    def body(xs, es):
        mean, new_e = quantized_all_reduce(
            xs[0], axis_name, es[0], bits=bits, block=block)
        return mean[None], new_e[None]

    out_mean_spec = P(None)
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec_x, spec_x),
        out_specs=(out_mean_spec, spec_x),
        axis_names={axis_name}, check_vma=False,
    )(x, error)
