"""Quantized collectives: int8 payloads on the wire, error feedback kept.

Role parity with the reference's communication reducers:
- ZeRO++ qgZ ``all_to_all_quant_reduce`` and LOCO variant
  (``runtime/comm/coalesced_collectives.py:31,81``): quantize -> all-to-all of
  the int8 chunks -> local dequant+reduce -> requantize -> all-gather -> dequant,
  with the second-stage (owner-segment) error fed back LOCO-style.
- 1-bit / compressed allreduce backends (``runtime/comm/nccl.py:17``,
  ``compressed.py:14``): rank-local error feedback so quantization bias
  vanishes over steps.

TPU-native expression: the whole reducer runs inside ``shard_map`` and the
``lax.all_to_all`` / ``all_gather`` operands ARE the int8 payload plus the
small fp32 per-block scale vectors — wire bytes drop ~4x vs an fp32 ring
allreduce (the HLO-level test asserts the collective operand dtype is s8).
Intended for the bandwidth-poor axis (DCN between slices — the TPU analog of
the reference's inter-node links).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.quantizer import (
    dequantize,
    dequantize_signs,
    quantize,
    quantize_signs,
)
from deepspeed_tpu.utils.compat import axis_size_compat, shard_map_compat

SUPPORTED_WIRE_BITS = (1, 4, 8)


def _pad_to(flat: jnp.ndarray, multiple: int) -> jnp.ndarray:
    pad = (-flat.size) % multiple
    return jnp.pad(flat, (0, pad)) if pad else flat


def _check_bits(bits: int) -> None:
    if bits not in SUPPORTED_WIRE_BITS:
        raise NotImplementedError(
            f"quantized collectives support bits in {SUPPORTED_WIRE_BITS}, "
            f"got {bits}")


def _wire_encode(rows: jnp.ndarray, bits: int, block: int):
    """[n, chunk] fp32 -> (wire payload [n, B], scales [n, S], dequantized
    round-trip [n, chunk]). The payload rows ARE what crosses the wire:
    uint8 sign-bytes (1-bit, B = chunk/8), nibble-packed int8 (4-bit,
    B = chunk/2) or int8 (8-bit, B = chunk)."""
    n, chunk = rows.shape
    if bits == 1:
        packed, scales = quantize_signs(rows, block)
        deq = dequantize_signs(packed, scales, rows.size, block).reshape(
            n, chunk)
        return packed.reshape(n, -1), scales.reshape(n, -1), deq
    qt = quantize(rows, bits=bits, block=block)
    deq = dequantize(qt).reshape(n, chunk)
    return qt.values.reshape(n, -1), qt.scales.reshape(n, -1), deq


def _wire_decode(vals: jnp.ndarray, scales: jnp.ndarray, bits: int,
                 block: int, n: int, chunk: int) -> jnp.ndarray:
    """Inverse of :func:`_wire_encode` -> fp32 [n, chunk]."""
    if bits == 1:
        return dequantize_signs(vals.reshape(-1), scales.reshape(-1),
                                n * chunk, block).reshape(n, chunk)
    from deepspeed_tpu.ops.quantizer import QuantizedTensor

    qt = QuantizedTensor(values=vals.reshape(-1, block if bits == 8
                                             else block // 2),
                         scales=scales.reshape(-1), shape=(n, chunk),
                         bits=bits, block=block)
    return dequantize(qt).reshape(n, chunk)


def quantized_all_reduce(x, axis_name: str, error=None, bits: int = 8,
                         block: int = 64):
    """Mean-allreduce of rank-local ``x`` over ``axis_name`` with a low-bit
    wire payload — 1-bit sign+scale (the reference compressed/1-bit
    allreduce, ``runtime/comm/nccl.py:17`` + ``csrc/quantization/
    quant_reduce.cu``), nibble-packed int4, or int8 (call inside
    ``shard_map``).

    Returns ``(mean, new_error)``. ``error`` is this rank's residual from the
    previous call (same shape as ``x``); the first-stage quantization error
    stays local, and the owner-segment second-stage error is re-injected
    scaled by the axis size so the *mean* converges.
    """
    _check_bits(bits)
    n = axis_size_compat(axis_name)
    my = lax.axis_index(axis_name)
    shape = x.shape
    xf = x.astype(jnp.float32)
    if error is not None:
        xf = xf + error.astype(jnp.float32)

    flat = _pad_to(xf.reshape(-1), n * block)
    chunk = flat.size // n
    chunks = flat.reshape(n, chunk)

    # stage 1: quantize per chunk; all-to-all the packed payload + scales
    v, s, deq = _wire_encode(chunks, bits, block)
    e1 = (chunks - deq).reshape(-1)
    v_recv = lax.all_to_all(v, axis_name, split_axis=0, concat_axis=0)
    s_recv = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)

    # local dequant + reduce of my segment
    seg = _wire_decode(v_recv, s_recv, bits, block, n, chunk).sum(axis=0) / n

    # stage 2: requantize my reduced segment; all-gather the packed payload
    v2, s2, deq2 = _wire_encode(seg[None], bits, block)
    e2 = seg - deq2[0]
    v2g = lax.all_gather(v2.reshape(-1), axis_name)
    s2g = lax.all_gather(s2.reshape(-1), axis_name)
    out = _wire_decode(v2g, s2g, bits, block, n, chunk).reshape(-1)
    mean = out[: xf.size].reshape(shape)

    # error feedback: my own stage-1 residuals (for every destination chunk)
    # plus my owner-segment stage-2 residual scaled back to sum space
    seg_err = lax.dynamic_update_slice(
        jnp.zeros_like(flat), e2 * n, (my * chunk,))
    new_error = (e1 + seg_err)[: xf.size].reshape(shape)
    return mean.astype(x.dtype), new_error.astype(jnp.float32)


def loco_quantized_all_reduce(x, axis_name: str, error_local=None,
                              error_server=None, bits: int = 8,
                              block: int = 64):
    """LOCO variant (reference ``coalesced_collectives.py:81``
    ``loco_all_to_all_quant_reduce``): like :func:`quantized_all_reduce` but
    the OWNER-side (second-stage) residual persists in its own buffer that
    compensates the *next* window's reduced segment, instead of being folded
    back into the sender-side residual. Keeping the two error sinks separate
    lets each converge at its own stage's statistics — the property LOCO adds
    over plain error feedback.

    Returns ``(mean, new_error_local, new_error_server)``. ``error_server``
    has the owner-segment shape: ``ceil(x.size / n)`` padded elements.
    """
    _check_bits(bits)
    n = axis_size_compat(axis_name)
    shape = x.shape
    xf = x.astype(jnp.float32)
    if error_local is not None:
        xf = xf + error_local.astype(jnp.float32)

    flat = _pad_to(xf.reshape(-1), n * block)
    chunk = flat.size // n
    chunks = flat.reshape(n, chunk)

    # stage 1: quantize per destination chunk; all-to-all payload + scales;
    # sender keeps its own residual (for every destination)
    v, s, deq = _wire_encode(chunks, bits, block)
    e1 = (chunks - deq).reshape(-1)
    v_recv = lax.all_to_all(v, axis_name, split_axis=0, concat_axis=0)
    s_recv = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)

    seg = _wire_decode(v_recv, s_recv, bits, block, n, chunk).sum(axis=0) / n
    # owner-side compensation: inject the PREVIOUS window's stage-2 residual
    if error_server is not None:
        seg = seg + error_server.astype(jnp.float32)

    # stage 2: requantize the compensated segment; residual stays owner-side
    v2, s2, deq2 = _wire_encode(seg[None], bits, block)
    new_es = seg - deq2[0]
    v2g = lax.all_gather(v2.reshape(-1), axis_name)
    s2g = lax.all_gather(s2.reshape(-1), axis_name)
    out = _wire_decode(v2g, s2g, bits, block, n, chunk).reshape(-1)
    mean = out[: xf.size].reshape(shape)

    new_el = e1[: xf.size].reshape(shape)
    return (mean.astype(x.dtype), new_el.astype(jnp.float32),
            new_es.astype(jnp.float32))


def loco_quantized_all_reduce_arrays(x, error_local, error_server, mesh,
                                     axis_name: str, bits: int = 8,
                                     block: int = 64):
    """Array-level wrapper for :func:`loco_quantized_all_reduce` (leading
    axis of size ``n`` sharded over ``axis_name``; the server residual is
    per-owner-segment, also leading-axis sharded)."""
    spec = P(axis_name)

    def body(xs, el, es):
        mean, nel, nes = loco_quantized_all_reduce(
            xs[0], axis_name, el[0], es[0], bits=bits, block=block)
        return mean[None], nel[None], nes[None]

    return shard_map_compat(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(P(None), spec, spec),
        axis_names={axis_name}, check_vma=False,
    )(x, error_local, error_server)


def quantized_all_reduce_arrays(x, error, mesh, axis_name: str,
                                bits: int = 8, block: int = 64):
    """Array-level wrapper for rank-varying inputs outside ``shard_map``:
    ``x``/``error`` carry a leading axis of size ``n`` sharded over
    ``axis_name`` (each rank's local contribution / residual)."""
    spec_x = P(axis_name)

    def body(xs, es):
        mean, new_e = quantized_all_reduce(
            xs[0], axis_name, es[0], bits=bits, block=block)
        return mean[None], new_e[None]

    out_mean_spec = P(None)
    return shard_map_compat(
        body, mesh=mesh,
        in_specs=(spec_x, spec_x),
        out_specs=(out_mean_spec, spec_x),
        axis_names={axis_name}, check_vma=False,
    )(x, error)
