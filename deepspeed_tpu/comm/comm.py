"""Collective-communication facade.

Role parity with ``deepspeed/comm/comm.py`` (module API: ``init_distributed:792``,
``all_reduce:645``, ``all_gather_into_tensor:314``, ``reduce_scatter_tensor:297``,
``all_to_all_single:348``, ``barrier``, all wrapped by ``timed_op:106``).

TPU-native design: two families of collectives.

1. **Mesh collectives** — used *inside* jitted/shard_mapped step functions; thin
   wrappers over ``jax.lax`` named-axis primitives (``psum``, ``all_gather``,
   ``psum_scatter``, ``all_to_all``, ``ppermute``). XLA compiles these onto
   ICI/DCN. The wrappers record the static comms plan into ``CommsLogger``.
2. **Host collectives** — eager, process-level operations used by the control
   plane (rendezvous, barriers, broadcast of config/checkpoint tags), built on
   ``jax.experimental.multihost_utils``. These are timed for real.

``init_distributed`` performs multi-host rendezvous (``jax.distributed``) and
builds the global mesh topology.
"""

from __future__ import annotations

import functools
import os
import time

import numpy as np

from deepspeed_tpu.comm import topology as topo_mod
from deepspeed_tpu.comm.topology import MeshTopology, get_topology, set_topology, topology_initialized
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.utils.comms_logging import COMMS_LOGGER, get_caller_func
from deepspeed_tpu.utils.logging import log_dist


# --------------------------------------------------------------------------- init
def init_distributed(
    mesh_config: MeshConfig | None = None,
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    devices: list | None = None,
) -> MeshTopology:
    """Rendezvous (multi-host) + build the named mesh.

    Reference flow: ``deepspeed.init_distributed`` -> ``torch.distributed.init_process_group``.
    Here: ``jax.distributed.initialize`` (only when a coordinator is configured or
    discoverable from env) -> ``MeshTopology.build``.
    """
    import jax

    if coordinator_address or os.environ.get("DSTPU_COORDINATOR"):
        # launcher-provided rendezvous env (launcher/runner.py build_node_cmd)
        if num_processes is None and os.environ.get("DSTPU_NUM_PROCESSES"):
            num_processes = int(os.environ["DSTPU_NUM_PROCESSES"])
        if process_id is None and os.environ.get("DSTPU_PROCESS_ID"):
            process_id = int(os.environ["DSTPU_PROCESS_ID"])
        jax.distributed.initialize(
            coordinator_address=coordinator_address or os.environ.get("DSTPU_COORDINATOR"),
            num_processes=num_processes,
            process_id=process_id,
        )
        log_dist(
            f"jax.distributed initialized: process {jax.process_index()}/{jax.process_count()}",
            ranks=[-1],
        )
    topo = MeshTopology.build(mesh_config or MeshConfig(), devices=devices)
    set_topology(topo)
    return topo


def is_initialized() -> bool:
    return topology_initialized()


def get_world_size() -> int:
    return get_topology().world_size


def get_rank() -> int:
    import jax

    return jax.process_index()


def get_mesh():
    return get_topology().mesh


# --------------------------------------------------------------- mesh collectives
def _axis_size(axis) -> int:
    from jax import lax

    try:
        if isinstance(axis, (tuple, list)):
            import math

            return math.prod(lax.axis_size(a) for a in axis)
        return lax.axis_size(axis)
    except Exception:
        if topology_initialized():
            if isinstance(axis, (tuple, list)):
                import math

                return math.prod(get_topology().size(a) for a in axis)
            return get_topology().size(axis)
        return 1


def _nbytes(x) -> int:
    import jax.numpy as jnp

    aval = jnp.shape(x), jnp.result_type(x)
    size = int(np.prod(aval[0])) if aval[0] else 1
    return size * jnp.dtype(aval[1]).itemsize


def _traced_op(op_name: str):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(x, axis, *args, **kw):
            COMMS_LOGGER.append_traced(
                op_name, _nbytes(x), str(axis), _axis_size(axis), caller=get_caller_func()
            )
            return fn(x, axis, *args, **kw)

        return wrapper

    return deco


@_traced_op("all_reduce")
def all_reduce(x, axis, op: str = "sum"):
    """Reference ``all_reduce:645``. op in {sum, mean, max, min}."""
    from jax import lax

    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


@_traced_op("all_gather")
def all_gather(x, axis, gather_dim: int = 0, tiled: bool = True):
    """Reference ``all_gather_into_tensor:314`` (concatenating gather)."""
    from jax import lax

    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


@_traced_op("reduce_scatter")
def reduce_scatter(x, axis, scatter_dim: int = 0):
    """Reference ``reduce_scatter_tensor:297``: sum-reduce then shard along dim."""
    from jax import lax

    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


@_traced_op("all_to_all")
def all_to_all(x, axis, split_dim: int, concat_dim: int, tiled: bool = True):
    """Reference ``all_to_all_single:348``; the Ulysses workhorse."""
    from jax import lax

    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=tiled)


@_traced_op("ppermute")
def ppermute(x, axis, perm: list):
    """Neighbor exchange (pipeline stage send/recv, ring collectives)."""
    from jax import lax

    return lax.ppermute(x, axis, perm)


def ring_shift(x, axis, shift: int = 1):
    """Convenience: rotate shards by ``shift`` along a ring on ``axis``."""
    n = _axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return ppermute(x, axis, perm=perm)


@_traced_op("broadcast")
def broadcast_in_mesh(x, axis, src_index: int = 0):
    """Broadcast the ``src_index`` shard to all ranks on ``axis``."""
    from jax import lax

    full = lax.all_gather(x, axis, axis=0, tiled=False)
    return lax.index_in_dim(full, src_index, axis=0, keepdims=False)


def axis_index(axis):
    from jax import lax

    return lax.axis_index(axis)


# --------------------------------------------------------------- host collectives
def _timed_host(op_name: str, size_bytes: int, fn):
    import jax

    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out) if out is not None else None
    COMMS_LOGGER.append_eager(op_name, size_bytes, time.perf_counter() - t0,
                              n_ranks=jax.process_count())
    return out


def barrier(name: str = "barrier") -> None:
    """Process-level barrier (reference ``comm.py barrier``)."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    _timed_host("barrier", 0, lambda: multihost_utils.sync_global_devices(name))


def host_broadcast(value: np.ndarray, is_source: bool | None = None):
    """Broadcast host data from process 0 to all (reference ``broadcast``)."""
    import jax

    if jax.process_count() <= 1:
        return value
    from jax.experimental import multihost_utils

    return _timed_host(
        "broadcast",
        int(np.asarray(value).nbytes),
        lambda: multihost_utils.broadcast_one_to_all(value, is_source=is_source),
    )


def host_allgather(value: np.ndarray):
    import jax

    if jax.process_count() <= 1:
        return np.asarray(value)[None]
    from jax.experimental import multihost_utils

    return _timed_host(
        "all_gather", int(np.asarray(value).nbytes), lambda: multihost_utils.process_allgather(value)
    )


def configure(comms_config) -> None:
    """Wire the comms logger config (reference ``dist.configure``)."""
    COMMS_LOGGER.configure(comms_config)


def log_summary(show_straggler: bool = False) -> str:
    text = COMMS_LOGGER.log_summary(show_straggler=show_straggler)
    # the rendered summary also lands in the telemetry event log, so a run's
    # JSONL record carries the same table the console printed (the per-op
    # counters are already live in the registry via the ledger bridge)
    from deepspeed_tpu.telemetry import TELEMETRY

    if TELEMETRY.enabled:
        TELEMETRY.event("comm/summary", text=text)
    return text
