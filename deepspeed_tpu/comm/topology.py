"""Named device-mesh topology.

Role parity with the reference's ``deepspeed/utils/groups.py`` (DP/TP/PP/EP/SP
process-group factory, built once and cached) — re-expressed the TPU-native way:
ONE global ``jax.sharding.Mesh`` with named axes, built once from ``MeshConfig``.
Where the reference hands out ``ProcessGroup`` objects
(``_create_model_parallel:255``, ``_get_expert_parallel_ranks:472``), we hand out
axis *names*; XLA lowers collectives over an axis to ICI rings (or DCN when the
axis is declared inter-slice).

Axis semantics:
  data      pure data parallel (batch split, grads averaged)
  fsdp      ZeRO axis (batch split AND param/grad/opt-state sharding)
  tensor    tensor (model) parallel
  sequence  Ulysses/ring sequence parallel (batch's sequence dim split)
  expert    MoE expert parallel; expert-parallel groups live inside data*fsdp
  pipeline  pipeline stages
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.utils.logging import log_dist

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "sequence"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipeline"
ALL_AXES = (AXIS_PIPE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR)
# Axes whose ranks consume distinct batch elements (the "DP world" of the batch
# triangle). sequence splits within a batch element, tensor/pipeline replicate
# it. expert is included: EP groups live inside the DP world (reference
# ``utils/groups.py:304 _create_expert_and_data_parallel``), so expert ranks
# consume distinct batch shards and exchange tokens at MoE layers.
BATCH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)


def batch_partition_axes(mesh) -> tuple:
    """Active batch axes of a live Mesh (size > 1), for PartitionSpecs."""
    return tuple(a for a in BATCH_AXES if mesh.shape.get(a, 1) > 1)


def batch_spec_entry(mesh):
    """The dim-0 entry of a batch PartitionSpec for this mesh."""
    axes = batch_partition_axes(mesh)
    if not axes:
        return None
    return axes if len(axes) > 1 else axes[0]


@dataclass
class MeshTopology:
    """Resolved topology + the live Mesh."""

    mesh: "object"  # jax.sharding.Mesh
    sizes: dict

    @classmethod
    def build(cls, cfg: MeshConfig, devices: list | None = None) -> "MeshTopology":
        import jax
        from jax.experimental import mesh_utils

        devices = devices if devices is not None else jax.devices()
        n = len(devices)
        sizes = {
            AXIS_DATA: cfg.data,
            AXIS_FSDP: cfg.fsdp,
            AXIS_TENSOR: cfg.tensor,
            AXIS_SEQ: cfg.sequence,
            AXIS_EXPERT: cfg.expert,
            AXIS_PIPE: cfg.pipeline,
        }
        fixed = math.prod(v for v in sizes.values() if v > 0)
        if sizes[AXIS_DATA] == -1:
            rest = math.prod(sizes[a] for a in ALL_AXES if a != AXIS_DATA)
            if n % rest:
                raise ValueError(
                    f"{n} devices not divisible by non-data axes product {rest} ({sizes})"
                )
            sizes[AXIS_DATA] = n // rest
        elif fixed != n:
            raise ValueError(f"Mesh axes product {fixed} != device count {n} ({sizes})")

        # Physical layout: axis order chosen so the most bandwidth-hungry axes
        # (tensor, then sequence/expert/fsdp) map to the innermost/fastest links.
        axis_order = list(ALL_AXES)
        shape = [sizes[a] for a in axis_order]
        if cfg.dcn_axes:
            dcn_shape = [sizes[a] if a in cfg.dcn_axes else 1 for a in axis_order]
            ici_shape = [1 if a in cfg.dcn_axes else sizes[a] for a in axis_order]
            device_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices, allow_split_physical_axes=True
            )
        else:
            try:
                device_array = mesh_utils.create_device_mesh(
                    shape, devices=devices, allow_split_physical_axes=True
                )
            except (ValueError, AssertionError, NotImplementedError):
                device_array = np.asarray(devices).reshape(shape)
        mesh = jax.sharding.Mesh(device_array, axis_order)
        topo = cls(mesh=mesh, sizes=sizes)
        log_dist(f"Mesh built: {topo.describe()}", ranks=[0])
        return topo

    # ------------------------------------------------------------ accessors
    def size(self, axis: str) -> int:
        return self.sizes[axis]

    @property
    def world_size(self) -> int:
        return math.prod(self.sizes.values())

    @property
    def dp_world_size(self) -> int:
        """Ranks consuming distinct batch elements (data * fsdp)."""
        return math.prod(self.sizes[a] for a in BATCH_AXES)

    @property
    def batch_axes(self) -> tuple:
        return tuple(a for a in BATCH_AXES if self.sizes[a] > 1) or (AXIS_DATA,)

    @property
    def model_axes(self) -> tuple:
        return tuple(
            a for a in (AXIS_TENSOR, AXIS_SEQ, AXIS_PIPE) if self.sizes[a] > 1
        )

    def active_axes(self) -> list:
        return [a for a in ALL_AXES if self.sizes[a] > 1]

    def describe(self) -> str:
        active = {a: s for a, s in self.sizes.items() if s > 1} or {AXIS_DATA: 1}
        return f"{self.world_size} devices as {active}"


_topology: MeshTopology | None = None


def set_topology(topo: MeshTopology) -> None:
    global _topology
    _topology = topo


def get_topology() -> MeshTopology:
    if _topology is None:
        raise RuntimeError("Mesh topology not initialized — call initialize()/init_distributed() first")
    return _topology


def topology_initialized() -> bool:
    return _topology is not None


def reset_topology() -> None:
    """Tear down the process-global topology (test harness API).

    Quiesces the devices first: with async dispatch, work from the previous
    engine can still be in flight on some of the simulated devices, and
    interleaving a new engine's collectives with it can deadlock the CPU
    backend's rendezvous (observed as an idle-CPU futex stall mid-suite on
    the 1-core CI box)."""
    global _topology
    try:
        import jax

        jax.effects_barrier()
    except Exception:
        pass
    # block on every live committed array so all per-device streams drain;
    # per-array guard: a deleted (donated) array raising must not skip the
    # rest of the quiesce
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:
        arrays = []
    for d in arrays:
        try:
            d.block_until_ready()
        except Exception:
            pass
    _topology = None
