"""Accelerator implementations and selection.

Role parity with ``accelerator/real_accelerator.py:51`` (``get_accelerator()``):
honors a ``DSTPU_ACCELERATOR`` env override, else probes the JAX backend.
Two concrete backends: TPU (real chips) and CPU (including the
``--xla_force_host_platform_device_count=N`` simulated multi-device mesh used by
tests). GPU-via-JAX also routes through ``TpuAccelerator`` semantics minus
Pallas-TPU kernels.
"""

from __future__ import annotations

import functools
import os

from deepspeed_tpu.accelerator.abstract_accelerator import Accelerator
from deepspeed_tpu.utils.logging import logger


class TpuAccelerator(Accelerator):
    _name = "tpu"

    def communication_backend_name(self) -> str:
        return "xla-ici"

    def device_count(self) -> int:
        import jax

        return jax.local_device_count()

    def global_device_count(self) -> int:
        import jax

        return jax.device_count()

    def devices(self) -> list:
        import jax

        return jax.local_devices()

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def is_fp8_supported(self) -> bool:
        return True

    def supports_pallas(self) -> bool:
        return True

    # core keys plus the allocator-shape extras (fragmentation = reserved
    # minus in-use; largest_free_block bounds the biggest single allocation
    # that can still succeed) — passed through only where the backend
    # reports them
    _STAT_EXTRAS = ("bytes_reserved", "largest_free_block_bytes",
                    "num_allocs", "bytes_reservable_limit")

    def memory_stats(self, device=None) -> dict[str, int]:
        import jax

        device = device or jax.local_devices()[0]
        stats = getattr(device, "memory_stats", lambda: None)() or {}
        out = {
            "bytes_in_use": stats.get("bytes_in_use", 0),
            "bytes_limit": stats.get("bytes_limit", 0),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        }
        for key in self._STAT_EXTRAS:
            if key in stats:
                out[key] = stats[key]
        return out

    def memory_stats_all_devices(self) -> list[dict[str, int]]:
        """Per-local-device stats, index-aligned with ``devices()``."""
        return [self.memory_stats(d) for d in self.devices()]

    def pinned_memory_sharding(self):
        import jax

        try:
            dev = jax.local_devices()[0]
            return jax.sharding.SingleDeviceSharding(dev, memory_kind="pinned_host")
        except Exception:
            return None


class CpuAccelerator(Accelerator):
    _name = "cpu"

    def communication_backend_name(self) -> str:
        return "gloo-sim"

    def device_count(self) -> int:
        import jax

        return jax.local_device_count()

    def global_device_count(self) -> int:
        import jax

        return jax.device_count()

    def devices(self) -> list:
        import jax

        return jax.local_devices()

    def is_bf16_supported(self) -> bool:
        return True  # emulated on host; numerics preserved

    def is_fp16_supported(self) -> bool:
        return True

    def supports_pallas(self) -> bool:
        return False  # Pallas TPU kernels run in interpret mode only

    def memory_stats(self, device=None) -> dict[str, int]:
        try:
            import resource

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            rss = 0
        return {"bytes_in_use": rss, "bytes_limit": 0, "peak_bytes_in_use": rss}

    def memory_stats_all_devices(self) -> list[dict[str, int]]:
        # simulated CPU devices share one host process: one stats row
        return [self.memory_stats()]


_accelerator: Accelerator | None = None


def get_accelerator() -> Accelerator:
    global _accelerator
    if _accelerator is not None:
        return _accelerator
    override = os.environ.get("DSTPU_ACCELERATOR")
    if override:
        _accelerator = {"tpu": TpuAccelerator, "cpu": CpuAccelerator}[override.lower()]()
        logger.info(f"Accelerator selected from DSTPU_ACCELERATOR: {override}")
        return _accelerator
    import jax

    platform = jax.default_backend()
    if platform == "cpu":
        _accelerator = CpuAccelerator()
    else:
        # tpu, axon (tunneled tpu), gpu all get full JAX semantics.
        _accelerator = TpuAccelerator()
        if platform not in ("tpu", "axon"):
            _accelerator._name = platform
    return _accelerator


def set_accelerator(acc: Accelerator) -> None:
    global _accelerator
    _accelerator = acc
