"""Hardware-abstraction interface.

Role parity with the reference's ``accelerator/abstract_accelerator.py:10``
(``DeepSpeedAccelerator`` ABC, ~70 methods). In a JAX world most of the CUDA
surface (streams, events, graph capture) is owned by the XLA runtime, so the
interface shrinks to what callers genuinely vary on: device discovery, dtype
capability probes, memory introspection, RNG, synchronization, profiler ranges,
and the communication-backend name. Kernel lookup (the reference's
``op_builder`` factory, ``abstract_accelerator.py:268-303``) maps to the Pallas
kernel registry in :mod:`deepspeed_tpu.ops`.
"""

from __future__ import annotations

import abc
from typing import Any


class Accelerator(abc.ABC):
    _name: str = "abstract"

    # ------------------------------------------------------------ identity
    def device_name(self) -> str:
        return self._name

    @abc.abstractmethod
    def communication_backend_name(self) -> str: ...

    # ------------------------------------------------------------ devices
    @abc.abstractmethod
    def device_count(self) -> int:
        """Addressable (process-local) device count."""

    @abc.abstractmethod
    def global_device_count(self) -> int: ...

    @abc.abstractmethod
    def devices(self) -> list: ...

    def current_device(self):
        return self.devices()[0]

    # ------------------------------------------------------------ capabilities
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool: ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool: ...

    def is_fp8_supported(self) -> bool:
        return False

    @abc.abstractmethod
    def supports_pallas(self) -> bool:
        """Can compiled Pallas TPU kernels run natively (vs interpret mode)?"""

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.is_bf16_supported() else jnp.float32

    # ------------------------------------------------------------ memory
    @abc.abstractmethod
    def memory_stats(self, device=None) -> dict[str, int]:
        """Returns at least {'bytes_in_use': int, 'bytes_limit': int} when known."""

    def available_memory(self, device=None) -> int:
        stats = self.memory_stats(device)
        return max(stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0), 0)

    def memory_stats_all_devices(self) -> list[dict[str, int]]:
        """Per-local-device stats rows (default: one aggregate row)."""
        return [self.memory_stats()]

    # ------------------------------------------------------------ execution
    def synchronize(self) -> None:
        import jax

        jax.block_until_ready(jax.device_put(0))

    def default_mesh_axis_order(self) -> list[str]:
        """Preferred physical ordering of logical axes (innermost = fastest links)."""
        return ["pipeline", "data", "fsdp", "expert", "sequence", "tensor"]

    # ------------------------------------------------------------ RNG
    def default_rng_impl(self) -> str | None:
        return None

    # ------------------------------------------------------------ profiling
    def range_push(self, name: str) -> Any:
        import jax.profiler

        tc = jax.profiler.TraceAnnotation(name)
        tc.__enter__()
        return tc

    def range_pop(self, ctx: Any) -> None:
        ctx.__exit__(None, None, None)

    # ------------------------------------------------------------ host memory
    def pinned_memory_sharding(self):
        """Sharding placing arrays in pinned host memory, or None if unsupported."""
        return None
