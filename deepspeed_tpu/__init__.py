"""deepspeed_tpu: a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas framework with the capabilities of DeepSpeed
(reference surveyed in SURVEY.md): config-driven training engine, ZeRO-style
sharding over named meshes, offload tiers, TP/PP/EP/SP parallelism, fused Pallas
kernels, comms logging, universal checkpointing, launcher, profilers, and an
inference path.

Top-level API parity (reference ``deepspeed/__init__.py``):
  initialize()       -> (engine, optimizer, dataloader, lr_scheduler)
  init_distributed() -> mesh topology rendezvous
  init_inference()   -> inference engine
"""

__version__ = "0.1.0"

from deepspeed_tpu.comm.comm import init_distributed  # noqa: F401
from deepspeed_tpu.config.config import Config, load_config  # noqa: F401
from deepspeed_tpu.accelerator.real_accelerator import get_accelerator  # noqa: F401
from deepspeed_tpu.models.api import ModelSpec, ShardCtx  # noqa: F401


def initialize(*args, **kwargs):
    """Build the training engine (reference ``deepspeed/__init__.py:93``).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    Thin lazy wrapper so importing the package stays cheap.
    """
    try:
        from deepspeed_tpu.runtime.engine import initialize as _initialize
    except ImportError as e:
        raise NotImplementedError(
            "deepspeed_tpu.runtime.engine is not available in this build yet"
        ) from e
    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    try:
        from deepspeed_tpu.inference.engine import init_inference as _init_inference
    except ImportError as e:
        raise NotImplementedError(
            "deepspeed_tpu.inference.engine is not available in this build yet"
        ) from e
    return _init_inference(*args, **kwargs)
