"""Block-sparse attention: compute only the active key blocks.

Role parity with the reference ``deepspeed/ops/sparse_attention`` (Triton
block-sparse SDD/DSD matmuls + ``SparseSelfAttention``, with the
``SparsityConfig`` pattern zoo: fixed, BigBird, BSLongformer, variable —
``sparsity_config.py``) and its ``csrc/sparse_attention`` helpers.

TPU-native expression: the sparsity LAYOUT is a host-side numpy block mask
``[num_q_blocks, num_k_blocks]`` (static at trace time, like the reference's
layout tensors). Each query block GATHERS only its active key/value blocks
through a padded ``[nq, A]`` index table (A = max active blocks per row), so
compute and memory scale with ``A/nk`` of dense attention — XLA tiles the
resulting block einsums straight onto the MXU, no custom kernel needed. The
dense-equivalent mask semantics are exact (verified against dense attention
under the same mask), including causal filtering inside active blocks.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention import repeat_kv


# ------------------------------------------------------------------ layouts
def _with_diagonal(layout: np.ndarray) -> np.ndarray:
    n = min(layout.shape)
    layout[np.arange(n), np.arange(n)] = True
    return layout


def make_local_layout(num_blocks: int, window: int) -> np.ndarray:
    """Sliding-window: each query block attends its last ``window`` blocks."""
    i = np.arange(num_blocks)[:, None]
    j = np.arange(num_blocks)[None, :]
    return _with_diagonal((j <= i) & (j > i - window))


def make_fixed_layout(num_blocks: int, local_window: int,
                      global_stride: int) -> np.ndarray:
    """Reference ``FixedSparsityConfig``-style: local window + every
    ``global_stride``-th block visible to everyone."""
    layout = make_local_layout(num_blocks, local_window)
    layout[:, ::global_stride] = True
    return _with_diagonal(layout)


def make_bslongformer_layout(num_blocks: int, window: int,
                             num_global: int) -> np.ndarray:
    """Reference ``BSLongformerSparsityConfig``-style: sliding window + the
    first ``num_global`` blocks are global (everyone sees them, they see all)."""
    layout = make_local_layout(num_blocks, window)
    layout[:, :num_global] = True
    layout[:num_global, :] = True
    return _with_diagonal(layout)


@dataclass
class SparsityConfig:
    """Pattern factory (reference ``sparsity_config.py`` family)."""

    mode: str = "fixed"          # fixed | local | bslongformer
    block_size: int = 64
    local_window: int = 4        # blocks
    global_stride: int = 8       # fixed mode
    num_global_blocks: int = 1   # bslongformer mode

    def layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block_size:
            raise ValueError(
                f"seq_len {seq_len} not divisible by block_size {self.block_size}")
        nb = seq_len // self.block_size
        if self.mode == "local":
            return make_local_layout(nb, self.local_window)
        if self.mode == "fixed":
            return make_fixed_layout(nb, self.local_window, self.global_stride)
        if self.mode == "bslongformer":
            return make_bslongformer_layout(nb, self.local_window,
                                            self.num_global_blocks)
        raise ValueError(f"unknown sparsity mode {self.mode!r}")


# ------------------------------------------------------------------ kernel
def _index_table(layout: np.ndarray, causal: bool):
    """Host-side layout -> (active_idx [nq, A], valid [nq, A])."""
    layout = np.asarray(layout, bool).copy()
    if causal:
        nq, nk = layout.shape
        layout &= np.arange(nk)[None, :] <= np.arange(nq)[:, None]
    counts = layout.sum(axis=1)
    a = int(counts.max())
    if a == 0:
        raise ValueError("sparsity layout has an empty row")
    nq = layout.shape[0]
    idx = np.zeros((nq, a), np.int32)
    valid = np.zeros((nq, a), bool)
    for i in range(nq):
        js = np.flatnonzero(layout[i])
        idx[i, : len(js)] = js
        valid[i, : len(js)] = True
    return idx, valid


def blocksparse_attention(q, k, v, layout, block_size: int,
                          causal: bool = True, scale=None):
    """[B, S, H, D] attention computing only the layout's active blocks.

    ``layout``: host numpy bool ``[S/bs, S/bs]`` block mask (see the builders
    above / ``SparsityConfig.layout``). Exactly equals dense attention under
    the equivalent elementwise mask.
    """
    b, s, h, d = q.shape
    bs = block_size
    if s % bs:
        raise ValueError(f"seq {s} not divisible by block_size {bs}")
    nq = s // bs
    if tuple(np.shape(layout)) != (nq, nq):
        raise ValueError(
            f"layout shape {np.shape(layout)} != ({nq}, {nq}) for seq {s}")
    k = repeat_kv(k, h // k.shape[2])
    v = repeat_kv(v, h // v.shape[2])
    scale = scale if scale is not None else 1.0 / np.sqrt(d)

    idx_np, valid_np = _index_table(layout, causal)
    a = idx_np.shape[1]
    idx = jnp.asarray(idx_np)
    valid = jnp.asarray(valid_np)

    qb = (q * scale).astype(jnp.float32).reshape(b, nq, bs, h, d)
    kb = k.astype(jnp.float32).reshape(b, nq, bs, h, d)
    vb = v.astype(jnp.float32).reshape(b, nq, bs, h, d)

    # gather each query block's active K/V blocks: [B, nq, A, bs, H, D]
    kg = kb[:, idx]
    vg = vb[:, idx]

    scores = jnp.einsum("bqthd,bqashd->bhqtas", qb, kg)  # [B,H,nq,bs,A,bs]
    q_pos = jnp.arange(nq)[:, None, None, None] * bs \
        + jnp.arange(bs)[None, :, None, None]
    k_pos = idx[:, None, :, None] * bs + jnp.arange(bs)[None, None, None, :]
    mask = valid[:, None, :, None]
    if causal:
        mask = mask & (k_pos <= q_pos)
    scores = jnp.where(mask[None, None], scores, -1e30)

    flat = scores.reshape(b, h, nq, bs, a * bs)
    p = jax.nn.softmax(flat, axis=-1).reshape(scores.shape)
    out = jnp.einsum("bhqtas,bqashd->bqthd", p, vg)
    return out.reshape(b, s, h, d).astype(q.dtype)


class SparseSelfAttention:
    """Reference ``SparseSelfAttention`` analog: a configured, reusable
    block-sparse attention callable (layout cached per sequence length)."""

    def __init__(self, config: SparsityConfig | None = None, causal: bool = True):
        self.config = config or SparsityConfig()
        self.causal = causal
        self._layouts: dict[int, np.ndarray] = {}

    def __call__(self, q, k, v):
        s = q.shape[1]
        layout = self._layouts.get(s)
        if layout is None:
            layout = self.config.layout(s)
            self._layouts[s] = layout
        return blocksparse_attention(q, k, v, layout, self.config.block_size,
                                     causal=self.causal)
