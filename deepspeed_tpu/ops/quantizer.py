"""Block quantization primitives (int8 / int4, symmetric per-block scales).

Role parity with the reference quantizer kernels
(``csrc/quantization/{quantize,dequantize,quant_reduce,swizzled_quantize}.cu``)
used by ZeRO++ (qwZ quantized weights, qgZ quantized gradient collectives) and
inference WOQ. On TPU these are jnp expressions XLA fuses into surrounding
ops; the int4 packing uses two nibbles per int8 lane.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    values: jnp.ndarray   # int8 payload (int4: packed two-per-byte)
    scales: jnp.ndarray   # f32 per-block scales
    shape: tuple          # original shape
    bits: int             # 8 or 4
    block: int


def _to_blocks(x: jnp.ndarray, block: int):
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, block), pad


def _from_blocks(vals: jnp.ndarray, shape: tuple, dtype) -> jnp.ndarray:
    """Inverse of :func:`_to_blocks`: drop padding, restore shape."""
    flat = vals.reshape(-1)
    size = 1
    for s in shape:
        size *= s
    return flat[:size].reshape(shape).astype(dtype)


def quantize(x: jnp.ndarray, bits: int = 8, block: int = 256) -> QuantizedTensor:
    """Symmetric per-block quantization (reference ``quantize.cu`` semantics)."""
    assert bits in (8, 4), bits
    blocks, _ = _to_blocks(x.astype(jnp.float32), block)
    qmax = 127.0 if bits == 8 else 7.0
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / qmax
    q = jnp.clip(jnp.round(blocks / scale), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        lo = q[:, 0::2] & 0x0F
        hi = (q[:, 1::2] & 0x0F) << 4
        q = (lo | hi).astype(jnp.int8)
    return QuantizedTensor(values=q, scales=scale[:, 0], shape=tuple(x.shape),
                           bits=bits, block=block)


def dequantize(qt: QuantizedTensor, dtype=jnp.float32) -> jnp.ndarray:
    """Reference ``dequantize.cu`` semantics."""
    q = qt.values
    if qt.bits == 4:
        lo = (q << 4).astype(jnp.int8) >> 4          # sign-extend low nibble
        hi = q >> 4                                   # arithmetic shift keeps sign
        q = jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)
    vals = q.astype(jnp.float32) * qt.scales[:, None]
    return _from_blocks(vals, qt.shape, dtype)


def quantize_dequantize(x: jnp.ndarray, bits: int = 8, block: int = 256) -> jnp.ndarray:
    """Fake-quant round trip (reference ``fake_quantizer.cu``; QAT + tests)."""
    return dequantize(quantize(x, bits=bits, block=block), dtype=x.dtype)


def quantization_error(x: jnp.ndarray, bits: int = 8, block: int = 256) -> jnp.ndarray:
    """Residual for error-feedback compression (1-bit Adam family,
    ``runtime/comm/compressed.py`` semantics)."""
    return x - quantize_dequantize(x, bits=bits, block=block)
