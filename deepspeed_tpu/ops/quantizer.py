"""Block quantization primitives (int8 / int4, symmetric per-block scales).

Role parity with the reference quantizer kernels
(``csrc/quantization/{quantize,dequantize,quant_reduce,swizzled_quantize}.cu``)
used by ZeRO++ (qwZ quantized weights, qgZ quantized gradient collectives) and
inference WOQ. On TPU these are jnp expressions XLA fuses into surrounding
ops; the int4 packing uses two nibbles per int8 lane.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    values: jnp.ndarray   # int8 payload (int4: packed two-per-byte)
    scales: jnp.ndarray   # f32 per-block scales
    shape: tuple          # original shape
    bits: int             # 8 or 4
    block: int


def _to_blocks(x: jnp.ndarray, block: int):
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, block), pad


def _from_blocks(vals: jnp.ndarray, shape: tuple, dtype) -> jnp.ndarray:
    """Inverse of :func:`_to_blocks`: drop padding, restore shape."""
    flat = vals.reshape(-1)
    size = 1
    for s in shape:
        size *= s
    return flat[:size].reshape(shape).astype(dtype)


def quantize(x: jnp.ndarray, bits: int = 8, block: int = 256) -> QuantizedTensor:
    """Symmetric per-block quantization (reference ``quantize.cu`` semantics)."""
    assert bits in (8, 4), bits
    blocks, _ = _to_blocks(x.astype(jnp.float32), block)
    qmax = 127.0 if bits == 8 else 7.0
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / qmax
    q = jnp.clip(jnp.round(blocks / scale), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        lo = q[:, 0::2] & 0x0F
        hi = (q[:, 1::2] & 0x0F) << 4
        q = (lo | hi).astype(jnp.int8)
    return QuantizedTensor(values=q, scales=scale[:, 0], shape=tuple(x.shape),
                           bits=bits, block=block)


def dequantize(qt: QuantizedTensor, dtype=jnp.float32) -> jnp.ndarray:
    """Reference ``dequantize.cu`` semantics."""
    q = qt.values
    if qt.bits == 4:
        lo = (q << 4).astype(jnp.int8) >> 4          # sign-extend low nibble
        hi = q >> 4                                   # arithmetic shift keeps sign
        q = jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)
    vals = q.astype(jnp.float32) * qt.scales[:, None]
    return _from_blocks(vals, qt.shape, dtype)


def quantize_signs(x: jnp.ndarray, block: int = 256):
    """1-bit quantization (reference ``compressed_allreduce`` payload,
    ``runtime/comm/nccl.py:17`` / ``csrc/quantization/quant_reduce.cu``):
    sign bits packed 8-per-byte + per-block mean-|x| scales. Returns
    ``(packed uint8 [N/8], scales f32 [N/block])`` over the flattened,
    block-padded input; ``block`` must be a multiple of 8."""
    assert block % 8 == 0, block
    blocks, _ = _to_blocks(x.astype(jnp.float32), block)
    scales = jnp.mean(jnp.abs(blocks), axis=-1)
    bits = (blocks >= 0).astype(jnp.uint8).reshape(-1, 8)
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
    packed = jnp.sum(bits * weights[None, :], axis=-1, dtype=jnp.uint8)
    return packed, scales


def dequantize_signs(packed: jnp.ndarray, scales: jnp.ndarray, size: int,
                     block: int = 256, dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_signs`: ±scale per element, first ``size``
    elements (flat)."""
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
    bits = (packed.reshape(-1, 1) & weights[None, :]) > 0
    signs = jnp.where(bits, 1.0, -1.0).reshape(-1, block)
    vals = signs * scales[:, None]
    return vals.reshape(-1)[:size].astype(dtype)


def quantize_rows(x: jnp.ndarray, block: int = 128):
    """Shape-preserving symmetric int8 quantization with per-block scales
    along the LAST dim: ``x [..., L] -> (q int8 [..., L], scales [..., L/block])``.

    Unlike :func:`quantize` (which flattens), the output dims map 1:1 onto the
    input dims, so a sharded ``x`` quantizes shard-locally whenever the block
    axis isn't split mid-block — the property the ZeRO++ qwZ gather relies on
    (``parallel/qwz.py``; reference ``csrc/quantization/swizzled_quantize.cu``
    quantizes the local partition before the all-gather).
    """
    L = x.shape[-1]
    pad = (-L) % block
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    nb = xf.shape[-1] // block
    blocks = xf.reshape(*xf.shape[:-1], nb, block)
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    scales = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(blocks / scales[..., None]), -127, 127).astype(jnp.int8)
    q = q.reshape(*xf.shape[:-1], nb * block)
    if pad:
        q = q[..., :L]
    return q, scales


def dequantize_rows(q: jnp.ndarray, scales: jnp.ndarray, dtype=jnp.float32,
                    block: int | None = None) -> jnp.ndarray:
    """Inverse of :func:`quantize_rows`. ``block`` must be passed when the
    last dim was padded (it cannot be inferred from the shapes then)."""
    L = q.shape[-1]
    nb = scales.shape[-1]
    if block is None:
        if L % nb:
            raise ValueError(
                f"dequantize_rows: last dim {L} not divisible by {nb} blocks; "
                "pass the block size used at quantization")
        block = L // nb
    pad = nb * block - L
    qf = q.astype(jnp.float32)
    if pad:
        qf = jnp.pad(qf, [(0, 0)] * (q.ndim - 1) + [(0, pad)])
    vals = qf.reshape(*qf.shape[:-1], nb, block) * scales[..., None]
    vals = vals.reshape(*qf.shape[:-1], nb * block)
    if pad:
        vals = vals[..., :L]
    return vals.astype(dtype)


def quantize_dequantize(x: jnp.ndarray, bits: int = 8, block: int = 256) -> jnp.ndarray:
    """Fake-quant round trip (reference ``fake_quantizer.cu``; QAT + tests)."""
    return dequantize(quantize(x, bits=bits, block=block), dtype=x.dtype)


def quantization_error(x: jnp.ndarray, bits: int = 8, block: int = 256) -> jnp.ndarray:
    """Residual for error-feedback compression (1-bit Adam family,
    ``runtime/comm/compressed.py`` semantics)."""
    return x - quantize_dequantize(x, bits=bits, block=block)


# ------------------------------------------------------------------ WOQ params
class QuantizedWeight:
    """A weight stored quantized in a param pytree (weight-only-quant
    inference, reference ``inference/quantization/`` WOQ layers).

    Registered pytree node: (values, scales) are children so the tree flows
    through jit/scan/sharding; (shape, bits, block) are static aux data —
    unlike :class:`QuantizedTensor` (a NamedTuple whose shape ints would be
    traced), reshapes stay static under jit. Stacked layer weights keep a
    leading layer dim on the children; ``shape`` is the PER-LAYER shape, so
    a ``lax.scan`` slice of the tree dequantizes directly.
    """

    def __init__(self, values, scales, shape, bits, block):
        self.values = values
        self.scales = scales
        self.shape = tuple(shape)
        self.bits = int(bits)
        self.block = int(block)

    def tree_flatten(self):
        return (self.values, self.scales), (self.shape, self.bits, self.block)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


jax.tree_util.register_pytree_node(
    QuantizedWeight,
    lambda qw: qw.tree_flatten(),
    QuantizedWeight.tree_unflatten,
)


def maybe_dequantize(w, dtype):
    """Identity on arrays; dequantize on :class:`QuantizedWeight` — model
    code calls this at the point of use so dequantization happens just in
    time, per scanned layer slice (transient, fused by XLA)."""
    if not isinstance(w, QuantizedWeight):
        return w
    qt = QuantizedTensor(values=w.values, scales=w.scales, shape=w.shape,
                         bits=w.bits, block=w.block)
    return dequantize(qt, dtype)


def dequantize_layer(lp: dict, dtype) -> dict:
    """Just-in-time dequantization of a layer's weight dict (no-op on plain
    arrays); model layer fns call this first, so WOQ dense copies are
    per-scanned-layer transients."""
    return {k: maybe_dequantize(v, dtype) for k, v in lp.items()}


def quantize_params(params, bits: int = 8, block: int = 256,
                    skip: tuple = ("embed",), stacked_key: str = "layers"):
    """Quantize the matrix leaves of a param pytree into
    :class:`QuantizedWeight` (weight-only quantization).

    Leaves under ``stacked_key`` carry a leading layer dim: matrices there
    are ndim >= 3 and quantize per layer (so a decoder ``lax.scan`` slices
    the tree naturally); ndim-2 leaves there are stacked *vectors* (norms)
    and stay dense. Outside the stacked subtree, plain ndim-2 matrices
    quantize whole. Leaves whose path contains a name in ``skip`` stay
    dense (embedding gathers want a plain array)."""

    def q(path, leaf):
        names = {str(getattr(k, "key", "")) for k in path}
        stacked = stacked_key in names
        min_ndim = 3 if stacked else 2
        if (not hasattr(leaf, "ndim") or leaf.ndim < min_ndim
                or not jnp.issubdtype(leaf.dtype, jnp.floating)
                or names & set(skip)):
            return leaf
        if stacked:  # per-layer blocks
            def qvs(w):
                qt = quantize(w, bits=bits, block=block)
                return qt.values, qt.scales

            vals, scales = jax.vmap(qvs)(leaf)
            return QuantizedWeight(vals, scales, leaf.shape[1:], bits, block)
        qt = quantize(leaf, bits=bits, block=block)
        return QuantizedWeight(qt.values, qt.scales, qt.shape, bits, block)

    return jax.tree_util.tree_map_with_path(q, params)
