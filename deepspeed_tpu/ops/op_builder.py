"""Native-extension build system: JIT-compile C++ sources into cached .so
libraries loaded via ctypes.

Role parity with the reference ``op_builder/builder.py:116 OpBuilder``
(``jit_load():545``: compile-on-first-use with a content-hashed cache,
capability probes, graceful unavailability). The CUDA arch-flag machinery has
no TPU analog — device kernels are Pallas/XLA — so this builder only compiles
*host* runtime code (AIO, future data loaders), with g++ from the system
toolchain and no torch cpp_extension dependency.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import threading

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CACHE_DIR = os.environ.get(
    "DSTPU_OPS_CACHE", os.path.join(_REPO_ROOT, ".dstpu_ops_cache")
)
_LOCK = threading.Lock()
_LOADED: dict[str, ctypes.CDLL] = {}


class OpBuilder:
    """One builder per native op (reference: one ``op_builder/*.py`` per kernel)."""

    NAME = "base"
    SOURCES: list[str] = []       # repo-relative .cpp paths
    EXTRA_FLAGS: list[str] = []
    EXTRA_LIBS: list[str] = []    # e.g. ["-lpthread"]

    def is_compatible(self) -> bool:
        return shutil.which("g++") is not None

    def absolute_sources(self) -> list[str]:
        return [os.path.join(_REPO_ROOT, s) for s in self.SOURCES]

    def _cache_key(self) -> str:
        h = hashlib.sha256()
        for src in self.absolute_sources():
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.EXTRA_FLAGS + self.EXTRA_LIBS).encode())
        return h.hexdigest()[:16]

    def load(self) -> ctypes.CDLL:
        """Compile (cached) and dlopen (reference ``OpBuilder.load():526``)."""
        with _LOCK:
            if self.NAME in _LOADED:
                return _LOADED[self.NAME]
            if not self.is_compatible():
                raise RuntimeError(f"op {self.NAME}: no C++ toolchain available")
            os.makedirs(_CACHE_DIR, exist_ok=True)
            so_path = os.path.join(_CACHE_DIR, f"{self.NAME}-{self._cache_key()}.so")
            if not os.path.exists(so_path):
                cmd = (
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
                    + self.EXTRA_FLAGS
                    + self.absolute_sources()
                    + ["-o", so_path + ".tmp"]
                    + self.EXTRA_LIBS
                )
                logger.info(f"op {self.NAME}: compiling {' '.join(cmd)}")
                result = subprocess.run(cmd, capture_output=True, text=True)
                if result.returncode != 0:
                    raise RuntimeError(
                        f"op {self.NAME}: compile failed:\n{result.stderr[-2000:]}"
                    )
                os.replace(so_path + ".tmp", so_path)
            lib = ctypes.CDLL(so_path)
            _LOADED[self.NAME] = lib
            return lib


class AsyncIOBuilder(OpBuilder):
    """Reference ``op_builder/async_io.py`` analog (DeepNVMe host engine)."""

    NAME = "dstpu_aio"
    SOURCES = ["csrc/aio/dstpu_aio.cpp"]
    EXTRA_LIBS = ["-lpthread"]

    def load(self) -> ctypes.CDLL:
        lib = super().load()
        lib.dstpu_aio_create.restype = ctypes.c_void_p
        lib.dstpu_aio_create.argtypes = [ctypes.c_int, ctypes.c_uint64]
        lib.dstpu_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_submit_write.restype = ctypes.c_int
        lib.dstpu_aio_submit_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.dstpu_aio_submit_read.restype = ctypes.c_int
        lib.dstpu_aio_submit_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.dstpu_aio_wait.restype = ctypes.c_int64
        lib.dstpu_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dstpu_aio_wait_all.restype = ctypes.c_int64
        lib.dstpu_aio_wait_all.argtypes = [ctypes.c_void_p]
        return lib
