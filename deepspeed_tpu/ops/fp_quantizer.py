"""Floating-point (FP8/FP6/FP4) block quantization.

Role parity with the reference ``csrc/fp_quantizer`` (``fp_quantize.cpp`` /
``fp_quantize_impl.cu`` — FP6-LLM-style weight quantization to low-bit float
grids with per-block scales).

TPU-native expression: FP8 uses the MXU-native ``float8_e4m3fn`` /
``float8_e5m2`` dtypes directly (ml_dtypes); FP6/FP4 have no hardware dtype,
so they quantize onto the exact e3m2 / e2m1 value grid while storing int8
codes — the grid math is sign/exponent/mantissa rounding in pure jnp, so
encode/decode jit and fuse. Per-block absmax scaling matches the reference's
quantization group semantics.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer import _from_blocks, _to_blocks

# (exponent bits, mantissa bits) per format; fp8 formats also have a native dtype
_FORMATS = {
    "fp8_e4m3": (4, 3),
    "fp8_e5m2": (5, 2),
    "fp6_e3m2": (3, 2),
    "fp4_e2m1": (2, 1),
}
_NATIVE = {
    "fp8_e4m3": jnp.float8_e4m3fn,
    "fp8_e5m2": jnp.float8_e5m2,
}


# formats whose top exponent encodes inf/nan (IEEE-style); e4m3fn and the
# emulated fp6/fp4 grids use their full exponent range (finite-only grids)
_IEEE_INF_FORMATS = {"fp8_e5m2"}


def _grid_max(fmt: str) -> float:
    """Largest finite magnitude of the format's (sign, e, m) grid."""
    exp_bits, man_bits = _FORMATS[fmt]
    bias = 2 ** (exp_bits - 1) - 1
    if fmt in _IEEE_INF_FORMATS:
        max_exp = (2 ** exp_bits - 2) - bias          # top binade = inf/nan
        max_man = 2 - 2.0 ** (-man_bits)
    elif fmt == "fp8_e4m3":
        max_exp = (2 ** exp_bits - 1) - bias          # e4m3fn: NaN only at
        max_man = 2 - 2.0 ** (1 - man_bits)           # all-ones mantissa
    else:
        max_exp = (2 ** exp_bits - 1) - bias
        max_man = 2 - 2.0 ** (-man_bits)
    return max_man * 2.0 ** max_exp


class FPQuantizedTensor(NamedTuple):
    values: jnp.ndarray   # native fp8 dtype, or int8 s/e/m bit codes for fp6/fp4
    scales: jnp.ndarray   # f32 per-block scales
    shape: tuple
    fmt: str
    block: int


def _round_to_grid(x: jnp.ndarray, exp_bits: int, man_bits: int, limit: float) -> jnp.ndarray:
    """Round fp32 values (already scaled into the grid's range) onto the
    (1, exp_bits, man_bits) float grid, round-to-nearest-even, with proper
    subnormal handling."""
    bias = 2 ** (exp_bits - 1) - 1
    sign = jnp.sign(x)
    mag = jnp.abs(x).astype(jnp.float32)
    # exponent of each value, clamped to the grid's representable binades
    # (the top binade comes from `limit`, which already accounts for
    # inf/nan-reserved encodings)
    e = jnp.floor(jnp.log2(jnp.maximum(mag, 1e-45)))
    e = jnp.clip(e, 1 - bias, math.floor(math.log2(limit)))
    # quantum = distance between representable values in this binade
    quantum = jnp.exp2(e - man_bits)
    q = jnp.round(mag / quantum) * quantum
    return sign * jnp.clip(q, 0.0, limit)


def _encode_codes(v: jnp.ndarray, exp_bits: int, man_bits: int) -> jnp.ndarray:
    """Grid-exact fp32 values -> int8 sign/exponent/mantissa bit codes (the
    low-bit storage the reference fp_quantizer produces; fp6/fp4 codes occupy
    the low 1+e+m bits of each byte)."""
    bias = 2 ** (exp_bits - 1) - 1
    s = (v < 0).astype(jnp.int32)
    mag = jnp.abs(v)
    sub_limit = 2.0 ** (1 - bias)
    is_norm = mag >= sub_limit
    e_val = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(mag, 1e-45))), 1 - bias, None)
    E = jnp.where(is_norm, e_val + bias, 0).astype(jnp.int32)
    M = jnp.where(
        is_norm,
        jnp.round((mag / jnp.exp2(e_val) - 1.0) * 2.0 ** man_bits),
        jnp.round(mag / (sub_limit * 2.0 ** (-man_bits))),
    ).astype(jnp.int32)
    # mantissa overflow from top-binade clipping: saturate
    M = jnp.clip(M, 0, 2 ** man_bits - 1)
    return ((s << (exp_bits + man_bits)) | (E << man_bits) | M).astype(jnp.int8)


def _decode_codes(codes: jnp.ndarray, exp_bits: int, man_bits: int) -> jnp.ndarray:
    bias = 2 ** (exp_bits - 1) - 1
    c = codes.astype(jnp.int32)
    s = (c >> (exp_bits + man_bits)) & 1
    E = (c >> man_bits) & (2 ** exp_bits - 1)
    M = c & (2 ** man_bits - 1)
    mf = M.astype(jnp.float32) * 2.0 ** (-man_bits)
    mag = jnp.where(
        E > 0,
        (1.0 + mf) * jnp.exp2(E.astype(jnp.float32) - bias),
        mf * 2.0 ** (1 - bias),
    )
    return jnp.where(s == 1, -mag, mag)


def fp_quantize(x: jnp.ndarray, fmt: str = "fp8_e4m3",
                block: int = 256) -> FPQuantizedTensor:
    """Blockwise-scaled quantization onto a low-bit float grid."""
    if fmt not in _FORMATS:
        raise ValueError(f"unknown format {fmt!r} (choose from {sorted(_FORMATS)})")
    exp_bits, man_bits = _FORMATS[fmt]
    blocks, _ = _to_blocks(x.astype(jnp.float32), block)
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    limit = _grid_max(fmt)
    scale = jnp.maximum(absmax, 1e-30) / limit
    scaled = blocks / scale
    if fmt in _NATIVE:
        vals = scaled.astype(_NATIVE[fmt])  # hardware rounding + storage
    else:
        grid = _round_to_grid(scaled, exp_bits, man_bits, limit)
        vals = _encode_codes(grid, exp_bits, man_bits)  # int8 bit codes
    return FPQuantizedTensor(values=vals, scales=scale[:, 0],
                             shape=tuple(x.shape), fmt=fmt, block=block)


def fp_dequantize(qt: FPQuantizedTensor, dtype=jnp.float32) -> jnp.ndarray:
    if qt.fmt in _NATIVE:
        grid = qt.values.astype(jnp.float32)
    else:
        exp_bits, man_bits = _FORMATS[qt.fmt]
        grid = _decode_codes(qt.values, exp_bits, man_bits)
    vals = grid * qt.scales[:, None]
    return _from_blocks(vals, qt.shape, dtype)


def fp_quantize_dequantize(x: jnp.ndarray, fmt: str = "fp8_e4m3",
                           block: int = 256) -> jnp.ndarray:
    """Fake-quant round trip (QAT / accuracy-evaluation helper, reference
    ``fake_quantizer.cu``)."""
    return fp_dequantize(fp_quantize(x, fmt=fmt, block=block), dtype=x.dtype)
