"""Attention ops with implementation dispatch.

Role parity with the reference's attention kernel stack
(``csrc/transformer/inference`` softmax/rope kernels, v2 ``ragged_ops`` blocked
flash attention) — on TPU the hot path is a Pallas flash-attention kernel
(``ops/pallas/flash_attention.py``); the reference path is a stable-softmax XLA
einsum that the compiler fuses well on the MXU. ``impl="auto"`` picks Pallas on
TPU for supported shapes, XLA otherwise.

Layouts: q [B, Sq, Hq, D], k/v [B, Skv, Hkv, D] (GQA: Hq % Hkv == 0).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand KV heads for grouped-query attention."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    bias: jnp.ndarray | None = None,
    scale: float | None = None,
) -> jnp.ndarray:
    """Reference attention: fp32 stable softmax, MXU-friendly einsums."""
    n_rep = q.shape[2] // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        # offset supports decode (q is a suffix of the kv sequence)
        idx_q = jnp.arange(sq)[:, None] + (sk - sq)
        idx_k = jnp.arange(sk)[None, :]
        mask = idx_q >= idx_k
        scores = jnp.where(mask[None, None], scores, jnp.float32(-1e30))
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)
    return out


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    bias: jnp.ndarray | None = None,
    scale: float | None = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Dispatching attention entry point used by all models."""
    if impl == "auto":
        impl = "pallas" if (_on_tpu() and bias is None) else "xla"
    if impl == "pallas":
        try:
            import os

            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

            # 1024x1024 blocks measured fastest on v5e (0.63 vs 0.54 MFU at
            # 256x512 on the 512M bench model; 2048 overflows VMEM — the
            # [bq, bk] fp32 probability block is the VMEM governor);
            # env-tunable for on-hardware sweeps. Halve down to a divisor of
            # the sequence so odd lengths (1536, 2560, ...) keep the kernel
            # instead of silently demoting to the XLA path.
            def fit(n, want):
                while want > 8 and n % min(want, n):
                    want //= 2
                return want

            return flash_attention(
                q, k, v, causal, scale,
                fit(q.shape[1],
                    int(os.environ.get("DSTPU_FLASH_BLOCK_Q", 1024))),
                fit(k.shape[1],
                    int(os.environ.get("DSTPU_FLASH_BLOCK_K", 1024))))
        except (ImportError, NotImplementedError):
            impl = "xla"
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, bias=bias, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")


_PAGED_FALLBACK_WARNED = False


def paged_attention(q, k_pool, v_pool, slots, positions, block_tables,
                    scale: float | None = None, impl: str = "auto"):
    """Ragged paged-KV attention: [T, Hq, D] tokens over the blocked pool
    (reference ``inference/v2/kernels/ragged_ops`` blocked flash attention).

    impl="pallas": stream blocks through VMEM via the block table (no padded
    gather); impl="xla": gather the padded context (fallback / CPU tests).

    A quantized pool (``inference/kvquant.QuantizedKV``) always takes the
    XLA path: the gather+dequant fuse into one program there (the fp
    context is a per-dispatch transient). A Pallas kernel that streams
    int8 blocks + scales through VMEM is the TPU drop-in point — it slots
    in at this dispatch without touching callers.
    """
    if getattr(k_pool, "is_quantized_kv", False):
        impl = "xla"
    if impl == "auto":
        import os

        impl = os.environ.get("DSTPU_PAGED_IMPL", "")
        if not impl:
            if not _on_tpu():
                impl = "xla"
            else:
                # measured on v5e (T=32, bs=32, bf16): the padded-gather XLA
                # path wins below ~2K tokens of real context (4.8 ms vs
                # 6.8 ms at 18 blocks) — decode there is tiny-matmul-bound
                # and the sequential per-(token, block) kernel grid loses to
                # one fused gather+attention op; past ~2K the gather's
                # O(T * ctx) materialization loses to the kernel's streamed
                # blocks (19.8 ms vs 29.5 ms at 8K). The engine slices the
                # block table to the batch's real context (_table_view), so
                # this width tracks actual context, not engine capacity.
                ctx = block_tables.shape[1] * k_pool.shape[1]
                cross = int(os.environ.get("DSTPU_PAGED_XLA_CTX", 2048))
                impl = "xla" if ctx <= cross else "pallas"
    if impl == "pallas":
        try:
            from deepspeed_tpu.ops.pallas.paged_attention import (
                paged_decode_attention,
            )

            return paged_decode_attention(q, k_pool, v_pool, slots, positions,
                                          block_tables, scale=scale)
        except (ImportError, NotImplementedError) as e:
            global _PAGED_FALLBACK_WARNED
            if not _PAGED_FALLBACK_WARNED:
                _PAGED_FALLBACK_WARNED = True
                from deepspeed_tpu.utils.logging import logger

                logger.warning(
                    "paged attention: Pallas kernel unavailable (%s); "
                    "falling back to the padded-gather XLA path — decode "
                    "memory/latency will degrade at long contexts", e)
            impl = "xla"
    if impl != "xla":
        raise ValueError(f"unknown paged attention impl {impl!r}")
    t_tokens, hq, d = q.shape
    hkv = k_pool.shape[2]
    tables = block_tables[slots]                       # [T, MB]
    if getattr(k_pool, "is_quantized_kv", False):
        ctx_k = repeat_kv(k_pool.gather_dequant(tables)
                          .reshape(t_tokens, -1, hkv, d), hq // hkv)
        ctx_v = repeat_kv(v_pool.gather_dequant(tables)
                          .reshape(t_tokens, -1, hkv, d), hq // hkv)
    else:
        ctx_k = repeat_kv(k_pool[tables].reshape(t_tokens, -1, hkv, d),
                          hq // hkv)
        ctx_v = repeat_kv(v_pool[tables].reshape(t_tokens, -1, hkv, d),
                          hq // hkv)
    scale = scale if scale is not None else 1.0 / jnp.sqrt(jnp.float32(d))
    k_pos = jnp.arange(ctx_k.shape[1])
    bias = jnp.where(k_pos[None, :] <= positions[:, None], 0.0, -1e30)
    scores = (jnp.einsum("thd,tchd->thc", (q * scale).astype(jnp.float32),
                         ctx_k.astype(jnp.float32)) + bias[:, None, :])
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("thc,tchd->thd", p, ctx_v.astype(jnp.float32)
                      ).astype(q.dtype)


def ragged_prefill_attention(q, k_pool, v_pool, tile_slot, tile_pos0,
                             tile_valid, block_tables, tile: int,
                             scale: float | None = None, impl: str = "auto"):
    """Tiled prefill attention over the blocked pool: ``q`` holds tile-aligned
    prefill tokens (one sequence per CT-token tile, consecutive positions,
    rows past ``tile_valid`` padding). The Pallas kernel fetches each KV block
    ONCE per tile instead of once per token
    (``ops/pallas/paged_attention.ragged_prefill_attention``); the XLA
    fallback expands the tile metadata to per-token (slot, position) arrays
    and reuses the padded-gather path.
    """
    if getattr(k_pool, "is_quantized_kv", False):
        impl = "xla"  # fused gather+dequant (see paged_attention)
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "xla"
    if impl == "pallas":
        try:
            from deepspeed_tpu.ops.pallas.paged_attention import (
                ragged_prefill_attention as _pallas_prefill,
            )

            return _pallas_prefill(q, k_pool, v_pool, tile_slot, tile_pos0,
                                   tile_valid, block_tables, tile, scale=scale)
        except (ImportError, NotImplementedError):
            impl = "xla"
    if impl != "xla":
        raise ValueError(f"unknown prefill attention impl {impl!r}")
    t = q.shape[0]
    c = jnp.arange(t) // tile
    i = jnp.arange(t) % tile
    pad_row = block_tables.shape[0] - 1  # all-scratch padding row
    valid = i < tile_valid[c]
    slots = jnp.where(valid, tile_slot[c], pad_row).astype(jnp.int32)
    positions = jnp.where(valid, tile_pos0[c] + i, 0).astype(jnp.int32)
    return paged_attention(q, k_pool, v_pool, slots, positions, block_tables,
                           scale=scale, impl="xla")


@functools.partial(jax.jit, static_argnums=(3,))
def apply_rope(q, k, positions, theta: float = 10000.0):
    """Rotary position embedding (reference: ``apply_rotary_pos_emb`` kernels,
    ``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu``).

    q/k: [B, S, H, D]; positions: [B, S] absolute positions.
    """
    d = q.shape[-1]
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, S, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        xr1 = x1.astype(jnp.float32) * cos - x2.astype(jnp.float32) * sin
        xr2 = x2.astype(jnp.float32) * cos + x1.astype(jnp.float32) * sin
        return jnp.concatenate([xr1, xr2], axis=-1).astype(x.dtype)

    return rot(q), rot(k)
