"""Evoformer (DS4Science) attention: MSA/pair attention with up to two biases.

Role parity with the reference ``DS4Sci_EvoformerAttention``
(``deepspeed/ops/deepspeed4science/evoformer_attn.py:88`` over the CUTLASS
fMHA kernels in ``csrc/deepspeed4science/evoformer_attn/``): 5-D
``[B, N_seq, N_res, H, D]`` attention with
- ``bias1`` ``[B, N_seq, 1, 1, N_res]`` (row mask, broadcast over heads and
  query residues) and
- ``bias2`` ``[B, 1, H, N_res, N_res]`` (pair bias, broadcast over sequences),
matching AlphaFold2-style MSA row attention.

TPU-native: one fused-by-XLA einsum softmax (the MXU handles the [R, R]
score block well at Evoformer's sizes); for long ``N_res`` an optional
``chunk_size`` maps the computation over query-residue chunks with
rematerialization so the [R, R] block never exceeds [chunk, R].
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def _check_bias_shapes(q, bias1, bias2):
    b, n, r = q.shape[0], q.shape[1], q.shape[2]
    h = q.shape[3]
    if bias1 is not None and tuple(bias1.shape) != (b, n, 1, 1, r):
        raise ValueError(
            f"bias1 shape {tuple(bias1.shape)} != {(b, n, 1, 1, r)} "
            "(reference bias_1_shape)")
    if bias2 is not None and tuple(bias2.shape) != (b, 1, h, r, r):
        raise ValueError(
            f"bias2 shape {tuple(bias2.shape)} != {(b, 1, h, r, r)} "
            "(reference bias_2_shape)")


def evoformer_attention(q, k, v, biases=(), chunk_size: int = 0):
    """softmax(q k^T / sqrt(d) + bias1 + bias2) v over 5-D MSA tensors.

    ``biases``: up to two optional arrays per the reference contract.
    ``chunk_size``: query-residue chunking (0 = dense); exact either way.
    """
    biases = list(biases) + [None] * (2 - len(biases))
    if len(biases) > 2:
        raise ValueError("at most two biases (reference assert len<=2)")
    bias1, bias2 = biases[0], biases[1]
    _check_bias_shapes(q, bias1, bias2)
    b, n, r, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def block(q_blk, b2_blk):
        # q_blk [B, N, C, H, D]; scores [B, N, H, C, R]
        s = jnp.einsum("bnchd,bnshd->bnhcs",
                       (q_blk * scale).astype(jnp.float32),
                       k.astype(jnp.float32))
        if bias1 is not None:
            s = s + bias1.astype(jnp.float32)      # [B,N,1,1,R] broadcasts
        if b2_blk is not None:
            s = s + b2_blk.astype(jnp.float32)     # [B,1,H,C,R] broadcasts
        p = jax.nn.softmax(s, axis=-1)
        # PV in v.dtype operands (fp32 accumulate on the MXU) — an fp32 GEMM
        # here would halve throughput (same choice as xla_attention)
        return jnp.einsum("bnhcs,bnshd->bnchd", p.astype(v.dtype), v
                          ).astype(q.dtype)

    if not chunk_size or chunk_size >= r:
        return block(q, bias2)
    if r % chunk_size:
        raise ValueError(f"N_res {r} not divisible by chunk_size {chunk_size}")
    nc = r // chunk_size
    q_c = q.reshape(b, n, nc, chunk_size, h, d).transpose(2, 0, 1, 3, 4, 5)
    if bias2 is not None:
        b2_c = bias2.reshape(b, 1, h, nc, chunk_size, r).transpose(3, 0, 1, 2, 4, 5)
        xs = (q_c, b2_c)
        body = jax.checkpoint(lambda xs: block(xs[0], xs[1]))
    else:
        xs = (q_c,)
        body = jax.checkpoint(lambda xs: block(xs[0], None))
    out = lax.map(body, xs)                        # [nc, B, N, C, H, D]
    return out.transpose(1, 2, 0, 3, 4, 5).reshape(b, n, r, h, d)


# reference-named alias (drop-in import surface)
DS4Sci_EvoformerAttention = evoformer_attention
