"""Pallas paged attention over the blocked KV pool: flash-decode + tiled prefill.

Role parity with the reference's ragged kernels
(``inference/v2/kernels/ragged_ops/`` blocked flash attention +
``ragged/csrc`` blocked-KV layout): each ragged token reads its sequence's
KV directly from the block pool through the block table — no gather of the
full padded context (the XLA fallback in ``models/llama.ragged_forward``
materializes ``[T, max_blocks*block, H, D]``; this kernel streams one block
at a time through VMEM with online-softmax accumulation).

Mechanism: ``PrefetchScalarGridSpec`` — the block table and slot/position
vectors are scalar-prefetch operands, so the KV BlockSpec index map resolves
``pool_block = block_tables[slots[t], j]`` *before* the kernel body runs and
the DMA fetches exactly that block (the TPU paged-attention idiom). Blocks
past the token's position are predicated off with ``pl.when``.

Inference-only (no VJP): the ragged engine never differentiates through
decode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

_NEG_INF = -1e30


def _kernel(slots_ref, pos_ref, bt_ref, q_ref, k_ref, v_ref, o_ref,
            acc, m_sc, l_sc, *, bs: int, rep: int, scale: float):
    t = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    pos = pos_ref[t]

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    @pl.when(j * bs <= pos)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # [Hq, D]
        k = k_ref[0].astype(jnp.float32)                  # [BS, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        hq, d = q.shape
        hkv = k.shape[1]
        qg = q.reshape(hkv, rep, d)
        # scores[g, r, k] over this block's keys
        s = jax.lax.dot_general(
            qg, k.transpose(1, 2, 0),                     # [Hkv, D, BS]
            (((2,), (1,)), ((0,), (0,))),                 # contract D, batch g
        )                                                 # [Hkv, rep, BS]
        kpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, 1, bs), 2)
        s = jnp.where(kpos <= pos, s, _NEG_INF)
        s = s.reshape(hq, bs)
        m_blk = jnp.max(s, axis=-1, keepdims=True)        # [Hq, 1]
        m_prev = m_sc[:, :1]
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)                            # [Hq, BS]
        corr = jnp.exp(m_prev - m_new)                    # [Hq, 1]
        l_sc[:, :1] = l_sc[:, :1] * corr + jnp.sum(p, -1, keepdims=True)
        m_sc[:, :1] = m_new
        pg = p.reshape(hkv, rep, bs)
        pv = jax.lax.dot_general(
            pg, v.transpose(1, 0, 2),                     # [Hkv, BS, D]
            (((2,), (1,)), ((0,), (0,))),                 # [Hkv, rep, D]
        ).reshape(hq, d)
        acc[:] = acc[:] * corr + pv

    @pl.when(j == nj - 1)
    def _finish():
        o_ref[0] = (acc[:] / jnp.maximum(l_sc[:, :1], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, slots, positions, block_tables,
                           scale: float | None = None):
    """[T, Hq, D] ragged tokens -> [T, Hq, D] attention outputs.

    ``k_pool``/``v_pool``: [NB, BS, Hkv, D]; ``block_tables``:
    [max_seqs+1, MB] mapping (slot, block-ordinal) -> pool block id. Exact
    vs the dense-gather path (same position masking).
    """
    if pltpu is None:
        raise NotImplementedError("pallas TPU backend unavailable")
    t_tokens, hq, d = q.shape
    nb, bs, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    rep = hq // hkv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    # Past the token's last valid block (j > pos // bs) the index map clamps
    # to that last block: the pipeline sees an unchanged block id, skips the
    # DMA, and the body's `pl.when` predicate skips the compute — so decode
    # bandwidth scales with the actual context, not the table width, and
    # nothing is ever read through freed/stale block_tables entries.
    def _kv_map(t, j, sl, po, bt):
        return (bt[sl[t], jnp.minimum(j, po[t] // bs)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t_tokens, mb),
        in_specs=[
            pl.BlockSpec((1, hq, d), lambda t, j, sl, po, bt: (t, 0, 0)),
            pl.BlockSpec((1, bs, hkv, d), _kv_map),
            pl.BlockSpec((1, bs, hkv, d), _kv_map),
        ],
        out_specs=pl.BlockSpec((1, hq, d), lambda t, j, sl, po, bt: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hq, d), jnp.float32),
            pltpu.VMEM((hq, 128), jnp.float32),
            pltpu.VMEM((hq, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_kernel, bs=bs, rep=rep, scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((t_tokens, hq, d), q.dtype),
        grid_spec=grid_spec,
        interpret=jax.default_backend() != "tpu",
    )(slots.astype(jnp.int32), positions.astype(jnp.int32),
      block_tables.astype(jnp.int32), q, k_pool, v_pool)


# --------------------------------------------------------------- tiled prefill
def _prefill_kernel(ts_ref, tp_ref, tv_ref, bt_ref, q_ref, k_ref, v_ref, o_ref,
                    acc, m_sc, l_sc, *, bs: int, ct: int, rep: int, scale: float):
    c = pl.program_id(0)   # query tile
    j = pl.program_id(1)   # kv block ordinal
    nj = pl.num_programs(1)
    pos0 = tp_ref[c]
    valid = tv_ref[c]
    max_pos = pos0 + valid - 1

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    @pl.when(jnp.logical_and(valid > 0, j * bs <= max_pos))
    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale        # [CT, Hq, D]
        k = k_ref[0].astype(jnp.float32)                  # [BS, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        hq, d = q.shape[1], q.shape[2]
        hkv = k.shape[1]
        # GQA layout: [Hkv, CT*rep, D]; row r -> query token i = r // rep
        qg = q.reshape(ct, hkv, rep, d).transpose(1, 0, 2, 3).reshape(
            hkv, ct * rep, d)
        s = jax.lax.dot_general(
            qg, k.transpose(1, 2, 0),                     # [Hkv, D, BS]
            (((2,), (1,)), ((0,), (0,))),
        )                                                 # [Hkv, CT*rep, BS]
        qi = jax.lax.broadcasted_iota(jnp.int32, (1, ct * rep, 1), 1) // rep
        qpos = pos0 + qi
        kpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, 1, bs), 2)
        mask = jnp.logical_and(kpos <= qpos, qi < valid)
        s = jnp.where(mask, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)        # [Hkv, CT*rep, 1]
        m_prev = m_sc[:, :, :1]
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)
        # fully-masked rows (pad queries / no visible keys in this block)
        # produce exp(-inf - -inf); zero them rather than poison l
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:, :, :1] = l_sc[:, :, :1] * corr + jnp.sum(p, -1, keepdims=True)
        m_sc[:, :, :1] = m_new
        pv = jax.lax.dot_general(
            p, v.transpose(1, 0, 2),                      # [Hkv, BS, D]
            (((2,), (1,)), ((0,), (0,))),
        )                                                 # [Hkv, CT*rep, D]
        acc[:] = acc[:] * corr + pv

    @pl.when(j == nj - 1)
    def _finish():
        hkv = acc.shape[0]
        d = acc.shape[2]
        out = acc[:] / jnp.maximum(l_sc[:, :, :1], 1e-30)
        o_ref[...] = out.reshape(hkv, ct, rep, d).transpose(1, 0, 2, 3).reshape(
            ct, hkv * rep, d).astype(o_ref.dtype)


def ragged_prefill_attention(q, k_pool, v_pool, tile_slot, tile_pos0,
                             tile_valid, block_tables, tile: int,
                             scale: float | None = None):
    """Tiled prefill attention: [NT*CT, Hq, D] tile-aligned prefill tokens ->
    outputs, one KV-block DMA shared by the whole CT-token tile (the
    SplitFuse blocked flash attention, reference
    ``inference/v2/kernels/ragged_ops`` — vs the decode kernel above, which
    fetches per TOKEN and is O(context) DMA per token).

    Scheduler contract (``inference/ragged.py``): each tile's tokens belong
    to ONE sequence at consecutive positions ``pos0..pos0+valid-1``; rows
    past ``valid`` are padding. ``tile_valid == 0`` marks an all-pad tile.
    """
    if pltpu is None:
        raise NotImplementedError("pallas TPU backend unavailable")
    t_tokens, hq, d = q.shape
    nb, bs, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    rep = hq // hkv
    ct = tile
    n_tiles = t_tokens // ct
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    # clamp past the tile's last needed block: unchanged id -> no new DMA
    def _kv_map(c, j, ts, tp, tv, bt):
        last = jnp.maximum(tp[c] + tv[c] - 1, 0) // bs
        return (bt[ts[c], jnp.minimum(j, last)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n_tiles, mb),
        in_specs=[
            pl.BlockSpec((ct, hq, d), lambda c, j, ts, tp, tv, bt: (c, 0, 0)),
            pl.BlockSpec((1, bs, hkv, d), _kv_map),
            pl.BlockSpec((1, bs, hkv, d), _kv_map),
        ],
        out_specs=pl.BlockSpec((ct, hq, d),
                               lambda c, j, ts, tp, tv, bt: (c, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, ct * rep, d), jnp.float32),
            pltpu.VMEM((hkv, ct * rep, 128), jnp.float32),
            pltpu.VMEM((hkv, ct * rep, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_prefill_kernel, bs=bs, ct=ct, rep=rep,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((t_tokens, hq, d), q.dtype),
        grid_spec=grid_spec,
        interpret=jax.default_backend() != "tpu",
    )(tile_slot.astype(jnp.int32), tile_pos0.astype(jnp.int32),
      tile_valid.astype(jnp.int32), block_tables.astype(jnp.int32),
      q, k_pool, v_pool)
