"""Pallas TPU flash attention (forward kernel + custom VJP).

Role parity with the reference's fused attention kernels
(``csrc/transformer/inference/csrc/softmax.cu``, v2 ``ragged_ops`` blocked
flash attention) — re-built as a Pallas kernel for the MXU: Q blocks stream
from VMEM, KV blocks stream through the sequential innermost grid dim with the
classic online-softmax accumulation, so the [Sq, Sk] score matrix never
materializes in HBM. Causal upper-triangle blocks are skipped with predicated
execution (``pl.when``), halving the work.

Layouts: q/k/v [B, S, H, D] (GQA supported: the K/V block index maps divide the
head index, so KV heads are never replicated in memory). The backward pass is
two Pallas kernels (dk/dv accumulated over q blocks; dq accumulated over kv
blocks) from the saved lse — the [Sq, Sk] score matrix never materializes in
either direction. Set ``DSTPU_FLASH_XLA_BWD=1`` to fall back to the XLA
recompute backward.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on pure-CPU builds of pallas
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_sc, l_sc,
                *, scale: float, causal: bool, block_q: int, block_k: int):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block (sequential innermost)
    nj = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    # skip blocks strictly above the diagonal (q ends before kv starts)
    run = True
    if causal:
        run = (i + 1) * block_q - 1 >= j * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                  # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_sc[:, 0:1]                                 # [bq, 1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)                                # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                        # [bq, 1]
        l_new = l_sc[:, 0:1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v_ref[0, 0].astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq, d]
        acc[:] = acc[:] * corr + pv
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(j == nj - 1)
    def _finish():
        l = l_sc[:, 0:1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_sc[:, 0:1] + jnp.log(safe_l)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    n_rep = hq // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)

    qt = q.transpose(0, 2, 1, 3)  # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, hq, sq // block_q, skv // block_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, i, j: (b_, h // n_rep, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, i, j: (b_, h // n_rep, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i, j: (b_, h, i, 0)),
        ),
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, 128)),
            _scratch((block_q, 128)),
        ],
        interpret=_interpret_mode(),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def _scratch(shape):
    if pltpu is None:
        raise NotImplementedError("pallas TPU backend unavailable")
    return pltpu.VMEM(shape, jnp.float32)


def _interpret_mode() -> bool:
    return jax.default_backend() != "tpu"


def _supported(q, k, block_q, block_k) -> bool:
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    if hq % hkv:
        return False
    if sq % min(block_q, sq) or skv % min(block_k, skv):
        return False
    if d % 8:
        return False
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, scale: float | None = None,
                    block_q: int = 256, block_k: int = 512):
    """Drop-in for ``ops.attention.xla_attention`` on TPU shapes."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if not _supported(q, k, block_q, block_k):
        raise NotImplementedError("flash_attention: unsupported shape")
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if not _supported(q, k, block_q, block_k):
        raise NotImplementedError("flash_attention: unsupported shape")
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                 dk_acc, dv_acc, *, scale: float, causal: bool,
                 block_q: int, block_k: int):
    j = pl.program_id(2)  # kv block
    i = pl.program_id(3)  # q block (sequential innermost)
    ni = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (i + 1) * block_q - 1 >= j * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)                  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)                # [bq, d]
        lse = lse_ref[0, 0]                                  # [bq, 1]
        delta = delta_ref[0, 0]                              # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - delta) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(i == ni - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
               *, scale: float, causal: bool, block_q: int, block_k: int):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block (sequential innermost)
    nj = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = (i + 1) * block_q - 1 >= j * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, do, scale, causal, block_q, block_k):
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    n_rep = hq // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = delta.transpose(0, 2, 1)[..., None]               # [B, Hq, Sq, 1]
    lse4 = lse[..., None]                                     # [B, Hq, Sq, 1]

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)

    q_spec_i = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0))
    q_spec_j = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, j, i: (b_, h, i, 0))
    kv_spec_i = pl.BlockSpec((1, 1, block_k, d), lambda b_, h, i, j: (b_, h // n_rep, j, 0))
    kv_spec_j = pl.BlockSpec((1, 1, block_k, d), lambda b_, h, j, i: (b_, h // n_rep, j, 0))
    row_spec_i = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i, j: (b_, h, i, 0))
    row_spec_j = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, j, i: (b_, h, i, 0))

    # dk/dv: one [B, Hq, Skv, D] buffer per q-head group, reduced below for GQA
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=(
            jax.ShapeDtypeStruct((b, hq, skv, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, skv, d), jnp.float32),
        ),
        grid=(b, hq, skv // block_k, sq // block_q),
        in_specs=[q_spec_j, kv_spec_j, kv_spec_j, q_spec_j, row_spec_j, row_spec_j],
        out_specs=(
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, j, i: (b_, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h, j, i: (b_, h, j, 0)),
        ),
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=_interpret_mode(),
    )(qt, kt, vt, dot, lse4, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        grid=(b, hq, sq // block_q, skv // block_k),
        in_specs=[q_spec_i, kv_spec_i, kv_spec_i, q_spec_i, row_spec_i, row_spec_i],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=_interpret_mode(),
    )(qt, kt, vt, dot, lse4, delta)

    dq = dq.transpose(0, 2, 1, 3)
    dk = dk_h.transpose(0, 2, 1, 3)
    dv = dv_h.transpose(0, 2, 1, 3)
    if n_rep > 1:
        dk = dk.reshape(b, skv, hkv, n_rep, d).sum(axis=3)
        dv = dv.reshape(b, skv, hkv, n_rep, d).sum(axis=3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _fa_bwd_xla(causal, scale, block_q, block_k, res, do):
    """Standard flash backward algebra from saved lse (XLA; fp32)."""
    q, k, v, out, lse = res
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    hq, hkv = q.shape[2], k.shape[2]
    n_rep = hq // hkv
    from deepspeed_tpu.ops.attention import repeat_kv

    kf = repeat_kv(k, n_rep).astype(jnp.float32)
    vf = repeat_kv(v, n_rep).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    of = out.astype(jnp.float32)

    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = (jnp.arange(sq)[:, None] + (sk - sq)) >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jnp.exp(s - lse[:, :, :, None])                       # [B,H,Sq,Sk]
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf)
    delta = jnp.sum(dof * of, axis=-1).transpose(0, 2, 1)     # [B,H,Sq]
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf).astype(q.dtype)
    dk_full = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
    if n_rep > 1:
        bsz, sk_, _, dh = dk_full.shape
        dk_full = dk_full.reshape(bsz, sk_, hkv, n_rep, dh).sum(axis=3)
        dv = dv.reshape(bsz, sk_, hkv, n_rep, dh).sum(axis=3)
    return dq, dk_full.astype(k.dtype), dv.astype(v.dtype)


def _fa_bwd(causal, scale, block_q, block_k, res, do):
    if os.environ.get("DSTPU_FLASH_XLA_BWD"):
        return _fa_bwd_xla(causal, scale, block_q, block_k, res, do)
    q, k, v, out, lse = res
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_bwd_pallas(q, k, v, out, lse, do, scale, causal, block_q, block_k)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
