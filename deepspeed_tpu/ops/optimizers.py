"""Optimizer registry.

Role parity with the reference's optimizer zoo (``ops/adam/fused_adam.py``,
``ops/adam/cpu_adam.py``, ``ops/lamb``, ``ops/lion``, ``ops/adagrad``,
``ops/muon`` + ``runtime/engine.py:1960 _configure_basic_optimizer``) — on TPU
the "fused multi-tensor kernel" concern disappears: optax transforms compile to
fused XLA loops over the (sharded) flat param pytree, which is exactly what
``multi_tensor_adam.cu`` hand-builds — no hand-written kernel is needed or
provided for the update itself.

``build_optimizer(config, schedule)`` returns an ``optax.GradientTransformation``
whose learning rate is the jittable schedule, so the whole update (lr included)
lives inside the compiled train step.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import optax

from deepspeed_tpu.config.config import OptimizerConfig


from deepspeed_tpu.config.config import (  # noqa: F401 (re-export)
    ONEBIT_ADAM_NAMES,
    ONEBIT_LAMB_NAMES,
    ZERO_ONE_ADAM_NAMES,
    is_onebit_family,
)


class ZeroOneAdamState(NamedTuple):
    """0/1 Adam state: ``vcount`` counts variance refreshes actually applied
    (the sparse schedule makes it lag ``count``), used for b2 bias correction."""

    count: Any
    vcount: Any
    mu: Any
    nu: Any


def _adam_args(p: dict) -> dict:
    betas = p.get("betas", (0.9, 0.999))
    return dict(
        b1=float(betas[0]),
        b2=float(betas[1]),
        eps=float(p.get("eps", 1e-8)),
    )


def build_optimizer(
    cfg: OptimizerConfig,
    learning_rate: Callable | float | None = None,
) -> optax.GradientTransformation:
    """Map an ``OptimizerConfig`` to an optax transformation.

    Supported types mirror the reference (engine.py:1960): adam/adamw (FusedAdam),
    sgd, lion (FusedLion), lamb (FusedLamb), adagrad, muon.
    """
    p = dict(cfg.params)
    lr = learning_rate if learning_rate is not None else float(p.get("lr", 1e-3))
    wd = float(p.get("weight_decay", 0.0))
    t = cfg.type.lower()

    if t == "adamw":
        return optax.adamw(lr, weight_decay=wd, **_adam_args(p))
    if t == "adam":
        # reference FusedAdam(adam_w_mode=False): L2-regularized Adam
        if wd:
            return optax.chain(
                optax.add_decayed_weights(wd), optax.adam(lr, **_adam_args(p))
            )
        return optax.adam(lr, **_adam_args(p))
    if t == "sgd":
        return optax.sgd(lr, momentum=float(p.get("momentum", 0.0)),
                         nesterov=bool(p.get("nesterov", False)))
    if t == "lion":
        betas = p.get("betas", (0.9, 0.99))
        return optax.lion(lr, b1=float(betas[0]), b2=float(betas[1]), weight_decay=wd)
    if t == "lamb":
        return optax.lamb(lr, weight_decay=wd, **_adam_args(p))
    if t == "adagrad":
        return optax.adagrad(lr, eps=float(p.get("eps", 1e-10)))
    if t == "muon":
        muon = getattr(getattr(optax, "contrib", None), "muon", None)
        if muon is None:
            raise NotImplementedError("optax.contrib.muon unavailable in this optax build")
        return muon(lr)
    if t.replace("-", "_") in tuple(
            s.replace("-", "_") for s in ONEBIT_ADAM_NAMES):
        tx = scale_by_onebit_adam(
            warmup_steps=int(p.get("freeze_step", p.get("warmup_steps", 100))),
            **_adam_args(p),
        )
        parts = [tx]
        if wd:
            parts.append(optax.add_decayed_weights(wd))
        parts.append(optax.scale_by_learning_rate(lr))
        return optax.chain(*parts)
    if t.replace("-", "_") in tuple(
            s.replace("-", "_") for s in ONEBIT_LAMB_NAMES):
        tx = scale_by_onebit_lamb(
            warmup_steps=int(p.get("freeze_step", p.get("warmup_steps", 100))),
            max_coeff=float(p.get("max_coeff", 10.0)),
            min_coeff=float(p.get("min_coeff", 0.01)),
            coeff_ratio=float(p.get("coeff_ratio", 2.0)),
            **_adam_args(p),
        )
        parts = [tx]
        if wd:
            parts.append(optax.add_decayed_weights(wd))
        parts.append(optax.scale_by_learning_rate(lr))
        return optax.chain(*parts)
    if t in ZERO_ONE_ADAM_NAMES:
        tx = scale_by_zero_one_adam(
            var_freeze_step=int(p.get("var_freeze_step", 100)),
            var_update_scaler=int(p.get("var_update_scaler", 16)),
            local_step_scaler=int(p.get("local_step_scaler", 32768)),
            **_adam_args(p),
        )
        parts = [tx]
        if wd:
            parts.append(optax.add_decayed_weights(wd))
        parts.append(optax.scale_by_learning_rate(lr))
        return optax.chain(*parts)
    raise ValueError(f"unsupported optimizer type {cfg.type!r}")


def scale_by_onebit_adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                         warmup_steps: int = 100) -> optax.GradientTransformation:
    """1-bit-Adam semantics (reference ``runtime/fp16/onebit/adam.py``):
    standard Adam during the warmup phase, then the variance ``nu`` FREEZES
    and only the momentum keeps updating — the property that makes compressed
    gradient/momentum communication safe after warmup. Pair with
    ``zero_optimization.quantized_gradients`` for the compressed wire
    (``comm/quantized_collectives.py``); this transform supplies the matching
    optimizer math.
    """
    import jax
    import jax.numpy as jnp

    if warmup_steps < 1:
        raise ValueError(
            "onebit_adam freeze_step must be >= 1 (the variance estimate "
            "needs at least one warmup step)"
        )

    def init(params):
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        nu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        in_warmup = count <= warmup_steps
        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, updates)
        nu = jax.tree_util.tree_map(
            lambda v, g: jnp.where(
                in_warmup, b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)), v),
            state.nu, updates)
        # bias correction: nu's correction uses the step it froze at
        nu_count = jnp.minimum(count, warmup_steps)
        mc = 1 - b1 ** count.astype(jnp.float32)
        vc = 1 - b2 ** nu_count.astype(jnp.float32)
        out = jax.tree_util.tree_map(
            lambda m, v: (m / mc) / (jnp.sqrt(v / vc) + eps), mu, nu)
        return out, optax.ScaleByAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def scale_by_onebit_lamb(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                         warmup_steps: int = 100,
                         max_coeff: float = 10.0, min_coeff: float = 0.01,
                         coeff_ratio: float = 2.0) -> optax.GradientTransformation:
    """1-bit LAMB semantics (reference ``runtime/fp16/onebit/lamb.py``):
    exact LAMB during warmup; after the freeze step the VARIANCE freezes —
    the property that makes compressed momentum communication safe, exactly
    as in 1-bit Adam — while the layerwise trust ratio stays live. The live
    trust ratio is the stabilizer: it renormalizes the update to the param
    norm, so a drifting momentum over a frozen variance cannot blow the step
    size up (it is computed locally from norms, no extra communication).
    ``min_coeff``/``max_coeff`` bound it (reference lamb coefficient bounds);
    ``coeff_ratio`` is accepted for reference-config compatibility.
    """
    del coeff_ratio
    import jax
    import jax.numpy as jnp

    if warmup_steps < 1:
        raise ValueError("onebit_lamb freeze_step must be >= 1")

    def trust(p, u):
        pn = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
        un = jnp.linalg.norm(u.astype(jnp.float32).reshape(-1))
        raw = jnp.where((pn > 0.0) & (un > 0.0), pn / un, 1.0)
        return jnp.clip(raw, min_coeff, max_coeff)

    def init(params):
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        nu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("onebit_lamb needs params (trust-ratio scaling)")
        count = state.count + 1
        in_warmup = count <= warmup_steps
        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, updates)
        nu = jax.tree_util.tree_map(
            lambda v, g: jnp.where(
                in_warmup, b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)), v),
            state.nu, updates)
        nu_count = jnp.minimum(count, warmup_steps)
        mc = 1 - b1 ** count.astype(jnp.float32)
        vc = 1 - b2 ** nu_count.astype(jnp.float32)
        raw = jax.tree_util.tree_map(
            lambda m, v: (m / mc) / (jnp.sqrt(v / vc) + eps), mu, nu)
        out = jax.tree_util.tree_map(
            lambda p, u: trust(p, u) * u, params, raw)
        return out, optax.ScaleByAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def scale_by_zero_one_adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                           var_freeze_step: int = 100,
                           var_update_scaler: int = 16,
                           local_step_scaler: int = 32768
                           ) -> optax.GradientTransformation:
    """0/1 Adam semantics (reference ``runtime/fp16/onebit/zoadam.py``):
    the variance is refreshed only at exponentially sparsifying intervals
    (every ``2^(k/var_update_scaler)`` steps, the reference's adaptive
    variance-update policy) and freezes entirely after ``var_freeze_step`` —
    by making variance updates rare from the START, both gradient and
    momentum communication can be compressed for the whole run (the "0" in
    0/1: some steps skip synchronization entirely; here the optimizer math is
    exact at every step and only the variance refresh is sparse, which is the
    part that gates compression safety).

    ``local_step_scaler`` is accepted for reference-config compatibility (it
    tunes the learning-rate-scaled local-step policy of the reference's
    communication skipping, which XLA's fused reduction replaces).
    """
    import jax
    import jax.numpy as jnp

    del local_step_scaler
    if var_freeze_step < 1:
        raise ValueError("zero_one_adam var_freeze_step must be >= 1")

    def init(params):
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        nu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return ZeroOneAdamState(count=jnp.zeros([], jnp.int32),
                                vcount=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        cf = count.astype(jnp.float32)
        # exponentially sparsifying refresh: interval doubles every
        # var_update_scaler steps; always refresh during the first interval
        k = jnp.floor(cf / float(var_update_scaler))
        interval = jnp.exp2(jnp.minimum(k, 30.0)).astype(jnp.int32)
        refresh = jnp.logical_and(count <= var_freeze_step,
                                  (count % jnp.maximum(interval, 1)) == 0)
        refresh = jnp.logical_or(refresh, count <= var_update_scaler)
        vcount = state.vcount + refresh.astype(jnp.int32)
        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, updates)
        nu = jax.tree_util.tree_map(
            lambda v, g: jnp.where(
                refresh, b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)), v),
            state.nu, updates)
        mc = 1 - b1 ** cf
        # bias-correct the variance by the number of refreshes ACTUALLY
        # applied (nu is an EMA over vcount samples, not count), otherwise
        # v-hat is underestimated between sparse refreshes and steps inflate
        vc = 1 - b2 ** jnp.maximum(vcount, 1).astype(jnp.float32)
        out = jax.tree_util.tree_map(
            lambda m, v: (m / mc) / (jnp.sqrt(v / vc) + eps), mu, nu)
        return out, ZeroOneAdamState(count=count, vcount=vcount, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def base_lr(cfg: OptimizerConfig) -> float:
    return float(cfg.params.get("lr", 1e-3))
