"""Optimizer registry.

Role parity with the reference's optimizer zoo (``ops/adam/fused_adam.py``,
``ops/adam/cpu_adam.py``, ``ops/lamb``, ``ops/lion``, ``ops/adagrad``,
``ops/muon`` + ``runtime/engine.py:1960 _configure_basic_optimizer``) — on TPU
the "fused multi-tensor kernel" concern disappears: optax transforms compile to
fused XLA loops over the (sharded) flat param pytree, which is exactly what
``multi_tensor_adam.cu`` hand-builds. A Pallas fused-update kernel slots in
behind the same interface for the hot path (see ``ops/pallas``).

``build_optimizer(config, schedule)`` returns an ``optax.GradientTransformation``
whose learning rate is the jittable schedule, so the whole update (lr included)
lives inside the compiled train step.
"""

from __future__ import annotations

from typing import Callable

import optax

from deepspeed_tpu.config.config import OptimizerConfig


def _adam_args(p: dict) -> dict:
    betas = p.get("betas", (0.9, 0.999))
    return dict(
        b1=float(betas[0]),
        b2=float(betas[1]),
        eps=float(p.get("eps", 1e-8)),
    )


def build_optimizer(
    cfg: OptimizerConfig,
    learning_rate: Callable | float | None = None,
) -> optax.GradientTransformation:
    """Map an ``OptimizerConfig`` to an optax transformation.

    Supported types mirror the reference (engine.py:1960): adam/adamw (FusedAdam),
    sgd, lion (FusedLion), lamb (FusedLamb), adagrad, muon.
    """
    p = dict(cfg.params)
    lr = learning_rate if learning_rate is not None else float(p.get("lr", 1e-3))
    wd = float(p.get("weight_decay", 0.0))
    t = cfg.type.lower()

    if t == "adamw":
        return optax.adamw(lr, weight_decay=wd, **_adam_args(p))
    if t == "adam":
        # reference FusedAdam(adam_w_mode=False): L2-regularized Adam
        if wd:
            return optax.chain(
                optax.add_decayed_weights(wd), optax.adam(lr, **_adam_args(p))
            )
        return optax.adam(lr, **_adam_args(p))
    if t == "sgd":
        return optax.sgd(lr, momentum=float(p.get("momentum", 0.0)),
                         nesterov=bool(p.get("nesterov", False)))
    if t == "lion":
        betas = p.get("betas", (0.9, 0.99))
        return optax.lion(lr, b1=float(betas[0]), b2=float(betas[1]), weight_decay=wd)
    if t == "lamb":
        return optax.lamb(lr, weight_decay=wd, **_adam_args(p))
    if t == "adagrad":
        return optax.adagrad(lr, eps=float(p.get("eps", 1e-10)))
    if t == "muon":
        muon = getattr(getattr(optax, "contrib", None), "muon", None)
        if muon is None:
            raise NotImplementedError("optax.contrib.muon unavailable in this optax build")
        return muon(lr)
    raise ValueError(f"unsupported optimizer type {cfg.type!r}")


def base_lr(cfg: OptimizerConfig) -> float:
    return float(cfg.params.get("lr", 1e-3))
