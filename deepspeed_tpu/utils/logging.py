"""Rank-aware logging.

Equivalent role to the reference's ``deepspeed/utils/logging.py`` (``logger``,
``log_dist(ranks=...)``, rank-0 helpers), re-expressed for a JAX process model:
"rank" is ``jax.process_index()`` rather than an env-var torch rank.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setFormatter(logging.Formatter(_FORMAT))
    lg.addHandler(handler)
    return lg


logger = _create_logger(
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), logging.INFO)
)


def set_log_level(level: str | int) -> None:
    if isinstance(level, str):
        level = LOG_LEVELS[level.lower()]
    logger.setLevel(level)


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # pre-init / no backend
        return 0


def log_dist(message: str, ranks: list[int] | None = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the listed process ranks (None or [-1] = all)."""
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        logger.info(message)


def warning_once(message: str, _seen: set = set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
