"""Version-compat shims for jax API drift.

The codebase targets the ``jax.shard_map(..., axis_names=, check_vma=)``
spelling (jax >= 0.6); older builds only ship
``jax.experimental.shard_map.shard_map`` with the equivalent
``auto=``/``check_rep=`` contract. ``inference/kvquant.py`` carried a local
try/except for this; every shard_map call site now routes through the shared
resolver here so the whole training/inference surface runs on whichever jax
the container pins.
"""

from __future__ import annotations

import jax

__all__ = ["axis_size_compat", "shard_map_compat", "supports_partial_manual"]


def supports_partial_manual(mesh, manual_axes) -> bool:
    """Whether this jax can run shard_map manual over ``manual_axes`` while
    other mesh axes of size > 1 stay GSPMD-auto.

    On pre-0.6 jax the experimental fallback compiles partial-manual
    ``ppermute`` into an XLA SPMD-partitioner CHECK failure (an uncatchable
    C++ abort: ``target.IsManualSubgroup() == sharding().IsManualSubgroup()``)
    — so the compat wrapper refuses that regime up front instead of letting
    the process die at compile time. Size-1 auto axes are fine.
    """
    if getattr(jax, "shard_map", None) is not None:
        return True
    manual = set(manual_axes or mesh.axis_names)
    return all(mesh.shape[a] <= 1 for a in mesh.axis_names if a not in manual)


def axis_size_compat(axis_name):
    """``lax.axis_size`` across jax versions.

    Pre-0.5 jax has no ``lax.axis_size``; ``lax.psum(1, axis)`` inside a
    manual region constant-folds to the same concrete int.
    """
    from jax import lax

    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


def shard_map_compat(f, mesh, in_specs, out_specs, axis_names=None,
                     check_vma=False):
    """``jax.shard_map`` across jax versions.

    ``axis_names`` is the set of MANUAL axes (the new-API meaning); omitted
    means manual over every mesh axis. On pre-0.6 jax this maps to the
    experimental module's complement spelling: ``auto`` = the non-manual
    axes, ``check_rep`` = ``check_vma``.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  axis_names=axis_names or set(mesh.axis_names),
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _esm

    manual = set(axis_names or mesh.axis_names)
    if not supports_partial_manual(mesh, manual):
        raise NotImplementedError(
            "partial-manual shard_map (manual over "
            f"{sorted(manual)} with live auto axes) fatally aborts XLA's "
            "SPMD partitioner on this jax version; upgrade jax or make the "
            "manual region cover every mesh axis of size > 1")
    auto = frozenset(a for a in mesh.axis_names if a not in manual)
    return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=check_vma, auto=auto)
