"""Per-collective communication logging.

Role parity with ``deepspeed/utils/comms_logging.py`` (``CommsLogger``,
``calc_bw_log:34``) and the ``timed_op`` wrapper (``comm/comm.py:106``): every
collective issued through :mod:`deepspeed_tpu.comm.comm` records op name, bytes
moved, call count, and — where measurable — latency and algorithmic/bus bandwidth.

TPU adaptation: collectives inside a jitted step are compiled into the XLA
program, so per-call host timing is meaningless there. We therefore keep two
ledgers: (1) a *trace-time* ledger of collectives captured while staging the step
(op, tensor bytes, axis, estimated bytes-on-wire) — the static "comms plan"; and
(2) an *eager* ledger with real wall-clock latency for host-level collectives
(barriers, broadcasts, checkpoint-tag validation). ``log_summary`` renders both,
with min/max across processes for straggler detection when distributed.
"""

from __future__ import annotations

import time
from collections import defaultdict
from dataclasses import dataclass, field

from deepspeed_tpu.utils.logging import log_dist, logger


def get_caller_func(depth: int = 2) -> str:
    import sys

    try:
        return sys._getframe(depth).f_code.co_name
    except ValueError:  # stack shallower than requested (REPL/top-level)
        return "<toplevel>"


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n_ranks: int):
    """Algorithmic and bus bandwidth in GB/s (reference: comms_logging.py:34)."""
    duration_s = max(duration_s, 1e-12)
    if comm_op in ("all_to_all",):
        # each rank sends size*(n-1)/n
        tput = size_bytes / duration_s
        busbw = tput * ((n_ranks - 1) / max(n_ranks, 1))
    elif comm_op in ("all_gather", "reduce_scatter"):
        size_bytes *= n_ranks
        tput = size_bytes / duration_s
        busbw = (size_bytes / duration_s) * ((n_ranks - 1) / max(n_ranks, 1))
    elif comm_op in ("all_reduce", "psum"):
        tput = size_bytes * 2 / duration_s
        busbw = (size_bytes / duration_s) * (2 * (n_ranks - 1) / max(n_ranks, 1))
    else:  # send/recv/broadcast/ppermute
        tput = size_bytes / duration_s
        busbw = tput
    return tput / 1e9, busbw / 1e9


@dataclass
class _OpRecord:
    count: int = 0
    total_bytes: int = 0
    total_latency: float = 0.0  # seconds; 0 for trace-time records
    n_ranks: int = 1  # participants of the last call (bandwidth accounting)
    sizes: dict = field(default_factory=lambda: defaultdict(lambda: [0, 0.0]))  # size -> [count, lat]


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False, debug: bool = False,
                 prof_all: bool = True, prof_ops: list | None = None,
                 straggler_warn_ratio: float = 2.0):
        self.enabled = enabled
        self.verbose = verbose
        self.debug = debug
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.straggler_warn_ratio = straggler_warn_ratio
        self.traced: dict[str, _OpRecord] = defaultdict(_OpRecord)
        self.eager: dict[str, _OpRecord] = defaultdict(_OpRecord)

    def configure(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.verbose = cfg.verbose
        self.debug = cfg.debug
        self.prof_all = cfg.prof_all
        self.prof_ops = list(cfg.prof_ops)
        self.straggler_warn_ratio = float(
            getattr(cfg, "straggler_warn_ratio", self.straggler_warn_ratio))

    def _should_log(self, op_name: str) -> bool:
        return self.enabled and (self.prof_all or op_name in self.prof_ops)

    # ------------------------------------------------------- trace-time ledger
    def append_traced(self, op_name: str, size_bytes: int, axis: str, n_ranks: int,
                      caller: str = "") -> None:
        # both ledgers also feed the telemetry metrics registry
        # (deepspeed_tpu/telemetry/): counters survive the run in the JSONL
        # snapshot / Prometheus endpoint even when this logger only prints
        from deepspeed_tpu.telemetry import TELEMETRY

        if TELEMETRY.enabled:
            TELEMETRY.counter(
                "comm_traced_calls_total",
                "collectives captured at step-trace time").inc(1, op=op_name)
            TELEMETRY.counter(
                "comm_traced_bytes_total",
                "bytes moved by trace-time collectives").inc(
                    size_bytes, op=op_name)
        if not self._should_log(op_name):
            return
        rec = self.traced[op_name]
        rec.count += 1
        rec.total_bytes += size_bytes
        rec.n_ranks = max(n_ranks, 1)
        rec.sizes[size_bytes][0] += 1
        if self.verbose:
            log_dist(
                f"comm trace: {op_name} | axis={axis} ranks={n_ranks} "
                f"bytes={size_bytes} caller={caller}",
                ranks=[0],
            )

    # ------------------------------------------------------- eager ledger
    def append_eager(self, op_name: str, size_bytes: int, latency_s: float, n_ranks: int) -> None:
        from deepspeed_tpu.telemetry import TELEMETRY

        if TELEMETRY.enabled:
            TELEMETRY.counter(
                "comm_eager_calls_total",
                "host-level collectives issued").inc(1, op=op_name)
            TELEMETRY.counter(
                "comm_eager_bytes_total",
                "bytes moved by host-level collectives").inc(
                    size_bytes, op=op_name)
            TELEMETRY.histogram(
                "comm_eager_latency_seconds",
                "host-level collective wall clock").observe(
                    latency_s, op=op_name)
        if not self._should_log(op_name):
            return
        rec = self.eager[op_name]
        rec.count += 1
        rec.total_bytes += size_bytes
        rec.total_latency += latency_s
        rec.n_ranks = max(n_ranks, 1)
        s = rec.sizes[size_bytes]
        s[0] += 1
        s[1] += latency_s
        if self.verbose:
            algbw, busbw = calc_bw_log(op_name, size_bytes, latency_s, n_ranks)
            log_dist(
                f"comm: {op_name} | bytes={size_bytes} latency={latency_s * 1e3:.3f}ms "
                f"algbw={algbw:.2f}GB/s busbw={busbw:.2f}GB/s",
                ranks=[0],
            )

    # ------------------------------------------------------- summary
    def log_summary(self, show_straggler: bool = False) -> str:
        lines = ["Comms summary (trace-time collectives inside jitted steps):"]
        for op, rec in sorted(self.traced.items()):
            lines.append(f"  {op:>18}: calls={rec.count:<6} total={rec.total_bytes / 1e6:.2f} MB")
        lines.append("Comms summary (eager host-level collectives):")
        for op, rec in sorted(self.eager.items()):
            avg_ms = 1e3 * rec.total_latency / max(rec.count, 1)
            # average-size/average-latency bandwidth per call (the reference
            # prints algbw/busbw per row; a sum/sum ratio would let one huge
            # transfer mask many slow small ones)
            algbw, busbw = calc_bw_log(
                op, rec.total_bytes / max(rec.count, 1),
                rec.total_latency / max(rec.count, 1), rec.n_ranks)
            lines.append(
                f"  {op:>18}: calls={rec.count:<6} total={rec.total_bytes / 1e6:.2f} MB "
                f"avg={avg_ms:.3f}ms algbw={algbw:.2f}GB/s busbw={busbw:.2f}GB/s"
            )
        if show_straggler:
            lines += self._straggler_lines()
        text = "\n".join(lines)
        logger.info(text)
        return text

    def _straggler_lines(self) -> list[str]:
        """Min/max eager latency across processes (reference: log_summary(show_straggler))."""
        try:
            import jax
            import numpy as np
            from jax.experimental import multihost_utils

            if jax.process_count() <= 1:
                return ["  (single process; no straggler data)"]
            lines = ["Straggler analysis (min/max across processes, "
                     f"warn ratio {self.straggler_warn_ratio:.2f}):"]
            for op, rec in sorted(self.eager.items()):
                mine = np.asarray([rec.total_latency], dtype=np.float32)
                gathered = multihost_utils.process_allgather(mine)
                mn, mx = float(gathered.min()), float(gathered.max())
                ratio = mx / max(mn, 1e-12)
                line = (
                    f"  {op:>18}: min={mn * 1e3:.3f}ms max={mx * 1e3:.3f}ms "
                    f"ratio={ratio:.2f}"
                )
                if ratio > self.straggler_warn_ratio:
                    line += "  <-- STRAGGLER"
                    logger.warning(
                        f"comm straggler: {op} max/min latency ratio "
                        f"{ratio:.2f} exceeds {self.straggler_warn_ratio:.2f} "
                        f"(min={mn * 1e3:.3f}ms max={mx * 1e3:.3f}ms)")
                lines.append(line)
            return lines
        except Exception as e:  # pragma: no cover
            return [f"  (straggler gather failed: {e})"]

    def reset(self) -> None:
        self.traced.clear()
        self.eager.clear()


COMMS_LOGGER = CommsLogger()
