"""Live param / optimizer-state access by name.

Role parity with the reference ``utils/tensor_fragment.py`` (``safe_get_full_
fp32_param``, ``safe_set_full_fp32_param``, ``safe_get_full_optimizer_state``,
``safe_get_full_grad`` — the debugging/EMA APIs that reach through ZeRO's flat
buffers). Here params are a pytree of (possibly sharded) jax.Arrays, so a
"fragment" lookup is a path walk; gathered values come back as full numpy
arrays regardless of the sharding plan.

Names are pytree paths like ``"layers/wq"`` or ``"embed"``.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def _walk(tree: Any, name: str):
    node = tree
    parts = [p for p in name.replace("[", "/").replace("]", "").replace("'", "")
             .split("/") if p]
    for part in parts:
        if isinstance(node, (list, tuple)):
            node = node[int(part)]
        else:
            node = node[part]
    return node, parts


def _set(tree: Any, name: str, value):
    node = tree
    parts = [p for p in name.replace("[", "/").replace("]", "").replace("'", "")
             .split("/") if p]
    for part in parts[:-1]:
        node = node[int(part)] if isinstance(node, (list, tuple)) else node[part]
    node[parts[-1]] = value


def list_param_names(engine) -> list[str]:
    return [
        jax.tree_util.keystr(path).replace("['", "/").replace("']", "").lstrip("/")
        for path, _ in jax.tree_util.tree_flatten_with_path(engine.params)[0]
    ]


def safe_get_full_fp32_param(engine, name: str) -> np.ndarray:
    """Full (gathered) fp32 master value of a parameter."""
    leaf, _ = _walk(engine.params, name)
    return np.asarray(leaf)


def safe_set_full_fp32_param(engine, name: str, value) -> None:
    """Overwrite a parameter, preserving its sharding (reference semantics:
    the update is visible to the next step)."""
    leaf, _ = _walk(engine.params, name)
    new = jax.device_put(
        np.asarray(value, dtype=leaf.dtype).reshape(leaf.shape), leaf.sharding
    )
    _set(engine.params, name, new)


def _param_leaf_index(engine, name: str) -> int:
    """Flat leaf index of a named parameter (grouped-offload addressing)."""
    target, _ = _walk(engine.params, name)
    for i, leaf in enumerate(jax.tree_util.tree_leaves(engine.params)):
        if leaf is target:
            return i
    raise KeyError(f"parameter {name!r} not found")


def _state_tuple_leaf(state, state_name: str, j: int):
    """The j-th tuple entry of the ``state_name`` field in a grouped optax
    state (grouped states hold moments as tuples of leaves)."""
    for element in jax.tree_util.tree_leaves(
        state, is_leaf=lambda x: hasattr(x, state_name)
    ):
        if hasattr(element, state_name):
            return getattr(element, state_name)[j]
    raise KeyError(f"no optimizer state {state_name!r} found")


def safe_get_full_optimizer_state(engine, name: str, state_name: str = "mu") -> np.ndarray:
    """Full value of an optimizer moment for a parameter (``exp_avg`` ->
    ``mu``, ``exp_avg_sq`` -> ``nu`` in optax terms; both aliases accepted).

    Works across all optimizer-state representations: the plain full tree,
    host-tier sub-groups (list of per-group states over leaf tuples), and
    NVMe-resident groups (read back through the swapper)."""
    alias = {"exp_avg": "mu", "exp_avg_sq": "nu"}
    state_name = alias.get(state_name, state_name)

    mode = getattr(engine, "_offload_mode", None)
    if mode is not None:
        i = _param_leaf_index(engine, name)
        g = next(gi for gi, idx in enumerate(engine._groups) if i in idx)
        j = engine._groups[g].index(i)
        if mode == "nvme":
            state = engine._swapper.swap_in_tree(
                f"opt_g{g}", engine._nvme_templates[g])
        else:
            state = engine.opt_state[g]
        return np.asarray(_state_tuple_leaf(state, state_name, j))

    for element in jax.tree_util.tree_leaves(
        engine.opt_state, is_leaf=lambda x: hasattr(x, state_name)
    ):
        if hasattr(element, state_name):
            leaf, _ = _walk(getattr(element, state_name), name)
            return np.asarray(leaf)
    raise KeyError(f"no optimizer state {state_name!r} found")


def safe_get_full_grad(engine, name: str) -> np.ndarray | None:
    """Accumulated gradient for a parameter (fwd/bwd protocol path only —
    the fused ``train_batch`` consumes gradients inside one XLA program)."""
    if engine._acc_grads is None:
        return None
    leaf, _ = _walk(engine._acc_grads, name)
    return np.asarray(leaf)
